"""TCP fanout broker: real cross-process streaming without RabbitMQ.

The reference's documented deployment is two shells joined through an
external RabbitMQ server (README.rst; SURVEY.md §2.4) — the broker is an
unshipped third component.  The ``local://`` transport (runtime/broker.py)
removed the dependency but cannot span OS processes; ``amqp://`` speaks to
real RabbitMQ but needs aio-pika + a running broker.  This module closes
the gap with an in-tree fanout broker speaking a minimal newline-delimited
JSON protocol over TCP:

    shell 1:  fanoutbroker --port 5673
    shell 2:  metersim --amqp-url tcp://127.0.0.1:5673
    shell 3:  pvsim out.csv --amqp-url tcp://127.0.0.1:5673

— the reference's exact deployment shape, zero external services.

Semantics mirror the AMQP fanout contract the apps rely on
(metersim.py:25-42, pvsim.py:56-67): named exchanges, every subscriber
sees every message published after it subscribed, measurement time rides
with the value.  Slow subscribers get per-connection buffering with
oldest-first drop beyond a cap (the funnel's leak-fix policy,
runtime/funnel.py) so one stalled consumer can never wedge the broker —
a deliberate improvement over the unbounded queues RabbitMQ would grow.

Wire protocol (one JSON object per line, UTF-8):

    {"op": "sub", "exchange": E}                      client -> broker
    {"op": "pub", "exchange": E, "v": f, "ts_us": n}  client -> broker
    {"v": f, "ts_us": n}                              broker -> subscriber

An optional ``"m"`` object on pub frames (metersim's additive seq +
monotonic publish-time stamp, obs/trace.py) is forwarded verbatim to
subscribers when it is a dict and silently dropped otherwise — old
brokers/clients that predate the key interoperate either way because
``"v"``/``"ts_us"`` keep their reference shape.

``ts_us`` is the measurement's NAIVE wall time encoded as INTEGER
microseconds since the epoch *as if UTC*: the apps join on naive
fixedclock datetimes, and pinning the wire encoding to UTC makes
producer and consumer agree even when their hosts run different
timezones (a naive ``.timestamp()`` round-trip would skew by the TZ
difference).  Integer microseconds — not float seconds — because the
funnel joins on exact datetime equality and a float64 epoch can perturb
the microsecond field of sub-second times through the json round-trip.
"""

from __future__ import annotations

import asyncio
import contextlib
import datetime as _dt
import json
import logging
from typing import AsyncIterator, Dict, Optional, Set, Tuple
from urllib.parse import urlparse

#: wire-protocol epoch for the integer-microsecond "ts_us" field
_EPOCH = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)

logger = logging.getLogger(__name__)

#: per-subscriber buffered messages before oldest-first drop
MAX_SUBSCRIBER_BACKLOG = 10_000


class _Subscriber:
    """One consumer connection: a bounded queue + drain task, so a slow or
    stalled consumer back-pressures onto ITS buffer, never the broker.

    ``tcpbroker.backlog_depth`` is the AGGREGATE queued-message count
    across all live subscribers, maintained by +/- deltas (an absolute
    ``set(qsize)`` per subscriber would be last-write-wins: with many
    concurrent subscribers the gauge read whichever one touched it
    last, hiding every other backlog)."""

    def __init__(self, writer: asyncio.StreamWriter,
                 max_backlog: int = MAX_SUBSCRIBER_BACKLOG):
        from tmhpvsim_tpu.obs import metrics as obs_metrics

        self.writer = writer
        self.max_backlog = int(max_backlog)
        self.queue: asyncio.Queue = asyncio.Queue()
        self.n_dropped = 0
        reg = obs_metrics.get_registry()
        self._c_dropped = reg.counter("tcpbroker.dropped_total")
        self._g_backlog = reg.gauge("tcpbroker.backlog_depth")

    def offer(self, line: bytes) -> None:
        while self.queue.qsize() >= self.max_backlog:
            self.queue.get_nowait()
            self._g_backlog.add(-1)
            self.n_dropped += 1
            self._c_dropped.inc()
            if self.n_dropped == 1 or self.n_dropped % 1000 == 0:
                logger.warning(
                    "tcp broker: subscriber backlog exceeded %d; dropped "
                    "%d oldest messages (consumer stalled?)",
                    self.max_backlog, self.n_dropped,
                )
        self.queue.put_nowait(line)
        self._g_backlog.add(1)

    def unregistered(self) -> None:
        """Hand back this queue's share of the aggregate backlog gauge
        (idempotent: the queue is emptied)."""
        n = self.queue.qsize()
        if n:
            self._g_backlog.add(-n)
        while not self.queue.empty():
            self.queue.get_nowait()

    async def drain(self) -> None:
        while True:
            line = await self.queue.get()
            self._g_backlog.add(-1)
            self.writer.write(line)
            await self.writer.drain()


class TcpFanoutBroker:
    """The broker server: named fanout exchanges over one TCP port."""

    def __init__(self, host: str = "127.0.0.1", port: int = 5673,
                 max_backlog: int = MAX_SUBSCRIBER_BACKLOG):
        self.host = host
        self.port = port
        self.max_backlog = int(max_backlog)
        self._exchanges: Dict[str, Set[_Subscriber]] = {}
        self._server: Optional[asyncio.base_events.Server] = None
        #: writers of ALL live connections (not just subscribers): since
        #: Python 3.12.1 Server.wait_closed() also waits for connection
        #: handlers, so stop() must actively disconnect clients or it
        #: deadlocks behind a handler parked in readline()
        self._conn_writers: Set[asyncio.StreamWriter] = set()

    async def __aenter__(self):
        await self.start()
        return self

    async def __aexit__(self, *exc):
        await self.stop()
        return False

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        # resolve port 0 -> the bound port, so tests can ask for "any"
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info("tcp fanout broker listening on %s:%d",
                    self.host, self.port)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            for w in list(self._conn_writers):  # see _conn_writers note
                w.close()
            await self._server.wait_closed()
            self._server = None

    async def serve_forever(self) -> None:
        if self._server is None:
            await self.start()
        async with self._server:
            await self._server.serve_forever()

    def _unregister(self, exchange: Optional[str],
                    sub: Optional[_Subscriber]) -> None:
        """Detach a subscriber (idempotent): stop fanning out to it and
        return its queued share of the backlog gauge."""
        subs = self._exchanges.get(exchange)
        if subs is not None and sub in subs:
            subs.discard(sub)
            if not subs:
                self._exchanges.pop(exchange, None)
            sub.unregistered()

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        sub: Optional[_Subscriber] = None
        sub_exchange: Optional[str] = None
        drain_task: Optional[asyncio.Task] = None
        self._conn_writers.add(writer)
        try:
            while True:
                line = await reader.readline()
                if not line:
                    break
                try:
                    frame = json.loads(line)
                    op = frame["op"]
                except (json.JSONDecodeError, KeyError, TypeError):
                    logger.warning("tcp broker: malformed frame %r",
                                   line[:100])
                    continue
                if op == "pub":
                    v, ts = frame.get("v"), frame.get("ts_us")
                    exchange = frame.get("exchange")
                    # validate here: forwarding a malformed frame would
                    # crash EVERY subscriber's decode loop, not just the
                    # bad publisher (and a non-str exchange would TypeError
                    # the dict lookup)
                    if not isinstance(v, (int, float)) or \
                            not isinstance(ts, (int, float)) or \
                            not isinstance(exchange, str):
                        logger.warning(
                            "tcp broker: dropping malformed pub frame: %r",
                            line[:100],
                        )
                        continue
                    frame_out = {"v": v, "ts_us": ts}
                    m = frame.get("m")
                    if isinstance(m, dict):  # additive meta passthrough
                        frame_out["m"] = m
                    out = json.dumps(frame_out).encode() + b"\n"
                    for s in self._exchanges.get(exchange, ()):  # fanout
                        s.offer(out)
                elif op == "sub" and sub is None:
                    sub_exchange = frame.get("exchange")
                    if not isinstance(sub_exchange, str):
                        logger.warning(
                            "tcp broker: dropping malformed sub frame: %r",
                            line[:100],
                        )
                        continue
                    sub = _Subscriber(writer, self.max_backlog)
                    self._exchanges.setdefault(sub_exchange, set()).add(sub)
                    drain_task = asyncio.create_task(sub.drain())
                    # a consumer that dies mid-write kills the drain task
                    # with ConnectionError while this reader loop may stay
                    # parked in readline() (half-open socket): unregister
                    # immediately so publishes stop piling into a queue
                    # nothing will ever drain
                    drain_task.add_done_callback(
                        lambda _t, e=sub_exchange, s=sub:
                        self._unregister(e, s))
                else:
                    logger.warning("tcp broker: unexpected op %r", op)
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            self._conn_writers.discard(writer)
            if sub is not None:
                self._unregister(sub_exchange, sub)
            if drain_task is not None:
                drain_task.cancel()
                # the drain task may already be DONE with a ConnectionError
                # (consumer died mid-write) — that must not re-raise here
                # and skip the writer cleanup below
                with contextlib.suppress(asyncio.CancelledError,
                                         ConnectionError):
                    await drain_task
            writer.close()
            with contextlib.suppress(ConnectionError):
                await writer.wait_closed()


class TcpTransport:
    """Client transport for ``tcp://host:port`` URLs — same interface as
    LocalTransport/AmqpTransport (runtime/broker.py), so the apps'
    forever-retry wrappers give the same broker-outage resilience the
    reference gets from aio-pika reconnects (metersim.py:13, pvsim.py:43):
    a dropped connection raises out of publish/subscribe and the app
    reconnects with backoff."""

    def __init__(self, url: str, exchange: str):
        parsed = urlparse(url)
        if parsed.scheme != "tcp":
            raise ValueError(f"TcpTransport needs a tcp:// URL, got {url!r}")
        self._host = parsed.hostname or "127.0.0.1"
        self._port = parsed.port or 5673
        self._exchange = exchange
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None

    async def __aenter__(self):
        from tmhpvsim_tpu.runtime import faults
        from tmhpvsim_tpu.runtime.broker import _count_connect

        if faults.ACTIVE is not None:
            await faults.afire("broker.connect")
        self._reader, self._writer = await asyncio.open_connection(
            self._host, self._port
        )
        _count_connect(f"tcp://{self._host}:{self._port}", self._exchange)
        return self

    async def __aexit__(self, *exc):
        if self._writer is not None:
            self._writer.close()
            with contextlib.suppress(ConnectionError):
                await self._writer.wait_closed()
        return False

    async def _send(self, frame: dict) -> None:
        self._writer.write(json.dumps(frame).encode() + b"\n")
        await self._writer.drain()

    async def publish(self, value: float, time: _dt.datetime,
                      meta: Optional[dict] = None) -> None:
        from tmhpvsim_tpu.obs import trace as obs_trace
        from tmhpvsim_tpu.runtime import faults
        from tmhpvsim_tpu.runtime.broker import _pub_counter

        # no-op unless trace propagation is on; the "m" key only appears
        # on the wire when there is meta to carry, so the off path stays
        # byte-identical to pre-propagation frames
        meta = obs_trace.stamp(meta)
        act = None
        if faults.ACTIVE is not None:
            act = await faults.afire("broker.publish")
            if act == "drop":
                return
        # naive wall time -> as-if-UTC epoch (see module docstring: makes
        # the join timezone-independent across hosts); aware datetimes
        # keep their real instant.  Wire encoding is INTEGER microseconds
        # ("ts_us"): the funnel joins on exact datetime equality, and a
        # float64-epoch round-trip through json can perturb the
        # microsecond field of sub-second times — integers cannot.
        if time.tzinfo is None:
            time = time.replace(tzinfo=_dt.timezone.utc)
        ts_us = round((time - _EPOCH) / _dt.timedelta(microseconds=1))
        frame = {"op": "pub", "exchange": self._exchange,
                 "v": value, "ts_us": ts_us}
        if meta:
            frame["m"] = meta
        # shielded like the AMQP path (metersim.py:43-45): a cancellation
        # mid-publish must not truncate the frame on the wire
        await asyncio.shield(self._send(frame))
        _pub_counter().inc()
        if act == "dup":
            await asyncio.shield(self._send(frame))
            _pub_counter().inc()

    async def subscribe(self, with_meta: bool = False) -> AsyncIterator:
        from tmhpvsim_tpu.runtime import faults
        from tmhpvsim_tpu.runtime.broker import _deliver_counter

        await self._send({"op": "sub", "exchange": self._exchange})
        deliver = _deliver_counter()
        while True:
            act = None
            if faults.ACTIVE is not None:
                # an injected partition drops the socket for real: the
                # reconnect loop upstream must re-attach and re-subscribe
                try:
                    await faults.afire("tcp.partition")
                except faults.FaultInjected:
                    self._writer.close()
                    raise
                act = await faults.afire("broker.deliver")
            line = await self._reader.readline()
            if not line:
                raise ConnectionError("tcp broker closed the connection")
            if act == "drop":
                continue
            frame = json.loads(line)
            deliver.inc()
            # inverse of publish: integer-us as-if-UTC epoch -> naive wall
            t = _EPOCH + _dt.timedelta(microseconds=frame["ts_us"])
            if with_meta:
                m = frame.get("m")
                item = (t.replace(tzinfo=None), frame["v"],
                        m if isinstance(m, dict) else None)
            else:
                item = (t.replace(tzinfo=None), frame["v"])
            yield item
            if act == "dup":
                deliver.inc()
                yield item
