"""Asyncio streaming runtime (the reference-compatible default backend)."""

from tmhpvsim_tpu.runtime.clock import fixedclock  # noqa: F401
from tmhpvsim_tpu.runtime.funnel import SynchronizingFunnel  # noqa: F401
from tmhpvsim_tpu.runtime.retry import asyncretry, forever  # noqa: F401
from tmhpvsim_tpu.runtime.run import asyncrun  # noqa: F401
