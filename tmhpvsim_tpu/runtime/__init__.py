"""Asyncio streaming runtime (the reference-compatible default backend)."""

from tmhpvsim_tpu.runtime.clock import fixedclock  # noqa: F401
from tmhpvsim_tpu.runtime.funnel import SynchronizingFunnel  # noqa: F401
from tmhpvsim_tpu.runtime.resilience import (  # noqa: F401
    CircuitBreaker,
    ResiliencePolicy,
    asyncretry,
    forever,
    reconnect_policy,
)
from tmhpvsim_tpu.runtime.run import asyncrun  # noqa: F401
