"""Supervised warm restart: rerun a crashed CLI child until it exits
cleanly.

``pvsim --supervise N`` (and ``pvsim-serve --supervise N``) run the
actual command in a child process; when the child dies — a crash, an
OOM kill, a chaos-injected SIGKILL (runtime/faults.py) — the supervisor
relaunches it with exponential backoff, up to N restarts.  Warmth is
what makes the relaunch cheap: the child resumes from its last block
checkpoint (engine/checkpoint.py) and recompiles nothing under the
persistent compile cache (engine/compilecache.py), so a restart costs
one backoff sleep plus one checkpoint load, not a cold start.

The restart attempt number rides into each child as
``TMHPVSIM_SUPERVISED_RESTART`` (0 on the first launch); apps/pvsim.py
surfaces it as the ``resilience.supervised_restarts`` gauge so the run
report's ``resilience`` section records how many lives the run used.
The marker doubles as the re-entrancy guard: a child never starts its
own supervisor even if a ``--supervise`` flag leaks through.

A SIGINT/SIGTERM at the supervisor is forwarded to the child and ends
supervision — an operator's ^C must stop the run, not fight a restart
loop.  With ``grace_s`` set (``--preempt-grace S``) the supervisor also
bounds how long the child may spend on its final snapshot after the
forwarded signal: a child still alive ``grace_s`` seconds after the stop
signal is SIGKILLed — the TPU-preemption-notice shape, where the
platform revokes the slice whether or not the snapshot finished.

Restart backoff rides :class:`~tmhpvsim_tpu.runtime.resilience
.ResiliencePolicy`'s decorrelated jitter rather than a hand-rolled
deterministic exponential, so N supervised hosts restarting off the
same outage don't synchronize into a thundering herd.
"""

from __future__ import annotations

import asyncio
import logging
import os
import signal
import subprocess
import sys
import time
from typing import Awaitable, Callable, List, Optional, Sequence

from tmhpvsim_tpu.runtime.resilience import ResiliencePolicy

log = logging.getLogger(__name__)

#: restart attempt number in the child's env ("0" = first launch)
ENV_RESTART = "TMHPVSIM_SUPERVISED_RESTART"


def strip_supervise(argv: Sequence[str]) -> List[str]:
    """``argv`` without ``--supervise N`` / ``--supervise=N`` — the
    child runs the command itself, never another supervisor."""
    out: List[str] = []
    skip = False
    for a in argv:
        if skip:
            skip = False
            continue
        if a == "--supervise":
            skip = True
            continue
        if a.startswith("--supervise="):
            continue
        out.append(a)
    return out


def child_argv(subcommand: str,
               argv: Optional[Sequence[str]] = None) -> List[str]:
    """Rebuild this process's invocation as a module-run child argv.

    Handles both launch styles: the console script (``pvsim out.csv
    ...`` — ``sys.argv[1:]`` lacks the subcommand) and the module group
    (``python -m tmhpvsim_tpu.cli pvsim out.csv ...`` — it leads).  The
    child always goes through ``python -m tmhpvsim_tpu.cli`` so the
    same interpreter and environment are reused.
    """
    tail = list(sys.argv[1:] if argv is None else argv)
    if not tail or tail[0] != subcommand:
        tail = [subcommand, *tail]
    return [sys.executable, "-m", "tmhpvsim_tpu.cli",
            *strip_supervise(tail)]


def _describe_exit(rc: int) -> str:
    if rc < 0:
        try:
            return f"on signal {signal.Signals(-rc).name}"
        except ValueError:
            return f"on signal {-rc}"
    return f"with code {rc}"


def _graceful_wait(proc: subprocess.Popen, stop_at: List[float],
                   grace_s: Optional[float]) -> int:
    """``proc.wait()`` that, once a stop signal has been forwarded
    (``stop_at`` holds its monotonic timestamp), gives the child at most
    ``grace_s`` seconds to finish its final snapshot before SIGKILL."""
    if grace_s is None:
        return proc.wait()
    while True:
        try:
            return proc.wait(timeout=0.5)
        except subprocess.TimeoutExpired:
            if stop_at and time.monotonic() - stop_at[0] > grace_s:
                log.warning(
                    "supervised child still alive %.1f s after the stop "
                    "signal; preemption grace expired — killing",
                    grace_s)
                proc.kill()
                return proc.wait()


def run_supervised(argv: Sequence[str], *, max_restarts: int,
                   backoff_base_s: float = 1.0,
                   backoff_max_s: float = 30.0,
                   grace_s: Optional[float] = None,
                   env: Optional[dict] = None) -> int:
    """Run ``argv`` as a child, restarting on crash; returns the final
    child's exit code (0 on any clean exit).  ``grace_s`` bounds the
    child's final-snapshot window after a forwarded stop signal."""
    base_env = dict(os.environ if env is None else env)
    attempt = 0
    proc: Optional[subprocess.Popen] = None
    stop_sig: List[int] = []
    stop_at: List[float] = []
    policy = ResiliencePolicy(attempts=max_restarts + 1,
                              base_delay_s=backoff_base_s,
                              max_delay_s=backoff_max_s,
                              name="supervise.restart")

    def _forward(signum, frame):
        if not stop_sig:
            stop_at.append(time.monotonic())
        stop_sig.append(signum)
        if proc is not None and proc.poll() is None:
            proc.send_signal(signum)

    old_handlers = {}
    for s in (signal.SIGINT, signal.SIGTERM):
        try:
            old_handlers[s] = signal.signal(s, _forward)
        except ValueError:  # pragma: no cover - non-main-thread caller
            pass
    prev = backoff_base_s
    try:
        while True:
            base_env[ENV_RESTART] = str(attempt)
            proc = subprocess.Popen(list(argv), env=base_env)
            rc = _graceful_wait(proc, stop_at, grace_s)
            if rc == 0 or stop_sig:
                return rc
            if attempt >= max_restarts:
                log.error(
                    "supervised child exited %s; %d restart(s) "
                    "exhausted — giving up", _describe_exit(rc),
                    max_restarts)
                return rc
            attempt += 1
            delay = policy.backoff(attempt, prev)
            prev = max(delay, backoff_base_s)
            log.warning(
                "supervised child exited %s; warm restart %d/%d in "
                "%.1f s", _describe_exit(rc), attempt, max_restarts,
                delay)
            time.sleep(delay)
    finally:
        for s, h in old_handlers.items():
            signal.signal(s, h)


async def supervise_service(run: Callable[[int], Awaitable[None]], *,
                            max_restarts: int,
                            backoff_base_s: float = 0.05,
                            backoff_max_s: float = 2.0,
                            name: str = "service",
                            registry=None) -> None:
    """In-process analogue of :func:`run_supervised` for asyncio
    services (the serving fleet's workers): ``await run(attempt)``
    until it returns cleanly; an exception is a crash and triggers a
    warm respawn under the same decorrelated-jitter backoff discipline,
    up to ``max_restarts`` lives.  The attempt number lands on the
    ``resilience.supervised_restarts.{name}`` gauge so a fleet's run
    report records how many lives each worker used.  Warmth is the
    same story as the subprocess supervisor: under a populated
    persistent compile cache a respawned worker deserialises every
    executable and compiles nothing cold."""
    policy = ResiliencePolicy(attempts=max_restarts + 1,
                              base_delay_s=backoff_base_s,
                              max_delay_s=backoff_max_s,
                              name=f"supervise.{name}")
    attempt = 0
    prev = backoff_base_s
    while True:
        try:
            await run(attempt)
            return
        except asyncio.CancelledError:
            raise
        except Exception as err:
            if attempt >= max_restarts:
                log.error(
                    "supervised service %r crashed (%s: %s); %d "
                    "restart(s) exhausted — giving up", name,
                    type(err).__name__, err, max_restarts)
                raise
            attempt += 1
            if registry is not None:
                registry.gauge(
                    f"resilience.supervised_restarts.{name}"
                ).set(attempt)
            delay = policy.backoff(attempt, prev)
            prev = max(delay, backoff_base_s)
            log.warning(
                "supervised service %r crashed (%s: %s); warm respawn "
                "%d/%d in %.2f s", name, type(err).__name__, err,
                attempt, max_restarts, delay)
            await asyncio.sleep(delay)
