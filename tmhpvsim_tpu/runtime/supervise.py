"""Supervised warm restart: rerun a crashed CLI child until it exits
cleanly.

``pvsim --supervise N`` (and ``pvsim-serve --supervise N``) run the
actual command in a child process; when the child dies — a crash, an
OOM kill, a chaos-injected SIGKILL (runtime/faults.py) — the supervisor
relaunches it with exponential backoff, up to N restarts.  Warmth is
what makes the relaunch cheap: the child resumes from its last block
checkpoint (engine/checkpoint.py) and recompiles nothing under the
persistent compile cache (engine/compilecache.py), so a restart costs
one backoff sleep plus one checkpoint load, not a cold start.

The restart attempt number rides into each child as
``TMHPVSIM_SUPERVISED_RESTART`` (0 on the first launch); apps/pvsim.py
surfaces it as the ``resilience.supervised_restarts`` gauge so the run
report's ``resilience`` section records how many lives the run used.
The marker doubles as the re-entrancy guard: a child never starts its
own supervisor even if a ``--supervise`` flag leaks through.

A SIGINT/SIGTERM at the supervisor is forwarded to the child and ends
supervision — an operator's ^C must stop the run, not fight a restart
loop.
"""

from __future__ import annotations

import logging
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional, Sequence

log = logging.getLogger(__name__)

#: restart attempt number in the child's env ("0" = first launch)
ENV_RESTART = "TMHPVSIM_SUPERVISED_RESTART"


def strip_supervise(argv: Sequence[str]) -> List[str]:
    """``argv`` without ``--supervise N`` / ``--supervise=N`` — the
    child runs the command itself, never another supervisor."""
    out: List[str] = []
    skip = False
    for a in argv:
        if skip:
            skip = False
            continue
        if a == "--supervise":
            skip = True
            continue
        if a.startswith("--supervise="):
            continue
        out.append(a)
    return out


def child_argv(subcommand: str,
               argv: Optional[Sequence[str]] = None) -> List[str]:
    """Rebuild this process's invocation as a module-run child argv.

    Handles both launch styles: the console script (``pvsim out.csv
    ...`` — ``sys.argv[1:]`` lacks the subcommand) and the module group
    (``python -m tmhpvsim_tpu.cli pvsim out.csv ...`` — it leads).  The
    child always goes through ``python -m tmhpvsim_tpu.cli`` so the
    same interpreter and environment are reused.
    """
    tail = list(sys.argv[1:] if argv is None else argv)
    if not tail or tail[0] != subcommand:
        tail = [subcommand, *tail]
    return [sys.executable, "-m", "tmhpvsim_tpu.cli",
            *strip_supervise(tail)]


def _describe_exit(rc: int) -> str:
    if rc < 0:
        try:
            return f"on signal {signal.Signals(-rc).name}"
        except ValueError:
            return f"on signal {-rc}"
    return f"with code {rc}"


def run_supervised(argv: Sequence[str], *, max_restarts: int,
                   backoff_base_s: float = 1.0,
                   backoff_max_s: float = 30.0,
                   env: Optional[dict] = None) -> int:
    """Run ``argv`` as a child, restarting on crash; returns the final
    child's exit code (0 on any clean exit)."""
    base_env = dict(os.environ if env is None else env)
    attempt = 0
    proc: Optional[subprocess.Popen] = None
    stop_sig: List[int] = []

    def _forward(signum, frame):
        stop_sig.append(signum)
        if proc is not None and proc.poll() is None:
            proc.send_signal(signum)

    old_handlers = {}
    for s in (signal.SIGINT, signal.SIGTERM):
        try:
            old_handlers[s] = signal.signal(s, _forward)
        except ValueError:  # pragma: no cover - non-main-thread caller
            pass
    try:
        while True:
            base_env[ENV_RESTART] = str(attempt)
            proc = subprocess.Popen(list(argv), env=base_env)
            rc = proc.wait()
            if rc == 0 or stop_sig:
                return rc
            if attempt >= max_restarts:
                log.error(
                    "supervised child exited %s; %d restart(s) "
                    "exhausted — giving up", _describe_exit(rc),
                    max_restarts)
                return rc
            attempt += 1
            delay = min(backoff_max_s,
                        backoff_base_s * 2.0 ** (attempt - 1))
            log.warning(
                "supervised child exited %s; warm restart %d/%d in "
                "%.1f s", _describe_exit(rc), attempt, max_restarts,
                delay)
            time.sleep(delay)
    finally:
        for s, h in old_handlers.items():
            signal.signal(s, h)
