"""Fixed-rate simulation clock.

Reference semantics (utils.py:13-45): an async generator yielding *ideal
grid* timestamps ``start + i/rate`` — never the actual wall time — so
downstream joins see a perfectly regular series even when the loop lags.
In realtime mode it sleeps until the wall clock reaches each tick and warns
when more than two periods behind (with the reference's f-string bug fixed,
utils.py:41).

Deliberate deviation: the reference sleeps >= 10 ms even with
``realtime=False`` (utils.py:36), capping every CPU simulation at ~100
simulated s/s — its de-facto throughput ceiling (SURVEY.md §6).  Here
non-realtime mode yields back to the event loop without a floor sleep
(``asyncio.sleep(0)``), which preserves cooperative scheduling but removes
the artificial cap.
"""

from __future__ import annotations

import asyncio
import datetime as _dt
import logging
import time
from typing import AsyncIterator, Optional

logger = logging.getLogger(__name__)


async def fixedclock(
    rate: float = 1.0,
    realtime: bool = True,
    start: Optional[_dt.datetime] = None,
    duration_s: Optional[float] = None,
) -> AsyncIterator[_dt.datetime]:
    """Yield naive-local datetimes on the ideal ``start + i/rate`` grid.

    ``duration_s`` bounds the stream (None = infinite, as the reference).
    """
    period = 1.0 / rate
    if start is None:
        start = _dt.datetime.now()
    start_wall = time.monotonic()
    i = 0
    while duration_s is None or i * period < duration_s:
        yield start + _dt.timedelta(seconds=i * period)
        i += 1
        if realtime:
            behind = (time.monotonic() - start_wall) - i * period
            if behind > 2 * period:
                logger.warning("We are %.2f seconds behind realtime", behind)
            await asyncio.sleep(max(0.0, -behind))
        else:
            await asyncio.sleep(0)
