"""Fixed-rate simulation clock.

Reference semantics (utils.py:13-45): an async generator yielding *ideal
grid* timestamps ``start + i/rate`` — never the actual wall time — so
downstream joins see a perfectly regular series even when the loop lags.
In realtime mode it sleeps until the wall clock reaches each tick and warns
when more than two periods behind (with the reference's f-string bug fixed,
utils.py:41).

Deliberate deviation: the reference sleeps >= 10 ms even with
``realtime=False`` (utils.py:36), capping every CPU simulation at ~100
simulated s/s — its de-facto throughput ceiling (SURVEY.md §6).  Here
non-realtime mode yields back to the event loop without a floor sleep
(``asyncio.sleep(0)``), which preserves cooperative scheduling but removes
the artificial cap.
"""

from __future__ import annotations

import asyncio
import datetime as _dt
import logging
import time
from typing import AsyncIterator, Optional

logger = logging.getLogger(__name__)


class PacingMonitor:
    """Realtime pacing lag as metrics + rate-limited WARNs.

    The reference warns on every late tick past two periods (utils.py:41)
    and records nothing — at 1 Hz a persistently-behind run floods the
    log while the total slip stays invisible.  This keeps two gauges on
    the metrics registry — ``clock.pacing_lag_s`` (current lag behind the
    ideal grid) and ``clock.pacing_slip_total_s`` (cumulative NEW slip:
    lag increases only, so recovered lag is not double-counted) — and
    emits at most one WARN per ``warn_every_s``, carrying the cumulative
    figure.

    ``observe`` takes an injectable ``now`` for tests and returns True
    when it warned.
    """

    def __init__(self, period: float, warn_every_s: float = 10.0):
        from tmhpvsim_tpu.obs import metrics as obs_metrics

        self.period = period
        self.warn_every_s = warn_every_s
        self._last_warn = None
        self._prev_lag = 0.0
        reg = obs_metrics.get_registry()
        self._g_lag = reg.gauge("clock.pacing_lag_s")
        self._g_slip = reg.gauge("clock.pacing_slip_total_s")
        self._g_lag.set(0.0)
        self._g_slip.set(0.0)

    def observe(self, behind: float, now: Optional[float] = None) -> bool:
        lag = max(0.0, behind)
        self._g_lag.set(lag)
        if lag > self._prev_lag:
            self._g_slip.add(lag - self._prev_lag)
        self._prev_lag = lag
        if behind <= 2 * self.period:
            return False
        if now is None:
            now = time.monotonic()
        if self._last_warn is not None and \
                now - self._last_warn < self.warn_every_s:
            return False
        self._last_warn = now
        logger.warning(
            "%.2f s behind realtime (cumulative slip %.2f s; warnings "
            "rate-limited to one per %.0f s)",
            behind, self._g_slip.value, self.warn_every_s,
        )
        return True


async def fixedclock(
    rate: float = 1.0,
    realtime: bool = True,
    start: Optional[_dt.datetime] = None,
    duration_s: Optional[float] = None,
) -> AsyncIterator[_dt.datetime]:
    """Yield naive-local datetimes on the ideal ``start + i/rate`` grid.

    ``duration_s`` bounds the stream (None = infinite, as the reference).
    """
    period = 1.0 / rate
    if start is None:
        start = _dt.datetime.now()
    start_wall = time.monotonic()
    monitor = PacingMonitor(period) if realtime else None
    i = 0
    while duration_s is None or i * period < duration_s:
        yield start + _dt.timedelta(seconds=i * period)
        i += 1
        if realtime:
            behind = (time.monotonic() - start_wall) - i * period
            monitor.observe(behind)
            await asyncio.sleep(max(0.0, -behind))
        else:
            await asyncio.sleep(0)
