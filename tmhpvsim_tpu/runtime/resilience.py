"""Unified resilience policy: retries, backoff, deadlines, breakers.

This module is the successor of ``runtime/retry.py`` (which shimmed to
here with a DeprecationWarning for one release and has since been
removed).  It keeps the reference's ``asyncretry`` decorator
semantics bit-for-bit (``forever`` sentinel, ``propagate`` fallback,
``CancelledError`` always fatal, per-qualname ``retry.*`` counters, the
exhaustion WARN) and layers the pieces the streaming/serving stack
shares on top:

* :class:`ResiliencePolicy` — one retry loop with exponential backoff
  and decorrelated jitter, optional per-attempt and total deadline
  budgets, and an optional circuit breaker.  Threaded through broker
  reconnect-and-resubscribe (apps + serve), ``ScenarioClient`` request
  publishing, and the serve reply path.
* :class:`CircuitBreaker` — a half-open breaker with ``resilience.*``
  metrics; serve dispatch trips it and sheds load with typed
  ``unavailable`` rejections instead of queueing doomed work.
* :class:`WarnRateLimiter` — the funnel-eviction WARN pattern (at most
  one per 10 s, suppressed-count suffix) applied to reconnect WARNs so
  a flapping broker cannot flood stderr.

Metrics (looked up per event on the current default registry, like the
old retry counters): ``retry.attempts.{name}`` / ``retry.exhausted.{name}``
(unchanged well-known names the streaming report section reads),
``resilience.retries_total`` / ``resilience.giveups_total`` aggregates,
and per-breaker ``resilience.breaker_open_total.{name}`` /
``resilience.breaker_rejected_total.{name}`` counters plus a
``resilience.breaker_state.{name}`` gauge (0 closed, 1 half-open,
2 open).
"""

from __future__ import annotations

import asyncio
import functools
import inspect
import logging
import random
import time
from typing import Optional

logger = logging.getLogger(__name__)

#: Sentinel for unbounded retries (the reference's ``forever = ...``,
#: utils.py:71).
forever = ...


class _Propagate:
    pass


propagate = _Propagate()

_UNSET = object()

#: default window for rate-limited reconnect WARNs (mirrors
#: ``funnel.EVICT_WARN_EVERY_S``)
WARN_EVERY_S = 10.0


class BreakerOpenError(ConnectionError):
    """Raised when a call is refused because its circuit breaker is
    open (subclasses ``ConnectionError`` so reconnect loops treat it as
    transient)."""


class WarnRateLimiter:
    """At most one WARN per ``every_s``, with a suppressed-count suffix
    (the funnel-eviction pattern).  ``now`` is injectable for tests."""

    def __init__(self, every_s: float = WARN_EVERY_S):
        self.every_s = every_s
        self._last: Optional[float] = None
        self._suppressed = 0

    @property
    def suppressed(self) -> int:
        return self._suppressed

    def warn(self, log: logging.Logger, fmt: str, *args,
             now: Optional[float] = None) -> bool:
        if now is None:
            now = time.monotonic()
        if self._last is not None and now - self._last < self.every_s:
            self._suppressed += 1
            return False
        suffix = ""
        if self._suppressed:
            suffix = (f" ({self._suppressed} similar warnings "
                      f"suppressed in the last {self.every_s:.0f} s)")
        self._last = now
        self._suppressed = 0
        log.warning(fmt + "%s", *args, suffix)
        return True


_STATE_CODES = {"closed": 0, "half_open": 1, "open": 2}


class CircuitBreaker:
    """Half-open circuit breaker.

    ``failure_threshold`` consecutive failures open it; after
    ``reset_s`` it lets exactly one probe through (half-open); the probe
    closing or re-opening it.  ``now`` is injectable for tests.  Metrics
    go to ``registry`` when given, else the current default registry at
    event time (apps swap registries per run).
    """

    def __init__(self, name: str = "default", *,
                 failure_threshold: int = 5, reset_s: float = 30.0,
                 registry=None, now=time.monotonic):
        self.name = name
        self.failure_threshold = failure_threshold
        self.reset_s = reset_s
        self._registry = registry
        self._now = now
        self._state = "closed"
        self._failures = 0
        self._opened_at = 0.0
        self._probe_in_flight = False

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from tmhpvsim_tpu.obs import metrics as obs_metrics

        return obs_metrics.get_registry()

    def _set_state(self, state: str) -> None:
        self._state = state
        self._reg().gauge(
            f"resilience.breaker_state.{self.name}").set(
                _STATE_CODES[state])

    def _maybe_half_open(self) -> None:
        if (self._state == "open"
                and self._now() - self._opened_at >= self.reset_s):
            self._set_state("half_open")
            self._probe_in_flight = False

    @property
    def state(self) -> str:
        self._maybe_half_open()
        return self._state

    def allow(self) -> bool:
        """True when a call may proceed (half-open admits one probe)."""
        self._maybe_half_open()
        if self._state == "closed":
            return True
        if self._state == "half_open" and not self._probe_in_flight:
            self._probe_in_flight = True
            return True
        self._reg().counter(
            f"resilience.breaker_rejected_total.{self.name}").inc()
        return False

    def count_rejected(self) -> None:
        """Count a load-shedding rejection taken on this breaker's
        behalf without consuming the half-open probe slot (the serve
        submit path sheds while open instead of calling allow())."""
        self._reg().counter(
            f"resilience.breaker_rejected_total.{self.name}").inc()

    def record_success(self) -> None:
        self._probe_in_flight = False
        self._failures = 0
        if self._state != "closed":
            logger.info("breaker %r closed after successful probe",
                        self.name)
            self._set_state("closed")

    def reset_remaining_s(self) -> float:
        """Seconds until an open breaker half-opens (0 when not open) —
        the honest ``retry_after`` hint for load shed on its behalf."""
        self._maybe_half_open()
        if self._state != "open":
            return 0.0
        return max(0.0, self.reset_s - (self._now() - self._opened_at))

    def record_failure(self) -> None:
        self._maybe_half_open()
        probe_failed = self._state == "half_open" and self._probe_in_flight
        self._probe_in_flight = False
        self._failures += 1
        tripped = (self._state == "closed"
                   and self._failures >= self.failure_threshold)
        if tripped or probe_failed:
            self._reg().counter(
                f"resilience.breaker_open_total.{self.name}").inc()
            logger.warning(
                "breaker %r open after %d consecutive failure(s); "
                "next probe in %.1f s", self.name, self._failures,
                self.reset_s)
            self._set_state("open")
            self._opened_at = self._now()


class ResiliencePolicy:
    """One retry loop for every reconnect/redeliver path in the stack.

    ``attempts`` may be an int or the ``forever`` sentinel.  Backoff is
    exponential (``base_delay_s * multiplier**(n-1)``, capped at
    ``max_delay_s``); with ``jitter=True`` (default) the delay is drawn
    with decorrelated jitter (``uniform(base, 3*prev)``, capped) from
    ``rng`` — injectable for determinism.  ``attempt_timeout_s`` bounds
    each attempt via ``wait_for``; ``total_timeout_s`` is a total retry
    budget after which the fallback policy applies even with attempts
    remaining.  Bounded policies log per-attempt INFO lines like the old
    decorator; ``forever`` policies are reconnect loops and WARN instead
    — rate-limited to one per ``warn_every_s`` with a suppressed-count
    suffix.  ``asyncio.CancelledError`` is always fatal.
    """

    def __init__(self, *, attempts=3, base_delay_s: float = 0.0,
                 max_delay_s: Optional[float] = None,
                 multiplier: float = 2.0, jitter: bool = True,
                 attempt_timeout_s: Optional[float] = None,
                 total_timeout_s: Optional[float] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 name: Optional[str] = None, fallback=propagate,
                 rng: Optional[random.Random] = None,
                 registry=None, warn_every_s: float = WARN_EVERY_S):
        self.attempts = attempts
        self.base_delay_s = base_delay_s
        self.max_delay_s = (base_delay_s if max_delay_s is None
                            else max_delay_s)
        self.multiplier = multiplier
        self.jitter = jitter
        self.attempt_timeout_s = attempt_timeout_s
        self.total_timeout_s = total_timeout_s
        self.breaker = breaker
        self.name = name
        self.fallback = fallback
        self._rng = rng if rng is not None else random.Random()
        self._registry = registry
        self._warn = WarnRateLimiter(warn_every_s)

    def backoff(self, n: int, prev: float) -> float:
        """Sleep before retry ``n`` (1-based), given the previous sleep."""
        if self.base_delay_s <= 0.0:
            return 0.0
        if not self.jitter:
            return min(self.max_delay_s,
                       self.base_delay_s * self.multiplier ** (n - 1))
        return min(self.max_delay_s,
                   self._rng.uniform(self.base_delay_s,
                                     max(prev, self.base_delay_s) * 3.0))

    async def call(self, fn, *args, name: Optional[str] = None,
                   fallback=_UNSET, **kwargs):
        """Invoke ``await fn(*args, **kwargs)`` under this policy."""
        from tmhpvsim_tpu.obs import metrics as obs_metrics

        qualname = name or self.name or getattr(
            fn, "__qualname__", repr(fn))
        fb = self.fallback if fallback is _UNSET else fallback
        unbounded = self.attempts is forever
        deadline = (None if self.total_timeout_s is None
                    else time.monotonic() + self.total_timeout_s)
        n = 0
        prev = self.base_delay_s
        while True:
            if self.breaker is not None and not self.breaker.allow():
                raise BreakerOpenError(
                    f"{qualname}: circuit breaker "
                    f"{self.breaker.name!r} is open")
            try:
                if self.attempt_timeout_s is not None:
                    result = await asyncio.wait_for(
                        fn(*args, **kwargs), self.attempt_timeout_s)
                else:
                    result = await fn(*args, **kwargs)
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                n += 1
                # per-qualname counters against the CURRENT process
                # default registry (looked up per event, not cached at
                # construction: apps swap registries per run), unless a
                # registry was bound explicitly (the serve stack)
                reg = self._registry or obs_metrics.get_registry()
                reg.counter(f"retry.attempts.{qualname}").inc()
                reg.counter("resilience.retries_total").inc()
                if self.breaker is not None:
                    self.breaker.record_failure()
                out_of_attempts = not unbounded and n >= self.attempts
                out_of_time = (deadline is not None
                               and time.monotonic() >= deadline)
                if out_of_attempts or out_of_time:
                    reg.counter(f"retry.exhausted.{qualname}").inc()
                    reg.counter("resilience.giveups_total").inc()
                    # WARN on exhaustion whichever way it resolves: the
                    # fallback path would otherwise swallow the failure
                    # silently (only per-attempt INFO lines exist)
                    why = ("re-raising" if fb is propagate
                           else "applying fallback")
                    if out_of_attempts:
                        logger.warning(
                            "%s exhausted %d attempt(s); final failure "
                            "%s: %s (%s)", qualname, n,
                            type(exc).__name__, exc, why)
                    else:
                        logger.warning(
                            "%s exceeded its %.1f s retry budget after "
                            "%d attempt(s); final failure %s: %s (%s)",
                            qualname, self.total_timeout_s, n,
                            type(exc).__name__, exc, why)
                    if fb is propagate:
                        raise
                    if callable(fb):
                        res = fb(exc)
                        if inspect.isawaitable(res):
                            res = await res
                        return res
                    return fb
                # a server-supplied retry_after hint (typed busy /
                # unavailable rejections, serve/schema.py) overrides the
                # blind jittered backoff: the far end knows its queue
                # depth and breaker reset better than our dice do
                hint = getattr(exc, "retry_after_s", None)
                if hint is not None:
                    prev = delay = max(0.0, float(hint))
                else:
                    prev = delay = self.backoff(n, prev)
                if deadline is not None:
                    delay = min(delay,
                                max(0.0, deadline - time.monotonic()))
                if unbounded:
                    # a forever policy is a reconnect loop: its failures
                    # deserve WARN visibility, but rate-limited so a
                    # flapping broker cannot flood stderr
                    self._warn.warn(
                        logger,
                        "%s failed (%s: %s); retrying in %.1f s "
                        "(attempt %s)", qualname, type(exc).__name__,
                        exc, delay, n)
                else:
                    logger.info(
                        "%s failed (%s: %s); retrying in %.1f s "
                        "(attempt %s)", qualname, type(exc).__name__,
                        exc, delay, f"{n}/{self.attempts}")
                await asyncio.sleep(delay)
            else:
                if self.breaker is not None:
                    self.breaker.record_success()
                return result

    def retrying(self, func):
        """Decorator form: wrap an async callable under this policy."""

        @functools.wraps(func)
        async def wrapper(*args, **kwargs):
            return await self.call(func, *args,
                                   name=func.__qualname__, **kwargs)

        return wrapper


def asyncretry(func=None, *, attempts=3, delay: float = 0.0,
               fallback=propagate):
    """Decorator: retry an async callable on exception.

    Reference semantics (utils.py:69-161) preserved exactly — constant
    ``delay`` between attempts, ``forever`` sentinel, fallback policy,
    ``CancelledError`` fatal — now expressed as a
    :class:`ResiliencePolicy` with jitter off and multiplier 1.  Usable
    bare (``@asyncretry``) or parameterised
    (``@asyncretry(delay=5, attempts=forever)``).
    """
    if func is None:
        return functools.partial(
            asyncretry, attempts=attempts, delay=delay, fallback=fallback
        )

    policy = ResiliencePolicy(attempts=attempts, base_delay_s=delay,
                              max_delay_s=delay, multiplier=1.0,
                              jitter=False, fallback=fallback)

    @functools.wraps(func)
    async def wrapper(*args, **kwargs):
        return await policy.call(func, *args, name=func.__qualname__,
                                 **kwargs)

    return wrapper


def reconnect_policy(name: Optional[str] = None,
                     **overrides) -> ResiliencePolicy:
    """The stack's standard reconnect-and-resubscribe policy: retry
    forever with decorrelated jitter between 0.5 s and 5 s (the old
    fixed 5 s reconnect sleep is now the cap, so brief broker blips
    recover in well under a second)."""
    kwargs = dict(attempts=forever, base_delay_s=0.5, max_delay_s=5.0,
                  name=name)
    kwargs.update(overrides)
    return ResiliencePolicy(**kwargs)
