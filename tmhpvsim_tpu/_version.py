"""SCM-derived version without vendored machinery.

The reference ships 2,342 lines of versioneer boilerplate
(versioneer.py + tmhpvsim/_version.py; setup.cfg:1-6) to derive versions
from git tags.  Same capability here in a few dozen lines: the installed
package reports its distribution version; a git checkout derives
``tag[+N.ghash]`` from ``git describe`` (versioneer's tag-distance-hash
idea as a PEP 440 local version), falling back to the static base when
git or tags are absent.  Resolution is LAZY (module ``__getattr__``):
importing the package never shells out to git — only reading
``__version__`` does, once.
"""

from __future__ import annotations

import os
import re
import subprocess

BASE_VERSION = "0.1.0"

_DESCRIBE_RE = re.compile(
    r"^v?(?P<tag>.+?)(?:-(?P<n>\d+)-g(?P<hash>[0-9a-f]+))?"
    r"(?P<dirty>-dirty)?$"
)


def _git_describe() -> str | None:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # exists, not isdir: in worktrees/submodules .git is a FILE pointing
    # at the real gitdir (git -C handles both)
    if not os.path.exists(os.path.join(repo, ".git")):
        return None
    try:
        r = subprocess.run(
            ["git", "-C", repo, "describe", "--tags", "--always", "--dirty"],
            capture_output=True, text=True, timeout=5,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    return r.stdout.strip() or None if r.returncode == 0 else None


def get_version() -> str:
    """Best available version: installed metadata, else parsed
    ``git describe`` (exact tag -> ``tag``; past a tag ->
    ``tag+N.ghash``; untagged repo -> ``base+ghash``; ``.dirty``
    appended when the tree is modified), else the static base."""
    try:
        from importlib.metadata import version

        v = version("tmhpvsim-tpu")
        if v and v != BASE_VERSION:
            return v
    except Exception:
        pass
    desc = _git_describe()
    if not desc:
        return BASE_VERSION
    m = _DESCRIBE_RE.match(desc)
    if m is None:
        return BASE_VERSION
    dirty = ".dirty" if m.group("dirty") else ""
    if m.group("hash"):          # tag-N-ghash: commits past a tag
        return (f"{m.group('tag')}+{m.group('n')}.g{m.group('hash')}"
                f"{dirty}")
    if re.fullmatch(r"[0-9a-f]+", m.group("tag")):  # bare hash: no tags
        return f"{BASE_VERSION}+g{m.group('tag')}{dirty}"
    return f"{m.group('tag')}{'+' + dirty[1:] if dirty else ''}"


def __getattr__(name: str) -> str:
    if name == "__version__":
        v = get_version()
        globals()["__version__"] = v  # cache: resolve once per process
        return v
    raise AttributeError(name)
