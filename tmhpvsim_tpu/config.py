"""Configuration dataclasses for tmhpvsim-tpu.

The reference hard-codes its site (Munich rooftop, pvmodel.py:19-30) and has
no config objects; here every knob is an explicit frozen dataclass so that a
whole simulation is a pure function of (config, PRNG seed, time grid) — the
property that makes checkpoint/resume and multi-chip sharding trivial.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from tmhpvsim_tpu.data import LINKE_TURBIDITY_MONTHLY_MUNICH


@dataclasses.dataclass(frozen=True)
class Site:
    """A PV plant site. Defaults replicate the reference's fixed Munich plant
    (pvmodel.py:19-30): Hanwha 250 W module + ABB micro-inverter, tilt equal
    to latitude, facing south."""

    latitude: float = 48.12
    longitude: float = 11.60
    altitude: float = 34.0
    surface_tilt: float = 48.12
    surface_azimuth: float = 180.0     # south
    albedo: float = 0.25
    timezone: str = "Europe/Berlin"
    linke_turbidity_monthly: tuple = LINKE_TURBIDITY_MONTHLY_MUNICH


#: columns SiteGrid.from_csv reads (others in the file are ignored)
_SITE_CSV_COLUMNS = frozenset({
    "latitude", "longitude", "altitude", "surface_tilt",
    "surface_azimuth", "albedo",
})

#: valid ranges for the geometry columns, inclusive: a CSV row outside
#: them is a data-entry error that must be refused by line, never fed
#: into the solar-geometry chain as silent NaN/garbage
_SITE_CSV_RANGES = {
    "latitude": (-90.0, 90.0),
    "longitude": (-180.0, 180.0),
    "altitude": (-430.0, 9000.0),
    "surface_tilt": (0.0, 90.0),
    "surface_azimuth": (0.0, 360.0),
    "albedo": (0.0, 1.0),
}


def _check_csv_range(path, line_num, name, value) -> None:
    rng = _SITE_CSV_RANGES.get(name)
    if rng is None:
        return
    lo, hi = rng
    import math as _math

    if not (_math.isfinite(value) and lo <= value <= hi):
        raise ValueError(
            f"{path} line {line_num}: {name}={value!r} outside "
            f"[{lo:g}, {hi:g}]")


@dataclasses.dataclass(frozen=True)
class SiteGrid:
    """Per-chain site parameters for multi-site runs (BASELINE config #3:
    "10k-site lat/lon grid").

    Each field is a length-n sequence; chain i simulates site i with its
    solar geometry evaluated *on device* from a float32-safe split-time
    representation (models/solar.py device_geometry) — host float64
    precompute per site would not scale.  The timezone (and hence the
    stochastic model's rollover calendar) and the turbidity climatology are
    shared across the grid; per-site climatologies can be added by widening
    ``linke_turbidity_monthly`` to one row per site.
    """

    latitude: tuple
    longitude: tuple
    altitude: tuple
    surface_tilt: tuple
    surface_azimuth: tuple
    albedo: tuple = None
    timezone: str = "Europe/Berlin"
    linke_turbidity_monthly: tuple = LINKE_TURBIDITY_MONTHLY_MUNICH

    def __post_init__(self):
        n = len(self.latitude)
        for f in ("longitude", "altitude", "surface_tilt",
                  "surface_azimuth"):
            if len(getattr(self, f)) != n:
                raise ValueError(f"SiteGrid.{f} must have length {n}")
        if self.albedo is None:
            object.__setattr__(self, "albedo", (0.25,) * n)
        elif len(self.albedo) != n:
            raise ValueError(f"SiteGrid.albedo must have length {n}")

    def __len__(self):
        return len(self.latitude)

    @classmethod
    def from_csv(cls, path: str, **kw):
        """A site list from a CSV with header.  Required columns
        ``latitude``, ``longitude``; optional ``altitude`` (default 100 m),
        ``surface_tilt`` (default: the site's latitude — the reference's
        tilt-equals-latitude convention, pvmodel.py:24), ``surface_azimuth``
        (default 180 = south), ``albedo`` (default 0.25).  Extra columns
        are ignored, so an asset-register export works as-is."""
        import csv as _csv

        rows = []
        with open(path, newline="") as f:
            reader = _csv.DictReader(f)
            cols = set(reader.fieldnames or ()) & _SITE_CSV_COLUMNS
            missing = {"latitude", "longitude"} - cols
            if missing:
                raise ValueError(
                    f"{path}: missing required column(s) {sorted(missing)}"
                )
            for row in reader:
                vals = {}
                for k in cols:
                    v = row.get(k)
                    if v is None or v == "":  # ragged row / blank cell
                        continue
                    try:
                        vals[k] = float(v)
                    except ValueError:
                        raise ValueError(
                            f"{path} line {reader.line_num}: bad value "
                            f"{v!r} for {k}"
                        ) from None
                    _check_csv_range(path, reader.line_num, k, vals[k])
                if "latitude" not in vals or "longitude" not in vals:
                    raise ValueError(
                        f"{path} line {reader.line_num}: latitude and "
                        "longitude are required in every row"
                    )
                rows.append(vals)
        if not rows:
            raise ValueError(f"{path}: no data rows")

        def col(name, default=None):
            return tuple(
                r.get(name, r["latitude"] if default == "latitude"
                      else default) for r in rows
            )

        return cls(
            latitude=col("latitude"),
            longitude=col("longitude"),
            altitude=col("altitude", 100.0),
            surface_tilt=col("surface_tilt", "latitude"),
            surface_azimuth=col("surface_azimuth", 180.0),
            albedo=col("albedo", 0.25),
            **kw,
        )

    @classmethod
    def regular(cls, lat_range, lon_range, n_lat: int, n_lon: int,
                altitude: float = 100.0, tilt=None, azimuth: float = 180.0,
                **kw):
        """A regular n_lat x n_lon lat/lon mesh; tilt defaults to latitude
        (the reference's tilt-equals-latitude convention, pvmodel.py:24)."""
        import numpy as _np

        lats = _np.linspace(*lat_range, n_lat)
        lons = _np.linspace(*lon_range, n_lon)
        glat, glon = _np.meshgrid(lats, lons, indexing="ij")
        glat, glon = glat.ravel(), glon.ravel()
        tilts = glat if tilt is None else _np.full_like(glat, tilt)
        n = glat.size
        return cls(
            latitude=tuple(glat),
            longitude=tuple(glon),
            altitude=(altitude,) * n,
            surface_tilt=tuple(tilts),
            surface_azimuth=(azimuth,) * n,
            **kw,
        )


def slice_grid(grid: Optional[SiteGrid], off: int, n: int
               ) -> Optional[SiteGrid]:
    """``grid`` restricted to sites [off, off+n) — the per-chain site rows
    a chain slab (or an autotune probe) of those chains simulates.  None
    passes through (single-site configs have no grid to slice)."""
    if grid is None:
        return None
    return dataclasses.replace(
        grid,
        latitude=tuple(grid.latitude[off:off + n]),
        longitude=tuple(grid.longitude[off:off + n]),
        altitude=tuple(grid.altitude[off:off + n]),
        surface_tilt=tuple(grid.surface_tilt[off:off + n]),
        surface_azimuth=tuple(grid.surface_azimuth[off:off + n]),
        albedo=tuple(grid.albedo[off:off + n]),
    )


@dataclasses.dataclass(frozen=True)
class ModelOptions:
    """Behavioural switches for the stochastic model.

    The reference contains latent bugs on its runtime path (SURVEY.md §2.2);
    each gets an explicit policy here instead of silent bug-for-bug porting:

    * ``persistent_cloud_chain`` — the reference *documents* a persistent
      Markov chain (cloud_cover_hourly.py:1-21) but its hourly sampler
      constructs a fresh generator per draw (clearskyindexmodel.py:61-63), so
      every hourly cloud-cover value is a single step from state 1.0 (i.e.
      i.i.d. near-overcast draws).  Default True = the documented persistent
      chain; False reproduces the reference's accidental i.i.d. behaviour.
    * ``swap_covered_branches`` — reference composes the *clear*-sky samplers
      when covered==1 and the *cloudy* samplers when covered==0
      (clearskyindexmodel.py:149-160), which reads inverted vs. the binary
      process semantics (cloud_cover_binary.py:109-117).  Default False keeps
      the reference's branch assignment so statistical parity holds; True
      applies the arguably-intended assignment.
    * ``advance_cloudy_hour`` — the reference's rollover cascade never
      advances the cloudy-csi sampler (no ``next`` call for it anywhere in
      clearskyindexmodel.py:101-111), so that sampler interpolates between
      its two construction-time draws forever.  Default True advances it on
      hour rollovers (evident intent); False reproduces the frozen pair.
    * the ``gamma.pdf(x, ...)`` NameError in the 6/8<=cc<7/8 band
      (clearskyindexmodel.py:80) is unconditionally fixed to ``gamma.rvs``
      (a crash is not behaviour worth reproducing).
    """

    persistent_cloud_chain: bool = True
    swap_covered_branches: bool = False
    advance_cloudy_hour: bool = True
    #: cap applied to hourly cloud cover before driving the binary renewal
    #: process (cloud_cover_binary.py:71)
    max_binary_cloudcover: float = 0.95


@dataclasses.dataclass(frozen=True)
class Plan:
    """A fully-RESOLVED execution plan: the knobs the engine actually runs
    with, after ``'auto'`` defaults, the autotuner, or a cache entry have
    been applied (engine/autotune.py).

    Unlike the corresponding ``SimConfig`` fields, nothing here is
    symbolic: ``block_impl`` is one of the three concrete formulations,
    ``stats_fusion`` one of the two concrete topologies, and
    ``slab_chains`` the concrete chain-slab size the ``SlabScheduler``
    executes (``slab_chains >= n_chains`` means no slabbing).  Every
    candidate plan of one config produces the same simulation up to float
    reassociation — within one ``block_impl``, unroll and slab variations
    are BIT-identical (keyed construction; tested in
    tests/test_autotune.py) — so plan choice is a pure performance
    decision.
    """

    #: resolved block formulation: 'wide' | 'scan' | 'scan2'
    block_impl: str
    #: lax.scan unroll factor (SimConfig.scan_unroll)
    scan_unroll: int
    #: resolved reduce-mode jit topology: 'fused' | 'split'
    stats_fusion: str
    #: chains per sequential slab; >= n_chains disables slabbing
    slab_chains: int
    #: provenance: 'static' (auto-defaults, no measurement) | 'probe'
    #: (measured this process) | 'cache' (persisted probe result) |
    #: 'broadcast' (received from process 0 on a multi-host mesh)
    source: str = "static"
    #: resolved in-graph telemetry level: 'off' | 'light' | 'full'
    #: (obs/telemetry.py).  Not a tuned knob — carried on the Plan so the
    #: engine builds its jits from one resolved object; autotune cache
    #: entries never persist it (engine/autotune.py re-applies the
    #: config's request on every cache hit).
    telemetry: str = "off"
    #: blocks executed per device dispatch (the multi-block fused
    #: dispatch, engine/simulation.py): K consecutive blocks run as one
    #: outer lax.scan inside a single jit, eliminating K-1 host
    #: round-trips per dispatch.  Always >= 1 here (SimConfig's 0 = auto
    #: is resolved by engine/autotune.py).  Purely a dispatch-granularity
    #: knob: per-block accumulator snapshots and telemetry deltas are
    #: stacked out of the scan, so checkpoints, the drift sentinel and
    #: trace instants keep their per-block boundaries and the outputs are
    #: bit-identical to per-block dispatch (tested in
    #: tests/test_executor.py).
    blocks_per_dispatch: int = 1
    #: resolved fleet-analytics level: 'off' | 'risk' | 'full'
    #: (obs/analytics.py).  Not a tuned knob — carried on the Plan so the
    #: engine builds its jits from one resolved object; autotune cache
    #: entries never persist it (engine/autotune.py re-applies the
    #: config's request on every cache hit).
    analytics: str = "off"
    #: resolved compute dtype for the per-second stream/physics path:
    #: 'f32' (the historical behaviour — byte-identical HLO) | 'bf16'
    #: (pre-drawn RNG streams, shared-site geometry and the PV physics
    #: chain run in bfloat16; all accumulators — reduce stats,
    #: TelemetryAcc, FleetAcc — and the csi/renewal scan carry stay
    #: f32/int32, so merges remain bit-exact and the drift sentinel vs
    #: the f64 golden mirror stays the correctness gate).  The autotuner
    #: may only select 'bf16' when the sentinel passes on the probe
    #: (engine/autotune.py).
    compute_dtype: str = "f32"
    #: resolved transcendental-kernel implementation for the solar/pv
    #: models: 'exact' (jnp's libm-equivalent ops — byte-identical HLO)
    #: | 'table' (minimax polynomials + the day-of-year lookup table,
    #: models/tables.py; validated against the f64 golden to published
    #: max-ULP bounds and to 1e-5 on end-of-run reduce stats).  Same
    #: sentinel gate as ``compute_dtype`` under the autotuner.
    kernel_impl: str = "exact"
    #: resolved RNG batching strategy for the scan-family block steps:
    #: 'scan' (the historical behaviour — byte-identical HLO: the flat
    #: scan pre-draws per-block streams, scan2 hashes one minute tile
    #: per outer step, wide hashes inside the producer) | 'block' (ALL
    #: of a block's second-noise draws are generated as one batched
    #: counter-mode tensor BEFORE the scan — same ``fold_in``
    #: global-minute keying, so every value is bit-identical to 'scan'
    #: (tested in tests/test_rng_batch.py) and the scan body reduces to
    #: a gather; the mega-dispatch path pre-generates per inner block
    #: inside the outer scan body to bound HBM at one block's streams).
    #: Same sentinel gate as ``compute_dtype`` under the autotuner.
    rng_batch: str = "scan"
    #: resolved solar-geometry evaluation stride in seconds: 1 (the
    #: historical per-second evaluation — byte-identical HLO) | 30 | 60
    #: (the PSA solar-position/geometry chain is evaluated on a
    #: stride-s grid and the trig-free fields — cos_zenith, cos_aoi,
    #: clear-sky irradiance terms — are linearly interpolated to 1 Hz;
    #: error vs the per-second float64 oracle is bounded by
    #: models/solar.py STRIDE_MAX_ABS_ERR and the end-of-run reduce
    #: stats hold the field-scale 1e-5 contract, tests/test_geom_stride
    #: .py).  Same sentinel gate as ``compute_dtype`` under the
    #: autotuner.
    geom_stride: int = 1


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """One simulation run: the time grid, the batch, and the output mode.

    The performance knobs (``block_impl``, ``scan_unroll``,
    ``stats_fusion`` and the chain-slab size) are REQUESTS: the engine
    resolves them into a concrete :class:`Plan` at construction —
    statically when ``tune='off'``, by measured probe (or a persisted
    probe result) when ``tune='auto'``/``'force'`` (engine/autotune.py).
    ``Simulation.plan`` records what actually ran.
    """

    start: str = "2019-09-05 12:00:00"   # naive local wall time at `site.timezone`
    duration_s: int = 86_400             # simulated seconds (1 Hz grid)
    n_chains: int = 1                    # independent stochastic realisations
    seed: int = 0
    #: Chain-slab support for runs bigger than the single-chip sweet spot
    #: (measured round 5 on TPU v5e: the scan-fused block runs ~14x
    #: faster per site-second at <=65536 chains than at 262144, where the
    #: unrolled body's live set spills VMEM).  A slab simulates chains
    #: [chain_offset, chain_offset + n_chains) of a notional
    #: ``n_chains_total``-chain run: per-chain keys come from
    #: split(seed-key, n_chains_total) sliced at the offset, so the
    #: concatenation of any slab partition is BIT-IDENTICAL to the
    #: unslabbed run (threefry split is counter-based; tested in
    #: tests/test_engine.py).  None => n_chains (no slabbing).
    n_chains_total: Optional[int] = None
    chain_offset: int = 0
    site: Site = dataclasses.field(default_factory=Site)
    #: per-chain sites (overrides `site`/`n_chains`: chain i = grid site i)
    site_grid: Optional[SiteGrid] = None
    #: heterogeneous fleet: per-site geometry + capacity/inverter/weather-
    #: regime/demand columns and cohort tags as one batched pytree on the
    #: chain axis (tmhpvsim_tpu.fleet.FleetParams; chain i = fleet row i,
    #: overrides `n_chains`).  A non-uniform-geometry fleet derives
    #: `site_grid` at engine construction; a uniform one lowers onto the
    #: scalar `site` path (byte-identical HLO when the electrical /
    #: stochastic columns are neutral).  Typed Optional[object] only to
    #: avoid a config -> fleet -> config import cycle.
    fleet: Optional[object] = None
    options: ModelOptions = dataclasses.field(default_factory=ModelOptions)

    #: meter demand upper bound [W]; reference draws uniform [0, 9000)
    #: (metersim.py:49-51)
    meter_max_w: float = 9000.0

    #: seconds per scan block (device memory / dispatch granularity);
    #: must be a multiple of 60 so blocks span whole minute-sampler
    #: intervals and every block compiles to the same shapes
    block_s: int = 8640

    #: 'trace'    -> per-second (meter, pv, residual) arrays are returned
    #: 'reduce'   -> only per-chain running statistics (sum/min/max/count)
    #: 'ensemble' -> per-second fleet means (one psum per block when
    #:               sharded; only (block_s,) vectors reach the host)
    output: str = "trace"

    #: computation dtype for the per-second path on device
    dtype: str = "float32"

    #: reduce-mode block formulation.  'wide' generates every per-second
    #: stream as (n_chains, block_s) arrays (batched RNG + elementwise
    #: pipeline + a minimal renewal scan) — best on XLA:CPU, but on TPU it
    #: is HBM-bandwidth-bound: ~20 (n_chains, block_s) f32 intermediates
    #: (sampler-interpolation gathers, physics stages, scan inputs) each
    #: round-trip HBM (measured v5e: ~55 GB accessed per 65536x1080 block,
    #: rate flat under a 2.3x flops change).  'scan' runs ONE lax.scan
    #: over the block's seconds with the entire pipeline (interpolation,
    #: renewal, physics, statistics fold) in the body on (n_chains,)
    #: vectors — nothing of shape (n_chains, block_s) is materialised
    #: except the three pre-drawn RNG streams, cutting HBM traffic ~20x.
    #: Identical RNG streams, so both produce the same simulation up to
    #: float reassociation (tested).  'scan2' nests the scan per minute,
    #: drawing each minute's RNG tile inside the outer body so even the
    #: pre-drawn streams never materialise at (n_chains, block_s) —
    #: bit-identical draws (benchmarks/PERF_ANALYSIS.md §4a).  'auto':
    #: scan on accelerators, wide on CPU.  Applies to reduce AND ensemble
    #: mode (each impl has its own series step); trace mode needs the
    #: wide arrays anyway.
    block_impl: str = "auto"

    #: lax.scan unroll factor for the per-second scan (both impls): keeps
    #: the carry in registers across iterations instead of round-tripping
    #: HBM (measured ~2x on the wide impl's renewal scan)
    scan_unroll: int = 8

    #: producer/stats jit topology for reduce mode.  'split' keeps the
    #: block step and the statistics fold in separate jits so XLA cannot
    #: re-fuse the stats backwards into a duplicated producer chain — the
    #: right call on XLA:CPU (measured: 2.56 vs 1.13 GFLOP compiled, ~3.5x
    #: wall; see Simulation._block_step).  'fused' runs producer + stats +
    #: accumulator merge as ONE jit: XLA:TPU does not duplicate the
    #: producer, and fusing means the (n_chains, block_s) meter/pv arrays
    #: never round-trip HBM — the stats fold consumes them from registers
    #: (measured on TPU v5e: the split path writes + re-reads ~566 MB per
    #: 65536x1080 block).  'auto' picks fused on accelerators, split on CPU.
    stats_fusion: str = "auto"

    #: blocks per device dispatch for reduce/ensemble/trace loops: K
    #: consecutive blocks run as one outer lax.scan inside a single jit
    #: (engine/simulation.py), so the host pays one dispatch + one sync
    #: per K blocks instead of per block.  0 = auto (resolve statically
    #: to 1; under ``tune='auto'``/``'force'`` the autotuner probes it as
    #: a grid axis).  Values >= 1 are used as-is.  Output is
    #: bit-identical to per-block dispatch; checkpoints land on megablock
    #: boundaries (apps gate saves on ``Simulation.state_block``).
    blocks_per_dispatch: int = 0

    #: runtime autotuning of the performance knobs (engine/autotune.py).
    #: 'off'   -> resolve 'auto' knobs statically (backend heuristics; the
    #:            historical behaviour, zero overhead)
    #: 'auto'  -> look up a measured plan in the persistent per-device
    #:            cache (~/.cache/tmhpvsim_tpu/autotune.json, overridable
    #:            via TMHPVSIM_AUTOTUNE_CACHE); on a miss, time a small
    #:            candidate grid (block_impl x scan_unroll x slab size)
    #:            with short real-block probes, pick the fastest and
    #:            persist it — subsequent runs at the same key pay zero
    #:            probe cost
    #: 'force' -> re-probe even on a cache hit (refresh a stale entry)
    tune: str = "off"

    #: JAX PRNG implementation for every stochastic draw.  'threefry2x32'
    #: (the JAX default) is fully counter-based and splittable but costs
    #: ~100 ALU ops per 64 bits — at one draw per site-second it is the
    #: single largest cost of the block step (measured on TPU v5e).
    #: 'rbg' keeps threefry for key derivation (split/fold_in — here only
    #: per chain and per minute) but generates the bits with the TPU's
    #: hardware RngBitGenerator, trading the strict cross-backend
    #: reproducibility guarantee for hardware-generated bits.  Measured
    #: history: in the round-4 wide formulation rbg cut compiled flops
    #: 2.26x (rate +<3%, HBM-bound); on the CURRENT TPU backend its
    #: vmapped per-chain draws serialize (~8 s vs 3.5 ms per 65536x1080
    #: scan-fused block, round 5 — benchmarks/PERF_ANALYSIS.md §7a), so
    #: threefry is both the default and the fast mode.  Statistical
    #: quality is equivalent for Monte-Carlo use; all parity/KS tests pass
    #: under either (the golden model is seeded numpy, not stream-matched).
    prng_impl: str = "threefry2x32"

    #: compute dtype for the per-second stream/physics path.  'auto'
    #: resolves to 'f32' (the historical path, byte-identical HLO) unless
    #: the autotuner's sentinel-gated probe selects 'bf16'; 'f32'/'bf16'
    #: pin it.  bf16 halves the HBM bytes of the pre-drawn RNG streams
    #: and the shared-site geometry and runs the PV physics chain in
    #: bfloat16 — accumulators (reduce stats, TelemetryAcc, FleetAcc)
    #: and the csi/renewal scan carry ALWAYS stay f32/int32, so slab /
    #: shard / fused-dispatch merges remain bit-exact and the PR-3 drift
    #: sentinel vs the f64 golden mirror remains the correctness gate.
    #: Requesting bf16 with ``telemetry='off'`` auto-escalates telemetry
    #: to 'light' so the sentinel actually watches the run.
    compute_dtype: str = "auto"

    #: transcendental-kernel implementation for the solar/pv models.
    #: 'auto' resolves to 'exact' (jnp sin/cos/exp/log/arccos —
    #: byte-identical HLO) unless the autotuner's sentinel-gated probe
    #: selects 'table'; 'exact'/'table' pin it.  'table' swaps the
    #: irradiance chain's transcendentals for minimax polynomials plus a
    #: 366-entry day-of-year lookup table (models/tables.py), validated
    #: against the f64 golden to published max-ULP bounds and to 1e-5 on
    #: end-of-run reduce stats (tests/test_precision.py).
    kernel_impl: str = "auto"

    #: RNG batching strategy for the scan-family block steps.  'auto'
    #: resolves to 'scan' (the historical behaviour, byte-identical
    #: HLO) unless the autotuner's sentinel-gated probe selects
    #: 'block'; 'scan'/'block' pin it.  'block' hoists ALL of a
    #: block's second-noise draws (csi u/z and the meter stream) into
    #: batched counter-mode tensors generated before the scan — the
    #: per-second body becomes a pure gather.  Keying is the same
    #: ``fold_in`` global-minute scheme, so the simulation is
    #: BIT-identical to 'scan' on every impl, sharded and
    #: mega-dispatched included (tests/test_rng_batch.py); the choice
    #: is purely a loop-structure/perf decision (ROADMAP item 3: batch
    #: random generation outside the sequential loop).
    rng_batch: str = "auto"

    #: solar-geometry evaluation stride in seconds.  0 = auto: resolves
    #: to 1 (per-second evaluation, byte-identical HLO) unless the
    #: autotuner's sentinel-gated probe selects a coarser stride.
    #: Explicit 1/30/60 pin it: the PSA solar-position solve changes by
    #: <0.01° between adjacent seconds, so geometry is evaluated every
    #: ``geom_stride`` seconds and the trig-free fields are linearly
    #: interpolated to 1 Hz (models/solar.py ``strided_geometry``;
    #: published float64-oracle bound STRIDE_MAX_ABS_ERR, field-scale
    #: 1e-5 reduce-stats contract over a simulated year —
    #: tests/test_geom_stride.py).  ``block_s`` must be a multiple of
    #: the stride (it already is: both divide 60).
    geom_stride: int = 0

    #: double-buffered host output for the trace/blocks loop
    #: (engine/simulation.py ``_iter_blocks``): 'auto' overlaps device
    #: dispatch of block N+1 with the host gather/CSV/telemetry flush of
    #: block N (donation-safe: only the carried state is donated, never
    #: the gathered outputs); 'off' keeps the strictly serial historical
    #: loop.  Checkpointed runs force 'off' (apps/pvsim.py): a
    #: checkpoint writer gates on ``state_block == block_index + 1``,
    #: which pipelining breaks by design.  Reduce mode is unaffected.
    output_overlap: str = "auto"

    #: in-graph numerics telemetry (obs/telemetry.py): 'off' (telemetry
    #: structurally absent from the traced graph — byte-identical HLO to
    #: a build without it), 'light' (per-field NaN/Inf counters + running
    #: moments on the scan carry, flushed per block into the metrics
    #: registry under device.* and checked by the drift sentinel), or
    #: 'full' (light + csi histogram + cloud-state occupancy).  Reduce
    #: mode only; other output modes ignore it.
    telemetry: str = "off"

    #: escalate drift-sentinel WARNs (NaN/Inf appearance, reference-band
    #: escape) to obs.sentinel.DriftError
    telemetry_strict: bool = False

    #: on-device fleet analytics (obs/analytics.py): 'off' (analytics
    #: structurally absent from the traced graph — byte-identical HLO to
    #: a build without it), 'risk' (residual-load quantile sketch,
    #: exceedance curve, loss-of-load probability, ramp-rate extremes —
    #: all integer-count/extremum leaves, so sharded/slabbed/fused runs
    #: merge bit-identically), or 'full' (risk + per-cloud-regime
    #: conditional means of meter/pv/residual).  Reduce mode only; other
    #: output modes ignore it.  Results surface as the RunReport
    #: ``fleet`` section and ``device.fleet.*`` metrics.
    analytics: str = "off"

    #: interior bins of the residual quantile sketch (per-quantile rank
    #: error is bounded by one bin's mass; 2048 is ~0.1 % on the
    #: reference run)
    analytics_bins: int = 2048

    #: loss-of-load capacity [W]; None -> 0.8 * meter_max_w
    analytics_capacity_w: Optional[float] = None

    #: consecutive exceedance seconds before loss of load registers
    analytics_lolp_k: int = 60

    #: exceedance threshold grid [W], strictly ascending; None -> the
    #: 1/8..7/8 fractions of meter_max_w
    analytics_thresholds: Optional[tuple] = None

    #: pod-scale observability (obs/pod.py): 'off' (the default — no
    #: monitor constructed, no heartbeat gathers, nothing stamped; the
    #: lowered HLO is byte-identical to a build without the axis, like
    #: telemetry/analytics) or 'on' (every block boundary gathers a
    #: per-host heartbeat row over process_allgather, computes pod-wide
    #: skew, and WARNs + counts ``pod.straggler_total`` when a host's
    #: block wall exceeds ``pod_straggler_factor`` × the pod median;
    #: surfaces as the RunReport v14 ``pod`` section and the
    #: ``pod.*`` metrics).  Host-side numpy only — never enters the
    #: traced graph.  Single-process runs gather locally (no
    #: collective), so 'on' is safe everywhere.
    pod_obs: str = "off"

    #: straggler threshold: a host whose block wall exceeds this factor
    #: times the pod-median block wall is flagged (WARN +
    #: ``pod.straggler_total``)
    pod_straggler_factor: float = 2.0

    #: semantic phase attribution (obs/attribution.py): 'off' (the
    #: default — no ``jax.named_scope`` entered anywhere, so the lowered
    #: HLO is byte-identical to a build without the axis, the same
    #: discipline as telemetry/analytics/pod_obs) or 'on' (the ~9
    #: semantic stages of the per-second chain — rng, markov, csi,
    #: geometry, physics, fleet, telemetry, analytics, collectives —
    #: are traced inside ``ph__<name>`` scopes, which land in every
    #: HLO op's ``op_name`` metadata; a device trace captured from such
    #: a build can then be split into per-phase device-time fractions
    #: and surfaced as the RunReport v15 ``attribution`` section and
    #: the ``device.phase.*`` gauges).  Purely metadata: numerics and
    #: op graphs are unchanged either way.
    phase_obs: str = "off"

    #: streaming-trace output path (obs/trace.py): per-block host-side
    #: instants land in the tracer ring and export as Chrome-trace JSON
    #: here on exit.  Pure host-side observability — never enters the
    #: traced graph and is NOT part of the checkpoint config echo
    #: (engine/checkpoint.py uses an explicit key list), so toggling it
    #: across a resume is safe.
    trace: Optional[str] = None

    #: scenario-serving batch buckets (serve/, engine/simulation.py):
    #: each entry B adds a scenario-batched reduce dispatch — the block
    #: scan with a leading (B,) vmap axis of per-request scenario knobs
    #: over the chain axis — to ``Simulation.aot_targets()``, so a
    #: server started under the persistent compile cache pre-compiles
    #: every bucket it will ever dispatch (zero fresh compiles on warm
    #: restart).  Empty (the default) leaves the batch path entirely
    #: unbuilt: nothing else in the engine reads it.  Ascending, each
    #: >= 1; the micro-batcher pads a partial batch up to the smallest
    #: bucket that fits (padding rows carry horizon_s=0 and fold
    #: nothing).
    serve_batch_sizes: tuple = ()

    #: scenario axis length M of the sharded run's device mesh
    #: (parallel/mesh.py ``make_mesh``): 0 (the default) keeps the flat
    #: 1-D ``(chains,)`` mesh; M >= 1 builds the named 2-D
    #: ``(n_devices // M, M)`` ``(chains, scenario)`` mesh.  Batch runs
    #: treat both axes as one data-parallel pool (an ``(N, 1)`` mesh is
    #: byte-identical HLO to 1-D; ``(N, M)`` is bit-identical to
    #: ``(N*M,)``); scenario SERVING maps the request batch onto the
    #: ``scenario`` axis so what-if batches parallelise across chips.
    #: Execution layout only — NOT part of the checkpoint config echo
    #: (resume under a different mesh is elastic by design).
    mesh_scenario: int = 0

    #: checkpoint generations retained on disk (engine/checkpoint.py
    #: rotation: the anchor plus the newest N ``.g<gen>`` siblings named
    #: by the sidecar manifest).  Operational robustness, not identity —
    #: NOT part of the checkpoint config echo, so changing it across a
    #: resume is safe.
    checkpoint_keep: int = 3

    #: "on" moves checkpoint serialization to a background writer thread
    #: (the scan loop pays only the device->host gather; the disk write,
    #: checksum, fsync and rotation happen off the critical path).
    #: "off" (the default) keeps today's synchronous save.  Pure host
    #: plumbing — NOT part of the checkpoint config echo.
    checkpoint_async: str = "off"

    #: seconds of preemption grace: > 0 arms a SIGTERM handler that
    #: finishes the current block, takes one final synchronous snapshot
    #: and exits cleanly (the supervisor bounds the window with SIGKILL,
    #: runtime/supervise.py).  0 keeps SIGTERM's default die-now
    #: behaviour.  Host-side lifecycle only — NOT part of the
    #: checkpoint config echo.
    preempt_grace_s: float = 0.0
