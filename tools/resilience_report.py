#!/usr/bin/env python
"""Validate + pretty-print the ``resilience`` section of run reports.

Accepts any mix of the shapes the repo's tooling writes (same intake as
``serve_report.py`` / ``fleet_report.py``):

* a bare RunReport JSON (``kind == "tmhpvsim_tpu.run_report"``);
* a bench doc — one JSON object with an embedded ``run_report`` key
  (``bench.py`` stdout lines / BENCH_*.json);
* a JSONL stream of either (bench batteries append one doc per phase).

For every embedded report carrying a ``resilience`` section (schema v7,
obs/report.py ``resilience_section``), the section is checked against
the shape that function emits — required counters, breaker sub-document
and state names, fault totals consistent with the per-point breakdown —
and printed as a readable recovery summary: resumes and supervised
restarts, retry/giveup aggregates, breaker opens/rejections and final
states, and what the chaos plan actually injected.

Exit code 0 when every *present* resilience section validates — reports
without one (healthy chaos-free runs, pre-v7 documents) are fine and
just noted, which is how ``run_tpu_round5b.sh`` consumes this
non-fatally after each bench doc.  Nonzero means a malformed section:
the resilience path wrote something ``resilience_section`` never emits.

No third-party imports: runs anywhere the repo checks out.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPORT_KIND = "tmhpvsim_tpu.run_report"

#: the counters resilience_section always emits (ints, >= 0)
_COUNTER_KEYS = ("resumes", "restarts", "retries", "giveups",
                 "faults_injected")

_BREAKER_STATES = ("closed", "half_open", "open")


def _check(cond: bool, errors: list, msg: str) -> None:
    if not cond:
        errors.append(msg)


def _is_count(v) -> bool:
    return isinstance(v, int) and not isinstance(v, bool) and v >= 0


def validate_resilience(sec) -> list:
    """Schema errors for one ``resilience`` section (empty = valid)."""
    errors: list = []
    if not isinstance(sec, dict):
        return [f"resilience section is {type(sec).__name__}, "
                f"not an object"]
    for key in _COUNTER_KEYS:
        _check(_is_count(sec.get(key)), errors,
               f"{key} missing/not a non-negative int")

    br = sec.get("breaker")
    if not isinstance(br, dict):
        errors.append("breaker missing/not an object")
    else:
        for key in ("opens", "rejected"):
            _check(_is_count(br.get(key)), errors,
                   f"breaker.{key} missing/not a non-negative int")
        states = br.get("states")
        if not isinstance(states, dict):
            errors.append("breaker.states missing/not an object")
        else:
            for name, st in states.items():
                _check(st in _BREAKER_STATES, errors,
                       f"breaker.states[{name!r}] = {st!r} not one of "
                       f"{', '.join(_BREAKER_STATES)}")

    by_point = sec.get("faults_by_point")
    if not isinstance(by_point, dict):
        errors.append("faults_by_point missing/not an object")
    else:
        for point, n in by_point.items():
            _check(_is_count(n), errors,
                   f"faults_by_point[{point!r}] not a non-negative int")
        if _is_count(sec.get("faults_injected")) and \
                all(_is_count(n) for n in by_point.values()):
            total = sum(by_point.values())
            _check(total == sec["faults_injected"], errors,
                   f"faults_by_point sums to {total} != "
                   f"faults_injected ({sec['faults_injected']})")

    rb = sec.get("resumed_block")
    if rb is not None:
        _check(_is_count(rb), errors,
               "resumed_block present but not a non-negative int")
        _check(_is_count(sec.get("resumes")) and sec["resumes"] > 0,
               errors, "resumed_block present with resumes == 0")
    return errors


def print_resilience(sec: dict, label: str) -> None:
    resumed = (f" from block {sec['resumed_block']}"
               if sec.get("resumed_block") is not None else "")
    print(f"{label}: resilience "
          f"(resumes={sec['resumes']:,}{resumed} "
          f"restarts={sec['restarts']:,} retries={sec['retries']:,} "
          f"giveups={sec['giveups']:,})")
    br = sec["breaker"]
    states = ", ".join(f"{n}={s}" for n, s in sorted(br["states"].items()))
    print(f"  breaker     opens={br['opens']:,} "
          f"rejected={br['rejected']:,}"
          + (f"  ({states})" if states else ""))
    if sec["faults_injected"]:
        points = ", ".join(f"{p}={n:,}" for p, n in
                           sorted(sec["faults_by_point"].items()))
        print(f"  chaos       injected={sec['faults_injected']:,}  "
              f"({points})")
    else:
        print("  chaos       (no faults injected)")


def _iter_docs(path: str):
    """Parsed JSON documents in ``path``: one whole-file document, or
    one per line (bench batteries write JSONL)."""
    with open(path) as f:
        text = f.read()
    try:
        yield json.loads(text)
        return
    except json.JSONDecodeError:
        pass
    for ln in text.splitlines():
        ln = ln.strip()
        if not ln.startswith("{"):
            continue
        try:
            yield json.loads(ln)
        except json.JSONDecodeError:
            continue


def _extract_reports(doc):
    """(label_suffix, report_dict) pairs embedded in one parsed doc."""
    if not isinstance(doc, dict):
        return
    if doc.get("kind") == REPORT_KIND:
        yield "", doc
        return
    rep = doc.get("run_report")
    if isinstance(rep, dict) and rep.get("kind") == REPORT_KIND:
        label = doc.get("phase") or doc.get("variant") or rep.get("app")
        yield f"[{label}]" if label else "", rep


def check_file(path: str, quiet: bool = False) -> bool:
    """Validate (and print) every resilience section in one file; True
    when all present sections pass.  A file with none passes
    trivially."""
    name = os.path.basename(path)
    try:
        docs = list(_iter_docs(path))
    except OSError as e:
        print(f"{name}: UNREADABLE ({e})", file=sys.stderr)
        return False
    found = 0
    ok = True
    for doc in docs:
        for suffix, rep in _extract_reports(doc):
            sec = rep.get("resilience")
            if sec is None:
                continue
            found += 1
            errors = validate_resilience(sec)
            if errors:
                ok = False
                print(f"{name}{suffix}: INVALID resilience section "
                      f"({len(errors)} error(s))", file=sys.stderr)
                for e in errors[:10]:
                    print(f"  {e}", file=sys.stderr)
                if len(errors) > 10:
                    print(f"  ... and {len(errors) - 10} more",
                          file=sys.stderr)
            elif not quiet:
                print_resilience(sec, f"{name}{suffix}")
    if not found and not quiet:
        print(f"{name}: no resilience section (healthy chaos-free run "
              f"or pre-v7 report)")
    return ok


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="validate + pretty-print RunReport resilience "
                    "sections (bare reports, bench docs, or JSONL of "
                    "either)")
    ap.add_argument("files", nargs="+", help="report/bench files to check")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="suppress the summaries (errors still print)")
    args = ap.parse_args(argv)

    ok = True
    for path in args.files:
        ok = check_file(path, quiet=args.quiet) and ok
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
