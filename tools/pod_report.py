#!/usr/bin/env python
"""Validate + pretty-print the ``pod`` section of run reports.

Accepts any mix of the shapes the repo's tooling writes (the same
contract as tools/mesh_report.py):

* a bare RunReport JSON (``kind == "tmhpvsim_tpu.run_report"``);
* a bench doc — one JSON object with an embedded ``run_report`` key
  (bench.py's per-phase stdout lines / BENCH_*.json, in particular the
  ``bench.py --hosts K`` artifact);
* a JSONL stream of either (bench.py batteries append one doc per
  phase: SWEEP_r05.jsonl and friends).

Every pod section found (schema v14, obs/pod.py ``PodMonitor.doc``) is
checked with ``obs.pod.validate_pod_section`` — process bounds, host
rows vs process count, skew positivity, comm_frac range — and printed
as a one-glance fleet line:

    HOSTS2.json[hosts][run_report]: pod 2 host(s), 3 block(s),
      skew max 1.42x, stragglers 0, comm 7.3%

Exit code 0 when every *present* pod section validates — reports
without one (pre-v14 documents, single-process runs, pod obs off) are
fine and just noted, which is how ``run_tpu_round5b.sh`` consumes this
non-fatally after each bench doc.  Nonzero means a malformed section:
the pod plumbing wrote something ``PodMonitor.doc`` never emits.

The only repo import is ``obs.pod`` (pure stdlib at import time): runs
anywhere the repo checks out, no jax required.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# repo-root import without installation (the tools/ scripts' pattern)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tmhpvsim_tpu.obs.pod import validate_pod_section  # noqa: E402

REPORT_KIND = "tmhpvsim_tpu.run_report"


def print_pod(sec: dict, label: str) -> None:
    skew = sec.get("skew") or {}
    line = (f"{label}: pod {sec.get('process_count')} host(s), "
            f"{sec.get('blocks_observed')} block(s), "
            f"skew max {skew.get('max_over_median')}x, "
            f"stragglers {sec.get('straggler_total')}")
    cf = sec.get("comm_frac")
    if isinstance(cf, (int, float)):
        line += f", comm {100.0 * cf:.1f}%"
    print(line)


def _iter_docs(path: str):
    """Parsed JSON documents in ``path``: one whole-file document, or
    one per line (bench batteries write JSONL)."""
    with open(path) as f:
        text = f.read()
    try:
        yield json.loads(text)
        return
    except json.JSONDecodeError:
        pass
    for ln in text.splitlines():
        ln = ln.strip()
        if not ln.startswith("{"):
            continue
        try:
            yield json.loads(ln)
        except json.JSONDecodeError:
            continue


def _extract_sections(doc):
    """(label_suffix, pod_section) pairs embedded in one parsed doc."""
    if not isinstance(doc, dict):
        return
    if doc.get("kind") == REPORT_KIND:
        if doc.get("pod") is not None:
            yield "", doc["pod"]
        return
    if "parsed" in doc and "cmd" in doc:   # driver round wrapper
        doc = doc.get("parsed") or {}
    label = doc.get("phase") or doc.get("variant") or doc.get("config")
    suffix = f"[{label}]" if label else ""
    rep = doc.get("run_report")
    if isinstance(rep, dict) and rep.get("pod") is not None:
        yield f"{suffix}[run_report]" if suffix else "[run_report]", \
            rep["pod"]


def check_file(path: str, quiet: bool = False) -> bool:
    """Validate (and print) every pod section in one file; True when
    all present sections pass.  A file with none passes trivially."""
    name = os.path.basename(path)
    try:
        docs = list(_iter_docs(path))
    except OSError as e:
        print(f"{name}: UNREADABLE ({e})", file=sys.stderr)
        return False
    found = 0
    ok = True
    for doc in docs:
        for suffix, sec in _extract_sections(doc):
            found += 1
            errors = validate_pod_section(sec)
            if errors:
                ok = False
                print(f"{name}{suffix}: INVALID pod section "
                      f"({len(errors)} error(s))", file=sys.stderr)
                for e in errors[:10]:
                    print(f"  {e}", file=sys.stderr)
                if len(errors) > 10:
                    print(f"  ... and {len(errors) - 10} more",
                          file=sys.stderr)
            elif not quiet:
                print_pod(sec, f"{name}{suffix}")
    if not found and not quiet:
        print(f"{name}: no pod section (single-process run, pod obs "
              f"off, or pre-v14 report)")
    return ok


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="validate + pretty-print RunReport pod sections "
                    "(bare reports, bench docs, or JSONL of either)")
    ap.add_argument("files", nargs="+", help="report/bench files to check")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="suppress the fleet lines (errors still print)")
    args = ap.parse_args(argv)

    ok = True
    for path in args.files:
        ok = check_file(path, quiet=args.quiet) and ok
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
