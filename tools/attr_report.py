#!/usr/bin/env python
"""Validate + pretty-print the ``attribution`` section of run reports.

Accepts any mix of the shapes the repo's tooling writes (the same
contract as tools/pod_report.py):

* a bare RunReport JSON (``kind == "tmhpvsim_tpu.run_report"``);
* a bench doc — one JSON object with an embedded ``run_report`` key,
  in particular the ``bench.py --attr DIR`` artifact, whose per-variant
  ``variants.<name>.attribution`` docs are checked too;
* a JSONL stream of either (bench.py batteries append one doc per
  phase: SWEEP_r05.jsonl and friends).

Every attribution section found (schema v15, obs/attribution.py
``attribute``) is checked with ``validate_attribution_section`` —
basis membership, non-negative seconds, fraction ranges, the
fractions-sum-plus-residual-≤-1 invariant — and printed as a
one-glance phase line:

    ATTR.json[run_report]: attribution scope 0.055s — markov 47.8%,
      physics 34.0%, geometry 13.0% (+2 more), unattributed 0.9%

Exit code 0 when every *present* attribution section validates —
reports without one (pre-v15 documents, phase_obs off) are fine and
just noted, which is how ``run_tpu_round5b.sh`` consumes this
non-fatally after each bench doc.  Nonzero means a malformed section:
the attribution plumbing wrote something ``attribute`` never emits.

The only repo import is ``obs.attribution`` (pure stdlib at import
time): runs anywhere the repo checks out, no jax required.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# repo-root import without installation (the tools/ scripts' pattern)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tmhpvsim_tpu.obs.attribution import (  # noqa: E402
    validate_attribution_section,
)

REPORT_KIND = "tmhpvsim_tpu.run_report"


def print_attribution(sec: dict, label: str) -> None:
    basis = sec.get("basis")
    line = f"{label}: attribution {basis}"
    if basis == "unavailable":
        print(line + " (trace carried nothing attributable)")
        return
    total = sec.get("total_device_s")
    if isinstance(total, (int, float)):
        line += f" {total:.3f}s"
    phases = sec.get("phases") or {}
    parts = [f"{name} {100.0 * p.get('frac', 0.0):.1f}%"
             for name, p in list(phases.items())[:3]]
    if len(phases) > 3:
        parts.append(f"(+{len(phases) - 3} more)")
    uf = sec.get("unattributed_frac")
    if isinstance(uf, (int, float)):
        parts.append(f"unattributed {100.0 * uf:.1f}%")
    if parts:
        line += " — " + ", ".join(parts)
    print(line)


def _iter_docs(path: str):
    """Parsed JSON documents in ``path``: one whole-file document, or
    one per line (bench batteries write JSONL)."""
    with open(path) as f:
        text = f.read()
    try:
        yield json.loads(text)
        return
    except json.JSONDecodeError:
        pass
    for ln in text.splitlines():
        ln = ln.strip()
        if not ln.startswith("{"):
            continue
        try:
            yield json.loads(ln)
        except json.JSONDecodeError:
            continue


def _extract_sections(doc):
    """(label_suffix, attribution_section) pairs in one parsed doc."""
    if not isinstance(doc, dict):
        return
    if doc.get("kind") == REPORT_KIND:
        if doc.get("attribution") is not None:
            yield "", doc["attribution"]
        return
    if "parsed" in doc and "cmd" in doc:   # driver round wrapper
        doc = doc.get("parsed") or {}
    label = doc.get("phase") or doc.get("variant") or doc.get("config")
    suffix = f"[{label}]" if label else ""
    # the --attr artifact: one attribution doc per traced variant
    variants = doc.get("variants")
    if isinstance(variants, dict):
        for name, v in variants.items():
            sec = isinstance(v, dict) and v.get("attribution")
            if isinstance(sec, dict):
                yield f"{suffix}[{name}]", sec
    rep = doc.get("run_report")
    if isinstance(rep, dict) and rep.get("attribution") is not None:
        yield f"{suffix}[run_report]" if suffix else "[run_report]", \
            rep["attribution"]


def check_file(path: str, quiet: bool = False) -> bool:
    """Validate (and print) every attribution section in one file; True
    when all present sections pass.  A file with none passes
    trivially."""
    name = os.path.basename(path)
    try:
        docs = list(_iter_docs(path))
    except OSError as e:
        print(f"{name}: UNREADABLE ({e})", file=sys.stderr)
        return False
    found = 0
    ok = True
    for doc in docs:
        for suffix, sec in _extract_sections(doc):
            found += 1
            errors = validate_attribution_section(sec)
            if errors:
                ok = False
                print(f"{name}{suffix}: INVALID attribution section "
                      f"({len(errors)} error(s))", file=sys.stderr)
                for e in errors[:10]:
                    print(f"  {e}", file=sys.stderr)
                if len(errors) > 10:
                    print(f"  ... and {len(errors) - 10} more",
                          file=sys.stderr)
            elif not quiet:
                print_attribution(sec, f"{name}{suffix}")
    if not found and not quiet:
        print(f"{name}: no attribution section (phase_obs off, no "
              f"scoped trace, or pre-v15 report)")
    return ok


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="validate + pretty-print RunReport attribution "
                    "sections (bare reports, bench docs, or JSONL of "
                    "either)")
    ap.add_argument("files", nargs="+", help="report/bench files to check")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="suppress the phase lines (errors still print)")
    args = ap.parse_args(argv)

    ok = True
    for path in args.files:
        ok = check_file(path, quiet=args.quiet) and ok
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
