#!/usr/bin/env python
"""Validate + pretty-print the ``serving`` section of run reports.

Accepts any mix of the shapes the repo's tooling writes (same intake as
``fleet_report.py``):

* a bare RunReport JSON (``kind == "tmhpvsim_tpu.run_report"``);
* a bench doc — one JSON object with an embedded ``run_report`` key
  (``bench.py --serve`` stdout lines / BENCH_*.json);
* a JSONL stream of either (bench batteries append one doc per phase).

For every embedded report carrying a ``serving`` section (schema v6,
obs/report.py ``serving_section``), the section is checked against the
shape that function emits — required counters, occupancy consistency,
latency-quantile ordering, conservation between requests and outcomes —
and printed as a readable SLO table with the request-coalescing ratio
(requests per fused dispatch) the micro-batcher exists to maximise.

A v16 ``serving.fleet`` sub-section (obs/report.py
``fleet_serving_section``: the router's counters plus one row per
worker) is validated too when present: router counters must be
non-negative ints, outcomes must not exceed intake, and the per-worker
request totals must PARTITION the router's forwarded total —
``sum(workers[].requests) == router.routed + router.rerouted`` — i.e.
every request the router forwarded landed on exactly one worker life
and none materialised out of thin air.  Reports without the
sub-section (single-worker serves, pre-v16) validate as before.

Exit code 0 when every *present* serving section validates — reports
without one (non-serving runs, pre-v6 documents) are fine and just
noted, which is how ``run_tpu_round5b.sh`` consumes this non-fatally
after each bench doc.  Nonzero means a malformed section: the serving
path wrote something ``serving_section`` never emits.

No third-party imports: runs anywhere the repo checks out.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPORT_KIND = "tmhpvsim_tpu.run_report"

_NUM = (int, float)

#: the counters serving_section always emits (ints, >= 0)
_COUNTER_KEYS = ("requests", "replies", "rejected", "timeouts",
                 "batches", "in_flight")

#: the latency sub-documents (_latency_doc shape, or null when the
#: histogram never observed)
_LATENCY_KEYS = ("queue_wait", "dispatch", "reply_latency")


def _check(cond: bool, errors: list, msg: str) -> None:
    if not cond:
        errors.append(msg)


def _validate_latency(doc, name: str, errors: list) -> None:
    if doc is None:
        return
    if not isinstance(doc, dict):
        errors.append(f"{name} neither object nor null")
        return
    for key in ("count", "mean_s", "min_s", "max_s",
                "p50_s", "p90_s", "p99_s"):
        _check(isinstance(doc.get(key), _NUM), errors,
               f"{name}.{key} missing/non-numeric")
    if all(isinstance(doc.get(k), _NUM) for k in
           ("min_s", "max_s", "p50_s", "p90_s", "p99_s")):
        _check(doc["min_s"] <= doc["max_s"], errors,
               f"{name}: min_s > max_s")
        q = [doc["p50_s"], doc["p90_s"], doc["p99_s"]]
        _check(q == sorted(q), errors,
               f"{name}: quantiles not non-decreasing: {q}")
        _check(all(v >= 0 for v in q + [doc["min_s"]]), errors,
               f"{name}: negative latency")


def validate_serving(sec) -> list:
    """Schema errors for one ``serving`` section (empty list = valid)."""
    errors: list = []
    if not isinstance(sec, dict):
        return [f"serving section is {type(sec).__name__}, not an object"]
    for key in _COUNTER_KEYS:
        v = sec.get(key)
        if not isinstance(v, int) or isinstance(v, bool):
            errors.append(f"{key} missing/not an int")
        elif v < 0:
            errors.append(f"{key} negative: {v}")
    if errors:
        return errors
    # outcomes never exceed intake (in-flight work may make it a strict
    # inequality on a live snapshot)
    _check(sec["replies"] + sec["rejected"] <= sec["requests"], errors,
           f"replies+rejected ({sec['replies']}+{sec['rejected']}) "
           f"exceed requests ({sec['requests']})")

    occ = sec.get("occupancy")
    if occ is not None:
        if not isinstance(occ, dict):
            errors.append("occupancy neither object nor null")
        else:
            for key in ("batches", "mean", "max", "p50"):
                _check(isinstance(occ.get(key), _NUM), errors,
                       f"occupancy.{key} missing/non-numeric")
            if isinstance(occ.get("batches"), int):
                _check(occ["batches"] == sec["batches"], errors,
                       f"occupancy.batches ({occ['batches']}) != batches "
                       f"counter ({sec['batches']})")
            if all(isinstance(occ.get(k), _NUM) for k in ("mean", "max")):
                _check(1.0 <= occ["mean"] <= occ["max"], errors,
                       f"occupancy mean {occ['mean']} outside "
                       f"[1, max={occ['max']}]")
    for name in _LATENCY_KEYS:
        _validate_latency(sec.get(name), name, errors)
    if "fleet" in sec and sec["fleet"] is not None:
        errors.extend(validate_fleet(sec["fleet"]))
    return errors


#: router counters fleet_serving_section always emits (ints, >= 0)
_ROUTER_KEYS = ("requests", "routed", "replies", "rejected",
                "quota_rejected", "shed", "rerouted", "dup_replies",
                "timeouts", "worker_down", "workers_ready", "pending")

#: per-worker counters (ints, >= 0)
_WORKER_KEYS = ("requests", "replies", "rejected", "timeouts",
                "batches", "backfilled", "compile_cold", "compile_warm",
                "restarts")


def validate_fleet(fleet) -> list:
    """Schema errors for one v16 ``serving.fleet`` sub-section."""
    errors: list = []
    if not isinstance(fleet, dict):
        return [f"fleet is {type(fleet).__name__}, not an object"]
    router = fleet.get("router")
    workers = fleet.get("workers")
    if not isinstance(router, dict):
        errors.append("fleet.router missing/not an object")
    if not isinstance(workers, list):
        errors.append("fleet.workers missing/not a list")
    if errors:
        return errors
    for key in _ROUTER_KEYS:
        v = router.get(key)
        if not isinstance(v, int) or isinstance(v, bool):
            errors.append(f"fleet.router.{key} missing/not an int")
        elif v < 0:
            errors.append(f"fleet.router.{key} negative: {v}")
    _validate_latency(router.get("reply_latency"),
                      "fleet.router.reply_latency", errors)
    for i, w in enumerate(workers):
        if not isinstance(w, dict):
            errors.append(f"fleet.workers[{i}] not an object")
            continue
        if not isinstance(w.get("name"), str) or not w.get("name"):
            errors.append(f"fleet.workers[{i}].name missing/empty")
        for key in _WORKER_KEYS:
            v = w.get(key)
            if not isinstance(v, int) or isinstance(v, bool):
                errors.append(
                    f"fleet.workers[{i}].{key} missing/not an int")
            elif v < 0:
                errors.append(f"fleet.workers[{i}].{key} negative: {v}")
        occ = w.get("occupancy")
        if occ is not None:
            if not isinstance(occ, dict):
                errors.append(
                    f"fleet.workers[{i}].occupancy neither object "
                    f"nor null")
            else:
                for key in ("batches", "mean", "max", "p50"):
                    _check(isinstance(occ.get(key), _NUM), errors,
                           f"fleet.workers[{i}].occupancy.{key} "
                           f"missing/non-numeric")
    if errors:
        return errors
    names = [w["name"] for w in workers]
    _check(len(set(names)) == len(names), errors,
           f"duplicate worker names: {names}")
    if all(isinstance(router.get(k), int)
           for k in ("requests", "routed", "rejected")):
        _check(router["routed"] + router["rejected"]
               <= router["requests"], errors,
               f"router routed+rejected "
               f"({router['routed']}+{router['rejected']}) exceed "
               f"requests ({router['requests']})")
    # THE partition invariant: every forwarded request (original route
    # or failover re-route) landed on exactly one worker life
    forwarded = router["routed"] + router["rerouted"]
    landed = sum(w["requests"] for w in workers)
    _check(landed == forwarded, errors,
           f"worker requests ({landed}) do not partition the router's "
           f"forwarded total (routed {router['routed']} + rerouted "
           f"{router['rerouted']} = {forwarded})")
    return errors


def _fmt_ms(v) -> str:
    return "-" if v is None else f"{1e3 * v:,.1f}ms"


def _lat_line(doc) -> str:
    if not doc or not doc.get("count"):
        return "(no observations)"
    return (f"p50={_fmt_ms(doc.get('p50_s'))} "
            f"p90={_fmt_ms(doc.get('p90_s'))} "
            f"p99={_fmt_ms(doc.get('p99_s'))} "
            f"max={_fmt_ms(doc.get('max_s'))}  (n={doc['count']:,})")


def print_serving(sec: dict, label: str) -> None:
    print(f"{label}: scenario serving "
          f"(requests={sec['requests']:,} replies={sec['replies']:,} "
          f"rejected={sec['rejected']:,} timeouts={sec['timeouts']:,} "
          f"in-flight={sec['in_flight']:,})")
    occ = sec.get("occupancy")
    if occ:
        ratio = sec["requests"] / sec["batches"] if sec["batches"] else 0.0
        print(f"  batches     {sec['batches']:,}  occupancy "
              f"mean={occ['mean']:.2f} p50={occ['p50']:.2f} "
              f"max={occ['max']:g}  (coalescing {ratio:.2f}x)")
    else:
        print(f"  batches     {sec['batches']:,}  (no occupancy samples)")
    print(f"  queue wait  {_lat_line(sec.get('queue_wait'))}")
    print(f"  dispatch    {_lat_line(sec.get('dispatch'))}")
    print(f"  reply       {_lat_line(sec.get('reply_latency'))}")
    if sec.get("fleet"):
        print_fleet(sec["fleet"])


def print_fleet(fleet: dict) -> None:
    r = fleet["router"]
    print(f"  fleet       {len(fleet['workers'])} worker(s), "
          f"{r['workers_ready']} ready  (routed={r['routed']:,} "
          f"rerouted={r['rerouted']:,} shed={r['shed']:,} "
          f"quota={r['quota_rejected']:,} dup_replies="
          f"{r['dup_replies']:,} worker_down={r['worker_down']:,})")
    print(f"    route lat {_lat_line(r.get('reply_latency'))}")
    for w in fleet["workers"]:
        occ = w.get("occupancy")
        occ_s = (f"occ mean={occ['mean']:.2f} max={occ['max']:g}"
                 if occ else "no occupancy")
        cold = w.get("compile_cold")
        print(f"    {w['name']:<8} requests={w['requests']:,} "
              f"batches={w['batches']:,} "
              f"backfilled={w['backfilled']:,}  {occ_s}  "
              f"cold={cold} restarts={w['restarts']}")


def _iter_docs(path: str):
    """Parsed JSON documents in ``path``: one whole-file document, or
    one per line (bench batteries write JSONL)."""
    with open(path) as f:
        text = f.read()
    try:
        yield json.loads(text)
        return
    except json.JSONDecodeError:
        pass
    for ln in text.splitlines():
        ln = ln.strip()
        if not ln.startswith("{"):
            continue
        try:
            yield json.loads(ln)
        except json.JSONDecodeError:
            continue


def _extract_reports(doc):
    """(label_suffix, report_dict) pairs embedded in one parsed doc."""
    if not isinstance(doc, dict):
        return
    if doc.get("kind") == REPORT_KIND:
        yield "", doc
        return
    rep = doc.get("run_report")
    if isinstance(rep, dict) and rep.get("kind") == REPORT_KIND:
        label = doc.get("phase") or doc.get("variant") or rep.get("app")
        yield f"[{label}]" if label else "", rep


def check_file(path: str, quiet: bool = False) -> bool:
    """Validate (and print) every serving section in one file; True when
    all present sections pass.  A file with none passes trivially."""
    name = os.path.basename(path)
    try:
        docs = list(_iter_docs(path))
    except OSError as e:
        print(f"{name}: UNREADABLE ({e})", file=sys.stderr)
        return False
    found = 0
    ok = True
    for doc in docs:
        for suffix, rep in _extract_reports(doc):
            sec = rep.get("serving")
            if sec is None:
                continue
            found += 1
            errors = validate_serving(sec)
            if errors:
                ok = False
                print(f"{name}{suffix}: INVALID serving section "
                      f"({len(errors)} error(s))", file=sys.stderr)
                for e in errors[:10]:
                    print(f"  {e}", file=sys.stderr)
                if len(errors) > 10:
                    print(f"  ... and {len(errors) - 10} more",
                          file=sys.stderr)
            elif not quiet:
                print_serving(sec, f"{name}{suffix}")
    if not found and not quiet:
        print(f"{name}: no serving section (not a serving run or "
              f"pre-v6 report)")
    return ok


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="validate + pretty-print RunReport scenario-serving "
                    "sections (bare reports, bench docs, or JSONL of "
                    "either)")
    ap.add_argument("files", nargs="+", help="report/bench files to check")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="suppress the tables (errors still print)")
    args = ap.parse_args(argv)

    ok = True
    for path in args.files:
        ok = check_file(path, quiet=args.quiet) and ok
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
