#!/usr/bin/env python
"""Validate + pretty-print the ``mesh`` section of run reports.

Accepts any mix of the shapes the repo's tooling writes:

* a bare RunReport JSON (``kind == "tmhpvsim_tpu.run_report"``);
* a bench doc — one JSON object with an embedded ``run_report`` key
  (bench.py's per-phase stdout lines / BENCH_*.json), or a ``bench.py
  --hosts`` artifact carrying the mesh doc at top level;
* a JSONL stream of either (bench.py batteries append one doc per
  phase: SWEEP_r05.jsonl and friends).

Every mesh section found (schema v13, parallel/distributed.py
``mesh_doc``) is checked with ``obs.report.validate_mesh_section`` —
shape/axis-name consistency, device-count product, process bounds,
chain-range divisibility — and printed as a one-glance topology line:

    HEADLINE_r06.json: mesh 4x2 (chains, scenario) over 8 devices,
      host 0/2, chains 0..512 of 1024 (64/device)

Exit code 0 when every *present* mesh section validates — reports
without one (pre-v13 documents, unsharded runs) are fine and just
noted, which is how ``run_tpu_round5b.sh`` consumes this non-fatally
after each bench doc.  Nonzero means a malformed section: the mesh
plumbing wrote something ``mesh_doc`` never emits.

The only repo import is ``obs.report`` (pure stdlib): runs anywhere
the repo checks out, no jax required.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# repo-root import without installation (the tools/ scripts' pattern)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tmhpvsim_tpu.obs.report import validate_mesh_section  # noqa: E402

REPORT_KIND = "tmhpvsim_tpu.run_report"


def print_mesh(sec: dict, label: str) -> None:
    shape = "x".join(str(s) for s in sec.get("shape", []))
    axes = ", ".join(sec.get("axis_names", []))
    line = (f"{label}: mesh {shape} ({axes}) over "
            f"{sec.get('n_devices')} device(s)")
    pc = sec.get("process_count")
    if isinstance(pc, int) and pc > 1:
        line += f", host {sec.get('process_index')}/{pc}"
    if sec.get("n_chains") is not None:
        line += f", chains"
        if sec.get("chain_start") is not None:
            line += f" {sec['chain_start']}..{sec['chain_stop']} of"
        line += f" {sec['n_chains']}"
        if sec.get("chains_per_device") is not None:
            line += f" ({sec['chains_per_device']}/device)"
    print(line)


def _iter_docs(path: str):
    """Parsed JSON documents in ``path``: one whole-file document, or
    one per line (bench batteries write JSONL)."""
    with open(path) as f:
        text = f.read()
    try:
        yield json.loads(text)
        return
    except json.JSONDecodeError:
        pass
    for ln in text.splitlines():
        ln = ln.strip()
        if not ln.startswith("{"):
            continue
        try:
            yield json.loads(ln)
        except json.JSONDecodeError:
            continue


def _extract_sections(doc):
    """(label_suffix, mesh_section) pairs embedded in one parsed doc."""
    if not isinstance(doc, dict):
        return
    if doc.get("kind") == REPORT_KIND:
        if doc.get("mesh") is not None:
            yield "", doc["mesh"]
        return
    if "parsed" in doc and "cmd" in doc:   # driver round wrapper
        doc = doc.get("parsed") or {}
    label = doc.get("phase") or doc.get("variant") or doc.get("config")
    suffix = f"[{label}]" if label else ""
    if isinstance(doc.get("mesh"), dict):   # --hosts artifact top level
        yield suffix, doc["mesh"]
    rep = doc.get("run_report")
    if isinstance(rep, dict) and rep.get("mesh") is not None:
        yield f"{suffix}[run_report]" if suffix else "[run_report]", \
            rep["mesh"]


def check_file(path: str, quiet: bool = False) -> bool:
    """Validate (and print) every mesh section in one file; True when
    all present sections pass.  A file with none passes trivially."""
    name = os.path.basename(path)
    try:
        docs = list(_iter_docs(path))
    except OSError as e:
        print(f"{name}: UNREADABLE ({e})", file=sys.stderr)
        return False
    found = 0
    ok = True
    for doc in docs:
        for suffix, sec in _extract_sections(doc):
            found += 1
            errors = validate_mesh_section(sec)
            if errors:
                ok = False
                print(f"{name}{suffix}: INVALID mesh section "
                      f"({len(errors)} error(s))", file=sys.stderr)
                for e in errors[:10]:
                    print(f"  {e}", file=sys.stderr)
                if len(errors) > 10:
                    print(f"  ... and {len(errors) - 10} more",
                          file=sys.stderr)
            elif not quiet:
                print_mesh(sec, f"{name}{suffix}")
    if not found and not quiet:
        print(f"{name}: no mesh section (unsharded run or pre-v13 report)")
    return ok


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="validate + pretty-print RunReport mesh sections "
                    "(bare reports, bench docs, or JSONL of either)")
    ap.add_argument("files", nargs="+", help="report/bench files to check")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="suppress the topology lines (errors still "
                         "print)")
    args = ap.parse_args(argv)

    ok = True
    for path in args.files:
        ok = check_file(path, quiet=args.quiet) and ok
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
