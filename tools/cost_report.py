#!/usr/bin/env python
"""Validate + pretty-print the v10 ``cost`` section of RunReport /
bench artifacts.

Reads one or more JSON files — bare RunReports, bench headline docs
(with an embedded ``run_report`` and per-variant ``cost`` docs), or
driver wrappers (``{"parsed": ...}``) — finds every cost doc inside,
runs :func:`tmhpvsim_tpu.obs.cost.validate_cost` over each, and prints
one human line per doc::

    HEADLINE_r05.json scan2/bf16/table  1.2e9 site-s/s  achieved 561.6
    GFLOP/s (9.2% vpu) / 79.2 GB/s (9.7% hbm)  north-star 0.183  [model]

Exit code: 0 when every cost doc found validates (including files with
none — the tool is wired NON-fatally into the bench battery, where
pre-v10 artifacts are the norm), 1 when any doc fails validation, 2 on
unreadable input.  ``--json`` emits the findings as one machine-readable
document instead.

Stdlib + tmhpvsim_tpu only — runs anywhere the repo checks out.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tmhpvsim_tpu.obs.cost import validate_cost  # noqa: E402


def find_cost_docs(doc, where: str = "$") -> list:
    """Every ``cost`` section in a document: ``(json_path, doc)`` pairs.

    Looks in the places the repo's artifact shapes put them — a bare
    RunReport's top-level ``cost``, a headline's ``run_report.cost``,
    each variant's ``cost``, and a driver wrapper's ``parsed`` payload.
    """
    found = []
    if not isinstance(doc, dict):
        return found
    if "parsed" in doc and "cmd" in doc:
        return find_cost_docs(doc.get("parsed"), where + ".parsed")
    if isinstance(doc.get("cost"), dict):
        found.append((where + ".cost", doc["cost"]))
    rep = doc.get("run_report")
    if isinstance(rep, dict) and isinstance(rep.get("cost"), dict):
        found.append((where + ".run_report.cost", rep["cost"]))
    variants = doc.get("variants")
    if isinstance(variants, dict):
        for name, v in sorted(variants.items()):
            if isinstance(v, dict) and isinstance(v.get("cost"), dict):
                found.append((f"{where}.variants.{name}.cost", v["cost"]))
    return found


def render(cost: dict) -> str:
    """One human line for a valid cost doc."""
    cell = "/".join((cost.get("block_impl", "?"),
                     cost.get("compute_dtype", "?"),
                     cost.get("kernel_impl", "?")))
    parts = [cell]
    rate = cost.get("site_s_per_s")
    if rate is not None:
        parts.append(f"{rate:.3g} site-s/s")
    gf, gb = cost.get("achieved_gflops"), cost.get("achieved_gbs")
    if gf is not None:
        vpu = cost.get("roofline_frac_vpu")
        hbm = cost.get("roofline_frac_hbm")
        fl = f"achieved {gf:g} GFLOP/s"
        if vpu is not None:
            fl += f" ({vpu * 100:.1f}% vpu)"
        fl += f" / {gb:g} GB/s"
        if hbm is not None:
            fl += f" ({hbm * 100:.1f}% hbm)"
        parts.append(fl)
    nsf = cost.get("north_star_frac")
    if nsf is not None:
        parts.append(f"north-star {nsf:.3f}")
    parts.append(f"[{cost.get('basis', 'model')}]")
    return "  ".join(parts)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="validate + pretty-print v10 cost sections")
    ap.add_argument("files", nargs="+",
                    help="RunReport / bench artifact JSON files")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as one JSON document")
    ap.add_argument("--require", action="store_true",
                    help="also fail (exit 1) when a file contains NO "
                         "cost doc at all (default: pre-v10 artifacts "
                         "pass silently)")
    args = ap.parse_args(argv)

    rc = 0
    findings = []
    for path in args.files:
        name = os.path.basename(path)
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"{name}: unreadable: {e}", file=sys.stderr)
            rc = max(rc, 2)
            continue
        docs = find_cost_docs(doc)
        if not docs:
            findings.append({"file": name, "path": None, "ok": True,
                             "note": "no cost section (pre-v10)"})
            if args.require:
                print(f"{name}: no cost section", file=sys.stderr)
                rc = max(rc, 1)
            continue
        for where, cost in docs:
            errors = validate_cost(cost)
            finding = {"file": name, "path": where,
                       "ok": not errors, "cost": cost}
            if errors:
                finding["errors"] = errors
                rc = max(rc, 1)
                if not args.json:
                    print(f"{name} {where}: INVALID: "
                          + "; ".join(errors))
            elif not args.json:
                print(f"{name} {where.removeprefix('$.')}: "
                      + render(cost))
            findings.append(finding)
    if args.json:
        print(json.dumps({"ok": rc == 0, "findings": findings},
                         indent=1))
    return rc


if __name__ == "__main__":
    sys.exit(main())
