#!/usr/bin/env python
"""Validate + pretty-print the ``fleet`` section of run reports.

Accepts any mix of the shapes the repo's tooling writes:

* a bare RunReport JSON (``--run-report``, ``kind ==
  "tmhpvsim_tpu.run_report"``);
* a bench doc — one JSON object with an embedded ``run_report`` key
  (bench.py's per-phase stdout lines / BENCH_*.json);
* a JSONL stream of either (bench.py batteries append one doc per
  phase: SWEEP_r05.jsonl and friends).

For every embedded report carrying a ``fleet`` section (schema v5,
obs/analytics.py ``summarize``), the section is checked against the
shape ``summarize`` emits — required keys, numeric types, exceedance
monotonicity, quantile ordering — and printed as a readable risk table.

Exit code 0 when every *present* fleet section validates — reports
without one (pre-v5 documents, ``--analytics off`` runs) are fine and
just noted, which is how ``run_tpu_round5b.sh`` consumes this
non-fatally after each bench doc.  Nonzero means a malformed section:
the analytics path wrote something ``summarize`` never emits.

No third-party imports: runs anywhere the repo checks out.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPORT_KIND = "tmhpvsim_tpu.run_report"

_NUM = (int, float)
_OPT_NUM = (int, float, type(None))


def _check(cond: bool, errors: list, msg: str) -> None:
    if not cond:
        errors.append(msg)


def validate_fleet(sec) -> list:
    """Schema errors for one ``fleet`` section (empty list = valid)."""
    errors: list = []
    if not isinstance(sec, dict):
        return [f"fleet section is {type(sec).__name__}, not an object"]
    for key in ("level", "count", "residual", "exceedance", "lolp",
                "ramp", "sketch"):
        if key not in sec:
            errors.append(f"missing required key {key!r}")
    if errors:
        return errors
    _check(sec["level"] in ("risk", "full"), errors,
           f"level {sec['level']!r} not 'risk'/'full'")
    _check(isinstance(sec["count"], int) and sec["count"] >= 0, errors,
           f"count {sec['count']!r} not a non-negative int")

    res = sec["residual"]
    if isinstance(res, dict):
        _check(isinstance(res.get("min"), _OPT_NUM), errors,
               "residual.min not numeric/null")
        _check(isinstance(res.get("max"), _OPT_NUM), errors,
               "residual.max not numeric/null")
        q = res.get("quantiles")
        if isinstance(q, dict):
            vals = []
            for name in ("p1", "p5", "p50", "p95", "p99"):
                v = q.get(name)
                _check(isinstance(v, _NUM), errors,
                       f"quantile {name} missing/non-numeric")
                if isinstance(v, _NUM):
                    vals.append(v)
            _check(vals == sorted(vals), errors,
                   f"quantiles not non-decreasing: {vals}")
        elif q is not None:
            errors.append("residual.quantiles neither object nor null")
    else:
        errors.append("residual is not an object")

    exc = sec["exceedance"]
    if isinstance(exc, list):
        secs = []
        for j, row in enumerate(exc):
            if not isinstance(row, dict):
                errors.append(f"exceedance[{j}] not an object")
                continue
            for key, types in (("threshold_w", _NUM), ("seconds", int),
                               ("prob", _NUM)):
                _check(isinstance(row.get(key), types), errors,
                       f"exceedance[{j}].{key} missing/mistyped")
            if isinstance(row.get("seconds"), int):
                secs.append(row["seconds"])
        # ascending thresholds => non-increasing exceedance mass
        _check(all(b <= a for a, b in zip(secs, secs[1:])), errors,
               f"exceedance seconds not non-increasing: {secs}")
    else:
        errors.append("exceedance is not a list")

    lolp = sec["lolp"]
    if isinstance(lolp, dict):
        for key, types in (("capacity_w", _NUM), ("k_s", int),
                           ("loss_seconds", int), ("events", int),
                           ("prob", _NUM)):
            _check(isinstance(lolp.get(key), types), errors,
                   f"lolp.{key} missing/mistyped")
        if isinstance(lolp.get("prob"), _NUM):
            _check(0.0 <= lolp["prob"] <= 1.0, errors,
                   f"lolp.prob {lolp['prob']} outside [0, 1]")
    else:
        errors.append("lolp is not an object")

    if isinstance(sec["ramp"], dict):
        for w, v in sec["ramp"].items():
            _check(isinstance(v, _OPT_NUM), errors,
                   f"ramp[{w!r}] not numeric/null")
    else:
        errors.append("ramp is not an object")

    sk = sec["sketch"]
    if isinstance(sk, dict):
        for key in ("bins", "lo_w", "hi_w", "width_w", "underflow",
                    "overflow"):
            _check(isinstance(sk.get(key), _NUM), errors,
                   f"sketch.{key} missing/non-numeric")
    else:
        errors.append("sketch is not an object")

    reg = sec.get("regimes")
    if reg is not None:
        if not isinstance(reg, dict):
            errors.append("regimes neither object nor null")
        else:
            for name, row in reg.items():
                if not isinstance(row, dict) or not isinstance(
                        row.get("seconds"), int):
                    errors.append(f"regimes[{name!r}] malformed")

    co = sec.get("cohorts")
    if co is not None:
        if not isinstance(co, list):
            errors.append("cohorts neither list nor null")
        else:
            total = 0
            for j, row in enumerate(co):
                if not isinstance(row, dict):
                    errors.append(f"cohorts[{j}] not an object")
                    continue
                for key, types in (("cohort", int), ("count", int)):
                    _check(isinstance(row.get(key), types), errors,
                           f"cohorts[{j}].{key} missing/mistyped")
                for key in ("residual_min", "residual_max", "meter_mean",
                            "pv_mean", "residual_mean"):
                    _check(isinstance(row.get(key, None), _OPT_NUM),
                           errors, f"cohorts[{j}].{key} not numeric/null")
                q = row.get("quantiles")
                if isinstance(q, dict):
                    vals = [q.get(name) for name in ("p5", "p50", "p95")]
                    _check(all(isinstance(v, _NUM) for v in vals), errors,
                           f"cohorts[{j}].quantiles missing/non-numeric")
                    if all(isinstance(v, _NUM) for v in vals):
                        _check(vals == sorted(vals), errors,
                               f"cohorts[{j}].quantiles not "
                               f"non-decreasing: {vals}")
                elif q is not None:
                    errors.append(
                        f"cohorts[{j}].quantiles neither object nor null")
                if isinstance(row.get("count"), int):
                    total += row["count"]
            # every folded chain-second is tagged with exactly one
            # cohort, so the group-by partitions the total count
            if isinstance(sec.get("count"), int):
                _check(total == sec["count"], errors,
                       f"cohort counts sum to {total} != "
                       f"fleet count {sec['count']}")
    return errors


def _fmt_w(v) -> str:
    return "-" if v is None else f"{v:,.1f}"


def print_fleet(sec: dict, label: str) -> None:
    print(f"{label}: fleet risk summary (level={sec['level']}, "
          f"n={sec['count']:,} chain-seconds)")
    res = sec["residual"]
    q = res.get("quantiles") or {}
    print(f"  residual W  min={_fmt_w(res.get('min'))} "
          f"p5={_fmt_w(q.get('p5'))} p50={_fmt_w(q.get('p50'))} "
          f"p95={_fmt_w(q.get('p95'))} p99={_fmt_w(q.get('p99'))} "
          f"max={_fmt_w(res.get('max'))}")
    lolp = sec["lolp"]
    print(f"  lolp        {lolp['prob']:.3e} "
          f"({lolp['loss_seconds']:,} s / {lolp['events']:,} events; "
          f"capacity {_fmt_w(lolp['capacity_w'])} W, k={lolp['k_s']} s)")
    ramps = "  ".join(f"{w}={_fmt_w(v)}" for w, v in sec["ramp"].items())
    print(f"  ramp W      {ramps}")
    sk = sec["sketch"]
    if sk["underflow"] or sk["overflow"]:
        print(f"  sketch      {int(sk['underflow']):,} under / "
              f"{int(sk['overflow']):,} over of {int(sk['bins'])} bins "
              f"[{_fmt_w(sk['lo_w'])}, {_fmt_w(sk['hi_w'])})")
    rows = [(f"{r['threshold_w']:,.0f}", f"{r['seconds']:,}",
             f"{r['prob']:.3e}") for r in sec["exceedance"]]
    if rows:
        widths = [max(len(r[i]) for r in rows + [("thresh_W", "seconds",
                                                  "prob")])
                  for i in range(3)]
        print("  exceedance  " + "  ".join(
            h.rjust(w) for h, w in zip(("thresh_W", "seconds", "prob"),
                                       widths)))
        for r in rows:
            print("              " + "  ".join(
                c.rjust(w) for c, w in zip(r, widths)))
    reg = sec.get("regimes")
    if reg:
        for name, row in reg.items():
            means = "  ".join(
                f"{k.removesuffix('_mean')}={_fmt_w(v)}"
                for k, v in row.items() if k.endswith("_mean"))
            print(f"  regime      {name}: {row['seconds']:,} s  {means}")
    co = sec.get("cohorts")
    if co:
        heads = ("cohort", "seconds", "res_min_W", "res_p50_W",
                 "res_max_W", "meter_mean_W", "pv_mean_W")
        rows = []
        for row in co:
            q = row.get("quantiles") or {}
            rows.append((str(row["cohort"]), f"{row['count']:,}",
                         _fmt_w(row.get("residual_min")),
                         _fmt_w(q.get("p50")),
                         _fmt_w(row.get("residual_max")),
                         _fmt_w(row.get("meter_mean")),
                         _fmt_w(row.get("pv_mean"))))
        widths = [max(len(r[i]) for r in rows + [heads])
                  for i in range(len(heads))]
        print("  cohorts     " + "  ".join(
            h.rjust(w) for h, w in zip(heads, widths)))
        for r in rows:
            print("              " + "  ".join(
                c.rjust(w) for c, w in zip(r, widths)))


def _iter_docs(path: str):
    """Parsed JSON documents in ``path``: one whole-file document, or
    one per line (bench batteries write JSONL)."""
    with open(path) as f:
        text = f.read()
    try:
        yield json.loads(text)
        return
    except json.JSONDecodeError:
        pass
    for ln in text.splitlines():
        ln = ln.strip()
        if not ln.startswith("{"):
            continue
        try:
            yield json.loads(ln)
        except json.JSONDecodeError:
            continue


def _extract_reports(doc):
    """(label_suffix, report_dict) pairs embedded in one parsed doc."""
    if not isinstance(doc, dict):
        return
    if doc.get("kind") == REPORT_KIND:
        yield "", doc
        return
    rep = doc.get("run_report")
    if isinstance(rep, dict) and rep.get("kind") == REPORT_KIND:
        label = doc.get("phase") or doc.get("variant") or rep.get("app")
        yield f"[{label}]" if label else "", rep


def check_file(path: str, quiet: bool = False) -> bool:
    """Validate (and print) every fleet section in one file; True when
    all present sections pass.  A file with none passes trivially."""
    name = os.path.basename(path)
    try:
        docs = list(_iter_docs(path))
    except OSError as e:
        print(f"{name}: UNREADABLE ({e})", file=sys.stderr)
        return False
    found = 0
    ok = True
    for doc in docs:
        for suffix, rep in _extract_reports(doc):
            sec = rep.get("fleet")
            if sec is None:
                continue
            found += 1
            errors = validate_fleet(sec)
            if errors:
                ok = False
                print(f"{name}{suffix}: INVALID fleet section "
                      f"({len(errors)} error(s))", file=sys.stderr)
                for e in errors[:10]:
                    print(f"  {e}", file=sys.stderr)
                if len(errors) > 10:
                    print(f"  ... and {len(errors) - 10} more",
                          file=sys.stderr)
            elif not quiet:
                print_fleet(sec, f"{name}{suffix}")
    if not found and not quiet:
        print(f"{name}: no fleet section (analytics off or pre-v5 report)")
    return ok


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="validate + pretty-print RunReport fleet-analytics "
                    "sections (bare reports, bench docs, or JSONL of "
                    "either)")
    ap.add_argument("files", nargs="+", help="report/bench files to check")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="suppress the tables (errors still print)")
    args = ap.parse_args(argv)

    ok = True
    for path in args.files:
        ok = check_file(path, quiet=args.quiet) and ok
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
