#!/usr/bin/env python
"""Validate + pretty-print the ``precision`` / ``probe`` sections of
run reports (schema v8).

Accepts any mix of the shapes the repo's tooling writes (same intake as
``serve_report.py`` / ``fleet_report.py``):

* a bare RunReport JSON (``kind == "tmhpvsim_tpu.run_report"``);
* a bench doc — one JSON object with an embedded ``run_report`` key
  (bench.py stdout lines / BENCH_*.json);
* a JSONL stream of either (bench batteries append one doc per phase).

Two emitters write ``precision`` sections and both shapes are checked:

* the engine echo (``Simulation.run_report``): the resolved
  ``compute_dtype`` / ``kernel_impl`` axes of a non-default run, the
  run's telemetry level, and whether host-output overlap was active —
  validated for legal axis values and for the bf16 invariant (mixed
  precision auto-escalates telemetry, so a bf16 section claiming
  ``telemetry: off`` means the escalation chain broke);
* the bench pricing (``bench._precision_doc``): per-variant rates keyed
  by their axes plus ``speedup_vs_exact_f32`` against the sweep's own
  exact/f32 baseline — validated for positive rates and for the
  speedups actually being rate/baseline.

``probe`` sections (bench.py's resilience-wrapped backend probe) are
checked for attempt/timeout accounting consistency.

Exit code 0 when every *present* section validates — reports without
one (default-precision runs, pre-v8 documents) are fine and just noted,
which is how ``run_tpu_round5b.sh`` consumes this non-fatally after
each bench doc.  Nonzero means a malformed section.

No third-party imports: runs anywhere the repo checks out.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPORT_KIND = "tmhpvsim_tpu.run_report"

_NUM = (int, float)

_DTYPES = ("f32", "bf16")
_KIMPLS = ("exact", "table")
_TELEMETRY = ("off", "light", "full")
# v11 scan-restructuring axes (optional: pre-v11 sections omit them)
_RNG_BATCHES = ("scan", "block")
_GEOM_STRIDES = (1, 30, 60)


def _check(cond: bool, errors: list, msg: str) -> None:
    if not cond:
        errors.append(msg)


def _validate_axes(doc: dict, prefix: str, errors: list) -> None:
    cdt = doc.get("compute_dtype", "f32")
    kimpl = doc.get("kernel_impl", "exact")
    _check(cdt in _DTYPES, errors,
           f"{prefix}compute_dtype {cdt!r} not in {_DTYPES}")
    _check(kimpl in _KIMPLS, errors,
           f"{prefix}kernel_impl {kimpl!r} not in {_KIMPLS}")
    rb = doc.get("rng_batch", "scan")
    gs = doc.get("geom_stride", 1)
    _check(rb in _RNG_BATCHES, errors,
           f"{prefix}rng_batch {rb!r} not in {_RNG_BATCHES}")
    _check(gs in _GEOM_STRIDES, errors,
           f"{prefix}geom_stride {gs!r} not in {_GEOM_STRIDES}")


def validate_precision(sec) -> list:
    """Schema errors for one ``precision`` section (empty list = ok)."""
    errors: list = []
    if not isinstance(sec, dict):
        return [f"precision section is {type(sec).__name__}, "
                f"not an object"]
    variants = sec.get("variants")
    if variants is not None:                      # bench pricing shape
        if not isinstance(variants, dict) or not variants:
            return ["variants present but not a non-empty object"]
        base = sec.get("baseline_rate_exact_f32")
        _check(base is None or (isinstance(base, _NUM) and base > 0),
               errors, f"baseline_rate_exact_f32 not positive: {base!r}")
        for name, v in variants.items():
            if not isinstance(v, dict):
                errors.append(f"variants[{name}] not an object")
                continue
            _validate_axes(v, f"variants[{name}].", errors)
            rate = v.get("rate")
            if not isinstance(rate, _NUM) or rate <= 0:
                errors.append(f"variants[{name}].rate not positive: "
                              f"{rate!r}")
                continue
            speed = v.get("speedup_vs_exact_f32")
            if speed is None:
                continue
            _check(isinstance(speed, _NUM) and speed > 0, errors,
                   f"variants[{name}].speedup_vs_exact_f32 not "
                   f"positive: {speed!r}")
            if isinstance(speed, _NUM) and isinstance(base, _NUM) and base:
                # bench rounds the stored speedup to 2 decimals
                want = rate / base
                _check(abs(speed - want) <= 0.005 + 1e-9, errors,
                       f"variants[{name}]: speedup {speed} != "
                       f"rate/baseline {want:.4f}")
        return errors

    # engine echo shape
    _validate_axes(sec, "", errors)
    tel = sec.get("telemetry")
    if tel is not None:
        _check(tel in _TELEMETRY, errors,
               f"telemetry {tel!r} not in {_TELEMETRY}")
        # the bf16 auto-escalation invariant (engine/autotune.py): a
        # mixed-precision run never executes with the sentinel off
        _check(not (sec.get("compute_dtype") == "bf16" and tel == "off"),
               errors, "bf16 section claims telemetry 'off' — the "
                       "auto-escalation chain broke")
    ov = sec.get("output_overlap")
    _check(ov is None or isinstance(ov, bool), errors,
           f"output_overlap neither bool nor absent: {ov!r}")
    return errors


def validate_probe(sec) -> list:
    """Schema errors for one ``probe`` section (empty list = ok)."""
    errors: list = []
    if not isinstance(sec, dict):
        return [f"probe section is {type(sec).__name__}, not an object"]
    att = sec.get("probe_attempts")
    tmo = sec.get("probe_timeouts")
    for key, v in (("probe_attempts", att), ("probe_timeouts", tmo)):
        if not isinstance(v, int) or isinstance(v, bool):
            errors.append(f"{key} missing/not an int")
        elif v < 0:
            errors.append(f"{key} negative: {v}")
    if not errors:
        _check(att >= 1, errors,
               f"probe section written without an attempt ({att})")
        _check(tmo <= att, errors,
               f"probe_timeouts ({tmo}) exceed probe_attempts ({att})")
    for key in ("attempt_timeout_s", "total_timeout_s"):
        v = sec.get(key)
        _check(v is None or (isinstance(v, _NUM) and v > 0), errors,
               f"{key} not positive: {v!r}")
    return errors


def print_precision(sec: dict, label: str) -> None:
    variants = sec.get("variants")
    if variants is None:
        print(f"{label}: precision axes compute_dtype="
              f"{sec.get('compute_dtype', 'f32')} kernel_impl="
              f"{sec.get('kernel_impl', 'exact')} rng_batch="
              f"{sec.get('rng_batch', 'scan')} geom_stride="
              f"{sec.get('geom_stride', 1)} telemetry="
              f"{sec.get('telemetry', '-')} output_overlap="
              f"{sec.get('output_overlap', '-')}")
        return
    base = sec.get("baseline_rate_exact_f32")
    print(f"{label}: precision pricing "
          f"(baseline exact/f32 rate: "
          f"{base if base is not None else 'none in sweep'})")
    width = max(len(n) for n in variants)
    for name, v in sorted(variants.items()):
        speed = v.get("speedup_vs_exact_f32")
        print(f"  {name.ljust(width)}  {v.get('compute_dtype', 'f32'):>4}"
              f"/{v.get('kernel_impl', 'exact'):<5}  "
              f"rng={v.get('rng_batch', 'scan'):<5} "
              f"gs={v.get('geom_stride', 1):<2}  "
              f"rate={v.get('rate'):,}  "
              + ("-" if speed is None else f"{speed:.2f}x vs exact/f32"))


def print_probe(sec: dict, label: str) -> None:
    print(f"{label}: backend probe attempts={sec.get('probe_attempts')} "
          f"timeouts={sec.get('probe_timeouts')} "
          f"(attempt {sec.get('attempt_timeout_s')}s / total "
          f"{sec.get('total_timeout_s')}s budget)")


def _iter_docs(path: str):
    """Parsed JSON documents in ``path``: one whole-file document, or
    one per line (bench batteries write JSONL)."""
    with open(path) as f:
        text = f.read()
    try:
        yield json.loads(text)
        return
    except json.JSONDecodeError:
        pass
    for ln in text.splitlines():
        ln = ln.strip()
        if not ln.startswith("{"):
            continue
        try:
            yield json.loads(ln)
        except json.JSONDecodeError:
            continue


def _extract_reports(doc):
    """(label_suffix, report_dict) pairs embedded in one parsed doc."""
    if not isinstance(doc, dict):
        return
    if doc.get("kind") == REPORT_KIND:
        yield "", doc
        return
    rep = doc.get("run_report")
    if isinstance(rep, dict) and rep.get("kind") == REPORT_KIND:
        label = doc.get("phase") or doc.get("variant") or rep.get("app")
        yield f"[{label}]" if label else "", rep


def check_file(path: str, quiet: bool = False) -> bool:
    """Validate (and print) every precision/probe section in one file;
    True when all present sections pass.  None present passes
    trivially."""
    name = os.path.basename(path)
    try:
        docs = list(_iter_docs(path))
    except OSError as e:
        print(f"{name}: UNREADABLE ({e})", file=sys.stderr)
        return False
    found = 0
    ok = True
    for doc in docs:
        for suffix, rep in _extract_reports(doc):
            for key, validate, show in (
                    ("precision", validate_precision, print_precision),
                    ("probe", validate_probe, print_probe)):
                sec = rep.get(key)
                if sec is None:
                    continue
                found += 1
                errors = validate(sec)
                if errors:
                    ok = False
                    print(f"{name}{suffix}: INVALID {key} section "
                          f"({len(errors)} error(s))", file=sys.stderr)
                    for e in errors[:10]:
                        print(f"  {e}", file=sys.stderr)
                    if len(errors) > 10:
                        print(f"  ... and {len(errors) - 10} more",
                              file=sys.stderr)
                elif not quiet:
                    show(sec, f"{name}{suffix}")
    if not found and not quiet:
        print(f"{name}: no precision/probe section (default-precision "
              f"run or pre-v8 report)")
    return ok


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="validate + pretty-print RunReport precision/probe "
                    "sections (bare reports, bench docs, or JSONL of "
                    "either)")
    ap.add_argument("files", nargs="+", help="report/bench files to check")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="suppress the tables (errors still print)")
    args = ap.parse_args(argv)
    ok = True
    for path in args.files:
        ok = check_file(path, quiet=args.quiet) and ok
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
