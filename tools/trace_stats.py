#!/usr/bin/env python
"""Validate + summarise Chrome-trace JSON files (obs/trace.py exports).

Checks each file against the subset of the Trace Event Format the
tracer emits — and that Perfetto / chrome://tracing actually require to
load a file — then prints a per-category table of span counts and
durations plus instant-event counts:

* top level is an object with a ``traceEvents`` list (the "JSON Object
  Format"; a bare array is also accepted since the viewers take both);
* every event is an object with a string ``ph`` and, except for
  metadata events, a numeric ``ts`` (microseconds);
* complete spans (``ph == "X"``) carry a numeric ``dur >= 0``;
* ``pid``/``tid`` are integers when present (string ids are legal in
  the wild but the tracer never emits them, and Perfetto's track
  grouping degrades on mixed types);
* metadata events (``ph == "M"``) carry a string ``name``.

Exit code 0 when every file validates, nonzero otherwise — which is how
``run_tpu_round5b.sh`` and the tier-1 round-trip test consume it.

``--stitch OUT.json`` additionally merges every input file into ONE
timeline: each (file, pid) pair gets its own process track (labelled
``file:pid`` via a ``process_name`` metadata event, so client / broker /
server processes stay visually distinct in Perfetto), and events are
grouped by the cross-process trace ids obs/trace.py stamps —
``args.trace_id`` on client/server spans, plus every entry of the
``args.trace_ids`` list a fused batcher dispatch carries.  A per-trace
table then shows how many events and processes each request touched and
its end-to-end wall span, which is how the serve soak test proves one id
correlates client → broker → queue-wait → dispatch → reply.

No third-party imports: runs anywhere the repo checks out.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

#: event phases the tracer emits (chrome's full alphabet is larger; an
#: unknown phase is reported as a warning, not an error, so merged
#: jax.profiler traces with richer phases still validate)
KNOWN_PHASES = {"X", "i", "I", "M", "B", "E", "C", "b", "e", "n", "s",
                "t", "f"}


def validate(doc) -> tuple[list, list]:
    """(errors, events): schema errors for one parsed trace document."""
    errors: list = []
    if isinstance(doc, list):            # JSON Array Format
        events = doc
    elif isinstance(doc, dict):          # JSON Object Format
        events = doc.get("traceEvents")
        if not isinstance(events, list):
            return ["top-level 'traceEvents' is missing or not a list"], []
    else:
        return ["top level is neither an object nor an array"], []

    for i, ev in enumerate(events):
        where = f"event[{i}]"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if not isinstance(ph, str) or not ph:
            errors.append(f"{where}: missing/non-string 'ph'")
            continue
        if ph == "M":
            if not isinstance(ev.get("name"), str):
                errors.append(f"{where}: metadata event without a "
                              "string 'name'")
            continue
        if not isinstance(ev.get("ts"), (int, float)):
            errors.append(f"{where}: ph={ph!r} without numeric 'ts'")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errors.append(f"{where}: complete span without "
                              f"numeric dur >= 0 (got {dur!r})")
        for key in ("pid", "tid"):
            if key in ev and not isinstance(ev[key], int):
                errors.append(f"{where}: non-integer {key!r} "
                              f"({ev[key]!r})")
    return errors, events


def summarize(events: list) -> dict:
    """Per-category stats: span count/total/max duration, instant count."""
    cats: dict = {}
    for ev in events:
        if not isinstance(ev, dict) or ev.get("ph") == "M":
            continue
        cat = ev.get("cat") if isinstance(ev.get("cat"), str) else "-"
        c = cats.setdefault(cat, {"spans": 0, "dur_us": 0.0,
                                  "max_us": 0.0, "instants": 0})
        if ev.get("ph") == "X":
            c["spans"] += 1
            dur = ev.get("dur")
            if isinstance(dur, (int, float)):
                c["dur_us"] += dur
                c["max_us"] = max(c["max_us"], dur)
        elif ev.get("ph") in ("i", "I"):
            c["instants"] += 1
    return cats


def _print_summary(name: str, events: list) -> None:
    cats = summarize(events)
    print(f"{name}: {len(events)} events, {len(cats)} categories")
    if not cats:
        return
    header = ("category", "spans", "total_ms", "max_ms", "instants")
    table = [header]
    for cat in sorted(cats):
        c = cats[cat]
        table.append((cat, str(c["spans"]), f"{c['dur_us'] / 1e3:.3f}",
                      f"{c['max_us'] / 1e3:.3f}", str(c["instants"])))
    widths = [max(len(line[i]) for line in table)
              for i in range(len(header))]
    for line in table:
        print("  " + "  ".join(c.ljust(w) for c, w in zip(line, widths))
              .rstrip())


def stitch(named_events: list) -> list:
    """Merge ``(name, events)`` pairs into one event list.

    Every (source file, original pid) pair is remapped to a fresh
    sequential pid so processes from different files never share a
    track, with a ``process_name`` metadata event labelling each track
    ``name:original_pid``.  Events without a pid inherit their file's
    first track.  Input events are not mutated.
    """
    merged: list = []
    next_pid = 1
    for name, events in named_events:
        remap: dict = {}

        def _track(orig) -> int:
            nonlocal next_pid
            if orig not in remap:
                remap[orig] = next_pid
                merged.append({"ph": "M", "name": "process_name",
                               "pid": next_pid,
                               "args": {"name": f"{name}:{orig}"}})
                next_pid += 1
            return remap[orig]

        for ev in events:
            if not isinstance(ev, dict):
                continue
            out = dict(ev)
            out["pid"] = _track(ev.get("pid") if
                                isinstance(ev.get("pid"), int) else None)
            merged.append(out)
    merged.sort(key=lambda ev: (ev.get("ph") != "M",
                                ev.get("ts") or 0))
    return merged


def trace_groups(events: list) -> dict:
    """Events per propagated trace id: ``{trace_id: [event, ...]}``.

    An event belongs to every id it references — its ``args.trace_id``
    plus each entry of ``args.trace_ids`` (a fused batcher dispatch
    serves many traces, so its one span appears in every group).
    """
    groups: dict = {}
    for ev in events:
        if not isinstance(ev, dict) or ev.get("ph") == "M":
            continue
        args = ev.get("args")
        if not isinstance(args, dict):
            continue
        ids = []
        if isinstance(args.get("trace_id"), str):
            ids.append(args["trace_id"])
        if isinstance(args.get("trace_ids"), list):
            ids.extend(t for t in args["trace_ids"]
                       if isinstance(t, str))
        for tid in dict.fromkeys(ids):
            groups.setdefault(tid, []).append(ev)
    return groups


def _print_trace_table(groups: dict) -> None:
    header = ("trace_id", "events", "procs", "span_ms", "names")
    table = [header]
    for tid in sorted(groups):
        evs = sorted(groups[tid], key=lambda ev: ev.get("ts") or 0)
        start = min(ev.get("ts", 0) for ev in evs)
        end = max(ev.get("ts", 0) + (ev.get("dur") or 0) for ev in evs)
        procs = {ev.get("pid") for ev in evs}
        names = ",".join(dict.fromkeys(
            str(ev.get("name", "?")) for ev in evs))
        if len(names) > 48:
            names = names[:45] + "..."
        table.append((tid, str(len(evs)), str(len(procs)),
                      f"{(end - start) / 1e3:.3f}", names))
    widths = [max(len(line[i]) for line in table)
              for i in range(len(header))]
    for line in table:
        print("  " + "  ".join(c.ljust(w) for c, w in zip(line, widths))
              .rstrip())


def check_file(path: str, quiet: bool = False) -> bool:
    """Validate + summarise one trace file; True when it passes."""
    name = os.path.basename(path)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"{name}: INVALID ({e})", file=sys.stderr)
        return False
    errors, events = validate(doc)
    unknown = {ev.get("ph") for ev in events if isinstance(ev, dict)
               and isinstance(ev.get("ph"), str)} - KNOWN_PHASES
    if errors:
        print(f"{name}: INVALID ({len(errors)} schema error(s))",
              file=sys.stderr)
        for e in errors[:10]:
            print(f"  {e}", file=sys.stderr)
        if len(errors) > 10:
            print(f"  ... and {len(errors) - 10} more", file=sys.stderr)
        return False
    if unknown and not quiet:
        print(f"{name}: note: unrecognised phase(s) "
              f"{sorted(unknown)} (accepted)", file=sys.stderr)
    if not quiet:
        _print_summary(name, events)
    return True


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="validate Chrome-trace JSON + print per-category "
                    "span statistics")
    ap.add_argument("files", nargs="+", help="trace files to check")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="suppress the summary table (errors still print)")
    ap.add_argument("--stitch", metavar="OUT.json",
                    help="merge all inputs into one timeline at "
                         "OUT.json (one process track per file:pid) "
                         "and print the per-trace-id correlation table")
    args = ap.parse_args(argv)

    ok = True
    for path in args.files:
        ok = check_file(path, quiet=args.quiet) and ok
    if ok and args.stitch:
        named = []
        for path in args.files:
            with open(path) as f:
                _, events = validate(json.load(f))
            named.append((os.path.basename(path), events))
        merged = stitch(named)
        with open(args.stitch, "w") as f:
            json.dump({"traceEvents": merged}, f)
        groups = trace_groups(merged)
        print(f"stitched {len(named)} file(s) -> {args.stitch}: "
              f"{len(merged)} events, {len(groups)} trace id(s)")
        if groups and not args.quiet:
            _print_trace_table(groups)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
