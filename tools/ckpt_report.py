#!/usr/bin/env python3
"""Verify checkpoints and pretty-print the v9 ``checkpoint`` report
section.

Two input kinds, auto-detected per argument:

* a checkpoint path (the anchor a run's ``--checkpoint`` named, or its
  ``.manifest.json`` sidecar): every generation recorded in the
  integrity manifest is re-verified — file present, size, CRC32, sha256
  — plus resumability (at least one generation loads), printed as a
  table.  A manifest-less single file is checked as a legacy
  generation-0 checkpoint.  Exit 1 when NOTHING verifies (a torn latest
  generation with a good older one still exits 0: that is exactly the
  fallback the runtime performs).
* a JSON/JSONL document holding run reports (bench artifacts embed them
  as ``run_report``): the ``checkpoint`` section is validated —
  counts/totals well-typed, v9 keys integral when present — and
  pretty-printed.  A document whose reports carry no checkpoint section
  passes trivially (not every run checkpoints).

Stdlib-only, like the other tools/ validators; wired non-fatally into
benchmarks/run_tpu_round5b.sh.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_NUM = (int, float)

#: section keys: name -> (required, value must be an int, counts must
#: be >= 0).  The four v8 keys are always present in a non-null section;
#: the v9 rotation/async/preempt keys are additive.
_KEYS = {
    "saves": (True, True),
    "save_total_s": (True, False),
    "restores": (True, True),
    "restore_total_s": (True, False),
    "generations": (False, True),
    "latest_generation": (False, True),
    "verify_failures": (False, True),
    "fallbacks": (False, True),
    "async_saves": (False, True),
    "async_dropped": (False, True),
    "async_write_failures": (False, True),
    "async_queue_depth": (False, True),
    "preempt_snapshots": (False, True),
}


def validate_checkpoint(sec) -> list:
    """Problems with one report's ``checkpoint`` section ([] = valid)."""
    if sec is None:
        return []
    if not isinstance(sec, dict):
        return [f"checkpoint section is {type(sec).__name__}, not dict"]
    problems = []
    for key, (required, integral) in _KEYS.items():
        if key not in sec:
            if required:
                problems.append(f"missing key {key!r}")
            continue
        v = sec[key]
        if integral and not isinstance(v, int):
            problems.append(f"{key} is {type(v).__name__}, not int")
        elif not isinstance(v, _NUM) or isinstance(v, bool):
            problems.append(f"{key} is {type(v).__name__}, not numeric")
        elif v < 0:
            problems.append(f"{key} is negative ({v})")
    if isinstance(sec.get("saves"), int) and isinstance(
            sec.get("async_saves"), int):
        if sec["async_saves"] > sec["saves"]:
            problems.append(
                f"async_saves {sec['async_saves']} exceeds saves "
                f"{sec['saves']}")
    return problems


def print_checkpoint(label: str, sec: dict) -> None:
    print(f"  [{label}]")
    print(f"    saves {sec.get('saves', 0)} "
          f"({sec.get('save_total_s', 0.0):.3f} s total), "
          f"restores {sec.get('restores', 0)} "
          f"({sec.get('restore_total_s', 0.0):.3f} s total)")
    if "generations" in sec or "latest_generation" in sec:
        print(f"    rotation: {sec.get('generations', '?')} "
              f"generation(s) on disk, latest g"
              f"{sec.get('latest_generation', '?')}")
    vf, fb = sec.get("verify_failures", 0), sec.get("fallbacks", 0)
    if vf or fb:
        print(f"    integrity: {vf} verify failure(s), {fb} "
              f"fallback(s) to an older generation")
    if "async_saves" in sec:
        print(f"    async: {sec['async_saves']} background save(s), "
              f"{sec.get('async_dropped', 0)} superseded, "
              f"{sec.get('async_write_failures', 0)} failed")
    if sec.get("preempt_snapshots"):
        print(f"    preemption: {sec['preempt_snapshots']} graceful "
              f"final snapshot(s)")


# --------------------------------------------------------------------------
# on-disk checkpoint verification
# --------------------------------------------------------------------------


def _looks_like_checkpoint(path: str) -> bool:
    """Heuristic input-kind switch: manifest sidecars and npz
    checkpoints are verified on disk; .json/.jsonl go down the report
    path."""
    from tmhpvsim_tpu.engine import checkpoint as ckpt

    if path.endswith(".manifest.json"):
        return True
    if path.endswith((".json", ".jsonl")):
        return False
    if ckpt.read_manifest(path) is not None or ckpt._shard_paths(path):
        return True
    if os.path.exists(path):  # a bare file: npz magic = zip "PK"
        try:
            with open(path, "rb") as f:
                return f.read(2) == b"PK"
        except OSError:
            return False
    return False


def check_checkpoint(path: str, quiet: bool = False) -> bool:
    """Verify one checkpoint's generations; True when it can resume."""
    from tmhpvsim_tpu.engine import checkpoint as ckpt

    if path.endswith(".manifest.json"):
        path = path[: -len(".manifest.json")]
    shards = ckpt._shard_paths(path)
    if not os.path.exists(path) and \
            ckpt.read_manifest(path) is None and shards:
        if not quiet:
            print(f"{path}: {len(shards)} per-host shard(s)")
        return all(check_checkpoint(sp, quiet) for sp in shards)

    man = ckpt.read_manifest(path)
    d = os.path.dirname(path) or "."
    ok_any = False
    if man is None:
        try:
            meta = ckpt.peek_meta(path)
            ok_any = True
            if not quiet:
                print(f"{path}: legacy single file (generation 0), "
                      f"resumes at block {meta.get('next_block')}")
        except ckpt.CheckpointError as e:
            print(f"{path}: FAIL — {e}")
        return ok_any

    if not quiet:
        print(f"{path}: manifest format {man.get('format')}, keep "
              f"{man.get('keep')}, latest g{man.get('latest')}")
    rows = []
    for e in sorted((e for e in man["generations"] if isinstance(e, dict)),
                    key=lambda e: e.get("gen", 0), reverse=True):
        fpath = os.path.join(d, e.get("file", ""))
        bad = ckpt._verify_entry(fpath, e)
        if bad is None:
            ok_any = True
        rows.append((e.get("gen"), e.get("next_block"),
                     e.get("size"), bad or "ok"))
    if not quiet:
        for gen, nb, size, verdict in rows:
            print(f"    g{gen}: next_block {nb}, {size} bytes — "
                  f"{verdict}")
    anchor = ("ok" if os.path.exists(path) else "MISSING")
    if not quiet:
        print(f"    anchor: {anchor}; resumable: "
              f"{'yes' if ok_any else 'NO'}")
    if not ok_any:
        print(f"{path}: FAIL — no generation passes verification")
    return ok_any


# --------------------------------------------------------------------------
# report-document path (resilience_report.py shape)
# --------------------------------------------------------------------------


def _iter_docs(path: str):
    with open(path) as f:
        text = f.read()
    try:
        yield json.loads(text)
        return
    except ValueError:
        pass
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("{"):
            yield json.loads(line)


def _extract_reports(doc: dict):
    """(label, report) pairs in a bench/report document."""
    if doc.get("kind") == "tmhpvsim_tpu.run_report":
        yield doc.get("app", "run"), doc
        return
    rep = doc.get("run_report")
    if isinstance(rep, dict):
        label = (doc.get("phase") or doc.get("variant")
                 or rep.get("app") or "run")
        yield label, rep


def check_file(path: str, quiet: bool = False) -> bool:
    """Validate every checkpoint section in ``path``; True when all
    present sections pass (absent = trivially true, with a note)."""
    ok = True
    seen = 0
    for doc in _iter_docs(path):
        if not isinstance(doc, dict):
            continue
        for label, rep in _extract_reports(doc):
            sec = rep.get("checkpoint")
            if sec is None:
                continue
            seen += 1
            problems = validate_checkpoint(sec)
            if problems:
                ok = False
                print(f"{path}: [{label}] INVALID checkpoint section:")
                for p in problems:
                    print(f"    - {p}")
            elif not quiet:
                print(f"{path}: checkpoint section valid")
                print_checkpoint(label, sec)
    if seen == 0 and not quiet:
        print(f"{path}: no checkpoint sections (ok — not every run "
              f"checkpoints)")
    return ok


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="verify checkpoints on disk and validate/pretty-"
                    "print v9 checkpoint report sections")
    ap.add_argument("paths", nargs="+",
                    help="checkpoint anchors / .manifest.json sidecars "
                         "and/or JSON(L) report documents")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="only print failures")
    args = ap.parse_args(argv)
    ok = True
    for path in args.paths:
        try:
            if _looks_like_checkpoint(path):
                ok = check_checkpoint(path, quiet=args.quiet) and ok
            else:
                ok = check_file(path, quiet=args.quiet) and ok
        except FileNotFoundError:
            print(f"{path}: no such file")
            ok = False
        except ValueError as e:
            print(f"{path}: malformed JSON ({e})")
            ok = False
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
