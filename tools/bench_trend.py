#!/usr/bin/env python
"""Benchmark trend table + steady-state regression gate.

Reads the checked-in per-round bench artifacts (``BENCH_r0*.json`` —
driver wrappers around bench.py's headline JSON) plus any newer
headline / run_report documents, prints a compile / steady / throughput
trend table, and exits nonzero when the newest round regressed its
steady-state block wall (or, when no steady timing is recorded,
its throughput) by more than ``--max-regress`` percent against the best
prior round on the SAME platform — the gate ``run_tpu_round5b.sh`` and
CI hang the bench trajectory on.

Accepted document shapes (the repo's bench history spans all four):

* driver wrapper: ``{"n", "cmd", "rc", "tail", "parsed"}`` — ``parsed``
  is the headline doc, or None for a failed round (shown as a failed
  row, never gated on);
* legacy headline (round 3): top-level ``value`` / ``compile_s`` /
  ``best_round_wall_s`` / ``timed_blocks``;
* variant headline (rounds 4+): ``variants`` dict keyed by variant
  name, ``headline_variant`` naming the winner; steady wall comes from
  the winner's ``best_round_wall_s`` over ``timed_blocks``, or from the
  embedded ``run_report.timing`` when present (PR-2 bench docs);
* a bare obs RunReport document (``kind: tmhpvsim_tpu.run_report``).

The table also carries each row's telemetry/analytics levels (from the
embedded config echo; pre-instrumentation docs read as 'off'), an
``ovh%`` column: the instrumented row's steady block wall vs the best
same-platform uninstrumented row, a ``serve`` column: the
scenario-serving request-coalescing ratio (requests per fused dispatch,
from a v6 ``serving`` section or a ``bench.py --serve`` doc), and the
v8 precision axes: ``cdt``/``kimpl`` (the winning plan's compute dtype
and kernel implementation; pre-v8 docs read as f32/exact) plus a
``prec`` column pricing the precision levers — the best
speedup-vs-exact/f32 from the row's own ``precision`` section when its
sweep timed both, else the row's throughput vs the best same-platform
exact/f32 row.  The v10 ``cost`` section adds a ``cost`` column — the
row's north-star fraction (and, parenthesised, its VPU roofline
fraction when the chip's peaks are known) — and the regression-gate
verdict reports the newest round's roofline fraction alongside the
steady-wall comparison.  The v13 ``mesh`` section (and ``bench.py
--hosts`` artifacts) adds ``mesh``/``hosts`` columns — the device-mesh
shape and process count.  The v15 ``attribution`` section adds a
``phases`` column — the dominant semantic phase and its device-time
share from the scoped-trace split (pre-v15 docs render ``-``) — and
every row whose headline is a cpu-fallback artifact (``"platform":
"cpu-fallback"`` or ``salvaged_after_tpu_failure``) carries an
explicit ``fallback`` marker in the note column, so a salvaged round
can never be misread as a TPU number.  A round's north-star fraction
always comes from its OWN top-level headline; a cpu-fallback doc's
embedded ``last_tpu_headline`` is a prior round's copy, flagged in the
note column and never promoted into the row (the BENCH_r05 stale-0.183
trap).  ``--json`` emits the rows + gate verdict as one JSON document
for machine consumers.

No third-party imports: runs anywhere the repo checks out.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

REPORT_KIND = "tmhpvsim_tpu.run_report"


def _steady_from_headline(doc: dict) -> float | None:
    """Steady block wall [s] of a headline doc, best effort."""
    rep = doc.get("run_report")
    if isinstance(rep, dict):
        timing = rep.get("timing") or {}
        if timing.get("steady_block_s") is not None:
            return float(timing["steady_block_s"])
    timed_blocks = doc.get("timed_blocks")
    variants = doc.get("variants")
    if isinstance(variants, dict) and variants:
        best = variants.get(doc.get("headline_variant"))
        if not isinstance(best, dict):
            rated = [v for v in variants.values()
                     if isinstance(v, dict) and "rate" in v]
            best = max(rated, key=lambda v: v["rate"]) if rated else None
        if isinstance(best, dict) and \
                best.get("best_round_wall_s") is not None and timed_blocks:
            return float(best["best_round_wall_s"]) / float(timed_blocks)
    if doc.get("best_round_wall_s") is not None and timed_blocks:
        return float(doc["best_round_wall_s"]) / float(timed_blocks)
    return None


def _compile_from_headline(doc: dict) -> float | None:
    variants = doc.get("variants")
    if isinstance(variants, dict):
        best = variants.get(doc.get("headline_variant"))
        if isinstance(best, dict) and best.get("compile_s") is not None:
            return float(best["compile_s"])
    if doc.get("compile_s") is not None:
        return float(doc["compile_s"])
    rep = doc.get("run_report")
    if isinstance(rep, dict):
        timing = rep.get("timing") or {}
        if timing.get("compile_s") is not None:
            return float(timing["compile_s"])
    return None


def _serve_ratio(doc) -> float | None:
    """Request-coalescing ratio (requests per fused dispatch) from a v6
    ``serving`` section or a ``bench.py --serve`` doc, best effort."""
    if doc.get("kind") == REPORT_KIND:
        sec = doc.get("serving")
    else:
        if isinstance(doc.get("coalescing"), (int, float)):
            return float(doc["coalescing"])
        rep = doc.get("run_report")
        sec = rep.get("serving") if isinstance(rep, dict) else None
    if isinstance(sec, dict) and sec.get("batches"):
        return float(sec.get("requests", 0)) / float(sec["batches"])
    return None


def _precision_axes(doc) -> tuple:
    """(compute_dtype, kernel_impl, rng_batch, geom_stride,
    best_sweep_speedup) of one document.

    Axes come from the winning plan echo (``tuned_plan`` on headline
    docs, the v8/v11 ``plan`` fields on RunReports); pre-v8 documents
    predate the precision fields and read as the exact/f32 defaults,
    pre-v11 documents predate the scan-restructuring fields and read as
    scan/1.  The last element is the best ``speedup_vs_exact_f32``
    among the non-default variants of the doc's own ``precision``
    section — the within-process pricing bench.py computed when its
    sweep timed both sides — or None."""
    if doc.get("kind") == REPORT_KIND:
        plan, rep = doc.get("plan"), doc
    else:
        rep = doc.get("run_report")
        rep = rep if isinstance(rep, dict) else {}
        plan = doc.get("tuned_plan")
        if not isinstance(plan, dict):
            plan = rep.get("plan")
    if not isinstance(plan, dict):
        plan = {}
    cdt = plan.get("compute_dtype") or "f32"
    kimpl = plan.get("kernel_impl") or "exact"
    rb = plan.get("rng_batch") or "scan"
    gs = plan.get("geom_stride") or 1
    speed = None
    prec = rep.get("precision")
    if isinstance(prec, dict):
        for v in (prec.get("variants") or {}).values():
            if not isinstance(v, dict):
                continue
            s = v.get("speedup_vs_exact_f32")
            nondefault = (v.get("compute_dtype", "f32") != "f32"
                          or v.get("kernel_impl", "exact") != "exact"
                          or v.get("rng_batch", "scan") != "scan"
                          or (v.get("geom_stride", 1) or 1) != 1)
            if s is not None and nondefault:
                speed = s if speed is None else max(speed, s)
    return cdt, kimpl, rb, gs, speed


def _cost_fields(doc) -> tuple:
    """(north_star_frac, roofline_frac_vpu) from a v10 ``cost`` section
    — the bare RunReport's, the headline's embedded run_report's, or
    the winning variant's.  Pre-v10 documents read as (None, None)."""
    sec = None
    if doc.get("kind") == REPORT_KIND:
        sec = doc.get("cost")
    else:
        rep = doc.get("run_report")
        if isinstance(rep, dict):
            sec = rep.get("cost")
        if not isinstance(sec, dict):
            variants = doc.get("variants")
            if isinstance(variants, dict):
                best = variants.get(doc.get("headline_variant"))
                if isinstance(best, dict):
                    sec = best.get("cost")
    if not isinstance(sec, dict):
        return None, None
    nsf = sec.get("north_star_frac")
    vpu = sec.get("roofline_frac_vpu")
    return (float(nsf) if isinstance(nsf, (int, float)) else None,
            float(vpu) if isinstance(vpu, (int, float)) else None)


def _mesh_fields(doc) -> tuple:
    """(mesh, hosts) of one document: the device-mesh shape as an
    ``NxM`` string and the process (host) count, from a v13 ``mesh``
    section — the bare RunReport's, the embedded run_report's, or a
    ``bench.py --hosts`` artifact's top-level mesh doc.  Pre-v13
    documents read as (None, None)."""
    sec = None
    if doc.get("kind") == REPORT_KIND:
        sec = doc.get("mesh")
    elif isinstance(doc.get("mesh"), dict):
        sec = doc["mesh"]
    else:
        rep = doc.get("run_report")
        if isinstance(rep, dict) and isinstance(rep.get("mesh"), dict):
            sec = rep["mesh"]
    hosts = doc.get("hosts") if isinstance(doc.get("hosts"), int) else None
    if not isinstance(sec, dict):
        return None, hosts
    shape = sec.get("shape")
    mesh = ("x".join(str(int(s)) for s in shape)
            if isinstance(shape, list) and shape else None)
    if hosts is None and isinstance(sec.get("process_count"), int):
        hosts = sec["process_count"]
    return mesh, hosts


def _pod_fields(doc) -> tuple:
    """(comm_frac, cost_err_pct) of one document: the collective time
    fraction from a v14 ``pod`` section and the signed flops model
    error from the v14 ``cost.model_error`` sub-doc — the bare
    RunReport's or the embedded run_report's.  Pre-v14 documents read
    as (None, None) and render as ``-``."""
    pod = cost = None
    if doc.get("kind") == REPORT_KIND:
        pod, cost = doc.get("pod"), doc.get("cost")
    else:
        rep = doc.get("run_report")
        if isinstance(rep, dict):
            pod, cost = rep.get("pod"), rep.get("cost")
    cf = pod.get("comm_frac") if isinstance(pod, dict) else None
    err = None
    if isinstance(cost, dict) and isinstance(cost.get("model_error"),
                                             dict):
        e = cost["model_error"].get("flops_err_pct")
        if isinstance(e, (int, float)):
            err = float(e)
    return (float(cf) if isinstance(cf, (int, float)) else None, err)


def _attr_fields(doc) -> str | None:
    """Dominant-phase cell ("markov:48%") from a v15 ``attribution``
    section — the bare RunReport's, the embedded run_report's, or a
    ``bench.py --attr`` artifact's baseline variant.  Pre-v15 documents
    and basis-``unavailable`` sections (trace carried no scope
    metadata) read as None and render ``-``."""
    sec = None
    if doc.get("kind") == REPORT_KIND:
        sec = doc.get("attribution")
    else:
        rep = doc.get("run_report")
        if isinstance(rep, dict):
            sec = rep.get("attribution")
        if not isinstance(sec, dict):
            variants = doc.get("variants")
            if isinstance(variants, dict):
                base = variants.get(doc.get("baseline"))
                if isinstance(base, dict):
                    sec = base.get("attribution")
    if not isinstance(sec, dict) or sec.get("basis") == "unavailable":
        return None
    phases = sec.get("phases")
    if not isinstance(phases, dict) or not phases:
        return None
    name, p = max(
        phases.items(),
        key=lambda kv: kv[1].get("frac", 0.0) if isinstance(kv[1], dict)
        else 0.0)
    frac = p.get("frac") if isinstance(p, dict) else None
    if not isinstance(frac, (int, float)):
        return None
    return f"{name}:{frac * 100:.0f}%"


def _mark_fallback(row: dict, doc: dict) -> None:
    """Attach the explicit ``fallback`` marker to a row whose headline
    is a CPU-fallback artifact — ``"platform": "cpu-fallback"`` or the
    watchdog-salvage flag ``salvaged_after_tpu_failure`` (bench.py sets
    both on a salvaged round).  The marker leads the note column so it
    survives next to the stale-embedded-headline flag."""
    if row.get("platform") == "cpu-fallback" \
            or doc.get("salvaged_after_tpu_failure"):
        row["fallback"] = True
        note = row.get("note")
        row["note"] = f"fallback; {note}" if note else "fallback"


def _stale_embedded_note(doc: dict) -> str | None:
    """A cpu-fallback headline carries the newest REAL-TPU headline as
    ``last_tpu_headline`` evidence (bench.py _last_tpu_evidence).  That
    embedded doc is a COPY of a prior round — its north_star_frac must
    never be read as this round's number (the BENCH_r05 stale-0.183
    trap).  Returns a flag note when such a copy is embedded."""
    stale = doc.get("last_tpu_headline")
    if not isinstance(stale, dict):
        return None
    nsf = stale.get("north_star_frac")
    tag = (f" (north_star_frac={nsf})"
           if isinstance(nsf, (int, float)) else "")
    return f"embedded last_tpu_headline{tag} is a prior round's copy"


def _levels(cfg) -> tuple:
    """(telemetry, analytics) levels from a config echo; pre-PR-3/PR-6
    documents predate the fields and read as 'off'."""
    if not isinstance(cfg, dict):
        cfg = {}
    return (cfg.get("telemetry") or "off", cfg.get("analytics") or "off")


def normalize(path: str) -> dict:
    """One artifact -> a trend row (``failed`` rows carry only a name)."""
    name = os.path.basename(path)
    row = {"name": name, "order": name, "platform": None, "value": None,
           "compile_s": None, "steady_block_s": None,
           "telemetry": None, "analytics": None, "serve": None,
           "compute_dtype": None, "kernel_impl": None,
           "rng_batch": None, "geom_stride": None,
           "precision_speedup": None, "north_star_frac": None,
           "roofline_frac_vpu": None, "fleet_sites": None,
           "fleet_ratio": None, "mesh": None, "hosts": None,
           "comm_frac": None, "cost_err_pct": None,
           "attr": None, "fallback": False,
           "fleet_workers": None, "cb_speedup": None,
           "failed": True}
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        # a bench_partial.jsonl journal is many records, not one doc —
        # show it as a partial row (never gated on) instead of
        # "unreadable" noise
        if path.endswith(".jsonl"):
            row["note"] = "partial journal (not a round artifact)"
        else:
            row["note"] = f"unreadable: {e}"
        return row
    if not isinstance(doc, dict):
        row["note"] = "not a JSON object"
        return row
    if "phase" in doc and "value" not in doc and "variants" not in doc:
        # a single journalled partial record (bench.py _persist_partial)
        row["note"] = f"partial record (phase={doc.get('phase')})"
        return row

    if "parsed" in doc and "cmd" in doc:          # driver wrapper
        if doc.get("n") is not None:
            row["order"] = f"{int(doc['n']):06d}"
            row["name"] = f"r{int(doc['n']):02d}"
        if doc.get("parsed") is None:
            row["note"] = f"round failed (rc={doc.get('rc')})"
            return row
        doc = doc["parsed"]

    if doc.get("kind") == REPORT_KIND:            # bare RunReport
        timing = doc.get("timing") or {}
        headline = doc.get("headline") or {}
        tel, ana = _levels(doc.get("config"))
        cdt, kimpl, rb, gs, prec_speed = _precision_axes(doc)
        nsf, vpu = _cost_fields(doc)
        fs, fr = _fleet_fields(doc)
        fw, cb = _serve_fleet_fields(doc)
        mesh, hosts = _mesh_fields(doc)
        cf, cerr = _pod_fields(doc)
        row.update(
            failed=False,
            fleet_workers=fw, cb_speedup=cb,
            platform=(doc.get("device") or {}).get("platform"),
            value=headline.get("site_seconds_per_s"),
            compile_s=timing.get("compile_s"),
            steady_block_s=timing.get("steady_block_s"),
            telemetry=tel, analytics=ana,
            serve=_serve_ratio(doc),
            compute_dtype=cdt, kernel_impl=kimpl,
            rng_batch=rb, geom_stride=gs,
            precision_speedup=prec_speed,
            north_star_frac=nsf, roofline_frac_vpu=vpu,
            fleet_sites=fs, fleet_ratio=fr,
            mesh=mesh, hosts=hosts,
            comm_frac=cf, cost_err_pct=cerr,
            attr=_attr_fields(doc),
        )
        _mark_fallback(row, doc)
        return row

    # headline docs, serve-only artifacts (bench.py --serve writes no
    # throughput value — the coalescing ratio IS the headline), and
    # --hosts multi-host mechanics artifacts
    if "value" in doc or "variants" in doc or "coalescing" in doc \
            or "hosts" in doc \
            or doc.get("artifact") == "scenario-serve fleet load":
        rep = doc.get("run_report")
        tel, ana = _levels(rep.get("config")
                           if isinstance(rep, dict) else None)
        cdt, kimpl, rb, gs, prec_speed = _precision_axes(doc)
        nsf, vpu = _cost_fields(doc)
        fs, fr = _fleet_fields(doc)
        fw, cb = _serve_fleet_fields(doc)
        mesh, hosts = _mesh_fields(doc)
        cf, cerr = _pod_fields(doc)
        # the round's OWN top-level headline is authoritative for the
        # north-star fraction; the cost-section copy is a fallback, and
        # anything inside an embedded last_tpu_headline is a prior
        # round's number and must never be promoted (BENCH_r05 carried
        # a stale 0.183 copy beside its true 0.001)
        top_nsf = doc.get("north_star_frac")
        if isinstance(top_nsf, (int, float)):
            nsf = float(top_nsf)
        row.update(
            failed=False,
            fleet_workers=fw, cb_speedup=cb,
            platform=doc.get("platform"),
            value=doc.get("value"),
            compile_s=_compile_from_headline(doc),
            steady_block_s=_steady_from_headline(doc),
            telemetry=tel, analytics=ana,
            serve=_serve_ratio(doc),
            compute_dtype=cdt, kernel_impl=kimpl,
            rng_batch=rb, geom_stride=gs,
            precision_speedup=prec_speed,
            north_star_frac=nsf, roofline_frac_vpu=vpu,
            fleet_sites=fs, fleet_ratio=fr,
            mesh=mesh, hosts=hosts,
            comm_frac=cf, cost_err_pct=cerr,
            attr=_attr_fields(doc),
        )
        stale = _stale_embedded_note(doc)
        if stale:
            row["note"] = stale
        _mark_fallback(row, doc)
        return row

    row["note"] = "unrecognised document shape"
    return row


def _fmt(v, unit="") -> str:
    if v is None:
        return "-"
    if isinstance(v, float) and (abs(v) >= 1e5 or 0 < abs(v) < 1e-3):
        return f"{v:.3g}{unit}"
    return f"{v:.3f}{unit}" if isinstance(v, float) else f"{v}{unit}"


def annotate_overhead(rows: list) -> None:
    """Attach ``overhead_pct`` to every instrumented row: its steady
    block wall vs the best same-platform row with BOTH telemetry and
    analytics off — the table's at-a-glance answer to "what does the
    in-graph observability cost?".  None when the row is itself
    uninstrumented, failed, or has no clean-row baseline."""
    base: dict = {}
    for r in rows:
        if r["failed"] or r["steady_block_s"] is None:
            continue
        if (r.get("telemetry") or "off") == "off" and \
                (r.get("analytics") or "off") == "off":
            p = r["platform"]
            if p not in base or r["steady_block_s"] < base[p]:
                base[p] = r["steady_block_s"]
    for r in rows:
        r["overhead_pct"] = None
        if r["failed"] or r["steady_block_s"] is None:
            continue
        if (r.get("telemetry") or "off") == "off" and \
                (r.get("analytics") or "off") == "off":
            continue
        b = base.get(r["platform"])
        if b:
            r["overhead_pct"] = (r["steady_block_s"] / b - 1.0) * 100.0


def _all_defaults(r) -> bool:
    """True when the row ran every speed-lever axis at its default —
    the only rows that may anchor the cross-row lever pricing."""
    return ((r.get("compute_dtype") or "f32") == "f32"
            and (r.get("kernel_impl") or "exact") == "exact"
            and (r.get("rng_batch") or "scan") == "scan"
            and (r.get("geom_stride") or 1) == 1)


def annotate_precision(rows: list) -> None:
    """Price the speed levers across rows: every row running a
    non-default compute_dtype/kernel_impl/rng_batch/geom_stride whose
    own document carried no sweep pricing gets ``precision_speedup`` =
    its throughput vs the best same-platform all-defaults row.  Rows
    priced by their own v8 ``precision`` section (bench.py timed both
    sides in one process — the cleaner comparison) keep that number."""
    base: dict = {}
    for r in rows:
        if r["failed"] or r["value"] is None:
            continue
        if _all_defaults(r):
            p = r["platform"]
            if p not in base or r["value"] > base[p]:
                base[p] = r["value"]
    for r in rows:
        r.setdefault("precision_speedup", None)
        if r.get("precision_speedup") is not None:
            continue
        if r["failed"] or r["value"] is None:
            continue
        if _all_defaults(r):
            continue
        b = base.get(r["platform"])
        if b:
            r["precision_speedup"] = round(r["value"] / b, 2)


def _fleet_fields(doc: dict) -> tuple:
    """(fleet_sites, fleet_ratio) — from a ``bench.py --fleet-*``
    artifact's ``fleet`` block (het_over_homog is the heterogeneity
    price), else from a v12 config echo's fleet identity (sites only).
    Fleet-less documents read as (None, None)."""
    sec = doc.get("fleet")
    if isinstance(sec, dict) and "n_sites" in sec:
        return sec.get("n_sites"), sec.get("het_over_homog")
    for rep in (doc, doc.get("run_report")):
        if isinstance(rep, dict):
            cfg = rep.get("config")
            if isinstance(cfg, dict) and isinstance(cfg.get("fleet"),
                                                    dict):
                return cfg["fleet"].get("n_sites"), None
    return None, None


def _serve_fleet_fields(doc) -> tuple:
    """(fleet_workers, cb_speedup) of the horizontally-scaled serving
    tier — worker count from a ``bench.py --serve-fleet`` doc or a v16
    ``serving.fleet`` report section, and the continuous-batching
    sustained-throughput speedup over the single-worker window batcher
    when the artifact timed both.  Fleet-less serves read (None, None)."""
    workers = cb = None
    if doc.get("artifact") == "scenario-serve fleet load":
        workers = doc.get("workers")
        cb = doc.get("speedup")
    for rep in (doc, doc.get("run_report")):
        if not isinstance(rep, dict) or rep.get("kind") != REPORT_KIND:
            continue
        sec = rep.get("serving")
        fleet = sec.get("fleet") if isinstance(sec, dict) else None
        if isinstance(fleet, dict) and workers is None:
            workers = len(fleet.get("workers") or [])
        hl = rep.get("headline")
        if isinstance(hl, dict) and cb is None \
                and isinstance(hl.get("speedup"), (int, float)):
            cb = hl["speedup"]
    return workers, cb


def _fmt_fleet(r) -> str:
    """The ``fleet`` cell: site count, with the heterogeneous-over-
    homogeneous throughput ratio appended when bench.py timed both."""
    fs = r.get("fleet_sites")
    if fs is None:
        return "-"
    fr = r.get("fleet_ratio")
    return f"{fs}" if fr is None else f"{fs}@{fr:.2f}x"


def _fmt_cost(r) -> str:
    """The ``cost`` cell: north-star fraction, with the VPU roofline
    fraction parenthesised when the chip's peaks were known."""
    nsf = r.get("north_star_frac")
    if nsf is None:
        return "-"
    vpu = r.get("roofline_frac_vpu")
    cell = f"{nsf:.3f}"
    if vpu is not None:
        cell += f"({vpu * 100:.1f}%vpu)"
    return cell


def print_table(rows: list) -> None:
    cols = ("round", "platform", "site-s/s/chip", "compile_s",
            "steady_block_s", "tel", "analytics", "ovh%", "serve",
            "wrk", "cb",
            "cdt", "kimpl", "rb", "gs", "prec", "fleet", "cost",
            "mesh", "hosts", "comm%", "cost-err", "phases", "note")
    table = [cols]
    for r in rows:
        ovh = r.get("overhead_pct")
        srv = r.get("serve")
        prec = r.get("precision_speedup")
        cf = r.get("comm_frac")
        cerr = r.get("cost_err_pct")
        fw = r.get("fleet_workers")
        cb = r.get("cb_speedup")
        table.append((
            r["name"], r["platform"] or "-", _fmt(r["value"]),
            _fmt(r["compile_s"]), _fmt(r["steady_block_s"]),
            r.get("telemetry") or "-", r.get("analytics") or "-",
            "-" if ovh is None else f"{ovh:+.1f}",
            "-" if srv is None else f"{srv:.2f}x",
            "-" if fw is None else str(fw),
            "-" if cb is None else f"{cb:.2f}x",
            r.get("compute_dtype") or "-", r.get("kernel_impl") or "-",
            r.get("rng_batch") or "-",
            "-" if r.get("geom_stride") is None else str(r["geom_stride"]),
            "-" if prec is None else f"{prec:.2f}x",
            _fmt_fleet(r),
            _fmt_cost(r),
            r.get("mesh") or "-",
            "-" if r.get("hosts") is None else str(r["hosts"]),
            "-" if cf is None else f"{cf * 100:.1f}",
            "-" if cerr is None else f"{cerr:+.1f}%",
            r.get("attr") or "-",
            r.get("note", ""),
        ))
    widths = [max(len(str(line[i])) for line in table)
              for i in range(len(cols))]
    for i, line in enumerate(table):
        print("  ".join(str(c).ljust(w) for c, w in zip(line, widths))
              .rstrip())
        if i == 0:
            print("  ".join("-" * w for w in widths))


def _cost_suffix(r) -> str:
    """Roofline report appended to the gate verdict (v10 cost rows):
    the newest round's north-star + VPU roofline fractions ride next to
    the steady-wall comparison so a wall regression and a roofline drop
    are read together."""
    nsf, vpu = r.get("north_star_frac"), r.get("roofline_frac_vpu")
    if nsf is None:
        return ""
    out = f"; north_star_frac={nsf:.3f}"
    if vpu is not None:
        out += f", roofline_vpu={vpu * 100:.2f}%"
    return out


def check_regression(rows: list, max_regress_pct: float):
    """(ok, message): newest valid round vs the best prior same-platform
    round — steady block wall when both recorded one, throughput
    otherwise.  Rows with a v10 cost section get their roofline
    fractions reported alongside the verdict."""
    valid = [r for r in rows if not r["failed"]]
    if not valid:
        return True, ("no prior same-platform round to compare against "
                      "(only partial/failed artifacts); gate passes")
    if len(valid) < 2:
        return True, ("no prior same-platform round to compare against; "
                      "gate passes")
    newest = valid[-1]
    prior = [r for r in valid[:-1] if r["platform"] == newest["platform"]]
    if not prior:
        return True, (f"no prior round on platform "
                      f"{newest['platform']!r}; gate passes")
    tol = max_regress_pct / 100.0
    steady_prior = [r for r in prior if r["steady_block_s"] is not None]
    if newest["steady_block_s"] is not None and steady_prior:
        best = min(steady_prior, key=lambda r: r["steady_block_s"])
        limit = best["steady_block_s"] * (1.0 + tol)
        if newest["steady_block_s"] > limit:
            return False, (
                f"STEADY-STATE REGRESSION: {newest['name']} "
                f"steady_block_s={newest['steady_block_s']:.4g} vs best "
                f"prior {best['name']}={best['steady_block_s']:.4g} "
                f"(+{(newest['steady_block_s'] / best['steady_block_s'] - 1) * 100:.1f}% "
                f"> {max_regress_pct:g}% allowed)" + _cost_suffix(newest)
            )
        return True, (
            f"steady gate ok: {newest['name']} "
            f"steady_block_s={newest['steady_block_s']:.4g} within "
            f"{max_regress_pct:g}% of best prior "
            f"{best['name']}={best['steady_block_s']:.4g}"
            + _cost_suffix(newest)
        )
    value_prior = [r for r in prior if r["value"] is not None]
    if newest["value"] is not None and value_prior:
        best = max(value_prior, key=lambda r: r["value"])
        limit = best["value"] * (1.0 - tol)
        if newest["value"] < limit:
            return False, (
                f"THROUGHPUT REGRESSION: {newest['name']} "
                f"value={newest['value']:.4g} vs best prior "
                f"{best['name']}={best['value']:.4g} "
                f"(-{(1 - newest['value'] / best['value']) * 100:.1f}% "
                f"> {max_regress_pct:g}% allowed)" + _cost_suffix(newest)
            )
        return True, (
            f"throughput gate ok: {newest['name']} "
            f"value={newest['value']:.4g} within {max_regress_pct:g}% of "
            f"best prior {best['name']}={best['value']:.4g}"
            + _cost_suffix(newest)
        )
    return True, "newest round records no comparable metric; gate passes"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="bench trend table + steady-state regression gate")
    ap.add_argument("files", nargs="*",
                    help="bench artifacts in round order (default: "
                         "BENCH_r*.json in the repo root, sorted)")
    ap.add_argument("--max-regress", type=float, default=10.0,
                    metavar="PCT",
                    help="allowed steady-state (or throughput) regression "
                         "of the newest round vs the best prior "
                         "same-platform round [%%] (default 10)")
    ap.add_argument("--json", action="store_true",
                    help="emit the rows + gate verdict as one JSON "
                         "document instead of the table (machine "
                         "consumers; exit code unchanged)")
    args = ap.parse_args(argv)

    files = args.files
    if not files:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        files = sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))
    if not files:
        print("bench_trend: no bench artifacts found", file=sys.stderr)
        return 0

    rows = [normalize(p) for p in files]
    rows.sort(key=lambda r: r["order"])
    annotate_overhead(rows)
    annotate_precision(rows)
    ok, msg = check_regression(rows, args.max_regress)
    if args.json:
        print(json.dumps({
            "rows": rows,
            "gate": {"ok": ok, "message": msg,
                     "max_regress_pct": args.max_regress},
        }, indent=1))
    else:
        print_table(rows)
        print(msg)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
