#!/bin/bash
# TPU tunnel probe loop. Each attempt is UNBOUNDED — no `timeout`:
# SIGTERM/SIGKILLing a dialing axon process leaves a stale tunnel grant
# that blocks the NEXT process for 10+ minutes (observed round 4;
# .claude/skills/verify). The tunnel fails in two modes: ERROR
# (UNAVAILABLE, process exits on its own — retry after a pause) and HANG
# (dial parks indefinitely — the attempt just waits; it completes the
# moment the tunnel answers). Either way no process is ever killed.
STATUS=/root/repo/benchmarks/tpu_status.txt
LOG=/root/repo/benchmarks/tpu_probe.log
attempt=0
while true; do
  attempt=$((attempt+1))
  echo "attempt $attempt dialing since $(date -u +%FT%TZ)" > "$STATUS"
  echo "--- attempt $attempt $(date -u +%FT%TZ)" >> "$LOG"
  python - >> "$LOG" 2>&1 <<'EOF'
import time
t0 = time.time()
import jax, jax.numpy as jnp
d = jax.devices()[0]
x = jnp.ones((128, 128))
(x @ x).block_until_ready()
print(f"OK platform={d.platform} kind={d.device_kind} "
      f"init+compile={time.time()-t0:.1f}s", flush=True)
EOF
  if [ $? -eq 0 ]; then
    echo "TPU_UP attempt=$attempt $(date -u +%FT%TZ)" > "$STATUS"
    exit 0
  fi
  echo "error-mode exit attempt=$attempt $(date -u +%FT%TZ)" > "$STATUS"
  sleep 120
done
