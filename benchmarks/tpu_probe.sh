#!/bin/bash
# ONE unbounded TPU tunnel probe. No `timeout`: SIGTERM/SIGKILLing a
# dialing axon process leaves a stale tunnel grant that blocks the NEXT
# process for 10+ minutes (observed round 4; .claude/skills/verify).
# The process parks while the tunnel is down and completes the moment it
# answers, writing TPU_UP to benchmarks/tpu_status.txt.
STATUS=/root/repo/benchmarks/tpu_status.txt
LOG=/root/repo/benchmarks/tpu_probe.log
echo "parked waiting for tunnel since $(date -u +%FT%TZ)" > "$STATUS"
python - >> "$LOG" 2>&1 <<'EOF'
import time
t0 = time.time()
import jax, jax.numpy as jnp
d = jax.devices()[0]
x = jnp.ones((128, 128))
(x @ x).block_until_ready()
print(f"OK platform={d.platform} kind={d.device_kind} "
      f"init+compile={time.time()-t0:.1f}s", flush=True)
EOF
if [ $? -eq 0 ]; then
  echo "TPU_UP $(date -u +%FT%TZ)" > "$STATUS"
else
  echo "probe exited nonzero $(date -u +%FT%TZ)" > "$STATUS"
fi
