#!/bin/bash
# Watches tpu_status.txt; the moment the probe reports TPU_UP, launches
# the battery (once).  Which battery is the optional first argument
# (default: the full round-5 battery; pass run_tpu_short.sh near the
# round's end so the launched work finishes before the driver's
# harvest needs the single-tenant tunnel).  Separate from tpu_probe.sh
# so the running probe loop's script file is never edited in place.
BATTERY=${1:-/root/repo/benchmarks/run_tpu_round5b.sh}
STATUS=/root/repo/benchmarks/tpu_status.txt
DONE=/root/repo/benchmarks/BATTERY_DONE
LAUNCH_LOG=/root/repo/benchmarks/BATTERY_LAUNCHED
# Completion — not launch — is the skip condition: a watcher restarted
# after a mid-battery crash must relaunch (BATTERY_DONE is only written
# by the battery's last line).  Within one watcher process the `exec`
# below prevents double-launch.
# The status file is CONSUMED (renamed) at launch, so one TPU_UP fires
# exactly one battery: a leftover TPU_UP from an earlier probe run once
# fired a second battery against a dead tunnel (2026-07-31 04:42; the
# whole take ran cpu-fallback).  An unconsumed TPU_UP of any age is
# trustworthy — the battery re-probes per phase and quarantines non-TPU
# results.  Crash recovery (battery died, no BATTERY_DONE): restart
# tpu_probe.sh — it re-verifies the tunnel (hang-dialing until any
# stale grant from the crash clears) and writes a fresh TPU_UP.
while true; do
  if grep -q '^TPU_UP' "$STATUS" 2>/dev/null && [ ! -e "$DONE" ]; then
    mv "$STATUS" "$STATUS.consumed" 2>/dev/null
    echo "launching battery $BATTERY $(date -u +%FT%TZ)" >> "$LAUNCH_LOG"
    exec "$BATTERY"
  fi
  sleep 30
done
