#!/bin/bash
# Watches tpu_status.txt; the moment the probe reports TPU_UP, launches
# the round-5 benchmark battery (once). Separate from tpu_probe.sh so the
# running probe loop's script file is never edited in place.
STATUS=/root/repo/benchmarks/tpu_status.txt
DONE=/root/repo/benchmarks/BATTERY_DONE
LAUNCH_LOG=/root/repo/benchmarks/BATTERY_LAUNCHED
# Completion — not launch — is the skip condition: a watcher restarted
# after a mid-battery crash must relaunch (BATTERY_DONE is only written
# by the battery's last line).  Within one watcher process the `exec`
# below prevents double-launch.
while true; do
  if grep -q '^TPU_UP' "$STATUS" 2>/dev/null && [ ! -e "$DONE" ]; then
    echo "launching battery $(date -u +%FT%TZ)" >> "$LAUNCH_LOG"
    exec /root/repo/benchmarks/run_tpu_round5b.sh
  fi
  sleep 30
done
