#!/bin/bash
# Watches tpu_status.txt; the moment the probe reports TPU_UP, launches
# the round-5 benchmark battery (once). Separate from tpu_probe.sh so the
# running probe loop's script file is never edited in place.
STATUS=/root/repo/benchmarks/tpu_status.txt
SENTINEL=/root/repo/benchmarks/BATTERY_LAUNCHED
while true; do
  if grep -q '^TPU_UP' "$STATUS" 2>/dev/null && [ ! -e "$SENTINEL" ]; then
    touch "$SENTINEL"
    echo "launching battery $(date -u +%FT%TZ)" >> "$SENTINEL"
    /root/repo/benchmarks/run_tpu_round5.sh
    exit 0
  fi
  sleep 30
done
