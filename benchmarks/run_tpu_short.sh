#!/bin/bash
# END-OF-ROUND short battery: the driver's harvest (~15:14 UTC) runs
# `python bench.py` against the single-tenant tunnel, so any battery
# still running then would starve it.  This variant runs only the
# highest-value phases — headline, a 3-trial repro, config 4 — and
# finishes in ~25-35 min, leaving the tunnel free for the harvest.
# Identical gate semantics to run_tpu_round5b.sh (functions sourced
# from it so they cannot drift).  Repro writes to its OWN artifact so
# a 3-trial short run can never replace a richer 6-trial
# REPRO_r05.jsonl a full battery may have committed.
set -u
cd /root/repo
LOG=benchmarks/tpu_round5.log
echo "=== short-battery start $(date -u +%FT%TZ)" >> "$LOG"
source <(sed -n '/^tpu_lines () {/,/^}$/p' benchmarks/run_tpu_round5b.sh)
source <(sed -n '/^run_json () {/,/^}$/p' benchmarks/run_tpu_round5b.sh)
# a failed extraction must not silently "complete" the battery: the
# watcher has already consumed TPU_UP, and BATTERY_DONE would block
# any relaunch with zero artifacts to show for the window
if ! declare -F tpu_lines >/dev/null || ! declare -F run_json >/dev/null; then
  echo "=== short-battery ABORT: gate function extraction failed $(date -u +%FT%TZ)" >> "$LOG"
  exit 1
fi
run_json benchmarks/HEADLINE_r05.json      headline-short
run_json benchmarks/REPRO_r05_short.jsonl  repro-short   --repro 3
run_json benchmarks/BENCH_config4.json     config4-short --config 4
echo "=== short-battery done $(date -u +%FT%TZ)" >> "$LOG"
touch benchmarks/BATTERY_DONE
