#!/bin/bash
# Round-5 TPU battery, take 2 (the first battery's sweep wedged on the
# tunnel's round-4 failure mode mid-wide-threefry; headline + 13 sweep
# entries landed first and are committed).  Differences from take 1:
#   - bench.py headline is fixed: threefry variants first, non-winning
#     sims freed immediately (resident sims measured ~30x degradation),
#     rbg demoted to a 1x1-block probe, configs default to threefry;
#   - --repro 6 runs right after the headline: six fresh-process
#     compiles of scan-threefry-u8 settle whether the 2.06e10 sweep
#     point is reproducible or a compile lottery;
#   - config 4 runs 100k chains as two <=65536-chain slabs (the
#     measured fast regime), bit-identical to the unslabbed run.
# Order: most important first, so a tunnel drop costs the least.
set -u
cd /root/repo
LOG=benchmarks/tpu_round5.log
echo "=== battery-2 start $(date -u +%FT%TZ)" >> "$LOG"

# Warm-start executor (engine/compilecache.py): every bench invocation
# below shares one persistent XLA cache under the benchmarks dir, so
# only the battery's FIRST compile of each executable is cold; the v4
# run_report executor sections record warm vs cold counts per phase.
# (--repro children opt out internally — they measure compile variance.)
export TMHPVSIM_COMPILE_CACHE=benchmarks/xla_cache

tpu_lines () {  # prints the number of top-level platform=="tpu" lines
  python - "$1" <<'EOF'
import json, sys
n = 0
try:
    for ln in open(sys.argv[1]):
        ln = ln.strip()
        if not ln:
            continue
        try:
            doc = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if doc.get("platform") == "tpu":
            n += 1
except OSError:
    pass
print(n)
EOF
}

doc_richness () {  # landed variant + slab entries summed over tpu docs:
                   # the tie-break when two takes have EQUAL tpu_lines (a
                   # wedged take's partial headline may carry fewer
                   # measured variants than the take it would replace)
  python - "$1" <<'EOF'
import json, sys
r = 0
try:
    for ln in open(sys.argv[1]):
        ln = ln.strip()
        if not ln:
            continue
        try:
            doc = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if doc.get("platform") != "tpu":
            continue
        r += sum(1 for v in doc.get("variants", {}).values()
                 if isinstance(v, dict) and "rate" in v)
        r += len(doc.get("echo", {}).get("slabs", doc.get("slabs", [])))
except OSError:
    pass
print(r)
EOF
}

has_partial_doc () {  # rc 0 iff any line carries "partial": true
  python - "$1" <<'EOF'
import json, sys
try:
    for ln in open(sys.argv[1]):
        ln = ln.strip()
        if not ln:
            continue
        try:
            doc = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if doc.get("partial"):
            sys.exit(0)
except OSError:
    pass
sys.exit(1)
EOF
}

run_json () {  # run_json <dest.json> <label> <args...>
  local dest="$1" label="$2"; shift 2
  echo "--- $label start $(date -u +%FT%TZ)" >> "$LOG"
  python bench.py "$@" > "$dest.tmp" 2>> "$LOG"
  local rc=$?
  local new_tpu
  new_tpu=$(tpu_lines "$dest.tmp")
  echo "--- $label rc=$rc tpu_lines=$new_tpu $(date -u +%FT%TZ)" >> "$LOG"
  if [ $rc -eq 0 ] && [ "$new_tpu" -gt 0 ] && ! has_partial_doc "$dest.tmp"; then
    mv "$dest.tmp" "$dest"
    # an earlier failed take's .partial is superseded — but only when
    # this artifact is at least as rich (a CPU-fallback exit is rc=0
    # with few TPU lines; never erase a richer partial with that)
    if [ "$new_tpu" -ge "$(tpu_lines "$dest.partial")" ]; then
      rm -f "$dest.partial"
    fi
    echo "--- $label: TPU artifact written to $dest" >> "$LOG"
  elif [ "$new_tpu" -gt 0 ]; then
    # failed/killed mid-phase (bench's wedged watchdog now exits rc=3)
    # or a partial:true doc slipped out under rc=0: REAL TPU lines
    # landed, so promote to a committed PARTIAL artifact — never to
    # $dest itself, so a wedged take cannot overwrite a previously
    # committed complete artifact (.tmp/.nontpu are gitignored — take
    # 1's 13 TPU sweep entries died with the checkout this way).
    # Never clobber a RICHER partial from a previous take with a
    # poorer one (watcher relaunches after mid-battery crashes); on
    # EQUAL line counts, compare single-doc richness (landed variant +
    # slab entries) and prefer the newer take when at least as rich.
    local old_tpu
    old_tpu=$(tpu_lines "$dest.partial")
    if [ "$new_tpu" -gt "$old_tpu" ] ||
       { [ "$new_tpu" -eq "$old_tpu" ] &&
         [ "$(doc_richness "$dest.tmp")" -ge "$(doc_richness "$dest.partial")" ]; }; then
      mv "$dest.tmp" "$dest.partial"
      echo "--- $label: rc=$rc, $new_tpu TPU line(s); kept as $dest.partial" >> "$LOG"
    else
      mv "$dest.tmp" "$dest.nontpu" 2>/dev/null
      echo "--- $label: rc=$rc, $new_tpu TPU line(s) <= existing $dest.partial ($old_tpu); kept as $dest.nontpu" >> "$LOG"
    fi
  else
    mv "$dest.tmp" "$dest.nontpu" 2>/dev/null
    echo "--- $label: NOT a TPU result; kept as $dest.nontpu" >> "$LOG"
  fi
}

# the headline sweep's variant matrix (bench.VARIANT_CFGS) now carries
# the scan-restructuring levers — scan2-rngblock (whole-block RNG
# pre-generation, bit-identical), scan2-stride60 (strided geometry +
# lerp, sentinel-watched), their combination, and the full stack on top
# of bf16/table — all priced per variant by obs/cost.py and folded into
# the doc's v11 precision section the report loops below validate
run_json benchmarks/HEADLINE_r05.json  headline2
# --repro is now a distribution mode: six fresh-process compiles, one
# seed per trial, summary with min/median/max + CoV (the compile-lottery
# answer in one number)
run_json benchmarks/REPRO_r05.jsonl    repro     --repro 6
run_json benchmarks/BENCH_config4.json config4   --config 4
run_json benchmarks/BENCH_config2.json config2   --config 2
run_json benchmarks/BENCH_config3a.json config3a --config 3a
run_json benchmarks/BENCH_config5.json config5   --config 5
# scenario-serving load point (serve/): coalescing ratio + reply-latency
# quantiles for 8 concurrent clients against one warm in-process server;
# the doc's run_report carries the v6 'serving' section serve_report.py
# validates below
run_json benchmarks/SERVE_r05b.json    serve     --serve 8 --serve-requests 8
# horizontally-scaled serving point (serve/fleet.py): continuous
# batching x 4 warm workers behind the shard-affinity router vs the
# single window worker under the same deep load; the doc's run_report
# carries the v16 'serving.fleet' section serve_report.py validates
# below.  Non-fatal like every phase here: run_json logs rc and the
# battery continues.
run_json benchmarks/SERVEFLEET_r05b.json servefleet --serve-fleet 4 --serve-requests 8
echo "--- scaling start $(date -u +%FT%TZ)" >> "$LOG"
if python bench.py --scaling > benchmarks/SCALING.json.tmp 2>> "$LOG"; then
  mv benchmarks/SCALING.json.tmp benchmarks/SCALING.json
fi
echo "--- profile start $(date -u +%FT%TZ)" >> "$LOG"
# rc=4 is the platform guard (obs/profiler.py): the trace captured a
# different backend than expected (round 5's "TPU" traces were silently
# CPU-fallback) — quarantine the capture so it cannot be archived as
# device evidence; trace_manifest.json inside records what actually ran
python bench.py --profile benchmarks/profile_r05 >> "$LOG" 2>&1
prof_rc=$?
if [ "$prof_rc" -eq 4 ]; then
  mv benchmarks/profile_r05 benchmarks/profile_r05.mismatch 2>/dev/null
  echo "--- profile: PLATFORM MISMATCH (rc=4); trace quarantined as benchmarks/profile_r05.mismatch" >> "$LOG"
elif [ "$prof_rc" -ne 0 ]; then
  echo "--- profile: failed rc=$prof_rc" >> "$LOG"
fi
# phase attribution (non-fatal): short phase-scoped traces of the
# all-defaults scan2 baseline plus one variant per static-v1 lever
# axis; the doc carries per-phase device-time fractions, the
# per-lever attribution diffs, and a v15 run_report whose cost
# model_error rows gain measured_phase_frac.  Traces + phase maps
# land under benchmarks/attr_r05/ (gitignored trace payloads); the
# JSON doc is the committed evidence.
echo "--- attr start $(date -u +%FT%TZ)" >> "$LOG"
if python bench.py --attr benchmarks/attr_r05 \
     > benchmarks/ATTR_r05.json.tmp 2>> "$LOG"; then
  mv benchmarks/ATTR_r05.json.tmp benchmarks/ATTR_r05.json
else
  echo "--- attr: failed rc=$?" >> "$LOG"
fi
# sweep late: the tuning matrix is the committed evidence for the
# fast-regime point (take 1's 13 TPU entries lived only in the
# gitignored journal and died with the checkout) and now includes the
# u12/bs2160 cliff-bracketing entries — but take 1 also WEDGED
# mid-sweep, and a wedged phase cannot be timeout-killed (stale tunnel
# grant), so it runs after everything except config 3: a recurrence
# costs only the full-year config whose 30-day slice already landed
run_json benchmarks/SWEEP_r05.jsonl    sweep     --sweep
# config 3 LAST (full-year 10k sites, the longest step)
run_json benchmarks/BENCH_config3.json  config3  --config 3
# perf-trend gate (non-fatal here: the battery's job is to collect
# evidence; rc=1 in the log flags a >10% steady-state regression vs the
# best prior same-platform round for the human doing the round writeup).
# On a checkout where only partial artifacts landed (a wedged battery)
# the gate prints "no prior same-platform round" and exits 0 — a
# partial round must not flag a regression it has no evidence for.
echo "--- bench_trend start $(date -u +%FT%TZ)" >> "$LOG"
python tools/bench_trend.py >> "$LOG" 2>&1 \
  || echo "--- bench_trend: REGRESSION OR ERROR rc=$?" >> "$LOG"
# trace sanity (non-fatal): any flight-recorder dump a wedged phase left
# behind (bench.py rc=3 salvage) must be loadable Chrome-trace JSON —
# an invalid dump is itself evidence of a tracer bug worth the log line
for trace_file in benchmarks/flight_watchdog.json benchmarks/*.trace.json; do
  [ -f "$trace_file" ] || continue
  echo "--- trace_stats $trace_file $(date -u +%FT%TZ)" >> "$LOG"
  python tools/trace_stats.py "$trace_file" >> "$LOG" 2>&1 \
    || echo "--- trace_stats: INVALID TRACE $trace_file rc=$?" >> "$LOG"
done
# fleet-analytics sanity (non-fatal): any bench doc that carried a
# RunReport fleet section must carry a WELL-FORMED one — a section that
# fails the shape check means the analytics fold wrote something
# obs/analytics.summarize never emits, worth the log line even though
# the battery's own runs default to --analytics off
for bench_doc in benchmarks/BENCH_*.json benchmarks/SWEEP_*.jsonl; do
  [ -f "$bench_doc" ] || continue
  echo "--- fleet_report $bench_doc $(date -u +%FT%TZ)" >> "$LOG"
  python tools/fleet_report.py "$bench_doc" >> "$LOG" 2>&1 \
    || echo "--- fleet_report: MALFORMED FLEET SECTION $bench_doc rc=$?" >> "$LOG"
done
# scenario-serving sanity (non-fatal), same contract as fleet_report:
# any doc carrying a RunReport 'serving' section must carry a
# WELL-FORMED one (obs/report.serving_section shape — counters,
# occupancy consistency, latency-quantile ordering; v16 adds the
# 'serving.fleet' router/worker partition the SERVEFLEET doc carries)
for bench_doc in benchmarks/SERVE_*.json benchmarks/SERVEFLEET_*.json \
                 benchmarks/BENCH_*.json; do
  [ -f "$bench_doc" ] || continue
  echo "--- serve_report $bench_doc $(date -u +%FT%TZ)" >> "$LOG"
  python tools/serve_report.py "$bench_doc" >> "$LOG" 2>&1 \
    || echo "--- serve_report: MALFORMED SERVING SECTION $bench_doc rc=$?" >> "$LOG"
done
# resilience sanity (non-fatal), same contract as serve_report: any doc
# carrying a RunReport 'resilience' section (schema v7 — recovery
# outcomes, breaker stats, injected-fault counts) must carry a
# WELL-FORMED one; chaos-free docs just note the absence
for bench_doc in benchmarks/SERVE_*.json benchmarks/BENCH_*.json; do
  [ -f "$bench_doc" ] || continue
  echo "--- resilience_report $bench_doc $(date -u +%FT%TZ)" >> "$LOG"
  python tools/resilience_report.py "$bench_doc" >> "$LOG" 2>&1 \
    || echo "--- resilience_report: MALFORMED RESILIENCE SECTION $bench_doc rc=$?" >> "$LOG"
done
# precision sanity (non-fatal), same contract as the loops above: any
# doc carrying a RunReport 'precision' or 'probe' section (schema v8 —
# the compute_dtype/kernel_impl axes, their sweep pricing, the
# resilience-wrapped backend-probe accounting) must carry a WELL-FORMED
# one; default-precision docs just note the absence.  The headline doc
# is included explicitly: it is where bench.py prices the levers —
# including the v11 rng_batch/geom_stride variants — and a wedged
# take's .partial headline gets the same check (its landed variants
# are the round's only precision evidence).
for bench_doc in benchmarks/HEADLINE_*.json benchmarks/HEADLINE_*.json.partial \
                 benchmarks/REPRO_*.jsonl \
                 benchmarks/SERVE_*.json benchmarks/BENCH_*.json; do
  [ -f "$bench_doc" ] || continue
  echo "--- precision_report $bench_doc $(date -u +%FT%TZ)" >> "$LOG"
  python tools/precision_report.py "$bench_doc" >> "$LOG" 2>&1 \
    || echo "--- precision_report: MALFORMED PRECISION SECTION $bench_doc rc=$?" >> "$LOG"
done
# checkpoint sanity (non-fatal), same contract as the loops above: any
# doc carrying a RunReport 'checkpoint' section (schema v9 — save/restore
# totals, generation rotation, integrity fallbacks, async-writer and
# preemption accounting; the headline doc carries the overhead pricing)
# must carry a WELL-FORMED one; checkpoint-free docs just note the
# absence.  ckpt_report.py also verifies on-disk checkpoints (manifest
# checksums, resumability) when pointed at one.
for bench_doc in benchmarks/HEADLINE_*.json benchmarks/SERVE_*.json \
                 benchmarks/BENCH_*.json; do
  [ -f "$bench_doc" ] || continue
  echo "--- ckpt_report $bench_doc $(date -u +%FT%TZ)" >> "$LOG"
  python tools/ckpt_report.py "$bench_doc" >> "$LOG" 2>&1 \
    || echo "--- ckpt_report: MALFORMED CHECKPOINT SECTION $bench_doc rc=$?" >> "$LOG"
done
# cost sanity (non-fatal), same contract as the loops above: any doc
# carrying a v10 'cost' section (obs/cost.py — static model flops/bytes
# per site-second for the plan's block_impl x compute_dtype x
# kernel_impl cell, achieved GFLOP/s-GB/s, roofline and north-star
# fractions) must carry a WELL-FORMED one; pre-v10 docs just note the
# absence.  The headline doc is where bench.py prices every landed
# variant.
for bench_doc in benchmarks/HEADLINE_*.json benchmarks/SERVE_*.json \
                 benchmarks/BENCH_*.json; do
  [ -f "$bench_doc" ] || continue
  echo "--- cost_report $bench_doc $(date -u +%FT%TZ)" >> "$LOG"
  python tools/cost_report.py "$bench_doc" >> "$LOG" 2>&1 \
    || echo "--- cost_report: MALFORMED COST SECTION $bench_doc rc=$?" >> "$LOG"
done
# mesh sanity (non-fatal), same contract as the loops above: any doc
# carrying a v13 'mesh' section (parallel/distributed.mesh_doc — mesh
# shape/axis names, device product, multi-host process bounds, the
# per-process chain carve) must carry a WELL-FORMED one; unsharded or
# pre-v13 docs just note the absence.  Catches a battery that silently
# ran on the wrong topology (e.g. 1 host where 2 were requested).
for bench_doc in benchmarks/HEADLINE_*.json benchmarks/SERVE_*.json \
                 benchmarks/BENCH_*.json benchmarks/HOSTS_*.json; do
  [ -f "$bench_doc" ] || continue
  echo "--- mesh_report $bench_doc $(date -u +%FT%TZ)" >> "$LOG"
  python tools/mesh_report.py "$bench_doc" >> "$LOG" 2>&1 \
    || echo "--- mesh_report: MALFORMED MESH SECTION $bench_doc rc=$?" >> "$LOG"
done
# pod sanity (non-fatal), same contract: any doc carrying a v14 'pod'
# section (obs/pod.py PodMonitor.doc — per-host heartbeat rows, skew
# stats, straggler totals, comm_frac) must carry a WELL-FORMED one;
# single-process or pre-v14 docs just note the absence.  Catches a
# multi-host battery whose pod plane silently produced garbage.
for bench_doc in benchmarks/HEADLINE_*.json benchmarks/SERVE_*.json \
                 benchmarks/BENCH_*.json benchmarks/HOSTS_*.json; do
  [ -f "$bench_doc" ] || continue
  echo "--- pod_report $bench_doc $(date -u +%FT%TZ)" >> "$LOG"
  python tools/pod_report.py "$bench_doc" >> "$LOG" 2>&1 \
    || echo "--- pod_report: MALFORMED POD SECTION $bench_doc rc=$?" >> "$LOG"
done
# attribution sanity (non-fatal), same contract: any doc carrying a v15
# 'attribution' section (obs/attribution.py attribute — per-phase
# device seconds/fractions from the scoped trace, basis, unattributed
# residual) must carry a WELL-FORMED one, including the --attr doc's
# per-variant sections; pre-v15 or phase_obs-off docs just note the
# absence.  Catches a capture whose trace-to-HLO join silently broke.
for bench_doc in benchmarks/ATTR_*.json benchmarks/HEADLINE_*.json \
                 benchmarks/BENCH_*.json; do
  [ -f "$bench_doc" ] || continue
  echo "--- attr_report $bench_doc $(date -u +%FT%TZ)" >> "$LOG"
  python tools/attr_report.py "$bench_doc" >> "$LOG" 2>&1 \
    || echo "--- attr_report: MALFORMED ATTRIBUTION SECTION $bench_doc rc=$?" >> "$LOG"
done
echo "=== battery-2 done $(date -u +%FT%TZ)" >> "$LOG"
touch benchmarks/BATTERY_DONE
