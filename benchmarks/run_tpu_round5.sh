#!/bin/bash
# Round-5 TPU benchmark battery. Run (once) when the tunnel answers:
#   nohup benchmarks/run_tpu_round5.sh >/dev/null 2>&1 &
# Sequential single processes, no timeouts (see tpu_probe.sh header on
# why), most-important-first so a mid-battery tunnel drop costs the least:
# headline -> sweep -> configs 4,2 -> scaling -> profile -> config 3a
# (quick 30-day slice) -> config 3 (full year; by far the longest, so
# it runs last).
# Config artifacts are only replaced when the new run measured real TPU
# (a cpu-fallback result must never overwrite a TPU artifact).
set -u
cd /root/repo
LOG=benchmarks/tpu_round5.log
echo "=== battery start $(date -u +%FT%TZ)" >> "$LOG"

# Top-level platform check (NOT grep: a cpu-fallback doc can embed a
# previous TPU headline under "last_tpu_headline", whose nested
# '"platform": "tpu"' must not count).
is_tpu_artifact () {
  python - "$1" <<'EOF'
import json, sys
ok = False
for ln in open(sys.argv[1]):
    ln = ln.strip()
    if not ln:
        continue
    try:
        doc = json.loads(ln)
    except json.JSONDecodeError:
        continue
    if doc.get("platform") == "tpu":
        ok = True
sys.exit(0 if ok else 1)
EOF
}

run_json () {  # run_json <dest.json> <label> <args...>
  local dest="$1" label="$2"; shift 2
  echo "--- $label start $(date -u +%FT%TZ)" >> "$LOG"
  python bench.py "$@" > "$dest.tmp" 2>> "$LOG"
  local rc=$?
  echo "--- $label rc=$rc $(date -u +%FT%TZ)" >> "$LOG"
  if [ $rc -eq 0 ] && is_tpu_artifact "$dest.tmp"; then
    mv "$dest.tmp" "$dest"
    echo "--- $label: TPU artifact written to $dest" >> "$LOG"
  else
    mv "$dest.tmp" "$dest.nontpu" 2>/dev/null
    echo "--- $label: NOT a TPU result; kept as $dest.nontpu" >> "$LOG"
  fi
}

run_json benchmarks/HEADLINE_r05.json  headline
run_json benchmarks/SWEEP_r05.jsonl    sweep     --sweep
run_json benchmarks/BENCH_config4.json config4   --config 4
run_json benchmarks/BENCH_config2.json config2   --config 2
# --scaling is the virtual-CPU-mesh mechanics artifact (CPU by design,
# no TPU gate): regenerate it alongside the TPU numbers per the round-4
# verdict, replacing only on success.
echo "--- scaling start $(date -u +%FT%TZ)" >> "$LOG"
if python bench.py --scaling > benchmarks/SCALING.json.tmp 2>> "$LOG"; then
  mv benchmarks/SCALING.json.tmp benchmarks/SCALING.json
fi
echo "--- profile start $(date -u +%FT%TZ)" >> "$LOG"
python bench.py --profile benchmarks/profile_r05 >> "$LOG" 2>&1
# config 3 LAST: its full-year 10k-site run is by far the longest step
# (hours at realistic rates); everything shorter must land first.  The
# quick 30-day slice (own artifact, own invocation) lands before the
# full-year attempt, so even a mid-run drop leaves a TPU number for
# the 10k-site shape; BENCH_config3.json is only ever replaced by a
# genuine full-year TPU doc.
run_json benchmarks/BENCH_config3a.json config3a --config 3a
run_json benchmarks/BENCH_config3.json  config3  --config 3
echo "=== battery done $(date -u +%FT%TZ)" >> "$LOG"
touch benchmarks/BATTERY_DONE
