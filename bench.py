"""Benchmark: simulated site-seconds per wall second per chip.

Runs the JAX-backend block loop (per-second stochastic csi scan + PV
physics + meter stream, device-side reduction) for a large chain batch on
whatever accelerator is available, and prints ONE JSON line:

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Baseline: the reference caps at ~100 simulated seconds/sec/process under
``--no-realtime`` (the 10 ms sleep floor in fixedclock, utils.py:36;
SURVEY.md §6) — vs_baseline is the speedup over that ceiling per chip.
"""

from __future__ import annotations

import json
import time

import jax

from tmhpvsim_tpu.config import SimConfig
from tmhpvsim_tpu.engine import Simulation

# Sized so one block's trace (chains x block_s) fits comfortably in HBM:
# 8192 chains x 8640 s x 4 B x ~4 live arrays ~= 1.1 GB.
N_CHAINS = 8192
BLOCK_S = 8640
N_BLOCKS = 5  # timed steady-state blocks


def main() -> None:
    cfg = SimConfig(
        start="2019-09-05 00:00:00",
        duration_s=BLOCK_S * (N_BLOCKS + 1),
        n_chains=N_CHAINS,
        seed=0,
        block_s=BLOCK_S,
        dtype="float32",
    )
    sim = Simulation(cfg)
    state = sim.init_state()
    sim.state = state

    # Warm-up block: triggers compilation of init + block step.
    inputs, _ = sim.host_inputs(0)
    sim.state, stats = sim._block_reduced_jit(sim.state, inputs)
    jax.block_until_ready(stats)

    t0 = time.perf_counter()
    for bi in range(1, N_BLOCKS + 1):
        inputs, _ = sim.host_inputs(bi)
        sim.state, stats = sim._block_reduced_jit(sim.state, inputs)
    jax.block_until_ready(stats)
    dt = time.perf_counter() - t0

    site_seconds = N_CHAINS * BLOCK_S * N_BLOCKS
    rate = site_seconds / dt
    ref_ceiling = 100.0  # simulated s/s/process, reference --no-realtime
    print(json.dumps({
        "metric": "simulated site-seconds/sec/chip",
        "value": round(rate, 1),
        "unit": "site-s/s/chip",
        "vs_baseline": round(rate / ref_ceiling, 1),
    }))


if __name__ == "__main__":
    main()
