"""Benchmark: simulated site-seconds per wall second per chip.

Runs the JAX-backend block loop (per-second stochastic csi scan + PV
physics + meter stream, device-side reduction) for a large chain batch on
whatever accelerator is available, and prints ONE JSON line:

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Baseline: the reference caps at ~100 simulated seconds/sec/process under
``--no-realtime`` (the 10 ms sleep floor in fixedclock, utils.py:36;
SURVEY.md §6) — vs_baseline is the speedup over that ceiling per chip.

Resilience: the environment pins ``JAX_PLATFORMS`` to a remote TPU tunnel
whose backend init can *hang* (not just error) — round 1 lost its only
measurement to exactly that.  Backend init happens deep inside process
state, so the only safe probe is a separate process: we spawn a child that
must complete one matmul within a deadline.  If it can't (twice), we flip
this process to the CPU backend (backends initialise lazily, so the config
update still takes effect — same mechanism as tests/conftest.py) and run a
scaled-down benchmark so a number is ALWAYS produced.  The JSON line then
carries ``"platform": "cpu-fallback"`` so nobody mistakes it for a TPU
measurement.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# Shape chosen by measurement (round 3): throughput saturates with total
# per-block work, and XLA materialises ~20 (block_s, chains) f32 temps, so
# more chains with proportionally smaller blocks beats the reverse; 65536
# x 1080 was the best point tried that stays well inside HBM.
N_CHAINS = 65536
BLOCK_S = 1080
N_BLOCKS = 5   # timed steady-state blocks per round
N_ROUNDS = 3   # best-of rounds: the remote-TPU tunnel's throughput varies
               # ~2x run to run, so a single timing is not trustworthy

# CPU fallback: same shape of work, sized to finish in seconds, clearly
# labelled — it exists so the harness records *something* diagnosable
# rather than rc=1/rc=124 (the round-1 failure mode).
CPU_N_CHAINS = 256
CPU_N_BLOCKS = 2

_PROBE_SRC = (
    "import jax, jax.numpy as jnp;"
    "x = jnp.ones((128, 128));"
    "(x @ x).block_until_ready();"
    "print(jax.devices()[0].platform)"
)


def _probe_backend(timeout_s: float) -> str | None:
    """Return the platform name if the pinned backend works, else None.

    Runs in a child process so a hanging backend init costs a bounded
    timeout instead of the whole benchmark.
    """
    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        print(f"# backend probe timed out after {timeout_s:.0f}s",
              file=sys.stderr)
        return None
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()[-3:]
        print("# backend probe failed:", *tail, sep="\n# ", file=sys.stderr)
        return None
    return (r.stdout or "").strip().splitlines()[-1] or None


def main() -> None:
    platform = None
    for attempt, deadline in enumerate((180.0, 90.0), 1):
        platform = _probe_backend(deadline)
        if platform:
            break
        print(f"# probe attempt {attempt} failed", file=sys.stderr)

    fallback = platform is None
    if fallback:
        os.environ["JAX_PLATFORMS"] = "cpu"
        # 8 virtual devices so the sharded entry still exercises (and
        # times) the real shard_map mechanics, like tests/conftest.py
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    import jax

    if fallback:
        # sitecustomize may have imported jax already; backends are lazy,
        # so redirecting the config here still works (tests/conftest.py).
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", 8)
        except Exception:
            pass  # already created; the XLA_FLAGS path may still hold
        platform = "cpu-fallback"
        n_chains, n_blocks = CPU_N_CHAINS, CPU_N_BLOCKS
    else:
        n_chains, n_blocks = N_CHAINS, N_BLOCKS

    from tmhpvsim_tpu.config import SimConfig
    from tmhpvsim_tpu.engine import Simulation
    from tmhpvsim_tpu.parallel import ShardedSimulation, make_mesh
    from tmhpvsim_tpu.parallel.distributed import initialize_from_env

    try:
        initialize_from_env()
    except Exception as e:  # single-process bench must not die on this
        print(f"# jax.distributed init skipped: {e}", file=sys.stderr)

    n_rounds = N_ROUNDS if not fallback else 1

    def make_cfg(n):
        return SimConfig(
            start="2019-09-05 00:00:00",
            duration_s=BLOCK_S * (n_blocks * n_rounds + 1),
            n_chains=n,
            seed=0,
            block_s=BLOCK_S,
            dtype="float32",
        )

    def timed_reduce_run(sim):
        """(compile_s, best_steady_s, best_rate): one warm-up block, then
        n_rounds x n_blocks timed reduce-mode blocks through the public
        step_acc path, best round kept (the tunnel TPU's throughput varies
        ~2x between otherwise identical runs)."""
        sim.state = sim.init_state()
        acc = sim.init_reduce_acc()
        t_c = time.perf_counter()
        inputs, _ = sim.host_inputs(0)
        sim.state, acc = sim.step_acc(sim.state, inputs, acc)
        jax.block_until_ready(acc)
        compile_s = time.perf_counter() - t_c

        best = float("inf")
        bi = 1
        for _ in range(n_rounds):
            t0 = time.perf_counter()
            for _ in range(n_blocks):
                inputs, _ = sim.host_inputs(bi)
                bi += 1
                sim.state, acc = sim.step_acc(sim.state, inputs, acc)
            jax.block_until_ready(acc)
            best = min(best, time.perf_counter() - t0)
        n = sim.config.n_chains
        return compile_s, best, n * BLOCK_S * n_blocks / best

    sim = Simulation(make_cfg(n_chains))
    compile_s, dt, rate = timed_reduce_run(sim)
    print(f"# warm-up (compile) {compile_s:.1f}s on "
          f"{jax.devices()[0].platform}", file=sys.stderr)

    # Sharded path over all local devices: on the single real TPU chip this
    # is a 1-device mesh (validates the shard_map machinery at full size);
    # scaling efficiency needs a real multi-chip slice (BASELINE.md).
    devices = jax.local_devices()
    n_dev = len(devices)
    sh_chains = max(n_dev, (n_chains // n_dev) * n_dev)
    try:
        ssim = ShardedSimulation(make_cfg(sh_chains), mesh=make_mesh(devices))
        sh_compile_s, sh_dt, sh_rate = timed_reduce_run(ssim)
        sharded = {
            "n_devices": n_dev,
            "n_chains": sh_chains,
            "rate_per_chip": round(sh_rate / n_dev, 1),
            "compile_s": round(sh_compile_s, 1),
            "best_round_wall_s": round(sh_dt, 2),
        }
    except Exception as e:  # sharded failure must not lose the main number
        print(f"# sharded bench failed: {e}", file=sys.stderr)
        sharded = {"error": str(e)[:200]}

    ref_ceiling = 100.0  # simulated s/s/process, reference --no-realtime
    # north star (BASELINE.json): 100k site-years < 60 s on v5e-8
    # = 100_000 * 365.25 * 86400 / 60 / 8 site-s/s/chip
    north_star = 100_000 * 365.25 * 86400 / 60.0 / 8.0
    print(json.dumps({
        "metric": "simulated site-seconds/sec/chip",
        "value": round(rate, 1),
        "unit": "site-s/s/chip",
        "vs_baseline": round(rate / ref_ceiling, 1),
        "north_star_frac": round(rate / north_star, 3),
        "platform": platform,
        "tpu": platform == "tpu",
        "n_chains": n_chains,
        "block_s": BLOCK_S,
        "timed_blocks": n_blocks,
        "timed_rounds": n_rounds,
        "compile_s": round(compile_s, 1),
        "best_round_wall_s": round(dt, 2),
        "sharded": sharded,
    }))


if __name__ == "__main__":
    main()
