"""Benchmark harness: headline number + the five BASELINE configs.

Default (no args) — the driver-run headline: simulated site-seconds per
wall second per chip for the reduce-mode block loop (per-second stochastic
csi scan + PV physics + meter stream, on-device statistics), printed as ONE
JSON line:

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Baseline: the reference caps at ~100 simulated seconds/sec/process under
``--no-realtime`` (the 10 ms sleep floor in fixedclock, utils.py:36;
SURVEY.md §6) — vs_baseline is the speedup over that ceiling per chip.
The headline config is the fastest documented mode: scan-fused block
(SimConfig.block_impl='scan') with the default threefry PRNG at
scan_unroll=8 (the hardware PRNG 'rbg' serializes ~76x inside the scan
on the current TPU backend — PERF_ANALYSIS.md §7a — and is demoted to a
1-block probe); scan2 and wide variants are measured alongside it.

Roofline fields: analytic+compiled accounting of the hot jit — flops and
HBM bytes from XLA's own cost model (``compiled.cost_analysis()``), wall
time from the steady-state measurement, reported as achieved GFLOP/s,
GB/s, and fractions of the chip's peak VPU / HBM rates (see _PEAKS for
the provenance of the peak numbers).

Subcommands (artifact producers, run during the build, committed under
benchmarks/):

    bench.py --config N    a BASELINE.md config (1-5; 3a = 30-day slice
                           of 3); on TPU, 4 and 5 run their full chain
                           counts as sequential <=65536-chain slabs
    bench.py --scaling     1->8 device scaling on the virtual CPU mesh
    bench.py --sweep       impl/PRNG/unroll/shape tuning matrix
    bench.py --repro K     K fresh-process compiles of the headline
                           variant (compile-variance probe)
    bench.py --profile DIR jax.profiler trace of steady headline blocks

Resilience: the environment pins ``JAX_PLATFORMS`` to a remote TPU tunnel
whose backend init can *hang* (not just error).  Backend init happens deep
inside process state, so the only safe probe is a separate process: we
spawn a child that must complete one matmul within a deadline.  If it
can't (twice), we flip this process to the CPU backend (backends
initialise lazily, so the config update still takes effect — same
mechanism as tests/conftest.py) and run a scaled-down benchmark so a
number is ALWAYS produced, labelled ``"platform": "cpu-fallback"``.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

# Headline shape (chosen by measurement, rounds 3-4): with the scan-fused
# block the throughput saturates with total per-block work; 65536 x 1080
# stays well inside HBM while amortising dispatch.
N_CHAINS = 65536
BLOCK_S = 1080
N_BLOCKS = 5   # timed steady-state blocks per round
N_ROUNDS = 3   # best-of rounds: the remote-TPU tunnel's throughput varies
               # ~2x run to run, so a single timing is not trustworthy

# CPU fallback: same shape of work, sized to finish in seconds, clearly
# labelled — it exists so the harness records *something* diagnosable
# rather than rc=1/rc=124 (the round-1 failure mode).
CPU_N_CHAINS = 256
CPU_N_BLOCKS = 2

#: Peak rates used for the roofline fractions, per chip — the single
#: definition (provenance included) lives in obs/cost.py now so the
#: live device.cost.* gauges, report validation and bench price against
#: the same numbers.
from tmhpvsim_tpu.obs.cost import NORTH_STAR  # noqa: E402
from tmhpvsim_tpu.obs.cost import PEAKS as _PEAKS  # noqa: E402

# The probe child routes its matmul compile through the persistent
# compilation cache (engine/compilecache.py): the first probe against a
# device kind compiles once and persists, every later probe — including
# the next battery round's — deserialises in milliseconds.  BENCH_r04/r05
# lost whole rounds to probes that burned their budget recompiling
# against a slow tunnel; with the cache the budget is spent only on the
# genuinely wedged case.  Best-effort: a missing package on the child's
# path must not fail the probe itself.
_PROBE_SRC = (
    "import jax;"
    "\ntry:\n"
    "    from tmhpvsim_tpu.engine import compilecache;"
    " compilecache.configure()\n"
    "except Exception as e:\n"
    "    import sys; print(f'# probe cache off: {e}', file=sys.stderr)\n"
    "import jax.numpy as jnp;"
    "x = jnp.ones((128, 128));"
    "jax.jit(lambda a: a @ a)(x).block_until_ready();"
    "print(jax.devices()[0].platform)"
)


def _probe_backend(timeout_s: float) -> str | None:
    """Return the platform name if the pinned backend works, else None.

    Runs in a child process so a hanging backend init costs a bounded
    timeout instead of the whole benchmark.
    """
    here = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["PYTHONPATH"] = (here + os.pathsep + env["PYTHONPATH"]
                         if env.get("PYTHONPATH") else here)
    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True, text=True, timeout=timeout_s,
            env=env, cwd=here,
        )
    except subprocess.TimeoutExpired:
        print(f"# backend probe timed out after {timeout_s:.0f}s",
              file=sys.stderr)
        return None
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()[-3:]
        print("# backend probe failed:", *tail, sep="\n# ", file=sys.stderr)
        return None
    return (r.stdout or "").strip().splitlines()[-1] or None


def _force_cpu(n_devices: int = 8):
    """Redirect this process to the CPU backend with virtual devices."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    import jax

    # sitecustomize may have imported jax already; backends are lazy, so
    # redirecting the config here still works (tests/conftest.py).
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
    except Exception:
        pass  # already created; the XLA_FLAGS path may still hold


#: backend-probe accounting, surfaced in the schema-v8 ``probe`` report
#: section of every bench artifact (obs/report.py): how many probe
#: attempts this process made and how many returned nothing (timeout or
#: child failure) before the platform was settled
_PROBE_STATS = {"probe_attempts": 0, "probe_timeouts": 0}

#: per-attempt bound handed to the probe child's subprocess timeout; the
#: policy's total budget caps the whole retry loop including backoff
_PROBE_ATTEMPT_TIMEOUT_S = 150.0
_PROBE_TOTAL_TIMEOUT_S = 240.0

#: warmed budget: when the persistent compile cache already holds
#: entries for SOME device kind, a healthy probe answers in seconds
#: (deserialise, not compile) — so a longer budget costs nothing on the
#: healthy path and buys the slow-but-alive tunnel more headroom before
#: we give up on it (the BENCH_r04/r05 failure was giving up too early,
#: then silently publishing CPU numbers)
_PROBE_WARM_ATTEMPT_TIMEOUT_S = 240.0
_PROBE_WARM_TOTAL_TIMEOUT_S = 420.0

#: --assume-tpu (or TMHPVSIM_ASSUME_TPU=1): a failed probe degrades to a
#: REAL watchdogged TPU attempt instead of the silent cpu-fallback —
#: headline() already bounds a wedged backend with its monitor thread
#: (rc=3 partial on hang), so assuming costs a bounded timeout, while a
#: wrong cpu-fallback costs the round's TPU numbers
ASSUME_TPU = False


def _compile_cache_warm() -> bool:
    """True when the persistent compile cache base dir already holds
    entries for any device kind (engine/compilecache.py layout: one
    subdir per device-kind slug)."""
    try:
        from tmhpvsim_tpu.engine import compilecache

        base = os.environ.get(compilecache.ENV_VAR) or \
            compilecache.default_dir()
        if str(base).strip().lower() in compilecache.OFF_VALUES:
            return False
        for sub in os.listdir(base):
            d = os.path.join(base, sub)
            if os.path.isdir(d) and os.listdir(d):
                return True
    except OSError:
        pass
    except Exception as e:  # import trouble must not fail the probe
        print(f"# compile-cache warm check failed: {e}", file=sys.stderr)
    return False


def _probe_doc() -> dict | None:
    """The ``probe`` report section, or None when no probe ran (so
    artifacts from probe-free paths stay byte-stable)."""
    if not _PROBE_STATS["probe_attempts"]:
        return None
    return dict(_PROBE_STATS)


def _probe_or_fallback() -> tuple[str, bool]:
    """(platform, fallback?) — probe the pinned backend, else force CPU
    (or, under ``--assume-tpu``, return "tpu" so the caller makes a real
    watchdogged attempt).

    The probe runs under ``runtime.resilience.ResiliencePolicy``
    (replacing the old ad-hoc two-timeout loop): two bounded attempts
    with jittered backoff inside a total budget, each attempt bounded by
    the probe child's own subprocess timeout (the policy's asyncio
    wait_for cannot pre-empt a blocking subprocess, so the bound lives
    where it works).  A no-platform attempt raises TimeoutError so the
    policy's retry/giveup machinery — and its ``retry.*`` counters —
    drive the loop; attempts/timeouts are also journalled into
    ``_PROBE_STATS`` for the v8 ``probe`` report section.  The budget is
    the lengthened warmed pair when the persistent compile cache already
    holds entries (the probe child deserialises instead of compiling)."""
    import asyncio

    from tmhpvsim_tpu.runtime.resilience import ResiliencePolicy

    warm = _compile_cache_warm()
    attempt_s = (_PROBE_WARM_ATTEMPT_TIMEOUT_S if warm
                 else _PROBE_ATTEMPT_TIMEOUT_S)
    total_s = (_PROBE_WARM_TOTAL_TIMEOUT_S if warm
               else _PROBE_TOTAL_TIMEOUT_S)
    _PROBE_STATS["cache_warm"] = warm
    _PROBE_STATS["attempt_timeout_s"] = attempt_s
    _PROBE_STATS["total_timeout_s"] = total_s

    async def attempt():
        _PROBE_STATS["probe_attempts"] += 1
        platform = _probe_backend(attempt_s)
        if platform is None:
            _PROBE_STATS["probe_timeouts"] += 1
            raise TimeoutError("backend probe returned no platform")
        return platform

    policy = ResiliencePolicy(
        attempts=2, base_delay_s=2.0, max_delay_s=10.0,
        total_timeout_s=total_s,
        name="bench.backend_probe", fallback=None)
    platform = asyncio.run(policy.call(attempt))
    if platform is None:
        if ASSUME_TPU:
            _PROBE_STATS["assumed_tpu"] = True
            print("# backend probe failed; --assume-tpu: making a real "
                  "watchdogged TPU attempt instead of cpu-fallback",
                  file=sys.stderr)
            return "tpu", False
        _force_cpu()
        return "cpu-fallback", True
    return platform, False


#: process-wide telemetry level for every config _make_cfg builds
#: (--telemetry; obs/telemetry.py).  Default off: the headline numbers
#: stay the untouched hot path.
TELEMETRY = "off"

#: process-wide fleet-analytics level, same contract as TELEMETRY
#: (--analytics; obs/analytics.py).
ANALYTICS = "off"

#: process-wide phase-scope level, same contract as TELEMETRY
#: (--phase-obs; obs/profiler.py phase_scope).  Default off lowers to
#: byte-identical HLO; --attr mode turns it on per-capture regardless.
PHASE_OBS = "off"


def _make_cfg(n_chains: int, n_blocks_total: int, block_s: int = BLOCK_S,
              **kw):
    from tmhpvsim_tpu.config import SimConfig

    base = dict(
        start="2019-09-05 00:00:00",
        duration_s=block_s * n_blocks_total,
        n_chains=n_chains,
        seed=0,
        block_s=block_s,
        dtype="float32",
        # threefry, NOT rbg: on the current tunnel backend rbg's vmapped
        # per-chain draws serialize (~8 s/block vs 3.5 ms — measured
        # round 5, see VARIANT_CFGS); every config/sharded/profile run
        # built from this default inherits the safe mode
        prng_impl="threefry2x32",
        block_impl="auto",      # scan-fused on accelerators
        telemetry=TELEMETRY,
        analytics=ANALYTICS,
        phase_obs=PHASE_OBS,
    )
    base.update(kw)
    return SimConfig(**base)


def _timed_reduce_run(sim, n_blocks: int, n_rounds: int, profile_dir=None,
                      expect_platform=None):
    """(compile_s, best_steady_s, rate): one warm-up block, then n_rounds x
    n_blocks timed reduce-mode blocks through the public step_acc path,
    best round kept (the tunnel TPU's throughput varies ~2x between
    otherwise identical runs).

    The timing loop itself lives in engine/autotune.py — the variant
    sweep and ``tune='auto'`` plan probes share one measurement path,
    so a bench rate and a probe rate are directly comparable.
    ``expect_platform`` arms the device-trace platform guard when
    ``profile_dir`` is set (obs/profiler.py)."""
    from tmhpvsim_tpu.engine.autotune import time_reduce_blocks

    return time_reduce_blocks(sim, n_blocks, n_rounds=n_rounds,
                              profile_dir=profile_dir,
                              expect_platform=expect_platform)


def _bench_timing(compile_s, steady_wall_s, n_timed_blocks, rate) -> dict:
    """A RunReport timing section from the bench measurement protocol
    (one compile-inclusive warm-up block, ``n_timed_blocks`` timed
    steady blocks of total wall ``steady_wall_s``)."""
    return {
        "compile_s": compile_s,
        "first_block_s": compile_s,
        "steady_block_s": (steady_wall_s / n_timed_blocks
                           if n_timed_blocks else None),
        "n_blocks_timed": int(n_timed_blocks) + 1,
        "site_seconds_per_s": rate,
        "rate_includes_compile": False,
    }


def _bench_report(app: str, *, config=None, plan=None, timing=None,
                  headline=None, profile=None, slabs=None,
                  device=None, executor=None,
                  precision=None, checkpoint=None,
                  cost=None, pod=None, attribution=None) -> dict | None:
    """A validated obs RunReport document, embedded ADDITIVELY in a bench
    artifact as ``doc["run_report"]`` (the legacy ad-hoc fields stay —
    battery scripts key richness decisions off them).  Never raises: a
    report failure must not cost the benchmark number it describes.

    ``executor`` defaults to the process's warm/cold compile + dispatch
    counters (schema v4 ``executor`` section, engine/compilecache.py) —
    process-cumulative at report time, so every mode's artifact shows
    how much of its compile cost the persistent cache absorbed."""
    from tmhpvsim_tpu.obs.report import RunReport

    try:
        if executor is None:
            from tmhpvsim_tpu.engine import compilecache

            executor = compilecache.executor_doc()
        rep = RunReport(app, config=config, plan=plan)
        rep.timing = timing
        rep.headline = headline
        rep.profile = profile
        rep.slabs = slabs
        rep.device = device
        rep.executor = executor
        rep.precision = precision
        rep.checkpoint = checkpoint
        rep.cost = cost  # v10 cost-attribution section (obs/cost.py)
        rep.pod = pod  # v14 pod-observability section (obs/pod.py)
        # v15 phase-attribution section (obs/attribution.py)
        rep.attribution = attribution
        # every bench artifact records how the backend probe went — the
        # v8 ``probe`` section; None when this path never probed
        rep.probe = _probe_doc()
        return rep.doc()
    except Exception as e:
        print(f"# run_report build failed ({app}): {e}", file=sys.stderr)
        return None


def _config_cost(plan, rate, device_kind,
                 phase_fractions=None) -> dict | None:
    """Static-model cost doc (obs/cost.py) for a config artifact's
    resolved plan × measured per-chip rate.  Never raises.
    ``phase_fractions`` — measured per-phase device-time shares from a
    scoped trace (obs/attribution.py), threaded into the v15
    ``model_error`` phase checks when the basis is measured."""
    try:
        from tmhpvsim_tpu.obs import cost as obs_cost

        p = plan if isinstance(plan, dict) else (_plan_doc(plan) or {})
        return obs_cost.cost_doc(
            site_s_per_s=rate, block_impl=p.get("block_impl"),
            compute_dtype=p.get("compute_dtype"),
            kernel_impl=p.get("kernel_impl"),
            rng_batch=p.get("rng_batch"),
            geom_stride=p.get("geom_stride"), device_kind=device_kind,
            phase_fractions=phase_fractions)
    except Exception as e:
        print(f"# cost doc failed: {e}", file=sys.stderr)
        return None


def _checkpoint_overhead_doc(n_chains: int, n_blocks: int = 4) -> dict:
    """Price checkpointing against the steady block wall: the same
    reduce run three times — no checkpoint, synchronous per-block save,
    async writer (engine/checkpoint.py AsyncCheckpointWriter) — timing
    only the post-compile blocks.  ``overhead_frac`` is each mode's
    steady-block slowdown vs the checkpoint-off baseline; the async
    number is the ISSUE-10 acceptance lever (≤ 2 % at 65536 chains,
    tested at scale in tests/test_checkpoint.py slow marks)."""
    import shutil
    import tempfile

    from tmhpvsim_tpu.engine import Simulation
    from tmhpvsim_tpu.engine import checkpoint as ckpt

    tmpdir = tempfile.mkdtemp(prefix="bench_ckpt_")
    out = {"n_chains": n_chains, "timed_blocks": n_blocks}
    try:
        for mode in ("off", "sync", "async"):
            cfg = _make_cfg(n_chains, n_blocks + 1)
            sim = Simulation(cfg)
            path = os.path.join(tmpdir, f"ck_{mode}.npz")
            writer = (ckpt.AsyncCheckpointWriter(path, config=cfg)
                      if mode == "async" else None)
            ticks: list = []

            def on_block(bi, state, acc, _sim=sim, _cfg=cfg,
                         _writer=writer, _path=path, _mode=mode,
                         _ticks=ticks):
                if _mode != "off" and _sim.state_block == bi + 1:
                    tree = _sim.host_local_tree(
                        {"state": state, "acc": acc})
                    if _writer is not None:
                        _writer.submit(tree, bi + 1)
                    else:
                        ckpt.save(_path, tree, bi + 1, _cfg)
                _ticks.append(time.monotonic())

            sim.run_reduced(on_block=on_block)
            if writer is not None:
                writer.close()
            del sim
            # ticks[0] lands after the compile-inclusive first block;
            # the remaining intervals are the steady blocks (with their
            # per-block save, in the checkpointed modes)
            steady = ((ticks[-1] - ticks[0]) / (len(ticks) - 1)
                      if len(ticks) > 1 else None)
            out[mode] = {"steady_block_s": steady}
        base = out["off"]["steady_block_s"]
        if base:
            for mode in ("sync", "async"):
                s = out[mode]["steady_block_s"]
                if s is not None:
                    out[mode]["overhead_frac"] = round(s / base - 1.0, 4)
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)
    return out


def _hot_jit_cost(sim) -> dict:
    """XLA's own cost model for the hot per-block jit: flops + HBM bytes.

    ``cost_analysis`` sums operand/result bytes per *fused* instruction,
    so it is an upper bound on true HBM traffic; flops are exact for the
    arithmetic it models (transcendentals counted approximately)."""
    import jax

    try:
        sim.state = sim.init_state()
        acc = sim.init_reduce_acc()
        inputs, _ = sim.host_inputs(0)
        if getattr(sim, "_impl", None) == "scan2":
            jf, args = sim._scan2_acc_jit, (sim.state, inputs, acc)
        elif getattr(sim, "_use_scan", False):
            jf, args = sim._scan_acc_jit, (sim.state, inputs, acc)
        elif getattr(sim, "_use_fused", False):
            jf, args = sim._fused_acc_jit, (sim.state, inputs, acc)
        else:
            jf, args = sim._block_jit, (sim.state, inputs)
        ca = jf.lower(*args).compile().cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        return {
            "flops_per_block": float(ca.get("flops", float("nan"))),
            "bytes_per_block": float(
                ca.get("bytes accessed", float("nan"))
            ),
        }
    except Exception as e:  # cost model availability varies per backend
        print(f"# cost_analysis unavailable: {e}", file=sys.stderr)
        return {}


def _roofline(cost: dict, block_wall_s: float, n_chains: int,
              block_s: int, device_kind: str) -> dict:
    """Achieved rates + fractions of the chip's peak VPU/HBM rates."""
    out = dict(cost)
    site_s = n_chains * block_s
    if "flops_per_block" in cost and block_wall_s > 0:
        out["flops_per_site_second"] = round(
            cost["flops_per_block"] / site_s, 1
        )
        out["bytes_per_site_second"] = round(
            cost["bytes_per_block"] / site_s, 1
        )
        out["achieved_gflops"] = round(
            cost["flops_per_block"] / block_wall_s / 1e9, 1
        )
        out["achieved_gbs"] = round(
            cost["bytes_per_block"] / block_wall_s / 1e9, 1
        )
        peaks = _PEAKS.get(device_kind)
        if peaks:
            out["pct_peak_vpu"] = round(
                100.0 * out["achieved_gflops"] / peaks["vpu_f32_gops"], 1
            )
            out["pct_peak_hbm"] = round(
                100.0 * out["achieved_gbs"] / peaks["hbm_gbs"], 1
            )
            out["peaks"] = peaks
    return out



def _impl_label(sim) -> str:
    """The block topology a Simulation will actually run (resolved from
    'auto') — echoed into every artifact so labels never lie."""
    if sim._impl in ("scan", "scan2"):
        return sim._impl
    return "fused" if sim._use_fused else "split"

# NORTH_STAR (site-s/s/chip) is imported from obs/cost.py above
REF_CEILING = 100.0  # simulated s/s/process, reference --no-realtime


#: the headline's variant matrix: the headline is the best documented
#: mode; the others are reported so the artifact shows WHY it won.
#: Headline variant matrix.  Order and composition are load-bearing
#: (learned on hardware in round 5): (1) threefry variants run FIRST and
#: rbg LAST — rbg's vmapped per-chain draws serialize on the current
#: tunnel backend (~8 s/block vs 3.5 ms, a ~2300x pathology) and any sim
#: left resident in HBM degrades every later timed run in the process
#: (scan-threefry measured 105 ms/block after two rbg sims vs 3.5 ms in
#: a fresh process; the sharded tail with four sims resident measured
#: 8 s/block on default threefry); (2) _run_variants therefore frees
#: every non-winning sim as soon as it is measured; (3) rbg is kept as
#: ONE short probe (_probe: 1 block x 1 round) to keep documenting the
#: pathology without burning minutes on it.
VARIANT_CFGS = {
    "scan-threefry": dict(prng_impl="threefry2x32", block_impl="auto"),
    "scan2-threefry": dict(prng_impl="threefry2x32", block_impl="scan2"),
    "wide-threefry": dict(prng_impl="threefry2x32", block_impl="wide",
                          stats_fusion="fused"),
    # precision levers priced on the scan2 path (threefry ONLY — the rbg
    # pathology above must never contaminate a precision comparison):
    # bf16 compute, tabulated solar/pv kernels, and both together.  bf16
    # auto-escalates telemetry to 'light' (engine/autotune.py), so these
    # rates already pay the sentinel's cost — the honest number.
    "scan2-bf16": dict(prng_impl="threefry2x32", block_impl="scan2",
                       compute_dtype="bf16"),
    "scan2-table": dict(prng_impl="threefry2x32", block_impl="scan2",
                        kernel_impl="table"),
    "scan2-bf16-table": dict(prng_impl="threefry2x32", block_impl="scan2",
                             compute_dtype="bf16", kernel_impl="table"),
    # scan-restructuring levers, also priced on the scan2 path.
    # rng_batch='block' hoists every per-minute noise draw into whole-
    # block counter-mode tensors before the scan — bit-identical by
    # construction (same fold_in keying, asserted in tests), so no
    # sentinel is owed.  geom_stride=60 is an approximation lever
    # (strided geometry + lerp, models/solar.py:STRIDE_MAX_ABS_ERR):
    # like bf16 it must never run unwatched, so its variants carry
    # telemetry='light' and the published rates pay the drift sentinel's
    # cost — the honest number.
    "scan2-rngblock": dict(prng_impl="threefry2x32", block_impl="scan2",
                           rng_batch="block"),
    "scan2-stride60": dict(prng_impl="threefry2x32", block_impl="scan2",
                           geom_stride=60, telemetry="light"),
    "scan2-rngblock-stride60": dict(
        prng_impl="threefry2x32", block_impl="scan2",
        rng_batch="block", geom_stride=60, telemetry="light"),
    # the full stack: both scan-restructuring levers on top of the PR-9
    # precision levers — the best-case composite rate
    "scan2-rngblock-stride60-bf16-table": dict(
        prng_impl="threefry2x32", block_impl="scan2",
        rng_batch="block", geom_stride=60,
        compute_dtype="bf16", kernel_impl="table", telemetry="light"),
    "scan-rbg": dict(prng_impl="rbg", block_impl="auto", _probe=True),
}

#: no-progress deadline for the TPU variants phase: the watchdog fires
#: only when NO variant attempt has finished (landed or errored) for this
#: long — i.e. the tunnel's HANGING mode.  Slow-but-erroring progress
#: (the other observed mode) keeps resetting the clock so the chain-count
#: step-down retries get their chance.  On firing it emits a headline
#: from whatever variants already landed, else salvages a CPU number.
TPU_VARIANTS_DEADLINE_S = 900.0

#: absolute cap on the whole TPU headline phase, hangs and retries
#: included — past it the watchdog fires regardless of progress
TPU_HEADLINE_TOTAL_S = 3600.0

#: every measured variant/config is appended here the moment it lands, so
#: a tunnel drop (or SIGKILL) mid-run still leaves TPU numbers on disk —
#: the round-4 outage zeroed a round for want of exactly this
PARTIAL_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "benchmarks", "bench_partial.jsonl")


def _persist_partial(record: dict) -> None:
    """Append one result record to the partial-results journal (flushed
    + fsynced: the record must survive the process dying next instant)."""
    try:
        rec = dict(record, ts=time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                            time.gmtime()))
        with open(PARTIAL_PATH, "a") as f:
            f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())
    except OSError as e:
        print(f"# partial-result persist failed: {e}", file=sys.stderr)


def _last_tpu_evidence() -> dict | None:
    """Most recent REAL-TPU headline this checkout has produced, for
    attachment to a cpu-fallback artifact — so a tunnel that was up
    mid-round but down at harvest time still shows its numbers in the
    final JSON instead of only in git history.  The journal is consulted
    FIRST: every in-process headline (battery runs included) lands
    there, so it is always at least as fresh as the committed
    HEADLINE_r05.json, which only matters on a fresh clone where the
    gitignored journal does not exist."""
    try:
        with open(PARTIAL_PATH) as f:
            lines = f.read().splitlines()
    except OSError:
        lines = []
    for ln in reversed(lines):
        try:
            rec = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if rec.get("phase") == "headline" and rec.get("platform") == "tpu":
            return rec
    bench_dir = os.path.dirname(PARTIAL_PATH)
    try:
        with open(os.path.join(bench_dir, "HEADLINE_r05.json")) as f:
            doc = json.loads(f.read().strip().splitlines()[-1])
        if doc.get("platform") == "tpu":
            return doc
    except (OSError, json.JSONDecodeError, IndexError):
        pass
    return None


def _plan_doc(plan) -> dict:
    """Resolved execution plan as a JSON-able echo (config.Plan fields)."""
    return {"block_impl": plan.block_impl, "scan_unroll": plan.scan_unroll,
            "stats_fusion": plan.stats_fusion,
            "slab_chains": plan.slab_chains, "source": plan.source,
            "blocks_per_dispatch": plan.blocks_per_dispatch,
            "compute_dtype": getattr(plan, "compute_dtype", "f32"),
            "kernel_impl": getattr(plan, "kernel_impl", "exact"),
            "rng_batch": getattr(plan, "rng_batch", "scan"),
            "geom_stride": getattr(plan, "geom_stride", 1)}


def _precision_doc(variants: dict) -> dict | None:
    """The v8 ``precision`` report section for one variant sweep: each
    fully-timed variant's rate keyed by its (compute_dtype, kernel_impl,
    rng_batch, geom_stride) axes, priced as a speedup against the best
    all-defaults variant in the SAME sweep (same platform, same process,
    same chain count — the only comparison that isolates the lever)."""
    rows = {}
    base = None
    for name, v in variants.items():
        if "rate" not in v or v.get("probe"):
            continue
        plan = v.get("plan") or {}
        cdt = plan.get("compute_dtype", "f32")
        kimpl = plan.get("kernel_impl", "exact")
        rb = plan.get("rng_batch", "scan")
        gs = plan.get("geom_stride", 1)
        rows[name] = {"compute_dtype": cdt, "kernel_impl": kimpl,
                      "rng_batch": rb, "geom_stride": gs,
                      "rate": v["rate"]}
        if cdt == "f32" and kimpl == "exact" and rb == "scan" and gs == 1:
            base = max(base or 0.0, v["rate"])
    if not rows:
        return None
    if base:
        for r in rows.values():
            r["speedup_vs_exact_f32"] = round(r["rate"] / base, 2)
    return {"baseline_rate_exact_f32": base, "variants": rows}


def _headline_doc(variants: dict, platform: str, **extra) -> dict:
    """The headline JSON from whatever variants have landed (shared by
    the normal path and the watchdog's partial-salvage path)."""
    ok = {k: v for k, v in variants.items() if "rate" in v}
    # probe entries (1x1-block micro-runs, see VARIANT_CFGS) document a
    # pathology; they must not outrank a fully-timed variant for the
    # published headline (only if nothing else landed)
    full = {k: v for k, v in ok.items() if not v.get("probe")}
    pick = full or ok
    best_name = max(pick, key=lambda k: pick[k]["rate"])
    rate = ok[best_name]["rate"]

    # price EVERY landed variant (obs/cost.py): static plan-cell model ×
    # its measured rate; the winner additionally carries the measured XLA
    # per-site flops/bytes when the roofline tail ran (basis: measured)
    import math

    from tmhpvsim_tpu.obs import cost as obs_cost

    roofline = extra.get("roofline") or {}
    for name, v in ok.items():
        vplan = v.get("plan") or {}
        measured = {}
        f_ss = roofline.get("flops_per_site_second")
        if (name == best_name and isinstance(f_ss, (int, float))
                and math.isfinite(f_ss) and f_ss > 0):
            measured = dict(
                measured_flops_per_site_s=f_ss,
                measured_bytes_per_site_s=roofline.get(
                    "bytes_per_site_second"))
        try:
            v["cost"] = obs_cost.cost_doc(
                site_s_per_s=v["rate"],
                block_impl=vplan.get("block_impl") or v.get("impl"),
                compute_dtype=vplan.get("compute_dtype"),
                kernel_impl=vplan.get("kernel_impl"),
                rng_batch=vplan.get("rng_batch"),
                geom_stride=vplan.get("geom_stride"),
                device_kind=extra.get("device_kind"), **measured)
        except Exception as e:  # pricing must never cost the headline
            print(f"# cost doc failed for {name}: {e}", file=sys.stderr)

    doc = {
        "metric": "simulated site-seconds/sec/chip",
        "value": rate,
        "unit": "site-s/s/chip",
        "vs_baseline": round(rate / REF_CEILING, 1),
        "north_star_frac": round(rate / NORTH_STAR, 3),
        "platform": platform,
        "tpu": platform == "tpu",
        "headline_variant": best_name,
        "variants": variants,
        **extra,
    }
    # the winning variant's resolved plan, when the sweep recorded one
    # (pre-autotuner partials journalled by older runs have no "plan")
    plan = ok[best_name].get("plan")
    if plan is not None:
        doc["tuned_plan"] = plan
    # schema-versioned report alongside the ad-hoc fields; device injected
    # from what the sweep already knows — this also runs on the watchdog
    # thread, where a fresh jax query against a wedged tunnel could hang
    # the salvage itself
    best = ok[best_name]
    timed_blocks = extra.get("timed_blocks")
    timing = None
    if timed_blocks and "best_round_wall_s" in best:
        timing = _bench_timing(best.get("compile_s"),
                               best["best_round_wall_s"], timed_blocks, rate)
    doc["run_report"] = _bench_report(
        "bench.headline", plan=plan, timing=timing,
        headline={"site_seconds_per_s": rate, "variant": best_name},
        device={"platform": platform,
                "device_kind": extra.get("device_kind")},
        precision=_precision_doc(variants),
        checkpoint=extra.get("checkpoint_overhead"),
        cost=ok[best_name].get("cost"),
    )
    return doc


def _run_variants(n_chains: int, n_blocks: int, n_rounds: int,
                  note: str = "", variants: dict | None = None,
                  on_progress=None) -> tuple[dict, dict]:
    """Measure the variant matrix once; returns (variants, sims).

    ``variants`` may be a caller-shared dict (the watchdog reads it to
    salvage partial results if the tunnel wedges mid-matrix); every
    completed entry is also journalled to ``PARTIAL_PATH``.
    ``on_progress()`` is called after every attempt — landed OR errored —
    so the hang watchdog can distinguish a slow-but-erroring tunnel
    (progress: let the step-down retries run) from a wedged one."""
    import contextlib

    from tmhpvsim_tpu.engine import Simulation
    from tmhpvsim_tpu.obs.trace import get_tracer

    tracer = get_tracer()
    n_total = n_blocks * n_rounds + 1
    variants = {} if variants is None else variants
    sims = {}

    def _best_rate() -> float:
        return max((v["rate"] for v in variants.values() if "rate" in v),
                   default=-1.0)

    for name, kw in VARIANT_CFGS.items():
        kw = dict(kw)
        probe = kw.pop("_probe", False)
        nb, nr = (1, 1) if probe else (n_blocks, n_rounds)
        try:
            prev_best = _best_rate()
            # the span brackets construct+compile+timed rounds: if the
            # tunnel wedges, the flight dump shows WHICH variant hung
            # (the open span never closes; the previous ones did)
            span = (tracer.span(f"variant:{name}", "bench",
                                n_chains=n_chains)
                    if tracer else contextlib.nullcontext())
            with span:
                sim = Simulation(_make_cfg(n_chains, nb * nr + 1, **kw))
                c_s, dt, rate = _timed_reduce_run(sim, nb, nr)
            # compare/store the SAME rounded value everywhere: headline()
            # picks best_name by the stored rate, and a raw-vs-rounded
            # mismatch here could retain a sim whose name the pick
            # doesn't match (dropping the roofline, keeping a stray sim
            # resident through the sharded run)
            rate = round(rate, 1)
            variants[name] = {
                "rate": rate, "compile_s": round(c_s, 1),
                "best_round_wall_s": round(dt, 2),
                # the RESOLVED topology ('auto' depends on the backend; on
                # a CPU run a 'scan-*' label would otherwise misdocument a
                # wide run)
                "impl": _impl_label(sim),
                "plan": _plan_doc(sim.plan),
            }
            if probe:
                variants[name]["probe"] = True  # 1x1 blocks, see VARIANT_CFGS
            # Keep at most ONE sim alive — the best-so-far (the headline
            # tail needs it for the roofline).  Resident sims degrade
            # every subsequent timed run on the tunnel TPU (measured 30x,
            # see VARIANT_CFGS); everything else is dropped the moment
            # its number is on disk.
            if rate > prev_best and not probe:
                sims.clear()
                sims[name] = (sim, dt)
            else:
                del sim
            _persist_partial({"phase": "headline-variant", "name": name,
                              "n_chains": n_chains, **variants[name]})
        except Exception as e:
            print(f"# variant {name} failed{note}: {e}", file=sys.stderr)
            variants[name] = {"error": str(e)[:200]}
        if on_progress is not None:
            on_progress()
    return variants, sims


def _salvage_cpu_headline(tpu_errors=None, timeout_s: float = 900.0) -> bool:
    """Re-run the headline scaled on CPU in a FRESH subprocess and print
    its JSON (with the TPU failure records attached).

    A fresh process is mandatory: once this process has initialised the
    TPU backend, jax 0.9 caches the backend registry and
    ``jax.config.update('jax_platforms', 'cpu')`` no longer switches —
    an in-process "CPU" rerun would silently re-measure the broken TPU.
    Returns True if a salvage line was printed."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="")
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True, timeout=timeout_s,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return False
    lines = [ln for ln in (r.stdout or "").splitlines() if ln.strip()]
    if r.returncode != 0 or not lines:
        return False
    try:
        doc = json.loads(lines[-1])
    except json.JSONDecodeError:
        return False
    doc["platform"] = "cpu-fallback"
    doc["salvaged_after_tpu_failure"] = True
    if tpu_errors is not None:
        doc["tpu_errors"] = tpu_errors
    # flush: callers os._exit() right after salvage, which skips the
    # interpreter's atexit stdio flush — under the battery gate's
    # block-buffered redirect an unflushed doc is lost entirely
    print(json.dumps(doc), flush=True)
    return True


#: where the watchdog's flight-recorder slice lands (same directory the
#: battery script collects artifacts from)
FLIGHT_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "benchmarks", "flight_watchdog.json")


def _dump_flight_recorder(reason: str, path: str = FLIGHT_PATH) -> bool:
    """Dump the process tracer's last-30-s window before a hard exit.

    The rc=3 salvage paths end in ``os._exit`` — no unwinding, no atexit
    — so this is the only record of what the harness was doing when the
    tunnel wedged.  Best-effort by design: a broken dump must never
    pre-empt the salvage output itself."""
    try:
        from tmhpvsim_tpu.obs.trace import get_tracer

        tracer = get_tracer()
        if not tracer or not len(tracer):
            return False
        tracer.dump_flight(path)
        print(f"# flight recorder ({reason}): last-30-s trace in {path}",
              file=sys.stderr)
        return True
    except Exception as e:
        print(f"# flight recorder dump failed: {e}", file=sys.stderr)
        return False


def headline() -> None:
    platform, fallback = _probe_or_fallback()
    import jax

    # per-variant spans land in the process tracer so a wedged-tunnel
    # watchdog exit can dump what was in flight (see _dump_flight_recorder)
    try:
        from tmhpvsim_tpu.obs.trace import Tracer, set_tracer

        set_tracer(Tracer())
    except Exception as e:
        print(f"# tracer init failed: {e}", file=sys.stderr)

    shared_variants: dict = {}
    monitor_state = {"last_progress": time.monotonic(),
                     "t0": time.monotonic(), "done": False}
    if platform == "tpu":
        n_chains, n_blocks, n_rounds = N_CHAINS, N_BLOCKS, N_ROUNDS
        # watchdog for the HANGING failure mode only: a monitor thread
        # fires when no variant attempt has finished (landed or errored)
        # for TPU_VARIANTS_DEADLINE_S — block_until_ready on a dead tunnel
        # never returns — or when the whole phase exceeds
        # TPU_HEADLINE_TOTAL_S.  A slow-but-ERRORING tunnel keeps making
        # progress, so the chain-count step-down retries below get their
        # chance instead of being os._exit'd mid-flight.  On firing it
        # emits a headline from the variants that already landed — REAL
        # TPU numbers beat a CPU fallback — else salvages a CPU number,
        # and hard-exits with rc=0 instead of the harness recording
        # rc=124 and nothing else (the round-4 failure mode).
        import threading

        def _wedged():
            # first thing, before any salvage that could itself hang: the
            # flight recorder is the wedge's only post-mortem evidence
            _dump_flight_recorder("TPU variants phase exceeded deadline")
            # snapshot first: the main thread mutates this dict
            snap = dict(shared_variants)
            # probe entries don't count as landed (same rule as _ok_full:
            # a 1x1-block probe must not be published as the headline nor
            # suppress the CPU salvage)
            done = {k: v for k, v in snap.items()
                    if "rate" in v and not v.get("probe")}
            if done:
                print("# TPU variants phase exceeded deadline; emitting "
                      f"partial headline from {len(done)} completed "
                      "variant(s)", file=sys.stderr)
                doc = _headline_doc(
                    snap, "tpu",
                    partial=True, n_chains=n_chains, block_s=BLOCK_S,
                    timed_blocks=n_blocks, timed_rounds=n_rounds,
                    error="tunnel wedged mid-matrix; remaining variants "
                          "unmeasured",
                )
                # journal it like the normal-completion path does: the
                # salvaged partial is exactly the record a later
                # cpu-fallback run's _last_tpu_evidence must find
                _persist_partial({"phase": "headline", **doc})
                # flush + NONZERO exit: os._exit skips the atexit stdio
                # flush (block-buffered redirects would lose the doc), and
                # rc=0 here let run_tpu_round5b.sh promote a partial doc
                # over a previously committed complete artifact — rc!=0
                # routes it to $dest.partial instead
                print(json.dumps(doc), flush=True)
                os._exit(3)
            print("# TPU variants phase exceeded deadline; salvaging CPU "
                  "number", file=sys.stderr)
            if not _salvage_cpu_headline(
                    {"error": "TPU variants phase hung past deadline"}):
                print(json.dumps({
                    "metric": "simulated site-seconds/sec/chip",
                    "value": 0.0, "unit": "site-s/s/chip",
                    "vs_baseline": 0.0, "platform": "tpu-hung",
                    "error": "TPU hung and CPU salvage failed",
                }), flush=True)
            os._exit(3)

        def _monitor():
            while not monitor_state["done"]:
                time.sleep(5)
                now = time.monotonic()
                if monitor_state["done"]:
                    return
                if (now - monitor_state["last_progress"]
                        > TPU_VARIANTS_DEADLINE_S or
                        now - monitor_state["t0"] > TPU_HEADLINE_TOTAL_S):
                    _wedged()

        threading.Thread(target=_monitor, daemon=True).start()
    else:
        # scaled-down run for ANY non-TPU platform — including an
        # env-pinned CPU backend where the probe "succeeds" on cpu: a
        # full-size CPU run would blow the harness timeout and record
        # nothing at all (the round-1 failure mode)
        n_chains, n_blocks, n_rounds = CPU_N_CHAINS, CPU_N_BLOCKS, 1

    from tmhpvsim_tpu.parallel import ShardedSimulation, make_mesh
    from tmhpvsim_tpu.parallel.distributed import initialize_from_env

    try:
        initialize_from_env()
    except Exception as e:  # single-process bench must not die on this
        print(f"# jax.distributed init skipped: {e}", file=sys.stderr)

    def _progress():
        monitor_state["last_progress"] = time.monotonic()

    def _ok_full(variants: dict) -> dict:
        """Fully-timed successes: a 1x1-block probe entry alone must not
        count as a landed headline (its metadata would claim the full
        timed_blocks x timed_rounds measurement) nor suppress the
        step-down/salvage paths."""
        return {k: v for k, v in variants.items()
                if "rate" in v and not v.get("probe")}

    n_total = n_blocks * n_rounds + 1
    variants, sims = _run_variants(n_chains, n_blocks, n_rounds,
                                   variants=shared_variants,
                                   on_progress=_progress)
    ok = _ok_full(variants)
    if not ok and platform == "tpu":
        # every variant ERRORED at the full shape (e.g. remote-compile
        # failures): step the chain count down before abandoning the TPU —
        # a small TPU number beats any CPU fallback.  The monitor only
        # fires on NO-PROGRESS, so these retries run as long as attempts
        # keep finishing (hang mid-retry still trips it).
        for smaller in (n_chains // 4, n_chains // 16):
            print(f"# all variants failed at n_chains={n_chains}; "
                  f"retrying at {smaller}", file=sys.stderr)
            n_chains = smaller
            shared_variants.clear()
            variants, sims = _run_variants(n_chains, n_blocks, n_rounds,
                                           variants=shared_variants,
                                           on_progress=_progress)
            ok = _ok_full(variants)
            if ok:
                break
    # the monitor stays armed through the roofline/sharded tail (a
    # post-variants hang would otherwise wedge with the landed numbers
    # unprinted); those phases finish well inside the no-progress window
    _progress()

    if not ok and not fallback:
        # the tunnel passed the probe but then ERRORED through every
        # shape: salvage a labelled CPU number in a fresh process
        # (see _salvage_cpu_headline on why in-process won't work)
        print("# all TPU variants failed; salvaging CPU number",
              file=sys.stderr)
        if _salvage_cpu_headline(variants):
            return
    if not ok:
        err_doc = {"metric": "simulated site-seconds/sec/chip",
                   "value": 0.0, "unit": "site-s/s/chip",
                   "vs_baseline": 0.0, "platform": platform,
                   "error": "all variants failed",
                   "variants": variants}
        if platform != "tpu":
            evidence = _last_tpu_evidence()
            if evidence is not None:
                err_doc["last_tpu_headline"] = evidence
        print(json.dumps(err_doc))
        return
    # ok is already probe-free (_ok_full)
    best_name = max(ok, key=lambda k: ok[k]["rate"])

    # --- roofline of the winning variant's hot jit (sims holds at most
    # the best non-probe sim; a probe winner has no retained sim)
    device_kind = jax.devices()[0].device_kind
    roofline = None
    if best_name in sims:
        best_sim, best_dt = sims[best_name]
        cost = _hot_jit_cost(best_sim)
        roofline = _roofline(cost, best_dt / n_blocks, n_chains, BLOCK_S,
                             device_kind)
        # free the winner's device buffers before the sharded run: any
        # resident sim degrades later timed runs on this backend
        del best_sim
        sims.clear()

    # Sharded path over all local devices: on the single real TPU chip this
    # is a 1-device mesh (validates the shard_map machinery at full size);
    # scaling efficiency needs a real multi-chip slice (--scaling runs the
    # virtual-CPU-mesh mechanics artifact).
    devices = jax.local_devices()
    n_dev = len(devices)
    sh_chains = max(n_dev, (n_chains // n_dev) * n_dev)
    try:
        ssim = ShardedSimulation(_make_cfg(sh_chains, n_total),
                                 mesh=make_mesh(devices))
        sh_c, sh_dt, sh_rate = _timed_reduce_run(ssim, n_blocks, n_rounds)
        sharded = {
            "n_devices": n_dev,
            "n_chains": sh_chains,
            "rate_per_chip": round(sh_rate / n_dev, 1),
            "compile_s": round(sh_c, 1),
            "best_round_wall_s": round(sh_dt, 2),
        }
    except Exception as e:  # sharded failure must not lose the main number
        print(f"# sharded bench failed: {e}", file=sys.stderr)
        sharded = {"error": str(e)[:200]}
    _progress()

    # checkpoint-overhead pricing (off / sync / async steady-block walls,
    # engine/checkpoint.py) — non-fatal like the other tail phases
    ck_overhead = None
    try:
        ck_overhead = _checkpoint_overhead_doc(n_chains)
    except Exception as e:
        print(f"# checkpoint-overhead bench failed: {e}", file=sys.stderr)
    _progress()

    extra = dict(roofline=roofline) if roofline is not None else {}
    if ck_overhead is not None:
        extra["checkpoint_overhead"] = ck_overhead
    doc = _headline_doc(
        variants, platform,
        device_kind=device_kind, n_chains=n_chains, block_s=BLOCK_S,
        timed_blocks=n_blocks, timed_rounds=n_rounds,
        sharded=sharded, **extra,
    )
    _persist_partial({"phase": "headline", **doc})
    if platform != "tpu":
        evidence = _last_tpu_evidence()
        if evidence is not None:
            doc["last_tpu_headline"] = evidence
    print(json.dumps(doc))
    monitor_state["done"] = True  # headline printed; stand the monitor down


# ---------------------------------------------------------------------------
# BASELINE.md configs 1-5 (artifact producers)
# ---------------------------------------------------------------------------


def _reduce_config_run(label: str, cfg, sharded: bool, note: str,
                       scaled_from: str | None = None) -> None:
    """Shared runner for configs 2-5: a reduce-mode run, full wall-time
    measurement (compile excluded), one JSON artifact line."""
    import jax

    from tmhpvsim_tpu.engine import Simulation
    from tmhpvsim_tpu.parallel import ShardedSimulation, make_mesh

    if sharded:
        sim = ShardedSimulation(cfg, mesh=make_mesh(jax.local_devices()))
    else:
        sim = Simulation(cfg)
    if sim.n_blocks < 2:
        raise ValueError(
            f"config {label!r} needs >= 2 blocks (warm-up + timed); "
            f"got duration_s={cfg.duration_s}, block_s={cfg.block_s}"
        )
    # warm-up on block 0, one timed round over blocks 1..n-1 — the shared
    # measurement protocol (_timed_reduce_run)
    compile_s, steady_s, rate = _timed_reduce_run(sim, sim.n_blocks - 1, 1)
    n_dev = len(jax.local_devices()) if sharded else 1
    doc = {
        "config": label,
        "metric": "simulated site-seconds/sec/chip",
        "value": round(rate / n_dev, 1),
        "unit": "site-s/s/chip",
        "vs_baseline": round(rate / n_dev / REF_CEILING, 1),
        "platform": jax.devices()[0].platform,
        "device_kind": jax.devices()[0].device_kind,
        "n_devices": n_dev,
        "echo": {
            "n_chains": cfg.n_chains, "duration_s": cfg.duration_s,
            "block_s": cfg.block_s, "prng_impl": cfg.prng_impl,
            "block_impl": _impl_label(sim),
            "site_grid": cfg.site_grid is not None,
            "start": cfg.start, "seed": cfg.seed,
        },
        "compile_s": round(compile_s, 1),
        "steady_wall_s": round(steady_s, 2),
        "scaled_from": scaled_from,
        "note": note,
    }
    doc["run_report"] = _bench_report(
        f"bench.config.{label}", config=cfg, plan=_plan_doc(sim.plan),
        timing=_bench_timing(compile_s, steady_s, sim.n_blocks - 1, rate),
        headline={"site_seconds_per_s": doc["value"]},
        cost=_config_cost(sim.plan, doc["value"], doc["device_kind"]),
    )
    _persist_partial({"phase": "config", **doc})
    print(json.dumps(doc))


#: single-chip chain-count sweet spot (round-5 sweep, TPU v5e): the
#: scan-fused block at unroll 8 runs ~3.5 ms/65536x1080 block, but falls
#: off a ~14x cliff at 262144 chains (the unrolled body's live set
#: spills VMEM).  Configs above this run as sequential chain slabs —
#: bit-identical to the unslabbed run (SimConfig.n_chains_total).
SLAB_CHAINS = 65536


def _slab_cfgs(total: int, blocks_per_slab: int, bs: int) -> list:
    """Chain-slab configs covering chains [0, total) in <= SLAB_CHAINS
    pieces, blocks_per_slab blocks of bs seconds each (shared by configs
    4 and 5 so slab-shape logic cannot drift between them)."""
    return [
        _make_cfg(min(SLAB_CHAINS, total - off), blocks_per_slab,
                  block_s=bs, n_chains_total=total, chain_offset=off)
        for off in range(0, total, SLAB_CHAINS)
    ]


def _reduce_config_run_slabs(label: str, cfgs: list, note: str,
                             scaled_from: str | None = None) -> None:
    """Chain-slab runner for configs whose n_chains exceeds SLAB_CHAINS:
    every cfg in ``cfgs`` simulates one slab [chain_offset, +n_chains) of
    the same notional run; slabs execute sequentially (one compile +
    warm-up block each) and the artifact's rate is total timed
    site-seconds over summed steady wall."""
    import jax

    from tmhpvsim_tpu.engine import Simulation

    total_site_s = 0.0
    total_steady = 0.0
    total_compile = 0.0
    n_timed_blocks = 0
    slab_plan = None
    slab_echo = []
    for cfg in cfgs:
        sim = Simulation(cfg)
        if sim.n_blocks < 2:
            raise ValueError(f"slab of {label!r} needs >= 2 blocks")
        c_s, steady, rate = _timed_reduce_run(sim, sim.n_blocks - 1, 1)
        total_site_s += cfg.n_chains * cfg.block_s * (sim.n_blocks - 1)
        total_steady += steady
        total_compile += c_s
        n_timed_blocks += sim.n_blocks - 1
        slab_plan = _plan_doc(sim.plan)  # equal-shape slabs share a plan
        slab_doc = {"chain_offset": cfg.chain_offset,
                    "n_chains": cfg.n_chains,
                    "steady_wall_s": round(steady, 2),
                    "rate": round(rate, 1)}
        # journal each slab as it lands: a crash (or a step-down restart
        # — cheap, since equal-shape slabs share one jit executable)
        # mid-config still leaves the finished slabs' numbers on disk
        _persist_partial({"phase": "config-slab", "config": label,
                          "block_s": cfg.block_s, **slab_doc})
        slab_echo.append(slab_doc)
        del sim  # resident sims degrade later timed runs (VARIANT_CFGS)
    rate = total_site_s / total_steady
    c0 = cfgs[0]
    doc = {
        "config": label,
        "metric": "simulated site-seconds/sec/chip",
        "value": round(rate, 1),
        "unit": "site-s/s/chip",
        "vs_baseline": round(rate / REF_CEILING, 1),
        "platform": jax.devices()[0].platform,
        "device_kind": jax.devices()[0].device_kind,
        "n_devices": 1,
        "echo": {
            "n_chains": sum(c.n_chains for c in cfgs),
            "n_chains_total": c0.n_chains_total,
            "slabs": slab_echo,
            "duration_s": c0.duration_s, "block_s": c0.block_s,
            "prng_impl": c0.prng_impl, "start": c0.start, "seed": c0.seed,
        },
        "compile_s": round(total_compile, 1),
        "steady_wall_s": round(total_steady, 2),
        "scaled_from": scaled_from,
        "note": note,
    }
    doc["run_report"] = _bench_report(
        f"bench.config.{label}", config=c0, plan=slab_plan,
        timing=_bench_timing(total_compile, total_steady, n_timed_blocks,
                             rate),
        headline={"site_seconds_per_s": doc["value"]},
        slabs={"completed": len(slab_echo), "total": len(cfgs)},
        cost=_config_cost(slab_plan, doc["value"], doc["device_kind"]),
    )
    _persist_partial({"phase": "config", **doc})
    print(json.dumps(doc))


def _reduce_config_run_resilient(label: str, make_cfg_bs, sharded: bool,
                                 note: str, scaled_from: str | None = None,
                                 block_s_steps=(8640, 4320, 1080)) -> None:
    """``_reduce_config_run`` with block_s step-down: the remote-compile
    service has failed nested/long-block compiles before (round-4
    PERF_ANALYSIS §4a), so a compile failure at the target block_s retries
    at successively smaller blocks instead of zeroing the artifact.
    ``make_cfg_bs(block_s)`` builds the config for one attempt — a LIST
    of configs means chain slabs (``_reduce_config_run_slabs``)."""
    last_err = None
    for bs in block_s_steps:
        n = note if last_err is None else (
            note + f" [block_s stepped down to {bs}; prior attempt "
                   f"failed: {last_err}]"
        )
        try:
            cfg = make_cfg_bs(bs)
            if isinstance(cfg, list):
                _reduce_config_run_slabs(label, cfg, note=n,
                                         scaled_from=scaled_from)
            else:
                _reduce_config_run(label, cfg, sharded=sharded,
                                   note=n, scaled_from=scaled_from)
            return
        except Exception as e:
            last_err = str(e)[:200]
            print(f"# config {label!r} failed at block_s={bs}: {last_err}",
                  file=sys.stderr)
    doc = {"config": label, "error": last_err,
           "block_s_tried": list(block_s_steps)}
    _persist_partial({"phase": "config", **doc})
    print(json.dumps(doc))


def fleet_bench(fleet_csv: str | None, fleet_synth: int | None,
                fleet_seed: int = 0) -> None:
    """Heterogeneous-fleet variant (--fleet-csv / --fleet-synth N): the
    standard reduce-mode measurement protocol run twice on the same
    chain shape — a homogeneous baseline, then a per-site parameter
    fleet (fleet/params.py) — so the artifact prices what heterogeneity
    costs and tools/bench_trend.py can carry it as the ``fleet``
    column.  Synthetic fleets are the seeded national-fleet sampler
    (FleetParams.synthetic); a CSV runs whatever installation list the
    operator exported."""
    import jax

    from tmhpvsim_tpu import fleet as fleet_mod
    from tmhpvsim_tpu.engine import Simulation

    platform, fallback = _probe_or_fallback()
    if fleet_csv is not None:
        fp = fleet_mod.FleetParams.from_csv(fleet_csv)
        source = "csv"
    else:
        fp = fleet_mod.FleetParams.synthetic(fleet_synth or 1024,
                                             seed=fleet_seed)
        source = "synthetic"
    n = len(fp)
    n_blocks, bs = (3, 1800) if platform != "tpu" else (4, BLOCK_S)

    def timed(cfg):
        sim = Simulation(cfg)
        c_s, steady, rate = _timed_reduce_run(sim, sim.n_blocks - 1, 1)
        plan = sim.plan
        del sim  # resident sims degrade later timed runs (VARIANT_CFGS)
        return c_s, steady, rate, plan

    c0, s0, r0, _ = timed(_make_cfg(n, n_blocks, block_s=bs))
    _persist_partial({"phase": "fleet-homog", "n_chains": n,
                      "rate": round(r0, 1)})
    het_cfg = _make_cfg(n, n_blocks, block_s=bs, fleet=fp)
    c1, s1, r1, plan = timed(het_cfg)
    doc = {
        "config": "fleet-het",
        "metric": "simulated site-seconds/sec/chip",
        "value": round(r1, 1),
        "unit": "site-s/s/chip",
        "vs_baseline": round(r1 / REF_CEILING, 1),
        "platform": jax.devices()[0].platform,
        "device_kind": jax.devices()[0].device_kind,
        "n_devices": 1,
        "fleet": {
            "n_sites": n,
            "n_cohorts": fp.n_cohorts,
            "digest": fp.digest()[:12],
            "source": source,
            "homog_rate": round(r0, 1),
            # the pricing lever: heterogeneous rate as a fraction of the
            # homogeneous rate on the identical chain shape
            "het_over_homog": round(r1 / r0, 3) if r0 else None,
        },
        "compile_s": round(c1, 1),
        "steady_wall_s": round(s1, 2),
        "note": "" if not fallback else "cpu-fallback",
    }
    doc["run_report"] = _bench_report(
        "bench.fleet", config=het_cfg, plan=_plan_doc(plan),
        timing=_bench_timing(c1, s1, n_blocks - 1, r1),
        headline={"site_seconds_per_s": doc["value"]},
        cost=_config_cost(plan, doc["value"], doc["device_kind"]),
    )
    _persist_partial({"phase": "fleet", **doc})
    print(json.dumps(doc))


def config_1() -> None:
    """1 site, 1 day @ 1 Hz on the asyncio/CPU reference path: the real
    app pair (metersim producer -> local transport -> pvsim consumer ->
    funnel join -> CSV), --no-realtime."""
    import asyncio
    import tempfile

    _force_cpu(1)

    from tmhpvsim_tpu.apps import metersim as m_app
    from tmhpvsim_tpu.apps import pvsim as p_app

    duration = 86_400

    async def pair(csv_path):
        import datetime as dt

        url, exchange = "local://bench", "meter"
        start = dt.datetime(2019, 9, 5, 0, 0, 0)
        # the test-suite's e2e pattern (tests/test_apps.py): consumer runs
        # unbounded, producer bounds the run, then drain + cancel
        cons = asyncio.create_task(
            p_app.pvsim_main(csv_path, url, exchange, realtime=False,
                             seed=2, duration_s=None, start=start)
        )
        await asyncio.sleep(0.05)
        await m_app.metersim_main(url, exchange, realtime=False, seed=1,
                                  duration_s=duration, start=start)
        await asyncio.sleep(0.5)
        cons.cancel()
        try:
            await cons
        except asyncio.CancelledError:
            pass

    with tempfile.TemporaryDirectory() as d:
        csv_path = os.path.join(d, "out.csv")
        t0 = time.perf_counter()
        asyncio.run(pair(csv_path))
        wall = time.perf_counter() - t0
        rows = sum(1 for _ in open(csv_path)) - 1
    rate = duration / wall
    doc = {
        "config": "1: 1 site x 1 day, asyncio/CPU reference path",
        "metric": "simulated seconds/sec (1 site)",
        "value": round(rate, 1),
        "unit": "sim-s/s",
        "vs_baseline": round(rate / REF_CEILING, 1),
        "platform": "cpu",
        "echo": {"duration_s": duration, "realtime": False,
                 "transport": "local://", "joined_rows": rows},
        "wall_s": round(wall, 2),
        "note": ("full app pair: metersim producer + pvsim consumer + "
                 "funnel join + CSV sink; the reference's own ceiling on "
                 "this config is ~100 sim-s/s (utils.py:36 10 ms floor)"),
    }
    doc["run_report"] = _bench_report(
        "bench.config.1", config=dict(doc["echo"]),
        headline={"sim_seconds_per_s": doc["value"]},
        device={"platform": "cpu"},  # asyncio path: no device involved
    )
    print(json.dumps(doc))


def config_2() -> None:
    """1k chains x 1 site, 1 year @ 1 Hz, single chip."""
    platform, fallback = _probe_or_fallback()
    year = 365 * 86_400
    if platform != "tpu":
        _reduce_config_run(
            "2: 1k chains x 1 year, single chip",
            _make_cfg(1000, 4, block_s=8640),
            sharded=False, note="cpu-fallback: duration scaled to 4 blocks",
            scaled_from="1000 chains x 1 year",
        )
        return
    _reduce_config_run_resilient(
        "2: 1k chains x 1 year, single chip",
        lambda bs: _make_cfg(1000, year // bs, block_s=bs),
        sharded=False, note="full 1-year run, 1000 chains, single chip",
    )


def _config_3_like(label: str, duration_s: int, note: str,
                   scaled_from: str | None) -> None:
    """Shared body of configs 3/3a: the 10k-site lat/lon grid with
    per-site device geometry, at the given duration."""
    from tmhpvsim_tpu.config import SiteGrid

    platform, fallback = _probe_or_fallback()
    grid = SiteGrid.regular((45.0, 55.0), (5.0, 15.0), 100, 100)
    if platform != "tpu":
        _reduce_config_run(
            label, _make_cfg(len(grid), 2, block_s=4320, site_grid=grid),
            sharded=False, note="cpu-fallback: duration scaled to 2 blocks",
            scaled_from="10k sites x 1 year",
        )
        return
    _reduce_config_run_resilient(
        label,
        lambda bs: _make_cfg(len(grid), duration_s // bs, block_s=bs,
                             site_grid=grid),
        sharded=False, note=note, scaled_from=scaled_from,
    )


def config_3a() -> None:
    """Quick 30-day slice of config 3, its own artifact: the full year at
    10k sites is the longest config (~3.15e12 site-seconds with
    per-site device geometry), and a short tunnel window must not leave
    the 10k-site shape unmeasured — this lands in minutes, disclosed as
    scaled."""
    _config_3_like(
        "3a: 10k-site grid x 30 days", 30 * 86_400,
        note=("30-day run, 100x100 lat/lon grid over central Europe, "
              "solar geometry evaluated per site on device"),
        scaled_from="10k sites x 1 year",
    )


def config_3() -> None:
    """10k-site lat/lon grid, 1 year, device-side per-site geometry."""
    _config_3_like(
        "3: 10k-site grid x 1 year", 365 * 86_400,
        note=("full 1-year run, 100x100 lat/lon grid over central "
              "Europe, solar geometry evaluated per site on device"),
        scaled_from=None,
    )


def config_4() -> None:
    """100k chains, per-second, sharded over the available mesh."""
    platform, fallback = _probe_or_fallback()
    if platform != "tpu":
        _reduce_config_run(
            "4: 100k chains per-second, sharded",
            _make_cfg(100_000 // 125, 3, block_s=1080),
            sharded=True, note="cpu-fallback: 800 chains x 3 blocks",
            scaled_from="100k chains x 1 day",
        )
        return
    total = 100_000
    _reduce_config_run_resilient(
        "4: 100k chains per-second, sharded",
        lambda bs: _slab_cfgs(total, 86_400 // bs, bs), sharded=False,
        note=("100k chains x 1 day on the single available chip, as "
              f"{-(-total // SLAB_CHAINS)} sequential <= {SLAB_CHAINS}"
              "-chain slabs — bit-identical to the unslabbed run "
              "(SimConfig.n_chains_total; tests/test_engine.py) and each "
              "slab inside the measured single-chip fast regime (the "
              "scan block spills VMEM above ~65536 chains, round-5 "
              "sweep).  The BASELINE target hardware is v5e-8 — per-chip "
              "rate is the comparable number; multi-chip sharding is "
              "validated by the 8-device dryrun"),
        # 1080 IS the measured fast regime at 65536 chains (4320 already
        # spills: 187 ms/block, round-5 sweep); stepping DOWN from 8640
        # would start two shapes deep in the spill zone.  540 is the
        # smaller-live-set resilience fallback.
        block_s_steps=(1080, 540),
    )


def config_5() -> None:
    """1M-chain ensemble, 10-year (BASELINE config 5).

    On TPU: the TRUE 1M chain count runs on the single available chip as
    sequential <= SLAB_CHAINS-chain slabs (bit-identical to the unslabbed
    run by keyed construction; round-4 verdict item 3 — chains must not
    be scaled, duration may, disclosed).  Duration is scaled 10 years ->
    4320 s per slab (constant across the block_s step-down; the first
    block of each slab is compile warm-up); the 10-year horizon itself
    is covered by the O(1)-state windowed sampler design (tests
    test_state_is_duration_independent) rather than wall-clock.

    Off TPU: scaled dryrun on the virtual CPU mesh — proves the 1M-chain
    mechanics (state construction, sharding, scan-fused reduce step)
    execute end-to-end on an 8-device mesh.
    """
    platform, fallback = _probe_or_fallback()
    if platform == "tpu":
        total = 1_000_000
        # per-slab simulated duration held constant across the step-down
        # (4320 s; first block of each slab is compile warm-up), so the
        # note stays true at every block_s
        slab_sim_s = 4320
        _reduce_config_run_resilient(
            "5: 1M-chain ensemble",
            lambda bs: _slab_cfgs(total, slab_sim_s // bs, bs),
            sharded=False,
            note=(f"full 1M chain count on the single available chip as "
                  f"{-(-total // SLAB_CHAINS)} sequential <= {SLAB_CHAINS}"
                  "-chain slabs (each inside the measured fast regime); "
                  f"duration scaled 10 years -> {slab_sim_s} s per slab "
                  "(first block of each slab is compile warm-up); the "
                  "BASELINE target hardware is a pod slice — per-chip "
                  "rate is the comparable number, multi-chip sharding "
                  "validated by the 8-device dryrun"),
            scaled_from="1M chains x 10 years on a pod slice",
            block_s_steps=(1080, 540),
        )
        return
    _force_cpu(8)
    # threefry here (rbg works on CPU but is slower there; the point is
    # the 1M-chain mechanics, not the CPU rate); block_impl='scan' FORCED
    # so the artifact exercises the TPU production path at the target
    # batch size — 'auto' would silently resolve to 'wide' on this host
    cfg = _make_cfg(1_000_000, 2, block_s=120, prng_impl="threefry2x32",
                    block_impl="scan")
    _reduce_config_run(
        "5: 1M-chain ensemble (scaled dryrun, 8 virtual CPU devices)",
        cfg, sharded=True,
        note=("full 1M chain count, duration scaled 10 years -> 2 blocks "
              "x 120 s; validates sharded state + scan-fused step at the "
              "target batch size (virtual CPU mesh, not TPU hardware)"),
        scaled_from="1M chains x 10 years on a pod slice",
    )


def scaling() -> None:
    """Weak-scaling mechanics on the virtual CPU mesh: same per-device
    work on 1, 2, 4, 8 devices.

    CAVEAT recorded in the artifact: this host has ONE physical core, so
    all virtual devices share it and wall time grows ~linearly with the
    device count — the artifact validates that the sharded program
    compiles, runs, and partitions correctly at every mesh size (the
    mechanics a real 1->8-chip measurement exercises), not hardware
    scaling efficiency, which needs a real multi-chip slice.
    """
    _force_cpu(8)
    import jax

    import multiprocessing

    from tmhpvsim_tpu.parallel import ShardedSimulation, make_mesh

    per_dev = 128
    n_total = 3
    results = []
    for n_dev in (1, 2, 4, 8):
        devices = jax.devices("cpu")[:n_dev]
        cfg = _make_cfg(per_dev * n_dev, n_total, block_s=360,
                        prng_impl="threefry2x32")
        sim = ShardedSimulation(cfg, mesh=make_mesh(devices))
        c_s, dt, rate = _timed_reduce_run(sim, n_total - 1, 1)
        results.append({
            "n_devices": n_dev, "n_chains": per_dev * n_dev,
            "rate": round(rate, 1),
            "rate_per_device": round(rate / n_dev, 1),
            "wall_s": round(dt, 3),
        })
        print(f"# {n_dev} devices: {rate:.3g} site-s/s", file=sys.stderr)
    base = results[0]["rate_per_device"]
    for r in results:
        r["efficiency_vs_1dev"] = round(r["rate_per_device"] / base, 3)
    doc = {
        "artifact": "weak-scaling mechanics, virtual CPU mesh",
        "per_device_chains": per_dev,
        "results": results,
        "physical_cores": multiprocessing.cpu_count(),
        "caveat": ("all virtual devices share this host's "
                   f"{multiprocessing.cpu_count()} physical core(s); this "
                   "validates sharded-program mechanics at each mesh "
                   "size, NOT hardware scaling efficiency (needs a real "
                   "multi-chip slice)"),
    }
    doc["run_report"] = _bench_report(
        "bench.scaling", config={"per_device_chains": per_dev},
        headline={"results": results},
    )
    print(json.dumps(doc))


def sweep() -> None:
    """Tuning matrix: one JSON line per (impl, prng, unroll, shape)
    variant — the measurement driver behind PERF_ANALYSIS.md."""
    platform, fallback = _probe_or_fallback()
    from tmhpvsim_tpu.engine import Simulation

    # scale down on anything that is not real TPU hardware (including an
    # env-pinned CPU backend, where the probe "succeeds" on CPU)
    scale = 1 if platform == "tpu" else 256
    # Matrix rewritten live in round 5 after the headline landed: rbg
    # inside the scan formulations measured ~76x slower than threefry on
    # the tunnel TPU (scan-rbg 8.8e6 vs scan-threefry 6.7e8 site-s/s/chip
    # — the vmapped per-chain RngBitGenerator draws serialize), so the
    # rbg x {unroll, block_s} half of the old matrix answers a dead
    # question.  What we need now: (a) does per-step scan overhead
    # dominate (rate should rise ~linearly with n_chains if so), (b) the
    # best unroll for scan-threefry, (c) whether scan2 — whose O(1)
    # state admits 1M+ chains — wins once chains amortise the overhead,
    # (d) wide at 4x chains / 4x block_s as the bandwidth-bound control.
    variants = [
        ("scan-threefry-u8", 65536, 1080, "threefry2x32", "scan", 8),
        ("scan-threefry-u4", 65536, 1080, "threefry2x32", "scan", 4),
        # the take-1 sweep only bracketed the VMEM cliff coarsely (u8 =
        # 3.5 ms fast, u16 = 60 ms spilled, 4320 = 187 ms spilled):
        # u12@1080 and u4/u8@2160 probe the space between the measured
        # fast point and the cliff — 2160 also halves the per-block
        # fixed host cost if it holds
        ("scan-threefry-u12", 65536, 1080, "threefry2x32", "scan", 12),
        ("scan-threefry-u8-bs2160", 65536, 2160, "threefry2x32", "scan", 8),
        ("scan-threefry-u4-bs2160", 65536, 2160, "threefry2x32", "scan", 4),
        ("scan-threefry-u16", 65536, 1080, "threefry2x32", "scan", 16),
        ("scan-threefry-u32", 65536, 1080, "threefry2x32", "scan", 32),
        ("scan-threefry-u8-x4chains", 262144, 1080, "threefry2x32",
         "scan", 8),
        ("scan-threefry-u8-big", 65536, 4320, "threefry2x32", "scan", 8),
        ("scan2-threefry-u8", 65536, 1080, "threefry2x32", "scan2", 8),
        ("scan2-threefry-u20", 65536, 1080, "threefry2x32", "scan2", 20),
        ("scan2-threefry-u8-x4chains", 262144, 1080, "threefry2x32",
         "scan2", 8),
        ("scan2-threefry-u8-x16chains", 1048576, 1080, "threefry2x32",
         "scan2", 8),
        ("wide-threefry", 65536, 1080, "threefry2x32", "wide", 8),
        ("wide-threefry-x4chains", 262144, 1080, "threefry2x32", "wide", 8),
        ("wide-threefry-big", 65536, 4320, "threefry2x32", "wide", 8),
    ]
    n_blocks, n_rounds = (4, 3) if platform == "tpu" else (2, 1)
    for label, n, bs, prng, impl, unroll in variants:
        try:
            cfg = _make_cfg(max(n // scale, 8),
                            n_blocks * n_rounds + 1, block_s=bs,
                            prng_impl=prng, block_impl=impl,
                            scan_unroll=unroll)
            sim = Simulation(cfg)
            c_s, dt, rate = _timed_reduce_run(sim, n_blocks, n_rounds)
            cost = _hot_jit_cost(sim)
            doc = {
                "label": label, "platform": platform,
                "rate": round(rate, 1), "compile_s": round(c_s, 1),
                "best_round_wall_s": round(dt, 3),
                "impl": _impl_label(sim),
                "n_chains": cfg.n_chains, "block_s": bs, "unroll": unroll,
                **cost,
            }
            doc["run_report"] = _bench_report(
                "bench.sweep", config=cfg, plan=_plan_doc(sim.plan),
                timing=_bench_timing(c_s, dt, n_blocks, rate),
                headline={"site_seconds_per_s": doc["rate"],
                          "variant": label},
            )
            _persist_partial({"phase": "sweep", **doc})
            print(json.dumps(doc), flush=True)
            # free device state/executable before the next variant
            # compiles — resident sims measured ~30x degradation on the
            # tunnel TPU (PERF_ANALYSIS §7a fact 2)
            del sim
        except Exception as e:
            sim = None
            print(json.dumps({"label": label, "error": str(e)[:200]}),
                  flush=True)


def profile(out_dir: str) -> None:
    """Capture a jax.profiler trace of steady headline blocks.

    The trace is only device evidence if it actually ran on the device
    it claims (round 5's profile_r05 "TPU" traces were silently
    CPU-fallback): the platform guard records the traced backend in
    ``trace_manifest.json`` and this mode exits rc=4 on a mismatch with
    the expected platform (env TMHPVSIM_PROFILE_EXPECT, default tpu) so
    battery scripts cannot archive a CPU trace as a TPU artifact."""
    platform, fallback = _probe_or_fallback()
    expect = os.environ.get("TMHPVSIM_PROFILE_EXPECT", "tpu")
    n_chains = N_CHAINS if platform == "tpu" else CPU_N_CHAINS
    from tmhpvsim_tpu.engine import Simulation
    from tmhpvsim_tpu.obs.profiler import read_manifest

    sim = Simulation(_make_cfg(n_chains, 4))
    c_s, dt, rate = _timed_reduce_run(sim, 3, 1, profile_dir=out_dir,
                                      expect_platform=expect)
    manifest = read_manifest(out_dir)
    mismatch = bool(manifest and manifest.get("platform_mismatch"))
    doc = {
        "artifact": "profiler trace", "dir": out_dir,
        "platform": platform, "rate": round(rate, 1),
        "compile_s": round(c_s, 1),
        "expected_platform": expect,
        "traced_platform": (manifest or {}).get("traced_platform"),
        "platform_mismatch": mismatch,
    }
    doc["run_report"] = _bench_report(
        "bench.profile", config=sim.config, plan=_plan_doc(sim.plan),
        timing=_bench_timing(c_s, dt, 3, rate), profile=manifest,
        headline={"site_seconds_per_s": doc["rate"]},
    )
    print(json.dumps(doc), flush=True)
    if mismatch:
        print(f"# platform_mismatch: trace in {out_dir} captured "
              f"{(manifest or {}).get('traced_platform')!r}, expected "
              f"{expect!r} — not device evidence (set "
              "TMHPVSIM_PROFILE_EXPECT to override)", file=sys.stderr)
        sys.exit(4)


#: attribution-bench block length: long enough that the per-minute scan
#: body gets real weight against the per-block markov window draw (at
#: 240 s the markov rejection whiles flooded the profiler's 1M-event
#: cap and the scan body fell off the end of the trace), short enough
#: that four scoped variants land in a few minutes on the CPU fallback
ATTR_BLOCK_S = 600

#: the attribution matrix: the all-defaults scan2 baseline plus one
#: variant per static-v1 lever axis obs/cost.py prices, so every
#: factor's claimed phase gets checked by a measured diff
ATTR_BASELINE = "scan2-threefry"
ATTR_LEVERS = ("scan2-stride60", "scan2-rngblock", "scan2-table")


def _attr_capture(name: str, base_dir: str, grid,
                  n_dispatches: int = 1) -> dict | None:
    """One variant's scoped capture (engine attribution_capture): a
    phase_obs='on' sim on the site grid (per-site device geometry — the
    shared-site path hoists geometry to the host, leaving nothing for
    geom_stride to move), warm-up compile OUTSIDE the trace, traced
    dispatches of the SAME compiled executable, the phase map written
    from that executable's HLO, and the attribution doc.  Returns None
    on failure — one variant dying must not cost the others' phase
    splits."""
    from tmhpvsim_tpu.engine import Simulation

    kw = {k: v for k, v in VARIANT_CFGS[name].items() if k != "_probe"}
    d = os.path.join(base_dir, name)
    try:
        sim = Simulation(_make_cfg(len(grid), 3, block_s=ATTR_BLOCK_S,
                                   site_grid=grid, phase_obs="on", **kw))
        doc, stats = sim.attribution_capture(d, n_dispatches=n_dispatches)
        rate = (len(grid) * ATTR_BLOCK_S * stats["n_dispatches"]
                / stats["traced_wall_s"])
        return {"sim": sim, "compile_s": stats["compile_s"],
                "steady_s": stats["traced_wall_s"],
                "rate": rate, "attribution": doc}
    except Exception as e:
        print(f"# attr variant {name} failed: {e}", file=sys.stderr)
        return None


def attribution_bench(out_dir: str) -> None:
    """Semantic phase attribution over the priced lever matrix.

    For the all-defaults scan2 baseline and one variant per static-v1
    lever axis (ATTR_LEVERS), capture a short phase-scoped device
    trace, split device time across the semantic phases
    (obs/attribution.py), and emit per-lever diffs against the
    baseline — "scan2-stride60 cut geometry share from X% to Y%".
    The artifact embeds a v15 run_report whose ``attribution`` section
    is the baseline's phase split and whose ``cost.model_error``
    factor rows (when the basis is measured) carry the measured share
    of each axis's claimed phase."""
    import jax

    from tmhpvsim_tpu.config import SiteGrid
    from tmhpvsim_tpu.obs import attribution

    platform, fallback = _probe_or_fallback()
    # CPU traces emit one event per while-body thunk per iteration, so
    # the shape must stay under the profiler's 1M-event cap; TPU traces
    # are far sparser and afford the full-width grid + a second dispatch
    side, n_disp = (64, 2) if platform == "tpu" else (8, 1)
    grid = SiteGrid.regular((45.0, 55.0), (5.0, 15.0), side, side)
    results = {}
    for name in (ATTR_BASELINE,) + ATTR_LEVERS:
        r = _attr_capture(name, out_dir, grid, n_dispatches=n_disp)
        if r is not None:
            results[name] = r
            a = r["attribution"]
            _persist_partial({
                "phase": "attr", "variant": name, "platform": platform,
                "rate": round(r["rate"], 1),
                "basis": a.get("basis") if a else None,
            })
    doc = {
        "artifact": "phase attribution", "dir": out_dir,
        "platform": platform, "n_sites": len(grid),
        "block_s": ATTR_BLOCK_S, "baseline": ATTR_BASELINE,
        "variants": {}, "diffs": {}, "notes": [],
    }
    base = results.get(ATTR_BASELINE)
    base_attr = base["attribution"] if base else None
    for name, r in results.items():
        a = r["attribution"]
        doc["variants"][name] = {
            "rate": round(r["rate"], 1),
            "compile_s": round(r["compile_s"], 1),
            "attribution": a,
        }
        if name == ATTR_BASELINE or a is None or base_attr is None:
            continue
        diff = attribution.diff_attribution(base_attr, a)
        if diff is not None:
            doc["diffs"][name] = diff
            doc["notes"].extend(
                attribution.describe_diff(name, diff, min_delta=0.005))
    if base is not None:
        sim = base["sim"]
        fracs = attribution.phase_fractions(base_attr)
        doc["run_report"] = _bench_report(
            "bench.attribution", config=sim.config,
            plan=_plan_doc(sim.plan),
            timing=_bench_timing(base["compile_s"], base["steady_s"], 2,
                                 base["rate"]),
            headline={"site_seconds_per_s": round(base["rate"], 1),
                      "baseline": ATTR_BASELINE},
            cost=_config_cost(sim.plan, base["rate"],
                              jax.devices()[0].device_kind,
                              phase_fractions=fracs),
            attribution=base_attr,
        )
    print(json.dumps(doc), flush=True)
    for note in doc["notes"]:
        print(f"# {note}", file=sys.stderr)


def repro(k: int) -> None:
    """Compile-variance probe: run the headline config (scan-threefry,
    N_CHAINS x BLOCK_S, default unroll) K times, each in a FRESH
    subprocess so the remote compile service produces a fresh executable
    every time, and print every trial's rate.  Motivated by round 5's
    observation of a 30x spread between two same-shape, same-code timed
    runs (105 ms/block in the headline process vs 3.5 ms/block in the
    sweep process): if the spread reproduces across fresh compiles, the
    tunnel's compiler is nondeterministic and the honest headline is the
    distribution, not one draw.

    Distribution mode: each trial runs under its OWN simulation seed
    (1000+i, echoed per-trial and listed in the summary), so the spread
    also covers seed-dependent compilation/layout effects, and the
    summary reports min/median/max plus the coefficient of variation —
    the single number a trend tool can threshold on."""
    rates = []
    seeds = []
    consec_non_tpu = 0
    ran = 0
    for i in range(k):
        ran = i + 1
        seed = 1000 + i
        # the compile-variance probe needs a FRESH compile per trial;
        # bench now enables the persistent compile cache by default
        # (main()), so each child must explicitly disable it — a cache
        # hit would measure deserialisation, not compile variance
        env = dict(os.environ, TMHPVSIM_BENCH_ONE_VARIANT="scan-threefry",
                   TMHPVSIM_COMPILE_CACHE="off",
                   TMHPVSIM_BENCH_SEED=str(seed))
        try:
            # Bounded: a wedged-tunnel trial must not hang the probe
            # forever.  The kill does leave a stale tunnel grant that can
            # park the NEXT trial for ~10 min (.claude/skills/verify) —
            # that next trial then waits inside ITS 25-min budget, so the
            # loop still terminates.
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--one-variant"],
                env=env, capture_output=True, text=True, timeout=1500,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            line = next((ln for ln in reversed((r.stdout or "").splitlines())
                         if ln.strip().startswith("{")), None)
            doc = (json.loads(line) if line
                   else {"error": (r.stderr or "")[-200:]})
        except subprocess.TimeoutExpired:
            doc = {"error": "trial timed out (wedged tunnel?)"}
        except json.JSONDecodeError:
            doc = {"error": f"malformed child output: {line[:120]!r}"}
        doc["trial"] = i
        doc.setdefault("seed", seed)
        # TPU rates only: a trial that fell back to CPU would otherwise
        # fabricate a giant "compile variance" spread in the summary
        if doc.get("platform") == "tpu":
            rates.append(doc.get("rate"))
            seeds.append(seed)
            consec_non_tpu = 0
        else:
            consec_non_tpu += 1
        _persist_partial({"phase": "repro", **doc})
        print(json.dumps(doc), flush=True)
        if consec_non_tpu >= 2:
            # two successive trials without a TPU rate — whether from a
            # down tunnel (probe fallback) or repeatedly dying children —
            # mean further ~5-min trials answer nothing: stop; the
            # battery machinery re-runs repro when the tunnel answers
            abort_doc = {"phase": "repro-abort",
                         "reason": "2 consecutive trials without a TPU "
                                   "result (tunnel down, or trials "
                                   "erroring — see their docs above)",
                         "completed": ran, "requested": k}
            _persist_partial(abort_doc)
            print(json.dumps(abort_doc), flush=True)
            break
    ok = sorted(r for r in rates if r)
    if ok:
        summary = {
            "phase": "repro-summary", "platform": "tpu",
            "trials": ran, "requested": k,
            "landed": len(ok), "seeds": seeds,
            "min": ok[0], "median": ok[len(ok) // 2], "max": ok[-1],
        }
        # coefficient of variation (sample stdev / mean): the spread in
        # one dimensionless number — >~0.1 means the compiler (or the
        # tunnel) is the variable, not the code under test
        if len(ok) >= 2:
            mean = sum(ok) / len(ok)
            var = sum((r - mean) ** 2 for r in ok) / (len(ok) - 1)
            summary["cov"] = (round((var ** 0.5) / mean, 4) if mean
                              else None)
        summary["run_report"] = _bench_report(
            "bench.repro",
            headline={"site_seconds_per_s": summary["median"],
                      "min": ok[0], "max": ok[-1], "landed": len(ok),
                      "cov": summary.get("cov")},
            device={"platform": "tpu"},  # summary of TPU-only trials
        )
        print(json.dumps(summary), flush=True)


def one_variant() -> None:
    """One fresh-process timed run of a single variant (repro() worker).
    Variant name from TMHPVSIM_BENCH_ONE_VARIANT (default scan-threefry)."""
    platform, _ = _probe_or_fallback()
    from tmhpvsim_tpu.engine import Simulation

    name = os.environ.get("TMHPVSIM_BENCH_ONE_VARIANT", "scan-threefry")
    # repro()'s distribution mode hands each trial its own seed; default
    # matches _make_cfg's so a bare --one-variant stays byte-stable
    seed = int(os.environ.get("TMHPVSIM_BENCH_SEED", "0"))
    n = N_CHAINS if platform == "tpu" else CPU_N_CHAINS
    nb, nr = (N_BLOCKS, N_ROUNDS) if platform == "tpu" else (CPU_N_BLOCKS, 1)
    kw = {k: v for k, v in VARIANT_CFGS[name].items() if k != "_probe"}
    sim = Simulation(_make_cfg(n, nb * nr + 1, seed=seed, **kw))
    c_s, dt, rate = _timed_reduce_run(sim, nb, nr)
    doc = {
        "variant": name, "platform": platform, "rate": round(rate, 1),
        "compile_s": round(c_s, 1), "best_round_wall_s": round(dt, 3),
        "block_ms": round(dt / nb * 1e3, 2), "n_chains": n,
        "impl": _impl_label(sim), "seed": seed,
    }
    doc["run_report"] = _bench_report(
        "bench.one_variant", config=sim.config, plan=_plan_doc(sim.plan),
        timing=_bench_timing(c_s, dt, nb, rate),
        headline={"site_seconds_per_s": doc["rate"], "variant": name},
    )
    print(json.dumps(doc), flush=True)


def serve_bench(clients: int, requests_per_client: int) -> None:
    """Scenario-serving load generator (serve/): one warm in-process
    server on the local transport, ``clients`` concurrent clients each
    issuing ``requests_per_client`` sequential queries.  The artifact
    line records the coalescing ratio (requests per fused dispatch),
    reply-latency quantiles and the schema-v6 ``serving`` RunReport
    section — the serving analogue of the block-throughput headline."""
    import asyncio

    platform, fallback = _probe_or_fallback()
    from tmhpvsim_tpu.obs import metrics as obs_metrics
    from tmhpvsim_tpu.obs.metrics import quantile_from_snapshot
    from tmhpvsim_tpu.obs.report import resilience_section, serving_section
    from tmhpvsim_tpu.runtime import faults
    from tmhpvsim_tpu.serve.server import (ScenarioClient, ScenarioServer,
                                           ServeConfig)

    # honour $TMHPVSIM_CHAOS so the load generator doubles as a chaos
    # soak driver; no spec = injection compiled out of the hot path
    faults.install_from_env()

    if platform == "tpu":
        n_chains, block_s, n_blocks, unroll = 16384, 1080, 2, 8
    else:
        # CPU: tiny shape + unroll 1 — the scenario jit's compile time
        # scales with unroll x vmapped-fold body, and this artifact
        # measures serving mechanics, not block throughput
        n_chains, block_s, n_blocks, unroll = 64, 60, 2, 1
    sim = _make_cfg(n_chains, n_blocks, block_s=block_s,
                    scan_unroll=unroll)
    reg = obs_metrics.MetricsRegistry()
    url = "local://bench-serve"
    cfg = ServeConfig(sim=sim, url=url, window_s=0.02,
                      max_batch=max(2, clients), timeout_s=600.0)
    counts = {"ok": 0, "err": 0}

    async def one_client(ci: int, c: ScenarioClient) -> None:
        for ri in range(requests_per_client):
            rep = await c.request(
                {"demand_scale": 1.0 + 0.05 * ci,
                 "weather_bias": 1.0 - 0.02 * (ri % 8),
                 "horizon_s": block_s},
                mode="reduce", timeout=600.0)
            counts["ok" if rep.get("ok") else "err"] += 1

    async def run() -> float:
        server = ScenarioServer(cfg, registry=reg)
        await server.start()
        try:
            async with ScenarioClient(url, cfg.exchange) as warm:
                # absorb the per-bucket compiles before the timed load
                await warm.request({"horizon_s": block_s}, timeout=600.0)
            clis = [ScenarioClient(url, cfg.exchange)
                    for _ in range(clients)]
            for c in clis:
                await c.__aenter__()
            try:
                t0 = time.perf_counter()
                await asyncio.gather(*[one_client(i, c)
                                       for i, c in enumerate(clis)])
                return time.perf_counter() - t0
            finally:
                for c in clis:
                    await c.__aexit__(None, None, None)
        finally:
            server.begin_drain()
            await server.stop()

    with obs_metrics.use_registry(reg):
        wall = asyncio.run(run())
    faults.deactivate()
    snap = reg.snapshot()
    serving = serving_section(snap) or {}
    resilience = resilience_section(snap)
    occ = serving.get("occupancy") or {}
    lat = snap.get("histograms", {}).get("serve.reply_latency_s")
    total = clients * requests_per_client
    doc = {
        "artifact": "scenario-serve load",
        "platform": platform,
        "clients": clients,
        "requests_per_client": requests_per_client,
        "ok": counts["ok"], "errors": counts["err"],
        "requests": serving.get("requests"),
        "replies": serving.get("replies"),
        "batches": serving.get("batches"),
        # the serving win in one number: >1 means concurrent requests
        # rode shared fused dispatches
        "coalescing": round(total / serving["batches"], 2)
        if serving.get("batches") else None,
        "occupancy_mean": round(occ["mean"], 2)
        if occ.get("mean") is not None else None,
        "occupancy_max": occ.get("max"),
        "reply_p50_ms": round(1e3 * quantile_from_snapshot(lat, 0.5), 1)
        if lat and lat.get("count") else None,
        "reply_p99_ms": round(1e3 * quantile_from_snapshot(lat, 0.99), 1)
        if lat and lat.get("count") else None,
        "replies_per_s": round(counts["ok"] / wall, 1) if wall else None,
        "wall_s": round(wall, 2),
        # non-null only under $TMHPVSIM_CHAOS / injected recoveries —
        # the v7 'resilience' report section's headline numbers
        "faults_injected": (resilience or {}).get("faults_injected"),
        "retries": (resilience or {}).get("retries"),
        "echo": {"n_chains": n_chains, "block_s": block_s,
                 "window_ms": cfg.window_s * 1e3,
                 "max_batch": cfg.max_batch, "scan_unroll": unroll},
    }
    try:
        from tmhpvsim_tpu.obs.report import RunReport

        rep = RunReport("bench.serve", config=sim)
        rep.attach_metrics(reg)
        rep.headline = {"replies_per_s": doc["replies_per_s"],
                        "coalescing": doc["coalescing"]}
        doc["run_report"] = rep.doc()
    except Exception as e:  # the load numbers must survive a report bug
        print(f"# run_report build failed (bench.serve): {e}",
              file=sys.stderr)
    _persist_partial({"phase": "serve", **doc})
    print(json.dumps(doc), flush=True)


def serve_fleet_bench(n_workers: int, requests_per_client: int) -> None:
    """Horizontally-scaled serving artifact (serve/fleet.py): the SAME
    sustained load against (A) the single-worker window batcher — the
    serving tier as of the first serve artifact — and (B) a fleet of
    ``n_workers`` continuous-batching warm workers behind the
    shard-affinity router.  Reports the sustained-throughput speedup at
    the client-observed latency quantiles, the per-worker occupancy
    split, and the schema-v16 ``serving.fleet`` RunReport section.

    The load is horizon-mixed (75 % one-block, 25 % full-horizon
    requests): exactly the mix where the window batcher pays the
    longest row's blocks for every row in the batch and continuous
    batching retires the short rows after one block and backfills their
    slots from the queue.  Both phases run the IDENTICAL worker
    template (same buckets, same physics) oversubscribed 2x per worker
    slot, so the only variables are the scheduler and the fleet.
    Replies are keyed by (client, request) and phase B must be
    bit-identical to phase A — the fleet must scale throughput, never
    perturb physics."""
    import asyncio

    platform, fallback = _probe_or_fallback()
    from tmhpvsim_tpu.obs import metrics as obs_metrics
    from tmhpvsim_tpu.runtime import faults
    from tmhpvsim_tpu.serve.fleet import FleetConfig, ServeFleet
    from tmhpvsim_tpu.serve.server import (ScenarioClient, ScenarioServer,
                                           ServeConfig)

    faults.install_from_env()
    if platform == "tpu":
        n_chains, block_s, n_blocks, unroll = 16384, 1080, 4, 8
    else:
        n_chains, block_s, n_blocks, unroll = 64, 60, 4, 1
    sim = _make_cfg(n_chains, n_blocks, block_s=block_s,
                    scan_unroll=unroll)
    # per-worker slot capacity, oversubscribed 6x by the client pool:
    # sustained saturation — continuous backfill always finds queued
    # work the moment a short row retires (the occupancy histogram's
    # right shift), and reply latency is queue-drain dominated, so the
    # faster tier's p95 is the lower one
    worker_batch = 16
    clients = 6 * worker_batch * n_workers
    total = clients * requests_per_client

    def scenario_for(ci: int, ri: int) -> dict:
        # 25 % full-horizon, spread across CLIENTS within each round
        # (ci + ri), so concurrent arrivals are horizon-mixed the way
        # real traffic is — not phase-locked into homogeneous windows
        return {"demand_scale": 1.0 + 0.05 * (ci % 64),
                "weather_bias": 1.0 - 0.02 * (ri % 8),
                "horizon_s": (n_blocks * block_s
                              if (ci + ri) % 4 == 3 else block_s)}

    async def load(url: str, exchange: str):
        """clients x requests_per_client sequential queries; returns
        (wall_s, client-observed latencies, replies by (ci, ri))."""
        lats: list = []
        replies: dict = {}

        async def one_client(ci: int, c: ScenarioClient) -> None:
            for ri in range(requests_per_client):
                t0 = time.perf_counter()
                rep = await c.request(scenario_for(ci, ri),
                                      mode="reduce", timeout=600.0)
                lats.append(time.perf_counter() - t0)
                replies[(ci, ri)] = rep

        clis = [ScenarioClient(url, exchange) for _ in range(clients)]
        for c in clis:
            await c.__aenter__()
        try:
            t0 = time.perf_counter()
            await asyncio.gather(*[one_client(i, c)
                                   for i, c in enumerate(clis)])
            wall = time.perf_counter() - t0
        finally:
            for c in clis:
                await c.__aexit__(None, None, None)
        return wall, lats, replies

    def lat_q(lats, q):
        s = sorted(lats)
        return s[min(len(s) - 1, int(q * len(s)))] if s else None

    # ---- phase A: single worker, window batching (the reference tier)
    base_reg = obs_metrics.MetricsRegistry()
    base_cfg = ServeConfig(sim=sim, url="local://bench-fleet-base",
                           window_s=0.02, max_batch=worker_batch,
                           timeout_s=600.0, batching="window")

    async def run_base():
        server = ScenarioServer(base_cfg, registry=base_reg)
        await server.start()
        try:
            async with ScenarioClient(base_cfg.url,
                                      base_cfg.exchange) as warm:
                await warm.request({"horizon_s": n_blocks * block_s},
                                   timeout=600.0)
            return await load(base_cfg.url, base_cfg.exchange)
        finally:
            server.begin_drain()
            await server.stop()

    with obs_metrics.use_registry(base_reg):
        base_wall, base_lats, base_replies = asyncio.run(run_base())

    # ---- phase B: n_workers continuous workers behind the router
    fleet_reg = obs_metrics.MetricsRegistry()
    fleet_cfg = FleetConfig(
        base=ServeConfig(sim=sim, url="local://bench-fleet",
                         window_s=0.02, max_batch=worker_batch,
                         timeout_s=600.0, starve_limit=2),
        n_workers=n_workers, batching="continuous", auto_respawn=False)
    fleet_holder: dict = {}

    async def run_fleet():
        fleet = ServeFleet(fleet_cfg, registry=fleet_reg)
        await fleet.start()
        try:
            async with ScenarioClient(fleet_cfg.base.url,
                                      fleet_cfg.base.exchange) as warm:
                await warm.request({"horizon_s": n_blocks * block_s},
                                   timeout=600.0)
            out = await load(fleet_cfg.base.url, fleet_cfg.base.exchange)
            fleet_holder["doc"] = fleet.fleet_doc()
            fleet_holder["snapshots"] = fleet.worker_snapshots()
            return out
        finally:
            await fleet.stop()

    with obs_metrics.use_registry(fleet_reg):
        fleet_wall, fleet_lats, fleet_replies = asyncio.run(run_fleet())
    faults.deactivate()

    # ---- bit-identity: same (ci, ri) -> same scenario -> the fleet
    # reply must equal the single-worker reference bit for bit
    mismatches = [k for k in base_replies
                  if base_replies[k].get("result")
                  != fleet_replies.get(k, {}).get("result")]
    base_ok = sum(1 for r in base_replies.values() if r.get("ok"))
    fleet_ok = sum(1 for r in fleet_replies.values() if r.get("ok"))

    def sched_stats(*snaps):
        """(batches, mean device dispatch ms, mean rows per dispatch)
        summed across the given registry snapshots."""
        batches = 0
        d_sum = d_cnt = 0.0
        o_sum = o_cnt = 0.0
        for snap in snaps:
            batches += snap.get("counters", {}).get(
                "serve.batches_total", 0)
            h = snap.get("histograms", {}).get("serve.dispatch_s") or {}
            d_sum += h.get("sum") or 0.0
            d_cnt += h.get("count") or 0
            o = snap.get("histograms", {}).get(
                "serve.batch_occupancy") or {}
            o_sum += o.get("sum") or 0.0
            o_cnt += o.get("count") or 0
        return (batches,
                round(1e3 * d_sum / d_cnt, 1) if d_cnt else None,
                round(o_sum / o_cnt, 2) if o_cnt else None)

    base_batches, base_dms, base_occ = sched_stats(base_reg.snapshot())
    fleet_batches, fleet_dms, fleet_occ = sched_stats(
        *[snap for _name, snap in fleet_holder.get("snapshots", [])])
    base_rps = base_ok / base_wall if base_wall else None
    fleet_rps = fleet_ok / fleet_wall if fleet_wall else None
    fdoc = fleet_holder.get("doc") or {}
    doc = {
        "artifact": "scenario-serve fleet load",
        "platform": platform,
        "workers": n_workers,
        "clients": clients,
        "requests_per_client": requests_per_client,
        "requests": total,
        "bit_identical": not mismatches,
        "mismatches": len(mismatches),
        "baseline": {
            "mode": "window x1", "ok": base_ok,
            "wall_s": round(base_wall, 2),
            "replies_per_s": round(base_rps, 1) if base_rps else None,
            "reply_p50_ms": round(1e3 * lat_q(base_lats, 0.5), 1),
            "reply_p95_ms": round(1e3 * lat_q(base_lats, 0.95), 1),
            "batches": base_batches, "dispatch_ms_mean": base_dms,
            "occupancy_mean": base_occ,
        },
        "fleet": {
            "mode": f"continuous x{n_workers}", "ok": fleet_ok,
            "wall_s": round(fleet_wall, 2),
            "replies_per_s": round(fleet_rps, 1) if fleet_rps else None,
            "reply_p50_ms": round(1e3 * lat_q(fleet_lats, 0.5), 1),
            "reply_p95_ms": round(1e3 * lat_q(fleet_lats, 0.95), 1),
            "batches": fleet_batches, "dispatch_ms_mean": fleet_dms,
            "occupancy_mean": fleet_occ,
            "per_worker": [
                {"name": w["name"], "requests": w["requests"],
                 "batches": w["batches"],
                 "backfilled": w["backfilled"],
                 "occupancy_mean": (round(w["occupancy"]["mean"], 2)
                                    if w.get("occupancy") else None)}
                for w in fdoc.get("workers", [])],
        },
        # the headline: sustained-throughput ratio fleet vs the
        # single-worker window tier under the identical load
        "speedup": (round(fleet_rps / base_rps, 2)
                    if base_rps and fleet_rps else None),
        "echo": {"n_chains": n_chains, "block_s": block_s,
                 "n_blocks": n_blocks, "max_batch": worker_batch,
                 "window_ms": 20.0, "scan_unroll": unroll,
                 "starve_limit": 2,
                 "horizon_mix": f"75% 1-block / 25% {n_blocks}-block"},
    }
    try:
        from tmhpvsim_tpu.obs.report import RunReport

        rep = RunReport("bench.serve-fleet", config=sim)
        rep.attach_metrics(fleet_reg)
        rep.attach_fleet_serving(fleet_reg.snapshot(),
                                 fleet_holder.get("snapshots", []))
        rep.headline = {"speedup": doc["speedup"],
                        "fleet_replies_per_s":
                            doc["fleet"]["replies_per_s"]}
        doc["run_report"] = rep.doc()
    except Exception as e:
        print(f"# run_report build failed (bench.serve-fleet): {e}",
              file=sys.stderr)
    _persist_partial({"phase": "serve-fleet", **doc})
    print(json.dumps(doc), flush=True)


#: worker body for --hosts K: one coordinated CPU process per simulated
#: host (gloo collectives, virtual devices), the same execution model a
#: TPU pod slice uses — and the same harness pattern as
#: tests/test_distributed.py.  Process 0 prints the JSON payload.
_HOSTS_WORKER_SRC = r"""
import json, os, tempfile, time
import jax

n_local = int(os.environ["TMHPVSIM_BENCH_LOCAL_DEVICES"])
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", n_local)
except AttributeError:  # jax < 0.5 spells it as an XLA flag
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n_local}")
try:  # jax < 0.5: cross-process CPU collectives need the gloo opt-in
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except (AttributeError, ValueError):
    pass  # newer jax: gloo is the default

from tmhpvsim_tpu.parallel.distributed import initialize_from_env, mesh_doc
assert initialize_from_env(), "coordinator env vars must initialise"

# throwaway per-worker compile cache: enables the AOT warm-up, whose
# cost_analysis() harvest is the measured cost basis (obs/cost.py)
from tmhpvsim_tpu.engine import compilecache
compilecache.configure(tempfile.mkdtemp(prefix="tmhpvsim-hosts-cache-"))

from tmhpvsim_tpu.config import SimConfig
from tmhpvsim_tpu.obs import pod as obs_pod
from tmhpvsim_tpu.obs.profiler import device_trace
from tmhpvsim_tpu.parallel import ShardedSimulation, make_mesh

n_chains = int(os.environ.get("TMHPVSIM_BENCH_HOSTS_CHAINS", "256"))
m = int(os.environ.get("TMHPVSIM_BENCH_MESH_SCENARIO", "0"))
mesh = make_mesh(scenario_devices=m) if m >= 1 else make_mesh()
cfg = SimConfig(start="2019-09-05 00:00:00", duration_s=3 * 360,
                n_chains=n_chains, seed=0, block_s=360, dtype="float32",
                prng_impl="threefry2x32", output="reduce",
                pod_obs="on")
sim = ShardedSimulation(cfg, mesh=mesh)
trace_dir = tempfile.mkdtemp(prefix="tmhpvsim-hosts-trace-")
t0 = time.perf_counter()
with device_trace(trace_dir, expect_platform="cpu", python_tracer=False):
    red = sim.run_reduced()
wall = time.perf_counter() - t0
ens = sim.ensemble_stats()
rate = n_chains * cfg.duration_s / wall
# collective-vs-compute split from this host's jax.profiler trace
comm = obs_pod.comm_split(trace_dir)
pod = None
if sim._pod is not None:
    if comm:
        sim._pod.attach_comm(comm)
    pod = sim._pod.doc()
if jax.process_index() == 0:
    from tmhpvsim_tpu.obs import cost as obs_cost
    plan = sim.plan
    cost = obs_cost.cost_doc(
        site_s_per_s=rate,
        block_impl=plan.block_impl,
        compute_dtype=getattr(plan, "compute_dtype", None),
        kernel_impl=getattr(plan, "kernel_impl", None),
        rng_batch=getattr(plan, "rng_batch", None),
        geom_stride=getattr(plan, "geom_stride", None),
        device_kind=jax.devices()[0].device_kind,
    )
    print(json.dumps({
        "mesh": mesh_doc(mesh, n_chains=n_chains),
        "rate": round(rate, 1),
        "rate_includes_compile": True,
        "wall_s": round(wall, 2),
        "n_seconds": int(ens["n_seconds"]),
        "pod": pod,
        "cost": cost,
    }), flush=True)
print(f"HOSTOK {jax.process_index()}", flush=True)
"""


def hosts_bench(k: int, mesh_scenario: int = 0) -> None:
    """--hosts K: multi-host mechanics artifact — K coordinated CPU
    processes on this machine, each owning its share of 8 virtual
    devices, joined into one global mesh over gloo.  Validates exactly
    the ``process_count() > 1`` paths a pod slice exercises (distributed
    init, per-host chain carving, cross-host psum) and emits one JSON
    line with the mesh document and the combined rate.  NOT a hardware
    number: every virtual device shares this host's cores."""
    import socket

    if k < 1 or 8 % k != 0:
        raise SystemExit(f"--hosts {k}: must divide 8 virtual devices")
    n_local = 8 // k
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    here = os.path.dirname(os.path.abspath(__file__))
    procs = []
    for pid in range(k):
        env = dict(
            os.environ,
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES=str(k),
            JAX_PROCESS_ID=str(pid),
            JAX_PLATFORMS="cpu",
            TMHPVSIM_BENCH_LOCAL_DEVICES=str(n_local),
            TMHPVSIM_BENCH_MESH_SCENARIO=str(mesh_scenario),
        )
        # the parent's XLA_FLAGS would fight jax_num_cpu_devices, and an
        # eagerly-initialising sitecustomize on PYTHONPATH forbids
        # jax.distributed.initialize (tests/test_distributed.py); cwd on
        # sys.path keeps tmhpvsim_tpu importable without it
        env.pop("XLA_FLAGS", None)
        env.pop("PYTHONPATH", None)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _HOSTS_WORKER_SRC], env=env, cwd=here,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=900)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    inner = None
    for ln in (outs[0][1] or "").splitlines():
        if ln.strip().startswith("{"):
            try:
                inner = json.loads(ln)
            except json.JSONDecodeError:
                pass
    failed = [i for i, (rc, _, _) in enumerate(outs) if rc != 0]
    for i in failed:
        tail = (outs[i][2] or "").strip().splitlines()[-5:]
        print(f"# hosts worker {i} failed rc={outs[i][0]}:",
              *tail, sep="\n# ", file=sys.stderr)
    pod = cost = None
    if inner:
        # the pod/cost sections belong in the schema'd run_report, not
        # the ad-hoc top level
        pod = inner.pop("pod", None)
        cost = inner.pop("cost", None)
    doc = {
        "artifact": "multi-host mechanics (gloo, virtual CPU devices)",
        "hosts": k,
        "local_devices_per_host": n_local,
        "platform": "cpu",
        "workers_ok": k - len(failed),
        "caveat": ("all simulated hosts share this machine's cores; "
                   "validates distributed init + carving + cross-host "
                   "psum mechanics, not hardware scaling"),
        **(inner or {"error": "worker 0 produced no JSON payload"}),
    }
    doc["run_report"] = _bench_report(
        "bench.hosts",
        config={"hosts": k, "local_devices_per_host": n_local,
                "mesh_scenario": mesh_scenario},
        headline={"site_seconds_per_s": doc.get("rate")},
        device={"platform": "cpu"},
        cost=cost, pod=pod,
    )
    _persist_partial({"phase": "hosts", **doc})
    print(json.dumps(doc), flush=True)
    if failed or inner is None:
        sys.exit(1)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config",
                    choices=["1", "2", "3", "3a", "4", "5"],
                    help="one of the BASELINE.md configs; 3a is the "
                         "quick 30-day slice of config 3")
    ap.add_argument("--scaling", action="store_true")
    ap.add_argument("--sweep", action="store_true")
    ap.add_argument("--profile", metavar="DIR")
    ap.add_argument("--attr", metavar="DIR",
                    help="semantic phase attribution: short phase-scoped "
                         "traces (SimConfig.phase_obs) of the scan2 "
                         "baseline + one variant per priced lever axis, "
                         "per-phase device-time split and per-lever diffs "
                         "vs baseline (obs/attribution.py); traces and "
                         "phase maps land under DIR")
    ap.add_argument("--repro", type=int, metavar="K",
                    help="distribution mode: K fresh-process timed runs "
                         "of the headline variant, one seed per run; "
                         "summary reports min/median/max + CoV "
                         "(compile-variance probe)")
    ap.add_argument("--one-variant", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--serve", type=int, metavar="N", default=None,
                    help="scenario-serving load generator: N concurrent "
                         "clients against one warm in-process server "
                         "(serve/); reports coalescing ratio, reply "
                         "latency quantiles and the v6 'serving' report "
                         "section")
    ap.add_argument("--serve-requests", type=int, metavar="R", default=8,
                    help="requests per client in --serve mode (default 8)")
    ap.add_argument("--serve-fleet", type=int, metavar="N", default=None,
                    help="horizontally-scaled serving artifact: the same "
                         "horizon-mixed load against the single-worker "
                         "window batcher and against N continuous-"
                         "batching warm workers behind the shard-"
                         "affinity router (serve/fleet.py); reports the "
                         "sustained-throughput speedup, per-worker "
                         "occupancy and the v16 'serving.fleet' section "
                         "(4N clients x --serve-requests each)")
    ap.add_argument("--fleet-csv", metavar="PATH", default=None,
                    help="heterogeneous-fleet variant from a site CSV "
                         "(fleet/params.py FleetParams.from_csv): prices "
                         "per-site parameters vs the homogeneous run")
    ap.add_argument("--fleet-synth", type=int, metavar="N", default=None,
                    help="heterogeneous-fleet variant: N synthetic sites "
                         "from the seeded national-fleet sampler "
                         "(FleetParams.synthetic)")
    ap.add_argument("--fleet-seed", type=int, default=0,
                    help="sampler seed for --fleet-synth (default 0)")
    ap.add_argument("--telemetry", choices=["off", "light", "full"],
                    default="off",
                    help="in-graph telemetry level for every config this "
                         "invocation runs (obs/telemetry.py; default off "
                         "keeps the headline hot path untouched)")
    ap.add_argument("--analytics", choices=["off", "risk", "full"],
                    default="off",
                    help="on-device fleet-analytics level for every config "
                         "this invocation runs (obs/analytics.py; default "
                         "off keeps the headline hot path untouched)")
    ap.add_argument("--phase-obs", choices=["off", "on"], default="off",
                    help="semantic phase scopes (obs/profiler.py "
                         "phase_scope) in every config this invocation "
                         "runs, so any device trace it captures is "
                         "attributable per phase; default off lowers to "
                         "byte-identical HLO.  --attr turns scopes on for "
                         "its own captures regardless")
    ap.add_argument("--compile-cache", metavar="DIR", default=None,
                    help="persistent XLA compilation-cache base dir (a "
                         "per-device-kind subdir is created under it; "
                         "engine/compilecache.py).  Default: "
                         "$TMHPVSIM_COMPILE_CACHE, else "
                         "~/.cache/tmhpvsim_tpu/xla; 'off' disables")
    ap.add_argument("--assume-tpu", action="store_true",
                    default=os.environ.get("TMHPVSIM_ASSUME_TPU", "")
                    in ("1", "true", "yes"),
                    help="on probe failure, attempt TPU anyway under the "
                         "headline watchdog (rc=3 partial on hang) "
                         "instead of the silent cpu-fallback; also "
                         "TMHPVSIM_ASSUME_TPU=1")
    ap.add_argument("--hosts", type=int, metavar="K", default=None,
                    help="multi-host mechanics artifact: K coordinated "
                         "CPU processes (gloo) sharing 8 virtual "
                         "devices, one global mesh — the simulated pod "
                         "slice from tests/test_distributed.py as a "
                         "bench mode")
    ap.add_argument("--mesh-scenario", type=int, metavar="M", default=0,
                    help="with --hosts: scenario-axis width of the 2-D "
                         "(chains, scenario) mesh (0 = flat 1-D mesh)")
    args = ap.parse_args()
    global TELEMETRY, ANALYTICS, PHASE_OBS, ASSUME_TPU
    TELEMETRY = args.telemetry
    ANALYTICS = args.analytics
    PHASE_OBS = args.phase_obs
    ASSUME_TPU = args.assume_tpu
    # default ON: every mode after the first run starts cache-warm, and
    # the v4 run_report executor section records warm vs cold compiles.
    # --repro children override via TMHPVSIM_COMPILE_CACHE=off (repro()).
    from tmhpvsim_tpu.engine import compilecache

    compilecache.configure(args.compile_cache)
    if args.config:
        {"1": config_1, "2": config_2, "3": config_3, "3a": config_3a,
         "4": config_4, "5": config_5}[args.config]()
    elif args.scaling:
        scaling()
    elif args.sweep:
        sweep()
    elif args.profile:
        profile(args.profile)
    elif args.attr:
        attribution_bench(args.attr)
    elif args.repro is not None:
        repro(args.repro)
    elif args.one_variant:
        one_variant()
    elif args.hosts is not None:
        hosts_bench(args.hosts, args.mesh_scenario)
    elif args.serve is not None:
        serve_bench(args.serve, args.serve_requests)
    elif args.serve_fleet is not None:
        serve_fleet_bench(args.serve_fleet, args.serve_requests)
    elif args.fleet_csv is not None or args.fleet_synth is not None:
        fleet_bench(args.fleet_csv, args.fleet_synth, args.fleet_seed)
    else:
        headline()


if __name__ == "__main__":
    main()
