"""Mixed-precision compute path + tabulated kernels (PR 9).

Covers the two opt-in speed axes and their correctness gates:

* every table/minimax kernel stays inside its PUBLISHED max-ULP bound
  (``models/tables.MAX_ULP``) against the NumPy float64 reference over
  the argument ranges the solar/pv chain actually produces
  (``ARG_RANGES``);
* a ``kernel_impl='table'`` run matches the exact run's end-of-run
  reduce statistics to 1e-5 at FIELD SCALE — the published contract is
  ``max|a-b| / max(max|a|, 1.0) <= 1e-5`` per stat field (per-element
  denominators fail spuriously on extremal stats when a 64-ULP powc
  perturbation switches which element wins an argmin);
* a ``compute_dtype='bf16'`` run auto-escalates telemetry and a
  doctored ensemble bias trips the drift sentinel under strict — the
  safety chain bf16 rides on;
* defaults lower BYTE-IDENTICALLY to explicit f32/exact pins (the new
  axes cost nothing until asked for);
* pre-axis autotuner cache entries load with the f32/exact defaults
  (plan-cache back-compat) and malformed axis values are rejected;
* double-buffered host output yields byte-equal blocks to the
  non-overlapped path.
"""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from tmhpvsim_tpu.config import SimConfig
from tmhpvsim_tpu.engine import Simulation, autotune
from tmhpvsim_tpu.models import clearsky_index as ci
from tmhpvsim_tpu.models import tables
from tmhpvsim_tpu.obs.metrics import MetricsRegistry, use_registry
from tmhpvsim_tpu.obs.sentinel import DriftError


def small_cfg(**kw):
    # 10:00 local start: the solar chain must see daylight, or the table
    # kernels go unexercised and every comparison passes vacuously
    base = dict(
        start="2019-09-05 10:00:00",
        duration_s=7200,
        n_chains=8,
        seed=7,
        block_s=3600,
        dtype="float32",
        block_impl="scan",
    )
    base.update(kw)
    return SimConfig(**base)


# ---------------------------------------------------------------------------
# kernel-level ULP bounds vs the float64 reference
# ---------------------------------------------------------------------------

def _ulp_err(got, ref64: np.ndarray) -> np.ndarray:
    """Error in float32 ULPs at the f64 reference, ULP floored at 1.0's
    (matches how the MAX_ULP bounds are published — tables.py)."""
    ulp = np.maximum(np.spacing(np.abs(ref64).astype(np.float32)),
                     np.spacing(np.float32(1.0)))
    return np.abs(np.asarray(got, np.float64) - ref64) / ulp


N_SAMPLES = 20_000


class TestTableKernelULP:
    @pytest.mark.parametrize("name", sorted(tables.MAX_ULP))
    def test_within_published_bound(self, name):
        k = tables.table_kernels(jnp)
        rng = np.random.default_rng(0)
        if name == "arctan2":
            y = rng.uniform(-1e3, 1e3, N_SAMPLES).astype(np.float32)
            x = rng.uniform(-1e3, 1e3, N_SAMPLES).astype(np.float32)
            err = _ulp_err(k.arctan2(jnp.asarray(y), jnp.asarray(x)),
                           np.arctan2(y.astype(np.float64),
                                      x.astype(np.float64)))
        elif name == "powc":
            lo, hi = tables.ARG_RANGES[name]
            x = rng.uniform(lo, hi, N_SAMPLES).astype(np.float32)
            errs = [_ulp_err(k.powc(jnp.asarray(x), p),
                             x.astype(np.float64) ** p)
                    for p in (-1.7, -1.0, -0.5, -0.1)]
            err = np.concatenate(errs)
        elif name == "spencer_factor":
            doy = np.arange(1, 367, dtype=np.float32)
            err = _ulp_err(k.spencer_factor(jnp.asarray(doy)),
                           tables._spencer_factor64(doy))
        else:
            lo, hi = tables.ARG_RANGES[name]
            x = rng.uniform(lo, hi, N_SAMPLES).astype(np.float32)
            err = _ulp_err(getattr(k, name)(jnp.asarray(x)),
                           getattr(np, name)(x.astype(np.float64)))
        worst = float(np.max(err))
        assert worst <= tables.MAX_ULP[name], (
            f"{name}: worst error {worst:.1f} ULP exceeds published "
            f"bound {tables.MAX_ULP[name]}")

    def test_exact_kernels_are_the_raw_ops(self):
        # the byte-identity discipline rests on this: k.sin IS jnp.sin
        k = tables.exact_kernels(jnp)
        assert k.sin is jnp.sin and k.exp is jnp.exp
        assert k.spencer_factor is None
        # and the set is memoized, so the closure identity is stable
        assert tables.exact_kernels(jnp) is k


# ---------------------------------------------------------------------------
# end-of-run reduce statistics: the 1e-5 field-scale contract
# ---------------------------------------------------------------------------

class TestReduceStatsContract:
    def _acc(self, **kw):
        sim = Simulation(small_cfg(**kw))
        reduced = sim.run_reduced()
        return sim, {k: np.asarray(v, np.float64)
                     for k, v in reduced.items()}

    def test_table_matches_exact_to_1e5_field_scale(self):
        _, a = self._acc()
        sim_t, b = self._acc(kernel_impl="table")
        assert sim_t.plan.kernel_impl == "table"
        # daylight guard: zero pv would make the comparison vacuous
        assert float(np.sum(a["pv_sum"])) > 0.0
        for name in a:
            diff = float(np.max(np.abs(a[name] - b[name])))
            scale = max(float(np.max(np.abs(a[name]))), 1.0)
            assert diff / scale <= 1e-5, (
                f"{name}: field-scale relerr {diff / scale:.3g} > 1e-5")

    def test_bf16_scan_and_scan2_bit_identical(self):
        # the draw plumbing hands compute_dtype straight to jax.random
        # with identical fold_in structure on both scan topologies — the
        # merge bit-exactness contract must survive bf16
        _, a = self._acc(compute_dtype="bf16")
        _, b = self._acc(compute_dtype="bf16", block_impl="scan2")
        for name in a:
            np.testing.assert_array_equal(a[name], b[name], err_msg=name)


# ---------------------------------------------------------------------------
# bf16 rides the sentinel: auto-escalation + strict trip
# ---------------------------------------------------------------------------

class TestBf16Sentinel:
    def test_bf16_auto_escalates_telemetry(self):
        sim = Simulation(small_cfg(compute_dtype="bf16"))
        assert sim.plan.compute_dtype == "bf16"
        assert sim.plan.telemetry == "light"  # was 'off' by default
        # explicit levels are respected, never downgraded
        sim2 = Simulation(small_cfg(compute_dtype="bf16",
                                    telemetry="full"))
        assert sim2.plan.telemetry == "full"

    def test_doctored_bias_trips_strict_sentinel(self, monkeypatch):
        orig = ci.csi_compose_step

        def biased(tables_, x, carry, options, dtype=jnp.float32):
            rc, csi, covered = orig(tables_, x, carry, options, dtype)
            return rc, csi + jnp.asarray(0.5, csi.dtype), covered

        monkeypatch.setattr(ci, "csi_compose_step", biased)
        with use_registry(MetricsRegistry()):
            sim = Simulation(small_cfg(compute_dtype="bf16",
                                       telemetry_strict=True))
            with pytest.raises(DriftError):
                sim.run_reduced()

    def test_run_report_precision_section(self):
        with use_registry(MetricsRegistry()):
            sim = Simulation(small_cfg(kernel_impl="table"))
            sim.run_reduced()
            doc = sim.run_report()
        assert doc["schema_version"] >= 8
        sec = doc["precision"]
        assert sec["kernel_impl"] == "table"
        assert sec["compute_dtype"] == "f32"
        assert doc["plan"]["kernel_impl"] == "table"
        # a defaults run writes NO precision section
        with use_registry(MetricsRegistry()):
            sim = Simulation(small_cfg())
            sim.run_reduced()
            assert sim.run_report()["precision"] is None


# ---------------------------------------------------------------------------
# defaults stay byte-identical
# ---------------------------------------------------------------------------

class TestDefaultHLOIdentity:
    def _scan_text(self, cfg) -> str:
        sim = Simulation(cfg)
        sim.state = sim.init_state()
        acc = sim.init_reduce_acc()
        inputs, _ = sim.host_inputs(0)
        return sim._scan_acc_jit.lower(sim.state, inputs, acc).as_text()

    def test_defaults_lower_identical_to_explicit_pins(self):
        a = self._scan_text(small_cfg())
        b = self._scan_text(small_cfg(compute_dtype="f32",
                                      kernel_impl="exact"))
        assert a == b

    def test_table_pin_changes_the_program(self):
        # the inverse guard: if 'table' lowered identically to 'exact',
        # the axis would be wired to nothing
        a = self._scan_text(small_cfg())
        b = self._scan_text(small_cfg(kernel_impl="table"))
        assert a != b


# ---------------------------------------------------------------------------
# autotuner plan-cache back-compat
# ---------------------------------------------------------------------------

class TestPlanCacheBackCompat:
    def cache_cfg(self, **kw):
        base = dict(start="2019-09-05 10:00:00", duration_s=7200,
                    n_chains=3, seed=7, block_s=3600, dtype="float32",
                    tune="auto")
        base.update(kw)
        return SimConfig(**base)

    def test_pre_axis_entry_loads_with_defaults(self, tmp_path,
                                                monkeypatch):
        path = str(tmp_path / "autotune.json")
        monkeypatch.setenv("TMHPVSIM_AUTOTUNE_CACHE", path)
        cfg = self.cache_cfg()
        # a cache entry persisted before the precision axes existed
        entry = {"plan": {"block_impl": "scan", "scan_unroll": 1,
                          "stats_fusion": "split",
                          "slab_chains": cfg.n_chains}}
        with open(path, "w") as f:
            json.dump({autotune.plan_key(cfg): entry}, f)
        before = autotune.PROBE_COUNT
        plan = autotune.resolve_plan(cfg)
        assert autotune.PROBE_COUNT == before  # pure cache hit
        assert plan.source == "cache"
        assert plan.compute_dtype == "f32"
        assert plan.kernel_impl == "exact"

    def test_malformed_axis_values_rejected(self):
        entry = {"plan": {"block_impl": "scan", "scan_unroll": 1,
                          "stats_fusion": "split", "slab_chains": 3,
                          "compute_dtype": "f16"}}
        with pytest.raises(ValueError, match="malformed"):
            autotune._plan_from_entry(entry)

    def test_config_pin_overrides_cached_axis(self, tmp_path,
                                              monkeypatch):
        path = str(tmp_path / "autotune.json")
        monkeypatch.setenv("TMHPVSIM_AUTOTUNE_CACHE", path)
        cfg = self.cache_cfg(kernel_impl="table")
        entry = {"plan": {"block_impl": "scan", "scan_unroll": 1,
                          "stats_fusion": "split",
                          "slab_chains": cfg.n_chains}}
        with open(path, "w") as f:
            json.dump({autotune.plan_key(cfg): entry}, f)
        plan = autotune.resolve_plan(cfg)
        assert plan.kernel_impl == "table"  # the pin wins over the cache

    def test_broadcast_plan_round_trips_axes(self):
        plan = autotune.static_plan(
            self.cache_cfg(tune="off", compute_dtype="bf16",
                           kernel_impl="table"))
        out = autotune.broadcast_plan(plan)
        assert out.compute_dtype == "bf16"
        assert out.kernel_impl == "table"
        assert out.telemetry != "off"  # escalation survives the decode


# ---------------------------------------------------------------------------
# double-buffered host output
# ---------------------------------------------------------------------------

class TestOutputOverlap:
    @pytest.mark.parametrize("impl", ["wide", "scan"])
    def test_overlap_matches_off_byte_for_byte(self, impl):
        def blocks(**kw):
            sim = Simulation(small_cfg(duration_s=4 * 3600,
                                       block_impl=impl, **kw))
            return list(sim.run_blocks())

        on = blocks()                       # 'auto': overlapped
        off = blocks(output_overlap="off")  # strictly serial
        assert len(on) == len(off) == 4
        for r_on, r_off in zip(on, off):
            assert r_on.offset == r_off.offset
            np.testing.assert_array_equal(r_on.epoch, r_off.epoch)
            for field in ("meter", "pv", "residual"):
                np.testing.assert_array_equal(getattr(r_on, field),
                                              getattr(r_off, field),
                                              err_msg=field)

    def test_bad_overlap_value_rejected(self):
        with pytest.raises(ValueError, match="output_overlap"):
            Simulation(small_cfg(output_overlap="on"))
