"""Tightened CPU<->JAX parity harness (round-1 verdict items 4/6).

RNG-stream equivalence between scipy/numpy and counter-based ``jax.random``
is impossible (SURVEY.md §7 hard part (c)), so parity means *statistical*
parity — and the round-1 harness only bounded ensemble means loosely
(|dmean| < 0.15 on a mean-1 process).  This file replaces that with tests
that would actually fail on a mis-set sigma or a swapped branch:

* **component-level two-sample KS tests** at large N, where the iid premise
  holds: per-bin Markov step distributions, cloudy-csi draws per cloud-
  cover band, minute/second noise sigmas (golden float64 numpy vs JAX).
  A whole-stream KS would be statistically invalid here: the csi stream's
  hour-scale modes (cloud cover, hourly/daily base samplers) give an
  effective sample size of ~n_chains regardless of stream length, so KS
  p-values on strided streams reject on shared slow-mode noise, not model
  error (measured: identical Markov chains, D=0.013-0.021, p>0.35 at
  N=4000/step — while the composed 16-chain stream shows D=0.08).
* **end-to-end moment parity with self-calibrated tolerance**: the
  golden-vs-JAX pooled mean/std must agree within 4 combined standard
  errors estimated from the per-chain spread — an honest bound that
  tightens automatically as the ensemble grows;
* a **sensitivity counterpart** proving the end-to-end statistic rejects a
  mis-configured model (swapped covered-branches) by a wide margin;
* a quantified **float32-vs-float64 budget**: pathwise over one simulated
  year of the deterministic physics chain; moment-level for the stochastic
  csi path (pathwise is impossible across dtypes: different draw bits).
"""

import datetime as dt

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy import stats as sps

from tmhpvsim_tpu.config import ModelOptions, Site
from tmhpvsim_tpu.data import SANDIA_INVERTER, SAPM_MODULE
from tmhpvsim_tpu.engine.golden import GoldenClearskyIndex
from tmhpvsim_tpu.models import clearsky_index as ci
from tmhpvsim_tpu.models import markov_hourly as mh
from tmhpvsim_tpu.models import pv as pvmod
from tmhpvsim_tpu.models import renewal as rnw
from tmhpvsim_tpu.models import solar
from tmhpvsim_tpu.models.timegrid import TimeGridSpec

N_CHAINS = 16
N_SEC = 2 * 3600
START = dt.datetime(2019, 9, 5, 10, 0)
START_STR = "2019-09-05 10:00:00"


def _golden_ensemble(opts: ModelOptions, seed0: int = 100) -> np.ndarray:
    out = np.empty((N_CHAINS, N_SEC))
    for c in range(N_CHAINS):
        m = GoldenClearskyIndex(START, opts, np.random.default_rng(seed0 + c))
        for i in range(N_SEC):
            out[c, i] = m.next(START + dt.timedelta(seconds=i))
    return out


def _jax_ensemble(opts: ModelOptions, dtype=jnp.float64,
                  seed: int = 3) -> np.ndarray:
    spec = TimeGridSpec.from_local_start(START_STR, N_SEC)
    feats = ci.HostFeatures.from_spec(spec)
    block_idx, (mlo, mhi) = ci.host_block_index(spec, 0, N_SEC, dtype)

    def one(key):
        k_arr, k_min, k_renew, k_scan = jax.random.split(key, 4)
        arrays = ci.build_chain_arrays(k_arr, feats, opts, dtype)
        mvals = ci.minute_noise_values(k_min, arrays["cc"], spec, mlo, mhi,
                                       dtype)
        carry = ci.init_renewal(k_renew, arrays, dtype)
        _, csi, _ = ci.csi_scan_block(k_scan, arrays, mvals, mlo, carry,
                                      block_idx, opts, dtype)
        return csi

    keys = jax.random.split(jax.random.key(seed), N_CHAINS)
    return np.asarray(jax.vmap(one)(keys))


def _gap_se(astat: np.ndarray, bstat: np.ndarray):
    """(|gap|, combined SE) for a per-chain statistic from each ensemble:
    within-chain samples are correlated, so the only safely independent
    unit is the chain and SEs come from the chain-level spread."""
    se = np.sqrt(astat.var(ddof=1) / len(astat)
                 + bstat.var(ddof=1) / len(bstat))
    return abs(astat.mean() - bstat.mean()), se


def _moment_gap_se(a: np.ndarray, b: np.ndarray):
    return _gap_se(a.mean(axis=1), b.mean(axis=1))


def _std_gap_se(a: np.ndarray, b: np.ndarray):
    return _gap_se(a.std(axis=1), b.std(axis=1))


# ---------------------------------------------------------------------------
# component-level two-sample tests (iid-valid, high power)
# ---------------------------------------------------------------------------


N_COMPONENT = 4000
KS_P = 1e-3  # rejects D >~ 0.045 at this N


class TestComponentKS:
    @pytest.mark.parametrize("state", [0.05, 0.2, 0.5, 0.8, 0.95, 0.995])
    def test_markov_step_per_bin(self, state):
        """Each cloud-cover bin's step distribution (AL or Student-t with
        its own loc/scale/kappa/df): a mis-set parameter in any single bin
        fails exactly that bin's case."""
        keys = jax.random.split(jax.random.key(int(state * 1e4)), N_COMPONENT)
        params = mh.step_params(jnp.float64)
        jx = np.asarray(jax.vmap(
            lambda k: mh.transition(k, jnp.float64(state), params,
                                    jnp.float64)
        )(keys))
        rng = np.random.default_rng(int(state * 1e4) + 1)
        gx = np.asarray([mh.transition_numpy(rng, state)
                         for _ in range(N_COMPONENT)])
        d, p = sps.ks_2samp(jx, gx)
        assert p > KS_P, (state, d, p)

    @pytest.mark.parametrize("cc", [0.3, 0.8, 0.95])
    def test_cloudy_csi_draw_per_band(self, cc):
        """The three cloudy-csi regimes (normal / gamma-mid / gamma-high,
        clearskyindexmodel.py:68-84)."""
        keys = jax.random.split(jax.random.key(int(cc * 100)), N_COMPONENT)
        jx = np.asarray(jax.vmap(
            lambda k: ci._cloudy_csi_draw(k, jnp.float64(cc), jnp.float64)
        )(keys))
        rng = np.random.default_rng(int(cc * 100) + 1)
        if cc < 6 / 8:
            gx = rng.normal(ci.CSI_CLOUDY_NORM_LOC, ci.CSI_CLOUDY_NORM_SCALE,
                            N_COMPONENT)
        else:
            a, s = (ci.CSI_CLOUDY_GAMMA_MID if cc < 7 / 8
                    else ci.CSI_CLOUDY_GAMMA_HIGH)
            gx = s * rng.gamma(a, size=N_COMPONENT)
        d, p = sps.ks_2samp(jx, gx)
        assert p > KS_P, (cc, d, p)

    @pytest.mark.parametrize("cc", [0.1, 0.6, 0.95])
    def test_minute_and_second_noise_sigma(self, cc):
        """Minute noise ~ N(1, sqrt(0.9)*(s0+s1*8*cc)); second noise ~
        N(0, sqrt(6)*(s0+s1*8*cc)) with the *clear* sigmas in both branches
        (clearskyindexmodel.py:139-158).  Verified against the analytic
        sigma to 4 standard errors of the sample std."""
        n = N_COMPONENT
        spec = TimeGridSpec.from_local_start(START_STR, 60 * n)
        feats = ci.HostFeatures.from_spec(spec)
        cc_arr = jnp.full((feats.n_hours + 1,), jnp.float64(cc))
        mvals = ci.minute_noise_values(jax.random.key(5), cc_arr, spec, 0,
                                       n, jnp.float64)
        for name, (s0, s1) in (("noise_min_cloudy", ci.NOISE_CLOUDY),
                               ("noise_min_clear", ci.NOISE_CLEAR)):
            sigma = ci.SIGMA_MIN_FACTOR * (s0 + s1 * 8.0 * cc)
            vals = np.asarray(mvals[name])
            se_std = sigma / np.sqrt(2 * (len(vals) - 1))
            assert abs(vals.mean() - 1.0) < 4 * sigma / np.sqrt(len(vals))
            assert abs(vals.std(ddof=1) - sigma) < 4 * se_std, (name, cc)

    @pytest.mark.parametrize("cc", [0.15, 0.4, 0.7, 0.9])
    def test_covered_fraction_per_band(self, cc):
        """The O(1) renewal kernel must track hourly cloud cover in every
        band — including low cc, where the reference's own algorithm is
        infeasible and both implementations deliberately fall back
        (models/renewal.py)."""
        windspeed = 5.0
        horizon = 4 * 3600

        def one(key):
            k0, k1 = jax.random.split(key)
            carry = rnw.init(k0, jnp.float64(cc), jnp.float64(windspeed),
                             jnp.float64)
            us = jax.random.uniform(k1, (horizon,), dtype=jnp.float64)

            def body(c, u):
                c, cov = rnw.step_from_u(c, u, cc, windspeed, jnp.float64)
                return c, cov

            _, covered = jax.lax.scan(body, carry, us)
            return covered.mean()

        keys = jax.random.split(jax.random.key(int(cc * 1000)), 16)
        jax_frac = float(np.mean(np.asarray(jax.vmap(one)(keys))))

        fracs = []
        for s in range(4):
            r = rnw.ReferenceRenewal(cc, windspeed,
                                     np.random.default_rng(50 + s))
            fracs.append(np.mean([next(r) for _ in range(horizon)]))
        ref_frac = float(np.mean(fracs))

        cc_eff = min(cc, rnw.MAX_CLOUDCOVER)
        assert abs(jax_frac - cc_eff) < 0.08, (cc, jax_frac)
        assert abs(ref_frac - cc_eff) < 0.08, (cc, ref_frac)
        assert abs(jax_frac - ref_frac) < 0.08, (cc, jax_frac, ref_frac)


# ---------------------------------------------------------------------------
# end-to-end moment parity + sensitivity
# ---------------------------------------------------------------------------


class TestEndToEnd:
    @pytest.mark.parametrize("opts", [
        ModelOptions(),                              # reference-parity mode
        ModelOptions(swap_covered_branches=True),    # intended-fix mode
    ], ids=["reference-branches", "swapped-branches"])
    def test_mean_parity_4se(self, opts):
        g = _golden_ensemble(opts)
        j = _jax_ensemble(opts)
        gap, se = _moment_gap_se(g, j)
        assert gap < 4 * se, (gap, se)
        sgap, sse = _std_gap_se(g, j)
        assert sgap < 4 * sse, (sgap, sse)

    def test_sensitivity_rejects_swapped_branches(self):
        """Power check: a swapped-branch model must shift the mean by many
        SEs — the failure the old 0.15 slack would have waved through."""
        g = _golden_ensemble(ModelOptions())
        j = _jax_ensemble(ModelOptions(swap_covered_branches=True))
        gap, se = _moment_gap_se(g, j)
        assert gap > 10 * se, (gap, se)
        assert gap > 0.15, gap  # absolute: covered>90% flips base ~1 -> ~0.5


# ---------------------------------------------------------------------------
# long-horizon composed-stream parity (slow; round-3 verdict item 6)
# ---------------------------------------------------------------------------


N_LONG_CHAINS = 64
N_LONG_H = 26                 # spans a midnight rollover from the 10:00 start
N_LONG_SEC = N_LONG_H * 3600  # and >= ~17 renewal cycles per chain


def _golden_long(opts: ModelOptions, seed0: int = 700):
    """(csi, covered) ensembles from the float64 golden model."""
    csi = np.empty((N_LONG_CHAINS, N_LONG_SEC))
    cov = np.empty((N_LONG_CHAINS, N_LONG_SEC), dtype=np.int8)
    for c in range(N_LONG_CHAINS):
        m = GoldenClearskyIndex(START, opts,
                                np.random.default_rng(seed0 + c))
        for i in range(N_LONG_SEC):
            csi[c, i] = m.next(START + dt.timedelta(seconds=i))
            cov[c, i] = m.last_covered
    return csi, cov


def _jax_long(opts: ModelOptions, seed: int = 8):
    """(csi, covered) ensembles from the JAX scan (float32, the TPU
    production dtype — the moments compare against float64 golden, so this
    doubles as a composed-stream f32 check)."""
    dtype = jnp.float32
    spec = TimeGridSpec.from_local_start(START_STR, N_LONG_SEC)
    feats = ci.HostFeatures.from_spec(spec)
    block_idx, (mlo, mhi) = ci.host_block_index(spec, 0, N_LONG_SEC, dtype)

    def one(key):
        k_arr, k_min, k_renew, k_scan = jax.random.split(key, 4)
        arrays = ci.build_chain_arrays(k_arr, feats, opts, dtype)
        mvals = ci.minute_noise_values(k_min, arrays["cc"], spec, mlo, mhi,
                                       dtype)
        carry = ci.init_renewal(k_renew, arrays, dtype)
        _, csi, covered = ci.csi_scan_block(k_scan, arrays, mvals, mlo,
                                            carry, block_idx, opts, dtype)
        return csi, covered

    keys = jax.random.split(jax.random.key(seed), N_LONG_CHAINS)
    csi, cov = jax.vmap(one)(keys)
    return np.asarray(csi), np.asarray(cov)


def _hourly_covered(cov: np.ndarray) -> np.ndarray:
    """(n_chains, n_hours) per-hour covered fraction."""
    return cov.reshape(cov.shape[0], N_LONG_H, 3600).mean(axis=2)


def _chain_autocorr(x: np.ndarray, lag: int) -> np.ndarray:
    """Per-chain lag autocorrelation of each row."""
    a = x[:, :-lag] - x[:, :-lag].mean(axis=1, keepdims=True)
    b = x[:, lag:] - x[:, lag:].mean(axis=1, keepdims=True)
    num = (a * b).mean(axis=1)
    den = a.std(axis=1) * b.std(axis=1)
    return num / den


@pytest.mark.slow
class TestLongHorizonComposedParity:
    """>= 64 chains over >= 26 h: the composed stream across a midnight
    rollover cascade and many renewal cycles, golden float64 vs the JAX
    float32 scan.  Beyond the 2 h moment test above, this pins the
    *temporal structure*: the hourly covered-fraction trajectory and the
    minute/hour-scale autocorrelation of csi — exactly where a subtle
    renewal/composition interaction bug (the one place the TPU kernel
    deviates from the reference's rejection heuristic, models/renewal.py)
    would hide from short-window moments."""

    _cache: dict = {}

    @classmethod
    def _ensembles(cls):
        if not cls._cache:
            cls._cache["g"] = _golden_long(ModelOptions())
            cls._cache["j"] = _jax_long(ModelOptions())
        return cls._cache["g"], cls._cache["j"]

    def test_moments(self):
        (g, _), (j, _) = self._ensembles()
        gap, se = _moment_gap_se(g, j)
        assert gap < 4 * se, (gap, se)
        sgap, sse = _std_gap_se(g, j)
        assert sgap < 4 * sse, (sgap, sse)

    def test_covered_fraction_trajectory(self):
        """Ensemble-mean hourly covered fraction, hour by hour: 5 combined
        SEs per hour (26 comparisons), 4 SEs on the overall mean."""
        (_, gc), (_, jc) = self._ensembles()
        gh, jh = _hourly_covered(gc), _hourly_covered(jc)
        for h in range(N_LONG_H):
            gap, se = _gap_se(gh[:, h], jh[:, h])
            assert gap < 5 * se, (h, gap, se)
        gap, se = _gap_se(gh.mean(axis=1), jh.mean(axis=1))
        assert gap < 4 * se, (gap, se)

    @pytest.mark.parametrize("lag", [60, 3600], ids=["minute", "hour"])
    def test_autocorrelation(self, lag):
        """Minute- and hour-scale autocorrelation of the composed csi
        stream: golden vs JAX within 4 combined SEs of the chain spread.
        Sanity-anchored: both must show strong minute-scale correlation
        (the interpolated-sampler structure), decaying with lag."""
        (g, _), (j, _) = self._ensembles()
        ga, ja = _chain_autocorr(g, lag), _chain_autocorr(j, lag)
        gap, se = _gap_se(ga, ja)
        assert gap < 4 * se, (lag, gap, se, ga.mean(), ja.mean())
        # sanity anchor: strong minute-scale structure (interpolated
        # samplers), weaker-but-present hour-scale structure (measured:
        # golden ~0.45 at 60 s, ~0.11 at 3600 s)
        floor = 0.2 if lag == 60 else 0.02
        assert ga.mean() > floor and ja.mean() > floor, (lag, ga.mean(),
                                                         ja.mean())

    def test_rejects_iid_hourly_fault(self):
        """Power check: the reference's accidental i.i.d. near-overcast
        hourly sampler (persistent_cloud_chain=False) — a fault invisible
        to any single-hour statistic — must be rejected by the long-
        horizon covered trajectory by a wide margin."""
        (_, gc), _ = self._ensembles()
        _, jc = _jax_long(ModelOptions(persistent_cloud_chain=False),
                          seed=9)
        gh, jh = _hourly_covered(gc), _hourly_covered(jc)
        gap, se = _gap_se(gh.mean(axis=1), jh.mean(axis=1))
        assert gap > 10 * se, (gap, se)


# ---------------------------------------------------------------------------
# float32 budget
# ---------------------------------------------------------------------------


class TestFloat32Budget:
    def test_physics_pathwise_year(self):
        """One simulated year of the deterministic chain (geometry + PV
        electrical) at hourly cadence: float32 vs float64 on identical csi
        inputs — the end-to-end precision budget of everything except the
        stochastic draws."""
        t0 = 1546300800  # 2019-01-01 00:00 UTC
        epoch = np.arange(t0, t0 + 365 * 86400, 3600, dtype=np.float64)
        doy = ((epoch - t0) // 86400 + 1).astype(np.float64)
        site = Site()
        rng = np.random.default_rng(9)
        csi = rng.uniform(0.05, 1.2, size=epoch.shape)

        geom64 = solar.block_geometry(epoch, doy, site, xp=np)
        ac64 = pvmod.power_from_csi(csi, geom64, SAPM_MODULE,
                                    SANDIA_INVERTER, xp=np)

        geom32 = {k: (v.astype(np.float32) if isinstance(v, np.ndarray)
                      else np.float32(v)) for k, v in geom64.items()}
        ac32 = pvmod.power_from_csi(csi.astype(np.float32), geom32,
                                    SAPM_MODULE, SANDIA_INVERTER, xp=np)

        err = np.abs(ac32.astype(np.float64) - ac64)
        # Budget on a ~250 W plant over 8760 hourly samples spanning all
        # seasons: worst-case sub-watt, mean centi-watt.
        assert err.max() < 1.0, err.max()
        assert err.mean() < 0.05, err.mean()
        # and the annual energy integral moves by < 0.01 %
        e64, e32 = ac64.sum(), ac32.astype(np.float64).sum()
        assert abs(e32 - e64) / e64 < 1e-4

    def test_csi_moments_f32_vs_f64(self):
        """The stochastic path cannot be compared pathwise across dtypes
        (different draw bits); its float32 moments must match float64
        within the ensemble's own sampling error."""
        j64 = _jax_ensemble(ModelOptions(), jnp.float64)
        j32 = _jax_ensemble(ModelOptions(), jnp.float32, seed=4)
        gap, se = _moment_gap_se(j64, j32)
        assert gap < 4 * se, (gap, se)
        sgap, sse = _std_gap_se(j64, j32)
        assert sgap < 4 * sse, (sgap, sse)
