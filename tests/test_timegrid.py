"""Time grid semantics vs a direct per-second datetime reference loop.

The reference derives fractions and rollovers from local `datetime` fields
(clearskyindexmodel.py:113-126); here we verify our vectorised modular
arithmetic reproduces a straightforward datetime loop exactly, including
across the European DST transitions.
"""

import datetime as dt
from zoneinfo import ZoneInfo

import numpy as np
import pytest

from tmhpvsim_tpu.models.timegrid import TimeGridSpec


def _golden_fields(start: dt.datetime, n: int, tz: str):
    """Per-second local fields computed the reference's way (datetime objects)."""
    z = ZoneInfo(tz)
    t0 = start.replace(tzinfo=z) if start.tzinfo is None else start
    epoch0 = int(t0.timestamp())
    out = []
    for i in range(n):
        t = dt.datetime.fromtimestamp(epoch0 + i, z)
        minf = t.second / 60
        hourf = (t.minute + minf) / 60
        dayf = (t.hour + hourf) / 24
        out.append((t.day, t.hour, t.minute, minf, hourf, dayf))
    return out


@pytest.mark.parametrize(
    "start,n",
    [
        ("2019-09-05 12:00:00", 7200),
        ("2019-09-05 23:58:30", 300),          # day rollover, offset phase
        ("2019-03-31 01:59:00", 7200),         # DST forward (02:00 -> 03:00 CEST)
        ("2019-10-27 01:59:00", 2 * 3600 + 300),  # DST backward (03:00 -> 02:00)
    ],
)
def test_fields_match_datetime_loop(start, n):
    spec = TimeGridSpec.from_local_start(start, n, "Europe/Berlin")
    blk = spec.block(0, n)
    golden = _golden_fields(dt.datetime.fromisoformat(start), n, "Europe/Berlin")

    for i in range(n):
        day, hour, minute, minf, hourf, dayf = golden[i]
        assert blk.min_fraction[i] == pytest.approx(minf)
        assert blk.hour_fraction[i] == pytest.approx(hourf)
        assert blk.day_fraction[i] == pytest.approx(dayf)
        if i > 0:
            pd, ph, pm = golden[i - 1][:3]
            assert blk.new_day[i] == (day != pd), i
            assert blk.new_hour[i] == (hour != ph), i
            assert blk.new_min[i] == (minute != pm), i
        else:
            assert not (blk.new_day[i] or blk.new_hour[i] or blk.new_min[i])

    # indices are cumulative rollover counts
    assert np.array_equal(blk.day_idx, np.cumsum(blk.new_day))
    assert np.array_equal(blk.hour_idx, np.cumsum(blk.new_hour))
    assert np.array_equal(blk.min_idx, np.cumsum(blk.new_min))


def test_blockwise_equals_whole():
    n = 10_000
    spec = TimeGridSpec.from_local_start("2019-12-31 22:00:00", n, "Europe/Berlin")
    whole = spec.block(0, n)
    parts = [spec.block(o, 4096) for o in range(0, n, 4096)]
    for name in ("min_idx", "hour_idx", "day_idx", "month0", "doy", "local_sec"):
        got = np.concatenate([getattr(p, name) for p in parts])
        assert np.array_equal(got, getattr(whole, name)), name


def test_minute_value_features_match_block_across_dst():
    """Hour features at minute-draw instants agree with block() features at
    those same seconds — including across the October backward transition,
    where the n_back correction must keep the cc gather index consistent."""
    n = 5 * 3600
    spec = TimeGridSpec.from_local_start("2019-10-27 00:30:00", n, "Europe/Berlin")
    blk = spec.block(0, n)
    lo, hi = 0, int(blk.min_idx[-1]) + 2
    h_idx, h_frac = spec.minute_value_features(lo, hi)
    for i in range(lo, hi):
        if i >= 2:
            rel = 60 * (i - 1) - spec.min_phase
            if rel >= n:
                continue  # value after grid end (the final 'after' draw)
            assert h_idx[i] == blk.hour_idx[rel], i
            assert h_frac[i] == blk.hour_fraction[rel], i
        else:
            assert h_idx[i] == blk.hour_idx[0]
            assert h_frac[i] == blk.hour_fraction[0]
    # the repeated 02:xx hour must not advance hour_idx twice
    assert blk.hour_idx[-1] == 4  # 5 wall-clock hours span only 4 rollovers


def test_interval_counts_cover_indices():
    n = 3 * 86400 + 123
    spec = TimeGridSpec.from_local_start("2019-03-30 17:23:45", n, "Europe/Berlin")
    blk = spec.block(0, n)
    assert blk.min_idx.max() + 1 == spec.n_minute_intervals
    assert blk.hour_idx.max() + 1 == spec.n_hour_intervals
    assert blk.day_idx.max() + 1 == spec.n_day_intervals
    assert blk.month0[0] == 2  # March, 0-based
    assert blk.doy[0] == 89
