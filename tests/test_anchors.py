"""External anchors for the physics chain.

Every other parity test in this suite compares models/solar.py + models/pv.py
against engine/golden.py — which calls the SAME formulas with ``xp=numpy``,
so a shared formula error is invisible to all of them.  This file pins the
chain to values that do NOT come from this repo's code:

* the worked example of the NREL Solar Position Algorithm report
  (Reda & Andreas 2004, NREL/TP-560-34302, §6 "Example"), the standard
  external test point for solar-position implementations;
* the Kasten & Young (1989) relative-airmass formula evaluated by hand at
  table zenith angles;
* Spencer (1971) extraterrestrial-radiation factors (as tabulated in
  Duffie & Beckman, "Solar Engineering of Thermal Processes", eq. 1.4.1b)
  with pvlib 0.6.3's solar constant 1366.1 W/m^2 — the reference's
  ``get_extra_radiation`` default (pvmodel.py:60-66 via pvlib);
* structural identities of the SAPM thermal model (King et al. 2004,
  eq. 11-12) and the Sandia inverter model (King et al. 2007): at the
  rated operating point (Vdco, Pdco) the model yields exactly Paco.

All literal expectations below were computed from the cited publications'
formulas in a fresh numpy session, not from this package.  Tolerances cover
the PSA algorithm's documented ~0.01 deg accuracy vs SPA plus refraction-
model differences — tight enough that any formula drift (wrong constant,
flipped sign, degree/radian slip) fails loudly.
"""

import datetime as dt

import numpy as np
import pytest

from tmhpvsim_tpu.models import solar


class TestSolarPositionSPA:
    """Reda & Andreas (2004) §6: 2003-10-17 12:30:30 local (UTC-7), Denver
    (39.742476 N, 105.1786 W, 1830.14 m, P=820 mbar, T=11 C); published
    topocentric results: zenith 50.11162 deg, azimuth 194.34024 deg
    (eastward from north)."""

    LAT, LON = 39.742476, -105.1786
    EPOCH = dt.datetime(2003, 10, 17, 19, 30, 30,
                        tzinfo=dt.timezone.utc).timestamp()  # 1066419030

    def pos(self):
        e = np.asarray([self.EPOCH], dtype=np.float64)
        return solar.sun_position(e, self.LAT, self.LON, xp=np)

    def test_topocentric_zenith(self):
        # sun_position works in radians throughout (models/solar.py)
        pos = self.pos()
        app_elev = solar.apparent_elevation(
            pos["zenith"], pressure=82000.0, temperature_c=11.0, xp=np,
        )
        app_zenith = 90.0 - np.degrees(float(app_elev[0]))
        assert app_zenith == pytest.approx(50.11162, abs=0.06)

    def test_topocentric_azimuth(self):
        pos = self.pos()
        az_deg = np.degrees(float(pos["azimuth"][0]))
        assert az_deg == pytest.approx(194.34024, abs=0.06)


class TestAirmassKastenYoung:
    """Kasten & Young (1989): AM = 1/(cos z + 0.50572*(96.07995-z)^-1.6364),
    z the apparent zenith in degrees.  Hand-evaluated literals."""

    @pytest.mark.parametrize("zenith, expected, tol", [
        (0.0, 0.9997, 1e-3),
        (30.0, 1.1540, 1e-3),
        (60.0, 1.9943, 1e-3),
        (85.0, 10.3058, 0.01),
    ])
    def test_values(self, zenith, expected, tol):
        am = solar.relative_airmass_kasten_young(
            np.radians(np.asarray([zenith])), xp=np
        )
        assert float(am[0]) == pytest.approx(expected, abs=tol)


class TestExtraRadiationSpencer:
    """Spencer (1971) E0 factor x 1366.1 W/m^2 (pvlib 0.6.3 default
    method='spencer', solar_constant=1366.1).  Hand-evaluated literals."""

    @pytest.mark.parametrize("doy, expected", [
        (1, 1413.98),     # perihelion side: ~+3.5 %
        (100, 1360.79),
        (182, 1320.54),   # aphelion side: ~-3.3 %
        (355, 1412.71),
    ])
    def test_values(self, doy, expected):
        got = solar.extra_radiation_spencer(np.asarray([float(doy)]), xp=np)
        assert float(got[0]) == pytest.approx(expected, abs=0.5)


class TestSAPMThermalAnchor:
    """King et al. (2004) eq. 11-12, open-rack glass/cell/glass mount
    (a=-3.47, b=-0.0594, deltaT=3): at POA=800 W/m^2, wind=0, T_amb=20 C
    the cell temperature is 800*exp(-3.47) + 20 + 0.8*3 = 47.294 C."""

    def test_cell_temp(self):
        from tmhpvsim_tpu.data import SAPM_MODULE
        from tmhpvsim_tpu.models import pv

        t = pv.sapm_cell_temp(np.asarray([800.0]), SAPM_MODULE,
                              wind_speed=0.0, temp_air_c=20.0, xp=np)
        assert float(t[0]) == pytest.approx(47.294, abs=0.01)


class TestSandiaInverterAnchor:
    """King et al. (2007): by construction of the model, AC power at the
    rated operating point (v_dc=Vdco, p_dc=Pdco) is exactly Paco — the C0
    curvature terms cancel.  Any sign/parenthesis drift in the implemented
    polynomial breaks this identity."""

    def test_rated_point_yields_paco(self):
        from tmhpvsim_tpu.data import SANDIA_INVERTER as inv
        from tmhpvsim_tpu.models import pv

        ac = pv.sandia_inverter_ac(
            np.asarray([inv["Vdco"]]), np.asarray([inv["Pdco"]]), inv, xp=np,
        )
        assert float(ac[0]) == pytest.approx(inv["Paco"], rel=1e-9)

    def test_below_startup_power_clips_to_zero(self):
        """Below Pso the inverter draws tare power; the chain clips to 0 W
        exactly like the reference cache fill (pvmodel.py:80)."""
        from tmhpvsim_tpu.data import SANDIA_INVERTER as inv
        from tmhpvsim_tpu.models import pv

        ac = pv.sandia_inverter_ac(
            np.asarray([inv["Vdco"]]), np.asarray([0.5 * inv["Pso"]]),
            inv, xp=np,
        )
        assert float(ac[0]) <= 0.0


class TestAbsoluteWattFixture:
    """Pinned end-to-end AC power at fixed (time, site, csi) inputs — the
    absolute-watt regression anchor for the whole chain (geometry ->
    Ineichen -> DISC -> Hay-Davies -> SAPM -> Sandia inverter).

    Provenance, stated honestly: the vendored module/inverter coefficients
    (data/parameters.py) are NOMINAL same-class values for the reference's
    Hanwha HSL60P6-PA-4-250T + ABB MICRO-0.25-I-OUTD-US-208 products
    (pvmodel.py:13-17) — the exact SAM database rows are not obtainable in
    this environment (no pvlib / SAM CSVs; zero egress).  Until the real
    rows are loaded via data/sam.py, absolute parity with the reference
    PLANT is a calibration question; what this fixture pins is that the
    ENGINE's watt scale never drifts silently: any change to a constant,
    a formula, or a coefficient shifts these values and fails loudly.

    Values computed 2026-07-30 from the float64 numpy chain (xp=np) at the
    default Munich site; sanity: STC p_mp == Impo*Vmpo == 249.754 W and
    every AC value is far below Paco = 250 W.
    """

    # (name, utc_epoch, day_of_year, csi, expected_ac_watts)
    FIXTURE = [
        ("summer_noon_clear", 1561111200, 172, 1.0, 183.188803),
        ("summer_noon_cloudy", 1561111200, 172, 0.35, 58.272738),
        ("winter_morning", 1547541000, 15, 0.9, 75.646413),
        ("autumn_evening", 1567698300, 248, 0.7, 38.470114),
        ("night", 1567638000, 248, 1.0, 0.0),
    ]

    @pytest.mark.parametrize("name,epoch,doy,csi,expect",
                             FIXTURE, ids=[f[0] for f in FIXTURE])
    def test_pinned_ac_watts(self, name, epoch, doy, csi, expect):
        from tmhpvsim_tpu.config import Site
        from tmhpvsim_tpu.data import SANDIA_INVERTER, SAPM_MODULE
        from tmhpvsim_tpu.models import pv as pvmod
        from tmhpvsim_tpu.models import solar

        g = solar.block_geometry(np.asarray([float(epoch)]),
                                 np.asarray([float(doy)]), Site(), xp=np)
        ac = pvmod.power_from_csi(np.asarray([csi]), g, SAPM_MODULE,
                                  SANDIA_INVERTER, xp=np)
        assert float(ac[0]) == pytest.approx(expect, rel=1e-6, abs=1e-6)

    def test_stc_nameplate(self):
        """At STC (Ee = 1 sun, T_cell = 25 C) the SAPM max-power point is
        exactly Impo*Vmpo — and that product is the ~250 W nameplate class
        of the reference module."""
        from tmhpvsim_tpu.data import SAPM_MODULE as mod
        from tmhpvsim_tpu.models import pv as pvmod

        dc = pvmod.sapm_dc(np.asarray([1.0]), np.asarray([25.0]), mod,
                           xp=np)
        assert float(dc["p_mp"][0]) == pytest.approx(
            mod["Impo"] * mod["Vmpo"], rel=1e-12
        )
        assert 240.0 <= mod["Impo"] * mod["Vmpo"] <= 260.0


class TestModuleSTCAnchors:
    """STC anchors on the coefficient TABLE (data/parameters.py): every
    relation here must hold for ANY valid SAM row of the reference's
    hardware class (Hanwha HSL60P6-PA-4-250T, 60-cell 250 W poly-Si;
    pvmodel.py:13-14), so they pin the vendored nominal set AND
    re-validate an exact row swapped in via data/sam.py — the
    MIGRATION.md "verified no-op path".  Bounds are the class's datasheet
    envelope: Pmp 250 W (0/+3%), Voc ~37-38 V, Isc ~8.6-9.0 A, fill
    factor 0.70-0.78, negative voltage / small positive current
    temperature coefficients."""

    def _mod(self):
        from tmhpvsim_tpu.data import SAPM_MODULE

        return SAPM_MODULE

    def test_pmp_within_nameplate_binning(self):
        mod = self._mod()
        pmp = mod["Impo"] * mod["Vmpo"]
        # 250 W nameplate, 0/+3% binning tolerance, plus 1% fitting slack
        assert 247.5 <= pmp <= 258.0

    def test_voc_isc_class_ranges(self):
        mod = self._mod()
        assert mod["Cells_in_Series"] == 60
        assert 36.0 <= mod["Voco"] <= 39.0      # 60-cell poly Voc at STC
        assert 8.4 <= mod["Isco"] <= 9.2        # 250 W-class Isc at STC

    def test_iv_curve_consistency(self):
        """MPP sits inside the IV envelope with a plausible fill factor."""
        mod = self._mod()
        assert mod["Vmpo"] < mod["Voco"]
        assert mod["Impo"] < mod["Isco"]
        ff = (mod["Impo"] * mod["Vmpo"]) / (mod["Isco"] * mod["Voco"])
        assert 0.70 <= ff <= 0.78

    def test_temperature_coefficient_signs(self):
        """Poly-Si signature: voltage falls, current creeps up with T."""
        mod = self._mod()
        assert -0.20 <= mod["Bvoco"] < -0.08    # V/C, 60-cell class
        assert -0.20 <= mod["Bvmpo"] < -0.08
        assert 0.0 <= mod["Aisc"] <= 0.001      # 1/C
        assert -0.0005 <= mod["Aimp"] <= 0.001

    def test_inverter_rated_point_class(self):
        from tmhpvsim_tpu.data import SANDIA_INVERTER as inv

        assert inv["Paco"] == pytest.approx(250.0, rel=0.02)
        eff_rated = inv["Paco"] / inv["Pdco"]
        assert 0.92 <= eff_rated <= 0.99        # micro-inverter CEC class
        assert 0.0 < inv["Pso"] < 5.0
