"""The battery's artifact gate (benchmarks/run_tpu_round5b.sh run_json)
decides which hardware measurements survive as committed files — a
regression silently loses TPU data (it already did once: take 1's 13
sweep entries died in a gitignored journal).  These tests drive the
shell functions directly with a stubbed ``python bench.py``.

Extraction safety: only function DEFINITIONS are sourced (anchored on
``name () {``), and the extracted text is asserted to contain no
battery phase invocations before it is executed — sourcing the
script's tail would RUN the battery against the stub (it did once,
2026-07-31 09:10; the repo survived because the stub broke the gate's
integer comparison, but SCALING.json and BATTERY_DONE had to be
restored)."""

import json
import re
import subprocess
import sys
from pathlib import Path

import pytest

SCRIPT = Path(__file__).resolve().parent.parent / "benchmarks" / \
    "run_tpu_round5b.sh"


def _extract_function(name: str) -> str:
    """The definition of one top-level shell function, nothing else."""
    text = SCRIPT.read_text()
    m = re.search(rf"^{re.escape(name)} \(\) \{{.*?^\}}$", text,
                  re.M | re.S)
    assert m, f"function {name} not found in {SCRIPT}"
    body = m.group(0)
    # belt and braces: the sourced text must define, never invoke
    for ln in body.splitlines():
        assert not re.match(r"^(run_json|tpu_lines)\s+[^()]", ln), \
            f"extraction picked up an invocation line: {ln!r}"
    return body


def _gate(tmp_path: Path, *, rc: int, new_lines, existing_partial=None,
          existing_dest=None):
    """Run run_json against a stubbed `python bench.py` and return the
    resulting (dest, dest.partial, dest.nontpu) parsed contents."""
    dest = tmp_path / "ART.jsonl"
    fake_out = tmp_path / "fake_bench_output.txt"
    fake_out.write_text(
        "\n".join(json.dumps(d) for d in new_lines) + "\n")
    # fake ONLY `python bench.py`; tpu_lines' `python - <file>` and any
    # other python must reach the real interpreter
    stub = tmp_path / "python"
    stub.write_text(
        "#!/bin/bash\n"
        'case "$1" in\n'
        f'  *bench.py) cat "{fake_out}"; exit {rc};;\n'
        f'  *) exec "{sys.executable}" "$@";;\n'
        "esac\n"
    )
    stub.chmod(0o755)
    if existing_partial is not None:
        (tmp_path / "ART.jsonl.partial").write_text(
            "\n".join(json.dumps(d) for d in existing_partial) + "\n")
    if existing_dest is not None:
        dest.write_text(
            "\n".join(json.dumps(d) for d in existing_dest) + "\n")
    funcs = tmp_path / "funcs.sh"
    funcs.write_text(_extract_function("tpu_lines") + "\n" +
                     _extract_function("run_json") + "\n")
    driver = (
        "set -u\n"
        f'cd "{tmp_path}"\n'
        f'LOG="{tmp_path}/gate.log"\n'
        'touch "$LOG"\n'
        f'PATH="{tmp_path}":$PATH\n'
        f'source "{funcs}"\n'
        f'run_json "{dest}" testphase --whatever\n'
    )
    subprocess.run(["bash", "-c", driver], check=True,
                   capture_output=True, text=True, cwd=tmp_path)

    def read(p):
        f = tmp_path / p
        if not f.exists():
            return None
        return [json.loads(ln) for ln in f.read_text().splitlines()
                if ln.strip()]
    return (read("ART.jsonl"), read("ART.jsonl.partial"),
            read("ART.jsonl.nontpu"))


TPU = {"platform": "tpu", "rate": 1.0}
CPU = {"platform": "cpu-fallback", "rate": 2.0}


def test_success_with_tpu_lines_promotes_to_dest(tmp_path):
    dest, partial, nontpu = _gate(tmp_path, rc=0, new_lines=[TPU, TPU])
    assert len(dest) == 2 and partial is None and nontpu is None


def test_failure_with_tpu_lines_keeps_partial(tmp_path):
    dest, partial, nontpu = _gate(tmp_path, rc=1, new_lines=[TPU, CPU])
    assert dest is None and len(partial) == 2 and nontpu is None


def test_non_tpu_output_is_quarantined(tmp_path):
    dest, partial, nontpu = _gate(tmp_path, rc=0, new_lines=[CPU])
    assert dest is None and partial is None and len(nontpu) == 1


def test_poorer_retry_never_clobbers_richer_partial(tmp_path):
    """The take-1 loss mode: a wedged retry with 1 TPU line must not
    replace a 13-line partial from the previous take."""
    rich = [dict(TPU, i=i) for i in range(13)]
    dest, partial, nontpu = _gate(tmp_path, rc=1, new_lines=[TPU],
                                  existing_partial=rich)
    assert dest is None
    assert len(partial) == 13 and partial[0]["i"] == 0
    assert len(nontpu) == 1


def test_richer_retry_supersedes_partial(tmp_path):
    dest, partial, nontpu = _gate(tmp_path, rc=1,
                                  new_lines=[TPU, TPU, TPU],
                                  existing_partial=[TPU])
    assert dest is None and len(partial) == 3


def test_cpu_fallback_success_keeps_richer_partial(tmp_path):
    """rc=0 with few TPU lines (early tunnel drop, CPU tail) must not
    erase a richer partial — only a >= artifact supersedes it."""
    rich = [dict(TPU, i=i) for i in range(5)]
    dest, partial, nontpu = _gate(tmp_path, rc=0, new_lines=[TPU, CPU],
                                  existing_partial=rich)
    assert len(dest) == 2      # the successful artifact is still written
    assert len(partial) == 5   # but the richer partial survives


def test_failed_retry_leaves_prior_success_untouched(tmp_path):
    """A failed rerun after a prior full success must not touch the
    committed artifact (regression guard for any mv-target slip in the
    rc!=0 branches)."""
    prior = [dict(TPU, committed=True), dict(TPU, committed=True)]
    dest, partial, nontpu = _gate(tmp_path, rc=1, new_lines=[CPU],
                                  existing_dest=prior)
    assert len(dest) == 2 and all(d.get("committed") for d in dest)
    assert partial is None and len(nontpu) == 1


def test_full_success_removes_superseded_partial(tmp_path):
    dest, partial, nontpu = _gate(tmp_path, rc=0,
                                  new_lines=[TPU, TPU],
                                  existing_partial=[TPU])
    assert len(dest) == 2 and partial is None


def test_gate_script_parses_and_extraction_is_definition_only():
    subprocess.run(["bash", "-n", str(SCRIPT)], check=True)
    _extract_function("tpu_lines")
    _extract_function("run_json")
