"""Golden streaming model: reference invariants + distributional parity
with the JAX path."""

import datetime as dt

import jax
import numpy as np
import pytest

from tmhpvsim_tpu.config import ModelOptions
from tmhpvsim_tpu.engine.golden import GoldenClearskyIndex, GoldenPVModel


def test_csi_range_invariant_25h():
    """The reference's own soak test: 25 h at 1 Hz from 2019-09-05 12:00,
    every csi in (0, 2) (reference tests/test_clearskyindexmodel.py:1-13).
    Run shortened to 3 h here; the full-length equivalent runs on the JAX
    path (test_clearsky_index.py::test_soak_25h_reference_invariant)."""
    start = dt.datetime(2019, 9, 5, 12, 0)
    model = GoldenClearskyIndex(start, rng=np.random.default_rng(0))
    csi = np.asarray([
        model.next(start + dt.timedelta(seconds=i))
        for i in range(3 * 3600)
    ])
    assert ((csi > 0) & (csi < 2)).all(), (csi.min(), csi.max())


def test_pv_nonnegative_day():
    """Reference invariant (tests/test_pvmodel.py): AC >= 0 over a day.
    Hour-sampled here (the 1 Hz version is the engine's job)."""
    start = dt.datetime(2019, 9, 5, 0, 0)
    model = GoldenPVModel(start, rng=np.random.default_rng(1), cache_s=900)
    # sample one value per 15 min to keep the scalar loop affordable
    vals = [model.next(start + dt.timedelta(seconds=s))
            for s in range(0, 86400, 900)]
    vals = np.asarray(vals)
    assert (vals >= 0).all()
    assert np.isfinite(vals).all()
    assert vals.max() > 10  # a September day generates something


def test_seeded_reproducible():
    start = dt.datetime(2019, 9, 5, 12, 0)
    a = GoldenClearskyIndex(start, rng=np.random.default_rng(7))
    b = GoldenClearskyIndex(start, rng=np.random.default_rng(7))
    sa = [a.next(start + dt.timedelta(seconds=i)) for i in range(600)]
    sb = [b.next(start + dt.timedelta(seconds=i)) for i in range(600)]
    assert sa == sb


def test_distributional_parity_with_jax_path():
    """CPU golden vs JAX csi streams agree in distribution (RNG streams
    cannot match; SURVEY.md §7 hard part (c)): compare mean/std of csi over
    the same 2 h window across an ensemble, KS-style quantile agreement."""
    import jax.numpy as jnp

    from tmhpvsim_tpu.models import clearsky_index as ci
    from tmhpvsim_tpu.models.timegrid import TimeGridSpec

    start = dt.datetime(2019, 9, 5, 10, 0)
    n_sec = 2 * 3600
    opts = ModelOptions()

    # golden ensemble: 8 seeds
    golden = []
    for seed in range(8):
        m = GoldenClearskyIndex(start, opts, np.random.default_rng(seed))
        golden.append([m.next(start + dt.timedelta(seconds=i))
                       for i in range(n_sec)])
    golden = np.asarray(golden)

    # jax ensemble: 8 chains
    spec = TimeGridSpec.from_local_start("2019-09-05 10:00:00", n_sec)
    feats = ci.HostFeatures.from_spec(spec)
    block_idx, (mlo, mhi) = ci.host_block_index(spec, 0, n_sec, jnp.float64)

    def one(key):
        k_arr, k_min, k_renew, k_scan = jax.random.split(key, 4)
        arrays = ci.build_chain_arrays(k_arr, feats, opts, jnp.float64)
        mvals = ci.minute_noise_values(k_min, arrays["cc"], spec, mlo, mhi,
                                       jnp.float64)
        carry = ci.init_renewal(k_renew, arrays, jnp.float64)
        _, csi, _ = ci.csi_scan_block(k_scan, arrays, mvals, mlo, carry,
                                      block_idx, opts, jnp.float64)
        return csi

    keys = jax.random.split(jax.random.key(3), 8)
    jaxcsi = np.asarray(jax.vmap(one)(keys))

    # pooled distribution comparison — loose bounds, these are 8-member
    # ensembles of a heavy-tailed process
    g, j = golden.ravel(), jaxcsi.ravel()
    assert abs(g.mean() - j.mean()) < 0.15, (g.mean(), j.mean())
    assert abs(g.std() - j.std()) < 0.2, (g.std(), j.std())
    for q in (0.1, 0.5, 0.9):
        gq, jq = np.quantile(g, q), np.quantile(j, q)
        assert abs(gq - jq) < 0.25, (q, gq, jq)


def test_compat_mode_iid_cloud_chain():
    """persistent_cloud_chain=False reproduces the reference's accidental
    i.i.d. near-overcast hourly draws: csi stays valid either way."""
    start = dt.datetime(2019, 9, 5, 12, 0)
    model = GoldenClearskyIndex(
        start, ModelOptions(persistent_cloud_chain=False),
        np.random.default_rng(2),
    )
    csi = [model.next(start + dt.timedelta(seconds=i)) for i in range(1800)]
    assert all(0 < c < 2 for c in csi)


def test_monotonic_time_required():
    start = dt.datetime(2019, 9, 5, 12, 0)
    model = GoldenPVModel(start, rng=np.random.default_rng(3), cache_s=120)
    model.next(start + dt.timedelta(seconds=10))
    with pytest.raises(ValueError, match="monotonic"):
        model.next(start - dt.timedelta(seconds=3600))
