"""Runtime primitives: fixedclock grid, funnel join, retry policy, broker."""

import asyncio
import datetime as dt
import math
from collections import namedtuple

import pytest

from tmhpvsim_tpu.runtime import (
    SynchronizingFunnel,
    asyncretry,
    fixedclock,
    forever,
)
from tmhpvsim_tpu.runtime.broker import LocalTransport, make_transport

Data = namedtuple("Data", ["meter", "pv"])


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


class TestFixedclock:
    def test_ideal_grid(self):
        """Yields start + i/rate exactly — never wall time (utils.py:13-45)."""

        async def collect():
            start = dt.datetime(2019, 9, 5, 12, 0, 0)
            return [
                t async for t in fixedclock(rate=1, realtime=False,
                                            start=start, duration_s=5)
            ]

        times = run(collect())
        assert times == [
            dt.datetime(2019, 9, 5, 12, 0, s) for s in range(5)
        ]

    def test_subsecond_rate(self):
        async def collect():
            start = dt.datetime(2019, 9, 5)
            return [
                t async for t in fixedclock(rate=4, realtime=False,
                                            start=start, duration_s=1)
            ]

        times = run(collect())
        assert len(times) == 4
        assert times[1] - times[0] == dt.timedelta(seconds=0.25)

    def test_no_realtime_is_fast(self):
        """The reference's 10 ms floor sleep is deliberately absent: 1000
        ticks must take well under 10 s (utils.py:36; SURVEY.md §6)."""
        import time

        async def collect():
            n = 0
            async for _ in fixedclock(rate=1, realtime=False,
                                      duration_s=1000):
                n += 1
            return n

        t0 = time.perf_counter()
        assert run(collect()) == 1000
        assert time.perf_counter() - t0 < 2.0


class TestFunnel:
    def test_join_emits_only_complete(self):
        async def go():
            out = asyncio.Queue()
            funnel = SynchronizingFunnel(Data, out)
            await funnel.put(1, meter=5.0)
            assert out.empty() and len(funnel) == 1
            await funnel.put(1, pv=2.0)
            assert out.qsize() == 1 and len(funnel) == 0
            return await out.get()

        time, rec = run(go())
        assert (time, rec) == (1, Data(meter=5.0, pv=2.0))

    def test_out_of_order_timestamps(self):
        async def go():
            out = asyncio.Queue()
            funnel = SynchronizingFunnel(Data, out)
            await funnel.put(2, meter=1.0)
            await funnel.put(1, meter=2.0)
            await funnel.put(1, pv=0.5)
            await funnel.put(2, pv=0.25)
            return [await out.get(), await out.get()]

        emitted = run(go())
        assert [t for t, _ in emitted] == [1, 2]  # completion order

    def test_eviction_bounds_cache(self):
        """The reference's unbounded leak (SURVEY.md §5) is fixed: a stalled
        pv stream cannot grow the cache past max_pending."""

        async def go():
            out = asyncio.Queue()
            funnel = SynchronizingFunnel(Data, out, max_pending=100)
            for t in range(500):
                await funnel.put(t, meter=float(t))
            return len(funnel), funnel.n_evicted

        size, evicted = run(go())
        assert size == 100
        assert evicted == 400

    def test_eviction_survives_broken_heap_invariant(self):
        """White-box guard: every cached time is normally heappushed in
        put(), but if that invariant is ever broken (a future direct
        _cache insert), eviction must rebuild the age heap from the
        cache instead of raising IndexError from an empty heap — and
        must still evict oldest-first."""

        async def go():
            out = asyncio.Queue()
            funnel = SynchronizingFunnel(Data, out, max_pending=3)
            for t in range(3):
                await funnel.put(t, meter=float(t))
            # violate the invariant: cached entries with no heap records
            funnel._age_heap.clear()
            await funnel.put(3, meter=3.0)  # must evict t=0, not raise
            return sorted(funnel._cache), funnel.n_evicted

        cached, evicted = run(go())
        assert evicted == 1
        assert cached == [1, 2, 3]  # oldest evicted even with a dry heap

    def test_backpressure_bounds_lookahead(self):
        """A producer must block once it is max_lookahead past the slowest
        other stream, and resume when that stream advances — the guard
        against no-realtime join starvation."""

        async def go():
            out = asyncio.Queue()
            funnel = SynchronizingFunnel(Data, out, max_lookahead=2,
                                         stall_timeout_s=30.0)
            await funnel.put(0, meter=1.0)

            async def pv_producer():
                for t in range(6):
                    await funnel.put(t, pv=float(t))

            task = asyncio.ensure_future(pv_producer())
            await asyncio.sleep(0.05)
            assert not task.done()  # pv blocked at t=3 > meter(0) + 2
            assert len(funnel) >= 3  # but its values WERE delivered
            await funnel.put(1, meter=2.0)  # meter advances -> t=3 admitted
            await asyncio.sleep(0.05)
            await funnel.put(4, meter=3.0)  # admits everything (6 <= 4+2)
            await asyncio.wait_for(task, timeout=5)
            return out.qsize()

        joined = run(go())
        assert joined == 3  # t = 0, 1, 4 had both fields

    def test_backpressure_ignores_stream_that_never_delivered(self):
        """A stream with no values yet has no clock to be ahead of: pv puts
        must not block before the first meter message (until the
        max_initial_pending cache cap)."""
        import time

        async def go():
            out = asyncio.Queue()
            funnel = SynchronizingFunnel(Data, out, max_lookahead=2,
                                         stall_timeout_s=30.0)
            for t in range(50):
                await funnel.put(t, pv=float(t))
            return len(funnel)

        t0 = time.perf_counter()
        assert run(go()) == 50
        assert time.perf_counter() - t0 < 1.0  # no stall waits

    def test_backpressure_initial_pending_cap(self):
        """Before the other stream's first value, a producer may pile up at
        most max_initial_pending records, then must wait — so a
        slow-to-start peer's joinable records aren't evicted; its first
        delivery releases the producer into the normal lookahead window."""

        async def go():
            out = asyncio.Queue()
            funnel = SynchronizingFunnel(Data, out, max_lookahead=100,
                                         stall_timeout_s=30.0,
                                         max_initial_pending=5)

            async def pv_producer():
                for t in range(20):
                    await funnel.put(t, pv=float(t))

            task = asyncio.ensure_future(pv_producer())
            await asyncio.sleep(0.05)
            assert not task.done()
            assert len(funnel) == 6  # cap + the blocked put's own record
            await funnel.put(0, meter=1.0)  # first delivery -> window mode
            await asyncio.wait_for(task, timeout=5)
            return out.qsize()

        assert run(go()) == 1  # t=0 joined

    def test_backpressure_three_streams_dead_plus_live(self):
        """3-stream join, one constraint stream dead and one live: the live
        stream's steady progress must NOT keep resetting the stall clock for
        the dead one pinning min(floors) — the producer must degrade to
        free-run after one timeout instead of blocking forever."""
        import time
        from collections import namedtuple

        Tri = namedtuple("Tri", ["a", "b", "c"])

        async def go():
            out = asyncio.Queue()
            funnel = SynchronizingFunnel(Tri, out, max_lookahead=2,
                                         stall_timeout_s=0.1)
            await funnel.put(0, b=1.0)  # b delivers once, then dies

            async def live_a():
                for t in range(200):
                    await funnel.put(t, a=float(t))
                    await asyncio.sleep(0.005)  # steady 200 Hz progress

            live = asyncio.ensure_future(live_a())
            # c runs far past b(0)+2: must suspend after ~0.1 s, not hang
            for t in range(10):
                await funnel.put(t, c=float(t))
            live.cancel()
            return True

        t0 = time.perf_counter()
        assert run(asyncio.wait_for(go(), timeout=5))
        assert time.perf_counter() - t0 < 2.0

    def test_backpressure_stall_degrades_to_free_run(self):
        """If the other stream goes silent after delivering, backpressure
        must give up after stall_timeout_s (one wait, then suspended)
        instead of hanging the app — a dead meter feed keeps the old
        free-run-and-evict behaviour."""
        import time

        async def go():
            out = asyncio.Queue()
            funnel = SynchronizingFunnel(Data, out, max_lookahead=2,
                                         stall_timeout_s=0.05)
            await funnel.put(0, meter=1.0)  # meter delivers once, then dies
            for t in range(50):
                await funnel.put(t, pv=float(t))
            return out.qsize()

        t0 = time.perf_counter()
        assert run(go()) == 1  # only t=0 joined
        # one stall wait at t=3, then suspended free-run — NOT ~47 waits
        assert time.perf_counter() - t0 < 1.0


class TestAsyncretry:
    def test_retries_then_succeeds(self):
        calls = []

        @asyncretry(attempts=5, delay=0)
        async def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("down")
            return "up"

        assert run(flaky()) == "up"
        assert len(calls) == 3

    def test_exhaustion_propagates(self):
        @asyncretry(attempts=2, delay=0)
        async def bad():
            raise ValueError("nope")

        with pytest.raises(ValueError):
            run(bad())

    def test_fallback_value(self):
        @asyncretry(attempts=1, delay=0, fallback=42)
        async def bad():
            raise ValueError

        assert run(bad()) == 42

    def test_cancellation_is_fatal(self):
        """CancelledError must never be retried (utils.py:78,116-117)."""
        calls = []

        async def go():
            @asyncretry(attempts=forever, delay=0)
            async def loops():
                calls.append(1)
                await asyncio.sleep(3600)

            task = asyncio.get_event_loop().create_task(loops())
            await asyncio.sleep(0.01)
            task.cancel()
            with pytest.raises(asyncio.CancelledError):
                await task

        run(go())
        assert len(calls) == 1


class TestBroker:
    def test_fanout_all_consumers_see_all(self):
        """Fanout semantics: N consumers each get every message
        (pvsim.py:62-63)."""

        async def go():
            t = dt.datetime(2019, 9, 5, 12, 0, 0)
            pub = LocalTransport("local://t1", "meter")
            subs = [LocalTransport("local://t1", "meter") for _ in range(2)]
            received = [[], []]

            async def consume(i):
                async for time, value in subs[i].subscribe():
                    received[i].append((time, value))
                    if len(received[i]) == 3:
                        return

            tasks = [asyncio.create_task(consume(i)) for i in range(2)]
            await asyncio.sleep(0.01)
            for k in range(3):
                await pub.publish(float(k), t + dt.timedelta(seconds=k))
            await asyncio.gather(*tasks)
            return received

        r = run(go())
        assert r[0] == r[1]
        assert [v for _, v in r[0]] == [0.0, 1.0, 2.0]
        assert r[0][0][0] == dt.datetime(2019, 9, 5, 12, 0, 0)

    def test_make_transport_local_default(self):
        assert isinstance(make_transport(None, "meter"), LocalTransport)
        assert isinstance(make_transport("local://x", "m"), LocalTransport)

    def test_amqp_without_aio_pika_raises(self):
        with pytest.raises(RuntimeError, match="aio_pika"):
            make_transport("amqp://localhost:5672/", "meter")
