"""Semantic phase attribution (obs/attribution.py + the v15 report
section):

* scope-path and op-name phase classification, including the
  transform-wrapped paths (``vmap(ph__markov)/while/body/...``) real
  scanned graphs produce;
* ``parse_hlo_phase_map`` — metadata extraction from compiled-HLO text,
  fusion majority inheritance, the computation-unanimity rule for
  unscoped plumbing, and the mixed-computation honesty carve-out;
* ``attribute`` on synthetic Chrome-trace fixtures — gzip'd and plain
  exports, scoped joins via the ``phase_map.json`` sidecar, mixed
  XLA/host threads, the container-op exclusion, the
  fractions-sum-plus-residual-≤-1 invariant, and the graceful
  degradation ladder (scope → opname-heuristic → unavailable+WARN);
* lever diffs (``diff_attribution`` / ``describe_diff``);
* ``validate_attribution_section`` shape rules and the report v15
  round-trip;
* the cost model's v15 phase checks (``model_error`` factor rows gain
  ``phases`` + ``measured_phase_frac``);
* HLO byte-identity: ``phase_obs`` off vs default (and on — the scopes
  live in location metadata, not the lowered text) for scan and scan2,
  with the compiled text carrying ``ph__`` metadata only when on;
* the CPU end-to-end capture: ``Simulation.attribution_capture`` on a
  device-geometry site grid yields a ``basis: "scope"`` split whose
  geometry share strictly drops under ``geom_stride=60``;
* the tools: ``attr_report.py`` validation/degradation and
  ``bench_trend.py``'s ``phases`` column + ``fallback`` marker.
"""

import gzip
import json
import logging
import pathlib
import sys

import pytest

from tmhpvsim_tpu.config import SimConfig, SiteGrid
from tmhpvsim_tpu.engine import Simulation
from tmhpvsim_tpu.obs import attribution as attr
from tmhpvsim_tpu.obs import cost as obs_cost
from tmhpvsim_tpu.obs.attribution import (
    PHASES,
    attribute,
    describe_diff,
    diff_attribution,
    parse_hlo_phase_map,
    phase_fractions,
    phase_of_op_name,
    phase_of_scope_path,
    read_phase_map,
    validate_attribution_section,
    write_phase_map,
)
from tmhpvsim_tpu.obs.metrics import MetricsRegistry
from tmhpvsim_tpu.obs.report import REPORT_SCHEMA_VERSION, validate_report

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))


def scfg(**kw):
    base = dict(
        start="2019-09-05 10:00:00",
        duration_s=120,
        n_chains=4,
        seed=7,
        block_s=60,
        dtype="float32",
        output="reduce",
        block_impl="scan",
        scan_unroll=1,
    )
    base.update(kw)
    return SimConfig(**base)


# ---------------------------------------------------------------------------
# phase classification
# ---------------------------------------------------------------------------


class TestPhaseClassification:
    def test_plain_scope_path(self):
        assert phase_of_scope_path(
            "jit(f)/jit(main)/ph__geometry/sin") == "geometry"

    def test_transform_wrapped_scope(self):
        """Under vmap/while the scope name is wrapped in the transform
        component — substring matching, not path components."""
        assert phase_of_scope_path(
            "jit(f)/jit(main)/vmap(ph__markov)/while/body/add") == "markov"

    def test_innermost_scope_wins(self):
        assert phase_of_scope_path(
            "jit(f)/ph__physics/vmap(ph__rng)/mul") == "rng"

    def test_no_scope_is_none(self):
        assert phase_of_scope_path("jit(f)/jit(main)/while/body/add") is None

    def test_op_name_heuristics(self):
        assert phase_of_op_name("all-reduce.1") == "collectives"
        assert phase_of_op_name("reduce-scatter.2") == "collectives"
        assert phase_of_op_name("threefry2x32.7") == "rng"
        assert phase_of_op_name("fusion.3") is None


# ---------------------------------------------------------------------------
# parse_hlo_phase_map
# ---------------------------------------------------------------------------


_HLO_TEXT = """\
HloModule jit_step

%markov_body (p.0: f32[]) -> f32[] {
  %p.0 = f32[] parameter(0)
  %add.1 = f32[] add(%p.0, %p.0), metadata={op_name="jit(f)/vmap(ph__markov)/while/body/add"}
  %mul.2 = f32[] multiply(%add.1, %add.1), metadata={op_name="jit(f)/vmap(ph__markov)/while/body/mul"}
  ROOT %copy.3 = f32[] copy(%mul.2)
}

%mixed_body (p.1: f32[]) -> f32[] {
  %p.1 = f32[] parameter(0)
  %sine.4 = f32[] sine(%p.1), metadata={op_name="jit(f)/ph__geometry/sin"}
  %exp.5 = f32[] exponential(%sine.4), metadata={op_name="jit(f)/ph__physics/exp"}
  ROOT %copy.6 = f32[] copy(%exp.5)
}

%geom_comp (p.2: f32[]) -> f32[] {
  %p.2 = f32[] parameter(0)
  %cosine.7 = f32[] cosine(%p.2), metadata={op_name="jit(f)/ph__geometry/cos"}
  ROOT %tan.8 = f32[] tan(%cosine.7), metadata={op_name="jit(f)/ph__geometry/tan"}
}

ENTRY %main (arg.0: f32[]) -> f32[] {
  %arg.0 = f32[] parameter(0)
  %fusion.9 = f32[] fusion(%arg.0), kind=kLoop, calls=%geom_comp
  %add.10 = f32[] add(%fusion.9, %fusion.9), metadata={op_name="jit(f)/ph__rng/threefry"}
  ROOT %convert.11 = f32[] convert(%add.10)
}
"""


class TestParseHloPhaseMap:
    def test_scoped_instructions_and_unanimity_inheritance(self):
        pm = parse_hlo_phase_map(_HLO_TEXT)
        assert pm["add.1"] == "markov"
        assert pm["mul.2"] == "markov"
        # unanimity rule: the unscoped while-body carry copy inherits
        # the computation's single phase (the >60%-of-device-time class)
        assert pm["copy.3"] == "markov"
        # parameters never inherit
        assert "p.0" not in pm and "arg.0" not in pm

    def test_mixed_computation_plumbing_stays_unattributed(self):
        pm = parse_hlo_phase_map(_HLO_TEXT)
        assert pm["sine.4"] == "geometry"
        assert pm["exp.5"] == "physics"
        assert "copy.6" not in pm  # mixed phases: no inheritance
        # ENTRY is mixed too (rng + inherited geometry): no inheritance
        assert "convert.11" not in pm

    def test_fusion_inherits_called_computation_majority(self):
        pm = parse_hlo_phase_map(_HLO_TEXT)
        assert pm["fusion.9"] == "geometry"
        assert pm["add.10"] == "rng"

    def test_sidecar_round_trip(self, tmp_path):
        merged = write_phase_map(str(tmp_path), [_HLO_TEXT])
        assert read_phase_map(str(tmp_path)) == merged
        assert read_phase_map(str(tmp_path / "missing")) is None


# ---------------------------------------------------------------------------
# attribute: trace fixtures
# ---------------------------------------------------------------------------


def _write_trace_gz(log_dir, events, host="host0"):
    d = log_dir / "plugins" / "profile" / "2026_08_07"
    d.mkdir(parents=True, exist_ok=True)
    path = d / f"{host}.trace.json.gz"
    with gzip.open(path, "wt", encoding="utf-8") as f:
        json.dump({"traceEvents": events}, f)
    return path


def _write_trace_plain(log_dir, events, name="extra.trace.json"):
    path = log_dir / name
    path.write_text(json.dumps({"traceEvents": events}))
    return path


def _xla_thread_meta(pid=1, tid=2):
    return [
        {"ph": "M", "pid": pid, "name": "process_name",
         "args": {"name": "python3"}},
        {"ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
         "args": {"name": "tf_XLATfrtCpuClient-0"}},
    ]


def _op(name, dur, ts=0, hlo_op=None, pid=1, tid=2):
    ev = {"ph": "X", "pid": pid, "tid": tid, "ts": ts, "dur": dur,
          "name": name}
    if hlo_op:
        ev["args"] = {"hlo_op": hlo_op}
    return ev


class TestAttribute:
    def test_scoped_join_mixed_gzip_and_plain(self, tmp_path, caplog):
        """gzip + plain exports in one dir, scoped joins by hlo_op and by
        event name, host threads ignored, container ops excluded, and
        the fractions-sum invariant."""
        write_phase_map(str(tmp_path), [_HLO_TEXT])
        _write_trace_gz(tmp_path, _xla_thread_meta() + [
            # joined via args.hlo_op (TPU-style export)
            _op("fusion", 400, hlo_op="fusion.9"),           # geometry
            # joined via the event name itself (CPU-style export)
            _op("add.1", 300, ts=400),                       # markov
            # a while container re-spans its body: excluded, not counted
            _op("while", 9999, hlo_op="while.77"),
            # host thread: ignored wholesale
            _op("add.1", 5000, tid=9),
        ])
        _write_trace_plain(tmp_path, _xla_thread_meta(pid=3, tid=4) + [
            _op("copy.3", 200, pid=3, tid=4),                # markov (inherited)
            _op("convert.99", 100, pid=3, tid=4),            # residual
        ])
        with caplog.at_level(logging.WARNING):
            out = attribute(str(tmp_path))
        assert out is not None and out["basis"] == "scope"
        assert out["n_events"] == 4
        assert out["total_device_s"] == pytest.approx(1000e-6)
        assert out["phases"]["markov"]["seconds"] == pytest.approx(500e-6)
        assert out["phases"]["geometry"]["frac"] == pytest.approx(0.4)
        assert out["unattributed_frac"] == pytest.approx(0.1)
        fr = sum(p["frac"] for p in out["phases"].values())
        assert fr + out["unattributed_frac"] == pytest.approx(1.0, abs=1e-4)
        assert validate_attribution_section(out) == []
        assert not caplog.records  # a scoped join warns about nothing

    def test_no_map_degrades_to_opname_heuristic(self, tmp_path):
        _write_trace_gz(tmp_path, _xla_thread_meta() + [
            _op("threefry2x32.1", 250),
            _op("all-reduce.2", 250, ts=250),
            _op("fusion.3", 500, ts=500),
        ])
        out = attribute(str(tmp_path))
        assert out["basis"] == "opname-heuristic"
        assert out["phases"]["rng"]["frac"] == pytest.approx(0.25)
        assert out["phases"]["collectives"]["frac"] == pytest.approx(0.25)
        assert out["unattributed_frac"] == pytest.approx(0.5)
        assert validate_attribution_section(out) == []

    def test_nothing_attributable_is_unavailable_with_warn(
            self, tmp_path, caplog):
        """Scope-less trace of unrecognisable ops: basis 'unavailable',
        one rate-limited WARN, never an exception — and the section
        still validates (satellite: graceful degrade)."""
        _write_trace_gz(tmp_path, _xla_thread_meta() + [
            _op("fusion.1", 600),
            _op("convert.2", 400, ts=600),
        ])
        attr._last_warn[0] = -1e9  # reset the rate limiter
        with caplog.at_level(logging.WARNING,
                             logger="tmhpvsim_tpu.obs.attribution"):
            out = attribute(str(tmp_path))
        assert out["basis"] == "unavailable"
        assert out["phases"] == {}
        assert out["unattributed_frac"] == pytest.approx(1.0)
        assert validate_attribution_section(out) == []
        warns = [r for r in caplog.records
                 if "attribution unavailable" in r.getMessage()]
        assert len(warns) == 1
        # rate-limited: an immediate second call stays quiet
        caplog.clear()
        with caplog.at_level(logging.WARNING,
                             logger="tmhpvsim_tpu.obs.attribution"):
            attribute(str(tmp_path))
        assert not [r for r in caplog.records
                    if "attribution unavailable" in r.getMessage()]
        # and phase_fractions refuses to feed it downstream
        assert phase_fractions(out) is None

    def test_empty_dir_returns_none(self, tmp_path):
        assert attribute(str(tmp_path)) is None


# ---------------------------------------------------------------------------
# lever diffs
# ---------------------------------------------------------------------------


def _attr_doc(fracs, basis="scope"):
    total = 1.0
    phases = {n: {"seconds": f, "frac": f} for n, f in fracs.items()}
    resid = round(total - sum(fracs.values()), 6)
    return {"schema_version": 1, "basis": basis, "total_device_s": total,
            "n_events": 10, "n_scope_events": 8, "phases": phases,
            "unattributed_s": resid, "unattributed_frac": resid}


class TestDiff:
    def test_diff_and_describe(self):
        base = _attr_doc({"geometry": 0.3, "markov": 0.5})
        variant = _attr_doc({"geometry": 0.05, "markov": 0.7})
        d = diff_attribution(base, variant)
        assert d["basis"] == "scope"
        assert d["phases"]["geometry"]["delta_frac"] == pytest.approx(-0.25)
        lines = describe_diff("stride60", d, min_delta=0.01)
        assert any("stride60 cut geometry share from 30.0% to 5.0%" in ln
                   for ln in lines)
        assert any("raised markov" in ln for ln in lines)

    def test_unavailable_side_kills_the_diff(self):
        base = _attr_doc({"geometry": 0.3})
        assert diff_attribution(base, None) is None
        assert diff_attribution(
            base, _attr_doc({}, basis="unavailable")) is None
        assert describe_diff("x", None) == []


# ---------------------------------------------------------------------------
# validate_attribution_section
# ---------------------------------------------------------------------------


class TestValidateSection:
    def test_valid_passes(self):
        assert validate_attribution_section(
            _attr_doc({"rng": 0.2, "physics": 0.7})) == []

    @pytest.mark.parametrize("mutate,needle", [
        (lambda s: s.update(basis="vibes"), "basis"),
        (lambda s: s.update(total_device_s=-1), "total_device_s"),
        (lambda s: s.update(n_events=1.5), "n_events"),
        (lambda s: s.update(phases="x"), "phases"),
        (lambda s: s["phases"]["rng"].update(frac=1.5), "> 1"),
        (lambda s: s.update(unattributed_frac=0.9), "sum to"),
    ])
    def test_mutations_are_caught(self, mutate, needle):
        sec = _attr_doc({"rng": 0.2, "physics": 0.7})
        mutate(sec)
        errs = validate_attribution_section(sec)
        assert errs and any(needle in e for e in errs), errs

    def test_not_a_dict(self):
        errs = validate_attribution_section([1, 2])
        assert len(errs) == 1 and "expected dict" in errs[0]


# ---------------------------------------------------------------------------
# RunReport v15 round-trip + cost phase checks
# ---------------------------------------------------------------------------


class TestReportV15:
    def test_attribution_round_trips(self):
        sim = Simulation(scfg())
        sim.run_reduced()
        doc = sim.run_report()
        assert doc["schema_version"] == REPORT_SCHEMA_VERSION == 16
        assert doc["attribution"] is None  # no capture ran
        doc["attribution"] = _attr_doc({"markov": 0.6, "physics": 0.3})
        validate_report(json.loads(json.dumps(doc)))

    def test_malformed_attribution_is_refused(self):
        sim = Simulation(scfg())
        sim.run_reduced()
        doc = sim.run_report()
        doc["attribution"] = {"basis": "vibes", "phases": {}}
        with pytest.raises(ValueError, match="attribution"):
            validate_report(doc)

    def test_cost_model_error_phase_checks(self):
        doc = obs_cost.cost_doc(site_s_per_s=1e6, block_impl="scan")
        me = obs_cost.model_error_doc(
            doc, doc["flops_per_site_s"] * 1.5, None,
            phase_fractions={"geometry": 0.3, "rng": 0.1,
                             "physics": 0.4, "csi": 0.05})
        gs = me["factors"]["geom_stride"]
        assert gs["phases"] == ["geometry"]
        assert gs["measured_phase_frac"] == pytest.approx(0.3)
        cd = me["factors"]["compute_dtype"]
        assert set(cd["phases"]) == {"physics", "csi"}
        assert cd["measured_phase_frac"] == pytest.approx(0.45)
        assert me["factors"]["block_impl"]["phases"] == []
        # the keys are optional: a v14-style call still validates
        plain = obs_cost.model_error_doc(doc, doc["flops_per_site_s"], None)
        assert "phases" not in plain["factors"]["geom_stride"]
        doc["model_error"] = me
        assert obs_cost.validate_cost(doc) == [], obs_cost.validate_cost(doc)

    def test_publish_phase_gauges(self):
        reg = MetricsRegistry()
        attr.publish_phase_gauges(reg, _attr_doc({"markov": 0.6}))
        text = reg.openmetrics_text()
        assert "device_phase_markov_frac 0.6" in text
        # unavailable docs publish nothing
        reg2 = MetricsRegistry()
        attr.publish_phase_gauges(reg2, _attr_doc({}, basis="unavailable"))
        assert "device_phase" not in reg2.openmetrics_text()


# ---------------------------------------------------------------------------
# HLO byte-identity + compiled-metadata sanity
# ---------------------------------------------------------------------------


class TestHLOIdentity:
    @pytest.mark.parametrize("impl", ["scan", "scan2"])
    def test_lowered_identical_off_vs_default_vs_on(self, impl):
        """phase_obs must cost nothing off (the acceptance bar), and the
        scopes live in location metadata — so even on, the lowered
        TEXT is unchanged; only the compiled module's op_name metadata
        differs (next test)."""

        def lowered(**kw) -> str:
            sim = Simulation(scfg(block_impl=impl, **kw))
            state = sim.init_state()
            acc = sim.init_reduce_acc()
            inputs, _ = sim.host_inputs(0)
            jit = (sim._scan_acc_jit if impl == "scan"
                   else sim._scan2_acc_jit)
            return jit.lower(state, inputs, acc).as_text()

        off = lowered(phase_obs="off")
        assert lowered() == off
        assert lowered(phase_obs="on") == off

    def test_scopes_reach_compiled_metadata_only_when_on(self):
        import jax

        from tmhpvsim_tpu.engine import compilecache

        # the persistent XLA cache's key ignores location metadata, so a
        # warm cache would serve a scope-free executable for the
        # byte-identical "on" program (and vice versa) — compile both
        # uncached.  A bare config update is not enough: jax memoises
        # the is-cache-used decision and the live cache object per
        # process, so the singleton must be reset too.  configure("off")
        # additionally stops Simulation AOT warm-up from seeding the
        # cache; the conftest isolation fixture restores all of it.
        compilecache.configure("off")
        jax.config.update("jax_compilation_cache_dir", None)
        compilecache._reset_cache_singleton()
        on = "".join(Simulation(
            scfg(block_impl="scan2",
                 phase_obs="on")).attribution_hlo_texts())
        off = "".join(Simulation(
            scfg(block_impl="scan2")).attribution_hlo_texts())
        assert "ph__" in on and "ph__" not in off
        pm = parse_hlo_phase_map(on)
        assert pm and set(pm.values()) <= set(PHASES)
        assert {"rng", "markov", "csi", "physics"} <= set(pm.values())


# ---------------------------------------------------------------------------
# CPU end-to-end capture + geom_stride lever diff
# ---------------------------------------------------------------------------


class TestCaptureEndToEnd:
    def test_scoped_capture_and_stride_cuts_geometry(self, tmp_path):
        """The full protocol on a device-geometry site grid: AOT-compile,
        trace the same executables, join — basis 'scope', bounded
        residual — then the geom_stride=60 variant's geometry share
        strictly drops (the acceptance-criteria diff)."""
        grid = SiteGrid.regular((45.0, 55.0), (5.0, 15.0), 2, 2)

        def capture(sub, **kw):
            cfg = scfg(duration_s=240, block_s=120, block_impl="scan2",
                       site_grid=grid, phase_obs="on", **kw)
            sim = Simulation(cfg)
            doc, stats = sim.attribution_capture(str(tmp_path / sub),
                                                 n_dispatches=1)
            assert stats["n_dispatches"] == 1
            return doc

        base = capture("base")
        assert base is not None and base["basis"] == "scope"
        fr = sum(p["frac"] for p in base["phases"].values())
        assert fr + base["unattributed_frac"] <= 1 + 1e-6
        assert base["unattributed_frac"] <= 0.5  # bounded residual
        assert validate_attribution_section(base) == []

        strided = capture("stride", geom_stride=60)
        bf, vf = phase_fractions(base), phase_fractions(strided)
        assert bf.get("geometry", 0.0) > 0.01  # device geometry is real
        assert vf.get("geometry", 0.0) < bf["geometry"]
        d = diff_attribution(base, strided)
        assert d["basis"] == "scope"
        assert d["phases"]["geometry"]["delta_frac"] < 0


# ---------------------------------------------------------------------------
# tools: attr_report + bench_trend columns
# ---------------------------------------------------------------------------


class TestAttrReportTool:
    def _report_doc(self, sec):
        return {"kind": "tmhpvsim_tpu.run_report",
                "schema_version": 16, "attribution": sec}

    def test_valid_sections_print_and_pass(self, tmp_path, capsys):
        import attr_report
        p = tmp_path / "rep.json"
        p.write_text(json.dumps(self._report_doc(
            _attr_doc({"markov": 0.6, "physics": 0.3}))))
        assert attr_report.main([str(p)]) == 0
        out = capsys.readouterr().out
        assert "attribution scope" in out and "markov 60.0%" in out

    def test_attr_artifact_variants_are_checked(self, tmp_path, capsys):
        import attr_report
        doc = {"artifact": "phase attribution", "baseline": "b",
               "variants": {"b": {"attribution": _attr_doc({"rng": 0.9})}}}
        p = tmp_path / "attr.json"
        p.write_text(json.dumps(doc))
        assert attr_report.main([str(p)]) == 0
        assert "[b]" in capsys.readouterr().out

    def test_absent_section_is_fine(self, tmp_path, capsys):
        import attr_report
        p = tmp_path / "old.json"
        p.write_text(json.dumps({"value": 1.0, "platform": "tpu"}))
        assert attr_report.main([str(p)]) == 0
        assert "no attribution section" in capsys.readouterr().out

    def test_malformed_section_fails(self, tmp_path):
        import attr_report
        p = tmp_path / "bad.json"
        p.write_text(json.dumps(self._report_doc(
            {"basis": "vibes", "phases": {}})))
        assert attr_report.main([str(p)]) == 1


class TestBenchTrendColumns:
    def test_fallback_marker(self, tmp_path):
        import bench_trend
        p = tmp_path / "fb.json"
        p.write_text(json.dumps({
            "value": 1.0, "platform": "cpu-fallback",
            "salvaged_after_tpu_failure": True}))
        row = bench_trend.normalize(str(p))
        assert row["fallback"] is True
        assert row["note"].startswith("fallback")
        # a real TPU doc carries no marker
        p2 = tmp_path / "tpu.json"
        p2.write_text(json.dumps({"value": 2.0, "platform": "tpu"}))
        row2 = bench_trend.normalize(str(p2))
        assert row2["fallback"] is False and "note" not in row2

    def test_phases_column_from_attribution(self, tmp_path):
        import bench_trend
        sec = _attr_doc({"markov": 0.48, "physics": 0.34})
        p = tmp_path / "attr.json"
        p.write_text(json.dumps({
            "value": 1.0, "platform": "cpu", "baseline": "b",
            "variants": {"b": {"attribution": sec, "rate": 1.0}}}))
        row = bench_trend.normalize(str(p))
        assert row["attr"] == "markov:48%"
        # pre-v15 docs render '-' (attr None)
        p2 = tmp_path / "old.json"
        p2.write_text(json.dumps({"value": 1.0, "platform": "tpu"}))
        assert bench_trend.normalize(str(p2))["attr"] is None
        # unavailable basis never fills the column
        p3 = tmp_path / "unavail.json"
        p3.write_text(json.dumps({
            "value": 1.0, "platform": "cpu", "baseline": "b",
            "variants": {"b": {"attribution":
                               _attr_doc({}, basis="unavailable")}}}))
        assert bench_trend.normalize(str(p3))["attr"] is None
