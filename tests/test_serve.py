"""Scenario-serving runtime (tmhpvsim_tpu/serve/): schema validation,
micro-batch coalescing, request/reply correlation over all three
transports, batch-of-N vs batch-of-1 bit identity, the e2e acceptance
run (concurrent clients coalesce into fewer dispatches than requests,
every reply bit-identical to a fresh batch-of-1 answer), warm restart
with zero fresh compiles, the schema-v6 ``serving`` report section, and
tools/serve_report.py.

Shapes are tiny (4 chains, 2 blocks of 60 s) with ``scan_unroll=1``:
the scenario jit's compile time scales with unroll x the vmapped fold
body, and these tests exercise serving mechanics, not throughput.
"""

import asyncio
import contextlib
import dataclasses
import datetime as dt
import json
import pathlib
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from tmhpvsim_tpu.config import SimConfig
from tmhpvsim_tpu.engine import Simulation, compilecache
from tmhpvsim_tpu.obs.metrics import MetricsRegistry, use_registry
from tmhpvsim_tpu.obs.report import (
    REPORT_SCHEMA_VERSION,
    RunReport,
    fleet_serving_section,
    serving_section,
    validate_report,
)
from tmhpvsim_tpu.runtime import broker as broker_mod
from tmhpvsim_tpu.runtime.broker import make_transport
from tmhpvsim_tpu.runtime.resilience import CircuitBreaker, ResiliencePolicy
from tmhpvsim_tpu.runtime.tcpbroker import TcpFanoutBroker, _Subscriber
from tmhpvsim_tpu.serve import schema
from tmhpvsim_tpu.serve.batcher import (
    OCCUPANCY_BUCKETS,
    ContinuousBatcher,
    MicroBatcher,
)
from tmhpvsim_tpu.serve.schema import Request, RequestError, Scenario
from tmhpvsim_tpu.serve.server import (
    ScenarioClient,
    ScenarioEngine,
    ScenarioServer,
    ServeConfig,
    default_buckets,
)

# reuse test_amqp's fake aio_pika (registers the fixture here too)
from test_amqp import fake_aio_pika  # noqa: F401

REPO = pathlib.Path(__file__).resolve().parent.parent
SERVE_REPORT = REPO / "tools" / "serve_report.py"
BENCH_TREND = REPO / "tools" / "bench_trend.py"


def _run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def scfg(**kw):
    base = dict(
        start="2019-09-05 10:00:00",
        duration_s=120,
        n_chains=4,
        seed=7,
        block_s=60,
        dtype="float32",
        output="reduce",
        block_impl="scan",
        scan_unroll=1,
    )
    base.update(kw)
    return SimConfig(**base)


def req(rid, scenario, mode="reduce"):
    return Request(id=rid, reply_to="r", mode=mode, scenario=scenario)


@pytest.fixture(scope="module")
def engine():
    """One warm engine for every direct-dispatch test in the module
    (each bucket shape compiles once, on first use)."""
    with use_registry(MetricsRegistry()):
        return ScenarioEngine(scfg(), (1, 4, 8))


# ---------------------------------------------------------------------------
# schema: strict request validation
# ---------------------------------------------------------------------------


class TestSchema:
    def test_defaults_are_neutral(self):
        s = schema.parse_scenario(None, max_horizon_s=120)
        assert s == Scenario(demand_scale=1.0, demand_shift_w=0.0,
                             dc_capacity_scale=1.0, weather_bias=1.0,
                             curtail_w=None, horizon_s=120)

    def test_knob_bounds_enforced(self):
        for doc in ({"demand_scale": 99.0}, {"demand_scale": -0.1},
                    {"weather_bias": 0.1}, {"weather_bias": 5.0},
                    {"dc_capacity_scale": 8.5},
                    {"demand_shift_w": 1e9}, {"curtail_w": -1.0}):
            with pytest.raises(RequestError) as ei:
                schema.parse_scenario(doc, max_horizon_s=120)
            assert ei.value.code == "invalid"

    def test_type_strictness(self):
        # bool is not a number, NaN is not finite, strings are not knobs
        for doc in ({"demand_scale": True}, {"demand_scale": float("nan")},
                    {"demand_scale": "1.0"}, {"horizon_s": 60.0},
                    {"horizon_s": True}, "not-an-object", 7):
            with pytest.raises(RequestError) as ei:
                schema.parse_scenario(doc, max_horizon_s=120)
            assert ei.value.code == "invalid"

    def test_unknown_knob_rejected(self):
        with pytest.raises(RequestError, match="unknown knob"):
            schema.parse_scenario({"volcano": 2.0}, max_horizon_s=120)

    def test_horizon_range(self):
        assert schema.parse_scenario({"horizon_s": 1},
                                     max_horizon_s=120).horizon_s == 1
        for h in (0, -5, 121):
            with pytest.raises(RequestError):
                schema.parse_scenario({"horizon_s": h}, max_horizon_s=120)

    def test_parse_request_rejects_malformed(self):
        ok = schema.request_meta("a", "reply.x", "fleet",
                                 {"horizon_s": 60})
        r = schema.parse_request(ok, max_horizon_s=120)
        assert (r.id, r.mode, r.scenario.horizon_s) == ("a", "fleet", 60)
        bad = [
            {**ok, "id": ""}, {**ok, "id": "x" * 65}, {**ok, "id": 7},
            {**ok, "reply_to": ""}, {**ok, "mode": "bogus"},
            {**ok, "surprise": 1},
        ]
        for meta in bad:
            with pytest.raises(RequestError) as ei:
                schema.parse_request(meta, max_horizon_s=120)
            assert ei.value.code == "invalid"

    def test_pick_bucket_smallest_fit(self):
        assert schema.pick_bucket(1, (1, 4, 8)) == 1
        assert schema.pick_bucket(3, (1, 4, 8)) == 4
        assert schema.pick_bucket(8, (1, 4, 8)) == 8
        with pytest.raises(ValueError):
            schema.pick_bucket(9, (1, 4, 8))

    def test_encode_batch_pads_neutral(self):
        s = Scenario(demand_scale=2.0, dc_capacity_scale=0.5,
                     curtail_w=1e3, horizon_s=60)
        enc = schema.encode_batch([s], 4, np.float32)
        assert enc["demand_scale"].shape == (4,)
        assert enc["demand_scale"].dtype == np.float32
        assert enc["pv_scale"][0] == np.float32(0.5)
        assert enc["curtail_w"][0] == np.float32(1e3)
        # padding rows: neutral knobs, horizon 0 (folds nothing),
        # curtail at the dtype's no-cap sentinel
        no_cap = np.float32(np.finfo(np.float32).max)
        assert list(enc["horizon_s"]) == [60, 0, 0, 0]
        assert all(enc["demand_scale"][1:] == np.float32(1.0))
        assert all(enc["curtail_w"][1:] == no_cap)
        with pytest.raises(ValueError):
            schema.encode_batch([s, s], 1, np.float32)

    def test_default_buckets_and_serve_config(self):
        assert default_buckets(16) == (1, 2, 4, 8, 16)
        assert default_buckets(6) == (1, 2, 4, 6)
        assert ServeConfig(sim=scfg(),
                           batch_sizes=(8, 1, 8)).buckets() == (1, 8)
        with pytest.raises(ValueError):
            ServeConfig(sim=scfg(), batch_sizes=(0, 2)).buckets()


# ---------------------------------------------------------------------------
# micro-batcher (stub dispatch: no device work)
# ---------------------------------------------------------------------------


class TestMicroBatcher:
    def test_coalesces_and_demuxes(self):
        async def main():
            reg = MetricsRegistry()
            calls = []

            def dispatch(reqs):
                calls.append(len(reqs))
                time.sleep(0.005)
                return [f"r:{r}" for r in reqs]

            b = MicroBatcher(dispatch, window_s=0.05, max_batch=8,
                             registry=reg)
            b.start()
            futs = [b.submit(f"q{i}") for i in range(5)]
            out = await asyncio.gather(*futs)
            assert [r for r, _ in out] == [f"r:q{i}" for i in range(5)]
            infos = [i for _, i in out]
            assert {i["batch"] for i in infos} == {5}
            assert all(i["queue_s"] >= 0.0 and i["dispatch_s"] > 0.0
                       for i in infos)
            assert calls == [5]
            await b.stop(drain=True)
            snap = reg.snapshot()
            assert snap["counters"]["serve.batches_total"] == 1.0
            assert snap["histograms"]["serve.batch_occupancy"]["max"] == 5.0
        _run(main())

    def test_max_batch_splits(self):
        async def main():
            b = MicroBatcher(lambda rs: list(rs), window_s=0.02,
                             max_batch=2, registry=MetricsRegistry())
            b.start()
            out = await asyncio.gather(*[b.submit(i) for i in range(5)])
            assert [r for r, _ in out] == list(range(5))
            assert all(i["batch"] <= 2 for _, i in out)
            await b.stop(drain=True)
        _run(main())

    def test_queue_limit_and_drain_rejections(self):
        async def main():
            b = MicroBatcher(lambda rs: list(rs), window_s=0.01,
                             max_batch=2, queue_limit=2,
                             registry=MetricsRegistry())
            # worker not started: the queue fills
            f1, f2 = b.submit("a"), b.submit("b")
            with pytest.raises(RequestError) as ei:
                b.submit("c")
            assert ei.value.code == "busy"
            await b.stop(drain=False)
            for f in (f1, f2):
                with pytest.raises(RequestError) as e2:
                    await f
                assert e2.value.code == "draining"
            with pytest.raises(RequestError) as e3:
                b.submit("d")
            assert e3.value.code == "draining"
        _run(main())

    def test_dispatch_error_is_typed_internal(self):
        async def main():
            def boom(reqs):
                raise RuntimeError("no device")

            b = MicroBatcher(boom, window_s=0.01, max_batch=2,
                             registry=MetricsRegistry())
            b.start()
            with pytest.raises(RequestError) as ei:
                await b.submit("x")
            assert ei.value.code == "internal"
            await b.stop(drain=True)
        _run(main())

    def test_batch_align_validation(self):
        with pytest.raises(ValueError, match="batch_align"):
            MicroBatcher(lambda rs: list(rs), window_s=0.01, max_batch=4,
                         registry=MetricsRegistry(), batch_align=0)

    def test_batch_align_tops_up_from_queue(self):
        """Soft alignment: at window close the batcher tops an odd batch
        up to the next multiple of ``batch_align`` from requests ALREADY
        queued — never waiting past the window for new ones.  With a
        zero window each batch would close at occupancy 1; align=2 pairs
        them up from the queue, and the last batch is allowed to stay
        ragged when the queue runs dry."""
        async def main():
            calls = []

            def dispatch(reqs):
                calls.append(len(reqs))
                return list(reqs)

            b = MicroBatcher(dispatch, window_s=0.0, max_batch=8,
                             registry=MetricsRegistry(), batch_align=2)
            futs = [b.submit(i) for i in range(5)]  # queue BEFORE start
            b.start()
            out = await asyncio.gather(*futs)
            assert [r for r, _ in out] == list(range(5))
            assert calls == [2, 2, 1]
            await b.stop(drain=True)
        _run(main())


# ---------------------------------------------------------------------------
# request/reply correlation over all three transports
# ---------------------------------------------------------------------------


async def _reverse_responder(url, exchange, expect):
    """Echo server that collects ``expect`` requests, then replies in
    REVERSE arrival order — correlation must come from ids, never from
    delivery order."""
    tx = make_transport(url, exchange)
    reply_txs = {}
    async with tx:
        try:
            got = []
            async for _t, _v, meta in tx.subscribe(with_meta=True):
                if not isinstance(meta, dict) or \
                        meta.get("op") != schema.OP_REQUEST:
                    continue
                got.append(meta)
                if len(got) < expect:
                    continue
                for m in reversed(got):
                    rt = m["reply_to"]
                    if rt not in reply_txs:
                        reply_txs[rt] = make_transport(url, rt)
                        await reply_txs[rt].__aenter__()
                    await reply_txs[rt].publish(
                        0.0, dt.datetime(2019, 1, 1),
                        meta=schema.ok_meta(m["id"],
                                            m.get("mode", "reduce"),
                                            {"echo": m["id"]}))
                got.clear()
        finally:
            for rtx in reply_txs.values():
                with contextlib.suppress(Exception):
                    await rtx.__aexit__(None, None, None)


async def _correlate(url, n=3):
    task = asyncio.create_task(_reverse_responder(url, "scenario", n))
    try:
        async with ScenarioClient(url) as c:
            await asyncio.sleep(0.1)  # responder subscription settles
            replies = await asyncio.gather(*[
                c.request(None, rid=f"q{i}", timeout=10)
                for i in range(n)])
        assert [r["result"]["echo"] for r in replies] \
            == [f"q{i}" for i in range(n)]
        assert all(r["ok"] for r in replies)
    finally:
        task.cancel()
        with contextlib.suppress(asyncio.CancelledError):
            await task


class TestCorrelation:
    def test_out_of_order_replies_local(self):
        _run(_correlate("local://corr-local"))

    def test_shared_reply_exchange_local(self):
        """Two clients deliberately sharing one reply exchange: each
        sees the other's replies and must route by id only."""
        url = "local://corr-shared"

        async def main():
            task = asyncio.create_task(
                _reverse_responder(url, "scenario", 2))
            try:
                async with ScenarioClient(url) as c1:
                    async with ScenarioClient(
                            url, reply_to=c1.reply_to) as c2:
                        await asyncio.sleep(0.1)
                        r1, r2 = await asyncio.gather(
                            c1.request(None, rid="one", timeout=10),
                            c2.request(None, rid="two", timeout=10))
                assert r1["result"]["echo"] == "one"
                assert r2["result"]["echo"] == "two"
            finally:
                task.cancel()
                with contextlib.suppress(asyncio.CancelledError):
                    await task
        _run(main())

    def test_out_of_order_replies_tcp(self):
        async def main():
            async with TcpFanoutBroker(port=0) as broker:
                await _correlate(f"tcp://127.0.0.1:{broker.port}")
        _run(main())

    def test_out_of_order_replies_amqp(self, fake_aio_pika):
        _run(_correlate("amqp://fake-host:5672/"))


# ---------------------------------------------------------------------------
# engine: batch-of-N answers are bit-identical to batch-of-1
# ---------------------------------------------------------------------------


class TestEngineBitIdentity:
    def test_batch_rows_match_singleton_runs(self, engine):
        reqs = [
            req("a", Scenario(horizon_s=120)),
            req("b", Scenario(demand_scale=1.5, demand_shift_w=250.0,
                              horizon_s=120), mode="fleet"),
            req("c", Scenario(weather_bias=0.5, dc_capacity_scale=2.0,
                              curtail_w=4000.0, horizon_s=60),
                mode="quantiles"),
        ]
        batch = engine.run(reqs)          # padded to bucket 4
        singles = [engine.run([r])[0] for r in reqs]  # bucket 1 each
        assert batch == singles
        assert batch[0]["stats"]["n_seconds"] == 120 * 4
        assert batch[1]["fleet"]["count"] == 120 * 4
        assert batch[2]["count"] == 60 * 4  # short horizon folds less

    def test_company_does_not_change_answers(self, engine):
        """The same scenario answered alone and next to very different
        company: identical bits (the vmapped fold is elementwise per
        row; padding rows fold nothing)."""
        probe = req("p", Scenario(demand_scale=2.0, horizon_s=120))
        alone = engine.run([probe])[0]
        noisy = engine.run([
            req("n1", Scenario(weather_bias=4.0, horizon_s=60)),
            probe,
            req("n2", Scenario(demand_shift_w=-5e4, horizon_s=120)),
        ])[1]
        assert alone == noisy

    def test_neutral_scenario_matches_plain_reduce_run(self, engine):
        """A neutral-knob scenario over the full horizon is THE batch
        run: its stats must equal output='reduce' run_reduced bitwise."""
        stats = engine.run(
            [req("n", Scenario(horizon_s=120))])[0]["stats"]
        with use_registry(MetricsRegistry()):
            red = Simulation(scfg()).run_reduced()
        assert stats["n_seconds"] == int(red["n_seconds"].sum())
        for name, key in (("pv_sum", "pv_sum_w"),
                          ("meter_sum", "meter_sum_w"),
                          ("residual_sum", "residual_sum_w")):
            assert stats[key] == float(
                red[name].astype(np.float64).sum())
        assert stats["pv_max_w"] == float(red["pv_max"].max())
        assert stats["residual_min_w"] == float(red["residual_min"].min())
        assert stats["residual_max_w"] == float(red["residual_max"].max())


# ---------------------------------------------------------------------------
# engine on the 2-D (chains, scenario) mesh
# ---------------------------------------------------------------------------


class TestShardedEngine:
    def test_sharded_replies_bit_identical(self):
        """mesh_scenario >= 1 routes the engine onto ShardedSimulation's
        scenario dispatch: buckets round UP to multiples of the scenario
        mesh dim (padding rows fold nothing, so a rounded bucket answers
        identically) and every reply matches the unsharded engine's
        bits — including a single request padded to the aligned bucket."""
        base = scfg(n_chains=8)
        reqs = [
            req("a", Scenario(horizon_s=120)),
            req("b", Scenario(demand_scale=1.5, demand_shift_w=250.0,
                              horizon_s=120), mode="fleet"),
            req("c", Scenario(weather_bias=0.5, dc_capacity_scale=2.0,
                              curtail_w=4000.0, horizon_s=60),
                mode="quantiles"),
        ]
        with use_registry(MetricsRegistry()):
            plain = ScenarioEngine(base, (1, 4))
        with use_registry(MetricsRegistry()):
            sharded = ScenarioEngine(
                dataclasses.replace(base, mesh_scenario=2), (1, 4))
        assert plain.batch_align == 1 and plain.buckets == (1, 4)
        assert sharded.batch_align == 2
        assert sharded.buckets == (2, 4)  # bucket 1 rounds up to 2
        assert sharded.run(reqs) == plain.run(reqs)
        # single request: sharded pads to bucket 2, plain runs bucket 1
        assert sharded.run(reqs[:1]) == plain.run(reqs[:1])


# ---------------------------------------------------------------------------
# e2e acceptance: concurrent clients coalesce; replies == batch-of-1
# ---------------------------------------------------------------------------


class TestEndToEnd:
    def test_concurrent_clients_coalesce_and_match(self):
        url = "local://e2e-serve"
        cfg = ServeConfig(sim=scfg(), url=url, window_s=0.25,
                          batch_sizes=(1, 4, 8), timeout_s=300.0)
        reg = MetricsRegistry()
        scens = [{"demand_scale": 1.0 + 0.1 * i, "horizon_s": 120}
                 for i in range(8)]

        async def main():
            server = ScenarioServer(cfg, registry=reg)
            await server.start()
            clients = [ScenarioClient(url) for _ in range(8)]
            try:
                for c in clients:
                    await c.__aenter__()
                replies = await asyncio.gather(*[
                    clients[i].request(scens[i], rid=f"c{i}", timeout=300)
                    for i in range(8)])
                assert all(r["ok"] for r in replies), replies
                snap1 = reg.snapshot()["counters"]
                # the acceptance inequality: fewer dispatches than
                # requests, occupancy above 1
                assert snap1["serve.batches_total"] < 8
                occ = reg.snapshot()["histograms"]["serve.batch_occupancy"]
                assert occ["max"] > 1.0
                assert max(r["t"]["batch"] for r in replies) > 1

                # fresh batch-of-1 runs on the same warm server:
                # sequential requests, one per window
                singles = []
                for i in range(8):
                    s = await clients[0].request(scens[i], timeout=300)
                    assert s["ok"]
                    singles.append(s)
                assert [r["result"] for r in replies] \
                    == [s["result"] for s in singles]

                # duplicate ids: first accepted, replay rejected typed
                first = await clients[0].request(scens[0], rid="dup-1",
                                                 timeout=300)
                assert first["ok"]
                replay = await clients[0].request(scens[0], rid="dup-1",
                                                  timeout=30)
                assert not replay["ok"]
                assert replay["error"]["code"] == "duplicate"

                # malformed payloads: typed invalid, server stays up
                for bad_scen, bad_mode in (
                        ({"volcano": 1.0}, "reduce"),
                        ({"demand_scale": 99.0}, "reduce"),
                        ({"horizon_s": 10**7}, "reduce"),
                        (None, "bogus")):
                    r = await clients[0].request(bad_scen, mode=bad_mode,
                                                 timeout=30)
                    assert not r["ok"]
                    assert r["error"]["code"] == "invalid"

                # graceful drain: new work typed-rejected, then stop
                server.begin_drain()
                r = await clients[0].request(scens[0], timeout=30)
                assert not r["ok"]
                assert r["error"]["code"] == "draining"
            finally:
                for c in clients:
                    await c.__aexit__(None, None, None)
                await server.stop()

            snap = reg.snapshot()
            sec = serving_section(snap)
            assert sec is not None
            assert sec["replies"] == 17       # 8 + 8 + dup-1's first
            assert sec["rejected"] == 6       # dup + 4 invalid + drain
            assert sec["in_flight"] == 0
            assert sec["occupancy"]["max"] > 1.0
        _run(main())


# ---------------------------------------------------------------------------
# continuous batching: the rolling scheduler (deterministic fake session)
# ---------------------------------------------------------------------------


class _FakeSession:
    """Duck-typed RollingSession for scheduler-policy tests: each
    ``step_finish`` signals entry then blocks until released, so the
    test controls exactly what is queued while a dispatch is in
    flight."""

    def __init__(self, bucket, blocks):
        self.bucket = bucket
        self._blocks = dict(blocks)  # rid -> horizon blocks
        self.rows = {}
        self.calls = []
        self.step_entered = threading.Semaphore(0)
        self.step_go = threading.Semaphore(0)
        self.fail_next = False
        self.recovered = 0

    def blocks_for(self, request):
        return self._blocks[request.id]

    def admit_rows(self, admits):
        for slot, request in admits:
            self.rows[slot] = request.id

    def step_finish(self, bi, sched, retiring):
        self.step_entered.release()
        assert self.step_go.acquire(timeout=10.0)
        if self.fail_next:
            self.fail_next = False
            raise RuntimeError("chaos dispatch")
        self.calls.append((bi, tuple(sched), tuple(retiring)))
        return {sl: {"rid": self.rows.pop(sl)} for sl in retiring}

    def recover(self):
        self.recovered += 1
        self.rows.clear()


async def _entered(sess, timeout=10.0):
    """Await the fake session's next step_finish entry."""
    deadline = time.monotonic() + timeout
    while not sess.step_entered.acquire(blocking=False):
        assert time.monotonic() < deadline, "dispatch never started"
        await asyncio.sleep(0.005)


class TestContinuousScheduler:
    def test_backfill_joins_next_dispatch_and_retires_early(self):
        """The tentpole mechanic: a request arriving while rows are
        resident backfills a free slot into the very next fused
        dispatch (no window wait) and retires as soon as ITS horizon is
        done — it never rides the residents' remaining blocks."""
        async def main():
            reg = MetricsRegistry()
            sess = _FakeSession(4, {"a": 3, "b": 3, "c": 1})
            b = ContinuousBatcher(sess, window_s=0.02, registry=reg)
            b.start()
            fa = b.submit(req("a", Scenario()))
            fb = b.submit(req("b", Scenario()))
            await _entered(sess)                 # block 0 of {a, b} in flight
            fc = b.submit(req("c", Scenario()))  # arrives mid-dispatch
            sess.step_go.release()
            for _ in range(3):
                await _entered(sess)
                sess.step_go.release()
            (ra, ia), (rb, ib), (rc, ic) = await asyncio.gather(fa, fb, fc)
            await b.stop(drain=True)
            # c backfilled at its own cursor; the residents' shared
            # cursor keeps the fattest fuse until they retire, then c's
            # single block dispatches and frees the batch
            assert sess.calls == [
                (0, (0, 1), ()),
                (1, (0, 1), ()),
                (2, (0, 1), (0, 1)),
                (0, (2,), (2,)),
            ]
            assert (ra["rid"], rb["rid"], rc["rid"]) == ("a", "b", "c")
            assert ia["blocks"] == 3 and ic["blocks"] == 1
            assert ia["batch"] == 2 and ic["batch"] == 1
            c = reg.snapshot()["counters"]
            assert c["serve.backfilled_total"] == 1.0
            assert c["serve.batches_total"] == 4.0
            assert reg.snapshot()["gauges"]["serve.resident_rows"] == 0.0

        _run(main())

    def test_starve_limit_forces_the_oldest_cursor(self):
        """A stream of fresh short rows outvotes a long resident row's
        cursor every iteration; after ``starve_limit`` skipped turns the
        scheduler dispatches the oldest row's cursor anyway."""
        async def main():
            reg = MetricsRegistry()
            blocks = {"L": 2, **{f"s{i}": 1 for i in range(6)}}
            sess = _FakeSession(8, blocks)
            b = ContinuousBatcher(sess, window_s=0.02, registry=reg,
                                  starve_limit=2)
            b.start()
            futs = [b.submit(req("L", Scenario()))]
            for wave in range(3):
                await _entered(sess)  # previous dispatch in flight
                futs += [b.submit(req(f"s{2 * wave + k}", Scenario()))
                         for k in range(2)]
                sess.step_go.release()
            await _entered(sess)
            sess.step_go.release()
            await _entered(sess)
            sess.step_go.release()
            await asyncio.gather(*futs)
            await b.stop(drain=True)
            # waves 1 and 2 skip L's cursor (starve 1, 2); wave 3 hits
            # the limit and L's block 1 dispatches ALONE despite two
            # fresh short rows waiting at cursor 0
            assert sess.calls == [
                (0, (0,), ()),          # L alone, block 0
                (0, (1, 2), (1, 2)),    # wave 1 shorts (L skipped)
                (0, (1, 2), (1, 2)),    # wave 2 shorts (L skipped)
                (1, (0,), (0,)),        # forced: L's starved cursor
                (0, (1, 2), (1, 2)),    # wave 3 shorts
            ]

        _run(main())

    def test_dispatch_failure_fails_residents_and_recovers(self):
        """A failed fused dispatch poisons the shared accumulator, so
        every RESIDENT row gets a typed ``internal`` error and the
        session recovers; later requests are served normally."""
        async def main():
            reg = MetricsRegistry()
            sess = _FakeSession(4, {"a": 2, "b": 1, "d": 1})
            b = ContinuousBatcher(sess, window_s=0.02, registry=reg)
            b.start()
            fa = b.submit(req("a", Scenario()))
            fb = b.submit(req("b", Scenario()))
            await _entered(sess)
            sess.fail_next = True
            sess.step_go.release()
            for f in (fa, fb):
                with pytest.raises(RequestError) as ei:
                    await f
                assert ei.value.code == "internal"
            assert sess.recovered == 1
            fd = b.submit(req("d", Scenario()))
            await _entered(sess)
            sess.step_go.release()
            rd, _info = await fd
            assert rd["rid"] == "d"
            await b.stop(drain=True)

        _run(main())


# ---------------------------------------------------------------------------
# continuous batching e2e: bit identity, coalescing, drain, mesh alignment
# ---------------------------------------------------------------------------


class TestContinuousEndToEnd:
    def test_replies_bit_identical_to_singletons(self, engine):
        """The tentpole acceptance: every reply from the continuous
        server is bit-identical to a fresh batch-of-1 run of the same
        scenario, while the rolling scheduler fuses far fewer dispatches
        than row-blocks."""
        url = "local://e2e-continuous"
        cfg = ServeConfig(sim=scfg(), url=url, window_s=0.25,
                          batch_sizes=(1, 4, 8), timeout_s=300.0,
                          batching="continuous", starve_limit=3)
        reg = MetricsRegistry()
        scens = [{"demand_scale": 1.0 + 0.1 * i,
                  "horizon_s": 120 if i % 2 else 60} for i in range(8)]
        modes = ["reduce", "fleet", "quantiles", "reduce"] * 2

        async def main():
            server = ScenarioServer(cfg, registry=reg)
            await server.start()
            # the ServeConfig knob reaches the scheduler, and the
            # rolling bucket is the largest compiled one
            assert server.batcher._starve_limit == 3
            assert server.batcher._session.bucket == 8
            try:
                async with ScenarioClient(url) as client:
                    replies = await asyncio.gather(*[
                        client.request(scens[i], mode=modes[i],
                                       rid=f"c{i}", timeout=300)
                        for i in range(8)])
                    assert all(r["ok"] for r in replies), replies
                    # graceful drain on the continuous path
                    server.begin_drain()
                    r = await client.request(scens[0], timeout=30)
                    assert r["error"]["code"] == "draining"
            finally:
                await server.stop()
            return replies

        replies = _run(main())
        # 12 useful row-blocks (4x1 + 4x2) fused into a handful of
        # dispatches with real co-residency
        c = reg.snapshot()["counters"]
        assert 2 <= c["serve.batches_total"] <= 8
        occ = reg.snapshot()["histograms"]["serve.batch_occupancy"]
        assert occ["max"] > 1.0
        refs = [engine.run([req(f"c{i}", schema.parse_scenario(
                    scens[i], max_horizon_s=engine.max_horizon_s),
                    mode=modes[i])])[0]
                for i in range(8)]
        assert [r["result"] for r in replies] == refs

    def test_mesh_scenario_alignment_and_padding_inert(self):
        """Continuous batching on the 2-D (chains, scenario) mesh: the
        rolling bucket respects the scenario batch alignment and padded
        slots stay bit-inert — replies match the UNsharded engine's
        batch-of-1 bits, concurrent or alone."""
        base = scfg(n_chains=8)
        with use_registry(MetricsRegistry()):
            plain = ScenarioEngine(base, (1, 4))
        cfg = ServeConfig(sim=dataclasses.replace(base, mesh_scenario=2),
                          url="local://e2e-mesh-continuous",
                          window_s=0.2, batch_sizes=(1, 4),
                          timeout_s=300.0, batching="continuous")
        reg = MetricsRegistry()
        scens = [
            ({"horizon_s": 120}, "reduce"),
            ({"demand_scale": 1.5, "demand_shift_w": 250.0,
              "horizon_s": 120}, "fleet"),
            ({"weather_bias": 0.5, "dc_capacity_scale": 2.0,
              "curtail_w": 4000.0, "horizon_s": 60}, "quantiles"),
        ]

        async def main():
            server = ScenarioServer(cfg, registry=reg)
            await server.start()
            # buckets round UP to multiples of the scenario mesh dim,
            # and the rolling session inherits the aligned width
            assert server.engine.batch_align == 2
            assert server.engine.buckets == (2, 4)
            assert server.batcher._session.bucket % 2 == 0
            try:
                async with ScenarioClient(url=cfg.url) as client:
                    batch = await asyncio.gather(*[
                        client.request(s, mode=m, rid=f"m{i}",
                                       timeout=300)
                        for i, (s, m) in enumerate(scens)])
                    lone = await client.request(
                        scens[0][0], mode=scens[0][1], timeout=300)
            finally:
                await server.stop()
            return batch, lone

        batch, lone = _run(main())
        assert all(r["ok"] for r in batch) and lone["ok"]
        refs = [plain.run([req(f"m{i}", schema.parse_scenario(
                    s, max_horizon_s=plain.max_horizon_s), mode=m)])[0]
                for i, (s, m) in enumerate(scens)]
        assert [r["result"] for r in batch] == refs
        assert lone["result"] == refs[0]


# ---------------------------------------------------------------------------
# retry_after hints: honest backoff from busy/unavailable rejections
# ---------------------------------------------------------------------------


class _Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


class TestRetryAfterHints:
    def test_queue_full_busy_carries_hint(self):
        async def main():
            b = MicroBatcher(lambda rs: list(rs), window_s=0.01,
                             max_batch=2, queue_limit=1,
                             registry=MetricsRegistry())
            f1 = b.submit("a")  # worker not started: the queue fills
            with pytest.raises(RequestError) as ei:
                b.submit("b")
            assert ei.value.code == "busy"
            assert ei.value.retry_after_ms >= 1
            assert ei.value.retry_after_s \
                == ei.value.retry_after_ms / 1000.0
            await b.stop(drain=False)
            with pytest.raises(RequestError):
                await f1

        _run(main())

    def test_breaker_open_hint_is_reset_remaining(self):
        async def main():
            reg = MetricsRegistry()
            clk = _Clock()
            br = CircuitBreaker("serve.dispatch", failure_threshold=1,
                                reset_s=30.0, registry=reg, now=clk)
            b = MicroBatcher(lambda reqs: list(reqs), window_s=0.005,
                             max_batch=2, registry=reg, breaker=br)
            b.start()
            br.record_failure()  # open
            clk.t = 12.0         # 18 s of the reset window remain
            with pytest.raises(RequestError) as ei:
                b.submit("x")
            assert ei.value.code == "unavailable"
            assert ei.value.retry_after_ms == 18_000
            await b.stop(drain=True)

        _run(main())

    def test_policy_sleeps_the_hint_not_the_dice(self, monkeypatch):
        """ResiliencePolicy honours a rejection's ``retry_after_s``
        attribute verbatim, overriding its own jittered backoff."""
        from tmhpvsim_tpu.runtime import resilience as resilience_mod

        delays = []

        async def fake_sleep(d):
            delays.append(d)

        monkeypatch.setattr(resilience_mod.asyncio, "sleep", fake_sleep)
        calls = {"n": 0}

        async def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise RequestError("busy", "queue full",
                                   retry_after_ms=40)
            return "done"

        pol = ResiliencePolicy(attempts=5, base_delay_s=7.0,
                               max_delay_s=9.0, name="hint.test",
                               registry=MetricsRegistry())
        assert _run(pol.call(flaky)) == "done"
        assert delays == [0.04, 0.04]  # the server's hint, not 7-9 s

    def test_client_rejection_policy_retries_same_rid(self):
        """ScenarioClient under a rejection_policy: a typed busy reply
        (with its retry_after_ms hint) is retried with the SAME request
        id, and on exhaustion the final typed reply surfaces as a
        value, never an exception."""
        url = "local://retry-hints"
        seen = []

        async def responder():
            async with make_transport(url, "scenario") as rx:
                async for _t, _v, meta in rx.subscribe(with_meta=True):
                    if not isinstance(meta, dict) or \
                            meta.get("op") != schema.OP_REQUEST:
                        continue
                    rid = meta["id"]
                    seen.append(rid)
                    if rid.startswith("always") or seen.count(rid) == 1:
                        out = schema.error_meta(
                            rid, "busy", "over quota", retry_after_ms=5)
                    else:
                        out = schema.ok_meta(rid, "reduce", {"x": 1})
                    async with make_transport(url,
                                              meta["reply_to"]) as tx:
                        await tx.publish(0.0, dt.datetime(2019, 9, 5),
                                         meta=out)

        async def main():
            task = asyncio.create_task(responder())
            await asyncio.sleep(0.05)
            pol = ResiliencePolicy(attempts=3, base_delay_s=0.01,
                                   max_delay_s=0.05, name="client.rej",
                                   registry=MetricsRegistry())
            try:
                async with ScenarioClient(
                        url, rejection_policy=pol) as client:
                    r = await client.request({"horizon_s": 60},
                                             rid="rr", timeout=10)
                    assert r["ok"] and r["result"] == {"x": 1}
                    assert seen == ["rr", "rr"]  # same id, one retry
                    r2 = await client.request({"horizon_s": 60},
                                              rid="always-1", timeout=10)
                    assert not r2["ok"]
                    assert r2["error"]["code"] == "busy"
                    assert r2["error"]["retry_after_ms"] == 5
                    assert seen.count("always-1") == 3  # exhausted
            finally:
                task.cancel()
                with contextlib.suppress(asyncio.CancelledError,
                                         ConnectionError):
                    await task

        _run(main())


# ---------------------------------------------------------------------------
# warm restart: zero fresh compiles against a populated cache
# ---------------------------------------------------------------------------


class TestWarmRestart:
    def test_restart_compiles_zero_times(self, tmp_path):
        """The serving acceptance criterion: a server built against the
        compile cache its first start populated deserialises every
        executable — scenario buckets included — with zero cold
        compiles (conftest's autouse fixture restores the suite cache
        afterwards)."""
        d = compilecache.configure(str(tmp_path))
        assert d is not None
        c = scfg(duration_s=60, n_chains=2,
                 serve_batch_sizes=(1, 2))
        reg1 = MetricsRegistry()
        with use_registry(reg1):
            sim = Simulation(c)
        names = [t[0] for t in sim.aot_targets()]
        assert "scenario_acc[1]" in names and "scenario_acc[2]" in names
        n_targets = len(names)
        s1 = reg1.snapshot()["counters"]
        assert s1.get("executor.aot_warmup_total", 0) == n_targets
        assert s1.get("executor.aot_warmup_errors_total", 0) == 0

        reg2 = MetricsRegistry()
        with use_registry(reg2):
            Simulation(c)
        s2 = reg2.snapshot()["counters"]
        # the module-level resume copies are shared with the first
        # build and may come from jax's in-process executable cache
        # without a cache event; every target that reaches the backend
        # must deserialise warm, and nothing may compile cold
        assert s2.get("executor.compile_warm_total", 0) >= n_targets - 2
        assert s2.get("executor.compile_cold_total", 0) == 0


# ---------------------------------------------------------------------------
# report schema v6: the serving section
# ---------------------------------------------------------------------------


def _serving_registry():
    reg = MetricsRegistry()
    reg.counter("serve.requests_total").inc(9)
    reg.counter("serve.replies_total").inc(8)
    reg.counter("serve.rejected_total").inc(1)
    reg.counter("serve.batches_total").inc(3)
    reg.gauge("serve.in_flight").set(0)
    occ = reg.histogram("serve.batch_occupancy", buckets=OCCUPANCY_BUCKETS)
    for v in (1.0, 3.0, 4.0):
        occ.observe(v)
    for name in ("serve.queue_wait_s", "serve.dispatch_s",
                 "serve.reply_latency_s"):
        h = reg.histogram(name)
        for x in (0.001, 0.01, 0.05):
            h.observe(x)
    return reg


def _fleet_inputs():
    """(router_snapshot, [(worker, snapshot), ...]) exercising every
    v16 ``serving.fleet`` field, with the partition invariant holding:
    5 + 4 worker requests == 8 routed + 1 rerouted."""
    reg = MetricsRegistry()
    reg.counter("router.requests_total").inc(11)
    reg.counter("router.routed_total").inc(8)
    reg.counter("router.rerouted_total").inc(1)
    reg.counter("router.replies_total").inc(8)
    reg.counter("router.rejected_total").inc(3)
    reg.counter("router.quota_rejected_total").inc(1)
    reg.counter("router.shed_total").inc(1)
    reg.counter("router.dup_replies_total").inc(1)
    reg.counter("router.worker_down_total").inc(1)
    reg.gauge("router.workers_ready").set(2)
    reg.gauge("router.pending").set(0)
    reg.gauge("resilience.supervised_restarts.w0").set(1)
    h = reg.histogram("router.reply_latency_s")
    for x in (0.002, 0.02, 0.2):
        h.observe(x)
    workers = []
    for name, n in (("w0", 5), ("w1", 4)):
        w = MetricsRegistry()
        w.counter("serve.requests_total").inc(n)
        w.counter("serve.replies_total").inc(n)
        w.counter("serve.batches_total").inc(2)
        w.counter("serve.backfilled_total").inc(1)
        w.counter("executor.compile_warm_total").inc(3)
        occ = w.histogram("serve.batch_occupancy",
                          buckets=OCCUPANCY_BUCKETS)
        for v in (1.0, float(n)):
            occ.observe(v)
        workers.append((name, w.snapshot()))
    return reg.snapshot(), workers


class TestServingReport:
    def test_v6_round_trip(self):
        rep = RunReport("pvsim.serve")
        rep.attach_metrics(_serving_registry())
        doc = rep.doc()
        assert doc["schema_version"] == REPORT_SCHEMA_VERSION == 16
        validate_report(doc)
        doc2 = json.loads(json.dumps(doc))
        validate_report(doc2)
        sec = doc2["serving"]
        assert (sec["requests"], sec["replies"], sec["rejected"],
                sec["timeouts"], sec["batches"]) == (9, 8, 1, 0, 3)
        assert sec["occupancy"]["batches"] == 3
        assert sec["occupancy"]["max"] == 4.0
        assert sec["reply_latency"]["count"] == 3

    def test_no_serve_metrics_no_section(self):
        reg = MetricsRegistry()
        reg.counter("broker.published_total").inc()
        rep = RunReport("pvsim")
        rep.attach_metrics(reg)
        assert rep.doc()["serving"] is None
        validate_report(rep.doc())

    def test_v16_fleet_round_trip(self):
        rep = RunReport("pvsim.serve")
        rep.attach_metrics(_serving_registry())
        rep.attach_fleet_serving(*_fleet_inputs())
        doc = json.loads(json.dumps(rep.doc()))
        assert doc["schema_version"] == 16
        validate_report(doc)
        fleet = doc["serving"]["fleet"]
        assert [w["name"] for w in fleet["workers"]] == ["w0", "w1"]
        # the partition invariant the tools enforce
        assert sum(w["requests"] for w in fleet["workers"]) \
            == fleet["router"]["routed"] + fleet["router"]["rerouted"]
        r = fleet["router"]
        assert (r["requests"], r["rejected"], r["quota_rejected"],
                r["shed"], r["rerouted"], r["dup_replies"],
                r["worker_down"]) == (11, 3, 1, 1, 1, 1, 1)
        assert r["workers_ready"] == 2
        assert r["reply_latency"]["count"] == 3
        w0 = fleet["workers"][0]
        assert (w0["backfilled"], w0["compile_cold"],
                w0["compile_warm"], w0["restarts"]) == (1, 0, 3, 1)
        assert fleet["workers"][1]["restarts"] == 0

    def test_router_only_registry_synthesizes_base_serving(self):
        """A router process has no ``serve.*`` names; the fleet attach
        synthesizes the documented base serving shape from the fleet
        totals so v1-v15 consumers keep reading the section."""
        rep = RunReport("pvsim.router")
        rep.attach_fleet_serving(*_fleet_inputs())
        doc = rep.doc()
        validate_report(doc)
        sec = doc["serving"]
        assert (sec["requests"], sec["replies"],
                sec["rejected"]) == (11, 8, 3)
        assert sec["batches"] == 4  # summed across the worker rows
        assert sec["fleet"]["router"]["quota_rejected"] == 1

    def test_v15_doc_still_validates(self):
        """Additive v16: a fleet-less v15 document (no ``fleet`` key)
        remains valid byte-for-byte."""
        doc = _serving_doc()
        doc["schema_version"] = 15
        validate_report(doc)
        assert "fleet" not in (doc["serving"] or {})


# ---------------------------------------------------------------------------
# tools/serve_report.py + the bench_trend serve column
# ---------------------------------------------------------------------------


def _run_tool(script, *argv):
    return subprocess.run(
        [sys.executable, str(script), *map(str, argv)],
        capture_output=True, text=True)


def _serving_doc():
    rep = RunReport("pvsim.serve")
    rep.attach_metrics(_serving_registry())
    return rep.doc()


def _fleet_doc():
    rep = RunReport("pvsim.serve")
    rep.attach_metrics(_serving_registry())
    rep.attach_fleet_serving(*_fleet_inputs())
    return rep.doc()


class TestServeReportTool:
    def test_valid_report_prints_table(self, tmp_path):
        path = tmp_path / "report.json"
        path.write_text(json.dumps(_serving_doc()))
        r = _run_tool(SERVE_REPORT, path)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "scenario serving" in r.stdout
        assert "coalescing 3.00x" in r.stdout

    def test_malformed_serving_section_fails(self, tmp_path):
        doc = _serving_doc()
        doc["serving"]["replies"] = 99      # exceeds requests
        doc["serving"]["occupancy"]["batches"] = 7   # != counter
        path = tmp_path / "bad.json"
        path.write_text(json.dumps(doc))
        r = _run_tool(SERVE_REPORT, path)
        assert r.returncode == 1
        assert "INVALID serving section" in r.stderr

    def test_report_without_serving_section_passes(self, tmp_path):
        doc = _serving_doc()
        doc["serving"] = None
        path = tmp_path / "off.json"
        path.write_text(json.dumps(doc))
        r = _run_tool(SERVE_REPORT, path)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "no serving section" in r.stdout

    def test_bench_doc_and_jsonl_shapes(self, tmp_path):
        bench = {"phase": "serve", "coalescing": 3.0,
                 "run_report": _serving_doc()}
        path = tmp_path / "serve.jsonl"
        path.write_text(json.dumps(bench) + "\n" + json.dumps(bench) + "\n")
        r = _run_tool(SERVE_REPORT, path)
        assert r.returncode == 0, r.stdout + r.stderr
        assert r.stdout.count("[serve]") == 2

    def test_fleet_section_prints_worker_rows(self, tmp_path):
        path = tmp_path / "fleet.json"
        path.write_text(json.dumps(_fleet_doc()))
        r = _run_tool(SERVE_REPORT, path)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "w0" in r.stdout and "w1" in r.stdout
        assert "cold=0 restarts=1" in r.stdout

    def test_fleet_partition_violation_fails(self, tmp_path):
        doc = _fleet_doc()
        doc["serving"]["fleet"]["workers"][0]["requests"] += 1
        path = tmp_path / "bad_fleet.json"
        path.write_text(json.dumps(doc))
        r = _run_tool(SERVE_REPORT, path)
        assert r.returncode == 1
        assert "partition" in r.stderr

    def test_bench_trend_fleet_columns(self, tmp_path):
        f = tmp_path / "fleet_bench.json"
        f.write_text(json.dumps({
            "artifact": "scenario-serve fleet load", "platform": "cpu",
            "workers": 4, "speedup": 2.36,
            "run_report": _fleet_doc(),
        }))
        r = _run_tool(BENCH_TREND, "--json", f)
        assert r.returncode == 0, r.stdout + r.stderr
        row = json.loads(r.stdout)["rows"][0]
        assert row["fleet_workers"] == 4
        assert row["cb_speedup"] == 2.36
        assert not row["failed"]

    def test_bench_trend_serve_column(self, tmp_path):
        a = tmp_path / "a.json"
        a.write_text(json.dumps({
            "value": 1e6, "platform": "cpu",
            "run_report": {"timing": {"steady_block_s": 0.1},
                           "config": {}},
        }))
        b = tmp_path / "b.json"
        b.write_text(json.dumps({
            "artifact": "scenario-serve load", "platform": "cpu",
            "coalescing": 2.5, "run_report": _serving_doc(),
        }))
        r = _run_tool(BENCH_TREND, "--json", a, b)
        assert r.returncode == 0, r.stdout + r.stderr
        rows = {row["name"]: row
                for row in json.loads(r.stdout)["rows"]}
        assert rows["a.json"]["serve"] is None
        assert rows["b.json"]["serve"] == 2.5
        assert not rows["b.json"]["failed"]


# ---------------------------------------------------------------------------
# broker backlog bounding (the satellite fix)
# ---------------------------------------------------------------------------


class TestBrokerBacklog:
    def test_local_broker_drops_oldest_past_cap(self, monkeypatch):
        monkeypatch.setattr(broker_mod, "MAX_CONSUMER_BACKLOG", 16)
        reg = MetricsRegistry()

        async def main():
            with use_registry(reg):
                b = broker_mod._LocalBroker()
                q = b.bind("x")
                for i in range(20):
                    b.publish("x", broker_mod.encode(
                        float(i), dt.datetime(2019, 1, 1)))
                assert q.qsize() == 16
                # oldest-first: messages 0..3 were dropped
                _t, v = broker_mod.decode(q.get_nowait())
                assert v == 4.0
        _run(main())
        assert reg.snapshot()["counters"]["broker.dropped_total"] == 4.0

    def test_tcp_subscriber_queue_bounded(self):
        reg = MetricsRegistry()

        async def main():
            with use_registry(reg):
                sub = _Subscriber(writer=None, max_backlog=5)
                for i in range(8):
                    sub.offer(b"%d\n" % i)
                assert sub.queue.qsize() == 5
                assert sub.n_dropped == 3
                # oldest-first: line 3 survives as the head (peek: only
                # drain() pops in production, decrementing the gauge)
                assert list(sub.queue._queue)[0] == b"3\n"
                snap = reg.snapshot()
                assert snap["counters"]["tcpbroker.dropped_total"] == 3.0
                assert snap["gauges"]["tcpbroker.backlog_depth"] == 5.0
                sub.unregistered()
                snap = reg.snapshot()
                assert snap["gauges"]["tcpbroker.backlog_depth"] == 0.0
                assert sub.queue.empty()
        _run(main())

    def test_tcp_aggregate_gauge_across_subscribers(self):
        reg = MetricsRegistry()

        async def main():
            with use_registry(reg):
                a = _Subscriber(writer=None, max_backlog=10)
                b = _Subscriber(writer=None, max_backlog=10)
                for i in range(3):
                    a.offer(b"x\n")
                for i in range(2):
                    b.offer(b"y\n")
                assert reg.snapshot()["gauges"][
                    "tcpbroker.backlog_depth"] == 5.0
                a.unregistered()
                assert reg.snapshot()["gauges"][
                    "tcpbroker.backlog_depth"] == 2.0
                b.unregistered()
                assert reg.snapshot()["gauges"][
                    "tcpbroker.backlog_depth"] == 0.0
        _run(main())
