"""rng_batch='block' (Plan.rng_batch): whole-block RNG pre-generation.

The lever hoists every per-minute second-noise draw out of the scan
body into batched counter-mode tensors generated before the scan.  The
keying is IDENTICAL to the in-scan path (``fold_in(key, minute)`` per
minute group, models/clearsky_index.py), so the contract is bit
identity — not statistical closeness — on every block implementation,
under sharding, under mega-dispatch, and across a checkpoint resume.
The default plan must also lower to byte-identical HLO: the lever is
structurally absent when off, not branched around.
"""

import jax
import numpy as np
import pytest

from tmhpvsim_tpu.config import SimConfig, SiteGrid
from tmhpvsim_tpu.engine import Simulation, checkpoint as ckpt
from tmhpvsim_tpu.models import clearsky_index as ci
from tmhpvsim_tpu.parallel import ShardedSimulation

IMPLS = ["wide", "scan", "scan2"]


def cfg(**kw):
    # 2 small blocks: enough for the merge/resume/mega-dispatch paths
    # while keeping the default lane fast; the slow lane re-runs the
    # heavy geometries (site grid, sharded) at the same shape
    base = dict(
        start="2019-09-05 10:00:00",
        duration_s=3600,
        n_chains=4,
        seed=7,
        block_s=1800,
        dtype="float32",
    )
    base.update(kw)
    return SimConfig(**base)


def grid(n=4):
    # equatorial, mid-latitude x2 and polar sites: exercises every
    # geometry regime the per-chain device path sees
    return SiteGrid(
        latitude=(0.0, 48.12, 52.5, 70.0),
        longitude=(11.6, 11.6, 13.4, 20.0),
        altitude=(10.0, 520.0, 34.0, 5.0),
        surface_tilt=(10.0, 30.0, 35.0, 60.0),
        surface_azimuth=(180.0, 180.0, 175.0, 180.0),
    )


def assert_stats_identical(a: dict, b: dict):
    assert set(a) == set(b)
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]),
                                      err_msg=k)


# ---------------------------------------------------------------------------
# bit identity: block vs scan on every impl, shared site and site grid
# ---------------------------------------------------------------------------

class TestBitIdentity:
    @pytest.mark.parametrize("impl", IMPLS)
    def test_reduce_stats_identical(self, impl):
        base = Simulation(cfg(block_impl=impl)).run_reduced()
        hoist = Simulation(cfg(block_impl=impl,
                               rng_batch="block")).run_reduced()
        assert_stats_identical(base, hoist)

    @pytest.mark.parametrize("impl", IMPLS)
    def test_site_grid_identical_to_ulps(self, impl):
        """Site-grid runs evaluate the transcendental geometry chain
        INSIDE the jitted step, and the hoist changes the program around
        it (xs grows the stream rows), so XLA's instruction selection
        (fusion / FMA contraction) over that chain may differ by a few
        f32 ULPs — the same measured caveat as sharded-vs-single layout
        changes (test_parallel.py).  The RNG streams themselves stay bit
        identical (``test_block_draws_match_in_scan_draws``); the
        whole-run statistics must agree to a handful of ULPs."""
        base = Simulation(cfg(block_impl=impl,
                              site_grid=grid())).run_reduced()
        hoist = Simulation(cfg(block_impl=impl, site_grid=grid(),
                               rng_batch="block")).run_reduced()
        assert set(base) == set(hoist)
        for k in base:
            x = np.asarray(base[k])
            y = np.asarray(hoist[k])
            if np.issubdtype(x.dtype, np.integer):
                np.testing.assert_array_equal(x, y, err_msg=k)
            else:
                np.testing.assert_allclose(x, y, rtol=1e-6, atol=1e-3,
                                           err_msg=k)

    def test_block_draws_match_in_scan_draws(self):
        # the public hoist wrapper must reproduce the in-scan draws
        # exactly — the unit-level statement of the keying contract
        key = jax.random.key(3, impl="threefry2x32")
        t = np.arange(123_456_060, 123_456_060 + 3600, dtype=np.int64)
        u1, z1 = ci.block_draws(key, t)
        u2, z2 = ci._minute_grouped_draws(key, t, np.float32)
        np.testing.assert_array_equal(np.asarray(u1), np.asarray(u2))
        np.testing.assert_array_equal(np.asarray(z1), np.asarray(z2))

    def test_sharded_identical(self):
        base = ShardedSimulation(cfg(block_impl="scan2",
                                     n_chains=8)).run_reduced()
        hoist = ShardedSimulation(cfg(block_impl="scan2", n_chains=8,
                                      rng_batch="block")).run_reduced()
        assert_stats_identical(base, hoist)

    @pytest.mark.parametrize("impl", ["scan", "scan2"])
    def test_mega_dispatch_identical(self, impl):
        # pre-generation happens per inner block inside the mega scan
        # body, so K-block dispatches stay bit-identical too (and HBM
        # stays bounded at one block's streams)
        base = Simulation(cfg(block_impl=impl,
                              blocks_per_dispatch=2)).run_reduced()
        hoist = Simulation(cfg(block_impl=impl, blocks_per_dispatch=2,
                               rng_batch="block")).run_reduced()
        assert_stats_identical(base, hoist)

    def test_checkpoint_resume_identical(self, tmp_path):
        """Stop after block 0 under rng_batch='scan', resume under
        rng_batch='block': the finished run must match an uninterrupted
        in-scan run bit for bit — the hoist changes no key material, so
        it can even be toggled across a restart."""
        straight = Simulation(cfg(block_impl="scan2")).run_reduced()

        path = str(tmp_path / "r.npz")
        a = Simulation(cfg(block_impl="scan2"))

        class Stop(Exception):
            pass

        def save_then_crash(bi, state, acc):
            ckpt.save(path, {"state": state, "acc": acc}, bi + 1, a.config)
            raise Stop

        with pytest.raises(Stop):
            a.run_reduced(on_block=save_then_crash)

        b = Simulation(cfg(block_impl="scan2", rng_batch="block"))
        tree, nb = ckpt.load(path, b.config)
        assert nb == 1
        resumed = b.run_reduced(state=tree["state"], acc=tree["acc"],
                                start_block=nb)
        assert_stats_identical(resumed, straight)


# ---------------------------------------------------------------------------
# defaults: the lever off must be structurally absent, not branched away
# ---------------------------------------------------------------------------

class TestDefaultHLOIdentity:
    @pytest.mark.parametrize("impl", IMPLS)
    def test_explicit_scan_lowers_byte_identical_to_default(self, impl):
        default = Simulation(cfg(block_impl=impl, n_chains=4))
        explicit = Simulation(cfg(block_impl=impl, n_chains=4,
                                  rng_batch="scan", geom_stride=1))
        state = default.init_state()
        acc = default.init_reduce_acc()
        inputs, _ = default.host_inputs(0)
        if impl == "wide":
            a = default._block_jit.lower(state, inputs).as_text()
            b = explicit._block_jit.lower(state, inputs).as_text()
        else:
            jit = f"_{impl}_acc_jit"
            a = getattr(default, jit).lower(state, inputs, acc).as_text()
            b = getattr(explicit, jit).lower(state, inputs, acc).as_text()
        assert a == b


# ---------------------------------------------------------------------------
# plan plumbing
# ---------------------------------------------------------------------------

class TestPlanPlumbing:
    def test_plan_carries_resolved_axis(self):
        assert Simulation(cfg()).plan.rng_batch == "scan"
        sim = Simulation(cfg(rng_batch="block"))
        assert sim.plan.rng_batch == "block"

    def test_bad_value_rejected(self):
        with pytest.raises(ValueError, match="rng_batch"):
            Simulation(cfg(rng_batch="hoist"))

    def test_precision_doc_carries_axis(self):
        sim = Simulation(cfg(rng_batch="block"))
        doc = sim.precision_doc()
        assert doc is not None and doc["rng_batch"] == "block"
        assert Simulation(cfg()).precision_doc() is None


# ---------------------------------------------------------------------------
# acceptance (slow lane): at least one lever beats baseline scan2 at the
# headline chain count, and neither regresses
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_scan_restructure_speedup_65536_chains():
    """At the headline chain count on CPU, rng_batch='block' or
    geom_stride=60 must run STRICTLY faster than the baseline scan2
    arm, and whichever doesn't win must not regress (25% slack for
    timer noise on the shared host — same budget as the fused-dispatch
    acceptance in test_executor.py).  All arms are timed on their
    second, compile-free run."""
    import time

    def timed_second_run(**kw):
        sim = Simulation(cfg(output="reduce", block_impl="scan2",
                             n_chains=65536, duration_s=1800,
                             block_s=600, **kw))
        sim.run_reduced()              # compile + first dispatch
        t0 = time.perf_counter()
        sim.run_reduced()
        return time.perf_counter() - t0

    base = timed_second_run()
    rngblock = timed_second_run(rng_batch="block")
    stride60 = timed_second_run(geom_stride=60)
    assert rngblock < base or stride60 < base, (base, rngblock, stride60)
    assert rngblock <= base * 1.25, (rngblock, base)
    assert stride60 <= base * 1.25, (stride60, base)


# ---------------------------------------------------------------------------
# satellite: the rbg 76x trap must warn at build time (raise under strict)
# ---------------------------------------------------------------------------

class TestRbgTrap:
    def test_rbg_warns_at_build(self):
        with pytest.warns(RuntimeWarning, match="76x"):
            Simulation(cfg(prng_impl="rbg"))

    def test_rbg_raises_under_strict(self):
        with pytest.raises(ValueError, match="rbg"):
            Simulation(cfg(prng_impl="rbg", telemetry="light",
                           telemetry_strict=True))

    def test_threefry_is_silent(self, recwarn):
        Simulation(cfg())
        assert not [w for w in recwarn.list
                    if issubclass(w.category, RuntimeWarning)
                    and "76x" in str(w.message)]
