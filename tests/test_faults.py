"""Deterministic fault injection (runtime/faults.py): the --chaos spec
grammar, trigger schedules (nK windows, everyK caps, seeded pFLOAT),
first-fire-wins arbitration, the fire/afire chokepoint contract, the
``faults.*`` metrics every injection records, and chokepoint behaviour
inside the local transport and the checkpoint writer.
"""

import asyncio
import datetime as dt
import os
import time

import numpy as np
import pytest

from tmhpvsim_tpu.engine import checkpoint as ckpt
from tmhpvsim_tpu.obs.metrics import MetricsRegistry, use_registry
from tmhpvsim_tpu.runtime import faults
from tmhpvsim_tpu.runtime.broker import make_transport
from tmhpvsim_tpu.runtime.faults import FaultInjected, FaultPlan


def _run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def plan(spec, seed=0):
    return FaultPlan.parse(spec, seed=seed)


@pytest.fixture(autouse=True)
def _no_leaked_chaos():
    """A test failing inside faults.active() must not leak its plan
    into the rest of the suite."""
    yield
    faults.deactivate()


# ---------------------------------------------------------------------------
# spec grammar
# ---------------------------------------------------------------------------


class TestSpecGrammar:
    def test_minimal_rule_parses(self):
        p = plan("broker.publish=raise@n3")
        assert p.describe() == "broker.publish=raise@n3"
        r = p.rules[0]
        assert (r.point, r.action, r.trigger, r.k) == \
            ("broker.publish", "raise", "n", 3)

    def test_multi_rule_whitespace_and_args(self):
        p = plan(" broker.publish=drop@n1 ;"
                 " funnel.stall=delay:0.5@every100 ; ")
        assert [r.point for r in p.rules] == \
            ["broker.publish", "funnel.stall"]
        assert p.rules[1].action == "delay"
        assert p.rules[1].arg == 0.5
        assert p.rules[1].count is None

    def test_empty_spec_rejected(self):
        with pytest.raises(ValueError, match="chaos spec is empty"):
            plan("")
        with pytest.raises(ValueError, match="chaos spec is empty"):
            plan(" ; ")

    @pytest.mark.parametrize("spec,match", [
        ("broker.publish", "expected POINT=ACTION@TRIGGER"),
        ("volcano.erupt=raise@n1", "unknown point"),
        ("broker.publish=explode@n1", "unknown action"),
        ("funnel.stall=delay@n1", "delay needs seconds"),
        ("broker.publish=raise:7@n1", "takes no argument"),
        ("broker.publish=raise@n1xzap", "not an"),
        ("broker.publish=raise@n1x0", "count must be >= 1"),
        ("broker.publish=raise@soon", "bad trigger"),
        ("broker.publish=raise@n0", "trigger index must be >= 1"),
        ("broker.publish=raise@p1.5", "probability outside"),
    ])
    def test_parse_errors_are_specific(self, spec, match):
        with pytest.raises(ValueError, match=match):
            plan(spec)


# ---------------------------------------------------------------------------
# trigger schedules (decide() without any I/O)
# ---------------------------------------------------------------------------


def decisions(p, point, n):
    out = []
    for _ in range(n):
        hit = p.decide(point)
        out.append(None if hit is None else hit.action)
    return out


class TestTriggers:
    def test_n_trigger_fires_once(self):
        p = plan("broker.publish=drop@n2")
        assert decisions(p, "broker.publish", 4) == \
            [None, "drop", None, None]

    def test_n_trigger_with_window(self):
        p = plan("broker.publish=drop@n2x2")
        assert decisions(p, "broker.publish", 5) == \
            [None, "drop", "drop", None, None]

    def test_every_trigger_with_cap(self):
        p = plan("broker.publish=drop@every2x2")
        assert decisions(p, "broker.publish", 8) == \
            [None, "drop", None, "drop", None, None, None, None]

    def test_probability_edges_and_cap(self):
        never = plan("broker.deliver=drop@p0")
        assert decisions(never, "broker.deliver", 10) == [None] * 10
        always = plan("broker.deliver=drop@p1x3")
        assert decisions(always, "broker.deliver", 5) == \
            ["drop", "drop", "drop", None, None]

    def test_probability_is_seed_deterministic(self):
        spec = "broker.deliver=drop@p0.5"
        a = decisions(plan(spec, seed=7), "broker.deliver", 40)
        b = decisions(plan(spec, seed=7), "broker.deliver", 40)
        assert a == b

    def test_points_count_independently(self):
        p = plan("broker.publish=drop@n2;broker.deliver=dup@n1")
        assert p.decide("broker.deliver").action == "dup"
        assert p.decide("broker.publish") is None
        assert p.decide("broker.publish").action == "drop"

    def test_first_firing_rule_wins_and_all_rules_count(self):
        p = plan("broker.publish=drop@n1;broker.publish=dup@n2")
        # call 1: rule 1 fires and wins; rule 2 counted the call too, so
        # its n2 lands on the NEXT publish
        assert decisions(p, "broker.publish", 3) == ["drop", "dup", None]
        q = plan("broker.publish=drop@n1;broker.publish=dup@n1")
        # both scheduled on call 1: the loser's slot is consumed
        assert decisions(q, "broker.publish", 2) == ["drop", None]


# ---------------------------------------------------------------------------
# fire/afire: actions, metrics, activation plumbing
# ---------------------------------------------------------------------------


class TestFire:
    def test_inactive_is_a_noop(self):
        assert faults.ACTIVE is None
        assert faults.fire("broker.publish") is None
        assert _run(faults.afire("broker.publish")) is None

    def test_raise_records_metrics(self):
        reg = MetricsRegistry()
        with use_registry(reg), \
                faults.active(plan("checkpoint.write=raise@n1")):
            with pytest.raises(FaultInjected, match="checkpoint.write"):
                faults.fire("checkpoint.write")
            assert faults.fire("checkpoint.write") is None
        c = reg.snapshot()["counters"]
        assert c["faults.injected_total"] == 1.0
        assert c["faults.injected.checkpoint.write"] == 1.0

    def test_drop_and_dup_are_returned_to_the_chokepoint(self):
        with use_registry(MetricsRegistry()), faults.active(
                plan("broker.publish=drop@n1;broker.publish=dup@n2")):
            assert faults.fire("broker.publish") == "drop"
            assert faults.fire("broker.publish") == "dup"
            assert faults.fire("broker.publish") is None

    def test_afire_delay_sleeps_then_returns_none(self):
        async def main():
            with use_registry(MetricsRegistry()), \
                    faults.active(plan("funnel.stall=delay:0.02@n1")):
                t0 = time.monotonic()
                assert await faults.afire("funnel.stall") is None
                assert time.monotonic() - t0 >= 0.015
        _run(main())

    def test_active_context_restores_none(self):
        p = plan("broker.publish=drop@n1")
        with faults.active(p):
            assert faults.ACTIVE is p
        assert faults.ACTIVE is None

    def test_install_from_env(self):
        try:
            p = faults.install_from_env({
                faults.ENV_SPEC: "broker.connect=raise@n1",
                faults.ENV_SEED: "5",
            })
            assert faults.ACTIVE is p
            assert p.seed == 5
            assert p.rules[0].point == "broker.connect"
        finally:
            faults.deactivate()
        assert faults.install_from_env({}) is None
        assert faults.ACTIVE is None


# ---------------------------------------------------------------------------
# chokepoints in the local transport
# ---------------------------------------------------------------------------


class TestTransportChokepoints:
    def _pubsub(self, url, spec_pub=None, spec_sub=None):
        """Publish [1, 2, 3] and return what a subscriber saw, with an
        optional plan active around the publishes or the consumption."""

        async def main():
            got = []
            sub_tx = make_transport(url, "m")
            async with sub_tx:
                async def consume():
                    async for _t, v in sub_tx.subscribe():
                        got.append(v)

                task = asyncio.create_task(consume())
                await asyncio.sleep(0.05)
                async with make_transport(url, "m") as pub:
                    if spec_pub:
                        with faults.active(plan(spec_pub)):
                            for v in (1.0, 2.0, 3.0):
                                await pub.publish(v, dt.datetime(2019, 9, 5))
                    elif spec_sub:
                        with faults.active(plan(spec_sub)):
                            for v in (1.0, 2.0, 3.0):
                                await pub.publish(v, dt.datetime(2019, 9, 5))
                            await asyncio.sleep(0.1)
                    else:
                        for v in (1.0, 2.0, 3.0):
                            await pub.publish(v, dt.datetime(2019, 9, 5))
                await asyncio.sleep(0.1)
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
            return got

        return _run(main())

    def test_publish_drop_suppresses_and_dup_doubles(self):
        reg = MetricsRegistry()
        with use_registry(reg):
            got = self._pubsub(
                "local://faults-pub",
                spec_pub="broker.publish=drop@n1;broker.publish=dup@n2")
        assert got == [2.0, 2.0, 3.0]
        c = reg.snapshot()["counters"]
        assert c["faults.injected.broker.publish"] == 2.0

    def test_deliver_drop_suppresses_and_dup_doubles(self):
        with use_registry(MetricsRegistry()):
            got = self._pubsub(
                "local://faults-sub",
                spec_sub="broker.deliver=drop@n1;broker.deliver=dup@n2")
        assert got == [2.0, 2.0, 3.0]

    def test_connect_raise_then_recovers(self):
        async def main():
            with faults.active(plan("broker.connect=raise@n1")):
                with pytest.raises(FaultInjected):
                    async with make_transport("local://faults-conn", "m"):
                        pass
                async with make_transport("local://faults-conn", "m"):
                    return True

        with use_registry(MetricsRegistry()):
            assert _run(main())


# ---------------------------------------------------------------------------
# checkpoint chokepoints: write before disk, committed after os.replace
# ---------------------------------------------------------------------------


class TestCheckpointChokepoints:
    def test_write_fault_leaves_no_file(self, tmp_path):
        path = str(tmp_path / "state.npz")
        state = {"x": np.arange(3)}
        with use_registry(MetricsRegistry()):
            with faults.active(plan("checkpoint.write=raise@n1")):
                with pytest.raises(FaultInjected):
                    ckpt.save(path, state, 1)
            assert not os.path.exists(path)
            ckpt.save(path, state, 1)
        assert ckpt.peek_meta(path)["next_block"] == 1

    def test_committed_fault_fires_after_atomic_replace(self, tmp_path):
        """The kill-site guarantee: a fault at ``checkpoint.committed``
        strikes AFTER the atomic rename, so the crash the recovery tests
        schedule there always leaves a valid checkpoint behind."""
        path = str(tmp_path / "state.npz")
        state = {"x": np.arange(3)}
        with use_registry(MetricsRegistry()):
            with faults.active(plan("checkpoint.committed=raise@n1")):
                with pytest.raises(FaultInjected):
                    ckpt.save(path, state, 2)
        assert ckpt.peek_meta(path)["next_block"] == 2
        assert not os.path.exists(path + ".tmp")


# ---------------------------------------------------------------------------
# the truncate action + the preemption/corruption chokepoints
# ---------------------------------------------------------------------------


class TestTruncateAction:
    def test_truncate_rule_parses(self):
        r = plan("checkpoint.corrupt=truncate:120@n2").rules[0]
        assert (r.point, r.action, r.arg, r.trigger, r.k) == \
            ("checkpoint.corrupt", "truncate", 120, "n", 2)

    @pytest.mark.parametrize("spec,match", [
        ("checkpoint.corrupt=truncate@n1",
         "truncate needs a byte offset"),
        ("checkpoint.corrupt=truncate:zap@n1",
         "truncate needs a byte offset"),
        ("checkpoint.corrupt=truncate:-1@n1",
         "truncate offset must be >= 0"),
    ])
    def test_truncate_parse_errors(self, spec, match):
        with pytest.raises(ValueError, match=match):
            plan(spec)

    def test_new_points_registered(self):
        for point in ("checkpoint.corrupt", "signal.preempt"):
            assert point in faults.POINTS

    def test_fire_truncates_the_context_path(self, tmp_path):
        p = tmp_path / "f.bin"
        p.write_bytes(b"x" * 100)
        reg = MetricsRegistry()
        with use_registry(reg), \
                faults.active(plan("checkpoint.corrupt=truncate:10@n1")):
            assert faults.fire("checkpoint.corrupt", path=str(p)) == \
                "truncate"
        assert p.stat().st_size == 10
        c = reg.snapshot()["counters"]
        assert c["faults.injected.checkpoint.corrupt"] == 1.0

    def test_truncate_beyond_size_is_clamped(self, tmp_path):
        p = tmp_path / "f.bin"
        p.write_bytes(b"x" * 5)
        with use_registry(MetricsRegistry()), \
                faults.active(plan("checkpoint.corrupt=truncate:99@n1")):
            faults.fire("checkpoint.corrupt", path=str(p))
        assert p.stat().st_size == 5

    def test_truncate_without_path_warns_not_crashes(self):
        # a truncate rule on a point that passes no path= context is a
        # misconfiguration, not a crash
        with use_registry(MetricsRegistry()), \
                faults.active(plan("signal.preempt=truncate:1@n1")):
            assert faults.fire("signal.preempt") == "truncate"

    def test_truncate_missing_file_warns_not_crashes(self, tmp_path):
        with use_registry(MetricsRegistry()), \
                faults.active(plan("checkpoint.corrupt=truncate:1@n1")):
            assert faults.fire("checkpoint.corrupt",
                               path=str(tmp_path / "nope")) == "truncate"

    def test_save_chokepoint_tears_then_rotation_recovers(self, tmp_path):
        """checkpoint.corrupt=truncate:K tears the generation that was
        JUST committed (the anchor hard-links it), and the loader falls
        back to the previous generation — the in-process version of the
        chaos torn-write recovery."""
        path = str(tmp_path / "s.npz")
        state = {"x": np.arange(6)}
        with use_registry(MetricsRegistry()):
            ckpt.save(path, state, 1)
            with faults.active(
                    plan("checkpoint.corrupt=truncate:64@n1")):
                ckpt.save(path, state, 2)  # g2 torn right after commit
            tree, nb = ckpt.load(path)
        assert nb == 1
        np.testing.assert_array_equal(tree["x"], state["x"])
