"""TCP fanout broker tests: real sockets, real processes.

The reference's deployment needs an external RabbitMQ server the repo can
only fake (tests/test_amqp.py); the in-tree TCP broker
(runtime/tcpbroker.py) gives the same fanout semantics over real TCP, so
these tests exercise an actual broker-mediated pipeline end to end — in
one event loop first, then across three OS processes exactly like the
reference's README deployment.
"""

import asyncio
import csv
import datetime as dt
import os
import subprocess
import sys

import pytest

from tmhpvsim_tpu.runtime.tcpbroker import TcpFanoutBroker, TcpTransport


def _run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


class TestFanoutSemantics:
    def test_every_subscriber_sees_every_message(self):
        """Two subscribers on one exchange: both receive the full stream
        (the AMQP fanout contract, pvsim.py:62-63)."""

        async def main():
            async with TcpFanoutBroker(port=0) as broker:
                url = f"tcp://127.0.0.1:{broker.port}"

                async def consume(n):
                    out = []
                    async with TcpTransport(url, "meter") as t:
                        async for time, value in t.subscribe():
                            out.append((time, value))
                            if len(out) == n:
                                return out

                c1 = asyncio.create_task(consume(3))
                c2 = asyncio.create_task(consume(3))
                await asyncio.sleep(0.1)  # let both subscribe
                async with TcpTransport(url, "meter") as pub:
                    for i in range(3):
                        await pub.publish(
                            100.0 + i, dt.datetime(2019, 9, 5, 12, 0, i)
                        )
                r1, r2 = await asyncio.gather(c1, c2)
                return r1, r2

        r1, r2 = _run(main())
        assert r1 == r2
        assert [v for _, v in r1] == [100.0, 101.0, 102.0]
        assert r1[0][0] == dt.datetime(2019, 9, 5, 12, 0, 0)

    def test_subsecond_timestamps_roundtrip_exactly(self):
        """The wire encodes integer epoch microseconds: a sub-second
        datetime must come back EXACTLY (the funnel joins on datetime
        equality; a float64-seconds encoding can perturb the microsecond
        field through json)."""
        times = [
            dt.datetime(2019, 9, 5, 12, 0, 0, 1),
            dt.datetime(2019, 9, 5, 12, 0, 0, 333333),
            dt.datetime(2038, 1, 19, 3, 14, 7, 999999),
            dt.datetime(1969, 12, 31, 23, 59, 59, 7),   # negative epoch
        ]

        async def main():
            async with TcpFanoutBroker(port=0) as broker:
                url = f"tcp://127.0.0.1:{broker.port}"

                async def consume(n):
                    out = []
                    async with TcpTransport(url, "meter") as t:
                        async for time, value in t.subscribe():
                            out.append((time, value))
                            if len(out) == n:
                                return out

                c = asyncio.create_task(consume(len(times)))
                await asyncio.sleep(0.1)
                async with TcpTransport(url, "meter") as pub:
                    for i, t in enumerate(times):
                        await pub.publish(float(i), t)
                return await c

        got = _run(main())
        assert [t for t, _ in got] == times

    def test_exchanges_are_isolated(self):
        """A subscriber on exchange A never sees exchange B's messages."""

        async def main():
            async with TcpFanoutBroker(port=0) as broker:
                url = f"tcp://127.0.0.1:{broker.port}"

                async def consume_one():
                    async with TcpTransport(url, "a") as t:
                        async for _, value in t.subscribe():
                            return value

                task = asyncio.create_task(consume_one())
                await asyncio.sleep(0.1)
                async with TcpTransport(url, "b") as pb, \
                        TcpTransport(url, "a") as pa:
                    await pb.publish(666.0, dt.datetime(2019, 9, 5))
                    await pa.publish(42.0, dt.datetime(2019, 9, 5))
                return await task

        assert _run(main()) == 42.0

    def test_subscriber_disconnect_does_not_break_publish(self):
        """Publishing keeps working after a consumer drops (its queue is
        unregistered; no stale writer is retained)."""

        async def main():
            async with TcpFanoutBroker(port=0) as broker:
                url = f"tcp://127.0.0.1:{broker.port}"

                async def consume_one():
                    async with TcpTransport(url, "meter") as t:
                        async for _, value in t.subscribe():
                            return value

                v = asyncio.create_task(consume_one())
                await asyncio.sleep(0.1)
                async with TcpTransport(url, "meter") as pub:
                    await pub.publish(1.0, dt.datetime(2019, 9, 5))
                    assert await v == 1.0
                    await asyncio.sleep(0.1)  # consumer gone
                    await pub.publish(2.0, dt.datetime(2019, 9, 5))
                assert not broker._exchanges.get("meter")
                return True

        assert _run(main())

    def test_stop_with_live_clients_does_not_hang(self):
        """Broker shutdown while a subscriber is still connected must
        return promptly: since Python 3.12.1, Server.wait_closed() also
        waits for connection handlers, so stop() has to disconnect live
        clients itself or it deadlocks behind a parked readline()."""

        async def main():
            broker = TcpFanoutBroker(port=0)
            await broker.start()
            url = f"tcp://127.0.0.1:{broker.port}"

            async def consume():
                async with TcpTransport(url, "meter") as t:
                    async for _ in t.subscribe():
                        pass

            task = asyncio.create_task(consume())
            await asyncio.sleep(0.1)  # subscriber bound and parked
            await asyncio.wait_for(broker.stop(), timeout=5)
            with pytest.raises((ConnectionError, asyncio.IncompleteReadError,
                                OSError)):
                await asyncio.wait_for(task, timeout=5)
            return True

        assert _run(main())

    def test_connection_error_raises_for_retry(self):
        """A dead broker must raise out of the transport so the apps'
        forever-retry reconnect loop engages (runtime/resilience.py)."""

        async def main():
            broker = TcpFanoutBroker(port=0)
            await broker.start()
            url = f"tcp://127.0.0.1:{broker.port}"
            await broker.stop()
            with pytest.raises(OSError):
                async with TcpTransport(url, "meter"):
                    pass
            return True

        assert _run(main())


def test_three_process_deployment(tmp_path):
    """The reference's README deployment, with the in-tree broker instead
    of RabbitMQ: broker, metersim and pvsim as three OS processes joined
    only by TCP.  The consumer's CSV must contain joined rows.

    Producer and consumer run under DIFFERENT host timezones: the wire
    protocol carries naive wall time as as-if-UTC epochs
    (runtime/tcpbroker.py), so the timestamp join must be host-TZ
    independent — a naive .timestamp() round-trip would skew the streams
    by 6 hours here and join nothing."""
    env = dict(os.environ, PYTHONPATH="", JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    out = tmp_path / "out.csv"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    broker = subprocess.Popen(
        [sys.executable, "-m", "tmhpvsim_tpu.cli", "fanoutbroker",
         "--port", "0"],
        env=env, stderr=subprocess.PIPE, text=True, cwd=repo,
    )
    try:
        line = broker.stderr.readline()  # "... listening on host:port"
        port = int(line.rsplit(":", 1)[1])
        url = f"tcp://127.0.0.1:{port}"
        start = "2019-09-05 12:00:00"

        consumer = subprocess.Popen(
            [sys.executable, "-m", "tmhpvsim_tpu.cli", "pvsim", str(out),
             "--amqp-url", url, "--no-realtime", "--start", start],
            env=dict(env, TZ="America/Chicago"), stderr=subprocess.PIPE,
            text=True, cwd=repo,
        )
        try:
            # Fanout delivers only to ALREADY-bound subscribers, and the
            # consumer's interpreter start + imports take seconds on this
            # host — wait for its CSV header (written at app start) plus a
            # beat for the subscribe frame, like the reference's two-shell
            # procedure starts pvsim first.
            import time as _time

            deadline = _time.time() + 60
            while _time.time() < deadline and not out.exists():
                _time.sleep(0.5)
            assert out.exists(), "consumer never started"
            _time.sleep(2.0)
            producer = subprocess.run(
                [sys.executable, "-m", "tmhpvsim_tpu.cli", "metersim",
                 "--amqp-url", url, "--no-realtime", "--duration", "40",
                 "--start", start, "--seed", "3"],
                env=dict(env, TZ="UTC"), capture_output=True, text=True,
                timeout=120, cwd=repo,
            )
            assert producer.returncode == 0, producer.stderr
            # let the join drain, then stop the (unbounded) consumer
            deadline = _time.time() + 30
            while _time.time() < deadline:
                if out.exists() and sum(1 for _ in open(out)) > 20:
                    break
                _time.sleep(0.5)
        finally:
            consumer.terminate()
            consumer.wait(timeout=30)
    finally:
        broker.terminate()
        broker.wait(timeout=30)

    with open(out) as f:
        rows = list(csv.reader(f))
    assert rows[0] == ["time", "meter", "pv", "residual load"]
    assert len(rows) > 20  # most of the 40 published seconds joined
    for time_s, meter, pv, residual in rows[1:]:
        assert float(meter) - float(pv) == pytest.approx(float(residual))
        assert 0 <= float(meter) < 9000
        assert time_s.startswith("2019-09-05 12:")
