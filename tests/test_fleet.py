"""Heterogeneous fleet subsystem (tmhpvsim_tpu/fleet/): per-site
parameters as a first-class batched pytree on the chain axis.

Covered here:
* FleetParams validation (lengths, ranges, regimes, cohorts), the
  heterogeneity flags, digest stability across builders, slice_fleet;
* builders: from_csv (line-numbered refusals, blank-cell defaults),
  the seeded synthetic national-fleet sampler (reproducible bit-stream);
* a NEUTRAL fleet is the absence of the feature: run_reduced bitwise
  equal to the no-fleet run AND byte-identical lowered HLO;
* per-site transform semantics: regime row 0 aliases the Munich fit,
  demand affine map, DC capacity scale + inverter AC clip;
* the partition exactness matrix (ISSUE satellite): a heterogeneous
  uniform-geometry fleet is bit-identical 8-device-sharded vs single
  device on wide/scan/scan2, slab-vs-monolithic, and mega-dispatch;
  per-cohort analytics merge with the established contract (int
  counts, extrema and quantiles exact; float-sum means reassociate);
* checkpoint config echo: a changed fleet refuses resume, the same
  fleet (and a fleet-less checkpoint) resumes fine;
* the scenario-serving site selector: a site/cohort-selected reply is
  bit-identical to simulating exactly those chains alone, and the
  selector validation is typed;
* RunReport v12: per-cohort ``fleet.cohorts`` table + config-echo fleet
  identity round-trip the validator (v11 documents still validate) and
  tools/fleet_report.py prints/validates the cohort table.

Geometry note: the fleet fixtures here are deliberately
geometry-UNIFORM (every site the Munich default) while heterogeneous in
demand/power/regime/cohort — per-site GEOMETRY already has its own
equivalence scope in tests/test_sitegrid.py (the CPU backend's
shape-dependent geometry codegen is float-close, not bitwise, across
shard layouts, a pre-existing property unrelated to the fleet leaves).
"""

import dataclasses
import json
import subprocess
import sys
from pathlib import Path

import jax
import numpy as np
import pytest

from tmhpvsim_tpu.config import Site, SimConfig
from tmhpvsim_tpu.engine import Simulation, autotune
from tmhpvsim_tpu.engine import checkpoint as ckpt
from tmhpvsim_tpu.fleet import (
    COLUMN_RANGES,
    N_REGIMES,
    NO_AC_LIMIT,
    FleetParams,
    slice_fleet,
)
from tmhpvsim_tpu.obs.metrics import MetricsRegistry, use_registry
from tmhpvsim_tpu.obs.report import REPORT_SCHEMA_VERSION, validate_report
from tmhpvsim_tpu.parallel import ShardedSimulation
from tmhpvsim_tpu.serve import schema
from tmhpvsim_tpu.serve.schema import RequestError, Scenario
from tmhpvsim_tpu.serve.server import ScenarioEngine

REPO = Path(__file__).resolve().parents[1]
FLEET_REPORT = REPO / "tools" / "fleet_report.py"

SITE = Site()
INF = float("inf")


def small_cfg(**kw):
    base = dict(
        start="2019-09-05 10:00:00",
        duration_s=7200,
        n_chains=8,
        seed=7,
        block_s=3600,
        dtype="float32",
        block_impl="scan",
    )
    base.update(kw)
    return SimConfig(**base)


def _geom(n):
    """Uniform geometry at the Munich default site (see module note)."""
    return dict(
        latitude=(SITE.latitude,) * n, longitude=(SITE.longitude,) * n,
        altitude=(SITE.altitude,) * n,
        surface_tilt=(SITE.surface_tilt,) * n,
        surface_azimuth=(SITE.surface_azimuth,) * n,
        albedo=(SITE.albedo,) * n,
    )


def het_fleet(n=8):
    """Heterogeneous in every non-geometry column: scaled+shifted demand,
    scaled+half-clipped pv, all three weather regimes, three cohorts."""
    return FleetParams(
        dc_capacity_scale=tuple(0.5 + 0.2 * i for i in range(n)),
        ac_limit_w=(150.0,) * (n // 2) + (INF,) * (n - n // 2),
        weather_regime=tuple(i % 3 for i in range(n)),
        demand_scale=tuple(1.0 + 0.1 * i for i in range(n)),
        demand_shift_w=tuple(10.0 * i for i in range(n)),
        cohort=tuple((0, 0, 1, 1, 2, 2, 0, 1)[i % 8] for i in range(n)),
        **_geom(n),
    )


def neutral_fleet(n=8):
    return FleetParams(**_geom(n))


def _reduced(cfg, plan=None, cls=Simulation):
    with use_registry(MetricsRegistry()):
        sim = cls(cfg, plan=plan)
        red = sim.run_reduced()
        return ({k: np.asarray(v) for k, v in red.items()},
                sim.fleet_summary())


def _assert_reduced_equal(a, b):
    assert set(a) == set(b)
    for k in a:
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)


def _assert_fleet_equal_cohort_means_close(a, b):
    """The merge contract for per-cohort sections: every risk leaf and
    every cohort counting/extremum/quantile leaf bitwise; the cohort
    float-sum means reassociate across shard/slab merges (float32), so
    they compare to tolerance — mirroring test_analytics.py's
    sharded-full-level contract."""
    ka, kb = dict(a), dict(b)
    ca, cb = ka.pop("cohorts"), kb.pop("cohorts")
    assert ka == kb
    assert ca is not None and cb is not None
    assert len(ca) == len(cb)
    for ra, rb in zip(ca, cb):
        for k in ("cohort", "count", "residual_min", "residual_max",
                  "quantiles"):
            assert rb[k] == ra[k], k
        for k in ("meter_mean", "pv_mean", "residual_mean"):
            if ra[k] is None:
                assert rb[k] is None
            else:
                assert rb[k] == pytest.approx(ra[k], rel=1e-4), k


# ---------------------------------------------------------------------------
# FleetParams: validation, flags, digest, slicing
# ---------------------------------------------------------------------------

class TestParams:
    def test_defaults_are_neutral(self):
        fp = FleetParams(latitude=(48.1, 47.0), longitude=(11.6, 9.5))
        assert len(fp) == 2
        assert fp.dc_capacity_scale == (1.0, 1.0)
        assert fp.ac_limit_w == (NO_AC_LIMIT, NO_AC_LIMIT)
        assert fp.weather_regime == (0, 0)
        assert fp.demand_scale == (1.0, 1.0)
        assert fp.demand_shift_w == (0.0, 0.0)
        assert fp.cohort == (0, 0)
        assert fp.surface_tilt == (48.1, 47.0)  # tilt-equals-latitude
        assert not (fp.het_demand or fp.het_power or fp.het_regime)
        assert fp.n_cohorts == 1

    def test_het_flags_gate_per_axis(self):
        kw = _geom(2)
        assert FleetParams(demand_scale=(1.0, 1.5), **kw).het_demand
        assert FleetParams(demand_shift_w=(0.0, 5.0), **kw).het_demand
        assert FleetParams(dc_capacity_scale=(1.0, 2.0), **kw).het_power
        # ANY finite AC limit is a heterogeneity (the clip is traced)
        assert FleetParams(ac_limit_w=(200.0, 200.0), **kw).het_power
        assert FleetParams(weather_regime=(0, 1), **kw).het_regime
        fp = FleetParams(demand_scale=(1.0, 1.5), **kw)
        assert not (fp.het_power or fp.het_regime)
        assert fp.uniform_geometry

    def test_length_mismatch_rejected(self):
        with pytest.raises(ValueError, match="must have length 2"):
            FleetParams(latitude=(48.0, 47.0), longitude=(11.0, 9.0),
                        demand_scale=(1.0,))

    def test_empty_fleet_rejected(self):
        with pytest.raises(ValueError, match="at least one site"):
            FleetParams(latitude=(), longitude=())

    @pytest.mark.parametrize("col,bad", [
        ("latitude", 95.0),
        ("albedo", 1.5),
        ("dc_capacity_scale", -0.5),
        ("demand_scale", -1.0),
        ("ac_limit_w", -10.0),
    ])
    def test_out_of_range_column_rejected(self, col, bad):
        kw = _geom(2)
        kw[col] = (kw.get(col, (1.0, 1.0))[0], bad) if col in kw \
            else (1.0, bad)
        assert col in COLUMN_RANGES  # the bound the refusal cites
        with pytest.raises(ValueError,
                           match=rf"FleetParams\.{col}\[1\]"):
            FleetParams(**kw)

    def test_bad_regime_and_cohort_rejected(self):
        with pytest.raises(ValueError, match="weather_regime"):
            FleetParams(weather_regime=(0, N_REGIMES), **_geom(2))
        with pytest.raises(ValueError, match="cohort"):
            FleetParams(cohort=(0, -1), **_geom(2))

    def test_digest_stable_and_content_addressed(self):
        fp = het_fleet(4)
        again = het_fleet(4)
        assert fp.digest() == again.digest()
        changed = dataclasses.replace(fp, demand_shift_w=(0.0, 10.0,
                                                          20.0, 31.0))
        assert changed.digest() != fp.digest()

    def test_uniform_site_is_the_munich_default(self):
        fp = neutral_fleet(4)
        assert fp.uniform_geometry
        assert fp.uniform_site() == SITE

    def test_slice_keeps_cohort_width(self):
        fp = het_fleet(8)
        assert fp.n_cohorts == 3
        sl = slice_fleet(fp, 2, 3)
        assert len(sl) == 3
        assert sl.latitude == fp.latitude[2:5]
        assert sl.cohort == fp.cohort[2:5]
        # the slice's cohort ids span < 3 values but the accumulator
        # width must stay the parent's (slab merges need equal shapes)
        assert sl.n_cohorts == 3
        assert slice_fleet(None, 0, 4) is None


# ---------------------------------------------------------------------------
# builders: CSV and the synthetic sampler
# ---------------------------------------------------------------------------

class TestBuilders:
    def _write(self, tmp_path, text):
        p = tmp_path / "fleet.csv"
        p.write_text(text)
        return str(p)

    def test_csv_full_columns(self, tmp_path):
        path = self._write(tmp_path, (
            "latitude,longitude,dc_capacity_scale,ac_limit_w,"
            "weather_regime,demand_scale,demand_shift_w,cohort,owner\n"
            "48.1,11.6,1.5,200,1,1.2,50,2,alice\n"
            "47.0,9.5,0.8,,0,0.9,-25,0,bob\n"
        ))
        fp = FleetParams.from_csv(path)
        assert len(fp) == 2
        assert fp.dc_capacity_scale == (1.5, 0.8)
        assert fp.ac_limit_w == (200.0, NO_AC_LIMIT)  # blank = no clip
        assert fp.weather_regime == (1, 0)
        assert fp.demand_shift_w == (50.0, -25.0)
        assert fp.cohort == (2, 0)
        assert fp.het_demand and fp.het_power and fp.het_regime

    def test_csv_defaults_applied(self, tmp_path):
        fp = FleetParams.from_csv(self._write(
            tmp_path, "latitude,longitude\n48.1,11.6\n"))
        assert fp.dc_capacity_scale == (1.0,)
        assert fp.ac_limit_w == (NO_AC_LIMIT,)
        assert not (fp.het_demand or fp.het_power or fp.het_regime)

    @pytest.mark.parametrize("row,match", [
        ("48.1,11.6,-2.0", r"line 3: demand_scale=-2\.0 outside"),
        ("95.0,11.6,1.0", r"line 3: latitude=95\.0 outside"),
        ("48.1,11.6,oops", r"line 3: bad value 'oops'"),
    ])
    def test_csv_refusals_name_the_line(self, tmp_path, row, match):
        path = self._write(tmp_path, (
            "latitude,longitude,demand_scale\n"
            "48.1,11.6,1.0\n" + row + "\n"
        ))
        with pytest.raises(ValueError, match=match):
            FleetParams.from_csv(path)

    def test_csv_bad_regime_names_the_line(self, tmp_path):
        path = self._write(tmp_path, (
            "latitude,longitude,weather_regime\n"
            f"48.1,11.6,{N_REGIMES}\n"
        ))
        with pytest.raises(ValueError, match="line 2: weather_regime"):
            FleetParams.from_csv(path)

    def test_csv_missing_required_column(self, tmp_path):
        path = self._write(tmp_path, "latitude,cohort\n48.1,0\n")
        with pytest.raises(ValueError, match="longitude"):
            FleetParams.from_csv(path)

    def test_synthetic_is_reproducible(self):
        a = FleetParams.synthetic(64, seed=11)
        assert len(a) == 64
        assert a.digest() == FleetParams.synthetic(64, seed=11).digest()
        assert a.digest() != FleetParams.synthetic(64, seed=12).digest()
        # a real national fleet is heterogeneous on every axis
        assert a.het_demand and a.het_power and a.het_regime
        assert not a.uniform_geometry
        assert a.n_cohorts == 3
        # validation ran in __post_init__, so every column is in range;
        # spot-check the documented envelope
        assert all(47.3 <= v <= 55.0 for v in a.latitude)
        assert all(v == NO_AC_LIMIT or v > 0 for v in a.ac_limit_w)


# ---------------------------------------------------------------------------
# neutral fleet == no fleet: bitwise results AND byte-identical HLO
# ---------------------------------------------------------------------------

class TestHomogeneousIsAbsent:
    def test_neutral_fleet_reduces_bitwise_to_baseline(self):
        base, _ = _reduced(small_cfg())
        fl, _ = _reduced(small_cfg(fleet=neutral_fleet(8)))
        _assert_reduced_equal(base, fl)

    @pytest.mark.parametrize("impl", ["scan", "scan2"])
    def test_neutral_fleet_lowers_byte_identical(self, impl):
        """The acceptance bar: a homogeneous FleetParams must not merely
        compute the same numbers — the traced block step must lower to
        byte-identical HLO (no dead leaves, no gated branches)."""
        bare = Simulation(small_cfg(block_impl=impl, n_chains=4))
        fleeted = Simulation(small_cfg(block_impl=impl, n_chains=4,
                                       fleet=neutral_fleet(4)))
        state = bare.init_state()
        acc = bare.init_reduce_acc()
        inputs, _ = bare.host_inputs(0)
        attr = f"_{impl}_acc_jit"
        a = getattr(bare, attr).lower(state, inputs, acc).as_text()
        b = getattr(fleeted, attr).lower(state, inputs, acc).as_text()
        assert a == b


# ---------------------------------------------------------------------------
# per-site transform semantics
# ---------------------------------------------------------------------------

class TestTransforms:
    def test_regime_zero_rows_alias_the_munich_fit(self):
        """Stacked regime tables: row 0 is the Munich fit byte-for-byte,
        so a regime-0 chain inside a heterogeneous-regime fleet must
        reproduce the no-fleet chain bitwise (same fold_in keying, same
        step-distribution constants)."""
        fp = FleetParams(weather_regime=(0, 1), **_geom(2))
        base, _ = _reduced(small_cfg(n_chains=2))
        fl, _ = _reduced(small_cfg(n_chains=2, fleet=fp))
        for k in base:
            np.testing.assert_array_equal(base[k][0], fl[k][0], err_msg=k)
        # ...and the regime-1 chain really simulates different weather
        assert fl["pv_sum"][1] != base["pv_sum"][1] or \
            fl["residual_sum"][1] != base["residual_sum"][1]

    def test_demand_affine_map(self):
        scale = (1.0, 1.5, 0.5, 2.0)
        shift = (0.0, 100.0, -50.0, 25.0)
        fp = FleetParams(demand_scale=scale, demand_shift_w=shift,
                         **_geom(4))
        cfg = small_cfg(n_chains=4, duration_s=3600)
        with use_registry(MetricsRegistry()):
            base = next(iter(Simulation(cfg).run_blocks()))
        with use_registry(MetricsRegistry()):
            het = next(iter(Simulation(
                dataclasses.replace(cfg, fleet=fp)).run_blocks()))
        # pv untouched by the demand axis
        np.testing.assert_array_equal(np.asarray(base.pv),
                                      np.asarray(het.pv))
        # the neutral row is untouched BITWISE (identity transform rows
        # still trace the op, but 1.0*x + 0.0 is exact in IEEE)
        np.testing.assert_array_equal(np.asarray(base.meter[0]),
                                      np.asarray(het.meter[0]))
        sc = np.asarray(scale, np.float32)[:, None]
        sh = np.asarray(shift, np.float32)[:, None]
        np.testing.assert_allclose(np.asarray(het.meter),
                                   np.asarray(base.meter) * sc + sh,
                                   rtol=1e-6, atol=1e-3)

    def test_capacity_scale_and_ac_clip(self):
        cap = (1.0, 2.0, 1.0, 0.5)
        lim = (INF, INF, 40.0, INF)
        fp = FleetParams(dc_capacity_scale=cap, ac_limit_w=lim,
                         **_geom(4))
        cfg = small_cfg(n_chains=4)  # 10:00-12:00, daylight
        base, _ = _reduced(cfg)
        het, _ = _reduced(dataclasses.replace(cfg, fleet=fp))
        assert base["pv_max"].max() > 40.0  # the clip actually bites
        # meter untouched by the power axis
        np.testing.assert_array_equal(base["meter_sum"], het["meter_sum"])
        # max(min(pv*c, L)) == min(max(pv)*c, L): f32 multiply by a
        # positive constant and min against it are monotone, so the
        # extremum transforms exactly
        expect = np.minimum(base["pv_max"] * np.float32(cap),
                            np.asarray(lim, np.float32))
        np.testing.assert_array_equal(het["pv_max"], expect)
        assert het["pv_max"][2] == np.float32(40.0)


# ---------------------------------------------------------------------------
# the partition exactness matrix (ISSUE satellite 3)
# ---------------------------------------------------------------------------

#: memoised monolithic references, keyed by config extras
_REF = {}


def _mono(impl="scan", **kw):
    key = (impl,) + tuple(sorted(kw.items()))
    if key not in _REF:
        _REF[key] = _reduced(small_cfg(fleet=het_fleet(8),
                                       analytics="risk",
                                       block_impl=impl, **kw))
    return _REF[key]


class TestPartitions:
    @pytest.mark.parametrize("impl", ["scan", "scan2", "wide"])
    def test_sharded_equals_single_device(self, impl):
        """Heterogeneous (uniform-geometry) fleet, 8 chains over 8
        devices vs one: per-chain reductions bitwise on all three block
        formulations; the fleet section merges with the cohort
        contract."""
        red1, sec1 = _mono(impl)
        red8, sec8 = _reduced(small_cfg(fleet=het_fleet(8),
                                        analytics="risk",
                                        block_impl=impl),
                              cls=ShardedSimulation)
        _assert_reduced_equal(red1, red8)
        _assert_fleet_equal_cohort_means_close(sec1, sec8)

    def test_slab_equals_monolithic(self):
        cfg = small_cfg(fleet=het_fleet(8), analytics="risk",
                        duration_s=3600, block_s=1800)
        plan = dataclasses.replace(autotune.static_plan(cfg),
                                   slab_chains=3)  # uneven 3+3+2
        red1, sec1 = _mono(duration_s=3600, block_s=1800)
        reds, secs = _reduced(cfg, plan=plan)
        _assert_reduced_equal(red1, reds)
        _assert_fleet_equal_cohort_means_close(sec1, secs)

    def test_mega_dispatch_is_fully_bitwise(self):
        """blocks_per_dispatch fuses blocks on ONE device in the same
        order — no reassociation anywhere, so even the cohort float
        sums are bitwise."""
        cfg = small_cfg(fleet=het_fleet(8), analytics="risk")
        plan = dataclasses.replace(autotune.static_plan(cfg),
                                   blocks_per_dispatch=2)
        red1, sec1 = _mono()
        redm, secm = _reduced(cfg, plan=plan)
        _assert_reduced_equal(red1, redm)
        assert secm == sec1

    def test_cohort_counts_partition_the_fleet(self):
        _, sec = _mono()
        rows = sec["cohorts"]
        assert [r["cohort"] for r in rows] == [0, 1, 2]
        # chains per cohort (0,0,1,1,2,2,0,1) x 7200 s
        assert [r["count"] for r in rows] == [3 * 7200, 3 * 7200,
                                              2 * 7200]
        assert sum(r["count"] for r in rows) == sec["count"]


# ---------------------------------------------------------------------------
# checkpoint config echo (ISSUE satellite 2)
# ---------------------------------------------------------------------------

class TestCheckpointEcho:
    def _run_and_save(self, tmp_path, cfg):
        with use_registry(MetricsRegistry()):
            sim = Simulation(cfg)
            list(sim.run_blocks())
        path = str(tmp_path / "ck.npz")
        ckpt.save(path, sim.state, 1, cfg)
        return path

    def test_changed_fleet_refuses_resume(self, tmp_path):
        fp = het_fleet(4)
        cfg = small_cfg(n_chains=4, fleet=fp)
        path = self._run_and_save(tmp_path, cfg)
        other = dataclasses.replace(
            fp, demand_shift_w=(0.0, 10.0, 20.0, 31.0))
        with pytest.raises(ValueError, match="different configuration"):
            ckpt.load(path, small_cfg(n_chains=4, fleet=other))
        # dropping the fleet entirely also refuses
        with pytest.raises(ValueError, match="different configuration"):
            ckpt.load(path, small_cfg(n_chains=4))
        # the same fleet resumes fine (digest equality, not identity)
        state, nb = ckpt.load(path, small_cfg(n_chains=4,
                                              fleet=het_fleet(4)))
        assert nb == 1

    def test_fleetless_checkpoint_roundtrips(self, tmp_path):
        cfg = small_cfg(n_chains=4)
        path = self._run_and_save(tmp_path, cfg)
        _, nb = ckpt.load(path, cfg)
        assert nb == 1
        with pytest.raises(ValueError, match="different configuration"):
            ckpt.load(path, dataclasses.replace(cfg,
                                                fleet=het_fleet(4)))


# ---------------------------------------------------------------------------
# scenario serving: the site/cohort selector
# ---------------------------------------------------------------------------

def _serve_cfg(**kw):
    base = dict(
        start="2019-09-05 10:00:00",
        duration_s=120,
        n_chains=4,
        seed=7,
        block_s=60,
        dtype="float32",
        output="reduce",
        block_impl="scan",
        scan_unroll=1,
    )
    base.update(kw)
    return SimConfig(**base)


def _serve_fleet():
    n = 4
    return FleetParams(
        dc_capacity_scale=(1.0, 1.5, 0.8, 2.0),
        ac_limit_w=(150.0, INF, INF, 300.0),
        weather_regime=(0, 1, 2, 0),
        demand_scale=(1.0, 1.2, 0.9, 1.1),
        demand_shift_w=(0.0, 40.0, -20.0, 10.0),
        cohort=(0, 0, 1, 1),
        **_geom(n),
    )


def _req(rid, scenario, mode="reduce"):
    return schema.Request(id=rid, reply_to="r", mode=mode,
                          scenario=scenario)


@pytest.fixture(scope="module")
def fleet_engine():
    with use_registry(MetricsRegistry()):
        return ScenarioEngine(_serve_cfg(fleet=_serve_fleet()), (1,))


class TestServeSelector:
    def test_selector_parse_rejections(self):
        ok = schema.parse_scenario({"site_index": 2}, max_horizon_s=120,
                                   n_sites=4, n_cohorts=2)
        assert ok.site_index == 2 and ok.cohort == -1
        with pytest.raises(RequestError, match="expected an integer"):
            schema.parse_scenario({"site_index": True},
                                  max_horizon_s=120, n_sites=4)
        with pytest.raises(RequestError, match=r"outside \[0, 4\)"):
            schema.parse_scenario({"site_index": 4}, max_horizon_s=120,
                                  n_sites=4)
        with pytest.raises(RequestError, match="no site axis"):
            schema.parse_scenario({"site_index": 0}, max_horizon_s=120)
        with pytest.raises(RequestError, match="no cohort tags"):
            schema.parse_scenario({"cohort": 0}, max_horizon_s=120,
                                  n_sites=4, n_cohorts=0)
        with pytest.raises(RequestError, match="mutually exclusive"):
            schema.parse_scenario({"site_index": 1, "cohort": 0},
                                  max_horizon_s=120, n_sites=4,
                                  n_cohorts=2)

    def test_engine_advertises_fleet_axes(self, fleet_engine):
        assert fleet_engine.n_sites == 4
        assert fleet_engine.n_cohorts == 2
        with use_registry(MetricsRegistry()):
            plain = ScenarioEngine(_serve_cfg(), (1,))
        assert plain.n_sites is None
        assert plain.n_cohorts == 0

    def test_site_selected_reply_is_the_single_site_run(self,
                                                        fleet_engine):
        """The acceptance bar: a site-selected reduce reply must be
        bit-identical to simulating exactly that installation alone —
        the same chain carved out via the slab machinery (global chain
        index preserved, fleet row sliced along)."""
        fp = _serve_fleet()
        sel = fleet_engine.run(
            [_req("s", Scenario(horizon_s=120, site_index=2))])[0]
        assert sel["site_index"] == 2
        carve = dataclasses.replace(
            _serve_cfg(fleet=None), n_chains=1, n_chains_total=4,
            chain_offset=2, fleet=slice_fleet(fp, 2, 1))
        with use_registry(MetricsRegistry()):
            alone = ScenarioEngine(carve, (1,)).run(
                [_req("a", Scenario(horizon_s=120))])[0]
        assert sel["stats"] == alone["stats"]
        assert sel["stats"]["n_seconds"] == 120

    def test_cohort_selected_reply_is_the_cohort_run(self, fleet_engine):
        """cohort=1 tags chains {2, 3} — a contiguous slab, so the
        selected reply must equal the 2-chain carve bitwise."""
        fp = _serve_fleet()
        sel = fleet_engine.run(
            [_req("c", Scenario(horizon_s=120, cohort=1))])[0]
        assert sel["cohort"] == 1
        carve = dataclasses.replace(
            _serve_cfg(fleet=None), n_chains=2, n_chains_total=4,
            chain_offset=2, fleet=slice_fleet(fp, 2, 2))
        with use_registry(MetricsRegistry()):
            alone = ScenarioEngine(carve, (1,)).run(
                [_req("a", Scenario(horizon_s=120))])[0]
        assert sel["stats"] == alone["stats"]
        assert sel["stats"]["n_seconds"] == 240

    def test_unselected_reply_has_no_selector_keys(self, fleet_engine):
        out = fleet_engine.run([_req("n", Scenario(horizon_s=120))])[0]
        assert "site_index" not in out and "cohort" not in out
        assert out["stats"]["n_seconds"] == 480


# ---------------------------------------------------------------------------
# RunReport v12: cohorts table + config echo, tools/fleet_report.py
# ---------------------------------------------------------------------------

def _v12_doc():
    with use_registry(MetricsRegistry()):
        sim = Simulation(small_cfg(fleet=het_fleet(8), analytics="risk"))
        sim.run_reduced()
        return sim.run_report()


class TestReportV12:
    def test_round_trip(self):
        doc = _v12_doc()
        assert doc["schema_version"] == REPORT_SCHEMA_VERSION == 16
        assert doc["config"]["fleet"]["n_sites"] == 8
        assert doc["config"]["fleet"]["n_cohorts"] == 3
        assert doc["config"]["fleet"]["digest"] == het_fleet(8).digest()
        rows = doc["fleet"]["cohorts"]
        assert [r["cohort"] for r in rows] == [0, 1, 2]
        validate_report(json.loads(json.dumps(doc)))

    def test_v11_documents_still_validate(self):
        doc = _v12_doc()
        doc["schema_version"] = 11
        doc["fleet"].pop("cohorts")
        doc["config"].pop("fleet")
        validate_report(doc)

    def test_cohortless_fleet_section_validates(self):
        with use_registry(MetricsRegistry()):
            sim = Simulation(small_cfg(analytics="risk"))
            sim.run_reduced()
            doc = sim.run_report()
        assert doc["fleet"]["cohorts"] is None
        assert doc["config"].get("fleet") is None
        validate_report(doc)

    def test_bad_cohort_rows_rejected(self):
        doc = _v12_doc()
        doc["fleet"]["cohorts"][1]["count"] = "many"
        with pytest.raises(ValueError, match="cohort"):
            validate_report(doc)

    def test_fleet_report_tool_prints_cohort_table(self, tmp_path):
        path = tmp_path / "report.json"
        path.write_text(json.dumps(_v12_doc()))
        r = subprocess.run([sys.executable, str(FLEET_REPORT),
                            str(path)], capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        assert "cohort" in r.stdout

    def test_fleet_report_tool_rejects_broken_partition(self, tmp_path):
        doc = _v12_doc()
        doc["fleet"]["cohorts"][0]["count"] += 1  # no longer partitions
        path = tmp_path / "report.json"
        path.write_text(json.dumps(doc))
        r = subprocess.run([sys.executable, str(FLEET_REPORT),
                            str(path)], capture_output=True, text=True)
        assert r.returncode != 0


# ---------------------------------------------------------------------------
# CLI end-to-end
# ---------------------------------------------------------------------------

class TestCli:
    def test_fleet_synth_end_to_end(self, tmp_path):
        from click.testing import CliRunner

        from tmhpvsim_tpu.cli import main as cli_main

        out = tmp_path / "fleet.csv"
        r = CliRunner().invoke(cli_main, [
            "pvsim", str(out), "--backend=jax", "--no-realtime",
            "--duration", "120", "--block-s", "60", "--seed", "5",
            "--fleet-synth", "4", "--fleet-seed", "1",
            "--output", "reduce", "--start", "2019-09-05 10:00:00",
        ])
        assert r.exit_code == 0, r.output
        lines = out.read_text().strip().splitlines()
        assert len(lines) == 1 + 4 + 1  # header + 4 chains + ensemble

    def test_fleet_flags_are_exclusive(self):
        from click.testing import CliRunner

        from tmhpvsim_tpu.cli import main as cli_main

        r = CliRunner().invoke(cli_main, [
            "pvsim", "out.csv", "--backend=jax", "--fleet-synth", "4",
            "--sites-csv", "README.md",
        ])
        assert r.exit_code != 0
        assert "mutually exclusive" in r.output
