"""Hourly Markov chain: invariants, scan-vs-loop exactness, distribution parity."""

import jax
import jax.numpy as jnp
import numpy as np
import scipy.stats as st

from tmhpvsim_tpu.models import markov_hourly as mh


def test_states_in_unit_interval():
    s = mh.chain(jax.random.key(0), 2000, dtype=jnp.float64)
    s = np.asarray(s)
    assert s.min() >= 0.0 and s.max() <= 1.0


def test_scan_matches_python_loop():
    """The jitted scan reproduces a per-step Python loop draw-for-draw.

    Tolerance is ~1 ulp (not bitwise): XLA may fuse/FMA differently inside
    the scan body than in op-by-op eager execution.
    """
    n = 100
    key = jax.random.key(42)
    params = mh.step_params(jnp.float64)
    state = jnp.asarray(1.0, dtype=jnp.float64)
    loop = []
    for i in range(n):
        # transition i is keyed by fold_in(key, i) — the random-access
        # keying contract of chain_window/chain
        state = mh.transition(jax.random.fold_in(key, i), state, params,
                              jnp.float64)
        loop.append(float(state))
    scan = np.asarray(mh.chain(key, n, dtype=jnp.float64))
    np.testing.assert_allclose(scan, np.asarray(loop), rtol=1e-12, atol=1e-14)


def test_transition_kernel_parity_with_numpy_golden():
    """Per-bin conditional step distributions of the JAX transition match the
    float64 numpy golden implementation.

    (Comparing whole trajectories with KS would be statistically invalid —
    Markov samples are autocorrelated — so we test the transition kernel
    itself: i.i.d. next-states from a fixed representative state per bin.)
    """
    n = 30_000
    rng = np.random.default_rng(99)
    for state in (0.05, 0.2, 0.5, 0.8, 0.95, 0.995):
        keys = jax.random.split(jax.random.key(int(state * 1000)), n)
        params = mh.step_params(jnp.float64)
        s0 = jnp.full((n,), state, dtype=jnp.float64)
        jx = np.asarray(
            jax.vmap(lambda k, s: mh.transition(k, s, params, jnp.float64))(keys, s0)
        )
        npy = np.asarray([mh.chain_numpy(rng, 1, state)[0] for _ in range(n)])
        stat, p = st.ks_2samp(jx, npy)
        assert p > 1e-4, f"state={state}: KS stat={stat:.4f} p={p:.2e}"


def test_iid_compat_mode_near_one():
    """Reference-compat i.i.d. mode: single steps from overcast state 1.0 stay
    close to 1 (bin (0.99, 1.0] has scale 0.0063)."""
    s = np.asarray(mh.iid_from_one(jax.random.key(1), 20_000, dtype=jnp.float64))
    assert s.min() >= 0.0 and s.max() <= 1.0
    assert np.quantile(s, 0.05) > 0.95


def test_vmap_chains_independent_and_batched():
    keys = jax.random.split(jax.random.key(3), 8)
    s = jax.vmap(lambda k: mh.chain(k, 500))(keys)
    assert s.shape == (8, 500)
    # different keys give different trajectories
    assert np.std(np.asarray(s)[:, -1]) > 0
