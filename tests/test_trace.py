"""Streaming trace timeline + flight recorder tests (obs/trace.py):
tracer semantics, Chrome-trace export shape, the trace_stats validator
round-trip, broker meta stamping, funnel/retry instrumentation, the
end-to-end --trace acceptance run, crash/watchdog flight dumps, report
schema back-compat, and the disabled-cost gate (slow lane)."""

import asyncio
import datetime as dt
import importlib.util
import json
import logging
import os
import pathlib
import subprocess
import sys

import pytest

from tmhpvsim_tpu.obs import metrics as obs_metrics
from tmhpvsim_tpu.obs.metrics import (
    MetricsRegistry,
    quantile_from_snapshot,
    use_registry,
)
from tmhpvsim_tpu.obs.report import validate_report
from tmhpvsim_tpu.obs.trace import (
    Tracer,
    get_tracer,
    set_tracer,
    use_tracer,
)

REPO = pathlib.Path(__file__).resolve().parent.parent
TRACE_STATS = REPO / "tools" / "trace_stats.py"


def _load_trace_stats():
    spec = importlib.util.spec_from_file_location("trace_stats", TRACE_STATS)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# tracer semantics
# ---------------------------------------------------------------------------

class TestTracer:
    def test_span_records_complete_event(self):
        t = Tracer()
        with t.span("work", "bench", n=3):
            pass
        (ev,) = t.events()
        assert ev["ph"] == "X"
        assert ev["name"] == "work"
        assert ev["cat"] == "bench"
        assert ev["dur"] >= 0
        assert ev["args"] == {"n": 3}
        assert ev["tid"].startswith("thread:")

    def test_instant_records_event(self):
        t = Tracer()
        t.instant("tick", "clock", seq=1)
        (ev,) = t.events()
        assert ev["ph"] == "i"
        assert ev["s"] == "t"
        assert ev["args"] == {"seq": 1}

    def test_disabled_tracer_is_falsy_and_records_nothing(self):
        t = Tracer(enabled=False)
        assert not t
        t.instant("x")
        with t.span("y"):
            pass
        assert len(t) == 0

    def test_enabled_tracer_is_truthy_and_none_is_falsy(self):
        # the call-site convention `if tracer:` must treat None and a
        # disabled tracer identically
        assert Tracer()
        assert not Tracer(enabled=False)
        assert not None

    def test_ring_is_bounded(self):
        t = Tracer(ring_capacity=4)
        for i in range(10):
            t.instant(f"e{i}")
        assert len(t) == 4
        assert [e["name"] for e in t.events()] == ["e6", "e7", "e8", "e9"]

    def test_task_label_inside_event_loop(self):
        t = Tracer()

        async def work():
            t.instant("in-task")

        async def main():
            await asyncio.create_task(work(), name="meter-reader")

        asyncio.run(main())
        (ev,) = t.events()
        assert ev["tid"] == "task:meter-reader"

    def test_export_shape(self, tmp_path):
        t = Tracer()
        with t.span("a", "c1"):
            pass
        t.instant("b", "c2")
        path = str(tmp_path / "t.json")
        doc = t.export(path, process_name="proc")
        on_disk = json.load(open(path))
        assert on_disk == doc
        evs = doc["traceEvents"]
        assert doc["displayTimeUnit"] == "ms"
        # metadata first: process_name + one thread_name per track label
        meta = [e for e in evs if e["ph"] == "M"]
        assert meta[0]["name"] == "process_name"
        assert meta[0]["args"]["name"] == "proc"
        assert any(e["name"] == "thread_name" and
                   e["args"]["name"].startswith("thread:") for e in meta)
        # real pid so jax.profiler traces merge as a separate process row
        assert all(e["pid"] == os.getpid() for e in evs)
        assert all(isinstance(e["tid"], int) for e in evs)

    def test_export_creates_parent_dir(self, tmp_path):
        t = Tracer()
        t.instant("x")
        path = str(tmp_path / "sub" / "t.json")
        t.export(path)
        assert json.load(open(path))["traceEvents"]

    def test_dump_flight_keeps_only_window(self, tmp_path):
        now = {"ns": 0}
        t = Tracer(clock=lambda: now["ns"])
        t.instant("old")                      # ts 0
        now["ns"] = int(100e9)
        t.instant("recent")                   # ts 100 s
        now["ns"] = int(110e9)                # dump at t=110 s, window 30 s
        doc = t.dump_flight(str(tmp_path / "f.json"), last_s=30.0)
        names = [e["name"] for e in doc["traceEvents"] if e["ph"] != "M"]
        assert names == ["recent"]

    def test_dump_flight_keeps_overlapping_span(self, tmp_path):
        # a span that STARTED before the window but overlaps it is the
        # story of a wedge — it must survive the cut
        now = {"ns": 0}
        t = Tracer(clock=lambda: now["ns"])
        with t.span("long"):
            now["ns"] = int(100e9)
        now["ns"] = int(110e9)
        doc = t.dump_flight(str(tmp_path / "f.json"), last_s=30.0)
        names = [e["name"] for e in doc["traceEvents"] if e["ph"] != "M"]
        assert names == ["long"]

    def test_set_and_use_tracer(self):
        assert get_tracer() is None
        t = Tracer()
        with use_tracer(t):
            assert get_tracer() is t
            inner = Tracer()
            prev = set_tracer(inner)
            assert prev is t
            set_tracer(t)
        assert get_tracer() is None


# ---------------------------------------------------------------------------
# histogram quantiles (the streaming report's p50/p90/p99)
# ---------------------------------------------------------------------------

class TestQuantiles:
    def test_quantile_none_on_empty(self):
        assert quantile_from_snapshot(None, 0.5) is None
        assert quantile_from_snapshot({"count": 0}, 0.5) is None

    def test_quantile_interpolates_and_clamps(self):
        h = MetricsRegistry().histogram("h", buckets=(1.0, 10.0, 100.0))
        for v in (2.0, 3.0, 4.0, 50.0):
            h.observe(v)
        snap = h.snapshot()
        p50 = quantile_from_snapshot(snap, 0.5)
        assert 1.0 <= p50 <= 10.0
        # clamped to [min, max]: never 0 when every observation is > 0
        assert quantile_from_snapshot(snap, 0.01) >= 2.0
        assert quantile_from_snapshot(snap, 0.999) <= 50.0

    def test_quantile_bucketless_json_snapshot(self):
        """Snapshots rebuilt from JSON may carry ``buckets: null`` — a
        valid "nothing bucketed" answer (falls back to the observed
        max), never a TypeError."""
        snap = {"count": 5, "sum": 10.0, "buckets": None,
                "min": 1.0, "max": 4.0}
        assert quantile_from_snapshot(snap, 0.5) == 4.0
        # ... and with no max recorded either, None — not an exception
        assert quantile_from_snapshot({"count": 3, "buckets": None},
                                      0.9) is None

    def test_quantile_nonzero_when_all_positive(self):
        h = MetricsRegistry().histogram("h")
        h.observe(0.005)
        for q in (0.5, 0.9, 0.99):
            assert quantile_from_snapshot(h.snapshot(), q) > 0


# ---------------------------------------------------------------------------
# trace_stats validator
# ---------------------------------------------------------------------------

class TestTraceStats:
    def test_round_trip_subprocess(self, tmp_path):
        t = Tracer()
        with t.span("a", "c"):
            pass
        t.instant("b", "c")
        path = str(tmp_path / "t.json")
        t.export(path)
        r = subprocess.run([sys.executable, str(TRACE_STATS), path],
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        assert "t.json" in r.stdout
        assert "c" in r.stdout  # per-category row

    def test_invalid_trace_fails_subprocess(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps(
            {"traceEvents": [{"ph": "X", "name": "a", "ts": 1}]}  # no dur
        ))
        r = subprocess.run([sys.executable, str(TRACE_STATS), str(bad)],
                           capture_output=True, text=True)
        assert r.returncode != 0
        assert "INVALID" in r.stderr

    def test_validate_rules(self):
        ts = _load_trace_stats()
        ok_doc = {"traceEvents": [
            {"ph": "M", "name": "process_name", "pid": 1, "tid": 0},
            {"ph": "X", "name": "a", "ts": 0, "dur": 2, "pid": 1, "tid": 1},
            {"ph": "i", "name": "b", "ts": 1, "pid": 1, "tid": 1},
        ]}
        errors, events = ts.validate(ok_doc)
        assert errors == []
        assert len(events) == 3
        assert ts.validate({"nope": []})[0]
        assert ts.validate({"traceEvents": [{"ph": "i"}]})[0]  # no ts
        assert ts.validate({"traceEvents": [
            {"ph": "X", "name": "a", "ts": 0, "dur": -1}]})[0]
        assert ts.validate({"traceEvents": [
            {"ph": "i", "ts": 0, "tid": "main"}]})[0]  # string tid

    def test_summarize_per_category(self):
        ts = _load_trace_stats()
        cats = ts.summarize([
            {"ph": "X", "cat": "a", "ts": 0, "dur": 5},
            {"ph": "X", "cat": "a", "ts": 0, "dur": 3},
            {"ph": "i", "cat": "b", "ts": 0},
            {"ph": "M", "name": "process_name"},
        ])
        assert cats["a"] == {"spans": 2, "dur_us": 8.0, "max_us": 5.0,
                             "instants": 0}
        assert cats["b"]["instants"] == 1


# ---------------------------------------------------------------------------
# broker meta: out-of-band seq + pub_us stamping
# ---------------------------------------------------------------------------

class TestBrokerMeta:
    def test_local_transport_meta_round_trip(self):
        from tmhpvsim_tpu.runtime.broker import LocalTransport

        async def run():
            got = []

            async def consume(tr):
                async for item in tr.subscribe(with_meta=True):
                    got.append(item)
                    if len(got) == 2:
                        return

            async with LocalTransport("local://meta-rt", "x") as tr:
                task = asyncio.create_task(consume(tr))
                await asyncio.sleep(0.01)
                t0 = dt.datetime(2019, 9, 5, 12, 0, 0)
                await tr.publish(1.0, t0, meta={"seq": 0, "pub_us": 42})
                await tr.publish(2.0, t0)
                await asyncio.wait_for(task, 5)
            return got

        got = asyncio.run(run())
        assert got[0][1] == 1.0
        assert got[0][2] == {"seq": 0, "pub_us": 42}
        assert got[1][2] is None  # unstamped message -> None, not {}

    def test_subscribe_default_stays_two_tuple(self):
        # reference-shaped consumers unpack (time, value); meta must be
        # strictly opt-in
        from tmhpvsim_tpu.runtime.broker import LocalTransport

        async def run():
            async with LocalTransport("local://meta-2t", "x") as tr:
                agen = tr.subscribe()
                task = asyncio.create_task(agen.__anext__())
                await asyncio.sleep(0.01)
                await tr.publish(3.0, dt.datetime(2019, 9, 5), meta={"a": 1})
                item = await asyncio.wait_for(task, 5)
                await agen.aclose()
            return item

        item = asyncio.run(run())
        assert item == (dt.datetime(2019, 9, 5), 3.0)

    def test_metersim_stamps_seq_and_pub_us(self):
        from tmhpvsim_tpu.apps.metersim import metersim_main
        from tmhpvsim_tpu.runtime.broker import LocalTransport

        async def run():
            got = []

            async def consume():
                async with LocalTransport("local://stamp", "meter") as tr:
                    async for _, _, meta in tr.subscribe(with_meta=True):
                        got.append(meta)

            task = asyncio.create_task(consume())
            await asyncio.sleep(0.01)
            await metersim_main("local://stamp", "meter", realtime=False,
                                seed=3, duration_s=5,
                                start=dt.datetime(2019, 9, 5, 12, 0, 0))
            await asyncio.sleep(0.05)
            task.cancel()
            return got

        metas = asyncio.run(run())
        assert [m["seq"] for m in metas] == list(range(len(metas)))
        assert len(metas) == 5
        assert all(isinstance(m["pub_us"], int) for m in metas)

    def test_connect_counters(self):
        from tmhpvsim_tpu.runtime.broker import LocalTransport

        reg = MetricsRegistry()

        async def run():
            with use_registry(reg):
                async with LocalTransport("local://cc", "x"):
                    pass
                async with LocalTransport("local://cc", "x"):
                    pass

        asyncio.run(run())
        c = reg.snapshot()["counters"]
        assert c["broker.connects_total"] == 2
        assert c["broker.reconnects_total"] == 1

    def test_tcp_meta_passthrough(self):
        from tmhpvsim_tpu.runtime.tcpbroker import (
            TcpFanoutBroker,
            TcpTransport,
        )

        async def run():
            got = []
            async with TcpFanoutBroker(port=0) as broker:
                url = f"tcp://127.0.0.1:{broker.port}"

                async def consume():
                    async with TcpTransport(url, "m") as tr:
                        async for item in tr.subscribe(with_meta=True):
                            got.append(item)
                            if len(got) == 2:
                                return

                task = asyncio.create_task(consume())
                await asyncio.sleep(0.1)
                async with TcpTransport(url, "m") as tr:
                    t0 = dt.datetime(2019, 9, 5, 12, 0, 0)
                    await tr.publish(1.5, t0, meta={"seq": 7, "pub_us": 9})
                    await tr.publish(2.5, t0)
                await asyncio.wait_for(task, 5)
            return got

        got = asyncio.run(run())
        assert got[0][0] == dt.datetime(2019, 9, 5, 12, 0, 0)
        assert got[0][2] == {"seq": 7, "pub_us": 9}
        assert got[1][2] is None


# ---------------------------------------------------------------------------
# funnel instrumentation + rate-limited eviction warning (satellite 2)
# ---------------------------------------------------------------------------

class TestFunnelObservability:
    def _funnel(self, reg, **kw):
        from collections import namedtuple

        from tmhpvsim_tpu.runtime.funnel import SynchronizingFunnel

        Rec = namedtuple("Rec", ["a", "b"])
        with use_registry(reg):
            return SynchronizingFunnel(Rec, asyncio.Queue(), **kw)

    def test_pending_and_eviction_counters(self):
        reg = MetricsRegistry()

        async def run():
            f = self._funnel(reg, max_pending=4, max_initial_pending=2,
                             max_lookahead=None)
            for t in range(8):
                await f.put(t, a=1.0)
            return f

        f = asyncio.new_event_loop().run_until_complete(run())
        snap = reg.snapshot()
        assert snap["counters"]["funnel.evicted_total"] == f.n_evicted > 0
        assert snap["gauges"]["funnel.pending_high_water"] >= \
            snap["gauges"]["funnel.pending_depth"] > 0

    def test_backpressure_and_stall_counters(self):
        reg = MetricsRegistry()

        async def run():
            f = self._funnel(reg, max_lookahead=2, stall_timeout_s=0.05,
                             max_initial_pending=None)
            await f.put(0, b=2.0)     # give stream b a clock
            for t in range(6):        # stream a runs ahead; b stalls
                await f.put(t, a=1.0)

        asyncio.new_event_loop().run_until_complete(run())
        c = reg.snapshot()["counters"]
        assert c["funnel.backpressure_waits_total"] >= 1
        assert c["funnel.stall_suspends_total"] >= 1

    def test_eviction_warn_rate_limited(self, caplog):
        from tmhpvsim_tpu.runtime.funnel import EVICT_WARN_EVERY_S

        reg = MetricsRegistry()

        async def make():
            return self._funnel(reg, max_pending=4)

        f = asyncio.new_event_loop().run_until_complete(make())
        with caplog.at_level(logging.WARNING,
                             logger="tmhpvsim_tpu.runtime.funnel"):
            assert f._warn_eviction(now=0.0) is True
            assert f._warn_eviction(now=1.0) is False   # rate-limited
            assert f._warn_eviction(now=9.9) is False
            assert f._warn_eviction(now=0.5 + EVICT_WARN_EVERY_S) is True
        warns = [r for r in caplog.records
                 if "funnel cache exceeded" in r.message]
        assert len(warns) == 2
        assert "suppressed" not in warns[0].getMessage()
        assert "2 similar warnings suppressed" in warns[1].getMessage()


# ---------------------------------------------------------------------------
# asyncretry: exhaustion warning + counters (satellite 3)
# ---------------------------------------------------------------------------

class TestRetryObservability:
    def test_exhaustion_warns_and_counts_on_reraise(self, caplog):
        from tmhpvsim_tpu.runtime.resilience import asyncretry

        reg = MetricsRegistry()

        @asyncretry(attempts=3, delay=0)
        async def always_fails():
            raise OSError("broker gone")

        with use_registry(reg):
            with caplog.at_level(logging.WARNING,
                                 logger="tmhpvsim_tpu.runtime.resilience"):
                with pytest.raises(OSError):
                    asyncio.run(always_fails())
        qn = always_fails.__qualname__
        c = reg.snapshot()["counters"]
        assert c[f"retry.attempts.{qn}"] == 3
        assert c[f"retry.exhausted.{qn}"] == 1
        (warn,) = [r for r in caplog.records if "exhausted" in r.message]
        assert "OSError" in warn.getMessage()
        assert "3 attempt(s)" in warn.getMessage()
        assert "re-raising" in warn.getMessage()

    def test_exhaustion_warns_on_silent_fallback(self, caplog):
        # the fallback path used to swallow the final failure with no log
        # at all — the WARNING is the satellite's point
        from tmhpvsim_tpu.runtime.resilience import asyncretry

        @asyncretry(attempts=2, delay=0, fallback=None)
        async def fails_with_fallback():
            raise ValueError("bad")

        with use_registry(MetricsRegistry()):
            with caplog.at_level(logging.WARNING,
                                 logger="tmhpvsim_tpu.runtime.resilience"):
                assert asyncio.run(fails_with_fallback()) is None
        (warn,) = [r for r in caplog.records if "exhausted" in r.message]
        assert "applying fallback" in warn.getMessage()
        assert "ValueError" in warn.getMessage()


# ---------------------------------------------------------------------------
# end-to-end acceptance: --trace over the local broker
# ---------------------------------------------------------------------------

def _run_streaming_pair(tmp_path, url, n=30, **pvsim_kw):
    from tmhpvsim_tpu.apps.metersim import metersim_main
    from tmhpvsim_tpu.apps.pvsim import pvsim_main

    out = tmp_path / "out.csv"
    start = dt.datetime(2019, 9, 5, 12, 0, 0)

    async def both():
        consumer = asyncio.create_task(
            pvsim_main(str(out), url, "meter", realtime=False, seed=1,
                       duration_s=None, start=start, **pvsim_kw)
        )
        await asyncio.sleep(0.05)
        await metersim_main(url, "meter", realtime=False, seed=2,
                            duration_s=n, start=start)
        await asyncio.sleep(0.3)
        consumer.cancel()
        try:
            await consumer
        except asyncio.CancelledError:
            pass

    asyncio.new_event_loop().run_until_complete(both())
    return out


def test_e2e_trace_and_streaming_report(tmp_path):
    """The PR's acceptance run: local-broker pair with --trace semantics
    produces a valid Chrome trace and a RunReport whose streaming section
    has nonzero publish→join latency quantiles."""
    trace_path = str(tmp_path / "stream.trace.json")
    report_path = str(tmp_path / "report.json")
    reg = MetricsRegistry()
    with use_registry(reg):
        out = _run_streaming_pair(tmp_path, "local://trace-e2e",
                                  trace=trace_path,
                                  run_report_path=report_path)
    assert sum(1 for _ in open(out)) > 15

    # trace: valid per the schema validator, with the expected categories
    ts = _load_trace_stats()
    doc = json.load(open(trace_path))
    errors, events = ts.validate(doc)
    assert errors == []
    cats = ts.summarize(events)
    assert cats["stream"]["spans"] > 0      # consume -> funnel.put
    assert cats["stream"]["instants"] > 0   # consume markers
    assert cats["funnel"]["instants"] > 0   # join-complete markers
    assert cats["csv"]["spans"] > 0         # csv.write
    r = subprocess.run([sys.executable, str(TRACE_STATS), trace_path],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr

    # report: schema v3 validates; streaming section carries nonzero
    # publish→join quantiles (producer + consumer share this process, so
    # the monotonic stamps are directly comparable)
    rep = validate_report(json.load(open(report_path)))
    s = rep["streaming"]
    assert s["publish_to_join"]["count"] > 0
    for q in ("p50_s", "p90_s", "p99_s"):
        assert s["publish_to_join"][q] > 0
    assert s["join_to_csv"]["count"] > 0
    assert s["rows_written"] == sum(1 for _ in open(out)) - 1
    assert s["broker"]["published"] == 30
    assert s["broker"]["connects"] >= 2
    assert s["funnel"]["pending_high_water"] >= 1
    assert s["retry"] == {"attempts": 0, "exhausted": 0}


def test_report_without_streaming_has_no_section(tmp_path):
    """A registry that never saw streaming metrics must not grow a
    streaming section (jax-backend reports keep their v2 shape)."""
    from tmhpvsim_tpu.obs.report import RunReport

    reg = MetricsRegistry()
    reg.counter("engine.blocks_total").inc()
    rep = RunReport("test")
    rep.attach_metrics(reg)
    doc = rep.doc()
    assert doc["streaming"] is None
    validate_report(doc)


def test_report_schema_v1_v2_still_validate():
    """The migration guarantee: documents written by the v1 and v2
    schemas keep validating against the current validator."""
    from tmhpvsim_tpu.obs.report import REPORT_SCHEMA_VERSION, RunReport

    assert REPORT_SCHEMA_VERSION == 16
    doc = RunReport("test").doc()
    for old in (1, 2):
        legacy = {k: v for k, v in doc.items()
                  if not (k == "serving" and old < 6)
                  and not (k == "fleet" and old < 5)
                  and not (k == "executor" and old < 4)
                  and not (k == "streaming" and old < 3)
                  and not (k == "telemetry" and old < 2)}
        legacy["schema_version"] = old
        validate_report(legacy)


# ---------------------------------------------------------------------------
# flight recorder: crash + watchdog dumps
# ---------------------------------------------------------------------------

def test_pvsim_crash_dumps_flight_recorder(tmp_path):
    """An unhandled exception inside pvsim_main must leave a valid
    crash trace at PATH.crash.json before re-raising."""
    from tmhpvsim_tpu.apps.pvsim import pvsim_main

    trace_path = str(tmp_path / "t.json")
    bad_out = str(tmp_path / "no-such-dir" / "out.csv")  # sink open fails

    async def run():
        with use_registry(MetricsRegistry()):
            await pvsim_main(bad_out, "local://crash", "meter",
                             realtime=False, seed=1, duration_s=10,
                             start=dt.datetime(2019, 9, 5, 12, 0, 0),
                             trace=trace_path)

    with pytest.raises(FileNotFoundError):
        asyncio.new_event_loop().run_until_complete(run())

    crash = trace_path + ".crash.json"
    assert os.path.exists(crash)
    ts = _load_trace_stats()
    for p in (crash, trace_path):  # the finally-export also lands
        errors, _ = ts.validate(json.load(open(p)))
        assert errors == [], (p, errors)


def test_metersim_crash_dumps_flight_recorder(tmp_path, monkeypatch):
    from tmhpvsim_tpu.apps import metersim as m

    trace_path = str(tmp_path / "m.json")

    async def boom(*a, **kw):
        raise RuntimeError("producer died")

    monkeypatch.setattr(m, "read_meter_values", boom)

    async def run():
        with use_registry(MetricsRegistry()):
            await m.metersim_main("local://mcrash", "meter", realtime=False,
                                  seed=1, duration_s=5, trace=trace_path)

    with pytest.raises(RuntimeError, match="producer died"):
        asyncio.new_event_loop().run_until_complete(run())
    assert os.path.exists(trace_path + ".crash.json")


def test_bench_watchdog_flight_dump(tmp_path):
    """The simulated rc=3 salvage path: bench._dump_flight_recorder
    writes the process tracer's window as a valid trace file."""
    sys.path.insert(0, str(REPO))
    try:
        import bench
    finally:
        sys.path.remove(str(REPO))

    path = str(tmp_path / "flight_watchdog.json")
    t = Tracer()
    with t.span("variant:scan", "bench", n_chains=64):
        pass
    t.instant("wedge-probe", "bench")
    with use_tracer(t):
        assert bench._dump_flight_recorder("test wedge", path=path) is True
    ts = _load_trace_stats()
    errors, events = ts.validate(json.load(open(path)))
    assert errors == []
    assert ts.summarize(events)["bench"]["spans"] == 1

    # without a tracer (or an empty one) there is nothing to dump
    with use_tracer(None):
        assert bench._dump_flight_recorder("no tracer",
                                           path=path + ".none") is False
    assert not os.path.exists(path + ".none")


def test_cli_trace_flag_exports(tmp_path):
    """--trace through the real CLI on both apps (asyncio backends)."""
    from click.testing import CliRunner

    from tmhpvsim_tpu.cli import main as cli_main

    m_trace = str(tmp_path / "meter.trace.json")
    r = CliRunner().invoke(cli_main, [
        "metersim", "--no-realtime", "--duration", "5", "--seed", "0",
        "--amqp-url", "local://cli-trace", "--trace", m_trace,
    ])
    assert r.exit_code == 0, r.output
    doc = json.load(open(m_trace))
    assert any(e.get("cat") == "broker" for e in doc["traceEvents"])


def test_cli_pvsim_jax_trace(tmp_path):
    """--trace on the jax backend: per-block engine instants export."""
    from click.testing import CliRunner

    from tmhpvsim_tpu.cli import main as cli_main

    out = str(tmp_path / "out.csv")
    trace_path = str(tmp_path / "jax.trace.json")
    r = CliRunner().invoke(cli_main, [
        "pvsim", out, "--backend=jax", "--no-realtime",
        "--duration", "120", "--seed", "5", "--block-s", "60",
        "--start", "2019-09-05 10:00:00", "--trace", trace_path,
    ])
    assert r.exit_code == 0, r.output
    doc = json.load(open(trace_path))
    blocks = [e for e in doc["traceEvents"]
              if e.get("name") == "block" and e.get("cat") == "engine"]
    assert len(blocks) == 2
    ts = _load_trace_stats()
    assert ts.validate(doc)[0] == []


# ---------------------------------------------------------------------------
# disabled-cost acceptance (slow lane)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_trace_disabled_overhead_65536_chains():
    """With --trace absent the instrumentation must be effectively free:
    (a) steady block walls of the 65536-chain CPU engine config with the
    disabled-tracer guard in its block hook within 1% of a hook without
    it; (b) funnel join throughput at 10k records with the `if tracer:`
    guarded put-loops within 1% of unguarded ones.  min-of-repeats on
    both arms filters scheduler noise on this 1-core host."""
    import time as _time
    from collections import namedtuple

    from tmhpvsim_tpu.config import SimConfig
    from tmhpvsim_tpu.engine import Simulation
    from tmhpvsim_tpu.runtime.funnel import SynchronizingFunnel

    # -- arm (a): engine block loop ------------------------------------
    def steady_min(guarded: bool) -> float:
        tracer = None  # --trace absent

        def on_block_guarded(bi, state, acc):
            if tracer:
                tracer.instant("block", "engine", block=bi)

        def on_block_plain(bi, state, acc):
            pass

        with use_registry(MetricsRegistry(enabled=False)):
            sim = Simulation(SimConfig(
                start="2019-09-05 10:00:00", duration_s=4 * 60,
                n_chains=65536, seed=7, block_s=60, dtype="float32",
                block_impl="wide", output="reduce"))
            sim.run_reduced(on_block=on_block_guarded if guarded
                            else on_block_plain)
        return min(sim.timer.block_times)

    steady_min(True)  # warm the jit + persistent cache
    plain = steady_min(False)
    guarded = steady_min(True)
    assert guarded <= plain * 1.01, (
        f"disabled-tracer block-hook overhead {guarded / plain - 1:.2%} "
        f"exceeds 1% (guarded {guarded:.4f} s vs plain {plain:.4f} s)"
    )

    # -- arm (b): funnel join throughput -------------------------------
    # production shape: datetime timestamps and the pvsim lookahead
    # window, so funnel.put pays its real cost and the guard's truth
    # test is measured against it (an integer-keyed lookahead-free put
    # is ~2x cheaper and overstates the guard's relative cost)
    Rec = namedtuple("Rec", ["meter", "pv"])
    N = 10_000
    base = dt.datetime(2019, 9, 5)
    times = [base + dt.timedelta(seconds=i) for i in range(N)]

    async def join_once(guarded: bool) -> float:
        tracer = None
        queue: asyncio.Queue = asyncio.Queue()
        with use_registry(MetricsRegistry(enabled=False)):
            funnel = SynchronizingFunnel(
                Rec, queue, max_lookahead=dt.timedelta(seconds=60))
        t0 = _time.perf_counter()
        if guarded:  # the read-loop shape with tracing compiled in but off
            for t in times:
                if tracer:
                    with tracer.span("funnel.put", "pv"):
                        await funnel.put(t, pv=1.0)
                else:
                    await funnel.put(t, pv=1.0)
                if tracer:
                    with tracer.span("funnel.put", "stream"):
                        await funnel.put(t, meter=2.0)
                else:
                    await funnel.put(t, meter=2.0)
        else:
            for t in times:
                await funnel.put(t, pv=1.0)
                await funnel.put(t, meter=2.0)
        dt_s = _time.perf_counter() - t0
        assert queue.qsize() == N  # every record joined
        return dt_s

    # interleaved repeats: clock-frequency / cache drift on this 1-core
    # host hits both arms alike, and min-of-10 filters the scheduler
    asyncio.run(join_once(True))
    asyncio.run(join_once(False))  # warm allocators/bytecode caches
    plain_reps, guarded_reps = [], []
    for _ in range(10):
        plain_reps.append(asyncio.run(join_once(False)))
        guarded_reps.append(asyncio.run(join_once(True)))
    plain_j = min(plain_reps)
    guarded_j = min(guarded_reps)
    assert guarded_j <= plain_j * 1.01, (
        f"disabled-tracer join overhead {guarded_j / plain_j - 1:.2%} "
        f"exceeds 1% ({N} records: guarded {guarded_j:.4f} s vs "
        f"plain {plain_j:.4f} s)"
    )
