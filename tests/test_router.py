"""Shard-affinity fleet router (tmhpvsim_tpu/serve/router.py): the
consistent-hash ring, per-tenant token buckets, admission control
(quota / queue-depth shed / draining, all with honest ``retry_after_ms``
hints), the exactly-once answered-id guard, failover re-routing under
the re-route budget, and an end-to-end fleet pass over the local broker
where the per-worker duplicate-id replay LRU backs the router up under
consistent-hash affinity.

The admission/reply/failover tests drive the router synchronously:
``_send`` / ``_send_worker`` are replaced with recording stubs so every
routing decision is observable without a broker or a clock.
"""

import asyncio
import collections
import hashlib

import pytest

from tmhpvsim_tpu.config import SimConfig
from tmhpvsim_tpu.obs.metrics import MetricsRegistry, use_registry
from tmhpvsim_tpu.serve import schema
from tmhpvsim_tpu.serve.fleet import FleetConfig, ServeFleet
from tmhpvsim_tpu.serve.router import (
    MAX_RETRY_AFTER_MS,
    HashRing,
    ScenarioRouter,
    TokenBucket,
    WorkerHandle,
    _stable_hash,
)
from tmhpvsim_tpu.serve.server import ScenarioClient, ServeConfig


def _run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def scfg(**kw):
    base = dict(
        start="2019-09-05 10:00:00",
        duration_s=120,
        n_chains=4,
        seed=7,
        block_s=60,
        dtype="float32",
        output="reduce",
        block_impl="scan",
        scan_unroll=1,
    )
    base.update(kw)
    return SimConfig(**base)


class _Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# consistent-hash ring
# ---------------------------------------------------------------------------


class TestHashRing:
    def test_stable_hash_is_md5_prefix(self):
        for key in ("site:0", "cohort:17", "x"):
            assert _stable_hash(key) == int.from_bytes(
                hashlib.md5(key.encode()).digest()[:8], "big")

    def test_preference_is_a_stable_permutation(self):
        names = [f"w{i}" for i in range(5)]
        ring = HashRing(names)
        twin = HashRing(names)  # same names -> same ring, any process
        for site in range(64):
            key = f"site:{site}"
            pref = ring.preference(key)
            assert sorted(pref) == sorted(names)  # every worker once
            assert pref == ring.preference(key)   # repeatable
            assert pref == twin.preference(key)   # instance-independent

    def test_first_choice_spreads_and_survives_unrelated_loss(self):
        names = [f"w{i}" for i in range(4)]
        ring = HashRing(names)
        first = collections.Counter(
            ring.preference(f"site:{s}")[0] for s in range(256))
        assert set(first) == set(names)  # no worker starves of keys
        # a key keeps its worker while that worker stays ready: dropping
        # ANY other worker never moves it (the failover property the
        # replay-LRU affinity test below leans on)
        for s in range(32):
            pref = ring.preference(f"site:{s}")
            for dead in names:
                if dead == pref[0]:
                    continue
                alive = [n for n in pref if n != dead]
                assert alive[0] == pref[0]


# ---------------------------------------------------------------------------
# per-tenant token bucket
# ---------------------------------------------------------------------------


class TestTokenBucket:
    def test_burst_then_refill(self):
        clk = _Clock()
        b = TokenBucket(rate=2.0, burst=2.0, now=clk)
        assert b.take() and b.take()
        assert not b.take()
        assert b.retry_after_s() == pytest.approx(0.5)
        clk.t = 0.5  # one token refilled
        assert b.retry_after_s() == 0.0
        assert b.take()
        assert not b.take()

    def test_burst_is_a_cap(self):
        clk = _Clock()
        b = TokenBucket(rate=10.0, burst=2.0, now=clk)
        assert b.take() and b.take()
        clk.t = 100.0  # a long idle spell never banks > burst tokens
        assert b.take() and b.take()
        assert not b.take()

    def test_zero_rate_never_refills(self):
        b = TokenBucket(rate=0.0, burst=1.0, now=_Clock())
        assert b.take()
        assert not b.take()
        assert b.retry_after_s() == float("inf")


# ---------------------------------------------------------------------------
# routing key extraction
# ---------------------------------------------------------------------------


class TestRoutingKey:
    def test_site_then_cohort_then_shardless(self):
        rk = ScenarioRouter.routing_key
        assert rk({"scenario": {"site_index": 3}}) == "site:3"
        assert rk({"scenario": {"cohort": 2}}) == "cohort:2"
        # site wins when both are present (schema rejects that combo
        # anyway, but the router must not flap between keys)
        assert rk({"scenario": {"site_index": 1, "cohort": 2}}) == "site:1"
        assert rk({"scenario": {"site_index": -1, "cohort": -1}}) is None
        assert rk({"scenario": {}}) is None
        assert rk({"scenario": None}) is None
        assert rk({}) is None
        # bools are not selectors even though bool is an int subtype
        assert rk({"scenario": {"site_index": True}}) is None


# ---------------------------------------------------------------------------
# admission control (sync: stubbed send paths)
# ---------------------------------------------------------------------------


def make_router(names=("w0", "w1", "w2"), **kw):
    """A router with every worker ready and the publish paths replaced
    by recording stubs; returns (router, forwarded, replied, registry)."""
    reg = MetricsRegistry()
    handles = [WorkerHandle(n, f"scen.{n}", lambda: (True, {}))
               for n in names]
    r = ScenarioRouter("local://router-unit", "scen", handles,
                       registry=reg, **kw)
    r._ready = set(names)
    forwarded, replied = [], []
    r._send_worker = lambda worker, meta, rid: forwarded.append(
        (worker, dict(meta)))
    r._send = lambda exchange, meta: replied.append(
        (exchange, dict(meta)))
    return r, forwarded, replied, reg


def rmeta(rid, scenario=None, tenant=None):
    m = schema.request_meta(rid, "rep", "reduce", scenario)
    if tenant is not None:
        m["tenant"] = tenant
    return m


class TestRouterAdmission:
    def test_affinity_same_key_same_worker_and_stamp(self):
        r, fwd, rep, reg = make_router()
        for i in range(6):
            r._handle(rmeta(f"a{i}", {"site_index": 7}))
        assert not rep
        workers = {w for w, _ in fwd}
        assert len(workers) == 1  # shard affinity: one worker owns site 7
        owner = workers.pop()
        assert owner == r._ring.preference("site:7")[0]
        for _, meta in fwd:
            # the stamp satellite: the forwarded meta names its worker
            # and redirects the reply to the router's own exchange
            assert meta["worker"] == owner
            assert meta["reply_to"] == r.reply_exchange
        assert reg.snapshot()["counters"]["router.routed_total"] == 6.0
        # distinct sites spread across the fleet
        r2, fwd2, _, _ = make_router()
        for s in range(32):
            r2._handle(rmeta(f"s{s}", {"site_index": s}))
        assert len({w for w, _ in fwd2}) == 3

    def test_shardless_falls_back_to_least_loaded(self):
        r, fwd, rep, _ = make_router()
        for i in range(6):
            r._handle(rmeta(f"q{i}"))  # no selector -> no ring key
        assert not rep
        loads = collections.Counter(w for w, _ in fwd)
        assert loads == {"w0": 2, "w1": 2, "w2": 2}

    def test_duplicate_in_flight_id_rejected_not_reforwarded(self):
        r, fwd, rep, reg = make_router()
        r._handle(rmeta("dup"))
        r._handle(rmeta("dup"))
        assert len(fwd) == 1  # the replay never reaches a second worker
        assert len(rep) == 1
        assert rep[0][1]["error"]["code"] == "duplicate"
        assert reg.snapshot()["counters"]["router.rejected_total"] == 1.0

    def test_quota_busy_carries_refill_hint(self):
        r, fwd, rep, reg = make_router(quota_rate=1.0, quota_burst=1.0)
        clk = _Clock()
        r._buckets["t1"] = TokenBucket(1.0, 1.0, now=clk)
        r._handle(rmeta("ok", tenant="t1"))
        r._handle(rmeta("over", tenant="t1"))
        assert len(fwd) == 1
        err = rep[0][1]["error"]
        assert err["code"] == "busy"
        assert err["retry_after_ms"] == 1001  # (1 token / 1 rps) + 1 ms
        # quotas are per tenant: another tenant's bucket is untouched
        r._handle(rmeta("other", tenant="t2"))
        assert len(fwd) == 2
        assert reg.snapshot()["counters"][
            "router.quota_rejected_total"] == 1.0

    def test_inflight_limit_sheds_with_retry_after(self):
        r, fwd, rep, reg = make_router(inflight_limit=2)
        for i in range(3):
            r._handle(rmeta(f"n{i}"))
        assert len(fwd) == 2
        err = rep[0][1]["error"]
        assert err["code"] == "busy"
        assert 1 <= err["retry_after_ms"] <= MAX_RETRY_AFTER_MS
        assert reg.snapshot()["counters"]["router.shed_total"] == 1.0

    def test_no_ready_worker_is_unavailable_with_hint(self):
        r, fwd, rep, _ = make_router()
        r._ready = set()
        r._handle(rmeta("x"))
        assert not fwd
        err = rep[0][1]["error"]
        assert err["code"] == "unavailable"
        assert err["retry_after_ms"] >= 1

    def test_draining_rejects_typed(self):
        r, fwd, rep, _ = make_router()
        r.begin_drain()
        r._handle(rmeta("x"))
        assert not fwd
        assert rep[0][1]["error"]["code"] == "draining"
        ok, detail = r.readiness()
        assert not ok and detail["draining"]


# ---------------------------------------------------------------------------
# reply path: exactly-once
# ---------------------------------------------------------------------------


def _reply(rid, worker="whoever"):
    return {"op": schema.OP_REPLY, "id": rid, "ok": True,
            "result": {"mode": "reduce"}, "worker": worker}


class TestRouterReplies:
    def test_reply_forwarded_once_with_worker_stamp(self):
        r, fwd, rep, reg = make_router()
        r._handle(rmeta("r1"))
        owner = fwd[0][0]
        r._on_reply(_reply("r1"))
        assert len(rep) == 1
        exchange, meta = rep[0]
        assert exchange == "rep"  # the CLIENT's reply exchange
        assert meta["ok"] and meta["worker"] == owner
        assert r._inflight[owner] == 0
        # the rerouted twin / late duplicate is dropped, not re-sent
        r._on_reply(_reply("r1"))
        assert len(rep) == 1
        c = reg.snapshot()["counters"]
        assert c["router.replies_total"] == 1.0
        assert c["router.dup_replies_total"] == 1.0

    def test_answered_lru_rejects_replayed_id(self):
        r, fwd, rep, _ = make_router()
        r._handle(rmeta("r1"))
        r._on_reply(_reply("r1"))
        r._handle(rmeta("r1"))  # replay after the answer
        assert len(fwd) == 1    # never re-executed
        assert rep[-1][1]["error"]["code"] == "duplicate"

    def test_answered_lru_is_bounded(self):
        r, fwd, rep, _ = make_router(answered_cap=2)
        for rid in ("a", "b", "c"):
            r._handle(rmeta(rid))
            r._on_reply(_reply(rid))
        assert list(r._answered) == ["b", "c"]  # "a" evicted at cap


# ---------------------------------------------------------------------------
# failover: re-route within the budget, exactly-once across the move
# ---------------------------------------------------------------------------


class TestRouterFailover:
    def test_reroute_moves_inflight_to_next_preference(self):
        r, fwd, rep, reg = make_router()
        r._handle(rmeta("f1", {"site_index": 7}))
        pref = r._ring.preference("site:7")
        first = fwd[0][0]
        assert first == pref[0]
        r._ready.discard(first)
        r._reroute_worker(first)
        assert len(fwd) == 2
        second, meta = fwd[1]
        assert second == pref[1]  # the ring's failover order
        assert meta["worker"] == second  # stamp follows the move
        assert r._pending["f1"].worker == second
        assert r._inflight[first] == 0 and r._inflight[second] == 1
        assert reg.snapshot()["counters"]["router.rerouted_total"] == 1.0
        # exactly-once across the move: the survivor's reply lands, the
        # dead worker's late twin is dropped
        r._on_reply(_reply("f1"))
        r._on_reply(_reply("f1"))
        assert len(rep) == 1 and rep[0][1]["worker"] == second

    def test_reroute_cap_spends_then_rejects_typed(self):
        r, fwd, rep, _ = make_router(reroute_cap=1)
        r._handle(rmeta("f1", {"site_index": 7}))
        pref = r._ring.preference("site:7")
        r._ready.discard(pref[0])
        r._reroute_worker(pref[0])
        r._ready.discard(pref[1])
        r._reroute_worker(pref[1])  # budget spent -> typed rejection
        assert len(fwd) == 2
        err = rep[0][1]["error"]
        assert err["code"] == "unavailable"
        assert err["retry_after_ms"] >= 1
        assert "f1" not in r._pending

    def test_lone_worker_death_has_no_fallback(self):
        r, fwd, rep, _ = make_router(names=("w0",))
        r._handle(rmeta("f1"))
        r._ready.discard("w0")
        r._reroute_worker("w0")
        assert rep[0][1]["error"]["code"] == "unavailable"


# ---------------------------------------------------------------------------
# end-to-end: a real 2-worker fleet over the local broker
# ---------------------------------------------------------------------------


class TestFleetEndToEnd:
    def test_affinity_replay_lru_and_worker_stamp(self):
        """The replay-LRU affinity satellite: with consistent-hash
        routing, a replayed site request lands on the SAME worker, whose
        duplicate-id LRU rejects it typed — even when the router's own
        answered guard has forgotten the id.  Replies carry the worker
        stamp, and the v16 partition invariant holds."""
        from tmhpvsim_tpu.config import SiteGrid

        sim = scfg(site_grid=SiteGrid.regular(
            (45.0, 46.0), (5.0, 6.0), 2, 2))
        url = "local://fleet-e2e"
        base = ServeConfig(sim=sim, url=url, window_s=0.05,
                           batch_sizes=(1, 4), timeout_s=120.0,
                           drain_timeout_s=10.0)
        reg = MetricsRegistry()
        fleet = ServeFleet(
            FleetConfig(base=base, n_workers=2, health_period_s=0.05),
            registry=reg)

        async def main():
            with use_registry(reg):
                await fleet.start()
            try:
                async with ScenarioClient(url) as client:
                    replies = await asyncio.gather(*[
                        client.request({"site_index": s % 4,
                                        "horizon_s": 60},
                                       rid=f"s{s}", timeout=120)
                        for s in range(8)])
                    assert all(m["ok"] for m in replies), replies
                    by_site = {}
                    for s, m in enumerate(replies):
                        assert m["worker"] in ("w0", "w1")
                        assert m["result"]["site_index"] == s % 4
                        by_site.setdefault(s % 4, set()).add(m["worker"])
                    # affinity: every site answered by exactly one worker
                    assert all(len(ws) == 1 for ws in by_site.values())

                    # replay while the router remembers: its answered
                    # LRU rejects without touching a worker
                    dup = await client.request(
                        {"site_index": 0, "horizon_s": 60}, rid="s0",
                        timeout=30)
                    assert dup["error"]["code"] == "duplicate"

                    # replay after the router forgot: affinity re-routes
                    # to the SAME worker, whose replay LRU rejects —
                    # the id is never executed twice anywhere
                    batches_before = sum(
                        snap["counters"].get("serve.batches_total", 0)
                        for _, snap in fleet.worker_snapshots())
                    fleet.router._answered.clear()
                    dup2 = await client.request(
                        {"site_index": 0, "horizon_s": 60}, rid="s0",
                        timeout=30)
                    assert dup2["error"]["code"] == "duplicate"
                    assert dup2["worker"] == by_site[0].copy().pop()
                    batches_after = sum(
                        snap["counters"].get("serve.batches_total", 0)
                        for _, snap in fleet.worker_snapshots())
                    assert batches_after == batches_before

                    doc = fleet.fleet_doc()
                    assert doc is not None
                    assert [w["name"] for w in doc["workers"]] \
                        == ["w0", "w1"]
                    # the partition invariant serve_report.py enforces
                    assert sum(w["requests"] for w in doc["workers"]) \
                        == doc["router"]["routed"] \
                        + doc["router"]["rerouted"]
            finally:
                await fleet.stop(drain_timeout_s=5.0)

        _run(asyncio.wait_for(main(), timeout=600))
