"""Chaos soak tests: the fault-injection, resilience-policy, and
warm-recovery layers working together against real transports.

* the serve stack under injected publish failures, a TCP partition, and
  a circuit-breaker trip — every accepted request answered exactly once
  or typed-rejected, zero duplicated replies;
* SIGKILL mid-run under ``--supervise``: the restarted child resumes
  from the block checkpoint with zero fresh compiles and produces a
  byte-identical CSV;
* reconnect-and-resubscribe across all three broker transports.
"""

import asyncio
import collections
import contextlib
import json
import logging
import pathlib
import subprocess
import sys
import threading

import pytest

from test_amqp import fake_aio_pika  # noqa: F401
from tmhpvsim_tpu.config import SimConfig
from tmhpvsim_tpu.obs.metrics import MetricsRegistry, use_registry
from tmhpvsim_tpu.obs.report import REPORT_SCHEMA_VERSION, validate_report
from tmhpvsim_tpu.runtime import faults
from tmhpvsim_tpu.runtime.broker import make_transport
from tmhpvsim_tpu.runtime.faults import FaultPlan
from tmhpvsim_tpu.runtime.resilience import (
    CircuitBreaker,
    ResiliencePolicy,
    reconnect_policy,
)
from tmhpvsim_tpu.runtime.tcpbroker import TcpFanoutBroker
from tmhpvsim_tpu.serve.batcher import MicroBatcher
from tmhpvsim_tpu.serve.schema import RequestError
from tmhpvsim_tpu.serve.server import (
    ScenarioClient,
    ScenarioServer,
    ServeConfig,
)

pytestmark = pytest.mark.chaos

REPO = pathlib.Path(__file__).resolve().parent.parent
RESILIENCE_REPORT = REPO / "tools" / "resilience_report.py"


def _run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def scfg(**kw):
    base = dict(
        start="2019-09-05 10:00:00",
        duration_s=120,
        n_chains=4,
        seed=7,
        block_s=60,
        dtype="float32",
        output="reduce",
        block_impl="scan",
        scan_unroll=1,
    )
    base.update(kw)
    return SimConfig(**base)


@pytest.fixture(autouse=True)
def _no_leaked_chaos():
    yield
    faults.deactivate()


# ---------------------------------------------------------------------------
# serve soak: publish faults + a TCP partition + a breaker trip in one run
# ---------------------------------------------------------------------------


class TestServeSoak:
    def test_accepted_requests_answered_exactly_once(self):
        """End-to-end over tcp://.  The plan injects two publish
        failures (absorbed by bounded retries), two dispatch failures
        (typed ``internal`` + breaker trip at threshold 2), and one
        mid-run partition (reconnect-and-resubscribe; at-least-once
        client retries, server replay cache dedupes).  Exactly-once:
        no id ever gets two ok replies."""
        reg = MetricsRegistry()
        plan = FaultPlan.parse(
            "broker.publish=raise@n6x2"
            ";tcp.partition=raise@n25"
            ";serve.dispatch=raise@n2x2")
        outcomes = {}
        ok_seen = collections.Counter()

        async def ask(client, rid, timeout=10.0):
            for _ in range(5):
                try:
                    return await client.request(rid=rid, timeout=timeout)
                except asyncio.TimeoutError:
                    continue  # at-least-once: same rid, server dedupes
            raise AssertionError(f"no reply for {rid}")

        async def monitor(url, reply_to):
            async def run():
                async with make_transport(url, reply_to) as tx:
                    async for _t, _v, meta in tx.subscribe(with_meta=True):
                        if isinstance(meta, dict) and meta.get("ok"):
                            ok_seen[meta.get("id")] += 1

            await reconnect_policy(
                name="soak.monitor", base_delay_s=0.01,
                max_delay_s=0.05, registry=reg).call(run)

        async def main():
            async with TcpFanoutBroker(port=0) as broker:
                url = f"tcp://127.0.0.1:{broker.port}"
                cfg = ServeConfig(
                    sim=scfg(), url=url, window_s=0.05,
                    batch_sizes=(1, 4, 8), timeout_s=30.0,
                    recent_ids_cap=8, breaker_threshold=2,
                    breaker_reset_s=1.5)
                server = ScenarioServer(cfg, registry=reg)
                await server.start()
                client = ScenarioClient(url, policy=ResiliencePolicy(
                    attempts=8, base_delay_s=0.01, max_delay_s=0.05,
                    name="soak.request", registry=reg))
                async with client:
                    mon = asyncio.create_task(
                        monitor(url, client.reply_to))
                    await asyncio.sleep(0.1)
                    try:
                        with faults.active(plan):
                            for rid in ("w1-0", "w2-0", "w3-0", "w4-0"):
                                outcomes[rid] = await ask(client, rid)
                            await asyncio.sleep(cfg.breaker_reset_s + 0.3)
                            w5 = await asyncio.gather(*[
                                ask(client, f"w5-{i}") for i in range(6)])
                            for i, meta in enumerate(w5):
                                outcomes[f"w5-{i}"] = meta
                        # snapshot before the replay probes below add
                        # fresh (legitimate) completions
                        snapshot = dict(ok_seen)
                        # chaos off: bounded-replay satellites.  w5-5 is
                        # still in the LRU -> typed duplicate; w1-0 was
                        # evicted (10 completions vs cap 8) -> fresh run
                        dup = await ask(client, "w5-5")
                        fresh = await ask(client, "w1-0")
                    finally:
                        mon.cancel()
                        with contextlib.suppress(asyncio.CancelledError,
                                                 ConnectionError):
                            await mon
                await server.stop()
                return snapshot, dup, fresh

        with use_registry(reg):
            snapshot, dup, fresh = _run(
                asyncio.wait_for(main(), timeout=240))

        # deterministic pre-partition outcomes
        assert outcomes["w1-0"]["ok"] is True
        assert outcomes["w2-0"]["error"]["code"] == "internal"
        assert outcomes["w3-0"]["error"]["code"] == "internal"
        assert outcomes["w4-0"]["error"]["code"] == "unavailable"
        # the partition window may turn any ok into a typed duplicate
        # (reply lost in the gap, client re-asked, server deduped) —
        # never into a recompute
        for i in range(6):
            meta = outcomes[f"w5-{i}"]
            assert meta["ok"] or \
                meta["error"]["code"] == "duplicate", meta
        # exactly-once: zero duplicated ok replies across the whole soak
        assert all(n <= 1 for n in snapshot.values()), snapshot
        assert dup["error"]["code"] == "duplicate"
        assert fresh["ok"] is True

        snap = reg.snapshot()
        c, g = snap["counters"], snap["gauges"]
        assert c["faults.injected.serve.dispatch"] == 2.0
        assert c["faults.injected.broker.publish"] == 2.0
        assert c["faults.injected.tcp.partition"] == 1.0
        assert c["faults.injected_total"] == 5.0
        assert c["resilience.breaker_open_total.serve.dispatch"] == 1.0
        assert c["resilience.breaker_rejected_total.serve.dispatch"] >= 1.0
        assert c["serve.replay_evictions_total"] >= 2.0
        assert c["resilience.retries_total"] >= 2.0
        assert g["resilience.breaker_state.serve.dispatch"] == 0.0


# ---------------------------------------------------------------------------
# fleet soak: worker SIGKILL + tcp partition under load, exactly-once
# replies, warm zero-cold-compile respawn
# ---------------------------------------------------------------------------


class TestFleetChaos:
    def test_worker_kill_partition_exactly_once_warm_respawn(
            self, tmp_path):
        """The serving-fleet soak over tcp://.  A worker SIGKILL under
        a concurrent burst: the router's health loop sheds the corpse,
        pending requests re-route to the survivor, every outcome is ok
        or typed, and no id ever collects two ok replies.  The
        replacement worker comes up against the warm compile cache
        with ZERO cold compiles.  A mid-run TCP partition drops a real
        socket; reconnect-and-resubscribe absorbs it."""
        from tmhpvsim_tpu.config import SiteGrid
        from tmhpvsim_tpu.engine import compilecache
        from tmhpvsim_tpu.serve.fleet import FleetConfig, ServeFleet

        compilecache.configure(str(tmp_path))
        reg = MetricsRegistry()
        ok_seen = collections.Counter()
        sim = scfg(n_chains=2, site_grid=SiteGrid.regular(
            (45.0, 46.0), (5.0, 6.0), 1, 2))

        async def monitor(url, reply_to):
            async def run():
                async with make_transport(url, reply_to) as tx:
                    async for _t, _v, meta in tx.subscribe(
                            with_meta=True):
                        if isinstance(meta, dict) and meta.get("ok"):
                            ok_seen[meta.get("id")] += 1

            await reconnect_policy(
                name="fleet.monitor", base_delay_s=0.01,
                max_delay_s=0.05, registry=reg).call(run)

        async def ask(client, rid, scenario=None, timeout=60.0):
            for _ in range(5):
                try:
                    return await client.request(scenario, rid=rid,
                                                timeout=timeout)
                except asyncio.TimeoutError:
                    continue  # at-least-once: the router dedupes
            raise AssertionError(f"no reply for {rid}")

        async def settle(fleet, want):
            for _ in range(150):
                _ok, detail = fleet.readiness()
                if detail.get("workers_ready") == want:
                    return detail
                await asyncio.sleep(0.1)
            raise AssertionError(
                f"ready set never became {want}: {detail}")

        async def main():
            async with TcpFanoutBroker(port=0) as broker:
                url = f"tcp://127.0.0.1:{broker.port}"
                base = ServeConfig(sim=sim, url=url, window_s=0.02,
                                   batch_sizes=(2,), timeout_s=60.0)
                fleet = ServeFleet(
                    FleetConfig(base, n_workers=2,
                                health_period_s=0.05),
                    registry=reg)
                await fleet.start()
                client = ScenarioClient(url, policy=ResiliencePolicy(
                    attempts=8, base_delay_s=0.01, max_delay_s=0.05,
                    name="fleet.request", registry=reg))
                async with client:
                    mon = asyncio.create_task(
                        monitor(url, client.reply_to))
                    await asyncio.sleep(0.1)
                    try:
                        # phase 1: both workers up; shard affinity is
                        # sticky per site key
                        p1 = await asyncio.gather(*[
                            ask(client, f"p1-{i}",
                                {"site_index": i % 2, "horizon_s": 60})
                            for i in range(4)])
                        assert all(r["ok"] for r in p1), p1
                        by_site = collections.defaultdict(set)
                        for i, r in enumerate(p1):
                            by_site[i % 2].add(r["worker"])
                        assert all(len(ws) == 1
                                   for ws in by_site.values()), by_site
                        # phase 2: SIGKILL w0 under a concurrent burst
                        burst = [
                            asyncio.create_task(ask(
                                client, f"p2-{i}",
                                {"site_index": i % 2,
                                 "horizon_s": 60}))
                            for i in range(6)]
                        await asyncio.sleep(0.02)
                        await fleet.kill_worker(0)
                        p2 = await asyncio.gather(*burst)
                        for meta in p2:
                            assert meta["ok"] or meta["error"]["code"] \
                                in ("unavailable", "busy",
                                    "duplicate"), meta
                        await settle(fleet, ["w1"])
                        # the survivor answers every site key now
                        p3 = await asyncio.gather(*[
                            ask(client, f"p3-{i}",
                                {"site_index": i % 2, "horizon_s": 60})
                            for i in range(2)])
                        assert all(r["ok"] and r["worker"] == "w1"
                                   for r in p3), p3
                        # phase 3: warm respawn — the replacement life
                        # compiles NOTHING cold (fleet acceptance)
                        await fleet.respawn_worker(0)
                        await settle(fleet, ["w0", "w1"])
                        wc = fleet.workers[0].registry.snapshot()[
                            "counters"]
                        assert wc.get("executor.compile_cold_total",
                                      0) == 0, wc
                        assert wc["executor.compile_warm_total"] >= 1
                        # phase 4: a real TCP partition mid-serve
                        plan = FaultPlan.parse("tcp.partition=raise@n3")
                        with faults.active(plan):
                            p4 = await asyncio.gather(*[
                                ask(client, f"p4-{i}",
                                    {"site_index": i % 2,
                                     "horizon_s": 60})
                                for i in range(4)])
                        for meta in p4:
                            assert meta["ok"] or meta["error"]["code"] \
                                in ("unavailable", "busy",
                                    "duplicate", "timeout"), meta
                        await asyncio.sleep(0.3)  # reconnects settle
                        final = await ask(client, "p5-0",
                                          {"horizon_s": 60})
                        assert final["ok"] is True, final
                        snapshot = dict(ok_seen)
                    finally:
                        mon.cancel()
                        with contextlib.suppress(asyncio.CancelledError,
                                                 ConnectionError):
                            await mon
                doc = fleet.fleet_doc()
                await fleet.stop(drain_timeout_s=5.0)
                return snapshot, doc

        with use_registry(reg):
            snapshot, doc = _run(asyncio.wait_for(main(), timeout=480))

        # exactly-once: zero duplicated ok replies across kill,
        # re-route, respawn and partition
        assert snapshot and all(n <= 1 for n in snapshot.values()), \
            snapshot
        c = reg.snapshot()["counters"]
        assert c["faults.injected.tcp.partition"] == 1.0
        assert c["router.worker_down_total"] >= 1.0
        # the v16 fleet doc holds its partition invariant end to end
        assert sum(w["requests"] for w in doc["workers"]) \
            == doc["router"]["routed"] + doc["router"]["rerouted"]


# ---------------------------------------------------------------------------
# SIGKILL mid-run: --supervise restarts warm, output byte-identical
# ---------------------------------------------------------------------------


def _env():
    import os

    env = dict(os.environ, PYTHONPATH="", JAX_PLATFORMS="cpu")
    for k in ("XLA_FLAGS", "TMHPVSIM_CHAOS", "TMHPVSIM_CHAOS_SEED"):
        env.pop(k, None)
    return env


class TestSigkillWarmRecovery:
    def test_supervised_restart_resumes_bit_identical(self, tmp_path):
        """A chaos-injected SIGKILL right after block 1's checkpoint
        commit; the supervisor restarts the child, which resumes from
        the checkpoint with zero cold compiles and completes a CSV
        byte-identical to an uninterrupted run."""
        pvsim = [sys.executable, "-m", "tmhpvsim_tpu.cli", "pvsim"]
        flags = ["--backend=jax", "--no-realtime", "--duration", "360",
                 "--seed", "9", "--start", "2019-09-05 10:00:00",
                 "--block-s", "120"]
        whole = tmp_path / "whole.csv"
        ref = subprocess.run([*pvsim, str(whole), *flags], env=_env(),
                             cwd=REPO, capture_output=True, text=True,
                             timeout=300)
        assert ref.returncode == 0, ref.stderr

        part = tmp_path / "part.csv"
        ck = tmp_path / "ck.npz"
        report = tmp_path / "report.json"
        sup = subprocess.run(
            [*pvsim, str(part), *flags,
             "--checkpoint", str(ck), "--supervise", "2",
             "--run-report", str(report),
             "--chaos", "checkpoint.committed=kill@n2"],
            env=_env(), cwd=REPO, capture_output=True, text=True,
            timeout=300)
        assert sup.returncode == 0, sup.stderr
        assert "warm restart 1/2" in sup.stderr

        assert part.read_bytes() == whole.read_bytes()

        doc = validate_report(json.loads(report.read_text()))
        assert doc["schema_version"] == REPORT_SCHEMA_VERSION == 16
        res = doc["resilience"]
        assert res["resumes"] == 1
        assert res["restarts"] == 1
        assert res["resumed_block"] == 2
        # zero fresh compiles on the warm restart: everything the
        # resumed child runs deserializes from the persistent cache
        assert doc["executor"]["compile_cold"] == 0

        tool = subprocess.run(
            [sys.executable, str(RESILIENCE_REPORT), str(report)],
            capture_output=True, text=True, timeout=60)
        assert tool.returncode == 0, tool.stdout + tool.stderr
        assert "resumes=1 from block 2" in tool.stdout


# ---------------------------------------------------------------------------
# torn-write + preemption recovery (engine/checkpoint.py rotation)
# ---------------------------------------------------------------------------


CKPT_REPORT = REPO / "tools" / "ckpt_report.py"

_PVSIM = [sys.executable, "-m", "tmhpvsim_tpu.cli", "pvsim"]
_FLAGS = ["--backend=jax", "--no-realtime", "--duration", "360",
          "--seed", "9", "--start", "2019-09-05 10:00:00",
          "--block-s", "120"]


class TestTornWriteRecovery:
    def test_truncated_generation_falls_back_and_completes(self, tmp_path):
        """Chaos tears the freshly committed generation AND SIGKILLs the
        child; each supervised restart detects the torn latest via the
        integrity manifest, falls back to the newest verifying
        generation (one lost block, a WARN), and the finished CSV is
        byte-identical to an uninterrupted run."""
        whole = tmp_path / "whole.csv"
        ref = subprocess.run([*_PVSIM, str(whole), *_FLAGS], env=_env(),
                             cwd=REPO, capture_output=True, text=True,
                             timeout=300)
        assert ref.returncode == 0, ref.stderr

        part = tmp_path / "part.csv"
        ck = tmp_path / "ck.npz"
        report = tmp_path / "report.json"
        sup = subprocess.run(
            [*_PVSIM, str(part), *_FLAGS,
             "--checkpoint", str(ck), "--supervise", "2",
             "--run-report", str(report),
             "--chaos", "checkpoint.corrupt=truncate:200@n2"
                        ";checkpoint.committed=kill@n2"],
            env=_env(), cwd=REPO, capture_output=True, text=True,
            timeout=600)
        assert sup.returncode == 0, sup.stderr
        assert "warm restart 1/2" in sup.stderr
        assert "falling back to generation" in sup.stderr
        assert part.read_bytes() == whole.read_bytes()

        doc = validate_report(json.loads(report.read_text()))
        sec = doc["checkpoint"]
        assert sec["fallbacks"] == 1
        assert sec["verify_failures"] >= 1

        # the stdlib checkpoint doctor agrees: resumable despite the
        # torn generation, and the report section is well-formed
        tool = subprocess.run(
            [sys.executable, str(CKPT_REPORT), str(ck), str(report)],
            env=_env(), cwd=REPO, capture_output=True, text=True,
            timeout=120)
        assert tool.returncode == 0, tool.stdout + tool.stderr


class TestPreemptionGrace:
    def test_chaos_preempt_stops_at_boundary_and_resumes(self, tmp_path):
        """The signal-free preemption path: a chaos ``signal.preempt``
        notice stops the run at the next block boundary with the
        snapshot durable and exit 0; rerunning the same command
        finishes the CSV byte-identically."""
        from click.testing import CliRunner

        from tmhpvsim_tpu.cli import main as cli_main
        from tmhpvsim_tpu.engine import checkpoint as ckpt

        def invoke(out, *extra):
            return CliRunner().invoke(cli_main, [
                "pvsim", out, *_FLAGS, *extra])

        whole = tmp_path / "whole.csv"
        r = invoke(str(whole))
        assert r.exit_code == 0, r.output

        part = tmp_path / "part.csv"
        ck = tmp_path / "ck.npz"
        r = invoke(str(part), "--checkpoint", str(ck),
                   "--chaos", "signal.preempt=raise@n2")
        assert r.exit_code == 0, r.output
        assert "preempted" in r.output
        faults.deactivate()
        assert ckpt.peek_meta(str(ck))["next_block"] == 2
        with open(part) as f:  # exactly the checkpointed blocks
            assert len(f.readlines()) == 1 + 240

        r = invoke(str(part), "--checkpoint", str(ck))
        assert r.exit_code == 0, r.output
        assert part.read_bytes() == whole.read_bytes()

    def test_sigterm_grace_snapshots_and_resumes(self, tmp_path):
        """A real SIGTERM under --preempt-grace: the child finishes the
        in-flight block, snapshots, exits 0; the rerun completes the CSV
        byte-identically.  (Chaos delays pace the saves so the signal
        lands mid-run; the finished-first race is tolerated — the rerun
        is then a no-op replay.)"""
        import signal
        import time

        from tmhpvsim_tpu.engine import checkpoint as ckpt

        whole = tmp_path / "whole.csv"
        ref = subprocess.run([*_PVSIM, str(whole), *_FLAGS], env=_env(),
                             cwd=REPO, capture_output=True, text=True,
                             timeout=300)
        assert ref.returncode == 0, ref.stderr

        part = tmp_path / "part.csv"
        ck = tmp_path / "ck.npz"
        proc = subprocess.Popen(
            [*_PVSIM, str(part), *_FLAGS, "--checkpoint", str(ck),
             "--preempt-grace", "60",
             "--chaos", "checkpoint.write=delay:0.5@every1"],
            env=_env(), cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True)
        deadline = time.monotonic() + 240
        while (time.monotonic() < deadline and proc.poll() is None
               and not ck.exists()):
            time.sleep(0.05)
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=300)
        assert proc.returncode == 0, err
        if "preempted" in out:
            assert ckpt.resumable(str(ck))

        fin = subprocess.run(
            [*_PVSIM, str(part), *_FLAGS, "--checkpoint", str(ck)],
            env=_env(), cwd=REPO, capture_output=True, text=True,
            timeout=300)
        assert fin.returncode == 0, fin.stderr
        assert part.read_bytes() == whole.read_bytes()


# ---------------------------------------------------------------------------
# reconnect-and-resubscribe across all three transports
# ---------------------------------------------------------------------------


async def _stream_with_reconnect(url, spec, reg, n=24):
    """Publish ``n`` seq-stamped messages while ``spec`` kills the
    subscription once mid-stream; the consumer reconnects under the
    stack's standard policy.  Returns the seqs it saw."""
    seen = []
    done = asyncio.Event()

    async def consume_once():
        async with make_transport(url, "meter") as tx:
            async for _t, _v, meta in tx.subscribe(with_meta=True):
                seen.append(int(meta["seq"]))
                if meta["seq"] >= n - 1:
                    done.set()
                    return

    with faults.active(FaultPlan.parse(spec)):
        consumer = asyncio.create_task(reconnect_policy(
            name="chaos.consume", base_delay_s=0.01, max_delay_s=0.05,
            registry=reg).call(consume_once))
        await asyncio.sleep(0.05)
        async with make_transport(url, "meter") as pub:
            import datetime as dt

            for i in range(n):
                await pub.publish(float(i), dt.datetime(2019, 9, 5),
                                  meta={"seq": i})
                await asyncio.sleep(0.03)
        await asyncio.wait_for(done.wait(), 30)
        consumer.cancel()
        with contextlib.suppress(asyncio.CancelledError):
            await consumer
    return seen


def _assert_reconnected(seen, reg, point, n=24):
    # strictly monotonic: no replays, no reordering across the gap
    assert seen == sorted(set(seen))
    assert seen[-1] == n - 1
    assert len(seen) >= n - 6  # the gap loses at most a few messages
    c = reg.snapshot()["counters"]
    assert c[f"faults.injected.{point}"] == 1.0
    assert c["retry.attempts.chaos.consume"] >= 1.0


class TestReconnectResubscribe:
    def test_tcp_partition_reconnects(self):
        reg = MetricsRegistry()

        async def main():
            async with TcpFanoutBroker(port=0) as broker:
                url = f"tcp://127.0.0.1:{broker.port}"
                return await _stream_with_reconnect(
                    url, "tcp.partition=raise@n3", reg)

        with use_registry(reg):
            seen = _run(main())
        _assert_reconnected(seen, reg, "tcp.partition")

    def test_local_deliver_fault_reconnects(self):
        reg = MetricsRegistry()
        with use_registry(reg):
            seen = _run(_stream_with_reconnect(
                "local://chaos-reconnect", "broker.deliver=raise@n3",
                reg))
        _assert_reconnected(seen, reg, "broker.deliver")

    def test_amqp_deliver_fault_reconnects(self, fake_aio_pika):  # noqa: F811
        reg = MetricsRegistry()
        with use_registry(reg):
            seen = _run(_stream_with_reconnect(
                "amqp://localhost", "broker.deliver=raise@n3", reg))
        _assert_reconnected(seen, reg, "broker.deliver")


# ---------------------------------------------------------------------------
# batcher satellites: breaker shedding + the drain deadline
# ---------------------------------------------------------------------------


class _Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


class TestBatcherResilience:
    def test_breaker_open_sheds_then_probe_recloses(self):
        async def main():
            reg = MetricsRegistry()
            clk = _Clock()
            br = CircuitBreaker("serve.dispatch", failure_threshold=1,
                                reset_s=30.0, registry=reg, now=clk)
            b = MicroBatcher(lambda reqs: list(reqs), window_s=0.005,
                             max_batch=2, registry=reg, breaker=br)
            b.start()
            br.record_failure()  # open
            with pytest.raises(RequestError) as ei:
                b.submit("x")
            assert ei.value.code == "unavailable"
            clk.t = 30.0  # half-open: the next batch is the probe
            result, info = await b.submit("y")
            assert result == "y" and info["batch"] == 1
            assert br.state == "closed"
            await b.stop(drain=True)
            c = reg.snapshot()["counters"]
            assert c["resilience.breaker_rejected_total.serve.dispatch"] \
                == 1.0

        _run(main())

    def test_drain_deadline_force_closes_with_typed_draining(self, caplog):
        release = threading.Event()

        async def main():
            reg = MetricsRegistry()

            def dispatch(reqs):
                release.wait(5.0)
                return [None] * len(reqs)

            b = MicroBatcher(dispatch, window_s=0.001, max_batch=1,
                             registry=reg)
            b.start()
            b.submit("a")  # occupies the worker thread
            f2, f3 = b.submit("b"), b.submit("c")
            await asyncio.sleep(0.05)
            with caplog.at_level(logging.WARNING,
                                 logger="tmhpvsim_tpu.serve.batcher"):
                await b.stop(drain=True, timeout=0.2)
            release.set()
            for f in (f2, f3):
                with pytest.raises(RequestError) as ei:
                    await f
                assert ei.value.code == "draining"
                assert "drain deadline (0.2 s) exceeded" in str(ei.value)
            assert any("force-closing" in r.getMessage()
                       for r in caplog.records)

        _run(main())
