"""AmqpTransport contract tests against a fake ``aio_pika``.

aio-pika isn't part of this image, so the real-broker class in
runtime/broker.py would otherwise be permanently unexecuted code.  The
fake below implements the slice of the aio-pika 9.x surface the transport
touches and *records* the topology calls, so these tests pin the exact
reference semantics (SURVEY.md §2.4): named FANOUT exchange, exclusive
consumer queue bound to it, prefetch 1, JSON float body, timestamp
property, shielded publish.
"""

import asyncio
import datetime as dt
import json
import sys
import types

import pytest

from tmhpvsim_tpu.runtime import broker as broker_mod


class FakeMessage:
    def __init__(self, body, timestamp=None, headers=None):
        self.body = body
        self.timestamp = timestamp
        self.headers = headers
        self.processed = False

    def process(self):
        msg = self

        class _Ctx:
            async def __aenter__(self):
                return msg

            async def __aexit__(self, *exc):
                msg.processed = True
                return False

        return _Ctx()


class FakeExchange:
    def __init__(self, name, type_, log):
        self.name = name
        self.type = type_
        self.queues = []
        self.log = log

    async def publish(self, message, routing_key=""):
        self.log.append(("publish", self.name, routing_key))
        for q in self.queues:
            q._items.put_nowait(message)


class FakeQueue:
    def __init__(self, exclusive, log):
        self.exclusive = exclusive
        self.log = log
        self._items = asyncio.Queue()

    async def bind(self, exchange):
        self.log.append(("bind", exchange.name, self.exclusive))
        exchange.queues.append(self)

    def iterator(self):
        q = self

        class _It:
            async def __aenter__(self):
                return self

            async def __aexit__(self, *exc):
                return False

            def __aiter__(self):
                return self

            async def __anext__(self):
                return await q._items.get()

        return _It()


class FakeChannel:
    """Exchanges live on the *broker*, shared across connections by name —
    the property the fanout join depends on."""

    _broker_exchanges = {}  # reset per fixture

    def __init__(self, log):
        self.log = log
        self.exchanges = FakeChannel._broker_exchanges

    async def declare_exchange(self, name, type_):
        self.log.append(("declare_exchange", name, type_))
        return self.exchanges.setdefault(
            name, FakeExchange(name, type_, self.log))

    async def set_qos(self, prefetch_count=None):
        self.log.append(("set_qos", prefetch_count))

    async def declare_queue(self, exclusive=False):
        self.log.append(("declare_queue", exclusive))
        return FakeQueue(exclusive, self.log)


class FakeConnection:
    def __init__(self, url, log):
        self.url = url
        self.log = log
        self._channel = FakeChannel(log)
        self.closed = False

    async def channel(self):
        return self._channel

    async def close(self):
        self.closed = True
        self.log.append(("close",))


@pytest.fixture
def fake_aio_pika(monkeypatch):
    log = []
    mod = types.ModuleType("aio_pika")
    mod.Message = FakeMessage
    mod.ExchangeType = types.SimpleNamespace(FANOUT="fanout")

    async def connect_robust(url):
        log.append(("connect", url))
        conn = FakeConnection(url, log)
        mod._connections.append(conn)
        return conn

    mod.connect_robust = connect_robust
    mod._connections = []
    FakeChannel._broker_exchanges = {}
    monkeypatch.setitem(sys.modules, "aio_pika", mod)
    return mod, log


def _run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def test_make_transport_selects_amqp_for_broker_urls(fake_aio_pika):
    t = broker_mod.make_transport("amqp://h:5672/", "meter")
    assert isinstance(t, broker_mod.AmqpTransport)


def test_amqp_requires_aio_pika():
    assert "aio_pika" not in sys.modules  # image really lacks it
    with pytest.raises(RuntimeError, match="aio_pika is not installed"):
        broker_mod.AmqpTransport("amqp://h/", "meter")


def test_publish_topology_and_wire_format(fake_aio_pika):
    mod, log = fake_aio_pika
    t0 = dt.datetime(2019, 9, 5, 12, 0, 0)

    async def scenario():
        async with broker_mod.AmqpTransport("amqp://host/", "meter") as t:
            await t.publish(1234.5, t0)

    _run(scenario())
    assert ("connect", "amqp://host/") in log
    # reference topology: named fanout exchange (metersim.py:25-28)
    assert ("declare_exchange", "meter", "fanout") in log
    assert ("publish", "meter", "") in log
    assert ("close",) in log


def test_wire_format_json_float_plus_timestamp(fake_aio_pika):
    """UTF-8 JSON float body + timestamp property (metersim.py:38-42)."""
    mod, log = fake_aio_pika
    t0 = dt.datetime(2019, 9, 5, 12, 0, 0)
    captured = FakeQueue(exclusive=True, log=log)

    async def scenario():
        async with broker_mod.AmqpTransport("amqp://host/", "meter") as t:
            t._exchange.queues.append(captured)
            await t.publish(4321.25, t0)

    _run(scenario())
    msg = captured._items.get_nowait()
    assert json.loads(msg.body.decode()) == 4321.25
    assert msg.timestamp == t0


def test_fanout_roundtrip_and_consumer_contract(fake_aio_pika):
    mod, log = fake_aio_pika
    t0 = dt.datetime(2019, 9, 5, 12, 0, 0)
    got = []

    async def scenario():
        async with broker_mod.AmqpTransport("amqp://host/", "meter") as pub:
            async with broker_mod.AmqpTransport("amqp://host/",
                                                "meter") as sub:
                async def consume():
                    async for time, value in sub.subscribe():
                        got.append((time, value))
                        if len(got) == 2:
                            return

                task = asyncio.ensure_future(consume())
                await asyncio.sleep(0)  # let subscribe bind first
                await pub.publish(100.0, t0)
                await pub.publish(200.5, t0 + dt.timedelta(seconds=1))
                await asyncio.wait_for(task, timeout=5)

    _run(scenario())
    # consumer contract: prefetch 1 + exclusive queue (pvsim.py:53-63)
    assert ("set_qos", 1) in log
    assert ("declare_queue", True) in log
    assert ("bind", "meter", True) in log
    assert got == [(t0, 100.0), (t0 + dt.timedelta(seconds=1), 200.5)]


def test_posix_timestamp_coerced_to_datetime(fake_aio_pika):
    """Brokers deliver the timestamp property as POSIX seconds; the
    consumer must coerce it (the reference leans on aio-pika's coercion,
    pvsim.py:69)."""
    mod, log = fake_aio_pika
    t0 = dt.datetime(2019, 9, 5, 12, 0, 0)
    got = []

    async def scenario():
        async with broker_mod.AmqpTransport("amqp://host/", "meter") as sub:
            async def consume():
                async for time, value in sub.subscribe():
                    got.append((time, value))
                    return

            task = asyncio.ensure_future(consume())
            await asyncio.sleep(0)
            # bypass publish(): inject a raw POSIX-stamped message like a
            # real broker would deliver
            exchange = mod._connections[0]._channel.exchanges["meter"]
            await exchange.publish(
                FakeMessage(json.dumps(42.0).encode(),
                            timestamp=t0.timestamp())
            )
            await asyncio.wait_for(task, timeout=5)

    _run(scenario())
    assert got == [(t0, 42.0)]


def test_meta_rides_amqp_headers(fake_aio_pika):
    """metersim's seq/pub_us stamps travel in AMQP *headers*, never the
    body: the body stays a bare JSON float for reference consumers, and
    subscribe(with_meta=True) surfaces the headers (or None)."""
    mod, log = fake_aio_pika
    t0 = dt.datetime(2019, 9, 5, 12, 0, 0)
    got = []

    async def scenario():
        async with broker_mod.AmqpTransport("amqp://host/", "meter") as pub:
            async with broker_mod.AmqpTransport("amqp://host/",
                                                "meter") as sub:
                async def consume():
                    async for item in sub.subscribe(with_meta=True):
                        got.append(item)
                        if len(got) == 2:
                            return

                task = asyncio.ensure_future(consume())
                await asyncio.sleep(0)
                await pub.publish(100.0, t0, meta={"seq": 0, "pub_us": 5})
                await pub.publish(200.5, t0)
                await asyncio.wait_for(task, timeout=5)

    _run(scenario())
    assert got[0] == (t0, 100.0, {"seq": 0, "pub_us": 5})
    assert got[1] == (t0, 200.5, None)


def test_apps_join_over_fake_amqp(fake_aio_pika, tmp_path):
    """metersim -> broker -> pvsim end to end over the fake AMQP stack:
    the apps must work against a real-broker URL, not only local://."""
    import csv

    from tmhpvsim_tpu.apps.metersim import metersim_main
    from tmhpvsim_tpu.apps.pvsim import pvsim_main

    out = tmp_path / "amqp.csv"
    start = dt.datetime(2019, 9, 5, 12, 0, 0)

    async def both():
        consumer = asyncio.ensure_future(
            pvsim_main(str(out), "amqp://host/", "meter", realtime=False,
                       seed=1, duration_s=None, start=start)
        )
        await asyncio.sleep(0.2)
        await metersim_main("amqp://host/", "meter", realtime=False, seed=2,
                            duration_s=20, start=start)
        await asyncio.sleep(0.3)
        consumer.cancel()
        try:
            await consumer
        except asyncio.CancelledError:
            pass

    _run(both())
    with open(out) as f:
        rows = list(csv.reader(f))
    assert rows[0] == ["time", "meter", "pv", "residual load"]
    assert len(rows) > 10
    for _, meter, pv, residual in rows[1:]:
        assert float(meter) - float(pv) == pytest.approx(float(residual))
