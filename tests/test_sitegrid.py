"""Multi-site grid path (BASELINE config #3: "10k-site lat/lon grid").

The reference simulates exactly one hard-coded site (pvmodel.py:19-30); the
grid path is a pure TPU-era capability: chain i simulates site i, with solar
geometry evaluated on device from the float32-safe split-time representation
(models/solar.py sun_position_split / device_geometry) instead of the
shared-site host-float64 precompute.

Covered here:
* algebraic equivalence of the split-time ephemeris with the raw-epoch one
  (same formulas, float64 in = bit-near-identical out);
* float32 accuracy of the split-time path against the float64 host path
  (the claim at models/solar.py:137-150: ~0.01 deg worst-case);
* end-to-end SimConfig(site_grid=...) runs on both the single-chip engine
  and the 8-device sharded mesh;
* a grid of identical sites reproduces the shared-site run (same seed ->
  same csi streams -> pv equal up to geometry-path float error);
* checkpoint config echo catches a changed grid across resume.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from tmhpvsim_tpu.config import Site, SiteGrid, SimConfig
from tmhpvsim_tpu.engine import Simulation
from tmhpvsim_tpu.engine import checkpoint as ckpt
from tmhpvsim_tpu.models import solar

SITE = Site()


def _day_epochs():
    # One UTC day at 60 s cadence, 2019-09-05 (the reference's test date).
    t0 = 1567641600  # 2019-09-05 00:00:00 UTC
    epoch = np.arange(t0, t0 + 86400, 60, dtype=np.int64)
    doy = np.full(epoch.shape, 248.0)
    return epoch, doy


def _split(epoch, dtype):
    return (
        (epoch // 86400 - 10957).astype(dtype),
        (epoch % 86400).astype(dtype),
    )


class TestSplitTimeGeometry:
    def test_split_matches_raw_in_float64(self):
        """Same ephemeris, different time plumbing: float64 split-time must
        agree with the raw-epoch path to sub-arcsecond level."""
        epoch, doy = _day_epochs()
        raw = solar.sun_position(epoch.astype(np.float64), SITE.latitude,
                                 SITE.longitude, xp=np)
        day2000, sec = _split(epoch, np.float64)
        split = solar.sun_position_split(day2000, sec, SITE.latitude,
                                         SITE.longitude, xp=np)
        # 1e-9 rad ~ 2e-4 arcsec: pure float64 rounding from the re-grouped
        # polynomial evaluation.
        np.testing.assert_allclose(split["zenith"], raw["zenith"], atol=1e-9)
        np.testing.assert_allclose(
            np.unwrap(split["azimuth"] - raw["azimuth"]), 0.0, atol=1e-9
        )

    def test_split_float32_accuracy(self):
        """The float32 split path must stay within the documented ~0.01 deg
        of the float64 host path (models/solar.py:137-150)."""
        epoch, doy = _day_epochs()
        ref = solar.sun_position(epoch.astype(np.float64), SITE.latitude,
                                 SITE.longitude, xp=np)
        day2000, sec = _split(epoch, np.float32)
        got = solar.sun_position_split(
            day2000, sec, np.float32(SITE.latitude),
            np.float32(SITE.longitude), xp=np,
        )
        err_deg = np.abs(got["zenith"] - ref["zenith"]) / solar.DEG
        assert err_deg.max() < 0.02, err_deg.max()

    def test_device_geometry_matches_block_geometry(self):
        """Full feature dict: device (split float32) vs host (raw float64)."""
        epoch, doy = _day_epochs()
        host = solar.block_geometry(epoch.astype(np.float64), doy, SITE,
                                    xp=np)
        day2000, sec = _split(epoch, np.float32)
        dev = solar.device_geometry(
            day2000, sec, doy.astype(np.float32),
            np.float32(SITE.latitude), np.float32(SITE.longitude),
            np.float32(SITE.altitude), np.float32(SITE.surface_tilt),
            np.float32(SITE.surface_azimuth), np.float32(0.25),
            np.asarray(SITE.linke_turbidity_monthly, np.float32), xp=np,
        )
        assert np.abs(dev["zenith"] - host["zenith"]).max() < 4e-4  # rad
        # Clear-sky GHI: ~1300 W/m2 peak; float32 geometry error must stay
        # in the sub-W/m2 range.
        assert np.abs(dev["ghi_clear"] - host["ghi_clear"]).max() < 1.0
        assert np.abs(dev["cos_aoi"] - host["cos_aoi"]).max() < 4e-4


def _grid_config(grid, **kw):
    defaults = dict(
        start="2019-09-05 10:00:00",
        duration_s=300,
        seed=7,
        block_s=300,
        dtype="float32",
    )
    defaults.update(kw)
    return SimConfig(site_grid=grid, n_chains=len(grid), **defaults)


class TestSiteGridEngine:
    def test_end_to_end_block(self):
        grid = SiteGrid.regular((46.0, 50.0), (9.0, 13.0), 2, 2)
        sim = Simulation(_grid_config(grid))
        blocks = list(sim.run_blocks())
        assert len(blocks) == 1
        blk = blocks[0]
        assert blk.pv.shape == (4, 300)
        assert np.isfinite(blk.pv).all()
        assert (blk.pv >= 0).all()
        assert np.isfinite(blk.residual).all()
        # Mid-morning on a September day: at least one southern-tilted site
        # should actually produce power.
        assert blk.pv.max() > 0.0

    def test_sites_actually_differ(self):
        """Two sites far apart in longitude must see different sun and hence
        different pv for the *same* stochastic chain seed."""
        n = 2
        grid = SiteGrid(
            latitude=(48.12, 48.12),
            longitude=(-60.0, 40.0),  # ~6.7 h of hour angle apart
            altitude=(34.0, 34.0),
            surface_tilt=(48.12, 48.12),
            surface_azimuth=(180.0, 180.0),
        )
        cfg = _grid_config(grid)
        sim = Simulation(cfg)
        blk = next(sim.run_blocks())
        # 10:00 Berlin wall time: the lon=40E site is in daylight; the
        # lon=60W site is pre-dawn — pv must differ strongly.
        assert not np.allclose(blk.pv[0], blk.pv[1])

    def test_identical_grid_matches_shared_site(self):
        """A grid of n copies of the default site must reproduce the
        shared-site run: same seed -> identical csi streams; pv differs only
        by the geometry path (host float64 vs device float32 split time)."""
        n = 4
        grid = SiteGrid(
            latitude=(SITE.latitude,) * n,
            longitude=(SITE.longitude,) * n,
            altitude=(SITE.altitude,) * n,
            surface_tilt=(SITE.surface_tilt,) * n,
            surface_azimuth=(SITE.surface_azimuth,) * n,
            albedo=(SITE.albedo,) * n,
        )
        cfg_grid = _grid_config(grid)
        cfg_shared = dataclasses.replace(cfg_grid, site_grid=None, n_chains=n)
        blk_g = next(Simulation(cfg_grid).run_blocks())
        blk_s = next(Simulation(cfg_shared).run_blocks())
        np.testing.assert_array_equal(blk_g.meter, blk_s.meter)
        # Power curves agree to within the float32 geometry error budget:
        # sub-W absolute on a ~250 W plant.
        assert np.abs(blk_g.pv - blk_s.pv).max() < 1.0

    def test_sharded_site_grid(self):
        import jax

        from tmhpvsim_tpu.parallel import ShardedSimulation, make_mesh

        mesh = make_mesh(jax.devices()[:8])
        grid = SiteGrid.regular((46.0, 50.0), (9.0, 13.0), 2, 4)
        sim = ShardedSimulation(_grid_config(grid), mesh=mesh)
        blk = next(sim.run_blocks())
        assert blk.pv.shape == (8, 300)
        assert np.isfinite(blk.pv).all()
        assert blk.ensemble["pv_mean"].shape == (300,)

    def test_checkpoint_echo_catches_grid_change(self, tmp_path):
        grid = SiteGrid.regular((46.0, 50.0), (9.0, 13.0), 2, 2)
        cfg = _grid_config(grid)
        sim = Simulation(cfg)
        list(sim.run_blocks())
        path = str(tmp_path / "ck.npz")
        ckpt.save(path, sim.state, 1, cfg)
        other = SiteGrid.regular((40.0, 44.0), (9.0, 13.0), 2, 2)
        with pytest.raises(ValueError, match="different configuration"):
            ckpt.load(path, _grid_config(other))
        # unchanged grid resumes fine
        state, nb = ckpt.load(path, cfg)
        assert nb == 1


class TestSiteGridFromCsv:
    """SiteGrid.from_csv: arbitrary fleet lists (the --sites-csv path)."""

    def _write(self, tmp_path, text):
        p = tmp_path / "sites.csv"
        p.write_text(text)
        return str(p)

    def test_full_columns(self, tmp_path):
        path = self._write(tmp_path, (
            "latitude,longitude,altitude,surface_tilt,surface_azimuth,"
            "albedo,owner\n"
            "48.1,11.6,520,30,180,0.2,alice\n"
            "47.0,9.5,800,45,170,0.3,bob\n"
        ))
        g = SiteGrid.from_csv(path)
        assert len(g) == 2
        assert g.latitude == (48.1, 47.0)
        assert g.altitude == (520.0, 800.0)
        assert g.albedo == (0.2, 0.3)  # extra 'owner' column ignored

    def test_defaults_applied(self, tmp_path):
        path = self._write(tmp_path, (
            "latitude,longitude\n48.1,11.6\n47.0,9.5\n"
        ))
        g = SiteGrid.from_csv(path)
        assert g.altitude == (100.0, 100.0)
        assert g.surface_tilt == (48.1, 47.0)  # tilt-equals-latitude
        assert g.surface_azimuth == (180.0, 180.0)
        assert g.albedo == (0.25, 0.25)

    def test_missing_required_column(self, tmp_path):
        path = self._write(tmp_path, "latitude,altitude\n48.1,100\n")
        with pytest.raises(ValueError, match="longitude"):
            SiteGrid.from_csv(path)

    def test_bad_value_reports_line(self, tmp_path):
        path = self._write(tmp_path,
                           "latitude,longitude\n48.1,11.6\n48.2,oops\n")
        with pytest.raises(ValueError, match="line 3"):
            SiteGrid.from_csv(path)

    def test_empty_file_rejected(self, tmp_path):
        path = self._write(tmp_path, "latitude,longitude\n")
        with pytest.raises(ValueError, match="no data rows"):
            SiteGrid.from_csv(path)

    @pytest.mark.parametrize("header,row,match", [
        ("latitude,longitude", "95.0,11.6",
         r"line 3: latitude=95\.0 outside \[-90, 90\]"),
        ("latitude,longitude", "48.1,191.0",
         r"line 3: longitude=191\.0 outside"),
        ("latitude,longitude,albedo", "48.1,11.6,1.5",
         r"line 3: albedo=1\.5 outside \[0, 1\]"),
        ("latitude,longitude,surface_tilt", "48.1,11.6,120",
         r"line 3: surface_tilt=120\.0 outside"),
    ])
    def test_out_of_range_value_names_the_line(self, tmp_path, header,
                                               row, match):
        """Physically impossible values are refused with the offending
        CSV line number — an asset register with one typo'd row among
        thousands must point AT the row, not just fail."""
        path = self._write(tmp_path,
                           f"{header}\n48.1,11.6{',0.2' * (header.count(',') - 1)}\n{row}\n")
        with pytest.raises(ValueError, match=match):
            SiteGrid.from_csv(path)

    def test_non_finite_value_rejected(self, tmp_path):
        path = self._write(tmp_path,
                           "latitude,longitude\n48.1,11.6\nnan,11.6\n")
        with pytest.raises(ValueError, match="line 3"):
            SiteGrid.from_csv(path)

    def test_cli_sites_csv_end_to_end(self, tmp_path):
        from click.testing import CliRunner

        from tmhpvsim_tpu.cli import main as cli_main

        sites = self._write(tmp_path, (
            "latitude,longitude\n48.1,11.6\n47.0,9.5\n46.0,8.0\n45.0,7.0\n"
        ))
        out = tmp_path / "fleet.csv"
        r = CliRunner().invoke(cli_main, [
            "pvsim", str(out), "--backend=jax", "--no-realtime",
            "--duration", "120", "--seed", "5", "--sites-csv", sites,
            "--output", "reduce", "--start", "2019-09-05 10:00:00",
        ])
        assert r.exit_code == 0, r.output
        with open(out) as f:
            lines = f.read().splitlines()
        assert len(lines) == 1 + 4 + 1  # header + 4 sites + ensemble row

    def test_ragged_and_blank_cells_rejected_cleanly(self, tmp_path):
        # ragged row: longitude missing entirely
        path = self._write(tmp_path, "latitude,longitude\n48.1\n")
        with pytest.raises(ValueError, match="line 2.*required"):
            SiteGrid.from_csv(path)
        # blank required cell
        path = self._write(tmp_path, "latitude,longitude\n,11.6\n")
        with pytest.raises(ValueError, match="line 2.*required"):
            SiteGrid.from_csv(path)

    def test_line_numbers_skip_blank_lines(self, tmp_path):
        path = self._write(
            tmp_path,
            "latitude,longitude\n48.1,11.6\n\n47.0,9.5\n48.2,oops\n",
        )
        with pytest.raises(ValueError, match="line 5"):
            SiteGrid.from_csv(path)
