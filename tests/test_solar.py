"""Solar geometry & irradiance model tests.

Ground truth strategy (pvlib is deliberately not a dependency, SURVEY.md §7
step 6): astronomical invariants with known tolerances — solstice/equinox
declination via the noon zenith, equation-of-time bounds, hemispheric
symmetry — plus numpy-float64 vs jitted-float32 cross-checks of the exact
same formulas.
"""

import datetime as dt

import jax.numpy as jnp
import numpy as np
import pytest

from tmhpvsim_tpu.config import Site
from tmhpvsim_tpu.models import solar

DEG = np.pi / 180.0


def epoch(*args):
    return dt.datetime(*args, tzinfo=dt.timezone.utc).timestamp()


def noon_zenith_deg(date_args, lat, lon):
    """Minimum zenith over the UTC day, scanned at 30 s resolution."""
    t0 = epoch(*date_args)
    t = t0 + np.arange(0, 86400, 30.0)
    pos = solar.sun_position(t, lat, lon, xp=np)
    return np.degrees(pos["zenith"].min())


class TestSunPosition:
    def test_solstice_declination_june(self):
        # Munich local solar noon, 2025-06-21: zenith = lat - dec(23.44)
        z = noon_zenith_deg((2025, 6, 21), 48.12, 11.60)
        assert z == pytest.approx(48.12 - 23.44, abs=0.3)

    def test_solstice_declination_december(self):
        z = noon_zenith_deg((2025, 12, 21), 48.12, 11.60)
        assert z == pytest.approx(48.12 + 23.44, abs=0.3)

    def test_equinox_declination(self):
        # 2025-03-20 equinox: noon zenith ~= latitude
        z = noon_zenith_deg((2025, 3, 20), 48.12, 11.60)
        assert z == pytest.approx(48.12, abs=0.3)

    def test_equation_of_time_bounds(self):
        # At lon=0 the daily zenith minimum must occur within +-17 min of
        # 12:00 UTC, any day of the year (equation of time envelope).
        for month, day in [(2, 11), (5, 14), (7, 26), (11, 3)]:
            t0 = epoch(2025, month, day)
            t = t0 + np.arange(0, 86400, 30.0)
            pos = solar.sun_position(t, 20.0, 0.0, xp=np)
            t_noon = t[np.argmin(pos["zenith"])]
            offset_min = (t_noon - (t0 + 12 * 3600)) / 60.0
            assert abs(offset_min) < 17.5, (month, day, offset_min)

    def test_azimuth_convention(self):
        # Northern mid-latitudes: sun rises east (az ~90), noon south
        # (az ~180), sets west (az ~270) — pvlib's degrees-east-of-north.
        t0 = epoch(2025, 6, 21)
        t = t0 + np.arange(0, 86400, 30.0)
        pos = solar.sun_position(t, 48.12, 11.60, xp=np)
        az_deg = np.degrees(pos["azimuth"])
        i_noon = np.argmin(pos["zenith"])
        assert az_deg[i_noon] == pytest.approx(180.0, abs=1.0)
        day = pos["cos_zenith"] > 0
        rise, set_ = np.nonzero(day)[0][[0, -1]]
        assert 30 < az_deg[rise] < 90
        assert 270 < az_deg[set_] < 330

    def test_night_below_horizon(self):
        pos = solar.sun_position(epoch(2025, 6, 21, 0, 0), 48.12, 11.60, xp=np)
        assert pos["cos_zenith"] < 0

    def test_jax_x64_matches_numpy(self):
        t = epoch(2025, 8, 1) + np.arange(0, 86400, 997.0)
        ref = solar.sun_position(t, 48.12, 11.60, xp=np)
        got = solar.sun_position(
            jnp.asarray(t, dtype=jnp.float64), 48.12, 11.60, xp=jnp
        )
        np.testing.assert_allclose(got["zenith"], ref["zenith"], atol=1e-9)

    def test_float32_epoch_rejected(self):
        # float32 absolute epochs quantize to ±64-128 s — a silent ~1 deg
        # hour-angle error; sun_position must refuse them.
        t = np.asarray([epoch(2025, 8, 1)], dtype=np.float32)
        with pytest.raises(TypeError, match="float64"):
            solar.sun_position(t, 48.12, 11.60, xp=np)

    def test_refraction_lifts_horizon_sun(self):
        # ~0.5 deg of refraction at the horizon, ~0 overhead.
        z_true = np.array([90.0, 30.0]) * DEG
        e_app = solar.apparent_elevation(z_true, xp=np)
        lift_deg = np.degrees(e_app) - (90.0 - np.degrees(z_true))
        assert 0.4 < lift_deg[0] < 0.6
        assert lift_deg[1] < 0.05


class TestIrradiance:
    def geom(self, date_args=(2025, 6, 21), step=60.0):
        site = Site()
        t0 = epoch(*date_args)
        t = t0 + np.arange(0, 86400, step)
        doy = np.full(t.shape, dt.date(*date_args[:3]).timetuple().tm_yday,
                      dtype=np.float64)
        return solar.block_geometry(t, doy, site, xp=np)

    def test_clearsky_summer_magnitude(self):
        g = self.geom()
        assert 800 < g["ghi_clear"].max() < 1000  # Munich summer noon
        night = g["cos_zenith"] < -0.1
        assert np.all(g["ghi_clear"][night] == 0)

    def test_clearsky_winter_magnitude(self):
        g = self.geom((2025, 12, 21))
        assert 150 < g["ghi_clear"].max() < 450

    def test_airmass_range(self):
        g = self.geom()
        day = g["cos_zenith"] > 0.05
        am = g["airmass_abs"][day]
        assert np.all(am >= 0.99)
        assert am.min() == pytest.approx(
            1.0 / g["cos_zenith"].max() * solar.alt2pres(34.0)
            / solar.STD_PRESSURE,
            rel=0.01,
        )

    def test_disc_clear_sky_split(self):
        # Under clear sky (csi=1), DISC should attribute most horizontal
        # irradiance to beam at noon: DNI in (600, 1100) W/m^2.
        g = self.geom()
        dni = solar.disc_dni(g["ghi_clear"], g["zenith"], g["doy"], xp=np)
        i = np.argmax(g["ghi_clear"])
        assert 600 < dni[i] < 1100
        assert np.all(dni >= 0)
        assert np.all(dni[g["ghi_clear"] == 0] == 0)

    def test_disc_zero_for_low_kt(self):
        dni = solar.disc_dni(
            np.array([5.0]), np.array([30 * DEG]), np.array([172.0]), xp=np
        )
        assert dni[0] < 10.0

    def test_csi_cap_shape(self):
        # Overhead sun: cap close to 1.08; near horizon: large.
        cap = solar.csi_zenith_cap(np.array([0.0, 85 * DEG]), xp=np)
        assert cap[0] == pytest.approx(1.08, abs=0.02)
        assert cap[1] > 2.0

    def test_csi_cap_finite_in_float32_at_night(self):
        """Below the horizon the raw enhancement fit reaches ~1e39, which
        overflowed the device float32 cast (min(csi, cap) makes any large
        ceiling equivalent, so the cap is clamped).  Night zenith here is
        142 deg — the deepest the default site reaches."""
        cap = solar.csi_zenith_cap(np.array([2.48, np.pi]), xp=np)
        cap32 = cap.astype(np.float32)
        assert np.isfinite(cap32).all()
        assert (cap32 > 100.0).all()  # still far above any physical csi

    def test_linke_turbidity_interpolation(self):
        monthly = Site().linke_turbidity_monthly
        tl = solar.linke_turbidity(np.arange(1.0, 366.0), monthly, xp=np)
        assert tl.min() >= min(monthly) - 1e-9
        assert tl.max() <= max(monthly) + 1e-9
        # mid-January equals the January anchor
        assert tl[14] == pytest.approx(monthly[0], abs=1e-9)

    def test_haydavies_tilt_gain(self):
        # A south-tilted plane at 48N should beat the horizontal in POA
        # at winter noon (low sun).
        g = self.geom((2025, 12, 21))
        dni = solar.disc_dni(g["ghi_clear"], g["zenith"], g["doy"], xp=np)
        dhi = np.maximum(g["ghi_clear"] - dni * g["cos_zenith"], 0.0)
        poa = solar.haydavies_poa(
            48.12, g["cos_aoi"], g["apparent_zenith"], g["ghi_clear"],
            dni, dhi, g["dni_extra"], xp=np,
        )
        i = np.argmax(g["ghi_clear"])
        assert poa["poa_global"][i] > 1.5 * g["ghi_clear"][i]
        assert np.all(poa["poa_global"] >= 0)

    def test_extra_radiation_annual_cycle(self):
        ext = solar.extra_radiation_spencer(np.arange(1.0, 366.0), xp=np)
        # perihelion (early Jan) ~1412, aphelion (early Jul) ~1321
        assert np.argmax(ext) < 20 or np.argmax(ext) > 350
        assert 150 < np.argmin(ext) < 210
        assert ext.max() / ext.min() == pytest.approx(1.069, abs=0.01)
