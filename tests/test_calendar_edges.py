"""Calendar and latitude edge-case soaks (slow lane): multi-day
scan-fused reduce runs across every hazardous calendar transition the
windowed sampler arrays must survive — DST in both directions (the
local time grid repeats/skips an hour, stressing the hour-index window
bounds, engine/simulation.py host_inputs), the year boundary
(day-of-year wrap feeding the turbidity interpolation and Spencer
extraterrestrial radiation), a leap day — plus the solar-geometry
extremes (polar night, midnight sun, southern hemisphere, equator)
where the device-side per-site geometry's twilight guards do the most
work.  The October fall-back soak is the case that surfaced the
float32 csi-cap overflow (models/solar.py)."""

import warnings

import numpy as np
import pytest

from tmhpvsim_tpu.config import SimConfig
from tmhpvsim_tpu.engine import Simulation

CASES = {
    "fall-dst": ("2019-10-26 00:00:00", 3 * 86400),   # CEST->CET repeat
    "spring-dst": ("2019-03-30 00:00:00", 3 * 86400),  # CET->CEST skip
    "year-wrap": ("2019-12-30 00:00:00", 3 * 86400),   # doy 365 -> 1
    "leap-day": ("2020-02-28 00:00:00", 2 * 86400),    # Feb 29 exists
}


# slow lane via the conftest registry (_SLOW_LANE), not a decorator
@pytest.mark.parametrize("case", list(CASES), ids=list(CASES))
def test_calendar_edge_soak(case):
    start, dur = CASES[case]
    cfg = SimConfig(start=start, duration_s=dur, n_chains=4, seed=5,
                    block_s=8640, dtype="float32", block_impl="scan")
    # warnings filters (unlike np.errstate) are process-global, so an
    # overflow warning raised in the InputPrefetcher worker thread
    # becomes an exception there and surfaces through fut.result() —
    # this is exactly how the csi-cap overflow was caught
    with warnings.catch_warnings():
        warnings.filterwarnings("error", message=".*overflow.*")
        stats = Simulation(cfg).run_reduced()
    assert (stats["n_seconds"] == dur).all()
    for k, v in stats.items():
        assert np.isfinite(v).all(), k
    assert (stats["pv_max"] >= 0).all()
    assert (stats["pv_max"] <= 260.0).all()  # <= inverter-class ceiling


LAT_CASES = {
    # polar night: the sun never rises -> exactly zero output
    "polar-night-68N": ((67.5, 68.5), "2019-12-20 00:00:00", "zero"),
    # midnight sun: the sun never sets -> output through local midnight
    "midnight-sun-68N": ((67.5, 68.5), "2019-06-20 00:00:00", "power"),
    "southern-35S-summer": ((-35.5, -34.5), "2019-12-20 00:00:00", "power"),
    "equator-equinox": ((-0.5, 0.5), "2019-03-20 00:00:00", "power"),
}


@pytest.mark.parametrize("case", list(LAT_CASES), ids=list(LAT_CASES))
def test_latitude_extreme_soak(case):
    from tmhpvsim_tpu.config import SiteGrid

    (la0, la1), start, expect = LAT_CASES[case]
    grid = SiteGrid.regular((la0, la1), (10.0, 11.0), 2, 2)
    cfg = SimConfig(start=start, duration_s=86400, n_chains=4, seed=9,
                    block_s=8640, dtype="float32", block_impl="scan",
                    site_grid=grid)
    with warnings.catch_warnings():
        warnings.filterwarnings("error", message=".*overflow.*")
        stats = Simulation(cfg).run_reduced()
    for k, v in stats.items():
        assert np.isfinite(v).all(), k
    if expect == "zero":
        assert (stats["pv_max"] == 0.0).all()
    else:
        assert (stats["pv_max"] > 50.0).all()
        assert (stats["pv_max"] <= 260.0).all()
