"""Engine tests: end-to-end block loop, block-size invariance, CSV format,
reduce mode, checkpoint/resume."""

import csv
import dataclasses

import numpy as np
import pytest

from tmhpvsim_tpu.config import SimConfig
from tmhpvsim_tpu.engine import Simulation
from tmhpvsim_tpu.engine.simulation import write_csv


def small_config(**kw):
    base = dict(
        start="2019-09-05 10:00:00",
        duration_s=7200,
        n_chains=3,
        seed=7,
        block_s=3600,
        dtype="float32",
    )
    base.update(kw)
    return SimConfig(**base)


@pytest.fixture(scope="module")
def run():
    sim = Simulation(small_config())
    blocks = list(sim.run_blocks())
    return sim, blocks


class TestRunBlocks:
    def test_shapes_and_order(self, run):
        sim, blocks = run
        assert len(blocks) == 2
        assert [b.offset for b in blocks] == [0, 3600]
        for b in blocks:
            assert b.meter.shape == (3, 3600)
            assert b.pv.shape == (3, 3600)
            assert np.all(np.diff(b.epoch) == 1)

    def test_physical_invariants(self, run):
        _, blocks = run
        pv = np.concatenate([b.pv for b in blocks], axis=1)
        meter = np.concatenate([b.meter for b in blocks], axis=1)
        residual = np.concatenate([b.residual for b in blocks], axis=1)
        assert np.isfinite(pv).all()
        assert (pv >= 0).all() and pv.max() < 260
        assert (meter >= 0).all() and (meter < 9000).all()
        np.testing.assert_allclose(residual, meter - pv, atol=1e-4)
        # mid-morning September start: there must BE daylight generation
        assert pv.max() > 10

    def test_night_is_zero(self):
        sim = Simulation(small_config(start="2019-09-05 00:00:00",
                                      duration_s=3600))
        blk = next(sim.run_blocks())
        assert blk.pv.max() == 0

    def test_chains_distinct(self, run):
        _, blocks = run
        m = blocks[0].meter
        assert not np.allclose(m[0], m[1])
        p = np.concatenate([b.pv for b in blocks], axis=1)
        daylight = p.sum(axis=1)
        assert len(np.unique(daylight)) == 3

    def test_padding_trimmed(self):
        # duration not a multiple of block_s: last block shorter
        sim = Simulation(small_config(duration_s=5400))
        blocks = list(sim.run_blocks())
        assert [b.pv.shape[1] for b in blocks] == [3600, 1800]


def test_block_size_invariance():
    """The same seed must produce the identical trace under different block
    partitions — the property that makes block_s purely a perf knob and
    checkpointing exact (global-index keying; engine docstring)."""
    a = Simulation(small_config(block_s=3600))
    b = Simulation(small_config(block_s=1200))
    trace_a = np.concatenate([blk.pv for blk in a.run_blocks()], axis=1)
    trace_b = np.concatenate([blk.pv for blk in b.run_blocks()], axis=1)
    np.testing.assert_allclose(trace_a, trace_b, rtol=0, atol=1e-5)
    meter_a = np.concatenate([blk.meter for blk in a.run_blocks()], axis=1)
    meter_b = np.concatenate([blk.meter for blk in b.run_blocks()], axis=1)
    np.testing.assert_array_equal(meter_a, meter_b)


def test_resume_equals_straight_run():
    """Stop after block 0, serialise state, rebuild, resume: identical."""
    import jax

    cfg = small_config()
    straight = Simulation(cfg)
    blocks = list(straight.run_blocks())

    first = Simulation(cfg)
    it = first.run_blocks()
    b0 = next(it)
    # round-trip the carried pytree through host numpy (what a checkpoint
    # file stores); keys survive via jax.random.key_data
    leaves, treedef = jax.tree.flatten(
        first.state, is_leaf=lambda x: hasattr(x, "dtype")
    )
    host = [np.asarray(jax.random.key_data(l))
            if jax.dtypes.issubdtype(l.dtype, jax.dtypes.prng_key) else
            np.asarray(l) for l in leaves]
    restored = [
        jax.random.wrap_key_data(h) if h.dtype == np.uint32 else h
        for h in host
    ]
    state = jax.tree.unflatten(treedef, restored)

    second = Simulation(cfg)
    b1 = next(second.run_blocks(state=state, start_block=1))
    np.testing.assert_array_equal(b0.pv, blocks[0].pv)
    np.testing.assert_allclose(b1.pv, blocks[1].pv, atol=1e-5)


def test_state_is_duration_independent(run):
    """Windowed sampler arrays: the per-chain state must have the SAME
    leaf shapes for a 2-hour and a 90-day run — sampler values are
    regenerated per block from global-index-keyed draws, so nothing in
    the carried pytree scales with duration (the property that makes the
    10-year x 1M-chain BASELINE config memory-feasible)."""
    sim, _ = run
    s_short = sim.init_state()
    s_long = Simulation(small_config(duration_s=90 * 86400)).init_state()
    import jax

    short_shapes = jax.tree.map(lambda a: a.shape, s_short)
    long_shapes = jax.tree.map(lambda a: a.shape, s_long)
    assert short_shapes == long_shapes


def test_scan_impl_matches_wide(run):
    """SimConfig.block_impl='scan' (the TPU formulation: whole pipeline in
    one lax.scan, stats in the carry) must produce the same per-chain
    statistics as the wide formulation — same RNG streams by construction
    (scan_draws_tmajor/meter_block_tmajor), so only float reassociation
    may differ.  CPU resolves 'auto' to 'wide', so this forces both."""
    wide = Simulation(small_config(block_impl="wide")).run_reduced()
    scan = Simulation(small_config(block_impl="scan")).run_reduced()
    np.testing.assert_array_equal(scan["n_seconds"], wide["n_seconds"])
    for k in wide:
        np.testing.assert_allclose(scan[k], wide[k], rtol=2e-5, atol=1e-2,
                                   err_msg=k)


def test_scan_impl_matches_wide_site_grid():
    """Same check on the site-grid path, where the scan body evaluates
    per-site solar geometry on device per step."""
    from tmhpvsim_tpu.config import SiteGrid

    grid = SiteGrid.regular((46, 50), (9, 13), 2, 2)
    base = dict(start="2019-09-05 10:00:00", duration_s=5400, n_chains=4,
                seed=7, block_s=3600, dtype="float32", site_grid=grid)
    wide = Simulation(SimConfig(block_impl="wide", **base)).run_reduced()
    scan = Simulation(SimConfig(block_impl="scan", **base)).run_reduced()
    for k in wide:
        np.testing.assert_allclose(scan[k], wide[k], rtol=2e-5, atol=1e-2,
                                   err_msg=k)


def test_ensemble_scan_matches_wide(run):
    """Ensemble mode's scan-fused series formulation must yield the same
    fleet-mean stream as the wide formulation (same RNG streams; float
    reassociation only — the per-second sum order differs)."""
    wide = list(Simulation(small_config(block_impl="wide")).run_ensemble())
    scan = list(Simulation(small_config(block_impl="scan")).run_ensemble())
    assert len(wide) == len(scan)
    for w, s in zip(wide, scan):
        assert s.meter.shape == w.meter.shape
        np.testing.assert_array_equal(s.epoch, w.epoch)
        np.testing.assert_allclose(s.meter, w.meter, rtol=2e-5, atol=1e-2)
        np.testing.assert_allclose(s.pv, w.pv, rtol=2e-5, atol=1e-2)
        np.testing.assert_allclose(s.residual, w.residual, rtol=2e-5,
                                   atol=1e-2)


def test_scan2_impl_matches_scan(run):
    """block_impl='scan2' (nested: per-minute RNG tiles drawn inside the
    outer scan) must reproduce 'scan' — the draws are the same keyed
    slots, so only compiler reassociation may differ."""
    scan = Simulation(small_config(block_impl="scan")).run_reduced()
    scan2 = Simulation(small_config(block_impl="scan2")).run_reduced()
    np.testing.assert_array_equal(scan2["n_seconds"], scan["n_seconds"])
    for k in scan:
        np.testing.assert_allclose(scan2[k], scan[k], rtol=2e-6, atol=1e-3,
                                   err_msg=k)


@pytest.mark.parametrize("impl", ["wide", "scan", "scan2"])
def test_impl_smoke_fast_lane(impl):
    """FAST-LANE smoke of every block formulation at a tiny shape (the
    full-shape equivalence tests live in the slow lane): each impl must
    run reduce AND ensemble mode and agree with itself across modes on
    the per-second fleet totals.  Keeps a scan/scan2 compile in the
    default test run so a formulation regression cannot ship through a
    green fast lane."""
    cfg = small_config(n_chains=2, duration_s=240, block_s=120,
                       block_impl=impl)
    reduced = Simulation(cfg).run_reduced()
    blocks = list(Simulation(cfg).run_ensemble())
    assert (reduced["n_seconds"] == 240).all()
    ens_pv = sum(float(b.pv.sum()) for b in blocks) * cfg.n_chains
    np.testing.assert_allclose(ens_pv, float(reduced["pv_sum"].sum()),
                               rtol=1e-4, atol=1e-2)
    ens_meter = sum(float(b.meter.sum()) for b in blocks) * cfg.n_chains
    np.testing.assert_allclose(ens_meter,
                               float(reduced["meter_sum"].sum()),
                               rtol=1e-4, atol=1e-2)


class TestInputPrefetcher:
    """The host-input prefetcher (worker-thread double-buffering of
    host_inputs) must be semantically invisible: same pytrees as direct
    calls, in any access order, including the zero-blocks-left resume."""

    def test_matches_direct_calls(self):
        from tmhpvsim_tpu.engine.simulation import InputPrefetcher

        a, b = Simulation(small_config()), Simulation(small_config())
        pf = InputPrefetcher(a, 0, a.n_blocks)
        try:
            for bi in range(a.n_blocks):
                (pi, pe), (di, de) = pf.get(bi), b.host_inputs(bi)
                np.testing.assert_array_equal(pe, de)
                ptree, dtree = (dict(pi), dict(di))
                for leaves in ("block_idx", "win", "geom"):
                    for k in dtree[leaves]:
                        np.testing.assert_array_equal(
                            np.asarray(ptree[leaves][k]),
                            np.asarray(dtree[leaves][k]), err_msg=k,
                        )
        finally:
            pf.close()

    def test_out_of_order_access(self):
        from tmhpvsim_tpu.engine.simulation import InputPrefetcher

        a, b = Simulation(small_config()), Simulation(small_config())
        pf = InputPrefetcher(a, 0, a.n_blocks)
        try:
            # consume the LAST block first: the prefetched slot (block 0)
            # must be bypassed, not returned
            pi, _ = pf.get(a.n_blocks - 1)
            di, _ = b.host_inputs(a.n_blocks - 1)
            np.testing.assert_array_equal(
                np.asarray(pi["block_idx"]["t"]),
                np.asarray(di["block_idx"]["t"]),
            )
        finally:
            pf.close()

    def test_zero_blocks_left_resume(self):
        from tmhpvsim_tpu.engine.simulation import InputPrefetcher

        sim = Simulation(small_config())
        pf = InputPrefetcher(sim, sim.n_blocks, sim.n_blocks)
        pf.close()  # nothing was prefetched; nothing should raise


def test_ensemble_scan2_matches_scan(run):
    """Ensemble mode's nested (scan2) series step must reproduce the flat
    scan series — same keyed draw slots, so only compiler reassociation
    may differ (no coercion: scan2 has its own series jit)."""
    scan = list(Simulation(small_config(block_impl="scan")).run_ensemble())
    scan2 = list(Simulation(small_config(block_impl="scan2")).run_ensemble())
    assert len(scan) == len(scan2)
    for s, s2 in zip(scan, scan2):
        assert s2.meter.shape == s.meter.shape
        np.testing.assert_array_equal(s2.epoch, s.epoch)
        np.testing.assert_allclose(s2.meter, s.meter, rtol=2e-6, atol=1e-3)
        np.testing.assert_allclose(s2.pv, s.pv, rtol=2e-6, atol=1e-3)


def test_fused_stats_topology_matches_split(run):
    """SimConfig.stats_fusion='fused' (one producer+stats+merge jit, the
    TPU reduce-mode topology) must produce the same per-chain statistics
    as the default split topology — fusion is a scheduling decision, not a
    semantic one.  Float sums may differ by reassociation ULPs only."""
    split = Simulation(small_config(stats_fusion="split")).run_reduced()
    fused = Simulation(small_config(stats_fusion="fused")).run_reduced()
    np.testing.assert_array_equal(fused["n_seconds"], split["n_seconds"])
    for k in split:
        np.testing.assert_allclose(fused[k], split[k], rtol=1e-6, atol=1e-3)


def test_reduce_mode_consistent(run):
    sim, blocks = run
    stats = Simulation(small_config()).run_reduced()
    pv = np.concatenate([b.pv for b in blocks], axis=1)
    np.testing.assert_allclose(stats["pv_sum"], pv.sum(1), rtol=1e-5)
    np.testing.assert_allclose(stats["pv_max"], pv.max(1), rtol=1e-6)
    assert (stats["n_seconds"] == 7200).all()


def test_step_reduced_is_one_block_of_stats(run):
    """step_reduced (the public per-block stats API) must agree with the
    trace-mode block: stats of block 0 == reductions of block 0's arrays."""
    _, blocks = run
    sim = Simulation(small_config())
    state = sim.init_state()
    inputs, _ = sim.host_inputs(0)
    _, stats = sim.step_reduced(state, inputs)
    b0 = blocks[0]
    np.testing.assert_allclose(
        np.asarray(stats["pv_sum"]), b0.pv.sum(1), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(stats["residual_max"]), b0.residual.max(1), rtol=1e-5
    )
    assert (np.asarray(stats["n_seconds"]) == 3600).all()


def test_ensemble_mode_is_chain_mean(run):
    """run_ensemble must yield exactly the per-second mean over chains of
    the trace-mode blocks (same seed, same stream)."""
    _, blocks = run
    sim = Simulation(small_config())
    for eblk, tblk in zip(sim.run_ensemble(), blocks):
        assert eblk.meter.shape == (1, tblk.meter.shape[1])
        np.testing.assert_allclose(
            eblk.meter[0], tblk.meter.mean(axis=0), rtol=1e-5, atol=1e-3
        )
        np.testing.assert_allclose(
            eblk.pv[0], tblk.pv.mean(axis=0), rtol=1e-5, atol=1e-3
        )
        np.testing.assert_allclose(
            eblk.residual[0], eblk.meter[0] - eblk.pv[0], rtol=1e-6
        )


def test_rbg_prng_impl_end_to_end():
    """prng_impl='rbg' (TPU hardware bit generator) must run the whole
    chain and keep the physical invariants; streams differ from threefry
    by design, so this checks distribution-level sanity, not equality."""
    sim = Simulation(small_config(prng_impl="rbg", duration_s=3600))
    blk = next(sim.run_blocks())
    assert np.isfinite(blk.pv).all()
    assert (blk.pv >= 0).all() and blk.pv.max() < 260
    assert (blk.meter >= 0).all() and (blk.meter < 9000).all()
    assert blk.pv.max() > 10  # mid-morning: daylight generation exists
    # chains remain distinct under the alternate impl
    assert not np.allclose(blk.meter[0], blk.meter[1])


def test_csv_format(tmp_path, run):
    """Reference row format (pvsim.py:78-83): header then
    time,meter,pv,residual rows, residual == meter - pv."""
    path = tmp_path / "out.csv"
    sim = Simulation(small_config(duration_s=120, block_s=60))
    write_csv(str(path), sim.run_blocks())
    with open(path) as f:
        rows = list(csv.reader(f))
    assert rows[0] == ["time", "meter", "pv", "residual load"]
    assert len(rows) == 1 + 120
    t0, m, p, r = rows[1]
    # residual computed on device in float32; 0.01 W agreement suffices
    assert float(m) - float(p) == pytest.approx(float(r), abs=1e-2)
    assert t0.startswith("2019-09-0")


class TestChainSlabs:
    """SimConfig.n_chains_total / chain_offset: a partitioned run must be
    bit-identical to the unslabbed one (slab keys are the total-run
    split's slice, engine/simulation.py init_state)."""

    def test_slab_concat_bit_identical(self):
        full = Simulation(small_config(n_chains=6)).run_reduced()
        parts = [
            Simulation(small_config(n_chains=n, n_chains_total=6,
                                    chain_offset=off)).run_reduced()
            for off, n in ((0, 2), (2, 4))
        ]
        for name, arr in full.items():
            got = np.concatenate([p[name] for p in parts])
            np.testing.assert_array_equal(got, arr, err_msg=name)

    def test_degenerate_slab_is_noop(self):
        a = Simulation(small_config(n_chains=3)).run_reduced()
        b = Simulation(small_config(n_chains=3, n_chains_total=3,
                                    chain_offset=0)).run_reduced()
        for name in a:
            np.testing.assert_array_equal(a[name], b[name], err_msg=name)

    def test_bad_slab_rejected(self):
        with pytest.raises(ValueError, match="slab"):
            Simulation(small_config(n_chains=4, n_chains_total=5,
                                    chain_offset=2))
        with pytest.raises(ValueError, match="chain_offset"):
            Simulation(small_config(n_chains=2, chain_offset=1))
