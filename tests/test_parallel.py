"""Sharded execution tests on the 8-virtual-CPU-device mesh (conftest)."""

import re

import jax
import numpy as np
import pytest

from tmhpvsim_tpu.config import SimConfig
from tmhpvsim_tpu.engine import Simulation
from tmhpvsim_tpu.fleet import FleetParams
from tmhpvsim_tpu.obs.metrics import MetricsRegistry, use_registry
from tmhpvsim_tpu.parallel import (
    ShardedSimulation,
    chain_sharding,
    make_mesh,
    scenario_sharding,
)
from tmhpvsim_tpu.parallel.distributed import local_chain_slice


def cfg(**kw):
    base = dict(
        start="2019-09-05 10:00:00",
        duration_s=3600,
        n_chains=8,
        seed=11,
        block_s=1800,
        dtype="float32",
    )
    base.update(kw)
    return SimConfig(**base)


def test_mesh_spans_virtual_devices():
    mesh = make_mesh()
    assert mesh.devices.size == 8
    assert mesh.axis_names == ("chains",)


def test_state_is_sharded():
    sim = ShardedSimulation(cfg())
    state = sim.init_state()
    sh = state["carry"]["sec"].sharding
    assert sh.is_equivalent_to(chain_sharding(sim.mesh), ndim=1)


def test_sharded_matches_single_chip():
    """Sharding is a layout decision, not a semantic one: same keys, same
    global indices (SURVEY.md §2.3 DP row).  The integer RNG streams are
    bit-identical under any layout; the float32 physics chain is identical
    only to a few ULPs, because XLA's instruction selection (fusion / FMA
    contraction) depends on the per-shard batch shape — measured: 8 chains
    on a 4- or 8-device mesh differ from the single-device run by <= 4e-4
    absolute on ~250 W values (~1.5e-6 relative), deterministically.  See
    ShardedSimulation's docstring."""
    single = Simulation(cfg())
    sharded = ShardedSimulation(cfg())
    b_single = list(single.run_blocks())
    b_sharded = list(sharded.run_blocks())
    assert len(b_single) == len(b_sharded)
    for a, b in zip(b_single, b_sharded):
        np.testing.assert_array_equal(a.meter, b.meter)  # threefry: exact
        np.testing.assert_allclose(a.pv, b.pv, rtol=1e-5, atol=1e-4)
        np.testing.assert_allclose(a.residual, b.residual,
                                   rtol=1e-5, atol=2e-3)


def test_ensemble_psum_is_global_mean():
    sharded = ShardedSimulation(cfg())
    for blk in sharded.run_blocks():
        np.testing.assert_allclose(
            blk.ensemble["pv_mean"], blk.pv.mean(axis=0), rtol=1e-4,
            atol=1e-3,
        )
        np.testing.assert_allclose(
            blk.ensemble["residual_mean"], blk.residual.mean(axis=0),
            rtol=1e-4, atol=1e-2,
        )


def test_sharded_ensemble_mode_matches_single():
    """run_ensemble under shard_map (psum consumer) must agree with the
    single-device fleet mean to the usual ULP tolerance."""
    single = list(Simulation(cfg()).run_ensemble())
    sharded = list(ShardedSimulation(cfg()).run_ensemble())
    assert len(single) == len(sharded)
    for a, b in zip(single, sharded):
        np.testing.assert_allclose(a.meter, b.meter, rtol=1e-5, atol=1e-3)
        np.testing.assert_allclose(a.pv, b.pv, rtol=1e-5, atol=1e-3)


def test_uneven_chains_rejected():
    with pytest.raises(ValueError, match="divisible"):
        ShardedSimulation(cfg(n_chains=6))


def test_more_chains_than_devices():
    sharded = ShardedSimulation(cfg(n_chains=32, duration_s=1800))
    blk = next(sharded.run_blocks())
    assert blk.pv.shape == (32, 1800)
    assert np.isfinite(blk.pv).all()


def test_local_chain_slice_single_process():
    sim = ShardedSimulation(cfg())
    sl = local_chain_slice(8, sim.mesh)
    assert (sl.start, sl.stop) == (0, 8)  # single process owns everything


class TestShardedReduce:
    """Reduce mode under shard_map: the scalable-output path for the 100k+
    chain configs (BASELINE #4/#5) — per-chain traces never reach the host,
    the accumulator stays sharded, the ensemble is one psum tree."""

    def test_matches_single_chip(self):
        # tolerance: ULP-scale shape-dependent codegen differences in the
        # f32 physics (see test_sharded_matches_single_chip), summed over
        # block_s seconds in the *_sum statistics
        r_single = Simulation(cfg()).run_reduced()
        r_sharded = ShardedSimulation(cfg()).run_reduced()
        assert set(r_single) == set(r_sharded)
        np.testing.assert_array_equal(
            r_sharded["n_seconds"], r_single["n_seconds"]  # ints: exact
        )
        for k in r_single:
            np.testing.assert_allclose(
                r_sharded[k], r_single[k], rtol=1e-5, atol=1e-2,
            )

    def test_step_reduced_matches_base(self):
        """Sharded step_reduced (one-block fold into the identity init)
        must agree with the base class's per-block statistics."""
        base = Simulation(cfg())
        sharded = ShardedSimulation(cfg())
        b_state, s_state = base.init_state(), sharded.init_state()
        inputs, _ = base.host_inputs(0)
        _, b_stats = base.step_reduced(b_state, inputs)
        _, s_stats = sharded.step_reduced(s_state, inputs)
        assert set(np.asarray(s_stats["n_seconds"])) == {1800}
        for k in b_stats:
            np.testing.assert_allclose(
                np.asarray(s_stats[k], np.float64),
                np.asarray(b_stats[k], np.float64), rtol=1e-5, atol=1e-2,
            )

    @pytest.mark.parametrize("variant", [
        dict(stats_fusion="fused"),
        dict(block_impl="scan"),
        dict(block_impl="scan2"),
    ], ids=["fused", "scan", "scan2"])
    def test_alt_topologies_match_split(self, variant):
        """The fused and scan-fused reduce topologies under shard_map must
        match the default split/wide one — same statistics, still
        chain-sharded (SimConfig.stats_fusion / .block_impl)."""
        split = ShardedSimulation(cfg(stats_fusion="split"))
        alt = ShardedSimulation(cfg(**variant))
        r_split = split.run_reduced()
        r_alt = alt.run_reduced()
        sh = alt._last_acc["pv_sum"].sharding
        assert sh.is_equivalent_to(chain_sharding(alt.mesh), ndim=1)
        np.testing.assert_array_equal(
            r_alt["n_seconds"], r_split["n_seconds"]
        )
        for k in r_split:
            np.testing.assert_allclose(
                r_alt[k], r_split[k], rtol=2e-5, atol=1e-2
            )

    @pytest.mark.parametrize("impl", ["scan", "scan2"])
    def test_ensemble_scan_matches_wide_sharded(self, impl):
        """Sharded ensemble mode: the scan-fused series steps (local sums
        + one psum pair per block; flat and nested) must match the wide
        producer+psum path."""
        wide = list(ShardedSimulation(cfg(block_impl="wide")).run_ensemble())
        scan = list(ShardedSimulation(cfg(block_impl=impl)).run_ensemble())
        assert len(wide) == len(scan)
        for w, s in zip(wide, scan):
            np.testing.assert_allclose(s.meter, w.meter, rtol=2e-5,
                                       atol=1e-2)
            np.testing.assert_allclose(s.pv, w.pv, rtol=2e-5, atol=1e-2)

    def test_accumulator_stays_sharded(self):
        sim = ShardedSimulation(cfg())
        sim.run_reduced()
        sh = sim._last_acc["pv_sum"].sharding
        assert sh.is_equivalent_to(chain_sharding(sim.mesh), ndim=1)

    def test_ensemble_matches_numpy(self):
        sim = ShardedSimulation(cfg())
        per_chain = sim.run_reduced()
        ens = sim.ensemble_stats()
        assert ens["n_seconds"] == int(per_chain["n_seconds"].sum())
        np.testing.assert_allclose(ens["pv_sum"], per_chain["pv_sum"].sum(),
                                   rtol=1e-5)
        np.testing.assert_allclose(ens["pv_max"], per_chain["pv_max"].max(),
                                   rtol=1e-6)
        np.testing.assert_allclose(
            ens["residual_min"], per_chain["residual_min"].min(), rtol=1e-6,
        )

    def test_counts_only_valid_seconds(self):
        # duration not a multiple of block_s: padding must not be counted
        c = cfg(duration_s=2700, block_s=1800)
        r = ShardedSimulation(c).run_reduced()
        assert (r["n_seconds"] == 2700).all()

    def test_local_view_single_process(self):
        sim = ShardedSimulation(cfg())
        reduced = sim.run_reduced()
        sl, local = sim.local_reduced_view(reduced)
        assert (sl.start, sl.stop) == (0, 8)
        np.testing.assert_array_equal(local["pv_sum"], reduced["pv_sum"])


# ---------------------------------------------------------------------------
# the 2-D (chains, scenario) mesh
# ---------------------------------------------------------------------------


def _hfleet(n):
    """Uniform geometry (bitwise across shard layouts on CPU — see
    tests/test_fleet.py module note), heterogeneous in every other column
    so the cohort psum path has real work."""
    from tmhpvsim_tpu.config import Site

    s = Site()
    return FleetParams(
        latitude=(s.latitude,) * n, longitude=(s.longitude,) * n,
        altitude=(s.altitude,) * n, surface_tilt=(s.surface_tilt,) * n,
        surface_azimuth=(s.surface_azimuth,) * n, albedo=(s.albedo,) * n,
        dc_capacity_scale=tuple(0.5 + 0.2 * i for i in range(n)),
        ac_limit_w=(150.0,) * (n // 2) + (float("inf"),) * (n - n // 2),
        weather_regime=tuple(i % 3 for i in range(n)),
        demand_scale=tuple(1.0 + 0.1 * i for i in range(n)),
        demand_shift_w=tuple(10.0 * i for i in range(n)),
        cohort=tuple(i % 3 for i in range(n)),
    )


def _mesh_cfg(impl="scan", tel="off", fleet="off", **kw):
    base = dict(
        start="2019-09-05 10:00:00", duration_s=120, n_chains=8, seed=11,
        block_s=60, dtype="float32", block_impl=impl, telemetry=tel,
    )
    if fleet != "off":
        base.update(analytics=fleet, fleet=_hfleet(8))
    base.update(kw)
    return SimConfig(**base)


def _run_combo(c, mesh):
    with use_registry(MetricsRegistry()):
        sim = (Simulation(c) if mesh is None
               else ShardedSimulation(c, mesh=mesh))
        red = {k: np.asarray(v) for k, v in sim.run_reduced().items()}
        ens = (sim.ensemble_stats() if mesh is not None else None)
        sec = (sim.fleet_summary() if c.analytics != "off" else None)
    return red, ens, sec


class TestMesh2D:
    def test_mesh_shapes_and_specs(self):
        m = make_mesh(scenario_devices=2)
        assert m.devices.shape == (4, 2)
        assert m.axis_names == ("chains", "scenario")
        assert scenario_sharding(m).spec == jax.sharding.PartitionSpec(
            "scenario", "chains")
        # chain data shards over BOTH axes: 8 shards either way
        assert chain_sharding(m).spec == jax.sharding.PartitionSpec(
            ("chains", "scenario"))
        with pytest.raises(ValueError, match="divide"):
            make_mesh(scenario_devices=3)
        with pytest.raises(ValueError, match="scenario"):
            scenario_sharding(make_mesh())

    def test_state_sharded_over_both_axes(self):
        sim = ShardedSimulation(cfg(), mesh=make_mesh(scenario_devices=2))
        state = sim.init_state()
        sh = state["carry"]["sec"].sharding
        assert sh.is_equivalent_to(chain_sharding(sim.mesh), ndim=1)
        assert len(state["carry"]["sec"].sharding.device_set) == 8

    def test_n1_mesh_lowers_byte_identical_to_1d(self):
        """The degenerate (N, 1) mesh is the acceptance bar for 'the 2-D
        specs cost nothing': the reduce-path jit must produce the same
        compiled HLO as the historical 1-D mesh, byte for byte.  The
        lowered StableHLO is compared after stripping ``jax.result_info``
        (pure result-naming metadata — the only textual difference);
        the compiled module must match with only debug-location metadata
        normalised: the persistent compilation cache keys on the module
        with source locations stripped, so a warm ``compile()`` can
        return an executable whose ``source_file``/``source_line``
        stamps came from a byte-identical trace through a DIFFERENT
        call site (the plain and analytics reduce bodies in
        engine/simulation.py lower to identical ops), depending on
        which test populated the entry first."""
        c = _mesh_cfg(duration_s=60)
        sim1 = ShardedSimulation(c, mesh=make_mesh())
        sim2 = ShardedSimulation(c, mesh=make_mesh(scenario_devices=1))
        assert sim2.mesh.devices.shape == (8, 1)
        strip = re.compile(r'jax\.result_info = "[^"]*"')
        strip_loc = re.compile(r' source_file="[^"]*" source_line=\d+')
        for attr in ("_scan_acc_jit", "_sharded_ensemble"):
            low1 = getattr(sim1, attr)
            low2 = getattr(sim2, attr)
            if attr == "_scan_acc_jit":
                a1 = (sim1.init_state(), sim1.host_inputs(0)[0],
                      sim1.init_reduce_acc())
                a2 = (sim2.init_state(), sim2.host_inputs(0)[0],
                      sim2.init_reduce_acc())
            else:
                sim1.run_reduced(), sim2.run_reduced()
                a1, a2 = (sim1._last_acc,), (sim2._last_acc,)
            l1, l2 = low1.lower(*a1), low2.lower(*a2)
            assert (strip.sub("", l1.as_text())
                    == strip.sub("", l2.as_text())), attr
            assert (strip_loc.sub("", l1.compile().as_text())
                    == strip_loc.sub("", l2.compile().as_text())), attr

    def test_nm_mesh_matches_1d_and_single(self):
        """(4, 2) vs (8,) vs one device on the default path: the mesh
        SHAPE is invisible (bit-identical — same per-shard batch shape,
        psum over the axis tuple), the mesh SIZE only moves f32 results
        by the documented ULPs (ints exact)."""
        c = _mesh_cfg()
        red2d, ens2d, _ = _run_combo(c, make_mesh(scenario_devices=2))
        red1d, ens1d, _ = _run_combo(c, make_mesh())
        assert set(red2d) == set(red1d)
        for k in red1d:
            np.testing.assert_array_equal(red2d[k], red1d[k], err_msg=k)
        assert ens2d == ens1d
        red1, _, _ = _run_combo(c, None)
        np.testing.assert_array_equal(red2d["n_seconds"],
                                      red1["n_seconds"])
        for k in red1:
            np.testing.assert_allclose(red2d[k], red1[k],
                                       rtol=2e-5, atol=1e-2, err_msg=k)

    @pytest.mark.parametrize("impl,tel,fleet", [
        ("scan", "light", "off"),
        ("scan", "off", "risk"),
        ("scan", "light", "risk"),
        ("scan2", "off", "off"),
        ("scan2", "light", "risk"),
        ("wide", "off", "off"),
        ("wide", "light", "risk"),
    ], ids=lambda v: str(v))
    def test_mesh2d_matrix_bit_identical(self, impl, tel, fleet):
        """The full impl x telemetry x fleet matrix: every sharded code
        path (split/scan/scan2/wide producers, the telemetry fold, the
        cohort fleet psum) must give BIT-identical results on (4, 2) vs
        (8,) — the collectives ride the axis-name tuple, nothing else
        changes — and match the single device at the ULP contract."""
        c = _mesh_cfg(impl, tel, fleet)
        red2d, ens2d, sec2d = _run_combo(c, make_mesh(scenario_devices=2))
        red1d, ens1d, sec1d = _run_combo(c, make_mesh())
        assert set(red2d) == set(red1d)
        for k in red1d:
            np.testing.assert_array_equal(red2d[k], red1d[k], err_msg=k)
        assert ens2d == ens1d
        assert sec2d == sec1d
        red1, _, _ = _run_combo(c, None)
        np.testing.assert_array_equal(red2d["n_seconds"],
                                      red1["n_seconds"])
        for k in red1:
            np.testing.assert_allclose(red2d[k], red1[k],
                                       rtol=2e-5, atol=1e-2, err_msg=k)

    def test_scenario_mesh_via_config(self):
        """SimConfig.mesh_scenario builds the 2-D mesh without an explicit
        mesh argument, and the scenario dispatch advertises the batch
        alignment the serve layer pads to."""
        sim = ShardedSimulation(_mesh_cfg(mesh_scenario=2))
        assert sim.mesh.devices.shape == (4, 2)
        assert sim.scenario_batch_align() == 2
