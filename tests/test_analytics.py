"""On-device fleet analytics: in-graph risk statistics, not traces.

Covers the FleetAcc sketches (obs/analytics.py) at the fold level
against a NumPy oracle, the 1e6-sample quantile rank-error budget, the
exactness contract (bit-identical fleet sections under every merge
topology of one stream — ``blocks_per_dispatch`` mega-blocks, 8-device
sharding, slab partitioning — and counting-statistic agreement across
scan/scan2/wide), the ``--analytics off`` byte-identical-HLO guarantee,
the RunReport v5 ``fleet`` section (+ v1-v4 back-compat),
tools/fleet_report.py, and tools/bench_trend.py's ``--json`` /
overhead columns.
"""

import dataclasses
import json
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tmhpvsim_tpu.config import SimConfig
from tmhpvsim_tpu.engine import Simulation, autotune
from tmhpvsim_tpu.obs import analytics as flt
from tmhpvsim_tpu.obs.metrics import MetricsRegistry, use_registry
from tmhpvsim_tpu.obs.report import REPORT_SCHEMA_VERSION, validate_report
from tmhpvsim_tpu.parallel import ShardedSimulation

REPO = Path(__file__).resolve().parents[1]
FLEET_REPORT = REPO / "tools" / "fleet_report.py"
BENCH_TREND = REPO / "tools" / "bench_trend.py"


def small_cfg(**kw):
    base = dict(
        start="2019-09-05 10:00:00",
        duration_s=7200,
        n_chains=8,
        seed=7,
        block_s=3600,
        dtype="float32",
        block_impl="scan",
    )
    base.update(kw)
    return SimConfig(**base)


#: compact sketch geometry for the unit tests (bin width exactly 1 W)
P = flt.FleetParams(lo=-4.0, hi=4.0, bins=8, thresholds=(0.0, 1.0, 2.0),
                    capacity_w=1.5, lolp_k=2, ramp_windows=(1, 2, 4))


def _host(acc):
    return {k: np.asarray(v) for k, v in acc.items()}


# ---------------------------------------------------------------------------
# sketch geometry
# ---------------------------------------------------------------------------

class TestParams:
    @pytest.mark.parametrize("bad", [
        dict(hi=-4.0),                      # hi <= lo
        dict(bins=0),
        dict(lolp_k=0),
        dict(thresholds=()),
        dict(thresholds=(1.0, 1.0)),        # not strictly ascending
        dict(ramp_windows=(0, 60)),
        dict(ramp_windows=(60, 1)),
    ])
    def test_invalid_geometry_rejected(self, bad):
        kw = dict(lo=-4.0, hi=4.0, bins=8, thresholds=(0.0,),
                  capacity_w=1.0, lolp_k=2)
        kw.update(bad)
        with pytest.raises(ValueError, match="FleetParams"):
            flt.FleetParams(**kw)

    def test_params_from_config_defaults(self):
        cfg = small_cfg()
        p = flt.params_from_config(cfg)
        mx = float(cfg.meter_max_w)
        assert (p.lo, p.hi, p.bins) == (-mx, mx, 2048)
        assert p.thresholds == tuple(mx * f / 8.0 for f in range(1, 8))
        assert p.capacity_w == pytest.approx(0.8 * mx)
        assert p.lolp_k == 60
        assert p.ramp_windows == flt.RAMP_WINDOWS

    def test_params_from_config_overrides(self):
        cfg = small_cfg(analytics_bins=64, analytics_thresholds=(1.0, 2.0),
                        analytics_capacity_w=5.0, analytics_lolp_k=3)
        p = flt.params_from_config(cfg)
        assert p.bins == 64
        assert p.thresholds == (1.0, 2.0)
        assert p.capacity_w == 5.0
        assert p.lolp_k == 3


# ---------------------------------------------------------------------------
# accumulator unit tests
# ---------------------------------------------------------------------------

class TestFold:
    def test_off_level_is_not_an_accumulator(self):
        with pytest.raises(ValueError):
            flt.init_acc("off", jnp.float32, params=P)

    def _fold(self, acc, residual, t, valid=True):
        r = jnp.asarray(residual, jnp.float32)
        return flt.fold_second(
            acc, "risk", P, meter=r, pv=jnp.zeros_like(r), residual=r,
            covered=jnp.zeros_like(r), t=jnp.asarray(t),
            valid=jnp.asarray(valid))

    def test_known_values_one_second(self):
        acc = flt.init_acc("risk", jnp.float32, n_chains=2, params=P)
        acc = self._fold(acc, [0.5, 2.5], t=0)
        acc = flt.reduce_chainwise(acc)
        # the per-chain fold collapses to the scalar leaf format
        assert sorted(acc) == sorted(flt.init_acc("risk", jnp.float32,
                                                  params=P))
        host = _host(acc)
        # interior slots 1..bins over [-4, 4) at width 1: 0.5 -> slot 5,
        # 2.5 -> slot 7; no under/overflow
        hist = np.zeros(P.bins + 2, np.int64)
        hist[[5, 7]] = 1
        np.testing.assert_array_equal(host["res_hist"], hist)
        # exceed slot = #thresholds strictly below r: 0.5 -> 1, 2.5 -> 3
        np.testing.assert_array_equal(host["exceed"], [0, 1, 0, 1])
        s = flt.summarize(host, P)
        assert s["level"] == "risk" and s["count"] == 2
        assert s["residual"]["min"] == 0.5 and s["residual"]["max"] == 2.5
        assert [e["seconds"] for e in s["exceedance"]] == [2, 1, 1]
        assert [e["prob"] for e in s["exceedance"]] == [1.0, 0.5, 0.5]
        # one 2.5 > capacity second: run length 1 < lolp_k=2, no loss yet
        assert s["lolp"]["loss_seconds"] == 0 and s["lolp"]["events"] == 0
        # a single second has no ramp pair on any window
        assert all(v is None for v in s["ramp"].values())
        assert s["regimes"] is None

    def test_second_fold_records_ramps_and_loss(self):
        acc = flt.init_acc("risk", jnp.float32, n_chains=1, params=P)
        for t, r in enumerate([0.0, 3.0, 3.0, 3.0]):
            acc = self._fold(acc, [r], t=t)
        s = flt.summarize(_host(flt.reduce_chainwise(acc)), P)
        # w=1 pairs every second: max |Δ| = 3.0; w=2 samples t=1,3 (both
        # usable): |3-3| = 0; w=4 samples only t=3 -> no pair
        assert s["ramp"]["1s"] == 3.0
        assert s["ramp"]["2s"] == 0.0
        assert s["ramp"]["4s"] is None
        # residual > 1.5 at t=1..3: run hits lolp_k=2 at t=2 (1 event),
        # loss seconds at run>=2 are t=2 and t=3
        assert s["lolp"]["loss_seconds"] == 2 and s["lolp"]["events"] == 1

    def test_nan_residual_drops_the_second(self):
        acc = flt.init_acc("risk", jnp.float32, n_chains=2, params=P)
        acc = self._fold(acc, [np.nan, np.inf], t=0)
        s = flt.summarize(_host(flt.reduce_chainwise(acc)), P)
        assert s["count"] == 0
        assert s["residual"]["min"] is None
        assert s["residual"]["quantiles"] is None
        assert all(e["seconds"] == 0 for e in s["exceedance"])

    def test_invalid_seconds_contribute_nothing(self):
        acc = flt.init_acc("risk", jnp.float32, n_chains=2, params=P)
        acc = self._fold(acc, [3.0, 3.0], t=0, valid=False)
        s = flt.summarize(_host(flt.reduce_chainwise(acc)), P)
        assert s["count"] == 0 and s["lolp"]["loss_seconds"] == 0

    @pytest.mark.parametrize("level", ["risk", "full"])
    def test_leaf_kinds_cover_every_leaf(self, level):
        acc = flt.init_acc(level, jnp.float32, n_chains=3, params=P)
        kinds = flt.leaf_kinds(acc)
        assert set(kinds) == set(acc)
        assert set(kinds.values()) <= {"sum", "min", "max"}

    @pytest.mark.parametrize("level", ["risk", "full"])
    def test_reduce_chainwise_matches_scalar_leafset(self, level):
        acc = flt.init_acc(level, jnp.float32, n_chains=3, params=P)
        assert sorted(flt.reduce_chainwise(acc)) == \
            sorted(flt.init_acc(level, jnp.float32, params=P))

    def test_merge_host_widens_and_accumulates(self):
        def delta(vals):
            acc = flt.init_acc("risk", jnp.float32, n_chains=2, params=P)
            return _host(flt.reduce_chainwise(self._fold(acc, vals, t=0)))

        a, b = delta([0.5, 2.5]), delta([-1.0, 3.5])
        total = flt.merge_host(None, a)
        total = flt.merge_host(total, b)
        assert total["count"].dtype == np.int64 and total["count"] == 4
        assert total["res_hist"].dtype == np.int64
        np.testing.assert_array_equal(total["res_hist"],
                                      a["res_hist"] + b["res_hist"])
        # extrema keep the compute dtype (selection is exact anyway)
        assert total["min_res"].dtype == np.float32
        assert total["min_res"] == np.float32(-1.0)
        assert total["max_res"] == np.float32(3.5)


# ---------------------------------------------------------------------------
# fold-level oracle: scan fold == wide fold == NumPy, exactly
# ---------------------------------------------------------------------------

def _oracle(r, t0, duration, p):
    """Straightforward NumPy restatement of the per-second statistics,
    including the NaN-drops-the-second and duration-mask rules."""
    n, T = r.shape
    t = t0 + np.arange(T)
    use = (t < duration)[None, :] & np.isfinite(r)
    out = {"count": int(use.sum())}
    # histogram: same float32 clip+floor arithmetic as the device fold
    inv_w = np.float32(p.bins / (p.hi - p.lo))
    b = np.clip(np.where(use, (r - np.float32(p.lo)) * inv_w,
                         np.float32(0.0)),
                np.float32(-1.0), np.float32(p.bins))
    idx = np.floor(b).astype(np.int64) + 1
    out["res_hist"] = np.bincount(idx[use], minlength=p.bins + 2)
    exceed = np.zeros(len(p.thresholds) + 1, np.int64)
    for v in r[use]:
        exceed[sum(th < v for th in p.thresholds)] += 1
    out["exceed"] = exceed
    out["min_res"] = r[use].min()
    out["max_res"] = r[use].max()
    loss_s = events = 0
    for i in range(n):
        run = 0
        for j in range(T):
            run = run + 1 if (use[i, j] and r[i, j] > p.capacity_w) else 0
            loss_s += run >= p.lolp_k
            events += run == p.lolp_k
    out["lol_seconds"], out["lol_events"] = loss_s, events
    for w in p.ramp_windows:
        best = None
        for i in range(n):
            prev, seen = None, False
            for j in range(T):
                if (t[j] + 1) % w:
                    continue
                if use[i, j]:
                    if seen:
                        d = abs(np.float32(r[i, j]) - np.float32(prev))
                        best = d if best is None else max(best, d)
                    prev, seen = r[i, j], True
                else:
                    seen = False
        out[f"max_ramp_{w}s"] = best
    return out


class TestOracle:
    def test_scan_and_wide_folds_match_numpy_oracle(self):
        p = flt.FleetParams(lo=-6.0, hi=6.0, bins=16,
                            thresholds=(-1.0, 0.5, 2.0), capacity_w=1.0,
                            lolp_k=3, ramp_windows=(1, 4, 16))
        rng = np.random.default_rng(3)
        n, T, t0, duration = 4, 257, 0, 250
        r = rng.normal(0.0, 2.0, size=(n, T)).astype(np.float32)
        r[1, 50] = np.nan                     # drops one second
        r[2, 100:110] = 5.0                   # a loss run ...
        r[2, 105] = np.nan                    # ... split by a NaN
        r[3, 7] = np.inf                      # non-finite at a ramp grid
        r[0, 252] = 7.0                       # past duration: must not count
        ts = jnp.arange(t0, t0 + T)

        @jax.jit
        def scan_fold(r):
            def body(acc, x):
                t, col = x
                return flt.fold_second(
                    acc, "risk", p, meter=col, pv=jnp.zeros_like(col),
                    residual=col, covered=jnp.zeros_like(col), t=t,
                    valid=t < duration), None
            acc0 = flt.init_acc("risk", jnp.float32, n_chains=n, params=p)
            acc, _ = jax.lax.scan(body, acc0, (ts, jnp.asarray(r).T))
            return flt.reduce_chainwise(acc)

        @jax.jit
        def wide_fold(r):
            acc0 = flt.init_acc("risk", jnp.float32, params=p)
            return flt.fold_wide(acc0, "risk", p, meter=jnp.asarray(r),
                                 pv=jnp.zeros_like(jnp.asarray(r)), t=ts,
                                 duration_s=duration)

        a, w = _host(scan_fold(r)), _host(wide_fold(r))
        # the two vectorisations are bit-identical on every leaf
        assert sorted(a) == sorted(w)
        for k in a:
            np.testing.assert_array_equal(a[k], w[k], err_msg=k)
        # ... and exactly match the NumPy restatement
        o = _oracle(r, t0, duration, p)
        assert int(a["count"]) == o["count"]
        np.testing.assert_array_equal(a["res_hist"], o["res_hist"])
        np.testing.assert_array_equal(a["exceed"], o["exceed"])
        assert float(a["min_res"]) == o["min_res"]
        assert float(a["max_res"]) == o["max_res"]
        assert int(a["lol_seconds"]) == o["lol_seconds"]
        assert int(a["lol_events"]) == o["lol_events"]
        for w_ in p.ramp_windows:
            assert o[f"max_ramp_{w_}s"] is not None
            assert float(a[f"max_ramp_{w_}s"]) == o[f"max_ramp_{w_}s"]

    def test_quantile_rank_error_within_half_percent(self):
        """Acceptance: p5/p50/p95/p99 of a 1e6-sample fold within 0.5%
        rank error of the exact sort (the default 2048-bin geometry at
        a comparable support-to-spread ratio)."""
        p = flt.FleetParams(lo=-4000.0, hi=4000.0, bins=2048,
                            thresholds=(0.0,), capacity_w=1000.0,
                            lolp_k=60)
        rng = np.random.default_rng(0)
        n, T = 128, 8192                      # 1,048,576 samples
        r = rng.normal(500.0, 800.0, size=(n, T)).astype(np.float32)
        acc = flt.init_acc("risk", jnp.float32, params=p)
        acc = flt.fold_wide(acc, "risk", p, meter=jnp.asarray(r),
                            pv=jnp.zeros_like(jnp.asarray(r)),
                            t=jnp.arange(T), duration_s=T)
        s = flt.summarize(_host(acc), p)
        assert s["count"] == n * T
        flat = np.sort(r.ravel())
        for q in (0.05, 0.50, 0.95, 0.99):
            est = s["residual"]["quantiles"][f"p{int(q * 100)}"]
            rank = np.searchsorted(flat, est) / flat.size
            assert abs(rank - q) <= 0.005, (q, est, rank)


# ---------------------------------------------------------------------------
# reduce-mode integration: metrics, report, bit-identity, exact merges
# ---------------------------------------------------------------------------

def _fleet_of(cfg, plan=None, cls=Simulation):
    with use_registry(MetricsRegistry()):
        sim = cls(cfg, plan=plan)
        sim.run_reduced()
        return sim.fleet_summary()


#: monolithic single-device fleet sections, memoised because every
#: topology test (mega, sharded, slab, tel-combo) compares its own
#: partitioned/merged section against one of these
_REF = {}


def _mono_ref(analytics="risk", **kw):
    key = (analytics,) + tuple(sorted(kw.items()))
    if key not in _REF:
        _REF[key] = _fleet_of(small_cfg(analytics=analytics, **kw))
    return _REF[key]


def _assert_fleet_close(a, b):
    """Cross-impl comparison: the three block vectorisations share RNG
    streams but compiler reassociation shifts samples by ULPs
    (test_engine.py's cross-impl contract), so counting leaves compare
    exactly and extremum/quantile leaves to float tolerance."""
    assert a["level"] == b["level"]
    assert a["count"] == b["count"]
    assert a["exceedance"] == b["exceedance"]
    assert a["lolp"] == b["lolp"]
    assert a["sketch"] == b["sketch"]
    for k in ("min", "max"):
        assert a["residual"][k] == pytest.approx(b["residual"][k],
                                                 rel=1e-4), k
    qa, qb = a["residual"]["quantiles"], b["residual"]["quantiles"]
    assert (qa is None) == (qb is None)
    for k in qa or ():
        assert qa[k] == pytest.approx(qb[k], rel=1e-4, abs=1e-3), k
    assert set(a["ramp"]) == set(b["ramp"])
    for k, v in a["ramp"].items():
        if v is None:
            assert b["ramp"][k] is None, k
        else:
            assert v == pytest.approx(b["ramp"][k], rel=1e-4), k


#: one risk-level run report, shared (as deep copies) by the schema and
#: tool tests — none of them re-exercise the engine
_DOC = []


def _risk_doc():
    if not _DOC:
        with use_registry(MetricsRegistry()):
            sim = Simulation(small_cfg(analytics="risk"))
            sim.run_reduced()
            _DOC.append(sim.run_report())
    return json.loads(json.dumps(_DOC[0]))


class TestReduceRun:
    def test_risk_publishes_metrics_and_report(self):
        with use_registry(MetricsRegistry()):
            sim = Simulation(small_cfg(analytics="risk"))
            sim.run_reduced()
            snap = sim.metrics.snapshot()
            doc = sim.run_report()
        n_seconds = 2 * 8 * 3600
        assert snap["counters"]["device.fleet.blocks_total"] == 2
        assert snap["counters"]["device.fleet.samples_total"] == n_seconds
        assert "device.fleet.residual.p50" in snap["gauges"]
        validate_report(doc)
        assert doc["schema_version"] == REPORT_SCHEMA_VERSION
        f = doc["fleet"]
        assert f["level"] == "risk" and f["count"] == n_seconds
        q = f["residual"]["quantiles"]
        vals = [q[k] for k in ("p1", "p5", "p50", "p95", "p99")]
        assert vals == sorted(vals)
        secs = [e["seconds"] for e in f["exceedance"]]
        assert all(b <= a for a, b in zip(secs, secs[1:]))
        assert f["regimes"] is None

    @pytest.mark.parametrize("impl", ["scan", "scan2", "wide"])
    def test_results_bit_identical_off_vs_risk(self, impl):
        """Analytics reads the stream; it must not perturb it."""
        with use_registry(MetricsRegistry()):
            on = Simulation(small_cfg(
                analytics="risk", block_impl=impl)).run_reduced()
        off = Simulation(small_cfg(
            analytics="off", block_impl=impl)).run_reduced()
        assert sorted(on) == sorted(off)
        for k in off:
            np.testing.assert_array_equal(off[k], on[k])

    @pytest.mark.parametrize("impl", ["scan2", "wide"])
    def test_fleet_section_matches_across_impls(self, impl):
        """Every counting statistic (exceedance, LOLP, histogram mass)
        agrees exactly across the three block vectorisations; extrema
        and quantiles to cross-impl float tolerance."""
        s = _fleet_of(small_cfg(analytics="risk", block_impl=impl))
        _assert_fleet_close(s, _mono_ref())

    def test_mega_dispatch_fleet_exactly_equal(self):
        cfg = small_cfg(analytics="risk")
        plan = dataclasses.replace(autotune.static_plan(cfg),
                                   blocks_per_dispatch=2)
        assert _fleet_of(cfg, plan=plan) == _mono_ref()

    def test_telemetry_combo_fleet_exactly_equal(self):
        """Both passengers on one carry (telemetry AND analytics): the
        fused tel+fleet block step must not disturb either stream."""
        assert _fleet_of(small_cfg(analytics="risk",
                                   telemetry="light")) == _mono_ref()

    def test_full_level_regimes_on_scan(self):
        s = _mono_ref(analytics="full")
        assert s["level"] == "full"
        reg = s["regimes"]
        assert set(reg) == {"covered", "clear"}
        assert reg["covered"]["seconds"] + reg["clear"]["seconds"] == \
            s["count"]
        assert reg["covered"]["seconds"] > 0
        for row in reg.values():
            if row["seconds"]:
                assert row["meter_mean"] is not None
        # the risk core of a full section matches the risk run exactly
        ref = dict(_mono_ref())
        full_core = {k: v for k, v in s.items()
                     if k not in ("level", "regimes")}
        risk_core = {k: v for k, v in ref.items()
                     if k not in ("level", "regimes")}
        assert full_core == risk_core

    def test_full_level_regimes_unobserved_on_wide(self):
        """The wide impl never materialises the Markov cloud state, so
        ``full`` degrades to unobserved regimes, not a zero table."""
        s = _fleet_of(small_cfg(analytics="full", block_impl="wide"))
        assert s["level"] == "full" and s["regimes"] is None

    def test_plan_carries_resolved_level(self):
        assert Simulation(
            small_cfg(analytics="risk")).plan.analytics == "risk"
        assert Simulation(small_cfg()).plan.analytics == "off"

    def test_bad_level_rejected(self):
        with pytest.raises(ValueError, match="analytics"):
            Simulation(small_cfg(analytics="verbose"))


# ---------------------------------------------------------------------------
# HLO identity: --analytics off must COMPILE OUT, not just branch away
# ---------------------------------------------------------------------------

class TestHLOIdentity:
    @pytest.mark.parametrize("impl", ["scan", "scan2"])
    def test_off_lowers_byte_identical_to_absent(self, impl):
        """The analytics=off jit must lower to byte-identical HLO with a
        reconstruction of the pre-analytics composition (setup +
        ``_make_acc_body`` + lax.scan), proving the feature is
        structurally absent from the hot path, not gated inside it."""
        sim = Simulation(small_cfg(analytics="off", block_impl=impl,
                                   n_chains=4))
        state = sim.init_state()
        acc = sim.init_reduce_acc()
        inputs, _ = sim.host_inputs(0)

        def rebuilt(state, inputs, acc, _sim=sim, _impl=impl):
            if _impl == "scan":
                xs, step, cc_carry = _sim._scan_block_setup(state, inputs)
                (rcarry, acc), _ = jax.lax.scan(
                    _sim._make_acc_body(step), (state["carry"], acc), xs,
                    unroll=_sim._unroll)
                return dict(state, carry=rcarry, cc_carry=cc_carry), acc
            return _sim._block_step_scan2_acc(state, inputs, acc)

        bound = getattr(sim, f"_block_step_{impl}_acc")
        rebuilt.__name__ = bound.__func__.__name__
        rebuilt.__qualname__ = bound.__func__.__qualname__
        fresh = jax.jit(rebuilt, donate_argnums=(0, 2))
        jit_attr = (sim._scan_acc_jit if impl == "scan"
                    else sim._scan2_acc_jit)
        a = jit_attr.lower(state, inputs, acc).as_text()
        b = fresh.lower(state, inputs, acc).as_text()
        assert a == b

    def test_off_builds_no_analytics_jits(self):
        sim = Simulation(small_cfg())
        for attr in ("_scan_acc_fleet_jit", "_scan2_acc_fleet_jit",
                     "_scan_acc_tel_fleet_jit", "_wide_fleet_jit"):
            assert not hasattr(sim, attr)
        assert sim._fleet_params is None
        assert sim.fleet_summary() is None


# ---------------------------------------------------------------------------
# sharded aggregation (satellite: merge associativity across the mesh)
# ---------------------------------------------------------------------------

class TestSharded:
    def test_sharded_fleet_section_equals_single_device(self):
        """psum/pmin/pmax across 8 shards of the same chains must give
        the EXACT single-device section (all risk leaves are int counts
        or extrema, and summarize is deterministic host float64)."""
        assert _fleet_of(small_cfg(analytics="risk"),
                         cls=ShardedSimulation) == _mono_ref()

    def test_sharded_mega_with_telemetry_equals_single_device(self):
        cfg = small_cfg(analytics="risk", telemetry="light")
        plan = dataclasses.replace(autotune.static_plan(cfg),
                                   blocks_per_dispatch=2)
        assert _fleet_of(cfg, plan=plan,
                         cls=ShardedSimulation) == _mono_ref()

    def test_sharded_full_level_exact_ints_close_means(self):
        """At ``full`` the regime conditional-mean float sums reassociate
        across shards (ULP-level), so: int leaves exact, means approx."""
        s1 = _mono_ref(analytics="full")
        s8 = _fleet_of(small_cfg(analytics="full"), cls=ShardedSimulation)
        for k in ("count", "exceedance", "lolp", "sketch", "residual",
                  "ramp"):
            assert s8[k] == s1[k], k
        r1, r8 = s1["regimes"], s8["regimes"]
        for name in ("covered", "clear"):
            assert r8[name]["seconds"] == r1[name]["seconds"]
            for f in ("meter_mean", "pv_mean", "residual_mean"):
                if r1[name][f] is None:
                    assert r8[name][f] is None
                else:
                    assert r8[name][f] == pytest.approx(
                        r1[name][f], rel=1e-4)


# ---------------------------------------------------------------------------
# slab partitioning (satellite: slab-vs-monolithic bit-compare)
# ---------------------------------------------------------------------------

#: half-size shape for the slab matrix: each sim runs 3 slab builds, so
#: the 3-impl sweep stays affordable on the fast lane; two blocks keep
#: the cross-block fleet_total hoisting exercised
_SLAB_SHAPE = dict(duration_s=3600, block_s=1800)


class TestSlab:
    @pytest.mark.parametrize("impl", ["scan", "scan2", "wide"])
    def test_slab_fleet_section_equals_monolithic(self, impl):
        """Uneven slabs (3+3+2 chains) merge-fold into the monolithic
        section exactly, on every impl (host int64 merges of exact
        per-slab int32 deltas)."""
        cfg = small_cfg(analytics="risk", block_impl=impl, **_SLAB_SHAPE)
        plan = dataclasses.replace(autotune.static_plan(cfg),
                                   slab_chains=3)
        assert _fleet_of(cfg, plan=plan) == \
            _mono_ref(block_impl=impl, **_SLAB_SHAPE)

    def test_slab_mega_dispatch_equals_monolithic(self):
        cfg = small_cfg(analytics="risk", **_SLAB_SHAPE)
        plan = dataclasses.replace(autotune.static_plan(cfg),
                                   slab_chains=3, blocks_per_dispatch=2)
        assert _fleet_of(cfg, plan=plan) == \
            _mono_ref(block_impl="scan", **_SLAB_SHAPE)


# ---------------------------------------------------------------------------
# report schema: v5 with fleet, v1-v4 back-compat
# ---------------------------------------------------------------------------

#: report sections by the schema version that introduced them
_SECTION_SINCE = {"telemetry": 2, "streaming": 3, "executor": 4,
                  "fleet": 5, "serving": 6, "resilience": 7}


class TestReportSchema:
    def test_v5_round_trips_through_validator(self):
        doc = _risk_doc()
        assert doc["schema_version"] == REPORT_SCHEMA_VERSION == 16
        assert doc["fleet"]["level"] == "risk"
        validate_report(json.loads(json.dumps(doc)))

    @pytest.mark.parametrize("version", [1, 2, 3, 4, 5, 6])
    def test_older_documents_still_validate(self, version):
        doc = _risk_doc()
        doc["schema_version"] = version
        for section, since in _SECTION_SINCE.items():
            if since > version:
                doc.pop(section, None)
        validate_report(doc)

    def test_newer_versions_rejected(self):
        doc = _risk_doc()
        doc["schema_version"] = REPORT_SCHEMA_VERSION + 1
        with pytest.raises(ValueError, match="newer"):
            validate_report(doc)

    def test_off_run_has_no_fleet_section(self):
        with use_registry(MetricsRegistry()):
            sim = Simulation(small_cfg())
            sim.run_reduced()
            doc = sim.run_report()
        assert doc["fleet"] is None
        validate_report(doc)


# ---------------------------------------------------------------------------
# tools/fleet_report.py
# ---------------------------------------------------------------------------

def _run_tool(script, *argv):
    return subprocess.run(
        [sys.executable, str(script), *map(str, argv)],
        capture_output=True, text=True)


class TestFleetReportTool:
    def test_valid_report_prints_table(self, tmp_path):
        path = tmp_path / "report.json"
        path.write_text(json.dumps(_risk_doc()))
        r = _run_tool(FLEET_REPORT, path)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "fleet risk summary" in r.stdout
        assert "lolp" in r.stdout and "exceedance" in r.stdout

    def test_malformed_fleet_section_fails(self, tmp_path):
        doc = _risk_doc()
        doc["fleet"]["lolp"]["prob"] = 2.0       # impossible probability
        del doc["fleet"]["residual"]
        path = tmp_path / "bad.json"
        path.write_text(json.dumps(doc))
        r = _run_tool(FLEET_REPORT, path)
        assert r.returncode == 1
        assert "INVALID fleet section" in r.stderr

    def test_report_without_fleet_section_passes(self, tmp_path):
        doc = _risk_doc()
        doc["fleet"] = None
        path = tmp_path / "off.json"
        path.write_text(json.dumps(doc))
        r = _run_tool(FLEET_REPORT, path)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "no fleet section" in r.stdout

    def test_bench_doc_and_jsonl_shapes(self, tmp_path):
        rep = _risk_doc()
        bench = {"phase": "steady", "value": 1.0, "run_report": rep}
        path = tmp_path / "sweep.jsonl"
        path.write_text(json.dumps(bench) + "\n" + json.dumps(bench) + "\n")
        r = _run_tool(FLEET_REPORT, path)
        assert r.returncode == 0, r.stdout + r.stderr
        assert r.stdout.count("[steady]") == 2


# ---------------------------------------------------------------------------
# tools/bench_trend.py: --json mode + overhead columns
# ---------------------------------------------------------------------------

class TestBenchTrendJson:
    def _headline(self, steady, telemetry="off", analytics="off"):
        return {
            "value": 1e6, "platform": "cpu", "unit": "x",
            "run_report": {
                "timing": {"compile_s": 1.0, "steady_block_s": steady},
                "config": {"telemetry": telemetry, "analytics": analytics},
            },
        }

    def test_json_mode_rows_and_overhead(self, tmp_path):
        a = tmp_path / "a.json"
        b = tmp_path / "b.json"
        a.write_text(json.dumps(self._headline(0.100)))
        b.write_text(json.dumps(self._headline(0.104, analytics="risk")))
        r = _run_tool(BENCH_TREND, "--json", a, b)
        assert r.returncode == 0, r.stdout + r.stderr
        doc = json.loads(r.stdout)
        rows = doc["rows"]
        assert [row["analytics"] for row in rows] == ["off", "risk"]
        assert [row["telemetry"] for row in rows] == ["off", "off"]
        # the uninstrumented baseline row carries no overhead; the
        # instrumented row is priced against it
        assert rows[0]["overhead_pct"] is None
        assert rows[1]["overhead_pct"] == pytest.approx(4.0)
        assert doc["gate"]["ok"] is True

    def test_table_mode_shows_levels_and_overhead(self, tmp_path):
        a = tmp_path / "a.json"
        b = tmp_path / "b.json"
        a.write_text(json.dumps(self._headline(0.100)))
        b.write_text(json.dumps(self._headline(0.104, analytics="risk")))
        r = _run_tool(BENCH_TREND, a, b)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "analytics" in r.stdout and "ovh%" in r.stdout
        assert "+4.0" in r.stdout

    def test_checked_in_history_parses_as_json(self):
        files = sorted(REPO.glob("BENCH_r0*.json"))
        assert files, "checked-in bench history missing"
        r = _run_tool(BENCH_TREND, "--json", *files)
        assert r.returncode == 0, r.stdout + r.stderr
        doc = json.loads(r.stdout)
        assert len(doc["rows"]) == len(files)
        assert doc["gate"]["ok"] is True
        # pre-instrumentation rounds read as 'off', never null
        for row in doc["rows"]:
            if not row["failed"]:
                assert row["analytics"] == "off"


# ---------------------------------------------------------------------------
# overhead acceptance (slow lane, conftest _SLOW_LANE)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_analytics_overhead_65536_chains():
    """analytics=risk steady-block wall within 2% of off at the
    65536-chain CPU config, on the impl the autotuner resolves for CPU
    at this shape (wide): the fold is a handful of bulk reductions over
    the already-materialised block arrays.  The scan impls' per-chain
    elementwise fold is designed for the bandwidth-bound TPU body and is
    not what a CPU run resolves to, so it is not the acceptance arm
    (same reasoning as the telemetry overhead test).
    min-of-steady-blocks filters scheduler noise."""
    def steady_min(level: str) -> float:
        with use_registry(MetricsRegistry()):
            sim = Simulation(small_cfg(
                analytics=level, n_chains=65536, duration_s=4 * 60,
                block_s=60, block_impl="wide"))
            sim.run_reduced()
        return min(sim.timer.block_times)

    steady_min("risk")  # warm both arms' jit + persistent cache
    off = steady_min("off")
    risk = steady_min("risk")
    assert risk <= off * 1.02, (
        f"analytics overhead {risk / off - 1:.2%} exceeds 2% "
        f"(risk {risk:.4f} s vs off {off:.4f} s)"
    )
