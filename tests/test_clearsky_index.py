"""Composed clear-sky-index model: reference invariants, block invariance,
compat modes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tmhpvsim_tpu.config import ModelOptions
from tmhpvsim_tpu.models import clearsky_index as ci
from tmhpvsim_tpu.models.timegrid import TimeGridSpec


def _run_chain(spec, key, options, offsets_lengths, dtype=jnp.float64):
    """Drive one chain through consecutive blocks; returns concatenated csi."""
    feats = ci.HostFeatures.from_spec(spec)
    k_arr, k_min, k_renew, k_scan = jax.random.split(key, 4)
    arrays = ci.build_chain_arrays(k_arr, feats, options, dtype)
    carry = ci.init_renewal(k_renew, arrays, dtype)
    out = []
    for off, length in offsets_lengths:
        block_idx, (mlo, mhi) = ci.host_block_index(spec, off, length, dtype)
        mvals = ci.minute_noise_values(k_min, arrays["cc"], spec, mlo, mhi, dtype)
        carry, csi, covered = ci.csi_scan_block(
            k_scan, arrays, mvals, mlo, carry, block_idx, options, dtype
        )
        out.append((np.asarray(csi), np.asarray(covered)))
    return (np.concatenate([c for c, _ in out]),
            np.concatenate([v for _, v in out]))


@pytest.fixture(scope="module")
def spec():
    return TimeGridSpec.from_local_start("2019-09-05 12:00:00", 6 * 3600)


def test_csi_range_invariant(spec):
    """Reference invariant (tests/test_clearskyindexmodel.py:13): csi in (0,2).

    The reference test asserts it over 25 h; statistically csi = base*(noise)
    with base ~ N(0.99, 0.08) clipped by usage and noise near 1, so (0, 2)
    holds with overwhelming probability per draw.  We allow the same bound.
    """
    csi, covered = _run_chain(
        spec, jax.random.key(0), ModelOptions(), [(0, 6 * 3600)]
    )
    assert csi.shape == (6 * 3600,)
    assert (csi > 0).all() and (csi < 2).all(), (csi.min(), csi.max())
    assert set(np.unique(covered)) <= {0.0, 1.0}


def test_block_split_invariance(spec):
    """Simulating in one block vs many blocks gives identical traces —
    the property that makes streaming + checkpoint/resume exact."""
    key = jax.random.key(1)
    opts = ModelOptions()
    whole, cov_w = _run_chain(spec, key, opts, [(0, 6 * 3600)])
    parts, cov_p = _run_chain(
        spec, key, opts, [(0, 5000), (5000, 5000), (10000, 6 * 3600 - 10000)]
    )
    np.testing.assert_array_equal(cov_w, cov_p)
    np.testing.assert_allclose(whole, parts, rtol=1e-12)


def test_compat_modes_run(spec):
    for opts in (
        ModelOptions(persistent_cloud_chain=False),
        ModelOptions(swap_covered_branches=True),
        ModelOptions(advance_cloudy_hour=False),
    ):
        csi, _ = _run_chain(spec, jax.random.key(2), opts, [(0, 3600)])
        assert (csi > 0).all() and (csi < 2).all()


def test_vmap_chains(spec):
    """Batched chains via vmap produce distinct traces, all in range."""
    feats = ci.HostFeatures.from_spec(spec)
    opts = ModelOptions()
    dtype = jnp.float32
    keys = jax.random.split(jax.random.key(3), 4)

    block_idx, (mlo, mhi) = ci.host_block_index(spec, 0, 3600, dtype)

    def one(key):
        k_arr, k_min, k_renew, k_scan = jax.random.split(key, 4)
        arrays = ci.build_chain_arrays(k_arr, feats, opts, dtype)
        mvals = ci.minute_noise_values(k_min, arrays["cc"], spec, mlo, mhi, dtype)
        carry = ci.init_renewal(k_renew, arrays, dtype)
        _, csi, _ = ci.csi_scan_block(
            k_scan, arrays, mvals, mlo, carry, block_idx, opts, dtype
        )
        return csi

    csi = jax.jit(jax.vmap(one))(keys)
    assert csi.shape == (4, 3600)
    assert (np.asarray(csi) > 0).all() and (np.asarray(csi) < 2).all()
    assert len({tuple(np.asarray(c[:10]).tolist()) for c in csi}) == 4


def test_soak_25h_reference_invariant():
    """The reference's own soak (25 h at 1 Hz, crossing a midnight): csi
    stays in (0, 2) — reference tests/test_clearskyindexmodel.py:1-13."""
    spec = TimeGridSpec.from_local_start("2019-09-05 12:00:00", 25 * 3600)
    csi, _ = _run_chain(spec, jax.random.key(4), ModelOptions(),
                        [(0, 25 * 3600)], dtype=jnp.float32)
    assert (csi > 0).all() and (csi < 2).all(), (csi.min(), csi.max())
