"""Unit tests for bench.py's outage-resilience logic (pure host logic —
no JAX device work): the partial-results journal, the headline-document
builder the watchdog shares with the normal path, and the config
block_s step-down.  These paths only fire during tunnel failures, so
without tests they would only ever be exercised mid-outage."""

import json

import bench


def test_headline_doc_picks_best_rate():
    variants = {
        "scan-rbg": {"rate": 100.0, "compile_s": 1.0},
        "scan2-rbg": {"rate": 250.0, "compile_s": 2.0},
        "wide-rbg": {"error": "compile failed"},
    }
    doc = bench._headline_doc(variants, "tpu", n_chains=64)
    assert doc["headline_variant"] == "scan2-rbg"
    assert doc["value"] == 250.0
    assert doc["tpu"] is True
    assert doc["n_chains"] == 64
    assert doc["variants"]["wide-rbg"] == {"error": "compile failed"}
    assert doc["vs_baseline"] == round(250.0 / bench.REF_CEILING, 1)
    assert doc["north_star_frac"] == round(250.0 / bench.NORTH_STAR, 3)


def test_headline_doc_embeds_valid_run_report():
    """Every headline doc — including the watchdog's partial salvage,
    which runs on a monitor thread against a possibly-wedged backend —
    carries a schema-valid RunReport with device injected from what the
    sweep already measured (no fresh jax queries)."""
    from tmhpvsim_tpu.obs.report import validate_report

    variants = {
        "scan-threefry": {"rate": 500.0, "compile_s": 2.0,
                          "best_round_wall_s": 1.2,
                          "plan": {"block_impl": "scan", "scan_unroll": 8,
                                   "stats_fusion": "fused",
                                   "slab_chains": 64, "source": "static"}},
    }
    doc = bench._headline_doc(variants, "tpu", partial=True, n_chains=64,
                              device_kind="TPU v5e", timed_blocks=4)
    rep = validate_report(doc["run_report"])
    assert rep["app"] == "bench.headline"
    assert rep["device"] == {"platform": "tpu", "device_kind": "TPU v5e"}
    assert rep["headline"]["variant"] == "scan-threefry"
    assert rep["headline"]["site_seconds_per_s"] == 500.0
    assert rep["timing"]["compile_s"] == 2.0
    assert rep["timing"]["steady_block_s"] == 1.2 / 4
    assert rep["timing"]["rate_includes_compile"] is False
    assert rep["plan"]["block_impl"] == "scan"
    # the whole doc (legacy fields + report) must stay one JSON line
    json.dumps(doc)


def test_headline_doc_run_report_survives_sparse_variants():
    """Old journalled partials have no plan/best_round_wall_s; the
    report must degrade (timing None) rather than fail the salvage."""
    from tmhpvsim_tpu.obs.report import validate_report

    doc = bench._headline_doc({"scan-rbg": {"rate": 9.0}}, "cpu-fallback")
    rep = validate_report(doc["run_report"])
    assert rep["timing"] is None
    assert rep["device"]["platform"] == "cpu-fallback"
    assert rep["device"]["device_kind"] is None


def test_persist_partial_appends_json_lines(tmp_path, monkeypatch):
    p = tmp_path / "journal.jsonl"
    monkeypatch.setattr(bench, "PARTIAL_PATH", str(p))
    bench._persist_partial({"phase": "headline-variant", "rate": 1.0})
    bench._persist_partial({"phase": "config", "value": 2.0})
    lines = [json.loads(ln) for ln in p.read_text().splitlines()]
    assert [ln["phase"] for ln in lines] == ["headline-variant", "config"]
    assert all("ts" in ln for ln in lines)  # landing time recorded


def test_config_stepdown_retries_smaller_blocks(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "PARTIAL_PATH", str(tmp_path / "j.jsonl"))
    attempts = []

    def fake_run(label, cfg, sharded, note, scaled_from=None):
        attempts.append((cfg, note))
        if cfg < 4320:  # "cfg" is the block_s passed through make_cfg_bs
            return
        raise RuntimeError(f"remote compile failed at block_s={cfg}")

    monkeypatch.setattr(bench, "_reduce_config_run", fake_run)
    bench._reduce_config_run_resilient(
        "t", lambda bs: bs, sharded=False, note="base note"
    )
    assert [a[0] for a in attempts] == [8640, 4320, 1080]
    assert "stepped down to 1080" in attempts[-1][1]
    assert "remote compile failed" in attempts[-1][1]


def test_last_tpu_evidence_prefers_fresher_journal(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "PARTIAL_PATH", str(tmp_path / "j.jsonl"))
    (tmp_path / "HEADLINE_r05.json").write_text(
        "# warm-up comment line\n"
        + json.dumps({"platform": "tpu", "value": 9e9}) + "\n"
    )
    bench._persist_partial({"phase": "headline", "platform": "tpu",
                            "value": 1e9})
    ev = bench._last_tpu_evidence()
    # the journal records every in-process headline (battery included),
    # so it is always at least as fresh as the committed artifact
    assert ev["value"] == 1e9


def test_last_tpu_evidence_artifact_fallback_fresh_clone(tmp_path,
                                                         monkeypatch):
    monkeypatch.setattr(bench, "PARTIAL_PATH", str(tmp_path / "j.jsonl"))
    (tmp_path / "HEADLINE_r05.json").write_text(
        json.dumps({"platform": "tpu", "value": 9e9}) + "\n"
    )
    ev = bench._last_tpu_evidence()  # no journal: committed artifact
    assert ev["value"] == 9e9


def test_last_tpu_evidence_journal_fallback(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "PARTIAL_PATH", str(tmp_path / "j.jsonl"))
    bench._persist_partial({"phase": "headline", "platform": "cpu-fallback",
                            "value": 1.0})
    bench._persist_partial({"phase": "headline", "platform": "tpu",
                            "value": 2e9})
    bench._persist_partial({"phase": "config", "platform": "tpu",
                            "value": 3.0})  # not a headline: skipped
    ev = bench._last_tpu_evidence()
    assert ev["value"] == 2e9


def test_last_tpu_evidence_none_when_no_tpu_ever(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "PARTIAL_PATH", str(tmp_path / "j.jsonl"))
    bench._persist_partial({"phase": "headline", "platform": "cpu-fallback"})
    assert bench._last_tpu_evidence() is None


def test_config_stepdown_exhaustion_emits_error_doc(tmp_path, monkeypatch,
                                                    capsys):
    monkeypatch.setattr(bench, "PARTIAL_PATH", str(tmp_path / "j.jsonl"))

    def always_fail(label, cfg, sharded, note, scaled_from=None):
        raise RuntimeError("tunnel dead")

    monkeypatch.setattr(bench, "_reduce_config_run", always_fail)
    bench._reduce_config_run_resilient(
        "t", lambda bs: bs, sharded=False, note="n"
    )
    doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert doc["config"] == "t"
    assert doc["error"] == "tunnel dead"
    assert doc["block_s_tried"] == [8640, 4320, 1080]


def test_repro_aborts_after_two_consecutive_non_tpu(tmp_path, monkeypatch,
                                                    capsys):
    """A down tunnel must not burn all K trials on 4.5-min probe
    timeouts: two successive non-TPU trials end the loop, and the abort
    doc reports how many trials actually ran."""
    monkeypatch.setattr(bench, "PARTIAL_PATH", str(tmp_path / "j.jsonl"))
    calls = []

    class FakeCompleted:
        stdout = json.dumps({"variant": "scan-threefry",
                             "platform": "cpu-fallback", "rate": 3e6})
        stderr = ""

    def fake_run(*a, **kw):
        calls.append(1)
        return FakeCompleted()

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    bench.repro(6)
    assert len(calls) == 2
    lines = [json.loads(ln)
             for ln in capsys.readouterr().out.strip().splitlines()]
    abort = [d for d in lines if d.get("phase") == "repro-abort"]
    assert abort and abort[0]["completed"] == 2
    assert abort[0]["requested"] == 6
    # no TPU trial landed -> no summary doc
    assert not any(d.get("phase") == "repro-summary" for d in lines)


def test_repro_counter_resets_on_tpu_trial(tmp_path, monkeypatch, capsys):
    """cpu, tpu, cpu, cpu -> abort after trial 4, summary over the one
    TPU rate with the true trial count."""
    monkeypatch.setattr(bench, "PARTIAL_PATH", str(tmp_path / "j.jsonl"))
    seq = iter(["cpu-fallback", "tpu", "cpu-fallback", "cpu-fallback",
                "tpu", "tpu"])

    def fake_run(*a, **kw):
        class C:
            stdout = json.dumps({"variant": "scan-threefry",
                                 "platform": next(seq), "rate": 2.06e10})
            stderr = ""
        return C()

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    bench.repro(6)
    lines = [json.loads(ln)
             for ln in capsys.readouterr().out.strip().splitlines()]
    abort = [d for d in lines if d.get("phase") == "repro-abort"]
    assert abort and abort[0]["completed"] == 4
    summary = [d for d in lines if d.get("phase") == "repro-summary"]
    assert summary and summary[0]["landed"] == 1
    assert summary[0]["trials"] == 4 and summary[0]["requested"] == 6


def test_slab_cfgs_cover_total_exactly():
    cfgs = bench._slab_cfgs(1_000_000, 4, 1080)
    assert len(cfgs) == 16
    assert sum(c.n_chains for c in cfgs) == 1_000_000
    assert all(c.n_chains <= bench.SLAB_CHAINS for c in cfgs)
    assert [c.chain_offset for c in cfgs] == [
        i * bench.SLAB_CHAINS for i in range(16)]
    assert all(c.n_chains_total == 1_000_000 for c in cfgs)
    # contiguous, non-overlapping cover
    end = 0
    for c in cfgs:
        assert c.chain_offset == end
        end += c.n_chains
    assert end == 1_000_000
