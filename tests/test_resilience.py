"""Unified resilience policy (runtime/resilience.py): backoff shapes,
retry/fallback/budget semantics of ResiliencePolicy.call, the half-open
CircuitBreaker lifecycle with its ``resilience.*`` metrics, WARN
rate-limiting, the reconnect_policy defaults every transport loop uses,
and the removal of the old runtime/retry.py shim.
"""

import asyncio
import importlib
import logging
import random
import sys

import pytest

from tmhpvsim_tpu.obs.metrics import MetricsRegistry, use_registry
from tmhpvsim_tpu.runtime.resilience import (
    BreakerOpenError,
    CircuitBreaker,
    ResiliencePolicy,
    WarnRateLimiter,
    forever,
    propagate,
    reconnect_policy,
)

LOGGER = "tmhpvsim_tpu.runtime.resilience"


def _run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


class _Clock:
    """Settable stand-in for time.monotonic."""

    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


class _Flaky:
    """Async callable failing ``fails`` times before returning ``value``."""

    def __init__(self, fails, value="ok", exc=OSError("nope")):
        self.fails = fails
        self.value = value
        self.exc = exc
        self.calls = 0

    async def __call__(self):
        self.calls += 1
        if self.calls <= self.fails:
            raise self.exc
        return self.value


# ---------------------------------------------------------------------------
# WarnRateLimiter
# ---------------------------------------------------------------------------


class TestWarnRateLimiter:
    def test_rate_limit_and_suppressed_suffix(self, caplog):
        lim = WarnRateLimiter(every_s=10.0)
        log = logging.getLogger(LOGGER)
        with caplog.at_level(logging.WARNING, logger=LOGGER):
            assert lim.warn(log, "boom %d", 1, now=0.0)
            assert not lim.warn(log, "boom %d", 2, now=3.0)
            assert not lim.warn(log, "boom %d", 3, now=6.0)
            assert lim.suppressed == 2
            assert lim.warn(log, "boom %d", 4, now=11.0)
            assert lim.suppressed == 0
        msgs = [r.getMessage() for r in caplog.records]
        assert msgs == [
            "boom 1",
            "boom 4 (2 similar warnings suppressed in the last 10 s)",
        ]


# ---------------------------------------------------------------------------
# backoff shapes
# ---------------------------------------------------------------------------


class TestBackoff:
    def test_exponential_without_jitter(self):
        p = ResiliencePolicy(base_delay_s=0.5, max_delay_s=4.0,
                             multiplier=2.0, jitter=False)
        delays, prev = [], p.base_delay_s
        for n in range(1, 6):
            prev = p.backoff(n, prev)
            delays.append(prev)
        assert delays == [0.5, 1.0, 2.0, 4.0, 4.0]

    def test_zero_base_means_no_sleep(self):
        p = ResiliencePolicy(base_delay_s=0.0)
        assert p.backoff(1, 0.0) == 0.0
        assert p.backoff(9, 123.0) == 0.0

    def test_decorrelated_jitter_is_bounded_and_seeded(self):
        def delays(seed):
            p = ResiliencePolicy(base_delay_s=0.5, max_delay_s=5.0,
                                 rng=random.Random(seed))
            out, prev = [], p.base_delay_s
            for n in range(1, 20):
                prev = p.backoff(n, prev)
                out.append(prev)
            return out

        a, b = delays(1), delays(1)
        assert a == b
        assert all(0.5 <= d <= 5.0 for d in a)


# ---------------------------------------------------------------------------
# ResiliencePolicy.call
# ---------------------------------------------------------------------------


class TestPolicyCall:
    def test_retries_then_succeeds_with_counters(self):
        reg = MetricsRegistry()
        fn = _Flaky(fails=2)
        p = ResiliencePolicy(attempts=4, registry=reg, name="unit.flaky")
        assert _run(p.call(fn)) == "ok"
        assert fn.calls == 3
        c = reg.snapshot()["counters"]
        assert c["retry.attempts.unit.flaky"] == 2.0
        assert c["resilience.retries_total"] == 2.0
        assert "retry.exhausted.unit.flaky" not in c

    def test_exhaustion_reraises_and_warns(self, caplog):
        reg = MetricsRegistry()
        p = ResiliencePolicy(attempts=3, registry=reg, name="unit.dead")
        with caplog.at_level(logging.WARNING, logger=LOGGER):
            with pytest.raises(OSError, match="nope"):
                _run(p.call(_Flaky(fails=99)))
        c = reg.snapshot()["counters"]
        assert c["retry.exhausted.unit.dead"] == 1.0
        assert c["resilience.giveups_total"] == 1.0
        warn = caplog.records[-1].getMessage()
        assert "unit.dead exhausted 3 attempt(s)" in warn
        assert "re-raising" in warn

    def test_fallback_value_callable_and_awaitable(self):
        reg = MetricsRegistry()
        p = ResiliencePolicy(attempts=1, registry=reg)
        assert _run(p.call(_Flaky(fails=9), fallback=None)) is None
        assert _run(p.call(_Flaky(fails=9), fallback=-1.0)) == -1.0
        assert _run(p.call(_Flaky(fails=9),
                           fallback=lambda exc: str(exc))) == "nope"

        async def afb(exc):
            return ("async", str(exc))

        assert _run(p.call(_Flaky(fails=9), fallback=afb)) == \
            ("async", "nope")

    def test_cancelled_error_is_always_fatal(self):
        reg = MetricsRegistry()
        p = ResiliencePolicy(attempts=5, registry=reg, fallback=None)

        async def cancelled():
            raise asyncio.CancelledError

        with pytest.raises(asyncio.CancelledError):
            _run(p.call(cancelled))
        assert reg.snapshot()["counters"] == {}

    def test_zero_total_budget_gives_up_on_first_failure(self, caplog):
        reg = MetricsRegistry()
        p = ResiliencePolicy(attempts=10, total_timeout_s=0.0,
                             registry=reg, name="unit.budget",
                             fallback="shed")
        with caplog.at_level(logging.WARNING, logger=LOGGER):
            assert _run(p.call(_Flaky(fails=9))) == "shed"
        warn = caplog.records[-1].getMessage()
        assert "unit.budget exceeded its 0.0 s retry budget" in warn
        assert "applying fallback" in warn
        assert reg.snapshot()["counters"]["resilience.giveups_total"] == 1.0

    def test_attempt_timeout_bounds_each_try(self):
        reg = MetricsRegistry()
        p = ResiliencePolicy(attempts=2, attempt_timeout_s=0.02,
                             registry=reg, name="unit.hang")

        async def hang():
            await asyncio.sleep(30)

        with pytest.raises(asyncio.TimeoutError):
            _run(p.call(hang))
        assert reg.snapshot()["counters"]["retry.attempts.unit.hang"] == 2.0

    def test_breaker_open_rejects_without_consuming_attempts(self):
        reg = MetricsRegistry()
        br = CircuitBreaker("unit", failure_threshold=1, registry=reg,
                            now=_Clock())
        p = ResiliencePolicy(attempts=5, breaker=br, registry=reg,
                             name="unit.br")
        with pytest.raises(BreakerOpenError, match="'unit' is open"):
            _run(p.call(_Flaky(fails=9)))
        c = reg.snapshot()["counters"]
        assert c["retry.attempts.unit.br"] == 1.0
        assert c["resilience.breaker_open_total.unit"] == 1.0
        assert c["resilience.breaker_rejected_total.unit"] == 1.0

    def test_retrying_decorator_uses_qualname(self):
        reg = MetricsRegistry()
        p = ResiliencePolicy(attempts=3, registry=reg)
        flaky = _Flaky(fails=1)

        @p.retrying
        async def fetch_thing():
            return await flaky()

        assert _run(fetch_thing()) == "ok"
        keys = reg.snapshot()["counters"]
        assert any(k.startswith("retry.attempts.") and "fetch_thing" in k
                   for k in keys)

    def test_forever_policy_warns_rate_limited(self, caplog):
        reg = MetricsRegistry()
        p = ResiliencePolicy(attempts=forever, registry=reg,
                             name="loop", warn_every_s=3600.0)
        with caplog.at_level(logging.WARNING, logger=LOGGER):
            assert _run(p.call(_Flaky(fails=3))) == "ok"
        warns = [r for r in caplog.records if "loop failed" in r.getMessage()]
        assert len(warns) == 1
        assert "OSError: nope" in warns[0].getMessage()
        assert reg.snapshot()["counters"]["retry.attempts.loop"] == 3.0


# ---------------------------------------------------------------------------
# CircuitBreaker lifecycle
# ---------------------------------------------------------------------------


class TestCircuitBreaker:
    def test_open_half_open_probe_close(self):
        reg = MetricsRegistry()
        clk = _Clock()
        br = CircuitBreaker("b", failure_threshold=2, reset_s=30.0,
                            registry=reg, now=clk)
        assert br.state == "closed" and br.allow()
        br.record_failure()
        assert br.state == "closed"
        br.record_failure()
        assert br.state == "open"
        assert not br.allow()          # rejected while open
        clk.t = 29.0
        assert br.state == "open"
        clk.t = 30.0
        assert br.state == "half_open"
        assert br.allow()              # the single probe
        assert not br.allow()          # second concurrent call rejected
        br.record_success()
        assert br.state == "closed"
        snap = reg.snapshot()
        assert snap["counters"]["resilience.breaker_open_total.b"] == 1.0
        assert snap["counters"]["resilience.breaker_rejected_total.b"] == 2.0
        assert snap["gauges"]["resilience.breaker_state.b"] == 0.0

    def test_failed_probe_reopens(self):
        reg = MetricsRegistry()
        clk = _Clock()
        br = CircuitBreaker("b", failure_threshold=1, reset_s=10.0,
                            registry=reg, now=clk)
        br.record_failure()
        assert br.state == "open"
        clk.t = 10.0
        assert br.allow()
        br.record_failure()
        assert br.state == "open"
        assert reg.snapshot()["counters"][
            "resilience.breaker_open_total.b"] == 2.0

    def test_count_rejected_preserves_probe_slot(self):
        reg = MetricsRegistry()
        clk = _Clock()
        br = CircuitBreaker("b", failure_threshold=1, reset_s=5.0,
                            registry=reg, now=clk)
        br.record_failure()
        clk.t = 5.0
        assert br.state == "half_open"
        br.count_rejected()            # shed without touching the probe
        assert br.allow()              # probe still available
        assert reg.snapshot()["counters"][
            "resilience.breaker_rejected_total.b"] == 1.0


# ---------------------------------------------------------------------------
# reconnect_policy defaults
# ---------------------------------------------------------------------------


class TestReconnectPolicy:
    def test_defaults(self):
        p = reconnect_policy(name="loop.consume")
        assert p.attempts is forever
        assert p.base_delay_s == 0.5
        assert p.max_delay_s == 5.0
        assert p.name == "loop.consume"
        assert p.fallback is propagate

    def test_overrides_merge(self):
        p = reconnect_policy(base_delay_s=0.01, max_delay_s=0.05,
                             warn_every_s=1.0)
        assert p.attempts is forever
        assert p.base_delay_s == 0.01
        assert p.max_delay_s == 0.05


# ---------------------------------------------------------------------------
# runtime/retry.py shim is gone (deprecated PR 8, removed PR 11)
# ---------------------------------------------------------------------------


class TestRetryShim:
    def test_shim_removed(self):
        sys.modules.pop("tmhpvsim_tpu.runtime.retry", None)
        with pytest.raises(ImportError):
            importlib.import_module("tmhpvsim_tpu.runtime.retry")
