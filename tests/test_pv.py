"""PV electrical chain tests: SAPM + Sandia inverter + full csi->AC chain."""

import datetime as dt

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tmhpvsim_tpu.config import Site
from tmhpvsim_tpu.data import SANDIA_INVERTER, SAPM_MODULE
from tmhpvsim_tpu.models import pv, solar


def epoch(*args):
    return dt.datetime(*args, tzinfo=dt.timezone.utc).timestamp()


def day_geometry(date_args=(2025, 6, 21), step=60.0, xp=np):
    site = Site()
    t0 = epoch(*date_args)
    t = t0 + np.arange(0, 86400, step)
    doy = np.full(t.shape, dt.date(*date_args[:3]).timetuple().tm_yday,
                  dtype=np.float64)
    return solar.block_geometry(xp.asarray(t), xp.asarray(doy), site, xp=xp)


class TestSAPM:
    def test_reference_conditions(self):
        # At 1 sun effective irradiance and 25 C cell temperature the module
        # must reproduce its nameplate max-power point.
        dc = pv.sapm_dc(np.array([1.0]), np.array([25.0]), SAPM_MODULE, xp=np)
        assert dc["v_mp"][0] == pytest.approx(SAPM_MODULE["Vmpo"], rel=1e-6)
        imp_ref = SAPM_MODULE["Impo"] * (SAPM_MODULE["C0"] + SAPM_MODULE["C1"])
        assert dc["i_mp"][0] == pytest.approx(imp_ref, rel=1e-6)
        assert 245 < dc["p_mp"][0] < 255

    def test_temperature_derating(self):
        hot = pv.sapm_dc(np.array([1.0]), np.array([60.0]), SAPM_MODULE, xp=np)
        cold = pv.sapm_dc(np.array([1.0]), np.array([10.0]), SAPM_MODULE, xp=np)
        assert hot["p_mp"][0] < cold["p_mp"][0]

    def test_zero_irradiance_is_zero_not_nan(self):
        dc = pv.sapm_dc(np.array([0.0]), np.array([20.0]), SAPM_MODULE, xp=np)
        assert dc["p_mp"][0] == 0.0
        assert np.isfinite(dc["v_mp"][0])

    def test_cell_temp_noct_scale(self):
        # Open-rack at 800 W/m^2, 20 C ambient, no wind: cell temp in the
        # NOCT neighbourhood (42-50 C).
        tc = pv.sapm_cell_temp(np.array([800.0]), SAPM_MODULE, xp=np)
        assert 40 < tc[0] < 52

    def test_effective_irradiance_normal_incidence(self):
        # Beam-normal 1000 W/m^2, airmass 1.5, no diffuse: Ee ~ F1(1.5) suns.
        ee = pv.sapm_effective_irradiance(
            np.array([1000.0]), np.array([0.0]), np.array([1.5]),
            np.array([1.0]), SAPM_MODULE, xp=np,
        )
        f1 = (SAPM_MODULE["A0"] + SAPM_MODULE["A1"] * 1.5
              + SAPM_MODULE["A2"] * 1.5**2 + SAPM_MODULE["A3"] * 1.5**3
              + SAPM_MODULE["A4"] * 1.5**4)
        assert ee[0] == pytest.approx(f1, rel=1e-6)


class TestInverter:
    def test_rated_point(self):
        ac = pv.sandia_inverter_ac(
            np.array([SANDIA_INVERTER["Vdco"]]),
            np.array([SANDIA_INVERTER["Pdco"]]),
            SANDIA_INVERTER, xp=np,
        )
        assert ac[0] == pytest.approx(SANDIA_INVERTER["Paco"], rel=1e-6)

    def test_clipping_at_paco(self):
        ac = pv.sandia_inverter_ac(
            np.array([40.0]), np.array([400.0]), SANDIA_INVERTER, xp=np
        )
        assert ac[0] <= SANDIA_INVERTER["Paco"] + 1e-9

    def test_night_tare(self):
        ac = pv.sandia_inverter_ac(
            np.array([0.0]), np.array([0.0]), SANDIA_INVERTER, xp=np
        )
        assert ac[0] == pytest.approx(-SANDIA_INVERTER["Pnt"])

    def test_monotone_in_pdc(self):
        pdc = np.linspace(5.0, 250.0, 50)
        ac = pv.sandia_inverter_ac(np.full_like(pdc, 38.0), pdc,
                                   SANDIA_INVERTER, xp=np)
        assert np.all(np.diff(ac) > 0)


class TestFullChain:
    def test_clear_day_profile(self):
        # csi = 1 over a summer day: zero at night, peak 150-260 W around
        # noon for the 250 W system, everything finite and >= 0 — the
        # reference invariant (tests/test_pvmodel.py in the reference).
        geom = day_geometry()
        csi = np.ones_like(geom["ghi_clear"])
        ac = pv.power_from_csi(csi, geom, SAPM_MODULE, SANDIA_INVERTER, xp=np)
        assert np.all(np.isfinite(ac))
        assert np.all(ac >= 0)
        assert 150 < ac.max() < 260
        night = geom["cos_zenith"] < -0.1
        assert np.all(ac[night] == 0)

    def test_cloud_reduces_power(self):
        geom = day_geometry()
        i = int(np.argmax(geom["ghi_clear"]))
        sl = {
            k: (v[i : i + 1] if isinstance(v, np.ndarray) else v)
            for k, v in geom.items()
        }
        clear = pv.power_from_csi(np.array([1.0]), sl, SAPM_MODULE,
                                  SANDIA_INVERTER, xp=np)
        cloudy = pv.power_from_csi(np.array([0.3]), sl, SAPM_MODULE,
                                   SANDIA_INVERTER, xp=np)
        assert cloudy[0] < 0.6 * clear[0]
        assert cloudy[0] > 0

    def test_batched_csi_broadcasts(self):
        geom = day_geometry(step=600.0)
        n_t = geom["ghi_clear"].shape[0]
        csi = np.linspace(0.2, 1.2, 8)[:, None] * np.ones((1, n_t))
        ac = pv.power_from_csi(csi, geom, SAPM_MODULE, SANDIA_INVERTER, xp=np)
        assert ac.shape == (8, n_t)

    def test_jit_float32_close_to_numpy64(self):
        geom64 = day_geometry(step=300.0)
        geom32 = {
            k: (jnp.asarray(v, dtype=jnp.float32)
                if isinstance(v, np.ndarray) else v)
            for k, v in geom64.items()
        }
        csi = np.full(geom64["ghi_clear"].shape, 0.8)
        ref = pv.power_from_csi(csi, geom64, SAPM_MODULE, SANDIA_INVERTER,
                                xp=np)

        f = jax.jit(
            lambda c, g: pv.power_from_csi(c, g, SAPM_MODULE,
                                           SANDIA_INVERTER, xp=jnp)
        )
        got = np.asarray(f(jnp.asarray(csi, dtype=jnp.float32), geom32))
        # float32 end-to-end: absolute watt-level agreement on a 250 W system
        np.testing.assert_allclose(got, ref, atol=0.5)
