"""Checkpoint/resume: exact-resume guarantee and config safety."""

import csv

import numpy as np
import pytest
from click.testing import CliRunner

from tmhpvsim_tpu.config import SimConfig
from tmhpvsim_tpu.engine import Simulation
from tmhpvsim_tpu.engine import checkpoint as ckpt
from tmhpvsim_tpu.cli import main as cli_main


def cfg(**kw):
    base = dict(
        start="2019-09-05 10:00:00",
        duration_s=1800,
        n_chains=2,
        seed=13,
        block_s=600,
        dtype="float32",
    )
    base.update(kw)
    return SimConfig(**base)


def test_roundtrip_identical_state(tmp_path):
    sim = Simulation(cfg())
    it = sim.run_blocks()
    next(it)
    path = str(tmp_path / "state.npz")
    ckpt.save(path, sim.state, 1, sim.config)
    state, nb = ckpt.load(path, sim.config)
    assert nb == 1
    # every leaf identical
    flat_a = ckpt._flatten(sim.state)
    flat_b = ckpt._flatten(state)
    assert flat_a.keys() == flat_b.keys()
    for k in flat_a:
        np.testing.assert_array_equal(flat_a[k], flat_b[k])


def test_resume_bit_exact(tmp_path):
    """save -> new process-equivalent -> load -> remaining blocks match an
    uninterrupted run exactly."""
    straight = [b.pv for b in Simulation(cfg()).run_blocks()]

    a = Simulation(cfg())
    it = a.run_blocks()
    next(it)
    path = str(tmp_path / "s.npz")
    ckpt.save(path, a.state, 1, a.config)

    b = Simulation(cfg())  # fresh instance, as after a restart
    state, nb = ckpt.load(path, b.config)
    resumed = [blk.pv for blk in b.run_blocks(state=state, start_block=nb)]
    assert len(resumed) == 2
    np.testing.assert_array_equal(resumed[0], straight[1])
    np.testing.assert_array_equal(resumed[1], straight[2])


def test_reduce_resume_bit_exact(tmp_path):
    """Reduce-mode resume: the accumulator rides the checkpoint pytree, so
    stop-after-block-0 -> reload -> finish matches an uninterrupted
    reduce run on every statistic, bit for bit."""
    straight = Simulation(cfg()).run_reduced()

    path = str(tmp_path / "r.npz")
    a = Simulation(cfg())

    class Stop(Exception):
        pass

    def save_then_crash(bi, state, acc):
        ckpt.save(path, {"state": state, "acc": acc}, bi + 1, a.config)
        if bi == 0:
            raise Stop

    with pytest.raises(Stop):
        a.run_reduced(on_block=save_then_crash)

    b = Simulation(cfg())  # fresh instance, as after a restart
    tree, nb = ckpt.load(path, b.config)
    assert nb == 1
    resumed = b.run_reduced(state=tree["state"], acc=tree["acc"],
                            start_block=nb)
    assert set(resumed) == set(straight)
    for k in straight:
        np.testing.assert_array_equal(resumed[k], straight[k])


def test_resume_bit_exact_across_dst_boundary(tmp_path):
    """Checkpoint INSIDE the CEST->CET fall-back night and resume: the
    windowed sampler regeneration must reproduce the straight run bit
    for bit even when the resume point's local-time hour grid repeats an
    hour (the hour-window rebasing in host_inputs is keyed by global
    index, so a resume re-derives identical windows)."""
    dst_cfg = dict(start="2019-10-26 22:00:00", duration_s=4 * 3600,
                   block_s=3600, block_impl="scan")
    straight = Simulation(cfg(**dst_cfg)).run_reduced()

    path = str(tmp_path / "dst.npz")
    a = Simulation(cfg(**dst_cfg))

    class Stop(Exception):
        pass

    def save_then_crash(bi, state, acc):
        ckpt.save(path, {"state": state, "acc": acc}, bi + 1, a.config)
        if bi == 1:  # stop mid-run, two blocks before the repeated hour
            raise Stop

    with pytest.raises(Stop):
        a.run_reduced(on_block=save_then_crash)

    b = Simulation(cfg(**dst_cfg))
    tree, nb = ckpt.load(path, b.config)
    assert nb == 2
    resumed = b.run_reduced(state=tree["state"], acc=tree["acc"],
                            start_block=nb)
    for k in straight:
        np.testing.assert_array_equal(resumed[k], straight[k])


def test_resume_bit_exact_rbg_keys(tmp_path):
    """Checkpoint round-trip with prng_impl='rbg': key_data is 4 words
    instead of threefry's 2, so the impl must ride the checkpoint metadata
    for wrap_key_data to reconstruct the right key type on load."""
    c = cfg(prng_impl="rbg")
    straight = [b.pv for b in Simulation(c).run_blocks()]

    a = Simulation(c)
    it = a.run_blocks()
    next(it)
    path = str(tmp_path / "rbg.npz")
    ckpt.save(path, a.state, 1, a.config)

    b = Simulation(cfg(prng_impl="rbg"))
    state, nb = ckpt.load(path, b.config)
    resumed = [blk.pv for blk in b.run_blocks(state=state, start_block=nb)]
    np.testing.assert_array_equal(resumed[0], straight[1])
    # a threefry config must refuse an rbg checkpoint (echo mismatch)
    with pytest.raises(ValueError, match="different configuration"):
        ckpt.load(path, cfg())


def test_rbg_keys_survive_configless_save(tmp_path):
    """save() without a config must still record the PRNG impl (inferred
    from key_data width) so load() reconstructs rbg keys, not threefry."""
    sim = Simulation(cfg(prng_impl="rbg"))
    next(sim.run_blocks())
    path = str(tmp_path / "bare.npz")
    ckpt.save(path, sim.state, 1)  # public no-config signature
    state, _ = ckpt.load(path)
    import jax

    k = state["k_meter"]
    assert jax.random.key_data(k).shape[-1] == 4  # rbg layout preserved
    # and it must actually be usable as an rbg key
    jax.random.uniform(jax.random.fold_in(k[0], 1), (4,))


def test_old_stream_layout_checkpoint_refused(tmp_path, monkeypatch):
    """A checkpoint written by a build with a different random-stream
    layout (e.g. pre-minute-grouping) must be refused, not silently
    resumed onto different randomness mid-trace."""
    sim = Simulation(cfg())
    next(sim.run_blocks())
    path = str(tmp_path / "v1.npz")
    monkeypatch.setattr(ckpt, "RNG_STREAM_VERSION", 1)
    ckpt.save(path, sim.state, 1, sim.config)
    monkeypatch.undo()
    with pytest.raises(ValueError, match="rng_stream"):
        ckpt.load(path, cfg())


def test_reduce_resume_without_acc_rejected():
    """Resuming reduce mode trace-style (state + start_block, no acc) must
    fail loudly — a zero accumulator would silently report partial-run
    statistics as the full run's."""
    sim = Simulation(cfg())
    state = sim.init_state()
    with pytest.raises(ValueError, match="accumulator"):
        sim.run_reduced(state=state, start_block=1)


def test_sharded_reduce_resume_with_zero_blocks_left(tmp_path):
    """Re-invoking a finished sharded reduce run with its stale checkpoint
    must re-emit the same summary, not crash: the loop body never runs, so
    the loaded host-numpy accumulator must be re-placed with the chain
    sharding before the final gather and the ensemble psum tree."""
    from tmhpvsim_tpu.parallel import ShardedSimulation

    c = cfg(n_chains=8)
    sim = ShardedSimulation(c)
    saved = {}

    def hook(bi, state, acc):
        saved.update(state=state, acc=acc, nb=bi + 1)

    straight = sim.run_reduced(on_block=hook)
    ens_straight = sim.ensemble_stats()
    path = str(tmp_path / "s.npz")
    ckpt.save(path, {"state": saved["state"], "acc": saved["acc"]},
              saved["nb"], c)

    sim2 = ShardedSimulation(cfg(n_chains=8))
    tree, nb = ckpt.load(path, sim2.config)
    assert nb == sim2.n_blocks
    resumed = sim2.run_reduced(state=tree["state"], acc=tree["acc"],
                               start_block=nb)
    for k in straight:
        np.testing.assert_array_equal(resumed[k], straight[k])
    assert sim2.ensemble_stats() == ens_straight


def test_cli_reduce_checkpoint_crash_resume(tmp_path, monkeypatch):
    """Reduce-mode restart safety through the real CLI: crash mid-run,
    re-invoke with the same --checkpoint, summary CSV identical to an
    uninterrupted run."""
    def run_reduce(*extra):
        return CliRunner().invoke(cli_main, [
            "pvsim", *extra, "--backend=jax", "--no-realtime",
            "--duration", "360", "--seed", "9", "--output", "reduce",
            "--start", "2019-09-05 10:00:00", "--block-s", "120",
        ])

    whole = tmp_path / "whole.csv"
    r = run_reduce(str(whole))
    assert r.exit_code == 0, r.output

    part = tmp_path / "part.csv"
    ck = tmp_path / "ck.npz"

    import tmhpvsim_tpu.engine.checkpoint as ckmod

    real_save = ckmod.save
    calls = {"n": 0}

    def dying_save(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("simulated crash")
        return real_save(*a, **kw)

    monkeypatch.setattr(ckmod, "save", dying_save)
    r = run_reduce(str(part), "--checkpoint", str(ck))
    assert r.exit_code != 0  # crashed after block 0's checkpoint
    monkeypatch.setattr(ckmod, "save", real_save)
    assert not part.exists()  # reduce CSV only written at the end

    r = run_reduce(str(part), "--checkpoint", str(ck))
    assert r.exit_code == 0, r.output

    with open(part) as f:
        part_rows = list(csv.reader(f))
    with open(whole) as f:
        whole_rows = list(csv.reader(f))
    assert part_rows == whole_rows
    assert part_rows[-1][0] == "ensemble"


def test_config_mismatch_rejected(tmp_path):
    sim = Simulation(cfg())
    next(sim.run_blocks())
    path = str(tmp_path / "s.npz")
    ckpt.save(path, sim.state, 1, sim.config)
    with pytest.raises(ValueError, match="different configuration"):
        ckpt.load(path, cfg(seed=14))


def _cli_jax(*extra):
    return CliRunner().invoke(cli_main, [
        "pvsim", *extra, "--backend=jax", "--no-realtime",
        "--duration", "360", "--seed", "9",
        "--start", "2019-09-05 10:00:00", "--block-s", "120",
    ])


def test_cli_checkpoint_crash_resume(tmp_path, monkeypatch):
    """THE resume guarantee, via the real CLI path: crash after block 0,
    re-invoke with the same --checkpoint, final CSV identical to an
    uninterrupted run (exercises _truncate_csv, append mode, and the
    checkpoint flag wiring end to end)."""
    whole = tmp_path / "whole.csv"
    r = _cli_jax(str(whole))
    assert r.exit_code == 0, r.output

    part = tmp_path / "part.csv"
    ck = tmp_path / "ck.npz"

    # crash the run after block 0's rows are written and checkpoint saved:
    # ckpt.save raises on its second call (i.e. after block 1's rows)
    import tmhpvsim_tpu.engine.checkpoint as ckmod

    real_save = ckmod.save
    calls = {"n": 0}

    def dying_save(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("simulated crash")
        return real_save(*a, **kw)

    monkeypatch.setattr(ckmod, "save", dying_save)
    r = _cli_jax(str(part), "--checkpoint", str(ck))
    assert r.exit_code != 0  # crashed mid-run
    monkeypatch.setattr(ckmod, "save", real_save)

    # the crash window left rows beyond the checkpoint -> resume must
    # truncate them and complete the file exactly
    with open(part) as f:
        assert len(f.readlines()) > 1 + 120

    r = _cli_jax(str(part), "--checkpoint", str(ck))
    assert r.exit_code == 0, r.output

    with open(part) as f:
        part_rows = list(csv.reader(f))
    with open(whole) as f:
        whole_rows = list(csv.reader(f))
    assert part_rows == whole_rows
    assert len(part_rows) == 1 + 360


def test_cli_resume_missing_csv_rejected(tmp_path):
    """Resuming against a deleted CSV must fail loudly, not fabricate a
    headerless partial file."""
    part = tmp_path / "part.csv"
    ck = tmp_path / "ck.npz"
    r = _cli_jax(str(part), "--checkpoint", str(ck))
    assert r.exit_code == 0, r.output
    # checkpoint says "done"; shorten it to mid-run and delete the CSV
    state, _ = ckpt.load(str(ck))
    meta_cfg = ckpt.peek_meta(str(ck))["config"]
    cfg_ = SimConfig(
        start=meta_cfg["start"], duration_s=meta_cfg["duration_s"],
        n_chains=meta_cfg["n_chains"], seed=meta_cfg["seed"],
        block_s=meta_cfg["block_s"], dtype=meta_cfg["dtype"],
    )
    ckpt.save(str(ck), state, 1, cfg_)
    part.unlink()
    r = _cli_jax(str(part), "--checkpoint", str(ck))
    assert r.exit_code != 0
    assert "restore the CSV" in str(r.exception)


def test_foreign_state_layout_named_in_error(tmp_path):
    """A state whose leaf set does not match this build (e.g. an edited
    npz, or a pre-windowed layout past a bypassed version gate) must be
    refused with the offending leaf NAMES, not an opaque tree-structure
    error deep in jit (round-4 ADVICE)."""
    sim = Simulation(cfg())
    it = sim.run_blocks()
    next(it)
    path = str(tmp_path / "state.npz")
    ckpt.save(path, sim.state, 1, sim.config)
    state, nb = ckpt.load(path, sim.config)
    state["arrays"] = state.pop("cc_carry")  # simulate a foreign layout
    sim2 = Simulation(cfg())
    with pytest.raises(ValueError, match="arrays.*|cc_carry.*"):
        list(sim2.run_blocks(state=state, start_block=nb))


def test_matching_layout_passes_check(tmp_path):
    """The layout check is a no-op for a genuine checkpoint."""
    sim = Simulation(cfg())
    it = sim.run_blocks()
    next(it)
    path = str(tmp_path / "state.npz")
    ckpt.save(path, sim.state, 1, sim.config)
    state, nb = ckpt.load(path, sim.config)
    sim2 = Simulation(cfg())
    assert sim2._check_resume_layout(state) is state


def test_foreign_acc_layout_named_in_error(tmp_path):
    """The reduce accumulator half of a resume gets the same named-leaf
    guard as the state half."""
    sim = Simulation(cfg(output="reduce"))
    sim.run_reduced()
    acc = {k: np.asarray(v) for k, v in sim._last_acc.items()}
    acc["bogus_stat"] = acc.pop("pv_sum")
    state = {k: np.asarray(v) for k, v in ckpt._flatten(sim.state).items()}
    sim2 = Simulation(cfg(output="reduce"))
    loaded_state = ckpt._unflatten(
        {k: v for k, v in state.items()}, sim2.config.prng_impl)
    with pytest.raises(ValueError, match="bogus_stat"):
        sim2.run_reduced(state=loaded_state, acc=acc, start_block=1)


def test_wrong_dtype_leaf_named_in_error(tmp_path):
    """Right names but a wrong-dtype leaf (hand-edited npz) is refused
    with the leaf named, not an in-jit shape error."""
    sim = Simulation(cfg())
    it = sim.run_blocks()
    next(it)
    path = str(tmp_path / "state.npz")
    ckpt.save(path, sim.state, 1, sim.config)
    state, nb = ckpt.load(path, sim.config)
    state["cc_carry"] = state["cc_carry"].astype(np.float64)
    sim2 = Simulation(cfg())
    with pytest.raises(ValueError, match="cc_carry"):
        list(sim2.run_blocks(state=state, start_block=nb))
