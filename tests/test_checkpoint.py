"""Checkpoint/resume: exact-resume guarantee, config safety, rotation +
integrity manifests (torn-write fallback), topology-elastic resume, and
the async snapshot writer."""

import csv
import os
import threading
import time

import numpy as np
import pytest
from click.testing import CliRunner

from tmhpvsim_tpu.config import SimConfig
from tmhpvsim_tpu.engine import Simulation
from tmhpvsim_tpu.engine import checkpoint as ckpt
from tmhpvsim_tpu.obs.metrics import MetricsRegistry, use_registry
from tmhpvsim_tpu.cli import main as cli_main


def cfg(**kw):
    base = dict(
        start="2019-09-05 10:00:00",
        duration_s=1800,
        n_chains=2,
        seed=13,
        block_s=600,
        dtype="float32",
    )
    base.update(kw)
    return SimConfig(**base)


def test_roundtrip_identical_state(tmp_path):
    sim = Simulation(cfg())
    it = sim.run_blocks()
    next(it)
    path = str(tmp_path / "state.npz")
    ckpt.save(path, sim.state, 1, sim.config)
    state, nb = ckpt.load(path, sim.config)
    assert nb == 1
    # every leaf identical
    flat_a = ckpt._flatten(sim.state)
    flat_b = ckpt._flatten(state)
    assert flat_a.keys() == flat_b.keys()
    for k in flat_a:
        np.testing.assert_array_equal(flat_a[k], flat_b[k])


def test_resume_bit_exact(tmp_path):
    """save -> new process-equivalent -> load -> remaining blocks match an
    uninterrupted run exactly."""
    straight = [b.pv for b in Simulation(cfg()).run_blocks()]

    a = Simulation(cfg())
    it = a.run_blocks()
    next(it)
    path = str(tmp_path / "s.npz")
    ckpt.save(path, a.state, 1, a.config)

    b = Simulation(cfg())  # fresh instance, as after a restart
    state, nb = ckpt.load(path, b.config)
    resumed = [blk.pv for blk in b.run_blocks(state=state, start_block=nb)]
    assert len(resumed) == 2
    np.testing.assert_array_equal(resumed[0], straight[1])
    np.testing.assert_array_equal(resumed[1], straight[2])


def test_reduce_resume_bit_exact(tmp_path):
    """Reduce-mode resume: the accumulator rides the checkpoint pytree, so
    stop-after-block-0 -> reload -> finish matches an uninterrupted
    reduce run on every statistic, bit for bit."""
    straight = Simulation(cfg()).run_reduced()

    path = str(tmp_path / "r.npz")
    a = Simulation(cfg())

    class Stop(Exception):
        pass

    def save_then_crash(bi, state, acc):
        ckpt.save(path, {"state": state, "acc": acc}, bi + 1, a.config)
        if bi == 0:
            raise Stop

    with pytest.raises(Stop):
        a.run_reduced(on_block=save_then_crash)

    b = Simulation(cfg())  # fresh instance, as after a restart
    tree, nb = ckpt.load(path, b.config)
    assert nb == 1
    resumed = b.run_reduced(state=tree["state"], acc=tree["acc"],
                            start_block=nb)
    assert set(resumed) == set(straight)
    for k in straight:
        np.testing.assert_array_equal(resumed[k], straight[k])


def test_resume_bit_exact_across_dst_boundary(tmp_path):
    """Checkpoint INSIDE the CEST->CET fall-back night and resume: the
    windowed sampler regeneration must reproduce the straight run bit
    for bit even when the resume point's local-time hour grid repeats an
    hour (the hour-window rebasing in host_inputs is keyed by global
    index, so a resume re-derives identical windows)."""
    dst_cfg = dict(start="2019-10-26 22:00:00", duration_s=4 * 3600,
                   block_s=3600, block_impl="scan")
    straight = Simulation(cfg(**dst_cfg)).run_reduced()

    path = str(tmp_path / "dst.npz")
    a = Simulation(cfg(**dst_cfg))

    class Stop(Exception):
        pass

    def save_then_crash(bi, state, acc):
        ckpt.save(path, {"state": state, "acc": acc}, bi + 1, a.config)
        if bi == 1:  # stop mid-run, two blocks before the repeated hour
            raise Stop

    with pytest.raises(Stop):
        a.run_reduced(on_block=save_then_crash)

    b = Simulation(cfg(**dst_cfg))
    tree, nb = ckpt.load(path, b.config)
    assert nb == 2
    resumed = b.run_reduced(state=tree["state"], acc=tree["acc"],
                            start_block=nb)
    for k in straight:
        np.testing.assert_array_equal(resumed[k], straight[k])


def test_resume_bit_exact_rbg_keys(tmp_path):
    """Checkpoint round-trip with prng_impl='rbg': key_data is 4 words
    instead of threefry's 2, so the impl must ride the checkpoint metadata
    for wrap_key_data to reconstruct the right key type on load."""
    c = cfg(prng_impl="rbg")
    straight = [b.pv for b in Simulation(c).run_blocks()]

    a = Simulation(c)
    it = a.run_blocks()
    next(it)
    path = str(tmp_path / "rbg.npz")
    ckpt.save(path, a.state, 1, a.config)

    b = Simulation(cfg(prng_impl="rbg"))
    state, nb = ckpt.load(path, b.config)
    resumed = [blk.pv for blk in b.run_blocks(state=state, start_block=nb)]
    np.testing.assert_array_equal(resumed[0], straight[1])
    # a threefry config must refuse an rbg checkpoint (echo mismatch)
    with pytest.raises(ValueError, match="different configuration"):
        ckpt.load(path, cfg())


def test_rbg_keys_survive_configless_save(tmp_path):
    """save() without a config must still record the PRNG impl (inferred
    from key_data width) so load() reconstructs rbg keys, not threefry."""
    sim = Simulation(cfg(prng_impl="rbg"))
    next(sim.run_blocks())
    path = str(tmp_path / "bare.npz")
    ckpt.save(path, sim.state, 1)  # public no-config signature
    state, _ = ckpt.load(path)
    import jax

    k = state["k_meter"]
    assert jax.random.key_data(k).shape[-1] == 4  # rbg layout preserved
    # and it must actually be usable as an rbg key
    jax.random.uniform(jax.random.fold_in(k[0], 1), (4,))


def test_old_stream_layout_checkpoint_refused(tmp_path, monkeypatch):
    """A checkpoint written by a build with a different random-stream
    layout (e.g. pre-minute-grouping) must be refused, not silently
    resumed onto different randomness mid-trace."""
    sim = Simulation(cfg())
    next(sim.run_blocks())
    path = str(tmp_path / "v1.npz")
    monkeypatch.setattr(ckpt, "RNG_STREAM_VERSION", 1)
    ckpt.save(path, sim.state, 1, sim.config)
    monkeypatch.undo()
    with pytest.raises(ValueError, match="rng_stream"):
        ckpt.load(path, cfg())


def test_reduce_resume_without_acc_rejected():
    """Resuming reduce mode trace-style (state + start_block, no acc) must
    fail loudly — a zero accumulator would silently report partial-run
    statistics as the full run's."""
    sim = Simulation(cfg())
    state = sim.init_state()
    with pytest.raises(ValueError, match="accumulator"):
        sim.run_reduced(state=state, start_block=1)


def test_sharded_reduce_resume_with_zero_blocks_left(tmp_path):
    """Re-invoking a finished sharded reduce run with its stale checkpoint
    must re-emit the same summary, not crash: the loop body never runs, so
    the loaded host-numpy accumulator must be re-placed with the chain
    sharding before the final gather and the ensemble psum tree."""
    from tmhpvsim_tpu.parallel import ShardedSimulation

    c = cfg(n_chains=8)
    sim = ShardedSimulation(c)
    saved = {}

    def hook(bi, state, acc):
        saved.update(state=state, acc=acc, nb=bi + 1)

    straight = sim.run_reduced(on_block=hook)
    ens_straight = sim.ensemble_stats()
    path = str(tmp_path / "s.npz")
    ckpt.save(path, {"state": saved["state"], "acc": saved["acc"]},
              saved["nb"], c)

    sim2 = ShardedSimulation(cfg(n_chains=8))
    tree, nb = ckpt.load(path, sim2.config)
    assert nb == sim2.n_blocks
    resumed = sim2.run_reduced(state=tree["state"], acc=tree["acc"],
                               start_block=nb)
    for k in straight:
        np.testing.assert_array_equal(resumed[k], straight[k])
    assert sim2.ensemble_stats() == ens_straight


def test_cli_reduce_checkpoint_crash_resume(tmp_path, monkeypatch):
    """Reduce-mode restart safety through the real CLI: crash mid-run,
    re-invoke with the same --checkpoint, summary CSV identical to an
    uninterrupted run."""
    def run_reduce(*extra):
        return CliRunner().invoke(cli_main, [
            "pvsim", *extra, "--backend=jax", "--no-realtime",
            "--duration", "360", "--seed", "9", "--output", "reduce",
            "--start", "2019-09-05 10:00:00", "--block-s", "120",
        ])

    whole = tmp_path / "whole.csv"
    r = run_reduce(str(whole))
    assert r.exit_code == 0, r.output

    part = tmp_path / "part.csv"
    ck = tmp_path / "ck.npz"

    import tmhpvsim_tpu.engine.checkpoint as ckmod

    real_save = ckmod.save
    calls = {"n": 0}

    def dying_save(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("simulated crash")
        return real_save(*a, **kw)

    monkeypatch.setattr(ckmod, "save", dying_save)
    r = run_reduce(str(part), "--checkpoint", str(ck))
    assert r.exit_code != 0  # crashed after block 0's checkpoint
    monkeypatch.setattr(ckmod, "save", real_save)
    assert not part.exists()  # reduce CSV only written at the end

    r = run_reduce(str(part), "--checkpoint", str(ck))
    assert r.exit_code == 0, r.output

    with open(part) as f:
        part_rows = list(csv.reader(f))
    with open(whole) as f:
        whole_rows = list(csv.reader(f))
    assert part_rows == whole_rows
    assert part_rows[-1][0] == "ensemble"


def test_config_mismatch_rejected(tmp_path):
    sim = Simulation(cfg())
    next(sim.run_blocks())
    path = str(tmp_path / "s.npz")
    ckpt.save(path, sim.state, 1, sim.config)
    with pytest.raises(ValueError, match="different configuration"):
        ckpt.load(path, cfg(seed=14))


def _cli_jax(*extra):
    return CliRunner().invoke(cli_main, [
        "pvsim", *extra, "--backend=jax", "--no-realtime",
        "--duration", "360", "--seed", "9",
        "--start", "2019-09-05 10:00:00", "--block-s", "120",
    ])


def test_cli_checkpoint_crash_resume(tmp_path, monkeypatch):
    """THE resume guarantee, via the real CLI path: crash after block 0,
    re-invoke with the same --checkpoint, final CSV identical to an
    uninterrupted run (exercises _truncate_csv, append mode, and the
    checkpoint flag wiring end to end)."""
    whole = tmp_path / "whole.csv"
    r = _cli_jax(str(whole))
    assert r.exit_code == 0, r.output

    part = tmp_path / "part.csv"
    ck = tmp_path / "ck.npz"

    # crash the run after block 0's rows are written and checkpoint saved:
    # ckpt.save raises on its second call (i.e. after block 1's rows)
    import tmhpvsim_tpu.engine.checkpoint as ckmod

    real_save = ckmod.save
    calls = {"n": 0}

    def dying_save(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("simulated crash")
        return real_save(*a, **kw)

    monkeypatch.setattr(ckmod, "save", dying_save)
    r = _cli_jax(str(part), "--checkpoint", str(ck))
    assert r.exit_code != 0  # crashed mid-run
    monkeypatch.setattr(ckmod, "save", real_save)

    # the crash window left rows beyond the checkpoint -> resume must
    # truncate them and complete the file exactly
    with open(part) as f:
        assert len(f.readlines()) > 1 + 120

    r = _cli_jax(str(part), "--checkpoint", str(ck))
    assert r.exit_code == 0, r.output

    with open(part) as f:
        part_rows = list(csv.reader(f))
    with open(whole) as f:
        whole_rows = list(csv.reader(f))
    assert part_rows == whole_rows
    assert len(part_rows) == 1 + 360


def test_cli_resume_missing_csv_rejected(tmp_path):
    """Resuming against a deleted CSV must fail loudly, not fabricate a
    headerless partial file."""
    part = tmp_path / "part.csv"
    ck = tmp_path / "ck.npz"
    r = _cli_jax(str(part), "--checkpoint", str(ck))
    assert r.exit_code == 0, r.output
    # checkpoint says "done"; shorten it to mid-run and delete the CSV
    state, _ = ckpt.load(str(ck))
    meta_cfg = ckpt.peek_meta(str(ck))["config"]
    cfg_ = SimConfig(
        start=meta_cfg["start"], duration_s=meta_cfg["duration_s"],
        n_chains=meta_cfg["n_chains"], seed=meta_cfg["seed"],
        block_s=meta_cfg["block_s"], dtype=meta_cfg["dtype"],
    )
    ckpt.save(str(ck), state, 1, cfg_)
    part.unlink()
    r = _cli_jax(str(part), "--checkpoint", str(ck))
    assert r.exit_code != 0
    assert "restore the CSV" in str(r.exception)


def test_foreign_state_layout_named_in_error(tmp_path):
    """A state whose leaf set does not match this build (e.g. an edited
    npz, or a pre-windowed layout past a bypassed version gate) must be
    refused with the offending leaf NAMES, not an opaque tree-structure
    error deep in jit (round-4 ADVICE)."""
    sim = Simulation(cfg())
    it = sim.run_blocks()
    next(it)
    path = str(tmp_path / "state.npz")
    ckpt.save(path, sim.state, 1, sim.config)
    state, nb = ckpt.load(path, sim.config)
    state["arrays"] = state.pop("cc_carry")  # simulate a foreign layout
    sim2 = Simulation(cfg())
    with pytest.raises(ValueError, match="arrays.*|cc_carry.*"):
        list(sim2.run_blocks(state=state, start_block=nb))


def test_matching_layout_passes_check(tmp_path):
    """The layout check is a no-op for a genuine checkpoint."""
    sim = Simulation(cfg())
    it = sim.run_blocks()
    next(it)
    path = str(tmp_path / "state.npz")
    ckpt.save(path, sim.state, 1, sim.config)
    state, nb = ckpt.load(path, sim.config)
    sim2 = Simulation(cfg())
    assert sim2._check_resume_layout(state) is state


def test_foreign_acc_layout_named_in_error(tmp_path):
    """The reduce accumulator half of a resume gets the same named-leaf
    guard as the state half."""
    sim = Simulation(cfg(output="reduce"))
    sim.run_reduced()
    acc = {k: np.asarray(v) for k, v in sim._last_acc.items()}
    acc["bogus_stat"] = acc.pop("pv_sum")
    state = {k: np.asarray(v) for k, v in ckpt._flatten(sim.state).items()}
    sim2 = Simulation(cfg(output="reduce"))
    loaded_state = ckpt._unflatten(
        {k: v for k, v in state.items()}, sim2.config.prng_impl)
    with pytest.raises(ValueError, match="bogus_stat"):
        sim2.run_reduced(state=loaded_state, acc=acc, start_block=1)


def test_wrong_dtype_leaf_named_in_error(tmp_path):
    """Right names but a wrong-dtype leaf (hand-edited npz) is refused
    with the leaf named, not an in-jit shape error."""
    sim = Simulation(cfg())
    it = sim.run_blocks()
    next(it)
    path = str(tmp_path / "state.npz")
    ckpt.save(path, sim.state, 1, sim.config)
    state, nb = ckpt.load(path, sim.config)
    state["cc_carry"] = state["cc_carry"].astype(np.float64)
    sim2 = Simulation(cfg())
    with pytest.raises(ValueError, match="cc_carry"):
        list(sim2.run_blocks(state=state, start_block=nb))


# ---------------------------------------------------------------------------
# rotation + integrity manifest: generations, pruning, torn-write fallback
# ---------------------------------------------------------------------------


def _state_eq(a, b):
    fa, fb = ckpt._flatten(a), ckpt._flatten(b)
    assert fa.keys() == fb.keys()
    for k in fa:
        np.testing.assert_array_equal(fa[k], fb[k])


def test_rotation_keeps_n_generations(tmp_path):
    """save() rotates PATH.g<N> siblings, keeps the newest ``keep``,
    prunes the rest, and the anchor always IS the newest generation."""
    sim = Simulation(cfg())
    next(sim.run_blocks())
    path = str(tmp_path / "r.npz")
    for nb in range(1, 6):
        ckpt.save(path, sim.state, nb, sim.config, keep=3)
    man = ckpt.read_manifest(path)
    assert man["format"] == ckpt.MANIFEST_FORMAT
    assert man["latest"] == 5 and man["keep"] == 3
    assert [e["gen"] for e in man["generations"]] == [3, 4, 5]
    for g in (1, 2):
        assert not os.path.exists(f"{path}.g{g}")  # pruned
    for g in (3, 4, 5):
        assert os.path.exists(f"{path}.g{g}")
    # the anchor is a complete copy of the newest generation
    with open(path, "rb") as a, open(f"{path}.g5", "rb") as b:
        assert a.read() == b.read()
    _, nb = ckpt.load(path, sim.config)
    assert nb == 5


def test_load_survives_anchor_loss(tmp_path):
    """Deleting the anchor file must not kill the run: the manifest's
    surviving generation still resumes (resumable() is the rotation-aware
    replacement for bare os.path.exists)."""
    sim = Simulation(cfg())
    next(sim.run_blocks())
    path = str(tmp_path / "a.npz")
    ckpt.save(path, sim.state, 1, sim.config)
    ckpt.save(path, sim.state, 2, sim.config)
    os.remove(path)
    assert ckpt.resumable(path)
    state, nb = ckpt.load(path, cfg())
    assert nb == 2
    _state_eq(state, sim.state)
    assert not ckpt.resumable(str(tmp_path / "never_saved.npz"))


@pytest.mark.parametrize("where", ["header", "mid", "tail"])
def test_torn_write_falls_back_to_last_good_generation(tmp_path, where):
    """The torn-write matrix: the latest generation truncated at the npz
    header, mid-array, and near the end must each fall back (WARN +
    counters) to the previous generation, never dead-end the run."""
    sim = Simulation(cfg())
    it = sim.run_blocks()
    next(it)
    good = {k: np.array(v) for k, v in ckpt._flatten(sim.state).items()}
    path = str(tmp_path / f"t_{where}.npz")
    ckpt.save(path, sim.state, 1, sim.config)
    next(it)
    ckpt.save(path, sim.state, 2, sim.config)
    size = os.path.getsize(path)
    offset = {"header": 8, "mid": size // 2, "tail": size - 8}[where]
    # the anchor hard-links the newest generation: tearing it through
    # either name damages exactly that generation
    os.truncate(path, offset)
    reg = MetricsRegistry()
    with use_registry(reg):
        state, nb = ckpt.load(path, cfg())
    assert nb == 1
    flat = ckpt._flatten(state)
    for k in good:
        np.testing.assert_array_equal(flat[k], good[k])
    c = reg.snapshot()["counters"]
    assert c["checkpoint.verify_fail_total"] == 1.0
    assert c["checkpoint.fallback_total"] == 1.0


def test_bitflip_detected_by_checksum(tmp_path):
    """A same-size corruption (flipped byte, not a truncation) is caught
    by the CRC/sha sidecar, not by a size check."""
    sim = Simulation(cfg())
    next(sim.run_blocks())
    path = str(tmp_path / "b.npz")
    ckpt.save(path, sim.state, 1, sim.config)
    ckpt.save(path, sim.state, 2, sim.config)
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size // 2)
        byte = f.read(1)
        f.seek(size // 2)
        f.write(bytes([byte[0] ^ 0xFF]))
    assert os.path.getsize(path) == size  # same size, different bytes
    state, nb = ckpt.load(path, cfg())
    assert nb == 1


def test_all_generations_torn_raises_corrupt_error(tmp_path):
    """Only when NO generation verifies does load raise — a typed
    CheckpointCorruptError naming what was tried, with the hint."""
    sim = Simulation(cfg())
    next(sim.run_blocks())
    path = str(tmp_path / "dead.npz")
    ckpt.save(path, sim.state, 1, sim.config, keep=1)
    os.truncate(path, 4)
    with pytest.raises(ckpt.CheckpointCorruptError) as ei:
        ckpt.load(path, cfg())
    msg = str(ei.value)
    assert "no generation passed integrity verification" in msg
    assert "delete the checkpoint" in msg  # actionable hint
    assert isinstance(ei.value, ckpt.CheckpointError)


def test_missing_checkpoint_typed_error(tmp_path):
    path = str(tmp_path / "nope.npz")
    with pytest.raises(ckpt.CheckpointError, match="missing"):
        ckpt.load(path)
    with pytest.raises(ckpt.CheckpointError, match="missing"):
        ckpt.peek_meta(path)


def test_garbage_file_typed_error(tmp_path):
    """A non-npz file behind --checkpoint must surface as a typed
    CheckpointError with the path and a hint — not a raw
    zipfile.BadZipFile from deep inside numpy."""
    p = tmp_path / "junk.npz"
    p.write_bytes(b"this is not an npz checkpoint")
    with pytest.raises(ckpt.CheckpointError) as ei:
        ckpt.load(str(p))
    msg = str(ei.value)
    assert "unreadable as a checkpoint npz" in msg
    assert str(p) in msg and "delete the checkpoint" in msg
    with pytest.raises(ckpt.CheckpointError, match="no readable metadata"):
        ckpt.peek_meta(str(p))


def test_metadata_less_npz_typed_error(tmp_path):
    """A real npz that simply lacks the __meta__ record (foreign file)
    gets the same typed error, not a KeyError."""
    p = str(tmp_path / "m.npz")
    np.savez(p, a=np.zeros(3))
    with pytest.raises(ckpt.CheckpointError, match="KeyError"):
        ckpt.load(p)


def test_legacy_single_file_loads_as_generation_zero(tmp_path):
    """Pre-rotation checkpoints (one bare npz, no manifest) stay fully
    loadable, and the next save over them starts a fresh rotation."""
    sim = Simulation(cfg())
    it = sim.run_blocks()
    next(it)
    path = str(tmp_path / "legacy.npz")
    ckpt.save(path, sim.state, 1, sim.config)
    # strip the rotation artifacts: what an old build would have written
    os.remove(ckpt.manifest_path(path))
    os.remove(path + ".g1")
    assert ckpt.read_manifest(path) is None
    assert ckpt.resumable(path)
    state, nb = ckpt.load(path, sim.config)
    assert nb == 1
    _state_eq(state, sim.state)
    next(it)
    ckpt.save(path, sim.state, 2, sim.config)  # rotation restarts
    man = ckpt.read_manifest(path)
    assert man["latest"] == 1
    _, nb = ckpt.load(path, cfg())
    assert nb == 2


def test_peek_meta_falls_back_over_torn_anchor(tmp_path):
    """peek_meta (the CLI's seed probe) reads the newest READABLE
    generation, so a torn anchor cannot break the pre-run seed check."""
    sim = Simulation(cfg())
    next(sim.run_blocks())
    path = str(tmp_path / "p.npz")
    ckpt.save(path, sim.state, 1, sim.config)
    ckpt.save(path, sim.state, 2, sim.config)
    os.truncate(path, 16)  # tears the anchor AND g2 (shared inode)
    assert ckpt.peek_meta(path)["next_block"] == 1


# ---------------------------------------------------------------------------
# topology-elastic resume: host shards, reslicing, device-count changes
# ---------------------------------------------------------------------------


def _halves(flat, a, b, n, prng_impl):
    part = {k: (v[a:b] if getattr(v, "ndim", 0) >= 1 and v.shape[0] == n
                else v)
            for k, v in flat.items()}
    return ckpt._unflatten(part, prng_impl)


def test_host_shard_reassembly_bit_identical(tmp_path):
    """Two per-host PATH.host<i> shard files reassemble into the full
    chain axis bit-identically, and reslice back out to either half."""
    c = cfg(n_chains=4)
    sim = Simulation(c)
    next(sim.run_blocks())
    full = {k: np.array(v) for k, v in ckpt._flatten(sim.state).items()}
    base = str(tmp_path / "ck.npz")
    for hi, (a, b) in enumerate(((0, 2), (2, 4))):
        ckpt.save(f"{base}.host{hi}", _halves(full, a, b, 4, c.prng_impl),
                  1, c, layout={"n_chains": 4, "chain_start": a,
                                "chain_stop": b, "process_count": 2,
                                "process_index": hi})
    assert not os.path.exists(base)
    assert ckpt.resumable(base)  # shards count as resumable
    state, nb = ckpt.load_elastic(base, c)
    assert nb == 1
    got = ckpt._flatten(state)
    assert got.keys() == full.keys()
    for k in full:
        np.testing.assert_array_equal(got[k], full[k])
    # reslice to the second host's half: K-shard run resuming on 1 host
    # of a different slice
    state, _ = ckpt.load_elastic(base, c, chain_slice=(2, 4))
    got = ckpt._flatten(state)
    for k, v in full.items():
        want = v[2:4] if getattr(v, "ndim", 0) >= 1 and v.shape[0] == 4 \
            else v
        np.testing.assert_array_equal(got[k], want)
    # a slice the shards do not cover is refused with a hint
    with pytest.raises(ckpt.CheckpointError, match="does not cover"):
        ckpt.load_elastic(base, c, chain_slice=(2, 6))


def test_shard_straggler_aligns_on_common_block(tmp_path):
    """Shards whose newest generations disagree (host0 checkpointed one
    block further before the preemption) align on the oldest common
    resume point via each shard's rotation history."""
    c = cfg(n_chains=4)
    sim = Simulation(c)
    it = sim.run_blocks()
    next(it)
    fa = {k: np.array(v) for k, v in ckpt._flatten(sim.state).items()}
    next(it)
    fb = ckpt._flatten(sim.state)
    base = str(tmp_path / "ck.npz")
    lay = lambda a, b: {"n_chains": 4, "chain_start": a, "chain_stop": b}
    ckpt.save(f"{base}.host0", _halves(fa, 0, 2, 4, c.prng_impl), 1, c,
              layout=lay(0, 2))
    ckpt.save(f"{base}.host0", _halves(fb, 0, 2, 4, c.prng_impl), 2, c,
              layout=lay(0, 2))
    ckpt.save(f"{base}.host1", _halves(fa, 2, 4, 4, c.prng_impl), 1, c,
              layout=lay(2, 4))
    state, nb = ckpt.load_elastic(base, c)
    assert nb == 1  # aligned down to host1's newest block
    got = ckpt._flatten(state)
    for k in fa:
        np.testing.assert_array_equal(got[k], fa[k])


def test_elastic_resume_across_device_counts(tmp_path):
    """8-device <-> 1-device elastic resume: a checkpoint saved under
    either placement resumes under the other.  Placement never refuses;
    identity (seed, chains, models) still does.  Cross-topology numerics
    match at the repo's documented ULP tolerances (integer statistics
    exactly) — see test_parallel.TestShardedReduce."""
    from tmhpvsim_tpu.parallel import ShardedSimulation

    c = cfg(n_chains=8)
    straight = Simulation(cfg(n_chains=8)).run_reduced()

    class Stop(Exception):
        pass

    def stopper(path, sim):
        def hook(bi, state, acc):
            ckpt.save(path, {"state": state, "acc": acc}, bi + 1,
                      sim.config, layout=sim.checkpoint_layout())
            if bi == 0:
                raise Stop
        return hook

    # 8 devices -> 1 device
    sharded = ShardedSimulation(cfg(n_chains=8))
    p1 = str(tmp_path / "from8.npz")
    with pytest.raises(Stop):
        sharded.run_reduced(on_block=stopper(p1, sharded))
    assert ckpt.peek_meta(p1)["layout"]["n_devices"] == 8
    single = Simulation(cfg(n_chains=8))
    tree, nb = ckpt.load_elastic(p1, single.config,
                                 chain_slice=single.resume_chain_slice())
    assert nb == 1
    r1 = single.run_reduced(state=tree["state"], acc=tree["acc"],
                            start_block=nb)
    np.testing.assert_array_equal(r1["n_seconds"], straight["n_seconds"])
    for k in straight:
        np.testing.assert_allclose(r1[k], straight[k],
                                   rtol=1e-5, atol=1e-2)

    # 1 device -> 8 devices
    solo = Simulation(cfg(n_chains=8))
    p2 = str(tmp_path / "from1.npz")
    with pytest.raises(Stop):
        solo.run_reduced(on_block=stopper(p2, solo))
    sh2 = ShardedSimulation(cfg(n_chains=8))
    tree, nb = ckpt.load_elastic(p2, sh2.config,
                                 chain_slice=sh2.resume_chain_slice())
    assert nb == 1
    r2 = sh2.run_reduced(state=tree["state"], acc=tree["acc"],
                         start_block=nb)
    np.testing.assert_array_equal(r2["n_seconds"], straight["n_seconds"])
    for k in straight:
        np.testing.assert_allclose(r2[k], straight[k],
                                   rtol=1e-5, atol=1e-2)

    # identity is still enforced through the elastic path
    with pytest.raises(ValueError, match="different configuration"):
        ckpt.load_elastic(p1, cfg(n_chains=8, seed=14))


# ---------------------------------------------------------------------------
# the async snapshot writer
# ---------------------------------------------------------------------------


def test_async_writer_matches_sync(tmp_path):
    """An async snapshot is byte-for-byte the same checkpoint a
    synchronous save would have written (same leaves, same resume
    point, same manifest discipline)."""
    sim = Simulation(cfg())
    next(sim.run_blocks())
    spath = str(tmp_path / "sync.npz")
    apath = str(tmp_path / "async.npz")
    ckpt.save(spath, sim.state, 1, sim.config)
    reg = MetricsRegistry()
    with use_registry(reg):
        w = ckpt.AsyncCheckpointWriter(apath, config=sim.config)
        w.submit(sim.state, 1)
        assert w.flush(timeout=60)
        w.close(timeout=60)
    sa, na = ckpt.load(apath, cfg())
    ss, ns = ckpt.load(spath, cfg())
    assert na == ns == 1
    _state_eq(sa, ss)
    assert reg.snapshot()["counters"]["checkpoint.async_saves_total"] \
        == 1.0


def test_async_writer_latest_wins(tmp_path, monkeypatch):
    """Submitting while a snapshot is still writing replaces the queued
    one (depth-1 latest-wins): a slow disk degrades checkpoint cadence,
    never correctness — the newest submitted state is what lands."""
    gate = threading.Event()
    entered = threading.Event()
    real = ckpt._write_generation

    def slow(*a, **kw):
        entered.set()
        assert gate.wait(30)
        return real(*a, **kw)

    monkeypatch.setattr(ckpt, "_write_generation", slow)
    path = str(tmp_path / "lw.npz")
    state = {"x": np.arange(6)}
    reg = MetricsRegistry()
    with use_registry(reg):
        w = ckpt.AsyncCheckpointWriter(path, keep=5)
        w.submit(state, 1)
        assert entered.wait(10)  # writer busy on snapshot 1
        w.submit(state, 2)       # queued
        w.submit(state, 3)       # replaces 2: latest wins
        gate.set()
        w.close(timeout=60)
    _, nb = ckpt.load(path)
    assert nb == 3
    c = reg.snapshot()["counters"]
    assert c["checkpoint.async_dropped_total"] == 1.0
    assert c["checkpoint.async_saves_total"] == 2.0


def test_async_writer_close_raises_on_final_failure(tmp_path,
                                                    monkeypatch):
    """A run must not finish pretending its last snapshot is durable:
    close() re-raises when the final background write failed."""
    def boom(*a, **kw):
        raise RuntimeError("disk full")

    monkeypatch.setattr(ckpt, "_write_generation", boom)
    reg = MetricsRegistry()
    with use_registry(reg):
        w = ckpt.AsyncCheckpointWriter(str(tmp_path / "x.npz"))
        w.submit({"x": np.arange(3)}, 1)
        with pytest.raises(ckpt.CheckpointError,
                           match="final async checkpoint write failed"):
            w.close(timeout=60)
    assert reg.snapshot()["counters"][
        "checkpoint.async_write_failures_total"] == 1.0


@pytest.mark.slow
def test_async_overhead_within_two_percent(tmp_path):
    """Acceptance: at 65536 chains the async writer's steady-state cost
    per block is <= 2% of the block wall.  What the async design adds to
    the simulation thread is only the synchronous host gather in
    submit(); the npz serialization and hashing happen on the writer
    thread and overlap the next block's device compute.  On this 1-core
    CI host that overlap would instead serialize with the next block, so
    the test times submit() directly and drains the writer between
    blocks to keep the background write out of the measured region."""
    c = cfg(n_chains=65536, duration_s=4 * 600, block_s=600,
            output="reduce", block_impl="scan", scan_unroll=1)

    ticks = []
    Simulation(c).run_reduced(
        on_block=lambda bi, state, acc: ticks.append(time.perf_counter()))
    base = min(b - a for a, b in zip(ticks, ticks[1:]))
    # min: robust to GC/OS noise; skips the compile-laden first block

    writer = ckpt.AsyncCheckpointWriter(str(tmp_path / "ck.npz"),
                                        config=c)
    submit_costs = []

    def on_block(bi, state, acc):
        t0 = time.perf_counter()
        writer.submit({"state": state, "acc": acc}, bi + 1)
        submit_costs.append(time.perf_counter() - t0)
        writer.flush(timeout=600)

    Simulation(c).run_reduced(on_block=on_block)
    writer.close(timeout=600)
    assert min(submit_costs) <= base * 0.02 + 0.05, (base, submit_costs)


# ---------------------------------------------------------------------------
# CLI wiring: --checkpoint-keep / --checkpoint-async / --preempt-grace
# ---------------------------------------------------------------------------


def test_cli_checkpoint_keep_rotation(tmp_path):
    out = tmp_path / "out.csv"
    ck = tmp_path / "ck.npz"
    r = _cli_jax(str(out), "--checkpoint", str(ck),
                 "--checkpoint-keep", "2")
    assert r.exit_code == 0, r.output
    man = ckpt.read_manifest(str(ck))
    assert man["keep"] == 2 and man["latest"] == 3  # 3 blocks saved
    assert [e["gen"] for e in man["generations"]] == [2, 3]
    assert not (tmp_path / "ck.npz.g1").exists()


def test_cli_checkpoint_async_output_identical(tmp_path):
    """--checkpoint-async on must not perturb the simulation output, and
    the final background snapshot must be durable at exit."""
    whole = tmp_path / "whole.csv"
    r = _cli_jax(str(whole))
    assert r.exit_code == 0, r.output
    out = tmp_path / "async.csv"
    ck = tmp_path / "ck.npz"
    r = _cli_jax(str(out), "--checkpoint", str(ck),
                 "--checkpoint-async", "on")
    assert r.exit_code == 0, r.output
    assert out.read_bytes() == whole.read_bytes()
    assert ckpt.peek_meta(str(ck))["next_block"] == 3


def test_cli_checkpoint_knob_guards(tmp_path):
    out = str(tmp_path / "o.csv")
    r = CliRunner().invoke(cli_main, [
        "pvsim", out, "--backend=jax", "--no-realtime",
        "--duration", "360", "--checkpoint-keep", "0"])
    assert r.exit_code != 0
    assert "--checkpoint-keep must be >= 1" in r.output
    r = CliRunner().invoke(cli_main, [
        "pvsim", out, "--backend=jax", "--no-realtime",
        "--duration", "360", "--preempt-grace", "-1"])
    assert r.exit_code != 0
    assert "--preempt-grace must be >= 0" in r.output
    r = CliRunner().invoke(cli_main, [
        "pvsim", out, "--checkpoint-async", "on"])
    assert r.exit_code != 0
    assert "--checkpoint-async requires --backend=jax" in r.output
