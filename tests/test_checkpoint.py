"""Checkpoint/resume: exact-resume guarantee and config safety."""

import csv

import numpy as np
import pytest
from click.testing import CliRunner

from tmhpvsim_tpu.config import SimConfig
from tmhpvsim_tpu.engine import Simulation
from tmhpvsim_tpu.engine import checkpoint as ckpt
from tmhpvsim_tpu.cli import main as cli_main


def cfg(**kw):
    base = dict(
        start="2019-09-05 10:00:00",
        duration_s=1800,
        n_chains=2,
        seed=13,
        block_s=600,
        dtype="float32",
    )
    base.update(kw)
    return SimConfig(**base)


def test_roundtrip_identical_state(tmp_path):
    sim = Simulation(cfg())
    it = sim.run_blocks()
    next(it)
    path = str(tmp_path / "state.npz")
    ckpt.save(path, sim.state, 1, sim.config)
    state, nb = ckpt.load(path, sim.config)
    assert nb == 1
    # every leaf identical
    flat_a = ckpt._flatten(sim.state)
    flat_b = ckpt._flatten(state)
    assert flat_a.keys() == flat_b.keys()
    for k in flat_a:
        np.testing.assert_array_equal(flat_a[k], flat_b[k])


def test_resume_bit_exact(tmp_path):
    """save -> new process-equivalent -> load -> remaining blocks match an
    uninterrupted run exactly."""
    straight = [b.pv for b in Simulation(cfg()).run_blocks()]

    a = Simulation(cfg())
    it = a.run_blocks()
    next(it)
    path = str(tmp_path / "s.npz")
    ckpt.save(path, a.state, 1, a.config)

    b = Simulation(cfg())  # fresh instance, as after a restart
    state, nb = ckpt.load(path, b.config)
    resumed = [blk.pv for blk in b.run_blocks(state=state, start_block=nb)]
    assert len(resumed) == 2
    np.testing.assert_array_equal(resumed[0], straight[1])
    np.testing.assert_array_equal(resumed[1], straight[2])


def test_config_mismatch_rejected(tmp_path):
    sim = Simulation(cfg())
    next(sim.run_blocks())
    path = str(tmp_path / "s.npz")
    ckpt.save(path, sim.state, 1, sim.config)
    with pytest.raises(ValueError, match="different configuration"):
        ckpt.load(path, cfg(seed=14))


def test_cli_checkpoint_resume(tmp_path):
    """Interrupted CLI run + resumed run == single run, row for row."""
    whole = tmp_path / "whole.csv"
    r = CliRunner().invoke(cli_main, [
        "pvsim", str(whole), "--backend=jax", "--duration", "360",
        "--seed", "9", "--start", "2019-09-05 10:00:00",
    ])
    assert r.exit_code == 0, r.output

    # simulate an interrupt: run only the first block by running a shorter
    # duration against the same checkpoint file, then the full duration
    part = tmp_path / "part.csv"
    ck = tmp_path / "ck.npz"

    cfg_ = SimConfig(start="2019-09-05 10:00:00", duration_s=360,
                     n_chains=1, seed=9, block_s=180)
    from tmhpvsim_tpu.engine import Simulation as Sim
    from tmhpvsim_tpu.engine.simulation import write_csv
    from zoneinfo import ZoneInfo

    s = Sim(cfg_)
    it = s.run_blocks()
    first = next(it)
    write_csv(str(part), iter([first]), tz=ZoneInfo("Europe/Berlin"))
    ckpt.save(str(ck), s.state, 1, cfg_)

    s2 = Sim(cfg_)
    state, nb = ckpt.load(str(ck), cfg_)
    rest = list(s2.run_blocks(state=state, start_block=nb))
    write_csv(str(part), iter(rest), tz=ZoneInfo("Europe/Berlin"),
              append=True)

    with open(part) as f:
        part_rows = list(csv.reader(f))
    # independent straight run at the same block size for comparison
    whole2 = tmp_path / "whole2.csv"
    s3 = Sim(cfg_)
    write_csv(str(whole2), s3.run_blocks(), tz=ZoneInfo("Europe/Berlin"))
    with open(whole2) as f:
        whole_rows = list(csv.reader(f))
    assert part_rows == whole_rows
    assert len(part_rows) == 1 + 360
