"""Offline fitting pipeline: synthetic round-trips (the reference's
pipeline is broken and untested; SURVEY.md §2.2/§3.4)."""

import numpy as np
import pytest

from tmhpvsim_tpu.data import MARKOV_STEP_BINS, MARKOV_STEP_PARAMS
from tmhpvsim_tpu.models.markov_hourly import chain_numpy
from tmhpvsim_tpu.offline import fitting


def sample_al(rng, loc, scale, kappa, n):
    """Inverse-CDF sampler of the reference's asymmetric Laplace."""
    u = rng.uniform(size=n)
    k2 = kappa * kappa
    lo = kappa * np.log((1 + k2) / k2 * u)
    hi = -np.log((1 + k2) * (1 - u)) / kappa
    return loc + scale * np.where(u < k2 / (1 + k2), lo, hi)


class TestALFit:
    def test_recovers_parameters(self):
        rng = np.random.default_rng(0)
        x = sample_al(rng, loc=0.02, scale=0.05, kappa=1.8, n=20_000)
        fit = fitting.fit_asymmetric_laplace(x)
        assert fit.loc == pytest.approx(0.02, abs=0.01)
        assert fit.scale == pytest.approx(0.05, rel=0.1)
        assert fit.kappa == pytest.approx(1.8, rel=0.1)

    def test_skewness_direction(self):
        rng = np.random.default_rng(1)
        right_heavy = sample_al(rng, 0.0, 0.1, 0.5, 5000)   # kappa<1
        left_heavy = sample_al(rng, 0.0, 0.1, 2.0, 5000)    # kappa>1
        assert fitting.fit_asymmetric_laplace(right_heavy).kappa < 1
        assert fitting.fit_asymmetric_laplace(left_heavy).kappa > 1


class TestTFit:
    def test_recovers_parameters(self):
        rng = np.random.default_rng(2)
        x = 0.01 + 0.17 * rng.standard_t(df=8, size=20_000)
        fit = fitting.fit_student_t(x)
        assert fit.loc == pytest.approx(0.01, abs=0.01)
        assert fit.scale == pytest.approx(0.17, rel=0.1)
        assert 4 < fit.df < 16


class TestSelection:
    def test_aic_prefers_al_for_al_data(self):
        rng = np.random.default_rng(3)
        x = sample_al(rng, 0.0, 0.04, 2.2, 10_000)
        fit = fitting.fit_bin(x)
        assert not fit.is_t

    def test_thin_bin_returns_none(self):
        assert fitting.fit_bin(np.zeros(5)) is None


class TestPipeline:
    def test_round_trip_from_synthetic_chain(self):
        """Generate a year of hourly cloud cover with the shipped params,
        re-fit, and require agreement for the well-populated bins."""
        rng = np.random.default_rng(4)
        series = chain_numpy(rng, 5 * 8760, initial_state=0.5)
        fits = fitting.fit_all(series)
        params = np.asarray(MARKOV_STEP_PARAMS)
        checked = 0
        for b, fit in enumerate(fits):
            if fit is None or fit.n < 2000:
                continue
            loc, scale = params[b, 0], params[b, 1]
            assert fit.loc == pytest.approx(loc, abs=0.05)
            assert fit.scale == pytest.approx(scale, rel=0.6)
            checked += 1
        assert checked >= 3  # the chain dwells in several bins over 5 years

    def test_bin_membership_matches_runtime(self):
        """bin_steps uses the same searchsorted convention as the chain."""
        series = np.asarray([0.05, 0.5, 0.95, 1.0, 0.05])
        per_bin = fitting.bin_steps(series)
        assert per_bin[0].size == 1   # from 0.05
        assert per_bin[2].size == 1   # from 0.5
        assert per_bin[4].size == 1   # from 0.95
        assert per_bin[5].size == 1   # from 1.0

    def test_format_table(self):
        rng = np.random.default_rng(5)
        series = chain_numpy(rng, 8760)
        out = fitting.format_params_table(fitting.fit_all(series))
        assert out.startswith("MARKOV_STEP_PARAMS = (")
        assert out.count("\n") >= 12


def test_load_csv(tmp_path):
    p = tmp_path / "tcc.csv"
    np.savetxt(p, np.asarray([10.0, 50.0, 90.0]), delimiter=",")
    v = fitting.load_total_cloud_cover(str(p))
    np.testing.assert_allclose(v, [0.1, 0.5, 0.9])


class TestEra5Retrieval:
    """retrieve_total_cloud_cover against a fake cdsapi (the real one and
    CDS credentials don't exist here): request contract + cache behaviour,
    mirroring the reference's download step (cloud_cover_hourly.py:41-91)."""

    def _install_fake(self, monkeypatch, calls):
        import sys
        import types

        mod = types.ModuleType("cdsapi")

        class Client:
            def retrieve(self, dataset, request, target):
                calls.append((dataset, request, target))
                with open(target, "w") as f:
                    f.write("netcdf-bytes")

        mod.Client = Client
        monkeypatch.setitem(sys.modules, "cdsapi", mod)

    def test_request_contract(self, tmp_path, monkeypatch):
        calls = []
        self._install_fake(monkeypatch, calls)
        target = str(tmp_path / "tcc.nc")
        out = fitting.retrieve_total_cloud_cover(target, years=(2018, 2019))
        assert out == target
        [(dataset, request, tgt)] = calls
        assert dataset == fitting.ERA5_DATASET
        assert request["variable"] == fitting.ERA5_VARIABLE
        assert request["year"] == ["2018", "2019"]
        assert len(request["month"]) == 12 and len(request["time"]) == 24
        assert request["area"] == list(fitting.ERA5_AREA_MUNICH)
        assert tgt == target

    def test_cache_short_circuits(self, tmp_path, monkeypatch):
        calls = []
        self._install_fake(monkeypatch, calls)
        target = tmp_path / "tcc.nc"
        target.write_text("already here")
        fitting.retrieve_total_cloud_cover(str(target))
        assert calls == []  # no download when the file exists
        assert target.read_text() == "already here"

    def test_clear_error_without_cdsapi(self, tmp_path):
        import sys

        assert "cdsapi" not in sys.modules  # image really lacks it
        with pytest.raises(RuntimeError, match="cdsapi"):
            fitting.retrieve_total_cloud_cover(str(tmp_path / "x.nc"))
