"""geom_stride (Plan.geom_stride): strided solar geometry + 1 Hz lerp.

Accuracy strategy mirrors tests/test_solar.py: no pvlib — the oracle is
the repo's own per-second chain evaluated in numpy float64, against
which the stride-60 lerp must stay inside the published per-field
bounds (models/solar.py STRIDE_MAX_ABS_ERR) over solstice/equinox days
at equatorial, mid-latitude and polar sites.  End-to-end, a strided run
must hold the field-scale 1e-5 reduce-stats contract vs stride=1, and
``geom_stride=1`` must lower to byte-identical HLO (the lever is
structurally absent at the default, not branched around).
"""

import datetime as dt

import numpy as np
import pytest

from tmhpvsim_tpu.config import Site, SimConfig, SiteGrid
from tmhpvsim_tpu.engine import Simulation
from tmhpvsim_tpu.models import solar

# day starts (UTC) hitting both solstices and an equinox
DAYS = [(2025, 3, 20), (2025, 6, 21), (2025, 12, 21)]

# (name, latitude, longitude): the three geometry regimes — equatorial
# fast azimuth swing, mid-latitude reference, polar low-sun/midnight-sun
SITES = [
    ("equatorial", 0.0, 11.6),
    ("mid-latitude", 48.12, 11.6),
    ("polar", 70.0, 20.0),
]


def epoch(*args):
    return dt.datetime(*args, tzinfo=dt.timezone.utc).timestamp()


def day_grid(date_args):
    # the engine ships the true calendar day-of-year; the exact value
    # only keys the Spencer/turbidity terms and both paths get the SAME
    # one, so a constant UTC day index is fine for the oracle comparison
    t0 = epoch(*date_args)
    t = t0 + np.arange(0.0, 86400.0)
    d = dt.datetime(*date_args, tzinfo=dt.timezone.utc).timetuple().tm_yday
    return t, np.full_like(t, float(d))


def site(lat, lon):
    return Site(latitude=lat, longitude=lon, altitude=34.0,
                surface_tilt=30.0, surface_azimuth=180.0)


class TestOracleBounds:
    @pytest.mark.parametrize("day", DAYS, ids=[f"{m:02d}-{d:02d}"
                                               for _, m, d in DAYS])
    @pytest.mark.parametrize("name,lat,lon", SITES,
                             ids=[s[0] for s in SITES])
    def test_stride60_inside_published_bounds(self, name, lat, lon, day):
        t, doy = day_grid(day)
        s = site(lat, lon)
        oracle = solar.block_geometry(t, doy, s, xp=np)
        strided = solar.strided_block_geometry(t, doy, s, 60, xp=np)
        daytime = oracle["cos_zenith"] >= 0.01
        if not daytime.any():  # polar winter: nothing the bound covers
            pytest.skip("polar night — no daytime seconds")
        for field, bound in solar.STRIDE_MAX_ABS_ERR.items():
            err = np.abs(strided[field] - oracle[field])[daytime].max()
            assert err <= bound, (field, err, bound)

    def test_stride30_tighter_than_stride60(self):
        t, doy = day_grid((2025, 6, 21))
        s = site(48.12, 11.6)
        oracle = solar.block_geometry(t, doy, s, xp=np)
        s30 = solar.strided_block_geometry(t, doy, s, 30, xp=np)
        daytime = oracle["cos_zenith"] >= 0.01
        for field, bound in solar.STRIDE_MAX_ABS_ERR.items():
            err = np.abs(s30[field] - oracle[field])[daytime].max()
            assert err <= bound, (field, err, bound)

    def test_stride1_is_block_geometry(self):
        t, doy = day_grid((2025, 3, 20))
        s = site(48.12, 11.6)
        a = solar.block_geometry(t, doy, s, xp=np)
        b = solar.strided_block_geometry(t, doy, s, 1, xp=np)
        for k in a:
            np.testing.assert_array_equal(np.asarray(a[k]),
                                          np.asarray(b[k]), err_msg=k)

    def test_azimuth_held_not_lerped(self):
        # azimuth wraps at 2pi: it must be the left sample, never a lerp
        t, doy = day_grid((2025, 6, 21))
        s = site(0.0, 11.6)  # equatorial: fastest azimuth swing
        strided = solar.strided_block_geometry(t, doy, s, 60, xp=np)
        samples = solar.block_geometry(
            np.concatenate([t[::60], t[-1:] + 1.0]),
            np.concatenate([doy[::60], doy[-1:]]), s, xp=np)
        np.testing.assert_array_equal(
            strided["azimuth"], samples["azimuth"][np.arange(86400) // 60])

    def test_bad_stride_rejected(self):
        t, doy = day_grid((2025, 3, 20))
        s = site(48.12, 11.6)
        with pytest.raises(ValueError, match="geom_stride"):
            solar.strided_block_geometry(t, doy, s, 45, xp=np)
        with pytest.raises(ValueError, match="multiple"):
            solar.strided_block_geometry(t[:90], doy[:90], s, 60, xp=np)


# ---------------------------------------------------------------------------
# engine integration: reduce-stats contract, both geometry paths
# ---------------------------------------------------------------------------

def cfg(**kw):
    # 2 daylight blocks (08:00-12:48) keep the default lane fast; the
    # slow lane (site grid here, the full year below) re-runs the
    # contract at scale
    base = dict(
        start="2019-09-05 08:00:00",
        duration_s=2 * 8640,
        n_chains=4,
        seed=7,
        block_s=8640,
        dtype="float32",
        block_impl="scan2",
        output="reduce",
    )
    base.update(kw)
    return SimConfig(**base)


def grid():
    return SiteGrid(
        latitude=(0.0, 48.12, 52.5, 70.0),
        longitude=(11.6, 11.6, 13.4, 20.0),
        altitude=(10.0, 520.0, 34.0, 5.0),
        surface_tilt=(10.0, 30.0, 35.0, 60.0),
        surface_azimuth=(180.0, 180.0, 175.0, 180.0),
    )


def assert_field_scale_close(a: dict, b: dict, rtol=1e-5):
    """Every statistic within ``rtol`` of the run's field scale — the
    contract is relative to the magnitude of the quantity (mean |pv| or
    the stat's own scale), not elementwise."""
    assert set(a) == set(b)
    for k in a:
        x, y = np.asarray(a[k], np.float64), np.asarray(b[k], np.float64)
        scale = max(np.abs(x).max(), np.abs(y).max(), 1.0)
        assert np.abs(x - y).max() <= rtol * scale, (
            k, np.abs(x - y).max(), scale)


class TestEngineContract:
    @pytest.mark.parametrize("impl", ["wide", "scan", "scan2"])
    def test_shared_site_stride60_field_scale(self, impl):
        base = Simulation(cfg(block_impl=impl)).run_reduced()
        fast = Simulation(cfg(block_impl=impl,
                              geom_stride=60)).run_reduced()
        assert_field_scale_close(base, fast)

    @pytest.mark.parametrize("impl", ["wide", "scan", "scan2"])
    def test_site_grid_stride60_field_scale(self, impl):
        base = Simulation(cfg(block_impl=impl,
                              site_grid=grid())).run_reduced()
        fast = Simulation(cfg(block_impl=impl, site_grid=grid(),
                              geom_stride=60)).run_reduced()
        assert_field_scale_close(base, fast)

    def test_composes_with_rng_block(self):
        base = Simulation(cfg()).run_reduced()
        fast = Simulation(cfg(geom_stride=60,
                              rng_batch="block")).run_reduced()
        assert_field_scale_close(base, fast)

    def test_bad_value_rejected(self):
        with pytest.raises(ValueError, match="geom_stride"):
            Simulation(cfg(geom_stride=45))

    def test_plan_carries_resolved_axis(self):
        assert Simulation(cfg()).plan.geom_stride == 1
        assert Simulation(cfg(geom_stride=60)).plan.geom_stride == 60

    def test_precision_doc_carries_axis(self):
        doc = Simulation(cfg(geom_stride=60)).precision_doc()
        assert doc is not None and doc["geom_stride"] == 60


@pytest.mark.slow
class TestFullYearContract:
    def test_stride60_field_scale_over_a_year(self):
        """The acceptance contract: a full simulated year of strided
        geometry stays within field-scale 1e-5 of the per-second run on
        every reduce statistic (errors are bounded per second and
        uncorrelated across stride windows, so the year-long
        accumulation is where a systematic bias would surface)."""
        year = dict(duration_s=365 * 86400, n_chains=2, block_s=86400)
        base = Simulation(cfg(**year)).run_reduced()
        fast = Simulation(cfg(geom_stride=60, **year)).run_reduced()
        assert_field_scale_close(base, fast)


class TestDefaultHLOIdentity:
    @pytest.mark.parametrize("impl", ["scan", "scan2"])
    def test_stride1_lowers_byte_identical_to_default(self, impl):
        default = Simulation(cfg(block_impl=impl, n_chains=4,
                                 site_grid=grid()))
        explicit = Simulation(cfg(block_impl=impl, n_chains=4,
                                  site_grid=grid(), geom_stride=1))
        state = default.init_state()
        acc = default.init_reduce_acc()
        inputs, _ = default.host_inputs(0)
        jit = f"_{impl}_acc_jit"
        a = getattr(default, jit).lower(state, inputs, acc).as_text()
        b = getattr(explicit, jit).lower(state, inputs, acc).as_text()
        assert a == b
