"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so the multi-chip sharding layer
(`tmhpvsim_tpu.parallel`) is exercised without TPU hardware — the standard
JAX answer to testing `shard_map`/mesh logic (see SURVEY.md §4).  The env
vars must be set before `jax` is imported anywhere in the test process.
"""

import os

# Force CPU: the environment pins JAX_PLATFORMS=axon (remote TPU tunnel),
# which must never be used from tests — it serialises on one remote chip.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The environment's sitecustomize (PYTHONPATH=/root/.axon_site) imports jax
# during interpreter startup — *before* this conftest runs — so the env-var
# override above comes too late for the platform choice jax captured at
# import.  Backends initialise lazily, so updating the config here still
# redirects everything to CPU; assert no device backend has been created yet
# (if it has, tests would silently run on the remote chip).
jax.config.update("jax_platforms", "cpu")
if jax._src.xla_bridge.backends_are_initialized():
    # Too late to redirect — only acceptable if the chosen backend is
    # already CPU (the hazard is the remote 'axon' chip, not CPU itself).
    assert jax.default_backend() == "cpu", (
        "a non-CPU JAX backend initialised before conftest could force CPU"
    )

jax.config.update("jax_enable_x64", True)  # float64 golden paths on CPU

# Persistent compilation cache: the suite compiles dozens of jit variants
# (block steps x formulations x shardings); without a disk cache every run
# re-pays ~15 s x each on this 1-core host (round-4 verdict: 513 s for
# test_engine alone).  The cache key includes backend + XLA flags, so the
# 8-virtual-device CPU entries never collide with TPU entries.
_cache_dir = os.path.abspath(
    os.path.join(os.path.dirname(__file__), os.pardir, ".jax_cache")
)
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
# ... and through the environment too, so the CLI/app/distributed tests'
# SUBPROCESSES (which never import this conftest) share the same cache.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.3")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
# The warm-start executor (engine/compilecache.py) resolves its own cache
# base from this env var; point it at the same repo-local directory so
# CLI/app tests (in-process and subprocess) never write under ~/.cache.
os.environ.setdefault("TMHPVSIM_COMPILE_CACHE", _cache_dir)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False,
        help="also run tests marked slow (full-depth statistical / "
             "multi-process suites)",
    )


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: compile- or wall-time-heavy test, needs --runslow"
    )


#: The slow lane (round-4 verdict: the default suite must iterate fast on
#: this 1-core host; full depth runs under --runslow).  Central registry
#: by test name (parametrized variants included via originalname) rather
#: than per-file decorators so the lane's contents are auditable in one
#: place.  Every entry has a faster sibling covering the same code path
#: at a smaller shape; entries are the >3 s offenders of a cache-warm
#: full run (.pytest_full2.log, 2026-07-30).
_SLOW_LANE = {
    # real two-process jax.distributed runs (the smoke test stays fast)
    "test_two_process_sharded_simulation",
    "test_two_process_checkpoint_kill_resume",
    "test_two_process_straggler_detection",
    # full-depth statistical / golden parity (KS, moments, soak)
    "test_distributional_parity_with_jax_path",
    "test_transition_kernel_parity_with_numpy_golden",
    "test_mean_parity_4se",
    "test_csi_moments_f32_vs_f64",
    "test_soak_25h_reference_invariant",
    "test_csi_range_invariant",
    "test_block_split_invariance",
    "test_compat_modes_run",
    # cross-formulation equivalence at full block shapes
    "test_alt_topologies_match_split",
    "test_matches_single_chip",
    "test_sharded_matches_single_chip",
    "test_ensemble_scan_matches_wide_sharded",
    "test_scan_impl_matches_wide_site_grid",
    "test_scan2_impl_matches_scan",
    "test_ensemble_scan2_matches_scan",
    "test_scan_impl_matches_wide",
    "test_ensemble_scan_matches_wide",
    "test_fused_stats_topology_matches_split",
    "test_sharded_ensemble_mode_matches_single",
    "test_step_reduced_matches_base",
    "test_ensemble_psum_is_global_mean",
    "test_block_size_invariance",
    # subprocess/e2e app + checkpoint flows (cheaper siblings stay)
    "test_cli_pvsim_profile_writes_trace",
    "test_cli_pvsim_jax_realtime_paces",
    "test_cli_reduce_checkpoint_crash_resume",
    "test_cli_checkpoint_crash_resume",
    "test_three_process_deployment",
    "test_resume_bit_exact",
    "test_reduce_resume_bit_exact",
    "test_resume_bit_exact_rbg_keys",
    "test_resume_bit_exact_across_dst_boundary",
    "test_resume_equals_straight_run",
    # site-grid engine at full shapes
    "test_identical_grid_matches_shared_site",
    "test_checkpoint_echo_catches_grid_change",
    "test_end_to_end_block",
    # multi-day calendar-transition + latitude-extreme soaks
    # (tests/test_calendar_edges.py)
    "test_calendar_edge_soak",
    "test_latitude_extreme_soak",
    # mid-weight tier moved to keep the default lane ~2 min on this
    # 1-core host; each has a cheaper fast-lane sibling
    "test_sensitivity_rejects_swapped_branches",
    "test_sharded_reduce_resume_with_zero_blocks_left",
    "test_counts_only_valid_seconds",
    "test_sites_actually_differ",
    "test_rbg_keys_survive_configless_save",
    "test_cli_pvsim_site_grid",
    # obs acceptance: two full-size timed arms (enabled vs disabled
    # registry) at 65536 chains on CPU
    "test_metrics_overhead_65536_chains",
    # telemetry acceptance: same shape, light-vs-off arms
    "test_telemetry_overhead_65536_chains",
    # fleet-analytics acceptance: same shape, risk-vs-off arms
    "test_analytics_overhead_65536_chains",
    # trace acceptance: disabled-tracer engine arm at 65536 chains plus
    # a 10k-record join-throughput arm
    "test_trace_disabled_overhead_65536_chains",
    # warm-start executor acceptance: two full-size timed arms (fused vs
    # per-block dispatch) at 65536 chains on CPU
    "test_fused_dispatch_no_slower_65536_chains",
    # live-ops acceptance: trace-stamped vs off arms at 65536 chains
    "test_trace_stamp_overhead_65536_chains",
    # scan-restructuring heavy geometries: the fast lane keeps the
    # shared-site bit-identity / field-scale siblings at the same shape
    "test_site_grid_identical_to_ulps",
    "test_sharded_identical",
    "test_mega_dispatch_identical",
    "test_site_grid_stride60_field_scale",
    # 2-D (chains, scenario) mesh: the full impl x telemetry x fleet
    # bit-identity matrix (the fast lane keeps the default-path sibling
    # test_nm_mesh_matches_1d_and_single and the (N,1) HLO-identity bar)
    "test_mesh2d_matrix_bit_identical",
    # real two-process jax.distributed elastic-resume runs (the fast
    # lane keeps the single-process load_elastic tests in
    # tests/test_checkpoint.py)
    "test_two_process_elastic_resume",
    "test_million_site_two_host_elastic",
    # serving-fleet chaos soak: SIGKILL + respawn + tcp partition over a
    # 2-worker fleet (~75 s; the fast lane keeps the single-server soak
    # and the sync-stubbed failover tests in tests/test_router.py)
    "test_worker_kill_partition_exactly_once_warm_respawn",
}


def pytest_collection_modifyitems(config, items):
    run_slow = config.getoption("--runslow")
    skip = pytest.mark.skip(reason="slow: run with --runslow")
    for item in items:
        name = getattr(item, "originalname", None) or item.name
        if name in _SLOW_LANE:
            item.add_marker(pytest.mark.slow)
        if not run_slow and "slow" in item.keywords:
            item.add_marker(skip)


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


@pytest.fixture(autouse=True)
def _compilecache_isolation():
    """Restore the warm-start executor's process-global cache state after
    every test.

    The persistent-cache layer (engine/compilecache.py) is process-global
    by design — one active cache dir per process.  In-process app/CLI
    tests call ``compilecache.configure()``, and without this restore the
    residue would make EVERY later ``Simulation`` in the suite pay AOT
    warm-up, and tests that point the cache at a tmp dir would redirect
    the whole suite's compilation cache away from ``.jax_cache``."""
    from tmhpvsim_tpu.engine import compilecache

    # NOT the "listener" key: the jax.monitoring listener is append-only
    # (no unregister API); resetting it to None would make a later
    # configure() register a duplicate and double-count warm/cold events.
    saved_state = {k: compilecache._state[k]
                   for k in ("dir", "configured", "cost")}
    saved_cfg = {
        k: getattr(jax.config, k)
        for k in ("jax_compilation_cache_dir",
                  "jax_persistent_cache_min_compile_time_secs",
                  "jax_persistent_cache_min_entry_size_bytes")
    }
    yield
    dir_changed = (jax.config.jax_compilation_cache_dir
                   != saved_cfg["jax_compilation_cache_dir"])
    for k, v in saved_cfg.items():
        jax.config.update(k, v)
    compilecache._state.update(saved_state)
    if dir_changed:
        compilecache._reset_cache_singleton()
