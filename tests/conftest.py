"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so the multi-chip sharding layer
(`tmhpvsim_tpu.parallel`) is exercised without TPU hardware — the standard
JAX answer to testing `shard_map`/mesh logic (see SURVEY.md §4).  The env
vars must be set before `jax` is imported anywhere in the test process.
"""

import os

# Force CPU: the environment pins JAX_PLATFORMS=axon (remote TPU tunnel),
# which must never be used from tests — it serialises on one remote chip.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The environment's sitecustomize (PYTHONPATH=/root/.axon_site) imports jax
# during interpreter startup — *before* this conftest runs — so the env-var
# override above comes too late for the platform choice jax captured at
# import.  Backends initialise lazily, so updating the config here still
# redirects everything to CPU; assert no device backend has been created yet
# (if it has, tests would silently run on the remote chip).
jax.config.update("jax_platforms", "cpu")
if jax._src.xla_bridge.backends_are_initialized():
    # Too late to redirect — only acceptable if the chosen backend is
    # already CPU (the hazard is the remote 'axon' chip, not CPU itself).
    assert jax.default_backend() == "cpu", (
        "a non-CPU JAX backend initialised before conftest could force CPU"
    )

jax.config.update("jax_enable_x64", True)  # float64 golden paths on CPU

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(1234)
