"""Binary cloud renewal process: invariants + TPU-kernel vs faithful-reference
statistics."""

import jax
import jax.numpy as jnp
import numpy as np

from tmhpvsim_tpu.models import renewal


def _run_tpu(key, n, cc, ws, dtype=jnp.float64):
    k_init, k_run = jax.random.split(jax.random.key(key))
    carry = renewal.init(k_init, cc, ws, dtype)

    def body(c, k):
        c, cov = renewal.step(c, k, cc, ws, dtype)
        return c, cov

    _, covered = jax.lax.scan(body, carry, jax.random.split(k_run, n))
    return np.asarray(covered)


def test_tpu_kernel_binary_and_alternating():
    cov = _run_tpu(0, 20_000, cc=0.5, ws=5.0)
    assert set(np.unique(cov)) <= {0.0, 1.0}
    # both phases occur
    assert 0.05 < cov.mean() < 0.95


def test_tpu_cloud_fraction_tracks_cloudcover():
    """Long-run cloud fraction ~= capped hourly cloud cover (constraint (2))."""
    for cc in (0.2, 0.5, 0.8, 0.99):
        cov = _run_tpu(int(cc * 100), 400_000, cc=cc, ws=6.0)
        target = min(cc, renewal.MAX_CLOUDCOVER)
        assert abs(cov.mean() - target) < 0.08, (cc, cov.mean())


def test_reference_impl_fraction_and_bounds():
    rng = np.random.default_rng(5)
    for cc in (0.3, 0.7, 0.95):
        proc = renewal.ReferenceRenewal(cc, 6.0, rng)
        n = 400_000
        vals = np.fromiter((next(proc) for _ in range(n)), dtype=np.int64, count=n)
        assert set(np.unique(vals)) <= {0, 1}
        assert abs(vals.mean() - min(cc, 0.95)) < 0.08, (cc, vals.mean())


def test_reference_impl_low_cloudcover_no_crash():
    """cc below 1/12 crashes the reference algorithm; our guard keeps it alive."""
    proc = renewal.ReferenceRenewal(0.01, 5.0, np.random.default_rng(0))
    vals = [next(proc) for _ in range(10_000)]
    assert np.mean(vals) < 0.2


def test_tpu_vs_reference_cycle_length_distribution():
    """Cloud-interval transit times from both implementations follow the same
    truncated power law (compare log-spaced histograms loosely — the TPU
    kernel truncates at 5400*cc while the reference rejects+argmins, so we
    check order-of-magnitude agreement of the body of the distribution)."""
    cc, ws = 0.5, 6.0
    cov = _run_tpu(7, 300_000, cc=cc, ws=ws)
    # extract cloud run lengths
    change = np.diff(np.concatenate(([0], cov, [0])))
    starts = np.nonzero(change == 1)[0]
    ends = np.nonzero(change == -1)[0]
    tpu_runs = ends - starts

    rng = np.random.default_rng(11)
    proc = renewal.ReferenceRenewal(cc, ws, rng)
    ref = np.fromiter((next(proc) for _ in range(300_000)), dtype=np.int64,
                      count=300_000)
    change = np.diff(np.concatenate(([0], ref, [0])))
    ref_runs = np.nonzero(change == -1)[0] - np.nonzero(change == 1)[0]

    # medians within a factor of 3, both heavy-tailed
    m_tpu, m_ref = np.median(tpu_runs), np.median(ref_runs)
    assert m_ref / 3 < m_tpu < m_ref * 3, (m_tpu, m_ref)
    assert tpu_runs.max() > 10 * m_tpu
    assert ref_runs.max() > 10 * m_ref


def test_step_jit_vmap_shapes():
    """Kernel works vmapped over a chain batch inside jit."""
    n_chains = 16
    keys = jax.random.split(jax.random.key(0), n_chains)
    cc = jnp.linspace(0.1, 0.9, n_chains)
    ws = jnp.full((n_chains,), 5.0)
    carry = jax.vmap(lambda k, c, w: renewal.init(k, c, w))(keys, cc, ws)

    @jax.jit
    def advance(carry, keys):
        return jax.vmap(lambda c, k, ccc, www: renewal.step(c, k, ccc, www),
                        in_axes=(0, 0, 0, 0))(carry, keys, cc, ws)

    carry2, covered = advance(carry, jax.random.split(jax.random.key(1), n_chains))
    assert covered.shape == (n_chains,)
    assert jnp.all((covered == 0) | (covered == 1))
