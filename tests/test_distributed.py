"""Multi-host (multi-process) smoke tests: the pod-slice execution model.

The reference's only multi-process story is N consumers on one RabbitMQ
broker (SURVEY.md §2.4); the TPU-native equivalent is one JAX process per
host joined through ``jax.distributed`` (parallel/distributed.py), a mesh
spanning all hosts' devices, and collectives over ICI/DCN.

Real TPU pods aren't available in CI, so this does what the JAX ecosystem
does: two coordinated CPU processes on localhost, each owning 4 virtual
devices, forming one 8-device global mesh — which exercises exactly the
``process_count() > 1`` paths (initialize_from_env, cross-process psum,
local_chain_slice) that a pod slice uses.
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os
import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 4)
except AttributeError:  # jax < 0.5 spells it as an XLA flag
    import os as _os
    _os.environ["XLA_FLAGS"] = (_os.environ.get("XLA_FLAGS", "")
                                + " --xla_force_host_platform_device_count=4")
try:  # jax < 0.5: cross-process CPU collectives need the gloo opt-in
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except (AttributeError, ValueError):
    pass  # newer jax: gloo is the default

from tmhpvsim_tpu.parallel.distributed import (
    initialize_from_env, local_chain_slice,
)

assert initialize_from_env(), "env vars set; should have initialised"
assert jax.process_count() == 2, jax.process_count()
assert jax.local_device_count() == 4
assert jax.device_count() == 8

from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental import multihost_utils

from tmhpvsim_tpu.parallel import shard_map  # version-compat shim

from tmhpvsim_tpu.parallel import make_mesh
from tmhpvsim_tpu.parallel.mesh import CHAIN_AXIS

mesh = make_mesh()  # global: both processes' devices
assert mesh.devices.size == 8

# Each process contributes its local half of a 16-chain vector; the psum
# must see all 16 global chains -> the DCN analogue of the block step's
# ensemble reduction (parallel/mesh.py).
pid = jax.process_index()
local = np.full((8,), float(pid + 1))
arr = multihost_utils.host_local_array_to_global_array(
    local, mesh, P(CHAIN_AXIS)
)
total = jax.jit(shard_map(
    lambda x: jax.lax.psum(x.sum(), CHAIN_AXIS),
    mesh=mesh, in_specs=P(CHAIN_AXIS), out_specs=P(),
    check_vma=False,
))(arr)
assert float(total) == 8 * 1.0 + 8 * 2.0, float(total)

# Each host owns the contiguous half of the chain axis its devices hold.
sl = local_chain_slice(16, mesh)
expect = slice(0, 8) if pid == 0 else slice(8, 16)
assert (sl.start, sl.stop) == (expect.start, expect.stop), sl

print(f"DISTOK {pid}", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(src: str, timeout: float = 360.0, args=()):
    """Launch two coordinated worker processes running ``src``; return
    [(rc, stdout, stderr), ...].

    One retry on Gloo's 30 s context-init deadline: on this 1-core host
    the two workers' XLA compiles can starve the first cross-process
    collective past the (non-configurable) deadline — an infra timing
    flake, observed to pass on retry with warm compile caches.  Genuine
    failures don't match the signature and fail immediately.  Workers
    that take args (a workdir) may have written state before the flaky
    collective, so a rerun could resume from attempt 1's leftovers —
    those run once, no retry."""
    for attempt in (1, 2):
        outs = _run_workers_once(src, timeout, args)
        flaky = not args and any(
            rc != 0 and "Gloo context initialization failed" in (err or "")
            for rc, _, err in outs
        )
        if not flaky or attempt == 2:
            break
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout:{out}\nstderr:{err}"
    return outs


def _run_workers_once(src: str, timeout: float, args):
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(
            os.environ,
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES="2",
            JAX_PROCESS_ID=str(pid),
            JAX_PLATFORMS="cpu",
        )
        # the parent's 8-device XLA_FLAGS would fight jax_num_cpu_devices
        env.pop("XLA_FLAGS", None)
        # the axon sitecustomize (PYTHONPATH) eagerly initialises a backend,
        # which forbids jax.distributed.initialize afterwards — a TPU pod
        # launcher initialises distributed first, so drop it here
        env.pop("PYTHONPATH", None)
        for k in list(env):
            if k.startswith(("AXON_", "PALLAS_AXON_")):
                env.pop(k)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", src, *args], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        ))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return outs


def test_two_process_distributed_smoke():
    outs = _run_workers(_WORKER)
    assert "DISTOK 0" in outs[0][1]
    assert "DISTOK 1" in outs[1][1]


_SIM_WORKER = r"""
import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 4)
except AttributeError:  # jax < 0.5 spells it as an XLA flag
    import os as _os
    _os.environ["XLA_FLAGS"] = (_os.environ.get("XLA_FLAGS", "")
                                + " --xla_force_host_platform_device_count=4")
try:  # jax < 0.5: cross-process CPU collectives need the gloo opt-in
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except (AttributeError, ValueError):
    pass  # newer jax: gloo is the default

from tmhpvsim_tpu.parallel.distributed import (
    initialize_from_env, local_chain_slice,
)

assert initialize_from_env()

from tmhpvsim_tpu.config import SimConfig
from tmhpvsim_tpu.engine import Simulation
from tmhpvsim_tpu.parallel import ShardedSimulation, make_mesh

cfg = dict(start="2019-09-05 10:00:00", duration_s=120, n_chains=16,
           seed=5, block_s=60, dtype="float32")
mesh = make_mesh()  # 8 devices across 2 processes
assert not mesh.devices[0].process_index == mesh.devices[-1].process_index

sim = ShardedSimulation(SimConfig(**cfg), mesh=mesh)
sl = local_chain_slice(16, mesh)
ref = list(Simulation(SimConfig(**cfg)).run_blocks())  # local full run

# Trace mode on a pod-slice-shaped mesh: each host gets ONLY its own
# contiguous chain slice (no DCN gather), ensemble is the global view.
for b, r in zip(sim.run_blocks(), ref):
    assert b.meter.shape == (8, 60), b.meter.shape
    np.testing.assert_array_equal(b.meter, r.meter[sl])
    np.testing.assert_allclose(b.pv, r.pv[sl], rtol=1e-5, atol=1e-4)
    np.testing.assert_allclose(b.ensemble["pv_mean"], r.pv.mean(axis=0),
                               rtol=1e-4, atol=1e-3)

# Reduce mode: host-local accumulator slices; global psum ensemble.
rsim = ShardedSimulation(SimConfig(**cfg), mesh=mesh)
red = rsim.run_reduced()
assert len(red["pv_sum"]) == 8
ref_red = Simulation(SimConfig(**cfg)).run_reduced()
np.testing.assert_allclose(red["pv_sum"], ref_red["pv_sum"][sl],
                           rtol=1e-5, atol=1e-2)
ens = rsim.ensemble_stats()
np.testing.assert_allclose(ens["pv_sum"], ref_red["pv_sum"].sum(),
                           rtol=1e-5)
assert ens["n_seconds"] == 16 * 120

print(f"SIMOK {jax.process_index()}", flush=True)
"""


def test_two_process_sharded_simulation():
    """The full simulation over a 2-host mesh: state creation, trace mode
    with host-local gathers, reduce mode, and the DCN ensemble psum — the
    multi-host output contract of ShardedSimulation (parallel/mesh.py)."""
    outs = _run_workers(_SIM_WORKER)
    assert "SIMOK 0" in outs[0][1]
    assert "SIMOK 1" in outs[1][1]


_CKPT_WORKER = r"""
import os, sys, tempfile
import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 4)
except AttributeError:  # jax < 0.5 spells it as an XLA flag
    import os as _os
    _os.environ["XLA_FLAGS"] = (_os.environ.get("XLA_FLAGS", "")
                                + " --xla_force_host_platform_device_count=4")
try:  # jax < 0.5: cross-process CPU collectives need the gloo opt-in
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except (AttributeError, ValueError):
    pass  # newer jax: gloo is the default

from tmhpvsim_tpu.parallel.distributed import initialize_from_env
assert initialize_from_env()

from tmhpvsim_tpu.apps import pvsim as app
from tmhpvsim_tpu.obs.profiler import BlockTimer

pid = jax.process_index()
workdir = sys.argv[1]   # shared tmp dir passed by the harness
kw = dict(duration_s=240, n_chains=16, seed=5,
          start="2019-09-05 10:00:00", block_s=60,
          sharded=True, output="reduce")

# Uninterrupted reference run (its own files).
app.pvsim_jax(f"{workdir}/ref.csv", checkpoint=f"{workdir}/ref.npz", **kw)

# Interrupted run: crash in block 2 (after blocks 0-1 checkpointed) by
# making the timer hook blow up on its third tick — both hosts die at the
# same deterministic point, like a pod-wide preemption.
class Boom(Exception):
    pass

real_tick = BlockTimer.tick
def tick_bomb(self):
    if getattr(self, "_n", 0) >= 2:
        raise Boom()
    self._n = getattr(self, "_n", 0) + 1
    return real_tick(self)

BlockTimer.tick = tick_bomb
try:
    app.pvsim_jax(f"{workdir}/out.csv", checkpoint=f"{workdir}/run.npz", **kw)
    raise AssertionError("expected the injected crash")
except Boom:
    pass
finally:
    BlockTimer.tick = real_tick

assert os.path.exists(f"{workdir}/run.npz.host{pid}")

# Resume: picks up the per-host checkpoint at block 2, finishes 2-3.
app.pvsim_jax(f"{workdir}/out.csv", checkpoint=f"{workdir}/run.npz", **kw)

resumed = open(f"{workdir}/out.csv.host{pid}").read()
ref = open(f"{workdir}/ref.csv.host{pid}").read()
assert resumed == ref, (
    "resumed per-host summary differs from uninterrupted run:\n"
    f"resumed:\n{resumed}\nref:\n{ref}"
)
# global chain ids: host 0 rows 0-7, host 1 rows 8-15
first_chain = resumed.splitlines()[1].split(",")[0]
assert first_chain == ("0" if pid == 0 else "8"), first_chain

# Trace mode kill/resume: --chain is GLOBAL (chain 2 lives on host 0), so
# only host 0 writes the CSV; host 1 checkpoints state but must resume
# WITHOUT tripping the CSV exactly-once check on its never-written file.
tkw = dict(duration_s=240, n_chains=16, seed=5,
           start="2019-09-05 10:00:00", block_s=60,
           sharded=True, output="trace", chain=2)
BlockTimer.tick = tick_bomb
try:
    app.pvsim_jax(f"{workdir}/tr.csv", checkpoint=f"{workdir}/tr.npz", **tkw)
    raise AssertionError("expected the injected crash")
except Boom:
    pass
finally:
    BlockTimer.tick = real_tick
app.pvsim_jax(f"{workdir}/tr.csv", checkpoint=f"{workdir}/tr.npz", **tkw)
if pid == 0:
    rows = open(f"{workdir}/tr.csv.host0").read().splitlines()
    assert len(rows) == 1 + 240, len(rows)  # header + every second, once
else:
    assert not os.path.exists(f"{workdir}/tr.csv.host1")
print(f"CKPTOK {pid}", flush=True)
"""


def test_two_process_checkpoint_kill_resume(tmp_path):
    """Pod-slice checkpoint/resume end-to-end: a sharded reduce run over a
    2-host mesh is killed mid-run (deterministically, on both hosts), then
    resumed from the per-host checkpoint files — the final per-host
    summary CSVs must be BIT-identical to an uninterrupted run's, with
    global chain ids (apps/pvsim.py + ShardedSimulation.host_local_tree/
    _place_resume)."""
    outs = _run_workers(_CKPT_WORKER, timeout=600.0, args=[str(tmp_path)])
    assert "CKPTOK 0" in outs[0][1]
    assert "CKPTOK 1" in outs[1][1]


#: shared by the prep and worker sources below — the config must be
#: built IDENTICALLY on both sides (the checkpoint's config echo is part
#: of identity; only placement is elastic)
_ELASTIC_CFG = r"""
def _mkcfg(n, dur, blk):
    from tmhpvsim_tpu.config import SimConfig
    from tmhpvsim_tpu.fleet import FleetParams

    return SimConfig(start="2019-09-05 10:00:00", duration_s=dur,
                     n_chains=n, seed=5, block_s=blk, dtype="float32",
                     block_impl="scan", output="reduce", analytics="risk",
                     fleet=FleetParams.synthetic(n, seed=5))
"""

_ELASTIC_PREP = r"""
import sys
import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")
""" + _ELASTIC_CFG + r"""
from tmhpvsim_tpu.engine import Simulation
from tmhpvsim_tpu.engine import checkpoint as ckpt

workdir, n, dur, blk, want_ref = (sys.argv[1], int(sys.argv[2]),
                                  int(sys.argv[3]), int(sys.argv[4]),
                                  sys.argv[5])
cfg = _mkcfg(n, dur, blk)
sim = Simulation(cfg)


class Stop(Exception):
    pass


def hook(bi, state, acc):
    if bi == 0:
        ckpt.save(f"{workdir}/one_host.npz", {"state": state, "acc": acc},
                  bi + 1, cfg, layout=sim.checkpoint_layout())
        raise Stop


try:
    sim.run_reduced(on_block=hook)
    raise AssertionError("expected the injected stop after block 0")
except Stop:
    pass
assert ckpt.peek_meta(f"{workdir}/one_host.npz")["layout"]["n_devices"] == 1
if want_ref == "1":
    red = Simulation(_mkcfg(n, dur, blk)).run_reduced()  # uninterrupted
    np.savez(f"{workdir}/ref.npz", **red)
print("PREPOK", flush=True)
"""

_ELASTIC_WORKER = r"""
import json
import os
import sys
import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 4)
except AttributeError:  # jax < 0.5 spells it as an XLA flag
    import os as _os
    _os.environ["XLA_FLAGS"] = (_os.environ.get("XLA_FLAGS", "")
                                + " --xla_force_host_platform_device_count=4")
try:  # jax < 0.5: cross-process CPU collectives need the gloo opt-in
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except (AttributeError, ValueError):
    pass  # newer jax: gloo is the default

from tmhpvsim_tpu.parallel.distributed import initialize_from_env

assert initialize_from_env()
""" + _ELASTIC_CFG + r"""
from tmhpvsim_tpu.engine import checkpoint as ckpt
from tmhpvsim_tpu.parallel import ShardedSimulation, make_mesh

workdir, n, dur, blk = (sys.argv[1], int(sys.argv[2]), int(sys.argv[3]),
                        int(sys.argv[4]))
pid = jax.process_index()
cfg = _mkcfg(n, dur, blk)
mesh = make_mesh()  # 8 devices across 2 processes
assert mesh.devices.size == 8
sim = ShardedSimulation(cfg, mesh=mesh)

# Elastic resume: the full 1-host checkpoint resliced to the contiguous
# chain range THIS host's devices own (checkpoint.load_elastic +
# ShardedSimulation.resume_chain_slice), placed shard-by-shard with no
# DCN traffic (_place_resume).
sl = sim.resume_chain_slice()
assert sl == ((0, n // 2) if pid == 0 else (n // 2, n)), sl
tree, nb = ckpt.load_elastic(f"{workdir}/one_host.npz", cfg,
                             chain_slice=sl)
assert nb == 1, nb
red = sim.run_reduced(state=tree["state"], acc=tree["acc"],
                      start_block=nb)

# host-local output contract: this host's half, every chain complete
assert len(red["n_seconds"]) == n // 2
assert (red["n_seconds"] == dur).all()
ref_path = f"{workdir}/ref.npz"
if os.path.exists(ref_path):
    ref = np.load(ref_path)
    a, b = sl
    np.testing.assert_array_equal(red["n_seconds"],
                                  ref["n_seconds"][a:b])
    for k in ref.files:
        np.testing.assert_allclose(red[k], ref[k][a:b],
                                   rtol=1e-5, atol=1e-2, err_msg=k)

# global aggregates ride in-graph collectives (psum over ICI+DCN) and
# come back replicated — both processes must print identical documents
ens = sim.ensemble_stats()
assert ens["n_seconds"] == n * dur, ens["n_seconds"]  # incl. block 0
fleet = sim.fleet_summary()
rows = fleet["cohorts"]
assert [r["cohort"] for r in rows] == [0, 1, 2]
# the host-side fleet merge covers the blocks THIS run executed; the
# checkpointed accumulator carries block 0's per-chain stats (ens
# above), while block 0's fleet delta belongs to the interrupted run
assert sum(r["count"] for r in rows) == n * (dur - blk)
print("AGG " + json.dumps({"ens": ens, "fleet": fleet}, sort_keys=True),
      flush=True)
print(f"ELASTICOK {pid}", flush=True)
"""


def _run_single(src: str, args, timeout: float):
    """One uncoordinated subprocess with the workers' env scrub (no
    distributed init, no parent XLA_FLAGS/x64, same compile cache)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    env.pop("PYTHONPATH", None)
    for k in list(env):
        if k.startswith(("AXON_", "PALLAS_AXON_")):
            env.pop(k)
    proc = subprocess.run(
        [sys.executable, "-c", src, *args], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        timeout=timeout,
    )
    assert proc.returncode == 0, (
        f"prep failed rc={proc.returncode}\nstdout:{proc.stdout}\n"
        f"stderr:{proc.stderr}")
    return proc.stdout


def _elastic_roundtrip(tmp_path, n, dur, blk, want_ref,
                       prep_timeout, worker_timeout):
    args = [str(tmp_path), str(n), str(dur), str(blk)]
    out = _run_single(_ELASTIC_PREP, args + [want_ref], prep_timeout)
    assert "PREPOK" in out
    outs = _run_workers(_ELASTIC_WORKER, timeout=worker_timeout, args=args)
    assert "ELASTICOK 0" in outs[0][1]
    assert "ELASTICOK 1" in outs[1][1]
    aggs = [next(ln for ln in o[1].splitlines() if ln.startswith("AGG "))
            for o in outs]
    assert aggs[0] == aggs[1]  # replicated collectives agree across hosts


def test_two_process_elastic_resume(tmp_path):
    """A checkpoint written by a 1-host run resumes on a 2-host pod
    slice: load_elastic reslices the full chain axis to each host's
    range, the finished run matches an uninterrupted single-host
    reference at the documented tolerances (ints exact), and the
    in-graph ensemble + per-cohort fleet aggregates come back identical
    (replicated) on both hosts."""
    _elastic_roundtrip(tmp_path, n=64, dur=120, blk=60, want_ref="1",
                       prep_timeout=420.0, worker_timeout=600.0)


def test_million_site_two_host_elastic(tmp_path):
    """The pod-scale bar (ISSUE): 1M+ DISTINCT synthetic-fleet sites
    (FleetParams.synthetic — per-site capacity/clip/regime/demand/cohort
    columns) across 2 simulated hosts, per-cohort aggregation entirely
    in-graph, resuming from a 1-host checkpoint via load_elastic.
    Minimum horizon (2 blocks of the 60 s minute-grid minimum): the bar
    is scale x topology mechanics, not throughput — this host simulates
    ~0.05M site-seconds/s, so the 63M site-s prep block and the two
    concurrent 31M site-s resume halves each take ~20 min of wall clock
    on 1 core.  Deepest entry of the slow lane by design."""
    _elastic_roundtrip(tmp_path, n=1_048_576, dur=120, blk=60,
                       want_ref="0",
                       prep_timeout=2700.0, worker_timeout=2700.0)


def test_initialize_from_env_noop_single_process():
    """Without coordinator env vars the runtime must stay single-process."""
    from tmhpvsim_tpu.parallel.distributed import initialize_from_env

    saved = {k: os.environ.pop(k, None)
             for k in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES")}
    try:
        assert initialize_from_env() is False
    finally:
        for k, v in saved.items():
            if v is not None:
                os.environ[k] = v
