"""Multi-host (multi-process) smoke tests: the pod-slice execution model.

The reference's only multi-process story is N consumers on one RabbitMQ
broker (SURVEY.md §2.4); the TPU-native equivalent is one JAX process per
host joined through ``jax.distributed`` (parallel/distributed.py), a mesh
spanning all hosts' devices, and collectives over ICI/DCN.

Real TPU pods aren't available in CI, so this does what the JAX ecosystem
does: two coordinated CPU processes on localhost, each owning 4 virtual
devices, forming one 8-device global mesh — which exercises exactly the
``process_count() > 1`` paths (initialize_from_env, cross-process psum,
local_chain_slice) that a pod slice uses.
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os
import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 4)

from tmhpvsim_tpu.parallel.distributed import (
    initialize_from_env, local_chain_slice,
)

assert initialize_from_env(), "env vars set; should have initialised"
assert jax.process_count() == 2, jax.process_count()
assert jax.local_device_count() == 4
assert jax.device_count() == 8

from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map
from jax.experimental import multihost_utils

from tmhpvsim_tpu.parallel import make_mesh
from tmhpvsim_tpu.parallel.mesh import CHAIN_AXIS

mesh = make_mesh()  # global: both processes' devices
assert mesh.devices.size == 8

# Each process contributes its local half of a 16-chain vector; the psum
# must see all 16 global chains -> the DCN analogue of the block step's
# ensemble reduction (parallel/mesh.py).
pid = jax.process_index()
local = np.full((8,), float(pid + 1))
arr = multihost_utils.host_local_array_to_global_array(
    local, mesh, P(CHAIN_AXIS)
)
total = jax.jit(shard_map(
    lambda x: jax.lax.psum(x.sum(), CHAIN_AXIS),
    mesh=mesh, in_specs=P(CHAIN_AXIS), out_specs=P(),
    check_vma=False,
))(arr)
assert float(total) == 8 * 1.0 + 8 * 2.0, float(total)

# Each host owns the contiguous half of the chain axis its devices hold.
sl = local_chain_slice(16, mesh)
expect = slice(0, 8) if pid == 0 else slice(8, 16)
assert (sl.start, sl.stop) == (expect.start, expect.stop), sl

print(f"DISTOK {pid}", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_distributed_smoke():
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(
            os.environ,
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES="2",
            JAX_PROCESS_ID=str(pid),
            JAX_PLATFORMS="cpu",
        )
        # the parent's 8-device XLA_FLAGS would fight jax_num_cpu_devices
        env.pop("XLA_FLAGS", None)
        # the axon sitecustomize (PYTHONPATH) eagerly initialises a backend,
        # which forbids jax.distributed.initialize afterwards — a TPU pod
        # launcher initialises distributed first, so drop it here
        env.pop("PYTHONPATH", None)
        for k in list(env):
            if k.startswith(("AXON_", "PALLAS_AXON_")):
                env.pop(k)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _WORKER], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        ))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=360)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout:{out}\nstderr:{err}"
    assert "DISTOK 0" in outs[0][1]
    assert "DISTOK 1" in outs[1][1]


def test_initialize_from_env_noop_single_process():
    """Without coordinator env vars the runtime must stay single-process."""
    from tmhpvsim_tpu.parallel.distributed import initialize_from_env

    saved = {k: os.environ.pop(k, None)
             for k in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES")}
    try:
        assert initialize_from_env() is False
    finally:
        for k, v in saved.items():
            if v is not None:
                os.environ[k] = v
