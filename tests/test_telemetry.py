"""In-graph numerics telemetry, drift sentinel, and the perf-trend gate.

Covers the device-side accumulator (obs/telemetry.py), its threading
through the scan/scan2/wide reduce paths, the sharded psum aggregation,
the sentinel's NaN localisation + band checks (obs/sentinel.py), the
RunReport v2 telemetry section (+ v1 back-compat), the ``--telemetry
off`` byte-identical-HLO guarantee, and tools/bench_trend.py's
regression gate over the checked-in bench history.
"""

import json
import logging
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tmhpvsim_tpu.config import SimConfig
from tmhpvsim_tpu.engine import Simulation
from tmhpvsim_tpu.models import clearsky_index as ci
from tmhpvsim_tpu.obs import telemetry as tel
from tmhpvsim_tpu.obs.metrics import MetricsRegistry, use_registry
from tmhpvsim_tpu.obs.report import REPORT_SCHEMA_VERSION, validate_report
from tmhpvsim_tpu.obs.sentinel import DriftError, DriftSentinel
from tmhpvsim_tpu.parallel import ShardedSimulation

REPO = Path(__file__).resolve().parents[1]
BENCH_TREND = REPO / "tools" / "bench_trend.py"


def small_cfg(**kw):
    base = dict(
        start="2019-09-05 10:00:00",
        duration_s=7200,
        n_chains=8,
        seed=7,
        block_s=3600,
        dtype="float32",
        block_impl="scan",
    )
    base.update(kw)
    return SimConfig(**base)


# ---------------------------------------------------------------------------
# accumulator unit tests
# ---------------------------------------------------------------------------

class TestFold:
    def test_off_level_is_not_an_accumulator(self):
        with pytest.raises(ValueError):
            tel.init_acc("off", jnp.float32)

    def test_known_values_one_second(self):
        acc = tel.init_acc("full", jnp.float32, n_chains=2)
        acc = tel.fold_second(
            acc, "full",
            meter=jnp.asarray([1.0, 3.0], jnp.float32),
            pv=jnp.asarray([0.5, jnp.nan], jnp.float32),
            csi=jnp.asarray([0.3, jnp.inf], jnp.float32),
            residual=jnp.asarray([0.5, 3.0], jnp.float32),
            covered=jnp.asarray([True, False]),
            valid=jnp.asarray(True),
        )
        acc = tel.reduce_chainwise(acc)
        # the per-chain fold collapses to the scalar leaf format
        assert sorted(acc) == sorted(tel.init_acc("full", jnp.float32))
        s = tel.summarize({k: np.asarray(v) for k, v in acc.items()})
        assert s["count"] == 2
        m = s["fields"]["meter"]
        assert (m["nan"], m["inf"]) == (0, 0)
        assert m["min"] == 1.0 and m["max"] == 3.0 and m["mean"] == 2.0
        # non-finite values are counted, then excluded from the moments
        assert s["fields"]["pv"]["nan"] == 1
        assert s["fields"]["pv"]["min"] == s["fields"]["pv"]["max"] == 0.5
        assert s["fields"]["csi"]["inf"] == 1
        assert s["fields"]["csi"]["max"] == pytest.approx(0.3)
        # full level: histogram bin for csi=0.3 is bin1 ([0.25, 0.5));
        # the non-finite sample must not land in any bin
        assert s["csi_hist"] == [0, 1, 0, 0, 0, 0, 0, 0]
        assert s["cloud_occupancy"] == {"clear": 1, "covered": 1}

    def test_invalid_seconds_contribute_nothing(self):
        acc = tel.init_acc("light", jnp.float32, n_chains=2)
        args = dict(
            meter=jnp.asarray([jnp.nan, 2.0], jnp.float32),
            pv=jnp.asarray([1.0, 1.0], jnp.float32),
            csi=jnp.asarray([0.9, 0.9], jnp.float32),
            residual=jnp.asarray([1.0, 1.0], jnp.float32),
            covered=jnp.asarray([False, False]),
        )
        acc = tel.fold_second(acc, "light", valid=jnp.asarray(False), **args)
        acc = tel.reduce_chainwise(acc)
        s = tel.summarize({k: np.asarray(v) for k, v in acc.items()})
        assert s["count"] == 0
        assert s["fields"]["meter"]["nan"] == 0
        assert not s["fields"]["meter"]["observed"]

    def test_leaf_kinds_cover_every_leaf(self):
        acc = tel.init_acc("full", jnp.float32)
        kinds = tel.leaf_kinds(acc)
        assert set(kinds) == set(acc)
        assert set(kinds.values()) <= {"sum", "min", "max"}


# ---------------------------------------------------------------------------
# reduce-mode integration: metrics, report, bit-identity
# ---------------------------------------------------------------------------

class TestReduceRun:
    def test_light_publishes_metrics_and_report(self):
        with use_registry(MetricsRegistry()):
            sim = Simulation(small_cfg(telemetry="light"))
            sim.run_reduced()
            snap = sim.metrics.snapshot()
            doc = sim.run_report()
        assert snap["counters"]["device.telemetry.blocks_total"] == 2
        for f in tel.TELEMETRY_FIELDS:
            assert snap["counters"][f"device.nan_total.{f}"] == 0
            assert snap["gauges"][f"device.{f}.mean"] is not None
        validate_report(doc)
        assert doc["schema_version"] == REPORT_SCHEMA_VERSION
        t = doc["telemetry"]
        assert t["verdict"] == "ok"
        assert t["blocks_checked"] == 2
        assert set(t["worst_z"]) == set(tel.TELEMETRY_FIELDS)

    def test_full_publishes_histogram_and_occupancy(self):
        with use_registry(MetricsRegistry()):
            sim = Simulation(small_cfg(telemetry="full"))
            sim.run_reduced()
            snap = sim.metrics.snapshot()
        hist = {k: v for k, v in snap["counters"].items()
                if k.startswith("device.csi_hist.")}
        occ = {k: v for k, v in snap["counters"].items()
               if k.startswith("device.cloud_occupancy.")}
        n_seconds = 2 * 8 * 3600
        assert sum(hist.values()) == n_seconds  # every finite csi binned
        assert sum(occ.values()) == n_seconds
        assert occ["device.cloud_occupancy.covered"] > 0

    @pytest.mark.parametrize("impl", ["scan", "scan2", "wide"])
    def test_results_bit_identical_off_vs_light(self, impl):
        """Telemetry reads the stream; it must not perturb it."""
        with use_registry(MetricsRegistry()):
            on = Simulation(
                small_cfg(telemetry="light", block_impl=impl)).run_reduced()
        off = Simulation(
            small_cfg(telemetry="off", block_impl=impl)).run_reduced()
        assert sorted(on) == sorted(off)
        for k in off:
            np.testing.assert_array_equal(off[k], on[k])

    def test_wide_impl_skips_csi(self):
        """The wide fallback folds meter/pv/residual only; csi must be
        reported unobserved, not as a spurious all-zero distribution."""
        with use_registry(MetricsRegistry()):
            sim = Simulation(small_cfg(telemetry="light", block_impl="wide"))
            sim.run_reduced()
            snap = sim.metrics.snapshot()
            doc = sim.run_report()
        assert "device.csi.mean" not in snap["gauges"]
        assert "device.pv.mean" in snap["gauges"]
        assert "csi" not in doc["telemetry"]["worst_z"]

    def test_plan_carries_resolved_level(self):
        sim = Simulation(small_cfg(telemetry="light"))
        assert sim.plan.telemetry == "light"
        assert Simulation(small_cfg()).plan.telemetry == "off"

    def test_bad_level_rejected(self):
        with pytest.raises(ValueError, match="telemetry"):
            Simulation(small_cfg(telemetry="verbose"))


# ---------------------------------------------------------------------------
# HLO identity: --telemetry off must COMPILE OUT, not just branch away
# ---------------------------------------------------------------------------

class TestHLOIdentity:
    @pytest.mark.parametrize("impl", ["scan", "scan2"])
    def test_off_lowers_byte_identical_to_absent(self, impl):
        """The telemetry=off jit must lower to byte-identical HLO with a
        reconstruction of the pre-telemetry composition (setup +
        ``_make_acc_body`` + lax.scan), proving the feature is
        structurally absent from the hot path, not gated inside it."""
        sim = Simulation(small_cfg(telemetry="off", block_impl=impl,
                                   n_chains=4))
        state = sim.init_state()
        acc = sim.init_reduce_acc()
        inputs, _ = sim.host_inputs(0)

        def rebuilt(state, inputs, acc, _sim=sim, _impl=impl):
            if _impl == "scan":
                xs, step, cc_carry = _sim._scan_block_setup(state, inputs)
                (rcarry, acc), _ = jax.lax.scan(
                    _sim._make_acc_body(step), (state["carry"], acc), xs,
                    unroll=_sim._unroll)
                return dict(state, carry=rcarry, cc_carry=cc_carry), acc
            return _sim._block_step_scan2_acc(state, inputs, acc)

        # match the bound method's name so the lowered module name (which
        # embeds the function name) cannot mask a real difference
        bound = getattr(sim, f"_block_step_{impl}_acc")
        rebuilt.__name__ = bound.__func__.__name__
        rebuilt.__qualname__ = bound.__func__.__qualname__
        fresh = jax.jit(rebuilt, donate_argnums=(0, 2))
        jit_attr = (sim._scan_acc_jit if impl == "scan"
                    else sim._scan2_acc_jit)
        a = jit_attr.lower(state, inputs, acc).as_text()
        b = fresh.lower(state, inputs, acc).as_text()
        assert a == b

    def test_off_builds_no_telemetry_jits(self):
        sim = Simulation(small_cfg(telemetry="off"))
        assert not hasattr(sim, "_scan_acc_tel_jit")
        assert not hasattr(sim, "_wide_tel_jit")


# ---------------------------------------------------------------------------
# sentinel: NaN localisation, strictness, band checks
# ---------------------------------------------------------------------------

def _poison_csi(monkeypatch, from_t):
    """Make every csi sample at global second >= from_t NaN."""
    orig = ci.csi_compose_step

    def poisoned(tables, x, carry, options, dtype=jnp.float32):
        rc, csi, covered = orig(tables, x, carry, options, dtype)
        return rc, jnp.where(x["t"] >= from_t, jnp.nan, csi), covered

    monkeypatch.setattr(ci, "csi_compose_step", poisoned)


class TestSentinel:
    def test_nan_caught_within_one_block(self, monkeypatch, caplog):
        _poison_csi(monkeypatch, from_t=3600)  # poison block 1 onward
        with use_registry(MetricsRegistry()):
            sim = Simulation(small_cfg(telemetry="light", duration_s=10800))
            with caplog.at_level(logging.WARNING,
                                 logger="tmhpvsim_tpu.obs.sentinel"):
                sim.run_reduced()
            doc = sim.run_report()
        t = doc["telemetry"]
        assert t["verdict"] == "nan"
        assert t["nan"]["field"] == "csi"
        assert t["nan"]["block"] == 1  # localised to the poisoned block
        assert t["nan"]["nan"] == 8 * 3600
        assert any("non-finite values in field 'csi' at block 1" in r.message
                   for r in caplog.records)
        # the registry counter keeps accumulating past the first event:
        # blocks 1 AND 2 are poisoned (the sentinel localises the first)
        snap = sim.metrics.snapshot()
        assert snap["counters"]["device.nan_total.csi"] == 2 * 8 * 3600

    def test_strict_raises_on_first_poisoned_block(self, monkeypatch):
        _poison_csi(monkeypatch, from_t=3600)
        with use_registry(MetricsRegistry()):
            sim = Simulation(small_cfg(telemetry="light",
                                       telemetry_strict=True,
                                       duration_s=10800))
            with pytest.raises(DriftError, match="csi.*block 1"):
                sim.run_reduced()

    def test_band_escape_flags_drift(self, caplog):
        sent = DriftSentinel(small_cfg(), level="light", tol_std=4.0)
        sent._ref = [{"csi": (0.9, 0.02)}]  # stub golden reference
        summary = {
            "count": 1000,
            "fields": {
                "csi": {"nan": 0, "inf": 0, "observed": True,
                        "min": 0.0, "max": 2.0, "mean": 1.5, "std": 0.1},
            },
        }
        with caplog.at_level(logging.WARNING,
                             logger="tmhpvsim_tpu.obs.sentinel"):
            verdict = sent.observe_block(0, summary)
        assert verdict == "drift"
        assert sent.drift_events[0]["field"] == "csi"
        assert sent.worst_z["csi"] == pytest.approx((1.5 - 0.9) / 0.02)
        rep = sent.report()
        assert rep["verdict"] == "drift" and rep["drift"]

    def test_in_band_is_ok_and_records_worst_z(self):
        sent = DriftSentinel(small_cfg(), level="light", tol_std=4.0)
        sent._ref = [{"csi": (0.9, 0.1)}]
        summary = {
            "count": 1000,
            "fields": {
                "csi": {"nan": 0, "inf": 0, "observed": True,
                        "min": 0.0, "max": 2.0, "mean": 1.0, "std": 0.1},
            },
        }
        assert sent.observe_block(0, summary) == "ok"
        assert sent.worst_z["csi"] == pytest.approx(1.0)

    def test_reference_failure_degrades_not_kills(self, monkeypatch,
                                                  caplog):
        from tmhpvsim_tpu.obs import sentinel as sentmod

        def boom(config, n_blocks, realizations=4):
            raise RuntimeError("no golden mirror for this config")

        monkeypatch.setattr(sentmod, "_golden_reference", boom)
        sent = DriftSentinel(small_cfg(), level="light", strict=True)
        summary = {
            "count": 10,
            "fields": {
                "csi": {"nan": 0, "inf": 0, "observed": True,
                        "min": 0.5, "max": 1.2, "mean": 0.9, "std": 0.1},
            },
        }
        with caplog.at_level(logging.WARNING,
                             logger="tmhpvsim_tpu.obs.sentinel"):
            # strict=True: a reference failure must still not raise
            assert sent.observe_block(0, summary) == "ok"
        assert any("golden reference unavailable" in r.message
                   for r in caplog.records)
        # ... but NaN checking is still armed
        summary["fields"]["csi"]["nan"] = 3
        with pytest.raises(DriftError):
            sent.observe_block(1, summary)


# ---------------------------------------------------------------------------
# sharded aggregation
# ---------------------------------------------------------------------------

class TestSharded:
    def test_sharded_totals_match_single_device(self):
        kw = dict(telemetry="full", n_chains=8, seed=11)
        with use_registry(MetricsRegistry()):
            s1 = Simulation(small_cfg(**kw))
            s1.run_reduced()
            snap1 = s1.metrics.snapshot()
        with use_registry(MetricsRegistry()):
            s8 = ShardedSimulation(small_cfg(**kw))
            s8.run_reduced()
            snap8 = s8.metrics.snapshot()
            doc = s8.run_report()
        for k, v in snap1["counters"].items():
            if not k.startswith("device."):
                continue
            if "nan_total" in k or "inf_total" in k or "hist" in k \
                    or "occupancy" in k or "blocks" in k:
                assert snap8["counters"][k] == v, k  # integer-exact
            else:
                assert snap8["counters"][k] == pytest.approx(v), k
        for k, v in snap1["gauges"].items():
            if k.startswith("device."):
                # per-shard fusion differs by ULPs (test_parallel.py's
                # sharded-vs-single contract); moments agree to ~1e-4 rel
                assert snap8["gauges"][k] == pytest.approx(
                    v, rel=1e-4, abs=1e-3), k
        assert doc["telemetry"]["verdict"] == "ok"


# ---------------------------------------------------------------------------
# report schema: v2 with telemetry, v1 back-compat
# ---------------------------------------------------------------------------

class TestReportSchema:
    def _doc(self):
        with use_registry(MetricsRegistry()):
            sim = Simulation(small_cfg(telemetry="light"))
            sim.run_reduced()
            return sim.run_report()

    def test_current_schema_round_trips_through_validator(self):
        doc = self._doc()
        assert doc["schema_version"] == REPORT_SCHEMA_VERSION
        validate_report(json.loads(json.dumps(doc)))

    def test_v2_documents_still_validate(self):
        """PR-3 builds wrote v2 docs (telemetry, no streaming section);
        the v3 validator must keep accepting them."""
        doc = self._doc()
        doc["schema_version"] = 2
        doc.pop("streaming", None)
        validate_report(doc)

    def test_v1_documents_still_validate(self):
        """PR-2 readers wrote v1 docs without a telemetry section; this
        build's validator must keep accepting them."""
        doc = self._doc()
        doc["schema_version"] = 1
        del doc["telemetry"]
        validate_report(doc)

    def test_newer_versions_rejected(self):
        doc = self._doc()
        doc["schema_version"] = REPORT_SCHEMA_VERSION + 1
        with pytest.raises(ValueError, match="newer"):
            validate_report(doc)

    def test_off_run_has_no_telemetry_section(self):
        with use_registry(MetricsRegistry()):
            sim = Simulation(small_cfg())
            sim.run_reduced()
            doc = sim.run_report()
        assert doc["telemetry"] is None
        validate_report(doc)


# ---------------------------------------------------------------------------
# perf-trend gate (tools/bench_trend.py)
# ---------------------------------------------------------------------------

def _run_trend(*argv):
    return subprocess.run(
        [sys.executable, str(BENCH_TREND), *map(str, argv)],
        capture_output=True, text=True)


class TestBenchTrend:
    def test_checked_in_history_passes(self):
        files = sorted(REPO.glob("BENCH_r0*.json"))
        assert len(files) == 5
        r = _run_trend(*files)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "r05" in r.stdout and "gate ok" in r.stdout
        # failed rounds appear as rows, not crashes
        assert "round failed" in r.stdout

    def test_doctored_steady_regression_fails(self, tmp_path):
        doc = json.loads((REPO / "BENCH_r05.json").read_text())
        hv = doc["parsed"]["headline_variant"]
        doc["parsed"]["variants"][hv]["best_round_wall_s"] *= 1.25
        bad = tmp_path / "BENCH_r06.json"
        bad.write_text(json.dumps(doc))
        r = _run_trend(REPO / "BENCH_r04.json", REPO / "BENCH_r05.json",
                       bad)
        assert r.returncode == 1, r.stdout + r.stderr
        assert "STEADY-STATE REGRESSION" in r.stdout
        # a wider allowance lets the same history pass
        r2 = _run_trend(REPO / "BENCH_r04.json", REPO / "BENCH_r05.json",
                        bad, "--max-regress", "30")
        assert r2.returncode == 0, r2.stdout + r2.stderr

    def _headline(self, steady, platform="cpu"):
        return {
            "value": 1e6, "platform": platform, "unit": "x",
            "run_report": {"timing": {"compile_s": 1.0,
                                      "steady_block_s": steady}},
        }

    def test_synthetic_run_report_docs_gate_on_steady(self, tmp_path):
        a = tmp_path / "a.json"
        b = tmp_path / "b.json"
        a.write_text(json.dumps(self._headline(0.100)))
        b.write_text(json.dumps(self._headline(0.115)))  # +15%
        r = _run_trend(a, b)
        assert r.returncode == 1
        assert "STEADY-STATE REGRESSION" in r.stdout
        b.write_text(json.dumps(self._headline(0.105)))  # +5%: in budget
        assert _run_trend(a, b).returncode == 0

    def test_cross_platform_rounds_never_gate(self, tmp_path):
        a = tmp_path / "a.json"
        b = tmp_path / "b.json"
        a.write_text(json.dumps(self._headline(0.01, platform="tpu")))
        b.write_text(json.dumps(self._headline(10.0, platform="cpu")))
        r = _run_trend(a, b)
        assert r.returncode == 0
        assert "no prior round on platform" in r.stdout


# ---------------------------------------------------------------------------
# overhead acceptance (slow lane, conftest _SLOW_LANE)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_telemetry_overhead_65536_chains():
    """telemetry=light steady-block wall within 2% of off at the
    65536-chain CPU config, on the impl the autotuner resolves for CPU
    at this shape (wide): the fold is a few bulk reductions over the
    already-materialised block arrays, measured ~1% here.  The scan
    impls use a per-chain elementwise fold designed for the
    bandwidth-bound TPU body (ops fuse into the existing per-chain
    loop); on this compute-bound 1-core CPU host the same fold costs
    ~15% and is not what a CPU run resolves to, so it is not the
    acceptance arm.  min-of-steady-blocks filters scheduler noise."""
    def steady_min(level: str) -> float:
        with use_registry(MetricsRegistry()):
            sim = Simulation(small_cfg(
                telemetry=level, n_chains=65536, duration_s=4 * 60,
                block_s=60, block_impl="wide"))
            sim.run_reduced()
        return min(sim.timer.block_times)

    steady_min("light")  # warm both arms' jit + persistent cache
    off = steady_min("off")
    light = steady_min("light")
    assert light <= off * 1.02, (
        f"telemetry overhead {light / off - 1:.2%} exceeds 2% "
        f"(light {light:.4f} s vs off {off:.4f} s)"
    )
