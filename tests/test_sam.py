"""SAM database loaders (data/sam.py) + physical anchors for the PV chain.

The exact reference hardware rows (pvmodel.py:13-17) cannot be vendored in
this environment (no pvlib, no network — see data/sam.py docstring); these
tests pin down the *loader* against the real CSV shapes, so supplying the
public files via TMHPVSIM_SAM_* yields exact parity, and anchor the
physics chain to literature-scale absolute values independent of any
coefficient set.
"""

import subprocess
import sys
import textwrap

import numpy as np
import pytest

from tmhpvsim_tpu.data import SANDIA_INVERTER, SAPM_MODULE
from tmhpvsim_tpu.data.sam import (
    REFERENCE_INVERTER_NAME,
    REFERENCE_MODULE_NAME,
    load_sam_inverter,
    load_sam_module,
)

# Synthetic rows in the genuine SAM library CSV shape: header + units row +
# data, pvlib-style punctuation in names.  Values are small primes so any
# column-mapping mistake shows up as a wrong prime, not a plausible float.
MODULE_CSV = textwrap.dedent("""\
    Name,Vintage,Area,Material,Cells in Series,Parallel Strings,Isco,Voco,Impo,Vmpo,AIsc,AImp,C0,C1,BVoco,MBVoc,BVmpo,MBVmp,N,C2,C3,A0,A1,A2,A3,A4,B0,B1,B2,B3,B4,B5,DTC,FD,A,B,C4,C5,IXO,IXXO,C6,C7,Notes
    Units,,m2,,,,A,V,A,V,1/C,1/C,,,V/C,V/C,V/C,V/C,,,1/V,,,,,,,,,,,,C,,,,,,A,A,,,
    Hanwha HSL60P6-PA-4-250T [2013],2013,1.63,mc-Si,2,1,3,5,7,11,13,17,19,23,29,31,37,41,43,47,53,59,61,67,71,73,79,83,89,97,101,103,107,109,113,127,131,137,139,149,151,157,test row
    Other Module [2010],2010,1.6,c-Si,60,1,8.8,37,8.2,30,0.0006,0.0002,1,0,-0.13,0,-0.14,0,1.05,0.3,-7,0.93,0.066,-0.014,0.0013,-5e-05,1,-0.0024,0.00031,-1.2e-05,2.1e-07,-1.4e-09,3,1,-3.5,-0.06,0,0,0,0,0,0,
    """)

INVERTER_CSV = textwrap.dedent("""\
    Name,Vac,Pso,Paco,Pdco,Vdco,C0,C1,C2,C3,Pnt,Vdcmax,Idcmax,Mppt_low,Mppt_high,CEC_Date,CEC_Type
    Units,V,W,W,W,V,1/W,1/V,1/V,1/V,W,V,A,V,V,,
    ABB: MICRO-0.25-I-OUTD-US-208 [208V] [CEC 2014],208,2,3,5,7,11,13,17,19,23,600,10,20,50,2014,Utility
    """)


@pytest.fixture
def sam_files(tmp_path):
    m = tmp_path / "sam-library-sandia-modules-2015-6-30.csv"
    i = tmp_path / "sam-library-cec-inverters-2019-03-05.csv"
    m.write_text(MODULE_CSV)
    i.write_text(INVERTER_CSV)
    return str(m), str(i)


class TestSamLoaders:
    def test_module_row_mapping(self, sam_files):
        mpath, _ = sam_files
        mod = load_sam_module(mpath, REFERENCE_MODULE_NAME)
        # Every consumer key present, each sourced from the right column.
        assert set(mod) == set(SAPM_MODULE)
        assert mod["Cells_in_Series"] == 2
        assert mod["Isco"] == 3 and mod["Voco"] == 5
        assert mod["Impo"] == 7 and mod["Vmpo"] == 11
        assert mod["Aisc"] == 13 and mod["Aimp"] == 17
        assert mod["C0"] == 19 and mod["C1"] == 23
        assert mod["Bvoco"] == 29 and mod["Mbvoc"] == 31
        assert mod["Bvmpo"] == 37 and mod["Mbvmp"] == 41
        assert mod["N"] == 43 and mod["C2"] == 47 and mod["C3"] == 53
        assert [mod[f"A{k}"] for k in range(5)] == [59, 61, 67, 71, 73]
        assert [mod[f"B{k}"] for k in range(6)] == [79, 83, 89, 97, 101, 103]
        assert mod["T_deltaT"] == 107 and mod["FD"] == 109
        assert mod["T_a"] == 113 and mod["T_b"] == 127

    def test_inverter_row_mapping(self, sam_files):
        _, ipath = sam_files
        inv = load_sam_inverter(ipath, REFERENCE_INVERTER_NAME)
        assert set(inv) == set(SANDIA_INVERTER)
        assert inv == {
            "Pso": 2, "Paco": 3, "Pdco": 5, "Vdco": 7,
            "C0": 11, "C1": 13, "C2": 17, "C3": 19, "Pnt": 23,
        }

    def test_pvlib_name_normalisation(self, sam_files):
        """The punctuated CSV name must be reachable via pvlib's normalised
        form — the exact string the reference uses (pvmodel.py:13-17)."""
        mpath, ipath = sam_files
        assert load_sam_module(mpath, "Hanwha HSL60P6-PA-4-250T [2013]") == \
            load_sam_module(mpath, REFERENCE_MODULE_NAME)
        load_sam_inverter(ipath, REFERENCE_INVERTER_NAME)  # no KeyError

    def test_missing_row_lists_candidates(self, sam_files):
        mpath, _ = sam_files
        with pytest.raises(KeyError, match="Hanwha"):
            load_sam_module(mpath, "No_Such_Module")

    def test_env_override_wires_into_consumers(self, sam_files, tmp_path):
        """With TMHPVSIM_SAM_* set, `from tmhpvsim_tpu.data import ...`
        must expose the file's rows (subprocess: import-time wiring)."""
        mpath, ipath = sam_files
        code = (
            "from tmhpvsim_tpu.data import SAPM_MODULE, SANDIA_INVERTER;"
            "assert SAPM_MODULE['Isco'] == 3, SAPM_MODULE;"
            "assert SANDIA_INVERTER['Pdco'] == 5, SANDIA_INVERTER;"
            "print('override ok')"
        )
        import os

        env = dict(os.environ, TMHPVSIM_SAM_MODULES=mpath,
                   TMHPVSIM_SAM_INVERTERS=ipath, JAX_PLATFORMS="cpu")
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=120,
                           env=env)
        assert r.returncode == 0, r.stderr
        assert "override ok" in r.stdout


class TestPhysicalAnchors:
    """Absolute-scale anchors independent of the golden model (which shares
    formulas with the jax path — VERDICT round 1 'what's weak' #5)."""

    def test_clear_sky_noon_ghi_munich_scale(self):
        """Clear-sky GHI at Munich summer solar noon is ~800-950 W/m^2 in
        every published climatology; the Ineichen chain must land there."""
        from tmhpvsim_tpu.config import Site
        from tmhpvsim_tpu.models import solar

        # 2019-06-21 ~11:15 UTC = 13:15 CEST, close to Munich solar noon.
        epoch = np.asarray([1561115700.0])
        doy = np.asarray([172.0])
        geom = solar.block_geometry(epoch, doy, Site(), xp=np)
        assert geom["zenith"][0] < 30.0 * solar.DEG  # sanity: high sun
        assert 800.0 < geom["ghi_clear"][0] < 950.0

    def test_clear_sky_winter_noon_ghi(self):
        from tmhpvsim_tpu.config import Site
        from tmhpvsim_tpu.models import solar

        # 2019-12-21 ~11:20 UTC, Munich winter solstice noon: ~250-400 W/m^2.
        epoch = np.asarray([1576927200.0])
        doy = np.asarray([355.0])
        geom = solar.block_geometry(epoch, doy, Site(), xp=np)
        assert 250.0 < geom["ghi_clear"][0] < 420.0

    def test_peak_ac_power_is_plantlike(self):
        """csi=1 at summer noon on a 250 W module + 250 W micro-inverter
        must produce 150-250 W AC — the plant's nameplate scale."""
        from tmhpvsim_tpu.config import Site
        from tmhpvsim_tpu.data import SANDIA_INVERTER, SAPM_MODULE
        from tmhpvsim_tpu.models import pv as pvmod
        from tmhpvsim_tpu.models import solar

        epoch = np.asarray([1561115700.0])
        doy = np.asarray([172.0])
        geom = solar.block_geometry(epoch, doy, Site(), xp=np)
        ac = pvmod.power_from_csi(np.asarray([1.0]), geom, SAPM_MODULE,
                                  SANDIA_INVERTER, xp=np)
        assert 150.0 < ac[0] <= 250.0

    def test_night_power_is_zero(self):
        from tmhpvsim_tpu.config import Site
        from tmhpvsim_tpu.data import SANDIA_INVERTER, SAPM_MODULE
        from tmhpvsim_tpu.models import pv as pvmod
        from tmhpvsim_tpu.models import solar

        epoch = np.asarray([1561075200.0])  # 2019-06-21 00:00 UTC
        doy = np.asarray([172.0])
        geom = solar.block_geometry(epoch, doy, Site(), xp=np)
        ac = pvmod.power_from_csi(np.asarray([1.0]), geom, SAPM_MODULE,
                                  SANDIA_INVERTER, xp=np)
        assert ac[0] == 0.0
