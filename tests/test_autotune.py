"""Runtime autotuner + chain-slab scheduler (engine/autotune.py, slab.py).

The cache tests monkeypatch ``autotune.probe_plan`` with a deterministic
fake rater — ``probe_grid`` still walks the real candidate grid and bumps
``PROBE_COUNT`` per candidate, so cache-hit assertions ("zero probes on
the second run") exercise the real resolution path without timing real
blocks.  Real-block probing is covered by the ``slow``-marked test at the
acceptance shape (256 chains x 1080 s, narrowed grid).
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from tmhpvsim_tpu.config import Plan, SimConfig
from tmhpvsim_tpu.engine import Simulation, SlabScheduler
from tmhpvsim_tpu.engine import autotune


def small_config(**kw):
    base = dict(
        start="2019-09-05 10:00:00",
        duration_s=7200,
        n_chains=3,
        seed=7,
        block_s=3600,
        dtype="float32",
    )
    base.update(kw)
    return SimConfig(**base)


@pytest.fixture
def tmp_cache(tmp_path, monkeypatch):
    """Point the plan cache at a per-test file; returns its path."""
    path = str(tmp_path / "autotune.json")
    monkeypatch.setenv("TMHPVSIM_AUTOTUNE_CACHE", path)
    return path


@pytest.fixture
def fake_prober(monkeypatch):
    """Replace the real-block probe with a deterministic rater: the
    wide/unroll=4/unslabbed candidate wins (any fixed winner works —
    the tests assert the CACHED plan equals the PROBED winner).

    Stage-2 precision probing is neutralised by collapsing the
    candidate axes to their defaults — the sentinel gate runs real
    mini-simulations, and these tests count structural-grid probes
    only (precision probing has its own coverage in test_precision)."""
    def fake(config, plan, n_timed=autotune.PROBE_TIMED_BLOCKS):
        if (plan.block_impl == "wide" and plan.scan_unroll == 4
                and plan.slab_chains == config.n_chains):
            return 1000.0
        return 10.0 + plan.scan_unroll

    monkeypatch.setattr(autotune, "probe_plan", fake)
    monkeypatch.setattr(autotune, "CANDIDATE_COMPUTE_DTYPES", ("f32",))
    monkeypatch.setattr(autotune, "CANDIDATE_KERNEL_IMPLS", ("exact",))
    monkeypatch.setattr(autotune, "CANDIDATE_RNG_BATCHES", ("scan",))
    monkeypatch.setattr(autotune, "CANDIDATE_GEOM_STRIDES", (1,))
    return fake


def probes_during(fn):
    """(result, number of candidate probes performed by fn())."""
    before = autotune.PROBE_COUNT
    out = fn()
    return out, autotune.PROBE_COUNT - before


WINNER = dict(block_impl="wide", scan_unroll=4)


class TestPlanCache:
    def test_auto_probes_once_then_hits(self, tmp_cache, fake_prober):
        cfg = small_config(tune="auto")
        plan, n1 = probes_during(lambda: autotune.resolve_plan(cfg))
        assert n1 == len(autotune.candidate_plans(cfg))
        assert plan.source == "probe"
        assert plan.block_impl == WINNER["block_impl"]
        assert plan.scan_unroll == WINNER["scan_unroll"]
        assert plan.slab_chains == cfg.n_chains
        # second resolution at the same key: zero probes, same plan
        again, n2 = probes_during(lambda: autotune.resolve_plan(cfg))
        assert n2 == 0
        assert again.source == "cache"
        assert dataclasses.replace(again, source=plan.source) == plan

    def test_cache_round_trips_through_json(self, tmp_cache, fake_prober):
        cfg = small_config(tune="auto")
        autotune.resolve_plan(cfg)
        doc = json.load(open(tmp_cache))
        entry = doc[autotune.plan_key(cfg)]
        assert entry["plan"]["block_impl"] == WINNER["block_impl"]
        assert entry["plan"]["scan_unroll"] == WINNER["scan_unroll"]
        # candidate records persist WITH their measured rates
        cands = autotune.cached_candidates(cfg)
        assert len(cands) == len(autotune.candidate_plans(cfg))
        assert all("rate" in c for c in cands)

    def test_key_mismatch_reprobes(self, tmp_cache, fake_prober):
        autotune.resolve_plan(small_config(tune="auto"))
        other = small_config(tune="auto", n_chains=5)
        plan, n = probes_during(lambda: autotune.resolve_plan(other))
        assert n == len(autotune.candidate_plans(other))
        assert plan.source == "probe"
        # both keys now live in one cache file
        assert len(json.load(open(tmp_cache))) == 2

    def test_off_is_static_and_free(self, tmp_cache, fake_prober):
        cfg = small_config(tune="off")
        plan, n = probes_during(lambda: autotune.resolve_plan(cfg))
        assert n == 0
        assert plan.source == "static"
        assert plan.slab_chains == cfg.n_chains  # no slabbing
        assert not os.path.exists(tmp_cache)     # no cache IO at all

    def test_force_reprobes_on_a_hit(self, tmp_cache, fake_prober):
        autotune.resolve_plan(small_config(tune="auto"))
        cfg = small_config(tune="force")
        plan, n = probes_during(lambda: autotune.resolve_plan(cfg))
        assert n == len(autotune.candidate_plans(cfg))
        assert plan.source == "probe"

    def test_corrupt_cache_file_tolerated(self, tmp_cache, fake_prober):
        with open(tmp_cache, "w") as f:
            f.write("{not json")
        cfg = small_config(tune="auto")
        plan, n = probes_during(lambda: autotune.resolve_plan(cfg))
        assert n > 0 and plan.source == "probe"
        # the re-probe REPLACES the corrupt file with a valid one
        assert autotune.plan_key(cfg) in json.load(open(tmp_cache))

    def test_malformed_entry_reprobed(self, tmp_cache, fake_prober):
        cfg = small_config(tune="auto")
        with open(tmp_cache, "w") as f:
            json.dump({autotune.plan_key(cfg): {"plan": {
                "block_impl": "warp", "scan_unroll": 8,
                "stats_fusion": "split", "slab_chains": 3}}}, f)
        plan, n = probes_during(lambda: autotune.resolve_plan(cfg))
        assert n > 0 and plan.source == "probe"

    def test_bad_tune_value_raises(self, tmp_cache, fake_prober):
        with pytest.raises(ValueError, match="tune"):
            autotune.resolve_plan(small_config(tune="always"))

    def test_all_candidates_failing_falls_back_static(self, tmp_cache,
                                                      monkeypatch):
        def boom(config, plan, n_timed=2):
            raise RuntimeError("no device")

        monkeypatch.setattr(autotune, "probe_plan", boom)
        cfg = small_config(tune="auto")
        plan = autotune.resolve_plan(cfg)
        assert plan.source == "static"
        assert not os.path.exists(tmp_cache)  # the fallback is not cached


class TestSlabScheduler:
    def test_run_reduced_bit_identical_to_unslabbed(self):
        cfg = small_config(n_chains=6)
        full = Simulation(cfg).run_reduced()
        plan = dataclasses.replace(autotune.static_plan(cfg), slab_chains=2)
        slabbed = SlabScheduler(cfg, plan).run_reduced()
        assert set(slabbed) == set(full)
        for name, arr in full.items():
            np.testing.assert_array_equal(slabbed[name], arr, err_msg=name)

    def test_uneven_slabs_bit_identical(self):
        cfg = small_config(n_chains=5)
        full = Simulation(cfg).run_reduced()
        plan = dataclasses.replace(autotune.static_plan(cfg), slab_chains=2)
        sched = SlabScheduler(cfg, plan)  # slabs of 2, 2, 1
        assert len(sched) == 3
        slabbed = sched.run_reduced()
        for name, arr in full.items():
            np.testing.assert_array_equal(slabbed[name], arr, err_msg=name)

    def test_simulation_delegates_via_plan(self):
        cfg = small_config(n_chains=6)
        full = Simulation(cfg).run_reduced()
        plan = dataclasses.replace(autotune.static_plan(cfg), slab_chains=2)
        seen = []
        got = Simulation(cfg, plan=plan).run_reduced(
            on_block=lambda bi, state, acc: seen.append(bi))
        for name, arr in full.items():
            np.testing.assert_array_equal(got[name], arr, err_msg=name)
        # on_block sees a GLOBAL slab-major block counter: 3 slabs x 2
        # blocks each -> 0..5 monotonically
        assert seen == list(range(6))

    def test_run_ensemble_matches_unslabbed(self):
        cfg = small_config(n_chains=6)
        full = list(Simulation(cfg).run_ensemble())
        plan = dataclasses.replace(autotune.static_plan(cfg), slab_chains=2)
        slabbed = list(Simulation(cfg, plan=plan).run_ensemble())
        assert [b.offset for b in slabbed] == [b.offset for b in full]
        for s, f in zip(slabbed, full):
            np.testing.assert_array_equal(s.epoch, f.epoch)
            # weighted recombination of slab means reassociates the sum
            # over chains -> allclose, not bitwise
            np.testing.assert_allclose(s.meter, f.meter, rtol=1e-5)
            np.testing.assert_allclose(s.pv, f.pv, rtol=1e-5)
            np.testing.assert_allclose(s.residual, s.meter - s.pv)

    def test_explicit_slab_configs_never_reslabbed(self):
        cfg = small_config(n_chains=2, n_chains_total=6, chain_offset=2)
        plan = dataclasses.replace(autotune.static_plan(cfg), slab_chains=1)
        with pytest.raises(ValueError, match="n_chains_total"):
            SlabScheduler(cfg, plan)
        # and the Simulation guard (allow_slabs/_slab_scheduler) skips
        # slabbing for such configs instead of raising
        assert Simulation(cfg, plan=plan)._slab_scheduler() is None

    def test_degenerate_slab_size_rejected(self):
        cfg = small_config(n_chains=3)
        plan = dataclasses.replace(autotune.static_plan(cfg), slab_chains=3)
        with pytest.raises(ValueError, match="slab_chains"):
            SlabScheduler(cfg, plan)


class TestPlanParity:
    """Plan choice is a performance decision, never a results decision:
    within one block_impl every candidate (unroll, slab size) is BITWISE
    identical; across impls the reduction order differs (float
    reassociation) but n_seconds is exact everywhere."""

    def test_unroll_and_slab_bitwise_within_impl(self):
        cfg = small_config(n_chains=4, block_impl="scan")
        base = None
        for unroll, slab in ((1, 4), (8, 4), (8, 2)):
            plan = dataclasses.replace(
                autotune.static_plan(cfg), scan_unroll=unroll,
                slab_chains=slab)
            out = Simulation(cfg, plan=plan).run_reduced()
            if base is None:
                base = out
                continue
            for name, arr in base.items():
                np.testing.assert_array_equal(out[name], arr,
                                              err_msg=f"u{unroll}/s{slab}: "
                                                      f"{name}")

    def test_impls_agree_to_float_tolerance(self):
        cfg = small_config(n_chains=3)
        outs = {}
        for impl in ("wide", "scan", "scan2"):
            plan = dataclasses.replace(autotune.static_plan(cfg),
                                       block_impl=impl)
            outs[impl] = Simulation(cfg, plan=plan).run_reduced()
        for impl in ("scan", "scan2"):
            np.testing.assert_array_equal(
                outs[impl]["n_seconds"], outs["wide"]["n_seconds"])
            for name, arr in outs["wide"].items():
                np.testing.assert_allclose(outs[impl][name], arr, rtol=1e-4,
                                           err_msg=f"{impl}: {name}")


class TestMeshPlan:
    def test_mesh_plan_pins_slabbing_off(self, tmp_cache, fake_prober):
        cfg = small_config(n_chains=8, tune="auto")
        plan = autotune.resolve_plan_for_mesh(cfg, n_dev=4)
        # probed at the per-device shape, but the returned plan never
        # slabs the sharded loop
        assert plan.slab_chains == cfg.n_chains

    def test_mesh_plan_off_is_static(self, tmp_cache, fake_prober):
        cfg = small_config(n_chains=8, tune="off")
        plan, n = probes_during(
            lambda: autotune.resolve_plan_for_mesh(cfg, n_dev=4))
        assert n == 0 and plan.source == "static"


class TestScanRestructureAxes:
    """rng_batch / geom_stride join the sentinel-gated stage-2 grid."""

    def test_stage2_grid_includes_new_axes(self):
        cfg = small_config(tune="auto")
        winner = autotune.static_plan(cfg)
        variants = autotune._precision_variants(cfg, winner)
        combos = {(v.rng_batch, v.geom_stride) for v in variants}
        assert ("block", 1) in combos
        assert ("scan", 60) in combos
        assert ("block", 60) in combos

    def test_pinned_axes_collapse_stage2(self):
        cfg = small_config(tune="auto", rng_batch="block", geom_stride=60)
        winner = autotune.static_plan(cfg)
        assert winner.rng_batch == "block" and winner.geom_stride == 60
        for v in autotune._precision_variants(cfg, winner):
            assert v.rng_batch == "block" and v.geom_stride == 60

    def test_static_plan_resolves_auto_to_defaults(self):
        plan = autotune.static_plan(small_config())
        assert plan.rng_batch == "scan" and plan.geom_stride == 1

    def test_cached_plan_missing_axes_means_defaults(self, tmp_cache,
                                                     fake_prober):
        # a pre-v11 cache entry has no rng_batch/geom_stride keys: it
        # must load unchanged as the in-scan / stride-1 defaults
        cfg = small_config(tune="auto")
        autotune.resolve_plan(cfg)
        with open(tmp_cache) as f:
            cache = json.load(f)
        (key, entry), = cache.items()
        entry["plan"].pop("rng_batch", None)
        entry["plan"].pop("geom_stride", None)
        with open(tmp_cache, "w") as f:
            json.dump({key: entry}, f)
        plan, n = probes_during(lambda: autotune.resolve_plan(cfg))
        assert n == 0  # still a cache hit
        assert plan.rng_batch == "scan" and plan.geom_stride == 1


@pytest.mark.slow
def test_real_probe_beats_or_matches_static(tmp_path, monkeypatch):
    """Acceptance: on CPU at 256 chains x 1080 s, tune='auto' picks a plan
    whose MEASURED rate is >= the static default candidate's, and the
    second resolution is a pure cache hit (zero probes).  Real blocks are
    timed -> slow lane; the candidate grid is narrowed to keep it
    minutes, not hours."""
    monkeypatch.setenv("TMHPVSIM_AUTOTUNE_CACHE",
                       str(tmp_path / "autotune.json"))
    monkeypatch.setattr(autotune, "CANDIDATE_UNROLLS", (1, 8))
    monkeypatch.setattr(autotune, "CANDIDATE_SLAB_CHAINS", (None,))
    # the scan-restructuring axes have their own stage-2 coverage; keep
    # this acceptance at the structural grid it was written for
    monkeypatch.setattr(autotune, "CANDIDATE_RNG_BATCHES", ("scan",))
    monkeypatch.setattr(autotune, "CANDIDATE_GEOM_STRIDES", (1,))
    cfg = SimConfig(start="2019-09-05 00:00:00", duration_s=1080 * 3,
                    n_chains=256, seed=0, block_s=1080, dtype="float32",
                    tune="auto")
    plan, n = probes_during(lambda: autotune.resolve_plan(cfg))
    assert n == len(autotune.candidate_plans(cfg)) > 0
    assert plan.source == "probe"

    static = autotune.static_plan(cfg)
    cands = autotune.cached_candidates(cfg)
    rated = {(c["block_impl"], c["scan_unroll"]): c["rate"]
             for c in cands if "rate" in c}
    best_rate = max(rated.values())
    static_rate = rated[(static.block_impl, static.scan_unroll)]
    assert best_rate >= static_rate
    assert rated[(plan.block_impl, plan.scan_unroll)] == best_rate

    _, n2 = probes_during(lambda: autotune.resolve_plan(cfg))
    assert n2 == 0
