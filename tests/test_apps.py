"""Application-level tests: metersim + pvsim over the in-process broker,
and the CLI surface (SURVEY.md §4: the reference has no app tests at all)."""

import asyncio
import csv
import datetime as dt

import pytest
from click.testing import CliRunner

from tmhpvsim_tpu.apps.metersim import metersim_main
from tmhpvsim_tpu.apps.pvsim import pvsim_main
from tmhpvsim_tpu.cli import main as cli_main


def test_end_to_end_local_broker(tmp_path):
    """Producer and consumer in one process over local:// fanout: the CSV
    must contain joined rows with residual == meter - pv."""
    out = tmp_path / "out.csv"
    url = "local://e2e"
    start = dt.datetime(2019, 9, 5, 12, 0, 0)
    n = 30

    async def both():
        consumer = asyncio.create_task(
            pvsim_main(str(out), url, "meter", realtime=False, seed=1,
                       duration_s=None, start=start)
        )
        await asyncio.sleep(0.05)  # let the consumer bind before publishing
        await metersim_main(url, "meter", realtime=False, seed=2,
                            duration_s=n, start=start)
        # give the join a moment to drain, then stop the consumer
        await asyncio.sleep(0.3)
        consumer.cancel()
        try:
            await consumer
        except asyncio.CancelledError:
            pass

    asyncio.new_event_loop().run_until_complete(both())

    with open(out) as f:
        rows = list(csv.reader(f))
    assert rows[0] == ["time", "meter", "pv", "residual load"]
    assert len(rows) > n // 2  # most rows joined
    for time_s, meter, pv, residual in rows[1:]:
        assert float(meter) - float(pv) == pytest.approx(float(residual))
        assert 0 <= float(meter) < 9000
        assert float(pv) >= 0
        assert time_s.startswith("2019-09-05 12:")


def test_metersim_jax_backend_joins_and_is_deterministic(tmp_path):
    """metersim --backend=jax: device-batched meter blocks through the
    same publisher; joins with pvsim over local:// and the meter values
    are deterministic per seed."""
    start = dt.datetime(2019, 9, 5, 12, 0, 0)
    n = 30

    def run_once(tag):
        out = tmp_path / f"{tag}.csv"
        url = f"local://{tag}"

        async def both():
            consumer = asyncio.create_task(
                pvsim_main(str(out), url, "meter", realtime=False, seed=1,
                           duration_s=None, start=start)
            )
            await asyncio.sleep(0.05)
            await metersim_main(url, "meter", realtime=False, seed=7,
                                duration_s=n, start=start, backend="jax")
            await asyncio.sleep(0.3)
            consumer.cancel()
            try:
                await consumer
            except asyncio.CancelledError:
                pass

        asyncio.new_event_loop().run_until_complete(both())
        with open(out) as f:
            rows = list(csv.reader(f))
        return rows

    a, b = run_once("jax_a"), run_once("jax_b")
    assert a[0] == ["time", "meter", "pv", "residual load"]
    assert len(a) > n // 2
    # which rows join is timing-dependent; the *stream* is deterministic,
    # so compare by timestamp, not by row position
    meters_b = {row[0]: row[1] for row in b[1:]}
    for time_s, meter, _, _ in a[1:]:
        assert 0 <= float(meter) < 9000
        if time_s in meters_b:
            assert meter == meters_b[time_s]  # same seed -> same value


def test_cli_pvsim_jax_backend(tmp_path):
    out = tmp_path / "jax.csv"
    r = CliRunner().invoke(
        cli_main,
        ["pvsim", str(out), "--backend=jax", "--no-realtime",
         "--duration", "180", "--seed", "5",
         "--start", "2019-09-05 10:00:00"],
    )
    assert r.exit_code == 0, r.output
    with open(out) as f:
        rows = list(csv.reader(f))
    assert rows[0] == ["time", "meter", "pv", "residual load"]
    assert len(rows) == 1 + 180


def test_cli_jax_requires_duration(tmp_path):
    r = CliRunner().invoke(
        cli_main, ["pvsim", str(tmp_path / "x.csv"), "--backend=jax"]
    )
    assert r.exit_code != 0
    assert "--duration" in r.output


def test_cli_pvsim_jax_realtime_paces(tmp_path):
    """--backend=jax honours --realtime: rows are released on the 1 Hz
    wall clock (the reference's default streaming mode)."""
    import time

    out = tmp_path / "rt.csv"
    t0 = time.perf_counter()
    r = CliRunner().invoke(
        cli_main,
        ["pvsim", str(out), "--backend=jax", "--duration", "3",
         "--seed", "5", "--start", "2019-09-05 10:00:00"],
    )
    elapsed = time.perf_counter() - t0
    assert r.exit_code == 0, r.output
    with open(out) as f:
        assert len(f.readlines()) == 1 + 3
    assert elapsed >= 2.0  # 3 rows at 1 Hz (first fires immediately)


def test_cli_pvsim_jax_reduce_mode(tmp_path):
    """--output=reduce: per-chain summary rows + ensemble row, no trace."""
    out = tmp_path / "red.csv"
    r = CliRunner().invoke(
        cli_main,
        ["pvsim", str(out), "--backend=jax", "--no-realtime",
         "--duration", "180", "--seed", "5", "--chains", "4",
         "--start", "2019-09-05 10:00:00", "--output", "reduce"],
    )
    assert r.exit_code == 0, r.output
    with open(out) as f:
        rows = list(csv.reader(f))
    assert rows[0][0] == "chain"
    assert len(rows) == 1 + 4 + 1  # header + chains + ensemble
    assert rows[-1][0] == "ensemble"
    ns = rows[0].index("n_seconds")
    assert all(int(float(row[ns])) == 180 for row in rows[1:-1])
    pv_sum = rows[0].index("pv_sum")
    chain_total = sum(float(row[pv_sum]) for row in rows[1:-1])
    assert float(rows[-1][pv_sum]) == pytest.approx(chain_total, rel=1e-4)


def test_cli_pvsim_ensemble_mode(tmp_path):
    """--output=ensemble: reference row shape, fleet-mean values."""
    out = tmp_path / "ens.csv"
    r = CliRunner().invoke(
        cli_main,
        ["pvsim", str(out), "--backend=jax", "--no-realtime",
         "--duration", "180", "--chains", "4", "--seed", "5",
         "--output", "ensemble", "--start", "2019-09-05 10:00:00"],
    )
    assert r.exit_code == 0, r.output
    with open(out) as f:
        rows = list(csv.reader(f))
    assert rows[0] == ["time", "meter", "pv", "residual load"]
    assert len(rows) == 1 + 180
    for _, meter, pv, residual in rows[1:]:
        assert 0 <= float(meter) < 9000  # mean of uniforms stays in range
        assert float(meter) - float(pv) == pytest.approx(
            float(residual), abs=1e-2
        )


def test_cli_pvsim_block_impl_scan2_ensemble(tmp_path):
    """--block-impl=scan2 with --output=ensemble end to end through the
    CLI: the combination that used to be silently coerced to 'scan'
    must run the nested formulation and produce the same row shape and
    values as the default impl (bit-identical draw slots)."""
    rows_by_impl = {}
    for impl in ("scan", "scan2"):
        out = tmp_path / f"{impl}.csv"
        r = CliRunner().invoke(
            cli_main,
            ["pvsim", str(out), "--backend=jax", "--no-realtime",
             "--duration", "180", "--chains", "4", "--seed", "5",
             "--output", "ensemble", "--block-impl", impl,
             "--start", "2019-09-05 10:00:00"],
        )
        assert r.exit_code == 0, r.output
        with open(out) as f:
            rows_by_impl[impl] = list(csv.reader(f))
    a, b = rows_by_impl["scan"], rows_by_impl["scan2"]
    assert len(a) == len(b) == 1 + 180
    for ra, rb in zip(a[1:], b[1:]):
        assert ra[0] == rb[0]
        for va, vb in zip(ra[1:], rb[1:]):
            assert float(va) == pytest.approx(float(vb), abs=1e-3)


def test_cli_pvsim_site_grid(tmp_path):
    """--site-grid: one chain per grid site, end to end through the CLI."""
    out = tmp_path / "grid.csv"
    r = CliRunner().invoke(
        cli_main,
        ["pvsim", str(out), "--backend=jax", "--no-realtime",
         "--duration", "120", "--seed", "5",
         "--start", "2019-09-05 10:00:00",
         "--site-grid", "46:50:2,9:13:2", "--output", "reduce"],
    )
    assert r.exit_code == 0, r.output
    with open(out) as f:
        rows = list(csv.reader(f))
    assert len(rows) == 1 + 4 + 1  # 2x2 grid -> 4 chains


def test_cli_pvsim_profile_writes_trace(tmp_path):
    """--profile: a jax.profiler trace directory is produced."""
    import os

    out = tmp_path / "prof.csv"
    tdir = tmp_path / "trace"
    r = CliRunner().invoke(
        cli_main,
        ["pvsim", str(out), "--backend=jax", "--no-realtime",
         "--duration", "60", "--seed", "5",
         "--start", "2019-09-05 10:00:00", "--profile", str(tdir)],
    )
    assert r.exit_code == 0, r.output
    # the profiler lays out plugins/profile/<run>/...; existence of any
    # file under the dir is the contract
    found = [os.path.join(d, f) for d, _, fs in os.walk(tdir) for f in fs]
    assert found, f"no profiler output under {tdir}"


class TestWriteFileSink:
    """The CSV sink's contract (write_file): header shape, residual
    arithmetic, line-buffered tail-ability, and the rows-written metric."""

    @staticmethod
    def _feed(tmp_path, records, stream=None):
        from tmhpvsim_tpu.apps.pvsim import Data, write_file

        out = tmp_path / "sink.csv"

        async def run():
            queue: asyncio.Queue = asyncio.Queue()
            writer = asyncio.create_task(
                write_file(str(out), queue, stream=stream))
            for time, meter, pv in records:
                await queue.put((time, Data(meter=meter, pv=pv)))
            await queue.join()  # task_done per row: join == all flushed
            writer.cancel()
            try:
                await writer
            except asyncio.CancelledError:
                pass

        asyncio.new_event_loop().run_until_complete(run())
        return out

    def test_header_and_residual_arithmetic(self, tmp_path):
        t0 = dt.datetime(2019, 9, 5, 12, 0, 0)
        out = self._feed(tmp_path, [(t0, 450.0, 120.5),
                                    (t0 + dt.timedelta(seconds=1),
                                     300.0, 301.25)])
        with open(out) as f:
            rows = list(csv.reader(f))
        assert rows[0] == ["time", "meter", "pv", "residual load"]
        assert len(rows) == 3
        assert float(rows[1][3]) == pytest.approx(450.0 - 120.5)
        assert float(rows[2][3]) == pytest.approx(300.0 - 301.25)  # negative

    def test_line_buffered_rows_visible_while_writer_alive(self, tmp_path):
        """buffering=1 is the tail-ability contract: each row must be
        readable from the file while the writer task is still running."""
        from tmhpvsim_tpu.apps.pvsim import Data, write_file

        out = tmp_path / "tail.csv"
        seen = []

        async def run():
            queue: asyncio.Queue = asyncio.Queue()
            writer = asyncio.create_task(write_file(str(out), queue))
            t0 = dt.datetime(2019, 9, 5, 12, 0, 0)
            for i in range(3):
                await queue.put((t0 + dt.timedelta(seconds=i),
                                 Data(meter=float(i), pv=0.0)))
                await queue.join()
                assert not writer.done()
                with open(out) as f:  # a tail -f reader's view, mid-run
                    seen.append(len(f.readlines()))
            writer.cancel()
            try:
                await writer
            except asyncio.CancelledError:
                pass

        asyncio.new_event_loop().run_until_complete(run())
        assert seen == [2, 3, 4]  # header + i+1 rows after each put

    def test_rows_written_metric(self, tmp_path):
        from tmhpvsim_tpu.apps.pvsim import _StreamStats
        from tmhpvsim_tpu.obs.metrics import MetricsRegistry

        reg = MetricsRegistry()
        stream = _StreamStats(reg)
        t0 = dt.datetime(2019, 9, 5, 12, 0, 0)
        records = [(t0 + dt.timedelta(seconds=i), 100.0 + i, 1.0)
                   for i in range(7)]
        for time, _, _ in records:  # join-complete stamps (normally the
            stream.on_join(time)    # funnel front's job)
        self._feed(tmp_path, records, stream=stream)
        snap = reg.snapshot()
        assert snap["counters"]["pvsim.rows_written_total"] == 7
        # join->csv latency observed once per row
        assert snap["histograms"]["streaming.join_to_csv_s"]["count"] == 7


def test_cli_metersim_bounded():
    r = CliRunner().invoke(
        cli_main,
        ["metersim", "--no-realtime", "--duration", "5", "--seed", "0",
         "--amqp-url", "local://cli-meter"],
    )
    assert r.exit_code == 0, r.output


def test_cli_help_surfaces():
    for args in (["--help"], ["metersim", "--help"], ["pvsim", "--help"]):
        r = CliRunner().invoke(cli_main, args)
        assert r.exit_code == 0
    r = CliRunner().invoke(cli_main, ["pvsim", "--help"])
    for flag in ("--amqp-url", "--exchange", "--realtime", "--backend",
                 "--chains", "--duration"):
        assert flag in r.output
