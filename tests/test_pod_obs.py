"""Pod-scale observability (obs/pod.py + the v14 ``pod`` report section):

* ``validate_pod_section`` shape rules and the report v14 round-trip
  (v1–v13 documents still validate; malformed pod sections are refused);
* :class:`PodMonitor` — the single-process local path, straggler
  verdicts against a synthetic 2-host gather (WARN + counter), the
  gather-barrier wall correction, and non-fatal gather failures;
* ``comm_split`` — collective-vs-compute attribution from a synthetic
  gzip'd Chrome-trace export (XLA threads, infra/denylist frames);
* the ``/podmetrics`` exposition and per-process ``/metrics`` labels;
* the measured cost audit: ``compilecache`` auto-harvests the hot block
  jit's ``cost_analysis`` at AOT warm-up, ``cost_doc`` turns it into
  ``basis: "measured"`` + the per-factor ``model_error`` sub-doc with
  no manual plumbing;
* the ``block.stall`` chaos chokepoint (runtime/faults.py) — the
  deterministic straggler injector;
* HLO byte-identity: ``pod_obs`` on vs off lowers the same graph;
* the 2-process gloo run (slow lane): one host stalls via the
  chokepoint, both hosts' reports agree the straggler fired.
"""

import gzip
import json
import logging
import os
import pathlib
import sys

import numpy as np
import pytest

from tmhpvsim_tpu.config import SimConfig
from tmhpvsim_tpu.engine import Simulation, compilecache
from tmhpvsim_tpu.obs import cost as obs_cost
from tmhpvsim_tpu.obs import pod as obs_pod
from tmhpvsim_tpu.obs.metrics import MetricsRegistry, use_registry
from tmhpvsim_tpu.obs.pod import (
    PodMonitor,
    comm_split,
    is_collective,
    podmetrics_text,
    process_labels,
    validate_pod_section,
)
from tmhpvsim_tpu.obs.report import REPORT_SCHEMA_VERSION, validate_report
from tmhpvsim_tpu.runtime import faults
from tmhpvsim_tpu.runtime.faults import FaultPlan

from test_distributed import _run_workers  # noqa: E402  (2-proc harness)

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))


def scfg(**kw):
    base = dict(
        start="2019-09-05 10:00:00",
        duration_s=120,
        n_chains=4,
        seed=7,
        block_s=60,
        dtype="float32",
        output="reduce",
        block_impl="scan",
        scan_unroll=1,
    )
    base.update(kw)
    return SimConfig(**base)


@pytest.fixture(autouse=True)
def _clean_pod_state():
    """The latest-snapshot slot feeding /podmetrics is process-global;
    a leaked snapshot (or chaos plan) must not bleed across tests."""
    yield
    obs_pod._set_latest(None)
    faults.deactivate()


# ---------------------------------------------------------------------------
# validate_pod_section
# ---------------------------------------------------------------------------


def _valid_sec():
    return {
        "process_count": 2,
        "process_index": 0,
        "straggler_factor": 2.0,
        "blocks_observed": 3,
        "straggler_total": 1,
        "skew": {"max_over_median": 2.4, "last_over_median": 1.0,
                 "mean_over_median": 1.2},
        "hosts": [
            {"process": 0, "chain_start": 0, "chain_stop": 8, "block": 2,
             "block_wall_s": 0.11, "blocks_per_s": 9.1,
             "over_median": 1.0},
            {"process": 1, "chain_start": 8, "chain_stop": 16, "block": 2,
             "block_wall_s": 0.26, "blocks_per_s": 3.8,
             "over_median": 2.4},
        ],
        "comm_frac": 0.25,
    }


class TestValidatePodSection:
    def test_valid_section_passes(self):
        assert validate_pod_section(_valid_sec()) == []

    def test_not_a_dict(self):
        errs = validate_pod_section("nope")
        assert len(errs) == 1 and "expected dict" in errs[0]

    @pytest.mark.parametrize("mutate,needle", [
        (lambda s: s.update(straggler_total=-1), "straggler_total"),
        (lambda s: s.update(process_index=2), "process_index"),
        (lambda s: s.update(straggler_factor=0), "straggler_factor"),
        (lambda s: s["skew"].update(max_over_median=0), "skew.max"),
        (lambda s: s.update(hosts=[]), "hosts"),
        (lambda s: s["hosts"].pop(), "!= process_count"),
        (lambda s: s["hosts"][0].update(chain_start=9), "chain range"),
        (lambda s: s.update(comm_frac=1.5), "comm_frac"),
        (lambda s: s.update(comm="x"), "comm:"),
    ])
    def test_mutations_are_caught(self, mutate, needle):
        sec = _valid_sec()
        mutate(sec)
        errs = validate_pod_section(sec)
        assert errs and any(needle in e for e in errs), errs

    def test_null_comm_frac_is_fine(self):
        sec = _valid_sec()
        sec["comm_frac"] = None
        assert validate_pod_section(sec) == []


# ---------------------------------------------------------------------------
# PodMonitor
# ---------------------------------------------------------------------------


class TestPodMonitor:
    def test_doc_none_before_any_block(self):
        mon = PodMonitor(n_chains=4, block_s=60)
        assert mon.doc() is None

    def test_single_process_observe_block(self):
        reg = MetricsRegistry()
        mon = PodMonitor(n_chains=4, block_s=60, registry=reg)
        snap = mon.observe_block(0, 0.5, 2.0)
        assert snap is not None
        assert len(snap["hosts"]) == 1
        assert snap["stragglers"] == []
        h = snap["hosts"][0]
        assert (h["process"], h["chain_start"], h["chain_stop"]) == (0, 0, 4)
        assert h["block_wall_s"] == pytest.approx(0.5)
        doc = mon.doc()
        assert validate_pod_section(doc) == [], validate_pod_section(doc)
        assert doc["process_count"] == 1
        assert doc["blocks_observed"] == 1
        assert doc["straggler_total"] == 0
        assert doc["comm_frac"] is None
        g = reg.snapshot()["gauges"]
        assert g["pod.hosts"] == 1.0
        assert g["pod.block_wall_median_s"] == pytest.approx(0.5)

    def test_straggler_fires_warn_and_counter(self, monkeypatch, caplog):
        """A synthetic 2-host gather where host 1's wall is 5x host 0's:
        the straggler must be flagged (factor 2), logged at WARNING, and
        counted in pod.straggler_total."""
        from tmhpvsim_tpu.parallel import distributed

        rows = np.asarray([
            [0.0, 0.0, 8.0, 1.0, 0.1, 10.0],
            [1.0, 8.0, 16.0, 1.0, 0.5, 2.0],
        ])
        monkeypatch.setattr(distributed, "gather_rows", lambda row: rows)
        reg = MetricsRegistry()
        mon = PodMonitor(n_chains=16, block_s=60, registry=reg)
        mon.process_count, mon.process_index = 2, 0  # as a 2-proc run
        with caplog.at_level(logging.WARNING, logger="tmhpvsim_tpu.obs.pod"):
            snap = mon.observe_block(1, 0.1, 10.0)
        assert snap["stragglers"] == [1]
        assert mon.straggler_total == 1
        assert any("pod straggler" in r.message for r in caplog.records)
        snapshot = reg.snapshot()["counters"]
        assert snapshot["pod.straggler_total"] == 1.0
        doc = mon.doc()
        assert validate_pod_section(doc) == [], validate_pod_section(doc)
        assert doc["skew"]["max_over_median"] == pytest.approx(5.0)
        # attribution folds in after the fact (bench captures the trace)
        mon.attach_comm({"comm_frac": 0.25, "collective_s": 1.0,
                         "compute_s": 3.0})
        doc = mon.doc()
        assert doc["comm_frac"] == 0.25
        assert doc["comm"]["compute_s"] == 3.0
        assert validate_pod_section(doc) == []
        assert reg.snapshot()["gauges"]["device.pod.comm_frac"] == 0.25

    def test_median_low_lets_default_factor_fire_with_two_hosts(self):
        """The design point: with an interpolating median and 2 hosts the
        over-median ratio is bounded by 2b/(a+b) < 2 — the default
        factor 2.0 could mathematically never fire.  median_low compares
        the straggler against the FAST host instead."""
        import statistics

        a, b = 0.1, 0.5
        assert b / statistics.median([a, b]) < 2.0      # the trap
        assert b / statistics.median_low([a, b]) == 5.0  # the fix

    def test_gather_failure_is_nonfatal(self, monkeypatch):
        from tmhpvsim_tpu.parallel import distributed

        def boom(row):
            raise RuntimeError("DCN fell over")

        monkeypatch.setattr(distributed, "gather_rows", boom)
        mon = PodMonitor(n_chains=4, block_s=60)
        assert mon.observe_block(0, 0.5, 2.0) is None
        assert mon.blocks_observed == 0
        assert mon.doc() is None

    def test_gather_barrier_wait_subtracted_from_next_wall(self):
        """The heartbeat gather is a barrier: a fast host's wait there
        lands in its next dispatch-to-dispatch wall.  The monitor times
        the gather and subtracts it, keeping reported walls genuine."""
        mon = PodMonitor(n_chains=4, block_s=60)
        mon._prev_gather_wait_s = 0.4
        snap = mon.observe_block(0, 0.5, 2.0)
        assert snap["hosts"][0]["block_wall_s"] == pytest.approx(0.1)


# ---------------------------------------------------------------------------
# comm_split: collective-vs-compute attribution
# ---------------------------------------------------------------------------


def _write_trace(log_dir, events):
    d = log_dir / "plugins" / "profile" / "2026_08_07"
    d.mkdir(parents=True, exist_ok=True)
    path = d / "host0.trace.json.gz"
    with gzip.open(path, "wt", encoding="utf-8") as f:
        json.dump({"traceEvents": events}, f)
    return path


def _xla_thread_meta(pid=1, tid=2):
    return [
        {"ph": "M", "pid": pid, "name": "process_name",
         "args": {"name": "python3"}},
        {"ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
         "args": {"name": "tf_XLATfrtCpuClient-0"}},
    ]


class TestCommSplit:
    def test_is_collective_prefixes(self):
        assert is_collective("all-reduce.1")
        assert is_collective("all-gather-start.2")
        assert is_collective("reduce-scatter")
        assert not is_collective("fusion.3")
        assert not is_collective("multiply")

    def test_split_counts_xla_ops_only(self, tmp_path):
        events = _xla_thread_meta() + [
            # XLA ops on the executor thread: 300 us collective, 700 compute
            {"ph": "X", "pid": 1, "tid": 2, "ts": 0, "dur": 300,
             "name": "all-reduce.1"},
            {"ph": "X", "pid": 1, "tid": 2, "ts": 300, "dur": 700,
             "name": "multiply.2"},
            # infra frames on the same thread: never ops
            {"ph": "X", "pid": 1, "tid": 2, "ts": 0, "dur": 999,
             "name": "ThunkExecutor::Execute"},
            {"ph": "X", "pid": 1, "tid": 2, "ts": 0, "dur": 50,
             "name": "D2D Dispatch"},
            {"ph": "X", "pid": 1, "tid": 2, "ts": 0, "dur": 10,
             "name": "$python_frame"},
            # a host (non-XLA) thread: ignored wholesale
            {"ph": "X", "pid": 1, "tid": 9, "ts": 0, "dur": 5000,
             "name": "all-reduce.1"},
        ]
        _write_trace(tmp_path, events)
        out = comm_split(str(tmp_path))
        assert out is not None
        assert out["n_events"] == 2
        assert out["n_collective_events"] == 1
        assert out["comm_frac"] == pytest.approx(0.3)
        assert out["collective_s"] == pytest.approx(300e-6)
        assert out["compute_s"] == pytest.approx(700e-6)
        assert out["top_collectives"] == {"all-reduce": pytest.approx(300e-6)}

    def test_device_plane_process_name_also_matches(self, tmp_path):
        """TPU/GPU exports name the device plane via process_name; the
        thread name alone doesn't mark XLA there."""
        events = [
            {"ph": "M", "pid": 7, "name": "process_name",
             "args": {"name": "/device:TPU:0"}},
            {"ph": "X", "pid": 7, "tid": 1, "ts": 0, "dur": 100,
             "name": "all-gather.3"},
            {"ph": "X", "pid": 7, "tid": 1, "ts": 100, "dur": 100,
             "name": "fusion.7"},
        ]
        _write_trace(tmp_path, events)
        out = comm_split(str(tmp_path))
        assert out["comm_frac"] == pytest.approx(0.5)

    def test_no_trace_returns_none(self, tmp_path):
        assert comm_split(str(tmp_path)) is None

    def test_garbage_trace_returns_none(self, tmp_path):
        d = tmp_path / "plugins" / "profile" / "x"
        d.mkdir(parents=True)
        (d / "bad.trace.json.gz").write_bytes(b"not gzip at all")
        assert comm_split(str(tmp_path)) is None


# ---------------------------------------------------------------------------
# /podmetrics exposition + per-process /metrics labels
# ---------------------------------------------------------------------------


class TestFederation:
    def test_podmetrics_none_without_snapshot(self):
        obs_pod._set_latest(None)
        assert podmetrics_text() is None

    def test_podmetrics_renders_latest_snapshot(self):
        mon = PodMonitor(n_chains=4, block_s=60)
        mon.observe_block(2, 0.25, 4.0)
        text = podmetrics_text("tmhpvsim")
        assert text is not None
        assert "tmhpvsim_pod_hosts 1" in text
        assert "tmhpvsim_pod_block 2" in text
        assert 'tmhpvsim_pod_host_block_wall_seconds{process="0"} 0.25' \
            in text
        assert text.endswith("# EOF\n")

    def test_process_labels_empty_single_process(self):
        assert process_labels() == {}

    def test_openmetrics_labels_stamp_every_sample(self):
        reg = MetricsRegistry()
        reg.counter("broker.published").inc(3)
        reg.gauge("clock.lag_s").set(1.5)
        plain = reg.openmetrics_text()
        # None and {} are byte-identical: single-process scrapes are
        # unchanged by the federation feature
        assert reg.openmetrics_text(labels={}) == plain
        labelled = reg.openmetrics_text(labels={"process": "3"})
        assert 'tmhpvsim_broker_published_total{process="3"} 3' in labelled
        assert 'tmhpvsim_clock_lag_s{process="3"} 1.5' in labelled
        assert labelled.endswith("# EOF\n")


# ---------------------------------------------------------------------------
# RunReport v14: engine wiring, round-trip, back-compat
# ---------------------------------------------------------------------------

#: report version each optional section arrived in — a vN document must
#: not carry sections newer than N
_SECTION_SINCE = {
    "telemetry": 2, "streaming": 3, "executor": 4, "fleet": 5,
    "serving": 6, "resilience": 7, "precision": 8, "probe": 8,
    "cost": 10, "mesh": 13, "pod": 14, "attribution": 15,
}


class TestReportV14:
    def _run_doc(self):
        sim = Simulation(scfg(pod_obs="on"))
        sim.run_reduced()
        return sim.run_report()

    def test_engine_attaches_pod_section(self):
        doc = self._run_doc()
        assert doc["schema_version"] == REPORT_SCHEMA_VERSION == 16
        pod = doc["pod"]
        assert pod is not None
        assert validate_pod_section(pod) == [], validate_pod_section(pod)
        assert pod["process_count"] == 1
        assert pod["blocks_observed"] == 2  # 120 s / 60 s blocks
        assert pod["straggler_total"] == 0
        assert len(pod["hosts"]) == 1
        # JSON round-trip revalidates
        validate_report(json.loads(json.dumps(doc)))

    def test_pod_obs_off_omits_section(self):
        sim = Simulation(scfg())
        sim.run_reduced()
        doc = sim.run_report()
        assert doc["pod"] is None

    def test_prior_versions_still_validate(self):
        doc = self._run_doc()
        for v in range(1, REPORT_SCHEMA_VERSION):
            old = dict(doc)
            old["schema_version"] = v
            for key, since in _SECTION_SINCE.items():
                if since > v:
                    old.pop(key, None)
            validate_report(old)

    def test_malformed_pod_section_is_refused(self):
        doc = self._run_doc()
        doc["pod"] = dict(doc["pod"], straggler_total=-5)
        with pytest.raises(ValueError, match="pod"):
            validate_report(doc)


# ---------------------------------------------------------------------------
# HLO byte-identity: pod obs is host-side only
# ---------------------------------------------------------------------------


class TestHLOIdentity:
    @pytest.mark.parametrize("impl", ["scan", "scan2"])
    def test_block_jit_identical_on_vs_off(self, impl):
        """Pod observability is heartbeat gathers at block boundaries —
        the compiled per-block graph must not know it exists."""

        def lowered(pod_obs: str) -> str:
            sim = Simulation(scfg(block_impl=impl, pod_obs=pod_obs))
            state = sim.init_state()
            acc = sim.init_reduce_acc()
            inputs, _ = sim.host_inputs(0)
            jit = (sim._scan_acc_jit if impl == "scan"
                   else sim._scan2_acc_jit)
            return jit.lower(state, inputs, acc).as_text()

        assert lowered("on") == lowered("off")


# ---------------------------------------------------------------------------
# Measured cost audit: auto-harvest -> basis "measured" -> model_error
# ---------------------------------------------------------------------------


class TestMeasuredCost:
    def test_warmup_harvests_cost_and_cost_doc_uses_it(self, tmp_path):
        """The whole audit with NO manual plumbing: configure the warm-
        start executor, build a Simulation (AOT warm-up compiles the hot
        block jit and harvests its cost_analysis), then cost_doc picks
        the measurement up as basis "measured" with the per-factor
        model_error sub-doc."""
        cache = os.environ.get("TMHPVSIM_COMPILE_CACHE") \
            or str(tmp_path / "xla")
        compilecache.configure(cache)
        compilecache._state["cost"] = None
        Simulation(scfg())
        mc = compilecache.measured_cost()
        if mc is None:
            pytest.skip("cost_analysis unavailable on this jax build")
        assert mc["flops_per_site_s"] > 0
        assert not mc["target"].startswith(("mega_", "resume_copy",
                                            "scenario_acc"))
        doc = obs_cost.cost_doc(site_s_per_s=1e6, block_impl="scan")
        assert doc["basis"] == "measured"
        assert doc["measured_flops_per_site_s"] == pytest.approx(
            mc["flops_per_site_s"], rel=0.01)
        assert doc["measured_target"] == mc["target"]
        me = doc["model_error"]
        assert me["flops_ratio"] == pytest.approx(
            mc["flops_per_site_s"] / doc["flops_per_site_s"], rel=1e-3)
        assert set(me["factors"]) == {"block_impl", "compute_dtype",
                                      "kernel_impl", "rng_batch",
                                      "geom_stride"}
        assert obs_cost.validate_cost(doc) == [], obs_cost.validate_cost(doc)
        # the raw numbers also ride the executor section
        ex = compilecache.executor_doc()
        assert ex["cost_analysis"]["flops"] > 0

    def test_without_measurement_basis_stays_model(self, monkeypatch):
        monkeypatch.setitem(compilecache._state, "cost", None)
        doc = obs_cost.cost_doc(site_s_per_s=1e6, block_impl="scan")
        assert doc["basis"] == "model"
        assert "model_error" not in doc
        assert obs_cost.validate_cost(doc) == []

    def test_model_error_doc_ratios_and_implied_factors(self):
        doc = obs_cost.model_cost("scan", "f32", "exact")
        me = obs_cost.model_error_doc(
            doc, 2.0 * doc["flops_per_site_s"],
            0.5 * doc["bytes_per_site_s"])
        assert me["flops_ratio"] == pytest.approx(2.0)
        assert me["flops_err_pct"] == pytest.approx(100.0)
        assert me["bytes_ratio"] == pytest.approx(0.5)
        assert me["bytes_err_pct"] == pytest.approx(-50.0)
        row = me["factors"]["kernel_impl"]
        assert row["value"] == "exact"
        assert row["implied_flops_factor"] == pytest.approx(2.0)
        assert row["implied_bytes_factor"] == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# block.stall chaos chokepoint
# ---------------------------------------------------------------------------


class TestBlockStall:
    def test_stall_fires_in_run_reduced(self):
        """`--chaos 'block.stall=delay:...@every2'` is the deterministic
        straggler: host-side, per block dispatch, never in-graph.  Two
        blocks -> the every2 trigger fires exactly once."""
        reg = MetricsRegistry()
        with use_registry(reg), \
                faults.active(FaultPlan.parse(
                    "block.stall=delay:0.01@every2")):
            Simulation(scfg()).run_reduced()
        c = reg.snapshot()["counters"]
        assert c["faults.injected.block.stall"] == 1.0
        assert c["faults.injected_total"] == 1.0


# ---------------------------------------------------------------------------
# 2-process gloo: one host stalls, every report agrees (slow lane)
# ---------------------------------------------------------------------------

_POD_WORKER = r"""
import json
import logging
import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 4)
except AttributeError:  # jax < 0.5 spells it as an XLA flag
    import os as _os
    _os.environ["XLA_FLAGS"] = (_os.environ.get("XLA_FLAGS", "")
                                + " --xla_force_host_platform_device_count=4")
try:  # jax < 0.5: cross-process CPU collectives need the gloo opt-in
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except (AttributeError, ValueError):
    pass  # newer jax: gloo is the default

logging.basicConfig(level=logging.WARNING)  # pod straggler WARNs -> stderr

from tmhpvsim_tpu.parallel.distributed import initialize_from_env

assert initialize_from_env()

from tmhpvsim_tpu.config import SimConfig
from tmhpvsim_tpu.obs.pod import validate_pod_section
from tmhpvsim_tpu.obs.report import validate_report
from tmhpvsim_tpu.parallel import ShardedSimulation, make_mesh
from tmhpvsim_tpu.runtime import faults

pid = jax.process_index()
# ONLY host 1 stalls: 0.75 s before every 2nd block dispatch -- the
# deterministic straggler the chokepoint exists for.
if pid == 1:
    faults.activate(faults.FaultPlan.parse("block.stall=delay:0.75@every2"))

cfg = SimConfig(start="2019-09-05 10:00:00", duration_s=240, n_chains=16,
                seed=5, block_s=60, dtype="float32", output="reduce",
                pod_obs="on", pod_straggler_factor=2.0)
mesh = make_mesh()  # 8 devices across 2 processes
sim = ShardedSimulation(cfg, mesh=mesh)
red = sim.run_reduced()
assert len(red["pv_sum"]) == 8

doc = sim.run_report()
validate_report(json.loads(json.dumps(doc)))  # v14 round-trips
pod = doc["pod"]
assert pod is not None, "pod_obs=on must attach the section"
errs = validate_pod_section(pod)
assert not errs, errs
assert pod["process_count"] == 2
assert len(pod["hosts"]) == 2
assert pod["blocks_observed"] == 4, pod["blocks_observed"]
# the symmetric gather means EVERY host's report agrees on the verdict
assert pod["straggler_total"] >= 1, pod
assert pod["skew"]["max_over_median"] > 2.0, pod["skew"]
print("PODOK %d %d" % (pid, pod["straggler_total"]), flush=True)
"""


def test_two_process_straggler_detection():
    """End-to-end straggler story on a real 2-process gloo pod: host 1
    stalls via the block.stall chokepoint, the per-block heartbeat
    gather flags it on BOTH hosts (same straggler_total in both
    reports), and the WARN names the straggler."""
    outs = _run_workers(_POD_WORKER, timeout=600.0)
    assert "PODOK 0" in outs[0][1]
    assert "PODOK 1" in outs[1][1]
    totals = []
    for rc, out, err in outs:
        line = next(ln for ln in out.splitlines() if ln.startswith("PODOK"))
        totals.append(int(line.split()[2]))
        assert "pod straggler" in err, err[-2000:]
    assert totals[0] == totals[1] >= 1
