"""Statistical correctness of the keyed JAX samplers vs scipy references."""

import jax
import jax.numpy as jnp
import numpy as np
import scipy.stats as st

from tmhpvsim_tpu.models import distributions as d

N = 200_000


def _ks_against(samples, cdf, level=1e-3):
    stat, p = st.kstest(np.asarray(samples), cdf)
    assert p > level, f"KS stat={stat:.4f} p={p:.2e}"


def test_asymmetric_laplace_matches_scipy_cdf():
    kappa = 1.9354719304310923

    def cdf(x):
        k2 = kappa**2
        return np.where(
            x < 0,
            k2 / (1 + k2) * np.exp(x / kappa),
            1 - np.exp(-kappa * x) / (1 + k2),
        )

    s = d.asymmetric_laplace(jax.random.key(0), 0.0, 1.0, kappa, (N,), jnp.float64)
    _ks_against(s, cdf)
    # mean of standard AL is 1/kappa - kappa
    np.testing.assert_allclose(np.mean(np.asarray(s)), 1 / kappa - kappa, atol=0.02)


def test_asymmetric_laplace_ppf_roundtrip():
    q = jnp.linspace(0.001, 0.999, 101, dtype=jnp.float64)
    for kappa in (0.6, 1.0, 2.2375):
        x = np.asarray(d.asymmetric_laplace_ppf(q, kappa))
        k2 = kappa**2
        back = np.where(
            x < 0,
            k2 / (1 + k2) * np.exp(x / kappa),
            1 - np.exp(-kappa * x) / (1 + k2),
        )
        np.testing.assert_allclose(back, np.asarray(q), atol=1e-10)


def test_asymmetric_laplace_ppf_log_guard_at_edges():
    """The ppf's two branches both evaluate under ``jnp.where``; at the
    edges (q=0 selects the low branch, q=1 the high branch) the selected
    branch's log argument is exactly 0, and only the ``jnp.maximum(...,
    1e-38)`` guards keep the value (and its gradient) finite — without
    them both are ±inf (verified against the unguarded closed form)."""
    for kappa in (0.6, 1.0, 2.2375):
        x = np.asarray(d.asymmetric_laplace_ppf(
            jnp.asarray([0.0, 1.0], jnp.float64), kappa))
        assert np.isfinite(x).all(), (kappa, x)
        assert x[0] < 0 < x[1]  # extreme quantiles on the correct sides

    g = jax.grad(lambda q: d.asymmetric_laplace_ppf(q, 1.5))
    for q in (0.0, 1e-30, 0.2, 0.9, 1.0 - 1e-16, 1.0):
        assert np.isfinite(g(jnp.float64(q))), q


def test_student_t():
    df = 11.150488007085713
    s = d.student_t(jax.random.key(1), 0.0, 1.0, df, (N,), jnp.float64)
    _ks_against(s, st.t(df).cdf)


def test_truncated_powerlaw_bounds_and_dist():
    beta, xmin, xmax = 1.66, 0.1e3, 1e6
    s = np.asarray(
        d.truncated_powerlaw(jax.random.key(2), xmin, xmax, beta, (N,), jnp.float64)
    )
    assert s.min() >= xmin and s.max() <= xmax

    def cdf(x):
        a, b = xmax ** (1 - beta), xmin ** (1 - beta)
        return (x ** (1 - beta) - b) / (a - b)

    _ks_against(s, cdf)


def test_windspeed_gamma():
    s = d.windspeed(jax.random.key(3), (N,), jnp.float64)
    _ks_against(s, st.gamma(a=2.69, scale=2.14).cdf)
    assert np.asarray(s).min() > 0


def test_gamma_csi():
    s = d.gamma(jax.random.key(4), 3.5624, 0.0867, (N,), jnp.float64)
    _ks_against(s, st.gamma(a=3.5624, scale=0.0867).cdf)
